#!/usr/bin/env python
"""Headline benchmark: FP-Growth rule generation on a ds2-shaped workload.

The reference's published number (BASELINE.md): 20.31 s of rule generation —
mlxtend TransactionEncoder + FP-Growth + Python dict-expansion loops — on
ds2 (240,249 membership rows, 2,246 playlists, 2,171 tracks, min_support
0.05) on a CPU cluster node (relatorio.pdf p.6; timer bracket at
machine-learning/main.py:264,306-308).

This benchmark reproduces the same workload shape synthetically (the real
ds2 CSV is not distributed with the reference repo) and times the SAME
bracket for the TPU path: device one-hot encode + MXU pair-support matmul +
rule-tensor emission + host rule-dict expansion. Median of repeated runs,
compile excluded via warm-up (the reference's 20.31 s excludes Python/lib
import too).

Structure: this parent process never imports jax. Each phase runs in its
OWN subprocess, sequentially — matching deployment (batch job pod vs API
server pod are separate processes) and keeping phases from contending for
the single TPU chip (libtpu is one-process-per-chip on real hardware).

Resilience (round 1 lost its perf artifact to one transient backend
failure): the backend is probed first with a bounded timeout, phase
subprocesses retry on transient init errors with backoff, failures are
diagnosed as "TPU unreachable" vs "compute failed", and if the TPU cannot
be acquired at all the whole bench falls back to CPU — a labeled number
always beats no number.

Phases:
  1. mining  (required)  — the headline: median rule-generation seconds.
  2. popcount (TPU only) — the Pallas bitset-popcount kernel executed as a
     compiled TPU kernel at ds2 shape, counts asserted equal to the dense
     MXU path on-device, both timed.
  3. serving (optional)  — batch-32 recommend p50 on-device.
  4. replay  (optional)  — the full stack: real mining job → artifacts on a
     tmpdir "PVC" → real HTTP server process → open-loop 1k-QPS replay
     (BASELINE.json config 5; the reference never measured its serving
     path, rest_api/app/main.py:224-254).

Prints ONE JSON line:
    {"metric": ..., "value": <median mining seconds>, "unit": "s",
     "vs_baseline": <baseline_s / value>, "platform": "tpu"|"cpu",
     "serving_batch32_p50_ms": ..., "replay_p50_ms": ..., ...}

Extra context (per-run timings, diagnostics) goes to stderr.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

BASELINE_RULE_GEN_S = 20.31  # relatorio.pdf p.6 (BASELINE.md row 1)
MIN_SUPPORT = 0.05
REPEATS = 5

# soft wall-clock budget: optional phases are skipped once exceeded so the
# required JSON line is never lost to a driver-side timeout
DEADLINE_S = float(os.environ.get("KMLS_BENCH_DEADLINE_S", "2400"))
_T0 = time.monotonic()

_CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}

# substrings marking a backend-init failure worth retrying (vs a compute bug)
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "backend setup",
    "Unable to initialize backend",
    "failed to connect",
    "Connection reset",
    "Socket closed",
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _elapsed() -> float:
    return time.monotonic() - _T0


def _phase_env(platform: str) -> dict:
    env = os.environ.copy()
    if platform == "cpu":
        env.update(_CPU_ENV)
    return env


def _classify(stderr_text: str, timed_out: bool) -> str:
    """'hang' | 'transient' | 'hard' — drives retry + diagnosis wording."""
    if timed_out:
        return "hang"
    if any(m in stderr_text for m in _TRANSIENT_MARKERS):
        return "transient"
    return "hard"


_PROBE = "import jax; d = jax.devices()[0]; print('PROBE', d.platform, d.device_kind)"


def acquire_platform() -> str:
    """Decide tpu vs cpu for every phase, without ever letting a hung or
    flaky backend init kill the bench. → "tpu" or "cpu"."""
    if os.environ.get("KMLS_BENCH_CPU") == "1":  # debugging escape hatch
        log("KMLS_BENCH_CPU=1: skipping TPU, benching on CPU")
        return "cpu"
    attempts = 3
    for attempt in range(1, attempts + 1):
        log(f"probing TPU backend (attempt {attempt}/{attempts}, 240s limit)...")
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE],
                capture_output=True, text=True, timeout=240,
                env=os.environ.copy(),
            )
        except subprocess.TimeoutExpired:
            log(
                "diagnosis: TPU backend init HUNG — remote TPU pool "
                "unreachable (this is environmental, not a compute failure)"
            )
            # a hang rarely resolves on retry; one more try, then CPU
            if attempt >= 2:
                break
            continue
        if proc.returncode == 0 and "PROBE" in proc.stdout:
            kind = proc.stdout.strip().split("PROBE", 1)[1].strip()
            platform = kind.split()[0] if kind else "unknown"
            if platform != "cpu":
                log(f"TPU backend up: {kind}")
                return "tpu"
            log(f"probe found only CPU devices ({kind})")
            break
        tail = "\n".join(proc.stderr.strip().splitlines()[-4:])
        kind = _classify(proc.stderr, timed_out=False)
        log(f"probe failed (exit {proc.returncode}, {kind}):\n{tail}")
        if kind == "transient" and attempt < attempts:
            log("diagnosis: TPU unreachable (transient init error); backing off 30s")
            time.sleep(30)
            continue
        break
    log(
        "TPU could not be acquired — falling back to CPU so a perf number "
        "is still captured (JSON will carry platform=cpu)"
    )
    return "cpu"


_MINING_BENCH = r"""
import json, statistics, sys, time
import numpy as np
from kmlserver_tpu.config import MiningConfig
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_baskets
from kmlserver_tpu.mining.miner import mine

out_npz, min_support, repeats = sys.argv[1], float(sys.argv[2]), int(sys.argv[3])

import jax
dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)

baskets = synthetic_baskets(**DS2_SHAPE, seed=123)
print(
    f"workload: {len(baskets.playlist_rows)} memberships, "
    f"{baskets.n_playlists} playlists, {baskets.n_tracks} tracks, "
    f"min_support {min_support} (ds2 shape)", file=sys.stderr, flush=True,
)
cfg = MiningConfig(min_support=min_support, k_max_consequents=256)

# warm-up: compile every kernel in the bracket
result = mine(baskets, cfg)
result.tensors.to_rules_dict(result.vocab_names)
print(f"warm-up mine: {result.duration_s:.3f}s (includes compile)",
      file=sys.stderr, flush=True)

times = []
for i in range(repeats):
    t0 = time.perf_counter()
    result = mine(baskets, cfg)
    rules_dict = result.tensors.to_rules_dict(result.vocab_names)
    times.append(time.perf_counter() - t0)
    print(f"run {i}: {times[-1]:.3f}s ({len(rules_dict)} rule keys)",
          file=sys.stderr, flush=True)

np.savez(out_npz, rule_ids=result.tensors.rule_ids,
         rule_confs=result.tensors.rule_confs)
print(json.dumps({"median_s": statistics.median(times)}))
"""

_POPCOUNT_BENCH = r"""
import json, statistics, sys, time
import numpy as np
import jax, jax.numpy as jnp
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_baskets
from kmlserver_tpu.ops import encode, support
from kmlserver_tpu.ops.popcount import popcount_pair_counts

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
baskets = synthetic_baskets(**DS2_SHAPE, seed=123)
pr = jnp.asarray(baskets.playlist_rows)
ti = jnp.asarray(baskets.track_ids)
kw = dict(n_playlists=baskets.n_playlists, n_tracks=baskets.n_tracks)

dense_fn = jax.jit(lambda a, b: support.pair_counts(encode.onehot_matrix(a, b, **kw)))
dense = dense_fn(pr, ti)
dense.block_until_ready()  # warm-up/compile

def med(fn, n=5):
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn().block_until_ready()
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts) * 1e3

# compiled (interpret=False) Pallas bitset-popcount kernel — the config-4
# perf path, executed as a real TPU kernel. Mosaic lowering can't be
# pre-verified off-hardware, so try each (variant, popcount-impl) config
# until one compiles AND matches the dense counts exactly; report which.
chosen = None
for variant, swar in (("bcast", False), ("row", False),
                      ("bcast", True), ("row", True)):
    label = f"{variant}{'-swar' if swar else ''}"
    try:
        pc = popcount_pair_counts(
            baskets.playlist_rows, baskets.track_ids,
            interpret=False, variant=variant, swar=swar, **kw)
        pc.block_until_ready()
        np.testing.assert_array_equal(np.asarray(dense), np.asarray(pc))
        print(f"popcount[{label}] == dense on-device: EXACT",
              file=sys.stderr, flush=True)
        chosen = (variant, swar, label)
        break
    except Exception as exc:
        print(f"popcount[{label}] failed: {type(exc).__name__}: "
              f"{str(exc).splitlines()[0][:300]}", file=sys.stderr, flush=True)
if chosen is None:
    print("all popcount kernel configs failed to compile/run on this backend",
          file=sys.stderr, flush=True)
    sys.exit(1)

variant, swar, label = chosen
dense_ms = med(lambda: dense_fn(pr, ti))
pc_ms = med(lambda: popcount_pair_counts(
    baskets.playlist_rows, baskets.track_ids,
    interpret=False, variant=variant, swar=swar, **kw))
print(json.dumps({"dense_ms": dense_ms, "popcount_ms": pc_ms,
                  "exact": True, "kernel": label}))
"""

_SERVING_BENCH = r"""
import json, sys, time
import numpy as np
import jax, jax.numpy as jnp
from kmlserver_tpu.ops.serve import recommend_batch

with np.load(sys.argv[1]) as z:
    rule_ids = jax.device_put(jnp.asarray(z["rule_ids"]))
    rule_confs = jax.device_put(jnp.asarray(z["rule_confs"]))
v = rule_ids.shape[0]
rng = np.random.default_rng(0)
seeds = jnp.asarray(rng.integers(0, v, size=(32, 8), dtype=np.int32))
recommend_batch(rule_ids, rule_confs, seeds, k_best=10)[0].block_until_ready()
lat = []
for _ in range(50):
    t0 = time.perf_counter()
    recommend_batch(rule_ids, rule_confs, seeds, k_best=10)[0].block_until_ready()
    lat.append(time.perf_counter() - t0)
lat.sort()
print(json.dumps({"p50_ms": lat[len(lat) // 2] * 1e3}))
"""

# run scripts/scale_demo.py under _run_phase's retry/diagnosis machinery
# (cwd is the repo root, set by _run_phase)
_SCALE_BENCH = r"""
import runpy, sys
sys.argv = ["scale_demo"] + sys.argv[1:]
runpy.run_path("scripts/scale_demo.py", run_name="__main__")
"""

_CSV_SETUP = r"""
import sys
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
write_tracks_csv(sys.argv[1], synthetic_table(**DS2_SHAPE, seed=123))
print("{}")
"""

_REPLAY_CLIENT = r"""
import json, pickle, sys
from kmlserver_tpu.serving.replay import (
    pooled_http_sender_factory, replay_pooled, sample_seed_sets,
)

url, qps, n, pickles = sys.argv[1], float(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
# seed vocabulary straight from the artifact pickle — no jax in the client
# (the server owns the TPU; libtpu is one process per chip)
with open(pickles, "rb") as f:
    vocab = sorted(pickle.load(f).keys())
report = replay_pooled(
    pooled_http_sender_factory(url), sample_seed_sets(vocab, n), qps=qps
)
print(report.to_json())
"""


def _run_phase(
    name: str,
    code: str,
    argv: list[str],
    *,
    platform: str,
    timeout: float = 1800,
    attempts: int = 2,
    extra_env: dict | None = None,
) -> dict | None:
    """Run one bench phase in its own process with transient-failure
    retries; → parsed result JSON (last stdout line) or None (logged)."""
    env = _phase_env(platform)
    if extra_env:
        env.update(extra_env)
    for attempt in range(1, attempts + 1):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code, *argv],
                capture_output=True, text=True, timeout=timeout,
                env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired as exc:
            # CPython leaves TimeoutExpired.stderr as bytes even under
            # text=True — decode or the hang diagnostics print as b'...'
            tail = exc.stderr or b""
            if isinstance(tail, bytes):
                tail = tail.decode(errors="replace")
            for line in tail.splitlines()[-10:]:
                log(f"[{name}] {line}")
            log(f"{name} phase timed out after {timeout:.0f}s (backend hang?)")
            return None  # a hang already burned the budget once; don't repeat
        for line in proc.stderr.splitlines():
            log(f"[{name}] {line}")
        if proc.returncode == 0:
            try:
                return json.loads(proc.stdout.strip().splitlines()[-1])
            except (IndexError, ValueError) as exc:
                log(f"{name} phase produced unparseable output: {exc}")
                return None
        kind = _classify(proc.stderr, timed_out=False)
        if kind == "transient" and attempt < attempts:
            log(
                f"{name} phase hit a transient backend error "
                f"(attempt {attempt}/{attempts}); retrying in 30s"
            )
            time.sleep(30)
            continue
        log(
            f"{name} phase failed (exit {proc.returncode}): "
            + (
                "TPU unreachable (backend init error)"
                if kind == "transient"
                else f"compute failed on {platform}"
            )
        )
        return None
    return None


def _wait_ready(url: str, deadline_s: float) -> bool:
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        try:
            with urllib.request.urlopen(url + "/readyz", timeout=5) as resp:
                if resp.status == 200:
                    return True
        except Exception:
            pass
        time.sleep(1.0)
    return False


def replay_phase(platform: str) -> dict | None:
    """Full-stack serving measurement: mining job → PVC artifacts → real
    HTTP server (own process, owns the chip) → open-loop 1k-QPS replay."""
    qps = float(os.environ.get("KMLS_BENCH_REPLAY_QPS", "1000"))
    n_req = int(os.environ.get("KMLS_BENCH_REPLAY_REQUESTS", "8000"))
    with tempfile.TemporaryDirectory(prefix="kmls_bench_pvc_") as base:
        ds_dir = os.path.join(base, "datasets")
        os.makedirs(ds_dir)
        csv_path = os.path.join(ds_dir, "2023_spotify_ds2.csv")
        if _run_phase(
            "replay-setup", _CSV_SETUP, [csv_path], platform="cpu", timeout=300
        ) is None:
            return None
        job_env = {"BASE_DIR": base, "DATASETS_DIR": ds_dir,
                   "MIN_SUPPORT": str(MIN_SUPPORT)}
        env = _phase_env(platform)
        env.update(job_env)
        log(f"[replay] running the real mining job on {platform}...")
        try:
            job = subprocess.run(
                [sys.executable, "-m", "kmlserver_tpu.mining.job"],
                capture_output=True, text=True, timeout=900, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            log("replay skipped: mining job hung past 900s")
            return None
        if job.returncode != 0:
            for line in job.stdout.splitlines()[-10:]:
                log(f"[replay-job] {line}")
            for line in job.stderr.splitlines()[-10:]:
                log(f"[replay-job] {line}")
            log(f"replay skipped: mining job failed (exit {job.returncode})")
            return None

        srv_env = _phase_env(platform)
        srv_env.update({"BASE_DIR": base, "KMLS_PORT": "0",
                        "POLLING_WAIT_IN_MINUTES": "1"})
        server = subprocess.Popen(
            [sys.executable, "-m", "kmlserver_tpu.serving.server"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=srv_env, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        srv_lines: list[str] = []
        port_found = threading.Event()
        port_holder: list[int] = []

        def _drain() -> None:
            for line in server.stdout:  # type: ignore[union-attr]
                srv_lines.append(line.rstrip())
                m = re.search(r"serving on \S+?:(\d+)", line)
                if m and not port_found.is_set():
                    port_holder.append(int(m.group(1)))
                    port_found.set()

        t = threading.Thread(target=_drain, daemon=True)
        t.start()
        try:
            if not port_found.wait(timeout=120) or not port_holder:
                log("replay skipped: server never reported its port")
                for line in srv_lines[-10:]:
                    log(f"[replay-server] {line}")
                return None
            url = f"http://127.0.0.1:{port_holder[0]}"
            # jit warmup happens on first load; gate on readiness
            if not _wait_ready(url, deadline_s=300):
                log("replay skipped: server /readyz never went 200")
                for line in srv_lines[-10:]:
                    log(f"[replay-server] {line}")
                return None
            log(f"[replay] server ready at {url}; replaying {n_req} requests at {qps:.0f} QPS")
            pickles = os.path.join(base, "pickles", "recommendations.pickle")
            report = _run_phase(
                "replay-client", _REPLAY_CLIENT,
                [url, str(qps), str(n_req), pickles],
                platform="cpu", timeout=600,
            )
            return report
        finally:
            server.terminate()
            try:
                server.wait(timeout=10)
            except subprocess.TimeoutExpired:
                server.kill()


def main() -> int:
    platform = acquire_platform()
    result: dict = {}
    with tempfile.NamedTemporaryFile(suffix=".npz") as f:
        mining = _run_phase(
            "mining", _MINING_BENCH, [f.name, str(MIN_SUPPORT), str(REPEATS)],
            platform=platform, attempts=3,
        )
        if mining is None and platform == "tpu":
            log(
                "mining failed on TPU after retries — falling back to CPU "
                "so the headline number is still captured"
            )
            platform = "cpu"
            mining = _run_phase(
                "mining", _MINING_BENCH,
                [f.name, str(MIN_SUPPORT), str(REPEATS)],
                platform=platform, attempts=2,
            )
        if mining is None:
            log("FATAL: mining bench failed on every path; no number to report")
            return 1

        if platform == "tpu" and _elapsed() < DEADLINE_S:
            popcount = _run_phase(
                "popcount", _POPCOUNT_BENCH, [], platform=platform, timeout=900
            )
            if popcount is not None:
                log(
                    f"popcount kernel [{popcount['kernel']}] (compiled TPU, "
                    f"ds2 shape): {popcount['popcount_ms']:.2f}ms vs dense "
                    f"MXU {popcount['dense_ms']:.2f}ms, exact match"
                )
                result["popcount_ds2_ms"] = round(popcount["popcount_ms"], 3)
                result["dense_pair_ds2_ms"] = round(popcount["dense_ms"], 3)
                result["popcount_kernel"] = popcount["kernel"]

        if platform == "tpu" and _elapsed() < DEADLINE_S:
            # config-4 scale mechanics on real HBM: 1M playlists x 100k
            # vocab through Apriori prune + the bit-packed popcount path
            # (SCALE.md documents the model; this captures the numbers)
            scale = _run_phase(
                "scale", _SCALE_BENCH,
                ["--playlists", "1000000", "--tracks", "100000",
                 "--rows", "50000000", "--min-support", "0.001"],
                platform=platform, timeout=900,
            )
            if scale is not None:
                result["scale_1m_x_100k_mine_s"] = scale["mine_s"]
                result["scale_rows_per_s"] = scale["rows_per_s"]
                result["scale_frequent_items"] = scale["frequent_items"]

        if _elapsed() < DEADLINE_S:
            serving = _run_phase(
                "serving", _SERVING_BENCH, [f.name], platform=platform,
                timeout=900,
            )
            if serving is not None:
                p50 = serving["p50_ms"]
                log(
                    f"serving: batch-32 recommend p50 {p50:.3f}ms "
                    f"({p50 / 32 * 1e3:.1f}us/request)"
                )
                result["serving_batch32_p50_ms"] = round(p50, 3)

    if _elapsed() < DEADLINE_S:
        try:
            replay = replay_phase(platform)
        except Exception as exc:
            # the replay stack is optional evidence; the headline mining
            # number in hand must reach stdout no matter what breaks here
            log(f"replay phase crashed ({type(exc).__name__}: {exc}); skipping")
            replay = None
        if replay is not None:
            log(
                f"replay @ {replay['target_qps']:.0f} QPS: "
                f"p50 {replay['p50_ms']:.2f}ms p95 {replay['p95_ms']:.2f}ms "
                f"p99 {replay['p99_ms']:.2f}ms, achieved "
                f"{replay['achieved_qps']:.0f} QPS "
                f"({replay['n_errors']} errors/drops)"
            )
            result.update(
                replay_target_qps=replay["target_qps"],
                replay_achieved_qps=round(replay["achieved_qps"], 1),
                replay_p50_ms=round(replay["p50_ms"], 3),
                replay_p95_ms=round(replay["p95_ms"], 3),
                replay_p99_ms=round(replay["p99_ms"], 3),
                replay_errors=replay["n_errors"],
            )
    else:
        log(f"deadline ({DEADLINE_S:.0f}s) reached; optional phases skipped")

    median_s = mining["median_s"]
    line = {
        "metric": "fpgrowth_ds2_rule_generation_time",
        "value": round(median_s, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_RULE_GEN_S / median_s, 1),
        "platform": platform,
    }
    line.update(result)
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
