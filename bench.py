#!/usr/bin/env python
"""Headline benchmark: FP-Growth rule generation on a ds2-shaped workload.

The reference's published number (BASELINE.md): 20.31 s of rule generation —
mlxtend TransactionEncoder + FP-Growth + Python dict-expansion loops — on
ds2 (240,249 membership rows, 2,246 playlists, 2,171 tracks, min_support
0.05) on a CPU cluster node (relatorio.pdf p.6; timer bracket at
machine-learning/main.py:264,306-308).

This benchmark reproduces the same workload shape synthetically (the real
ds2 CSV is not distributed with the reference repo) and times the SAME
bracket for the TPU path: device one-hot encode + MXU pair-support matmul +
rule-tensor emission + host rule-dict expansion. Median of repeated runs,
compile excluded via warm-up (the reference's 20.31 s excludes Python/lib
import too).

Prints ONE JSON line:
    {"metric": ..., "value": <median seconds>, "unit": "s",
     "vs_baseline": <baseline_s / value = speedup factor>}

Extra context (per-phase timings, serving p50) goes to stderr.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

if os.environ.get("KMLS_BENCH_CPU") == "1":  # debugging escape hatch
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""

import jax
import jax.numpy as jnp
import numpy as np

from kmlserver_tpu.config import MiningConfig
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_baskets
from kmlserver_tpu.mining.miner import mine
from kmlserver_tpu.ops.serve import recommend_batch

BASELINE_RULE_GEN_S = 20.31  # relatorio.pdf p.6 (BASELINE.md row 1)
MIN_SUPPORT = 0.05
REPEATS = 5


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> int:
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")

    baskets = synthetic_baskets(**DS2_SHAPE, seed=123)
    log(
        f"workload: {len(baskets.playlist_rows)} memberships, "
        f"{baskets.n_playlists} playlists, {baskets.n_tracks} tracks, "
        f"min_support {MIN_SUPPORT} (ds2 shape)"
    )
    cfg = MiningConfig(min_support=MIN_SUPPORT, k_max_consequents=256)

    # warm-up: compile every kernel in the bracket
    result = mine(baskets, cfg)
    result.tensors.to_rules_dict(result.vocab_names)
    log(f"warm-up mine: {result.duration_s:.3f}s (includes compile)")

    times = []
    for i in range(REPEATS):
        t0 = time.perf_counter()
        result = mine(baskets, cfg)
        rules_dict = result.tensors.to_rules_dict(result.vocab_names)
        times.append(time.perf_counter() - t0)
        log(f"run {i}: {times[-1]:.3f}s ({len(rules_dict)} rule keys)")
    median_s = statistics.median(times)

    # serving context number (stderr only): batch-32 recommend p50
    rule_ids = jax.device_put(jnp.asarray(result.tensors.rule_ids))
    rule_confs = jax.device_put(jnp.asarray(result.tensors.rule_confs))
    rng = np.random.default_rng(0)
    seeds = jnp.asarray(
        rng.integers(0, baskets.n_tracks, size=(32, 8), dtype=np.int32)
    )
    recommend_batch(rule_ids, rule_confs, seeds, k_best=10)[0].block_until_ready()
    lat = []
    for _ in range(50):
        t0 = time.perf_counter()
        recommend_batch(rule_ids, rule_confs, seeds, k_best=10)[0].block_until_ready()
        lat.append(time.perf_counter() - t0)
    lat.sort()
    log(
        f"serving: batch-32 recommend p50 {lat[len(lat) // 2] * 1e3:.3f}ms "
        f"({lat[len(lat) // 2] / 32 * 1e6:.1f}us/request)"
    )

    print(
        json.dumps(
            {
                "metric": "fpgrowth_ds2_rule_generation_time",
                "value": round(median_s, 4),
                "unit": "s",
                "vs_baseline": round(BASELINE_RULE_GEN_S / median_s, 1),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
