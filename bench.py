#!/usr/bin/env python
"""Headline benchmark: FP-Growth rule generation on a ds2-shaped workload.

The reference's published number (BASELINE.md): 20.31 s of rule generation —
mlxtend TransactionEncoder + FP-Growth + Python dict-expansion loops — on
ds2 (240,249 membership rows, 2,246 playlists, 2,171 tracks, min_support
0.05) on a CPU cluster node (relatorio.pdf p.6; timer bracket at
machine-learning/main.py:264,306-308).

This benchmark reproduces the same workload shape synthetically (the real
ds2 CSV is not distributed with the reference repo) and times the SAME
bracket for the TPU path: device one-hot encode + MXU pair-support matmul +
rule-tensor emission + host rule-dict expansion. Median of repeated runs,
compile excluded via warm-up (the reference's 20.31 s excludes Python/lib
import too).

Structure: this parent process never imports jax. Each phase runs in its
OWN subprocess, sequentially — matching deployment (batch job pod vs API
server pod are separate processes) and keeping phases from contending for
the single TPU chip (libtpu is one-process-per-chip on real hardware). All
phases share one persistent JAX compilation cache directory, so on-TPU
compile cost is paid once across the whole bench, not per-subprocess.

TPU acquisition is PERSISTENT, not single-shot (round 2's artifact was
CPU-only because the pool was down at t=0 and never re-checked): if the
first probe fails, the CPU-safe phases run immediately — including
CPU-labeled stand-ins for the config-4 popcount/scale paths, so the
flagship scaling evidence is never absent from the artifact — while a
background thread keeps re-probing the pool on a ~3-minute schedule for as
long as the deadline allows. The moment a probe succeeds, the TPU phases
run on the chip. Every probe (timestamp, outcome, duration) is recorded in
the JSON line as ``probe_history``, so a CPU-only artifact PROVES the pool
was down for the whole window rather than just at t=0.

Phases (tpu suite), in priority order for a short pool window: mining
(headline, + an isolated MXU matmul timing with closed-form op counts →
MFU via the chained-scan slope), serving (batch-32 p50), replay (full
stack at 1k QPS, median of N runs, server-side /metrics percentiles next
to the client-observed ones), popcount (compiled Pallas kernel, counts
asserted equal on-device, words/s emitted), config4-devicegen (TRUE
10M×1M shape, workload born in HBM as a Bernoulli-Zipf bitset), scale
(1M×100k config-4 mechanics through the real host-data pipeline), sweep
(the reference's 68-point support grid, count-once).
Phases (cpu suite): mining, popcount stand-in (interpret mode, small
shape), scale stand-in (20k×5k on an 8-virtual-device mesh), serving,
replay — all keys labeled ``*_cpu*`` — plus replay10k (the 10k-QPS
Zipf-mix in-process bracket through cache → batcher → native kernel;
always CPU-measured and self-labeled, reported as ``replay10k_*`` with
``cache_hit_ratio`` and per-device dispatch counts), chaos (kill a
replica mid-run at 1k QPS, zero-5xx acceptance), loadshape (10x burst
trains / flash crowd / epoch-boundary hot-key flip through the
admission ladder — p99 < 10 ms and zero 5xx through the bursts,
``loadshape_*``), and mine-resume (kill
the mining job after a fixed phase's checkpoint, restart, report
resume-vs-full wall clock + artifact bit-identity, ``mine_resume_*``).

THE ARTIFACT IS UNLOSEABLE (VERDICT r3 next-round #1). The driver records
the LAST parseable JSON line on this process's stdout (r01/r02 artifacts
confirm: `parsed` = the final JSON line; r03's `parsed: null` happened
because the single end-of-run print never executed before the driver's
kill). Three mechanisms guarantee a parsed artifact from the moment the
headline mining number exists:

1. checkpoints — a complete, self-contained artifact line is printed after
   EVERY completed phase (marked ``"checkpoint": true``); later lines
   strictly supersede earlier ones, and only JSON lines ever go to stdout
   (all narrative goes to stderr);
2. SIGTERM/SIGINT/atexit handlers flush the best-so-far line (and kill
   live phase subprocesses) before exiting, so a driver kill at ANY time
   after the first mining result still yields a parsed artifact;
3. the soft deadline defaults to 1200 s — below the driver's observed
   ~1500 s kill — and TPU-pool probe timeouts decay to 60 s after the
   first hang (a pool that hung once will hang again; r03 burned ~24 min
   in six serial 240 s probes).

Final line (checkpoint flag absent):
    {"metric": ..., "value": <median mining seconds>, "unit": "s",
     "vs_baseline": <baseline_s / value>, "platform": "tpu"|"cpu",
     "probe_history": [...], ...}

Extra context (per-run timings, diagnostics) goes to stderr.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

BASELINE_RULE_GEN_S = 20.31  # relatorio.pdf p.6 (BASELINE.md row 1)
MIN_SUPPORT = 0.05
REPEATS = 5

# soft wall-clock budget: optional phases are skipped once exceeded so the
# required JSON line is never lost to a driver-side timeout. 1200 s sits
# well under the driver's observed ~1500 s kill (BENCH_r03.json, rc 124).
DEADLINE_S = 1200.0
_T0 = time.monotonic()


def _deadline_s() -> float:
    # env read at call time, not import time (envread checker): an
    # exported KMLS_BENCH_DEADLINE_S must keep working however late the
    # driver sets it relative to this module's first import
    return float(os.environ.get("KMLS_BENCH_DEADLINE_S", str(DEADLINE_S)))

_CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}

# one compilation cache for every phase subprocess (VERDICT r2 weak #6):
# jax persists compiled executables here, so the second process that
# compiles the same kernel (e.g. serving after mining, or the TPU suite
# after a mid-window probe success) hits the cache instead of re-lowering.
# Created lazily (importing this module for its helpers must not touch the
# filesystem) and removed at exit.
_cache_dir: str | None = None


def _cache_env() -> dict:
    global _cache_dir
    if _cache_dir is None:
        import atexit
        import shutil

        _cache_dir = tempfile.mkdtemp(prefix="kmls_bench_jaxcache_")
        atexit.register(shutil.rmtree, _cache_dir, ignore_errors=True)
    return {
        "JAX_COMPILATION_CACHE_DIR": _cache_dir,
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
    }

# peak int8 MXU throughput per chip, ops/s (public spec sheets), for the
# MFU denominator — the mining matmul is int8×int8→int32 (ops/support.py
# pair_counts). Matched by substring against jax's device_kind.
_INT8_PEAK_OPS = {
    "v6": 1836e12,
    "v5p": 918e12,
    "v5e": 394e12,  # a.k.a. v5 lite
    "v5lite": 394e12,
    "v4": 275e12,
}

# substrings marking a backend-init failure worth retrying (vs a compute bug)
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "backend setup",
    "Unable to initialize backend",
    "failed to connect",
    "Connection reset",
    "Socket closed",
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _elapsed() -> float:
    return time.monotonic() - _T0


def _remaining() -> float:
    return _deadline_s() - _elapsed()


def _phase_env(platform: str) -> dict:
    env = os.environ.copy()
    env.update(_cache_env())
    if platform == "cpu":
        env.update(_CPU_ENV)
    return env


def _classify(stderr_text: str, timed_out: bool) -> str:
    """'hang' | 'transient' | 'hard' — drives retry + diagnosis wording."""
    if timed_out:
        return "hang"
    if any(m in stderr_text for m in _TRANSIENT_MARKERS):
        return "transient"
    return "hard"


_PROBE = "import jax; d = jax.devices()[0]; print('PROBE', d.platform, d.device_kind)"


class TpuProber:
    """Persistent TPU acquisition: bounded probes, full history, optional
    background re-probing on a schedule (VERDICT r2 next-round #1)."""

    def __init__(self, probe_timeout_s: float | None = None,
                 interval_s: float | None = None):
        self.probe_timeout_s = probe_timeout_s if probe_timeout_s is not None \
            else float(os.environ.get("KMLS_BENCH_PROBE_TIMEOUT_S", "120"))
        self.interval_s = interval_s if interval_s is not None \
            else float(os.environ.get("KMLS_BENCH_PROBE_INTERVAL_S", "180"))
        # after the FIRST hang, later probes shrink to this fuse: a pool
        # that hung once will hang again, and 60 s suffices to re-detect —
        # r03 burned ~24 min of a ~25 min window on six 240 s probes
        self.decay_timeout_s = float(
            os.environ.get("KMLS_BENCH_PROBE_TIMEOUT_DECAY_S", "60")
        )
        self.history: list[dict] = []  # {"t_s", "outcome", "dur_s"}
        self.acquired = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    def probe_once(self) -> str:
        """→ 'tpu' | 'cpu_only' | 'hang' | 'error'; appends to history."""
        t_start = _elapsed()
        outcome = "error"
        detail = ""
        # _tracked_popen (not subprocess.run): the probe child is the
        # process most likely to be alive at driver-kill time, and the
        # crash handlers must be able to kill it — a hung `import jax`
        # orphan would keep its pool connection open indefinitely
        proc = _tracked_popen(
            [sys.executable, "-c", _PROBE],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env={**os.environ, **_cache_env()},
        )
        try:
            stdout_text, stderr_text = proc.communicate(
                timeout=self.probe_timeout_s
            )
            if proc.returncode == 0 and "PROBE" in stdout_text:
                kind = stdout_text.strip().split("PROBE", 1)[1].strip()
                detail = kind
                platform = kind.split()[0] if kind else "unknown"
                outcome = "cpu_only" if platform == "cpu" else "tpu"
            else:
                detail = "\n".join(stderr_text.strip().splitlines()[-3:])
                outcome = (
                    "transient_error"
                    if _classify(stderr_text, False) == "transient"
                    else "error"
                )
        except subprocess.TimeoutExpired:
            _kill_tree(proc)
            proc.communicate()
            outcome = "hang"
            detail = f"probe exceeded {self.probe_timeout_s:.0f}s (pool unreachable)"
            self.probe_timeout_s = min(self.probe_timeout_s, self.decay_timeout_s)
        entry = {
            "t_s": round(t_start, 1),
            "outcome": outcome,
            "dur_s": round(_elapsed() - t_start, 1),
        }
        with self._lock:
            self.history.append(entry)
        log(f"probe @ t={entry['t_s']:.0f}s: {outcome} ({detail.splitlines()[-1] if detail else ''})")
        if outcome == "tpu":
            self.acquired.set()
        return outcome

    def start_background(self) -> None:
        """Keep probing every ~interval_s until success, stop, or deadline."""

        def loop() -> None:
            while not self._stop.is_set() and not self.acquired.is_set():
                # stop probing when even a minimal TPU mining run no longer
                # fits before the deadline
                if _remaining() < 300 + self.probe_timeout_s:
                    log("prober: deadline headroom exhausted; stopping re-probes")
                    return
                t0 = _elapsed()
                outcome = self.probe_once()
                if outcome == "tpu":
                    return
                if outcome == "cpu_only":
                    # deterministic "this host has no TPU platform" — unlike
                    # a hang/transient error, re-probing cannot change it
                    log("prober: backend is CPU-only (not flaky); stopping")
                    return
                sleep_left = self.interval_s - (_elapsed() - t0)
                if sleep_left > 0 and self._stop.wait(timeout=sleep_left):
                    return

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def history_snapshot(self) -> list[dict]:
        with self._lock:
            return list(self.history)


# live phase subprocesses, killed by the crash handlers so a driver TERM
# doesn't leave an orphan holding the TPU chip. Reaped entries are pruned
# opportunistically at the next spawn.
_LIVE_PROCS: "set[subprocess.Popen]" = set()


def _tracked_popen(*args, **kwargs) -> subprocess.Popen:
    for p in [p for p in _LIVE_PROCS if p.poll() is not None]:
        _LIVE_PROCS.discard(p)
    # own process group: phases can spawn grandchildren (the tune phase
    # runs one worker subprocess per config), and killing only the direct
    # child would orphan a grandchild holding the TPU
    kwargs.setdefault("start_new_session", True)
    proc = subprocess.Popen(*args, **kwargs)
    _LIVE_PROCS.add(proc)
    return proc


def _kill_tree(proc: subprocess.Popen) -> None:
    """SIGKILL the phase's whole process group, then the direct child as
    a fallback (never raises)."""
    import signal as _signal

    try:
        os.killpg(proc.pid, _signal.SIGKILL)
    except (OSError, PermissionError):
        pass
    try:
        proc.kill()
    except Exception:
        pass


# hard bound on every stdout artifact line: the driver parses the last
# JSON line within a ~2,000-char tail window, and the r05 headline was
# lost to its own key growth (final line 2,112 chars → parsed: null in
# BENCH_r05.json). 1,800 leaves margin for a trailing newline + partial
# flushes.
COMPACT_LINE_LIMIT = 1800

# key order for the compact line: identity + headline first, then the
# judged serving-path numbers, then utilization/scale evidence; anything
# that doesn't fit lives only in the sidecar (which always has everything)
_COMPACT_PRIORITY = (
    "metric", "value", "unit", "vs_baseline", "platform",
    "checkpoint", "aborted", "full_artifact",
    "best_mining_s", "best_mining_platform", "vs_baseline_best",
    "mining_cpu_s", "mining_count_path",
    "replay_target_qps", "replay_achieved_qps", "replay_p50_ms",
    "replay_p95_ms", "replay_p99_ms", "replay_errors",
    "replay10k_qps", "replay10k_achieved_qps", "replay10k_p50_ms",
    "replay10k_p99_ms", "replay10k_errors", "replay10k_cache_hit_ratio",
    "replay10k_cached_p50_ms", "replay10k_uncached_p50_ms",
    "replay10k_devices_active",
    "chaos_qps", "chaos_errors", "chaos_http_5xx", "chaos_degraded_answers",
    "chaos_eject_recovery_ms", "chaos_redispatched",
    "loadshape_p99_ms", "loadshape_errors", "loadshape_http_5xx",
    "loadshape_shed", "loadshape_degraded", "loadshape_offered_qps",
    "loadshape_achieved_qps", "loadshape_p50_ms", "loadshape_burst_factor",
    "loadshape_onset_p99_ms", "loadshape_steady_p99_ms",
    "loadshape_flash_p99_ms", "loadshape_flash_http_5xx",
    "loadshape_flip_http_5xx", "loadshape_flip_errors",
    "loadshape_flip_epoch_moved", "loadshape_flip_singleflight",
    "mine_resume_s", "mine_resume_full_s", "mine_resume_saved_pct",
    "mine_resume_identical", "mine_resume_phase",
    "als_train_s", "hybrid_p50_ms", "hybrid_p99_ms", "hybrid_errors",
    "cold_start_hit_frac", "cold_start_seeds",
    "confserve_p50_ms", "confserve_p99_ms", "confserve_qps",
    "confserve_errors",
    "shardserve_sharded_p50_ms", "shardserve_sharded_p99_ms",
    "shardserve_replicated_p50_ms", "shardserve_replicated_p99_ms",
    "shardserve_identical", "shardserve_shards", "shardserve_unwarmed",
    "shardserve_max_catalog_bytes",
    "scale_shard_mine_s", "scale_shard_rows_per_s",
    "scale_shard_count_path", "scale_shard_shards",
    "replay_queue_wait_p99_ms", "replay_device_p99_ms",
    "replay_queue_wait_p50_ms", "replay_device_p50_ms", "replay_e2e_p999_ms",
    "replay_server_p50_ms", "replay_server_p95_ms", "replay_server_p99_ms",
    "serving_batch32_p50_ms", "serving_batch32_amortized_ms",
    "serving_batch256_p50_ms", "serving_batch256_amortized_ms",
    # judged tracing claims (ratio ≤ 1.05, zero-cost began_off == 0),
    # ranked below the TPU serving evidence; on/off/retained detail
    # lives in the sidecar
    "traceoverhead_p99_ratio", "traceoverhead_began_off",
    # judged freshness claims (ISSUE 10): delta vs full-path speedup
    # (≥ 5x), zero 5xx through the in-place apply, and the 3-replica
    # fleet hit-ratio multiplier — ranked with traceoverhead below the
    # TPU serving evidence (CPU-measured by construction); path/cache
    # detail is sidecar-only, the compact line sits at its budget
    "freshness_speedup", "freshness_http_5xx", "freshness_errors",
    "freshness_publish_to_applied_ms", "freshness_fleet_multiplier",
    # judged predictive-serving claims (ISSUE 17): the paired A/B legs'
    # p99 + onset-window p99 for ramp/sine (predictive must be no worse
    # on both and on shed/degrade at equal capacity), zero 5xx across
    # every leg, and the predictive legs' observation evidence — ranked
    # with the other CPU-measured judged brackets below the TPU serving
    # evidence; steady-window, constant-control and per-leg detail is
    # sidecar-only
    "loadshape_pred_ramp_react_p99_ms", "loadshape_pred_ramp_pred_p99_ms",
    "loadshape_pred_ramp_react_onset_p99_ms",
    "loadshape_pred_ramp_pred_onset_p99_ms",
    "loadshape_pred_sine_react_p99_ms", "loadshape_pred_sine_pred_p99_ms",
    "loadshape_pred_ramp_react_shed", "loadshape_pred_ramp_pred_shed",
    "loadshape_pred_http_5xx", "loadshape_pred_errors",
    "loadshape_pred_ramp_obs",
    # judged fleet cache-routing claims (ISSUE 15): routed vs
    # independent fleet hit ratio on 3 REAL server processes, the
    # multiplier achieved vs the PR 10 simulated prediction (≥ 0.9 of
    # it — one canonical ring on both sides), p99 and zero 5xx through
    # a mid-replay replica kill AND delta apply, with survivor answer
    # identity pinned — ranked with the freshness block below the TPU
    # serving evidence (CPU-measured by construction); per-peer and
    # router detail is sidecar-only
    "fleet_hit_ratio", "fleet_independent_hit_ratio",
    "fleet_multiplier_achieved", "fleet_multiplier_simulated",
    "fleet_p99_ms", "fleet_http_5xx", "fleet_errors",
    "fleet_identity_ok",
    # judged serve-mesh claims (ISSUE 16): gang answers bit-identical to
    # the single-process kernels with zero compiles, max servable
    # catalog = per-host budget x gang size, and zero 5xx / zero drops
    # through a mid-replay gang-member SIGKILL (the refusal + ejection
    # counters prove the shard loss actually happened) — ranked with the
    # fleet block below the TPU serving evidence (CPU-measured by
    # construction, the socket transport stands in for GSPMD-over-DCN);
    # per-peer, budget-bytes and replay detail is sidecar-only
    "meshserve_p50_ms", "meshserve_p99_ms", "meshserve_sharded_p50_ms",
    "meshserve_identical", "meshserve_gang", "meshserve_unwarmed",
    "meshserve_max_catalog_bytes", "meshserve_http_5xx",
    "meshserve_errors", "meshserve_mesh_unavailable", "meshserve_ejections",
    # judged gray-failure claims (ISSUE 18): hedged p99 ≥ 5x better than
    # the no-hedge control through a 200 ms alive-but-late stall at
    # equal capacity, hedge overhead ≤ 5% of dispatches, zero 5xx and
    # zero drops on every leg, answers bit-identical whichever copy wins,
    # and the KMLS_HEDGE=0 zero-cost pin (control leg leaves the module
    # hedge counter at exactly 0 under real traffic) — ranked with the
    # fleet/meshserve blocks below the TPU serving evidence (CPU-measured
    # by construction); per-leg latency and mesh-side detail is
    # sidecar-only
    "slowpeer_p99_ratio", "slowpeer_hedged_p99_ms",
    "slowpeer_control_p99_ms", "slowpeer_hedge_overhead_pct",
    "slowpeer_hedge_wins", "slowpeer_hedge_mismatch",
    "slowpeer_http_5xx", "slowpeer_errors", "slowpeer_identity_ok",
    "slowpeer_control_hedges_issued", "slowpeer_mesh_hedge_wins",
    # judged storage gray-failure claims (ISSUE 19): serving p99 unmoved
    # under the 400 ms PVC read stall, conviction flips /readyz to
    # degraded (never unready), the armed reload parks in bounded
    # backoff holding last-good, and the ENOSPC-mid-publish leg pins
    # exit 75 + bit-identity + zero torn temps — ranked with the
    # slowpeer block (CPU-measured by construction); per-leg latency
    # detail is sidecar-only
    "graystore_p99_ratio", "graystore_storage_slow",
    "graystore_readyz_degraded", "graystore_reload_deferred",
    "graystore_last_good_held", "graystore_enospc_exit_resumable",
    "graystore_enospc_identical", "graystore_torn_parts",
    "graystore_http_5xx", "graystore_errors",
    # judged quality-loop claims (ISSUE 14): held-out recall@k per
    # serving mode (blend at the MEASURED optimum vs both pure modes),
    # the measured weight round-tripping report → bundle → serve time,
    # and the compacted snapshot bit-identical to a full re-mine with
    # zero 5xx through the mid-replay swap — ranked with the freshness/
    # costattrib blocks below the TPU serving evidence (CPU-measured by
    # construction); sweep-curve/MRR/coverage detail is sidecar-only
    "quality_recall_blend", "quality_recall_rules", "quality_recall_embed",
    "quality_blend_weight", "quality_weight_roundtrip",
    "quality_compact_identical", "quality_compact_s",
    "quality_compact_speedup", "quality_http_5xx", "quality_errors",
    # judged sparsity-adaptive claims (ISSUE 13): ≥5x over the native
    # record path on the SAME ≥99%-sparse workload (density carries the
    # ≥99% part), every route bit-identical, and the auto dispatch
    # resolving from the measured table — ranked with the freshness/
    # costattrib blocks below the TPU serving evidence (CPU-measured by
    # construction); rows/s, shape and table detail are sidecar-only
    "sparse_speedup_vs_native", "sparse_identical",
    "sparse_headline_identical", "sparse_density",
    "sparse_auto_path", "sparse_auto_source",
    # judged cost-attribution claims (ISSUE 12): serve-kernel MFU +
    # roofline class (the ROADMAP TPU-window headline shape, CPU-labeled
    # until a window lands), live compiles==0 post-publish, and the
    # disabled-mode zero-observation proof; rate/detail keys are
    # sidecar-only like the traceoverhead/freshness detail
    "costattrib_mfu", "costattrib_roofline", "costattrib_compiles",
    "costattrib_obs_off",
    "mining_mfu_pct", "mining_mfu_peak_tops", "mining_matmul_gops_per_s",
    "config4_mine_s", "config4_rows_per_s", "scale_1m_x_100k_mine_s",
    "popcount_words_per_s", "sweep_points",
    "tpu_suite_from_bank", "tpu_bank_age_s",
)


def _compact_line(full: dict, limit: int = COMPACT_LINE_LIMIT) -> str:
    """Serialize ``full`` into a JSON line guaranteed ≤ ``limit`` chars:
    keys added greedily in priority order (then insertion order) while the
    serialized line still fits. The full dict always reaches the sidecar;
    this bounds only what rides stdout past the driver's tail window."""
    ordered = [k for k in _COMPACT_PRIORITY if k in full]
    seen = set(ordered)
    ordered += [k for k in full if k not in seen]
    out: dict = {}
    line = "{}"
    for key in ordered:
        candidate = json.dumps({**out, key: full[key]})
        if len(candidate) <= limit:
            out[key] = full[key]
            line = candidate
    return line


class ArtifactEmitter:
    """Crash-proof artifact emission (VERDICT r3 next-round #1).

    Holds the headline mining result + every optional phase's keys
    (``extras``) and prints an artifact line on every :meth:`checkpoint` —
    the driver parses the last JSON line on stdout, so each print strictly
    supersedes the previous one. Stdout lines are the COMPACT projection
    (≤ 1,800 chars — the r05 headline was lost to a 2,112-char line
    overrunning the driver's tail window) with the complete artifact
    mirrored to a sidecar file (``KMLS_BENCH_SIDECAR``, default
    ``bench_full.json``) on every emission. Signal-handler
    emissions (``note`` set) are prefixed with a newline so they land on
    a fresh line even if the signal interrupted the main thread
    mid-write; normal checkpoints don't need it (the emitter is the only
    stdout writer in this process), keeping the captured stream valid
    line-per-record JSONL. Thread-safe (the SIGTERM handler and the main
    thread both emit); RLock because the handler can fire while the main
    thread is mid-checkpoint.
    """

    def __init__(self, prober: TpuProber | None = None):
        self._lock = threading.RLock()
        self.prober = prober
        self.platform: str | None = None
        self.mining: dict | None = None
        self.cpu_mining: dict | None = None
        self.extras: dict = {}
        self._finalized = False
        self._last_printed: str | None = None
        # every stdout line is the COMPACT projection (≤ 1,800 chars so the
        # driver's tail window can never lose it again); the complete
        # artifact goes to this sidecar on every checkpoint. The default
        # name is per-PROCESS: the watcher and the driver share one cwd
        # (the same topology the bank's merge-on-write exists for), and a
        # fixed shared name would let them clobber each other's artifact
        # while both compact lines point at it. Empty string disables the
        # sidecar (stdout stays compact regardless).
        self.sidecar_path = (
            os.environ.get(
                "KMLS_BENCH_SIDECAR", f"bench_full_{os.getpid()}.json"
            ) or None
        )
        self._sidecar_ok = False

    def _write_sidecar(self, line: dict) -> None:
        if self.sidecar_path is None:
            return
        tmp = self.sidecar_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(line, f, indent=1)
            os.replace(tmp, self.sidecar_path)
            self._sidecar_ok = True
        except OSError as exc:
            # drop the pointer too: advertising full_artifact after a
            # failed write would hand consumers a STALE sidecar missing
            # this checkpoint's keys
            self._sidecar_ok = False
            log(f"sidecar write failed ({exc}); stdout line still emitted")

    def _render(self, line: dict) -> str:
        self._write_sidecar(line)
        if self._sidecar_ok:
            line = {**line, "full_artifact": self.sidecar_path}
        return _compact_line(line)

    def set_headline(self, platform: str, mining: dict) -> None:
        with self._lock:
            self.platform = platform
            self.mining = mining
        self.checkpoint()

    def set_cpu_comparison(self, cpu_mining: dict | None) -> None:
        with self._lock:
            self.cpu_mining = cpu_mining
        self.checkpoint()

    def compose(self, *, checkpoint: bool, note: str | None = None) -> dict | None:
        with self._lock:
            if self.mining is None:
                return None  # nothing judgeable yet — never print a dud line
            line = _headline_keys(self.platform, self.mining, self.cpu_mining)
            line.update(self.extras)
            if self.prober is not None:
                line["probe_history"] = self.prober.history_snapshot()
            if checkpoint:
                line["checkpoint"] = True
            if note:
                line["aborted"] = note
            return line

    def checkpoint(self, note: str | None = None) -> None:
        """Print the best-so-far artifact line (no-op before the headline
        exists or after finalize)."""
        with self._lock:
            if self._finalized:
                return
            line = self.compose(checkpoint=True, note=note)
            if line is None:
                return
            s = self._render(line)
            if s == self._last_printed:
                return
            sys.stdout.write(("\n" if note else "") + s + "\n")
            sys.stdout.flush()
            self._last_printed = s

    def finalize(self) -> bool:
        """Print the final line (checkpoint flag absent). → False when no
        headline was ever captured."""
        with self._lock:
            line = self.compose(checkpoint=False)
            if line is None:
                return False
            sys.stdout.write(self._render(line) + "\n")
            sys.stdout.flush()
            self._finalized = True
            return True

    def ever_printed(self) -> bool:
        """True once ANY artifact line (checkpoint or final) reached
        stdout — the signal handler's exit-code discriminator."""
        with self._lock:
            return self._last_printed is not None or self._finalized


def _install_crash_handlers(emitter: ArtifactEmitter) -> None:
    """SIGTERM/SIGINT/atexit → flush the best-so-far line, kill live phase
    subprocesses, exit. This is the mechanism that makes a driver kill at
    ANY time after the first mining result still yield a parsed artifact."""
    import atexit
    import signal

    def _flush(signum=None, frame=None):
        emitter.checkpoint(
            note=f"signal {signum} at t={_elapsed():.0f}s" if signum else None
        )
        for p in list(_LIVE_PROCS):
            _kill_tree(p)
        if signum is not None:
            sys.stdout.flush()
            sys.stderr.flush()
            # a kill BEFORE the first artifact line must not look like a
            # clean run: rc 0 is reserved for runs that flushed at least
            # one checkpoint (ADVICE r4 #3)
            os._exit(0 if emitter.ever_printed() else 128 + signum)

    atexit.register(_flush)
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _flush)
        except (ValueError, OSError):
            pass  # non-main thread / exotic platform: atexit still covers


class BenchState:
    """Cross-invocation TPU phase bank (VERDICT r4 next-round #6).

    Pool windows are short (~15 min) and sporadic; three 5-minute windows
    across a round must accumulate ONE full TPU artifact, not three
    headline-only ones. When a bank file is in play (``KMLS_BENCH_STATE``,
    or the newest ``bench_state_*_tpu.json`` the watcher left in cwd),
    every completed TPU-suite phase banks its raw result dict there
    (atomic tmp+rename, the io/artifacts.py discipline) and the next
    invocation replays banked phases into the artifact line instead of
    re-running them. The mining phase also banks its rule-tensor npz
    (sidecar ``<path>.npz``) so the serving phase still has its input
    when mining itself is skipped. Phases older than
    ``KMLS_BENCH_STATE_MAX_AGE_S`` (default 12 h, the round length) are
    dropped at load so a stale bank from a previous round can't leak
    into a fresh artifact. No usable path → no-op.

    ``replay_only`` (set by main()'s banked-takeover path) turns every
    live-run fallback off: banked phases replay, everything else is
    skipped — the mode that folds a prior window's measurements into an
    artifact produced while the pool is down.
    """

    MAX_AGE_S = 43200.0

    def _max_age_s(self) -> float:
        # env read at call time, not import time (envread checker)
        return float(
            os.environ.get("KMLS_BENCH_STATE_MAX_AGE_S", str(self.MAX_AGE_S))
        )

    def __init__(self, path: str | None):
        self.path = path
        self.phases: dict = {}
        self.banked_at: dict = {}
        self.replay_only = False
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
                if not isinstance(data, dict) or not isinstance(
                    data.get("phases"), dict
                ):
                    raise ValueError("not a phase-bank object")
                self.phases = dict(data["phases"])
                # every writer stamps banked_at (v2); an entry WITHOUT a
                # numeric timestamp is a legacy v1 bank (or a corrupted
                # one) of unknowable age — treat it as stale, never as
                # fresh: bench_state_*_tpu.json is committable round
                # evidence and _resolve_state_path auto-adopts it, so a
                # timestampless entry in the tree would otherwise replay
                # into every fresh-checkout artifact forever (ADVICE r5 #4)
                meta = data.get("banked_at")
                meta = meta if isinstance(meta, dict) else {}
                self.banked_at = {
                    n: t for n, t in meta.items()
                    if isinstance(t, (int, float))
                }
                now = time.time()
                stale = [
                    n for n in self.phases
                    if self.banked_at.get(n) is None
                    or now - self.banked_at[n] > self._max_age_s()
                ]
                for n in stale:
                    self.phases.pop(n, None)
                    self.banked_at.pop(n, None)
                if stale:
                    log(
                        f"state bank {path}: dropped stale phases "
                        f"{sorted(stale)} (> {self._max_age_s():.0f}s old)"
                    )
                log(
                    f"state bank {path}: resuming with "
                    f"{sorted(self.phases)} already banked"
                )
            except (OSError, ValueError, TypeError) as exc:
                log(f"state bank {path} unreadable ({exc}); starting fresh")
                self.phases = {}
                self.banked_at = {}

    @property
    def npz_path(self) -> str | None:
        return self.path + ".npz" if self.path else None

    def get(self, name: str) -> dict | None:
        return self.phases.get(name)

    def age_s(self, name: str) -> float | None:
        t = self.banked_at.get(name)
        return None if t is None else max(0.0, time.time() - t)

    def bank(self, name: str, result: dict) -> None:
        if self.path is None:
            return
        self.phases[name] = result
        self.banked_at[name] = time.time()
        # merge-on-write: the watcher and the driver can share one bank
        # (auto-adoption makes that the default topology) — a blind dump
        # of this process's view would erase phases the other process
        # banked since our load. NEWEST banked_at wins regardless of
        # origin (ADVICE r5 #2): "own names win" would let a process
        # overwrite a fresher on-disk result with the stale copy it merely
        # loaded at startup. The phase just banked above carries a
        # timestamp of now, so it wins its own name naturally.
        phases, banked_at = dict(self.phases), dict(self.banked_at)
        try:
            with open(self.path) as f:
                disk = json.load(f)
            if isinstance(disk, dict) and isinstance(disk.get("phases"), dict):
                disk_at = disk.get("banked_at")
                disk_at = disk_at if isinstance(disk_at, dict) else {}
                for other, res in disk["phases"].items():
                    disk_t = disk_at.get(other)
                    if not isinstance(disk_t, (int, float)):
                        continue  # timestampless disk entry = stale
                    ours_t = banked_at.get(other)
                    if other not in phases or ours_t is None or disk_t > ours_t:
                        phases[other] = res
                        banked_at[other] = disk_t
        except (OSError, ValueError, TypeError):
            pass  # no readable disk copy to merge — write ours
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"version": 2, "phases": phases,
                           "banked_at": banked_at}, f)
            os.replace(tmp, self.path)
        except OSError as exc:
            log(f"state bank write failed ({exc}); {name} not banked")


def _resolve_state_path() -> str | None:
    """KMLS_BENCH_STATE wins; empty string disables; unset adopts THIS
    round's watcher bank (scripts/tpu_watch.sh writes
    ``bench_state_r<N>_tpu.json``) so the driver's own plain
    ``python bench.py`` inherits everything a window captured. The round
    is inferred from the newest ``ROUND<N>.md`` response map — never a
    bare newest-file glob, which would let a PREVIOUS round's bank (left
    in the committed tree) masquerade as this round's measurements."""
    env = os.environ.get("KMLS_BENCH_STATE")
    if env is not None:
        return env or None
    import glob

    rounds = []
    for path in glob.glob("ROUND*.md"):
        m = re.fullmatch(r"ROUND(\d+)\.md", os.path.basename(path))
        if m:
            rounds.append(int(m.group(1)))
    if not rounds:
        return None
    candidate = f"bench_state_r{max(rounds):02d}_tpu.json"
    return candidate if os.path.exists(candidate) else None


STATE = BenchState(_resolve_state_path())


def _acquire_tpu_lock(timeout_s: float):
    """One TPU suite at a time per bank: the watcher's capture and the
    driver's round-end bench share one chip, and two suites contending
    through the tunnel corrupt BOTH timing sets. → an open fd holding
    the flock, the sentinel "nolock" when no bank is configured (nothing
    to coordinate through), or None when the lock stayed held past
    timeout_s (caller falls back to replaying what the holder banked)."""
    if STATE.path is None:
        return "nolock"
    import fcntl

    try:
        fd = open(STATE.path + ".lock", "w")
    except OSError as exc:
        # an unwritable bank path was always tolerated (BenchState.bank
        # just logs) — the lock must not be stricter than the bank
        log(f"TPU-suite lock unavailable ({exc}); proceeding unlocked")
        return "nolock"
    deadline = time.monotonic() + max(timeout_s, 0.0)
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return fd
        except BlockingIOError:
            if time.monotonic() >= deadline:
                fd.close()
                return None
            time.sleep(5)
        except OSError as exc:
            # flock itself unsupported (e.g. NFS without lockd): that is
            # not contention — don't burn the deadline or fake a fallback
            fd.close()
            log(f"TPU-suite lock unsupported here ({exc}); proceeding unlocked")
            return "nolock"


def _release_tpu_lock(lock) -> None:
    if lock is None or lock == "nolock":
        return
    import fcntl

    try:
        fcntl.flock(lock, fcntl.LOCK_UN)
    finally:
        lock.close()


def _banked(
    name: str, runner, budget_s: float | None = None,
    extras: dict | None = None,
) -> dict | None:
    """Replay ``name`` from the state bank, or run it live and bank the
    result. A banked phase replays for free — even past the deadline gate;
    a live run happens only with ``budget_s`` of deadline headroom (None =
    no gate, the caller gates) and never in replay-only mode.

    A replayed phase stamps ``<name>_from_bank`` / ``<name>_bank_age_s``
    into ``extras`` (the artifact's extra-key dict) so a mixed artifact —
    fresh mining next to hours-old banked phases — says which numbers came
    from which window (ADVICE r5 #1)."""
    cached = STATE.get(name)
    if cached is not None:
        log(f"{name}: banked from a prior window — skipping live run")
        if extras is not None:
            extras[f"{name}_from_bank"] = True
            age = STATE.age_s(name)
            if age is not None:
                extras[f"{name}_bank_age_s"] = round(age)
        return dict(cached)
    if STATE.replay_only:
        return None
    if budget_s is not None and _remaining() <= budget_s:
        return None
    result = runner()
    if result is not None:
        STATE.bank(name, result)
    return result


_MINING_BENCH = r"""
import json, statistics, sys, time
from functools import partial
import numpy as np
from kmlserver_tpu.config import MiningConfig
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_baskets
from kmlserver_tpu.mining.miner import mine

out_npz, min_support, repeats = sys.argv[1], float(sys.argv[2]), int(sys.argv[3])

import jax
import jax.numpy as jnp
dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)

baskets = synthetic_baskets(**DS2_SHAPE, seed=123)
print(
    f"workload: {len(baskets.playlist_rows)} memberships, "
    f"{baskets.n_playlists} playlists, {baskets.n_tracks} tracks, "
    f"min_support {min_support} (ds2 shape)", file=sys.stderr, flush=True,
)
cfg = MiningConfig(min_support=min_support, k_max_consequents=256)

# warm-up: compile every kernel in the bracket
result = mine(baskets, cfg)
result.tensors.to_rules_dict(result.vocab_names)
print(f"warm-up mine: {result.duration_s:.3f}s (includes compile)",
      file=sys.stderr, flush=True)

times = []
for i in range(repeats):
    t0 = time.perf_counter()
    result = mine(baskets, cfg)
    rules_dict = result.tensors.to_rules_dict(result.vocab_names)
    times.append(time.perf_counter() - t0)
    print(f"run {i}: {times[-1]:.3f}s ({len(rules_dict)} rule keys)",
          file=sys.stderr, flush=True)
print(
    "phase timings (last run): "
    + ", ".join(
        f"{k} {v * 1e3:.0f}ms"
        for k, v in (result.phase_timings or {}).items()
    ),
    file=sys.stderr, flush=True,
)

# isolated MXU pair-count matmul with a closed-form op count — the anchor
# for a utilization (MFU) judgement the full bracket can't provide (it
# includes host-side rule-dict expansion). ops = 2·P·V² (V² output cells,
# P int8 MACs each, 2 ops/MAC), per ops/support.py pair_counts.
from kmlserver_tpu.ops import encode, support
pr, ti = jnp.asarray(baskets.playlist_rows), jnp.asarray(baskets.track_ids)
x = jax.jit(partial(
    encode.onehot_matrix,
    n_playlists=baskets.n_playlists, n_tracks=baskets.n_tracks,
))(pr, ti)
support.pair_counts(x).block_until_ready()  # compile
mm = []
for _ in range(20):
    t0 = time.perf_counter()
    support.pair_counts(x).block_until_ready()
    mm.append(time.perf_counter() - t0)
matmul_s = statistics.median(mm)
# Device-resident chained timing — the honest MFU numerator. N matmuls run
# inside ONE compiled scan, each iteration data-dependent on the last
# (min(counts[0,0], 0) is always 0 at runtime but not provably so at
# compile time, so XLA can neither fold the chain nor overlap iterations),
# and the fetched scalar sums the carry so the host read cannot complete
# before all N iterations have. Timing the scan at two lengths and taking
# the slope cancels the tunnel round trip, dispatch cost, and async-ack
# artifacts that pollute per-call timing through this environment's
# remote-TPU tunnel (r03 preview: 50 overlapping dispatches "measured"
# 177% MFU — physically impossible; per-blocked-call timing is floored by
# the ~65ms round trip instead).
if dev.platform == "tpu":
    @partial(jax.jit, static_argnames=("n",))
    def _chained(x0, n):
        def step(carry, _):
            counts = support.pair_counts(carry)
            bump = jnp.minimum(counts[0, 0], 0).astype(carry.dtype)
            return carry + bump, ()
        out, _ = jax.lax.scan(step, x0, None, length=n)
        return jnp.sum(out, dtype=jnp.int32)

    def _timed_chain(n):
        float(jax.device_get(_chained(x, n)))  # compile + warm
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(jax.device_get(_chained(x, n)))
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    N1, N2 = 16, 1016
    t_short, t_long = _timed_chain(N1), _timed_chain(N2)
    slope = (t_long - t_short) / (N2 - N1)
    # noise guard: a non-positive slope means the two timings were
    # indistinguishable — fall back to the blocked per-call median
    matmul_amortized_s = slope if slope > 0 else matmul_s
    # the slope's raw inputs travel with the artifact so the MFU number is
    # auditable (VERDICT r3 next-round #2)
    chain_keys = {"chain_n1": N1, "chain_n2": N2,
                  "chain_t_short_s": t_short, "chain_t_long_s": t_long}
    print(f"isolated pair-count matmul: {matmul_s * 1e3:.3f}ms/call "
          f"blocked, {matmul_amortized_s * 1e3:.3f}ms/iter from the "
          f"{N2}-vs-{N1} chained-scan slope "
          f"(t({N1})={t_short:.4f}s, t({N2})={t_long:.4f}s)",
          file=sys.stderr, flush=True)
else:
    # CPU: per-call cost (~1s) dwarfs dispatch overhead; a short async
    # pipeline amortizes what little there is without chained compiles
    N_AMORT = 10
    t0 = time.perf_counter()
    rs = [support.pair_counts(x) for _ in range(N_AMORT)]
    jax.block_until_ready(rs)
    matmul_amortized_s = (time.perf_counter() - t0) / N_AMORT
    chain_keys = {}
    print(f"isolated pair-count matmul: {matmul_s * 1e3:.3f}ms/call "
          f"blocked, {matmul_amortized_s * 1e3:.3f}ms amortized over "
          f"{N_AMORT}", file=sys.stderr, flush=True)

np.savez(out_npz, rule_ids=result.tensors.rule_ids,
         rule_confs=result.tensors.rule_confs)
print(json.dumps({
    "median_s": statistics.median(times),
    "matmul_s": matmul_s,
    "matmul_amortized_s": matmul_amortized_s,
    "n_playlists": baskets.n_playlists,
    "n_tracks": baskets.n_tracks,
    "device_kind": dev.device_kind,
    "platform": dev.platform,
    "count_path": result.count_path,
    **chain_keys,
}))
"""

# popcount kernel evidence. argv: [mode, n_playlists, n_tracks, target_rows]
#   mode "compiled"  — real TPU kernel (interpret=False), ds2 shape
#   mode "interpret" — CPU stand-in (interpret=True), small shape, so a
#     CPU-only round still carries config-4 kernel evidence (VERDICT r2 #4)
# Both assert count equality vs the dense MXU path and report the
# closed-form word-op count (V_pad²·W_pad) → words/s (VERDICT r2 #2).
_POPCOUNT_BENCH = r"""
import json, statistics, sys, time
import numpy as np
import jax, jax.numpy as jnp
from kmlserver_tpu.data.synthetic import synthetic_baskets
from kmlserver_tpu.ops import encode, support
from kmlserver_tpu.ops import popcount as pc

mode = sys.argv[1]
n_playlists, n_tracks, target_rows = map(int, sys.argv[2:5])
interpret = mode == "interpret"

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind}), mode={mode}",
      file=sys.stderr, flush=True)
baskets = synthetic_baskets(
    n_playlists=n_playlists, n_tracks=n_tracks, target_rows=target_rows,
    seed=123)
pr = jnp.asarray(baskets.playlist_rows)
ti = jnp.asarray(baskets.track_ids)
kw = dict(n_playlists=baskets.n_playlists, n_tracks=baskets.n_tracks)

dense_fn = jax.jit(lambda a, b: support.pair_counts(encode.onehot_matrix(a, b, **kw)))
dense = dense_fn(pr, ti)
dense.block_until_ready()  # warm-up/compile

def med(fn, n=5):
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn().block_until_ready()
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts) * 1e3

def amortized(fn, n=20):
    # pipeline n async dispatches, block once: device throughput without
    # the per-call host<->device round trip (~65ms over the remote tunnel)
    fn().block_until_ready()
    t0 = time.perf_counter()
    jax.block_until_ready([fn() for _ in range(n)])
    return (time.perf_counter() - t0) / n * 1e3

# closed-form kernel work: every (i, j) output tile row processes W_pad
# words (AND + popcount + accumulate per word) → V_pad² · W_pad word-ops
v_pad, w_pad = pc.padded_shape(baskets.n_tracks, baskets.n_playlists)
word_ops = v_pad * v_pad * w_pad

reps = 2 if interpret else 5

# the production-default bit-packed impl: blocked unpack-matmul on the MXU
# (pure XLA — native on every backend, never interpreted)
mxu_keys = {}
mxu_fn = lambda: pc.popcount_pair_counts(
    baskets.playlist_rows, baskets.track_ids, impl="mxu", **kw)
try:
    res = mxu_fn()
    res.block_until_ready()
    np.testing.assert_array_equal(np.asarray(dense), np.asarray(res))
    print("bitpack[mxu] == dense (compiled): EXACT", file=sys.stderr, flush=True)
    mxu_keys["mxu_ms"] = med(mxu_fn, n=reps)
    mxu_keys["mxu_words_per_s"] = word_ops / (mxu_keys["mxu_ms"] / 1e3)
except Exception as exc:
    print(f"bitpack[mxu] failed: {type(exc).__name__}: "
          f"{(str(exc).splitlines() or [repr(exc)])[0][:300]}", file=sys.stderr, flush=True)

# the Pallas VPU kernel: try each (variant, popcount-impl) config until one
# compiles AND matches the dense counts exactly; report which. (Mosaic
# lowering can't be pre-verified off-hardware.)
chosen = None
for variant, swar in (("bcast", False), ("row", False),
                      ("bcast", True), ("row", True)):
    label = f"{variant}{'-swar' if swar else ''}"
    try:
        res = pc.popcount_pair_counts(
            baskets.playlist_rows, baskets.track_ids, impl="vpu",
            interpret=interpret, variant=variant, swar=swar, **kw)
        res.block_until_ready()
        np.testing.assert_array_equal(np.asarray(dense), np.asarray(res))
        print(f"popcount[{label}] == dense ({mode}): EXACT",
              file=sys.stderr, flush=True)
        chosen = (variant, swar, label)
        break
    except Exception as exc:
        print(f"popcount[{label}] failed: {type(exc).__name__}: "
              f"{(str(exc).splitlines() or [repr(exc)])[0][:300]}", file=sys.stderr, flush=True)
if chosen is None and not mxu_keys:
    print("all bit-packed counting impls failed to compile/run on this backend",
          file=sys.stderr, flush=True)
    sys.exit(1)

dense_ms = med(lambda: dense_fn(pr, ti))
if chosen is not None:
    variant, swar, label = chosen
    pc_fn = lambda: pc.popcount_pair_counts(
        baskets.playlist_rows, baskets.track_ids, impl="vpu",
        interpret=interpret, variant=variant, swar=swar, **kw)
    pc_ms = med(pc_fn, n=reps)
else:
    # VPU kernel unusable here; the MXU impl carries the popcount keys
    label = "mxu"
    pc_fn = mxu_fn
    pc_ms = mxu_keys["mxu_ms"]
out = {
    "dense_ms": dense_ms, "popcount_ms": pc_ms, "exact": True,
    "kernel": label, "mode": mode,
    "v_pad": v_pad, "w_pad": w_pad, "word_ops": word_ops,
    "words_per_s": word_ops / (pc_ms / 1e3),
    "shape": f"{n_playlists}x{n_tracks}",
}
out.update(mxu_keys)
if not interpret:
    # the kernel's true device rate (interpret mode is host-python slow,
    # amortizing it tells nothing) — this is the number that anchors
    # SCALE.md's VPU-rate extrapolation constant
    pc_amort_ms = amortized(pc_fn)
    dense_amort_ms = amortized(lambda: dense_fn(pr, ti))
    out["popcount_amortized_ms"] = pc_amort_ms
    out["dense_amortized_ms"] = dense_amort_ms
    out["words_per_s"] = word_ops / (pc_amort_ms / 1e3)
    out["words_per_s_blocked"] = word_ops / (pc_ms / 1e3)
    if mxu_keys:
        # when the VPU kernel failed entirely, pc_fn IS mxu_fn and the
        # amortized number above already measured it — don't pay another
        # 20 tunnel dispatches for a copy
        out["mxu_amortized_ms"] = (
            amortized(mxu_fn) if chosen is not None else pc_amort_ms
        )
        out["mxu_words_per_s"] = word_ops / (out["mxu_amortized_ms"] / 1e3)
print(json.dumps(out))
"""

_SERVING_BENCH = r"""
import json, sys, time
import numpy as np
import jax, jax.numpy as jnp
from kmlserver_tpu.ops.serve import recommend_batch

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
with np.load(sys.argv[1]) as z:
    rule_ids = jax.device_put(jnp.asarray(z["rule_ids"]))
    rule_confs = jax.device_put(jnp.asarray(z["rule_confs"]))
v = rule_ids.shape[0]
rng = np.random.default_rng(0)
seeds = jnp.asarray(rng.integers(0, v, size=(32, 8), dtype=np.int32))
recommend_batch(rule_ids, rule_confs, seeds, k_best=10)[0].block_until_ready()
lat = []
for _ in range(50):
    t0 = time.perf_counter()
    recommend_batch(rule_ids, rule_confs, seeds, k_best=10)[0].block_until_ready()
    lat.append(time.perf_counter() - t0)
lat.sort()
# pipelined rate: batches/s the device could sustain if requests kept the
# queue full (per-call p50 includes one full host<->device round trip)
t0 = time.perf_counter()
jax.block_until_ready([
    recommend_batch(rule_ids, rule_confs, seeds, k_best=10)[0]
    for _ in range(50)
])
amortized_ms = (time.perf_counter() - t0) / 50 * 1e3
# batch 256: the tunnel-riding replay config (KMLS_BATCH_MAX_SIZE=256 —
# the batcher self-sizes toward this under RTT backpressure); its
# on-device time anchors the throughput claim (256/amortized_s QPS/batch)
seeds256 = jnp.asarray(rng.integers(0, v, size=(256, 8), dtype=np.int32))
recommend_batch(rule_ids, rule_confs, seeds256, k_best=10)[0].block_until_ready()
lat256 = []
for _ in range(20):
    t0 = time.perf_counter()
    recommend_batch(rule_ids, rule_confs, seeds256, k_best=10)[0].block_until_ready()
    lat256.append(time.perf_counter() - t0)
lat256.sort()
t0 = time.perf_counter()
jax.block_until_ready([
    recommend_batch(rule_ids, rule_confs, seeds256, k_best=10)[0]
    for _ in range(20)
])
amortized256_ms = (time.perf_counter() - t0) / 20 * 1e3
print(json.dumps({"p50_ms": lat[len(lat) // 2] * 1e3,
                  "amortized_ms": amortized_ms,
                  "p50_256_ms": lat256[len(lat256) // 2] * 1e3,
                  "amortized_256_ms": amortized256_ms}))
"""

# run scripts/scale_demo.py under _run_phase's retry/diagnosis machinery
# (cwd is the repo root, set by _run_phase)
_SCALE_BENCH = r"""
import runpy, sys
sys.argv = ["scale_demo"] + sys.argv[1:]
runpy.run_path("scripts/scale_demo.py", run_name="__main__")
"""

# BASELINE config 4 (10M×1M) with the workload born in HBM as a
# Bernoulli-Zipf bitset (scripts/config4_tpu.py --device-gen): no host
# generation, no bulk transfer — viable inside a short pool window
_CONFIG4_BENCH = r"""
import runpy, sys
sys.argv = ["config4_tpu"] + sys.argv[1:]
runpy.run_path("scripts/config4_tpu.py", run_name="__main__")
"""

# the reference's 68-point support sweep (machine-learning/main.py:450-473
# grid) through the count-once harness, on-device
# on-hardware tile sweep for the Pallas VPU kernel (VERDICT r4 #4):
# scripts/popcount_tune.py runs each (variant, tile) config in its own
# subprocess and prints checkpoint + winner lines. The parent process
# must NOT import jax — holding a live TPU client would wedge every
# worker on a single-tenant chip — so the watchdog's "device:" match is
# satisfied with a sentinel; real backend-hang protection is each
# worker's own --timeout, and the workers' true device lines are relayed
# as they finish.
_TUNE_BENCH = r"""
import runpy, sys
print("device: pending (tune workers own the chip)", file=sys.stderr, flush=True)
sys.argv = ["popcount_tune", "--timeout", "300"] + sys.argv[1:]
runpy.run_path("scripts/popcount_tune.py", run_name="__main__")
"""

_SWEEP_BENCH = r"""
import json, os, sys, tempfile, time
import numpy as np
import jax
from kmlserver_tpu.config import MiningConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.sweep import run_sweep

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
with tempfile.TemporaryDirectory() as base:
    csv = os.path.join(base, "2023_spotify_ds2.csv")
    write_tracks_csv(csv, synthetic_table(**DS2_SHAPE, seed=123))
    cfg = MiningConfig(base_dir=base, datasets_dir=base)
    supports = np.arange(0.03, 0.2, 0.0025)  # the reference grid
    t0 = time.perf_counter()
    records = run_sweep(cfg, supports, dataset=csv)
    total_s = time.perf_counter() - t0
emission_s = sum(r["duration_s"] for r in records)
print(json.dumps({
    "points": len(records),
    "total_s": round(total_s, 3),
    "emission_total_s": round(emission_s, 3),
    "setup_plus_count_s": round(total_s - emission_s, 3),
    "missing_at_min_support": records[0]["missing_songs"],
    "missing_at_max_support": records[-1]["missing_songs"],
    "platform": dev.platform,
}))
"""

_CSV_SETUP = r"""
import sys
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
write_tracks_csv(sys.argv[1], synthetic_table(**DS2_SHAPE, seed=123))
print("{}")
"""

# the 10k-QPS throughput phase: in-process (cache → batcher → engine, the
# same path both HTTP front ends serve) with a Zipf-skewed query mix —
# real playlist-seed traffic repeats its head, which is what the
# epoch-keyed answer cache feeds on. In-process because at 10k QPS an HTTP
# loadgen on this syscall-taxed sandbox measures the loadgen, not the
# server (the 1k replay phase keeps the full-stack HTTP bracket).
_REPLAY10K_BENCH = r"""
import dataclasses, json, os, sys, tempfile
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.replay import replay_pooled, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
qps = float(os.environ.get("KMLS_BENCH_REPLAY10K_QPS", "10000"))
n_req = int(os.environ.get("KMLS_BENCH_REPLAY10K_REQUESTS", "40000"))
zipf_s = float(os.environ.get("KMLS_BENCH_REPLAY10K_ZIPF_S", "1.1"))
with tempfile.TemporaryDirectory(prefix="kmls_replay10k_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    run_mining_job(
        MiningConfig(base_dir=base, datasets_dir=ds_dir, min_support=0.05)
    )
    # shedding off for this bracket: overload must surface as LATENCY
    # (replay_pooled times from the scheduled arrival), not as 429 drops
    # that would void the zero-errors claim while hiding the tail
    cfg = dataclasses.replace(
        ServingConfig.from_env(), base_dir=base,
        batch_max_size=64, shed_queue_budget_ms=0.0,
    )
    app = RecommendApp(cfg)
    assert app.engine.load(), "mined artifacts must load"

    def make_send():
        def send(seeds):
            recs, source, cached = app.recommend_direct(seeds)
            return source, cached
        return send

    vocab = app.engine.bundle.vocab
    payloads = sample_seed_sets(vocab, n_req, rng_seed=11, zipf_s=zipf_s)
    # warm the answer cache + jit/native paths with the same Zipf pool
    # (steady state is what 10k QPS sustains; the measured hit ratio
    # below still comes only from the measured run's own responses)
    replay_pooled(
        make_send, payloads[: min(4000, n_req)], qps=qps / 4, n_workers=16
    )
    # 16 workers, not 64: with a warm cache most requests are dictionary
    # lookups, and on a small host the extra threads only convoy on the
    # GIL — measured here, 64 workers capped the whole phase at ~5.3k
    # QPS while 16 clear the target with headroom
    report = replay_pooled(
        make_send, payloads, qps=qps, n_workers=16, max_queue=8192
    )
    counts = list(app.engine.dispatch_counts)
    print(json.dumps({
        "qps": qps,
        "offered_qps": report.offered_qps,
        "achieved_qps": report.achieved_qps,
        "p50_ms": report.p50_ms,
        "p95_ms": report.p95_ms,
        "p99_ms": report.p99_ms,
        "errors": report.n_errors,
        "cache_hit_ratio": report.cache_hit_ratio,
        "cached_p50_ms": report.cached_p50_ms,
        "uncached_p50_ms": report.uncached_p50_ms,
        "zipf_s": zipf_s,
        "per_device_dispatch": counts,
        "devices_active": sum(1 for c in counts if c > 0),
        "n_replicas": app.engine.n_replicas,
        "platform": dev.platform,
    }))
"""

# the chaos phase: 1k-QPS replay through cache → batcher → two engine
# replicas while one replica is KILLED mid-run (permanent kernel fault via
# kmlserver_tpu/faults.py). Reports recovery time (kill → circuit-breaker
# ejection), degraded-answer count, and — the acceptance bar — zero 5xx /
# zero errors: every request is answered from the surviving replica
# (re-dispatch) or degrades to the popularity fallback. In-process for the
# same reason as replay10k: at QPS scale an HTTP loadgen on this sandbox
# measures the loadgen.
_CHAOS_BENCH = r"""
import dataclasses, json, os, sys, tempfile, threading, time
import jax
from kmlserver_tpu import faults
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.replay import replay_pooled, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
qps = float(os.environ.get("KMLS_BENCH_CHAOS_QPS", "1000"))
n_req = int(os.environ.get("KMLS_BENCH_CHAOS_REQUESTS", "8000"))
zipf_s = float(os.environ.get("KMLS_BENCH_CHAOS_ZIPF_S", "1.1"))
with tempfile.TemporaryDirectory(prefix="kmls_chaos_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    run_mining_job(
        MiningConfig(base_dir=base, datasets_dir=ds_dir, min_support=0.05)
    )
    # two device-path replicas (the native host kernel is single-replica
    # by design); shedding off so overload surfaces as latency, not 429s
    # that would muddy the zero-errors claim; a generous deadline so only
    # a genuine stall degrades, and a probe interval past the run length
    # so the killed replica stays out (recovery time stays well-defined)
    cfg = dataclasses.replace(
        ServingConfig.from_env(), base_dir=base,
        serve_devices=2, native_serve=False,
        batch_max_size=64, shed_queue_budget_ms=0.0,
        replica_eject_threshold=3, replica_probe_interval_s=3600.0,
        # >= eject_threshold: a request can be failed at most
        # eject_threshold times by one sick replica before the breaker
        # removes it, so this bound guarantees zero request deaths
        redispatch_max_retries=3,
        request_deadline_ms=2000.0,
    )
    app = RecommendApp(cfg)
    assert app.engine.load(), "mined artifacts must load"
    assert app.engine.n_replicas == 2, "two serving replicas required"
    http_5xx = [0]
    lock = threading.Lock()

    def make_send():
        def send(seeds):
            status, headers, _ = app.handle(
                "POST", "/api/recommend/",
                json.dumps({"songs": seeds}).encode(),
            )
            if status >= 500:
                with lock:
                    http_5xx[0] += 1
                raise RuntimeError(f"HTTP {status}")
            if status != 200:
                raise RuntimeError(f"HTTP {status}")
            return ("degraded" if "X-KMLS-Degraded" in headers else "ok"), None
        return send

    vocab = app.engine.bundle.vocab
    # the same Zipf-skewed mix replay10k uses (real playlist-seed traffic
    # repeats its head): cache hits resolve inline, misses exercise the
    # batcher/replica path — the killed replica is hit by every miss
    payloads = sample_seed_sets(vocab, n_req, rng_seed=7, zipf_s=zipf_s)
    # 32 workers, unlike replay10k's 16: these sends BLOCK on the batch
    # future (device path, near-zero cache hits on distinct seeds), so
    # worker count caps concurrency by Little's law — 16 blocked workers
    # at ~25ms/batch capped the loadgen at ~600 QPS — while 64 threads
    # convoy on the GIL of a small host and made it WORSE (380 QPS)
    replay_pooled(make_send, payloads[:1000], qps=qps / 2, n_workers=32)

    kill_t = [None]
    recovery_ms = [None]

    def killer():
        # kill replica 1 at ~40% through the measured run
        time.sleep((n_req / qps) * 0.4)
        kill_t[0] = time.perf_counter()
        faults.inject("replica.kernel", replica=1, times=-1)
        print("chaos: replica 1 killed", file=sys.stderr, flush=True)
        while time.perf_counter() - kill_t[0] < 30.0:
            if app.batcher.ejected_replicas() == [1]:
                recovery_ms[0] = (time.perf_counter() - kill_t[0]) * 1e3
                print(
                    f"chaos: replica 1 ejected after "
                    f"{recovery_ms[0]:.0f}ms", file=sys.stderr, flush=True,
                )
                return
            time.sleep(0.005)

    kt = threading.Thread(target=killer, daemon=True)
    kt.start()
    report = replay_pooled(
        make_send, payloads, qps=qps, n_workers=32, max_queue=8192
    )
    kt.join(timeout=35.0)
    print(json.dumps({
        "qps": qps,
        "offered_qps": report.offered_qps,
        "achieved_qps": report.achieved_qps,
        "p50_ms": report.p50_ms,
        "p99_ms": report.p99_ms,
        "errors": report.n_errors,
        "http_5xx": http_5xx[0],
        "degraded_answers": report.by_source.get("degraded", 0),
        "ok_answers": report.by_source.get("ok", 0),
        "redispatched": app.batcher.redispatch_total,
        "ejections": app.batcher.eject_total,
        "eject_recovery_ms": recovery_ms[0],
        "zipf_s": zipf_s,
        "cache_hit_ratio": app.cache.hit_ratio() if app.cache else None,
        "platform": dev.platform,
    }))
"""

# the continuous-freshness phase (ISSUE 10): the delta path's whole
# reason to exist is freshness lag — how long after new rows land does
# serving answer from them? Three judged brackets in one in-process run
# (CPU-platform by construction, self-labeled):
#   full path  — a second FULL re-mine + full reload on the ds2 shape:
#                the baseline freshness lag (mine + republish + swap);
#   delta path — append ~2% new rows, run the SAME pipeline entry (it
#                takes the delta route), and measure publish→applied
#                into the live engine through the production poll loop,
#                with a 1k-QPS-class Zipf replay running THROUGH the
#                apply: freshness_speedup = full_path_s / delta_path_s
#                (acceptance: ≥ 5x) and zero 5xx mid-apply;
#   fleet      — the 3-replica effective-hit-ratio multiplier from
#                freshness/ring.py's simulated topology (affinity vs
#                round-robin over the same key stream) — the ROADMAP's
#                measure-before-committing decision number.
# Selective invalidation is judged by the hit ratio: the delta touches a
# handful of vocab rows, so the Zipf head's cache entries must SURVIVE
# the apply (a wholesale epoch bump would re-compute all of them).
_FRESHNESS_BENCH = r"""
import dataclasses, json, os, sys, tempfile, threading, time
import numpy as np
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.replay import replay_pooled, sample_seed_sets
from kmlserver_tpu.freshness.ring import fleet_multiplier, seeds_key

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
qps = float(os.environ.get("KMLS_BENCH_FRESHNESS_QPS", "800"))
n_req = int(os.environ.get("KMLS_BENCH_FRESHNESS_REQUESTS", "6000"))
with tempfile.TemporaryDirectory(prefix="kmls_fresh_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    csv_path = os.path.join(ds_dir, "2023_spotify_ds2.csv")
    write_tracks_csv(csv_path, synthetic_table(**DS2_SHAPE, seed=123))
    mcfg = MiningConfig(
        base_dir=base, datasets_dir=ds_dir, min_support=0.05,
        delta_enabled=True,
    )
    run_mining_job(mcfg)  # base generation (arms the freshness state)
    cfg = dataclasses.replace(
        ServingConfig.from_env(), base_dir=base, delta_enabled=True,
        batch_max_size=64, shed_queue_budget_ms=0.0,
    )
    app = RecommendApp(cfg)
    assert app.engine.load(), "mined artifacts must load"

    # ---- full path baseline: re-mine everything + full reload (warm
    # jit; delta off — with it on, an unchanged dataset is a designed
    # no-op). This is exactly what the pre-delta GitOps posture pays on
    # EVERY sync cadence tick. Median of 3 — single-shot wall clocks on
    # a shared host are noisy enough to swing the speedup ratio 2x
    # (same discipline as loadshape's runs_p99_ms).
    full_runs = []
    for _ in range(3):
        t0 = time.perf_counter()
        run_mining_job(dataclasses.replace(mcfg, delta_enabled=False))
        assert app.engine.is_data_stale(), "full publication must rewrite the token"
        assert app.engine.load(), "full reload must succeed"
        full_runs.append(time.perf_counter() - t0)
    full_path_s = sorted(full_runs)[1]

    # re-arm the freshness base at the CURRENT generation (the baseline
    # run above retired the old base state by rewriting the token): the
    # delta route detects the mismatch and falls through to a full
    # re-mine that saves a fresh base. Untimed — arming, not the race.
    run_mining_job(mcfg)
    assert app.engine.load(), "re-arm reload must succeed"

    # appended rows concentrate on a ~128-track slice of the catalog —
    # the locality real incremental feeds have (uniform appends would
    # touch nearly every vocab column and degenerate the delta into a
    # full recount, which run_delta_job would do correctly but slowly)
    rng = np.random.default_rng(7)
    n_tracks = DS2_SHAPE["n_tracks"]
    def append_rows(first_pid, lo):
        lines = []
        for p in range(24):
            pid = first_pid + p
            for t in lo + rng.integers(0, 128, size=90):
                t = int(t)
                lines.append(
                    f"{pid},Track {t:07d},spotify:track:{t:07d},"
                    f"Artist {t % 997:04d},spotify:artist:{t % 997:04d},"
                    f"Album {t // 12:06d}"
                )
        # plus a brand-new track (vocabulary growth in a delta)
        t = 9_000_000 + first_pid % 1000
        lines.append(
            f"{first_pid},Track {t:07d},spotify:track:{t:07d},"
            f"Artist 0000,spotify:artist:0000,Album 000000"
        )
        with open(csv_path, "a") as fh:
            fh.write("\n".join(lines) + "\n")

    # ---- the production poll loop, 20 ms cadence ----
    stop = [False]
    def poller():
        while not stop[0]:
            app.engine.reload_if_required()
            time.sleep(0.02)
    pt = threading.Thread(target=poller, daemon=True)
    pt.start()

    # ---- idle deltas 1-3 (apples-to-apples with the idle full
    # baseline): append → the SAME pipeline entry takes the delta route
    # → publish → applied into the live engine by the poll loop.
    # Median of 3 cycles, mirroring the baseline's discipline.
    delta_runs, publish_runs, apply_gaps = [], [], []
    for cycle in range(3):
        append_rows(10_000_000 + cycle * 1_000, 96 + cycle * 160)
        t1 = time.perf_counter()
        summary = run_mining_job(mcfg)
        published_s = time.perf_counter() - t1
        assert summary.delta_seq == cycle + 1, (
            f"delta never published: {summary}"
        )
        t2 = time.perf_counter()
        while (
            app.engine.delta_seq < cycle + 1
            and time.perf_counter() - t2 < 30.0
        ):
            time.sleep(0.002)
        assert app.engine.delta_seq == cycle + 1, (
            f"delta {cycle + 1} never applied in serving"
        )
        delta_runs.append(time.perf_counter() - t1)
        publish_runs.append(published_s)
        apply_gaps.append((time.perf_counter() - t2) * 1e3)
    delta_path_s = sorted(delta_runs)[1]
    published_s = sorted(publish_runs)[1]
    publish_to_applied_ms = sorted(apply_gaps)[1]
    n_idle_deltas = 3

    http_5xx = [0]
    lock = threading.Lock()
    def make_send():
        def send(seeds):
            status, headers, _ = app.handle(
                "POST", "/api/recommend/",
                json.dumps({"songs": seeds}).encode(),
            )
            if status >= 500:
                with lock:
                    http_5xx[0] += 1
                raise RuntimeError(f"HTTP {status}")
            if status != 200:
                raise RuntimeError(f"HTTP {status}")
            cached = headers.get("X-KMLS-Cache") == "hit"
            return ("degraded" if "X-KMLS-Degraded" in headers else "ok",
                    cached)
        return send

    vocab = app.engine.bundle.vocab
    payloads = sample_seed_sets(vocab, n_req, rng_seed=11, zipf_s=1.1)
    # warm the Zipf head so the mid-replay apply hits a POPULATED cache —
    # survival of those entries is the selective-invalidation claim
    replay_pooled(make_send, payloads[: min(3000, n_req)],
                  qps=qps, n_workers=16)
    hits_before = app.cache.hits if app.cache else 0

    # ---- final delta, mid-replay: zero 5xx through the in-place apply
    mid_seq = n_idle_deltas + 1
    delta_mid = {}
    def run_delta_mid():
        append_rows(20_000_000, 640)
        t3 = time.perf_counter()
        s_mid = run_mining_job(mcfg)
        delta_mid["seq"] = s_mid.delta_seq
        while (
            app.engine.delta_seq < mid_seq
            and time.perf_counter() - t3 < 30.0
        ):
            time.sleep(0.002)
        delta_mid["applied_s"] = time.perf_counter() - t3
    mid_thread = threading.Thread(target=run_delta_mid, daemon=True)
    events = [(int(n_req * 0.25), mid_thread.start)]
    report = replay_pooled(
        make_send, payloads, qps=qps, n_workers=16, max_queue=8192,
        events=events,
    )
    # the replay can drain before a slow host finishes the mid-replay
    # mine: join the delta (and leave the poller running to apply it)
    # BEFORE asserting, or the assertions race the publication. ident
    # guard: joining a never-started thread (event never fired) raises
    if mid_thread.ident is not None:
        mid_thread.join(timeout=60.0)
    stop[0] = True
    pt.join(timeout=5.0)
    assert delta_mid.get("seq") == mid_seq, (
        f"mid-replay delta never published: {delta_mid}"
    )
    assert app.engine.delta_seq == mid_seq, (
        "mid-replay delta never applied in serving"
    )

    # ---- fleet multiplier: 3-replica simulated topology ----
    keys = [seeds_key(p) for p in payloads]
    fleet = fleet_multiplier(keys, n_replicas=3, capacity=512)

    cache = app.cache
    print(json.dumps({
        "qps": qps,
        "achieved_qps": report.achieved_qps,
        "p50_ms": report.p50_ms,
        "p99_ms": report.p99_ms,
        "errors": report.n_errors,
        "http_5xx": http_5xx[0],
        "full_path_s": full_path_s,
        "delta_path_s": delta_path_s,
        "delta_publish_s": published_s,
        "publish_to_applied_ms": publish_to_applied_ms,
        "delta_underload_s": delta_mid.get("applied_s"),
        "speedup": full_path_s / delta_path_s,
        "delta_applied_total": app.engine.delta_applied_total,
        "delta_rejected_total": app.engine.delta_rejected_total,
        "freshness_lag_s": app.engine.freshness_lag_s(),
        "cache_hit_ratio": cache.hit_ratio() if cache else None,
        "cache_hits_after_warm": (cache.hits - hits_before) if cache else None,
        "cache_invalidated_keys": cache.invalidated_keys if cache else None,
        "cache_selective_invalidations": (
            cache.selective_invalidations if cache else None
        ),
        "fleet_affinity_hit_ratio": fleet["affinity_hit_ratio"],
        "fleet_baseline_hit_ratio": fleet["baseline_hit_ratio"],
        "fleet_multiplier": fleet["multiplier"],
        "platform": dev.platform,
    }))
"""

# the storage gray-failure phase (ISSUE 19): the SAME in-process app the
# freshness bracket uses, with the artifact plane stall/ENOSPC-injected
# through the path-scoped io.* fault sites. Four legs: (1) clean control
# replay; (2) replay with every PVC read stalled 400 ms — serving runs
# from memory so p99 must not move, the reload (armed by a mid-leg
# invalidation) parks in bounded backoff at the read deadline with
# last-good serving, and the token-poll latency EWMA convicts
# storage-slow (/readyz ready-but-degraded); (3) ENOSPC exactly on the
# recommendations write of the next publication — resumable exit
# classification, token unconsumed, last-good BIT-IDENTICAL (sha256),
# no torn temp files, serving probe still 200; (4) clean re-publish
# recovers end-to-end. Zero 5xx across all legs.
_GRAYSTORE_BENCH = r"""
import dataclasses, errno, hashlib, json, os, sys, tempfile, threading, time
import jax
from kmlserver_tpu import faults
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.io import artifacts, iohealth, registry
from kmlserver_tpu.mining.job import EXIT_RESUMABLE, classify_exception
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.replay import replay_pooled, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
qps = float(os.environ.get("KMLS_BENCH_GRAYSTORE_QPS", "1000"))
n_req = int(os.environ.get("KMLS_BENCH_GRAYSTORE_REQUESTS", "6000"))
STALL_MS = 400.0  # > the 250 ms conviction default, < any replay budget
with tempfile.TemporaryDirectory(prefix="kmls_graystore_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    mcfg = MiningConfig(base_dir=base, datasets_dir=ds_dir, min_support=0.05)
    run_mining_job(mcfg)
    cfg = dataclasses.replace(
        ServingConfig.from_env(), base_dir=base, batch_max_size=64,
        shed_queue_budget_ms=0.0, io_read_deadline_s=0.15,
        reload_backoff_base_s=0.5, reload_backoff_max_s=4.0,
    )
    app = RecommendApp(cfg)
    assert app.engine.load(), "mined artifacts must load"
    pickles = os.path.join(base, "pickles")
    rec_path = os.path.join(pickles, mcfg.recommendations_file)

    http_5xx = [0]
    lock = threading.Lock()
    def send(seeds):
        status, headers, _ = app.handle(
            "POST", "/api/recommend/", json.dumps({"songs": seeds}).encode(),
        )
        if status >= 500:
            with lock:
                http_5xx[0] += 1
            raise RuntimeError(f"HTTP {status}")
        if status != 200:
            raise RuntimeError(f"HTTP {status}")
        return ("degraded" if "X-KMLS-Degraded" in headers else "ok", False)

    vocab = app.engine.bundle.vocab
    payloads = sample_seed_sets(vocab, n_req, rng_seed=11, zipf_s=1.1)

    # ---- leg 1: clean control ----
    control = replay_pooled(lambda: send, payloads, qps=qps, n_workers=16,
                            max_queue=8192)

    # ---- leg 2: every PVC read stalls 400 ms ----
    # the production poll loop keeps running (its token reads ARE the
    # conviction evidence); an invalidation mid-stall arms a reload that
    # must fail at the read deadline into backoff, not wedge
    stop = [False]
    def poller():
        while not stop[0]:
            app.engine.reload_if_required()
            time.sleep(0.02)
    pt = threading.Thread(target=poller, daemon=True)
    pt.start()
    token_before = app.engine.cache_value
    registry.append_history_and_invalidate(
        MiningConfig(base_dir=base), 1, "graystore-ds"
    )
    faults.inject("io.read", delay_s=STALL_MS / 1e3, times=-1)
    stalled = replay_pooled(lambda: send, payloads, qps=qps, n_workers=16,
                            max_queue=8192)
    # drive conviction to its sample floor: each pure staleness check IS
    # a stalled 400 ms token poll (production reaches the floor over
    # minutes of polling; the bench compresses that to ~3 s)
    for _ in range(12):
        if iohealth.MONITOR.storage_slow():
            break
        app.engine.is_data_stale()
    storage_slow = iohealth.MONITOR.storage_slow()
    reload_deferred = app.engine.consecutive_reload_failures >= 1
    backoff_bounded = (
        app.engine._backoff_until > 0.0
        and app.engine._backoff_until - time.monotonic() <= 8.0
    )
    last_good_held = (
        app.engine.finished_loading
        and app.engine.cache_value == token_before
    )
    status, _, payload = app.handle("GET", "/readyz", b"")
    readyz = json.loads(payload)
    readyz_degraded = (
        status == 200 and readyz.get("status") == "degraded"
        and "storage-slow" in readyz.get("reasons", ())
    )
    faults.clear()
    iohealth.MONITOR.reset()
    # drain the pending invalidation (loop: the poller may hold the
    # reload lock mid-stall for one last 400 ms read)
    deadline = time.monotonic() + 30.0
    while (
        app.engine.cache_value == token_before
        and time.monotonic() < deadline
    ):
        app.engine._backoff_until = 0.0
        app.engine.reload_if_required()
        time.sleep(0.05)
    assert app.engine.cache_value != token_before, (
        "reload must recover once the stall clears"
    )

    # ---- leg 3: ENOSPC exactly on the recommendations write ----
    with open(rec_path, "rb") as fh:
        sha_before = hashlib.sha256(fh.read()).hexdigest()
    token_path = registry.token_path_for(base, mcfg.data_invalidation_file)
    with open(token_path) as fh:
        disk_token_before = fh.read()
    faults.inject("io.write", kind="enospc", times=1, path="recommendations")
    enospc_exit = None
    try:
        run_mining_job(mcfg)
    except OSError as exc:
        if exc.errno == errno.ENOSPC:
            enospc_exit = classify_exception(exc)
    faults.clear()
    with open(rec_path, "rb") as fh:
        sha_after = hashlib.sha256(fh.read()).hexdigest()
    with open(token_path) as fh:
        disk_token_after = fh.read()
    torn_parts = sum(
        1 for name in os.listdir(pickles)
        if name.startswith(".tmp_") and name.endswith(".part")
    )
    probe = replay_pooled(lambda: send, payloads[:200], qps=qps,
                          n_workers=8, max_queue=8192)

    # ---- leg 4: clean re-publish recovers ----
    token_pre_recover = app.engine.cache_value
    run_mining_job(mcfg)
    deadline = time.monotonic() + 30.0
    while (
        app.engine.cache_value == token_pre_recover
        and time.monotonic() < deadline
    ):
        time.sleep(0.02)
    recovered = app.engine.cache_value != token_pre_recover
    stop[0] = True
    pt.join(timeout=5.0)

    print(json.dumps({
        "qps": qps,
        "requests": n_req,
        "stall_ms": STALL_MS,
        "control_p50_ms": control.p50_ms,
        "control_p99_ms": control.p99_ms,
        "stalled_p50_ms": stalled.p50_ms,
        "stalled_p99_ms": stalled.p99_ms,
        "p99_ratio": stalled.p99_ms / max(control.p99_ms, 1e-9),
        "storage_slow": bool(storage_slow),
        "readyz_degraded": bool(readyz_degraded),
        "reload_deferred": bool(reload_deferred),
        "backoff_bounded": bool(backoff_bounded),
        "last_good_held": bool(last_good_held),
        "enospc_exit": enospc_exit,
        "enospc_exit_resumable": enospc_exit == EXIT_RESUMABLE,
        "enospc_identical": sha_after == sha_before,
        "enospc_token_moved": disk_token_after != disk_token_before,
        "torn_parts": torn_parts,
        "probe_p99_ms": probe.p99_ms,
        "recovered": bool(recovered),
        "io_retries": iohealth.MONITOR.snapshot()["retries"],
        "http_5xx": http_5xx[0],
        "errors": (control.n_errors + stalled.n_errors + probe.n_errors),
        "platform": dev.platform,
    }))
"""

# the fleet cache-routing phase (ISSUE 15): N REAL server processes +
# the client-side consistent-hash router vs the same fleet under
# round-robin (independent caches) — the bracket that falsifies (or
# confirms) the PR 10 SIMULATED fleet multiplier with real sockets.
# Judged claims:
#   multiplier — routed fleet hit ratio >= independent x the simulated
#                multiplier (within 10%), judged on the pre-kill window
#                so the kill's cold remap doesn't blur the comparison;
#                the Zipf pool is sized past one replica's LRU (the
#                regime the tier exists for: no single pod can hold the
#                head, the fleet together can);
#   kill       — one replica SIGKILLed mid-replay: the router ejects it
#                (PR 3 breaker semantics) and spills its keys to their
#                next-highest rendezvous weight — zero 5xx, survivors
#                absorb, owner-stamped (misrouted) responses appear;
#   delta      — a delta publication lands mid-replay: every survivor
#                applies it in place with SELECTIVE per-seed
#                invalidation, and post-run probes pin answer identity
#                across survivors (per-shard invalidation held).
_FLEET_BENCH = r"""
import dataclasses, json, os, pickle, re, subprocess, sys, tempfile
import threading, time, urllib.request
import numpy as np
import jax
from kmlserver_tpu.config import MiningConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.freshness.ring import seeds_key, simulate_fleet
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.replay import replay_fleet_http, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
qps = float(os.environ.get("KMLS_BENCH_FLEET_QPS", "10500"))
n_req = int(os.environ.get("KMLS_BENCH_FLEET_REQUESTS", "42000"))
n_replicas = int(os.environ.get("KMLS_BENCH_FLEET_REPLICAS", "3"))
cache_entries = int(os.environ.get("KMLS_BENCH_FLEET_CACHE", "512"))
# Zipf pool wider than ONE replica's LRU but within the fleet's
# aggregate — the exact regime the routing tier exists for
pool = int(cache_entries * (n_replicas + 1.5))
peers = [f"replica-{i}" for i in range(n_replicas)]
peers_csv = ",".join(peers)

with tempfile.TemporaryDirectory(prefix="kmls_fleet_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    csv_path = os.path.join(ds_dir, "2023_spotify_ds2.csv")
    write_tracks_csv(csv_path, synthetic_table(**DS2_SHAPE, seed=123))
    mcfg = MiningConfig(
        base_dir=base, datasets_dir=ds_dir, min_support=0.05,
        delta_enabled=True,
    )
    run_mining_job(mcfg)  # base generation (arms the freshness state)
    with open(
        os.path.join(base, "pickles", "recommendations.pickle"), "rb"
    ) as fh:
        vocab = sorted(pickle.load(fh).keys())

    # ---- N real server processes, stable identities replica-0..N-1,
    # one shared PVC-shaped base dir — the statefulset.yaml topology
    # mirrored locally by the KMLS_FLEET_* knobs. Everything from the
    # first spawn runs under try/finally: a failed assert/probe must
    # not orphan N jax servers into the rest of the bench run (the
    # parent only killpg's this phase on TIMEOUT, not on nonzero exit,
    # and a retry would double the orphans).
    procs, ports, logs = [], {}, {}
    def _terminate_all():
        for proc in procs:
            try:
                proc.terminate()
            except Exception:
                pass
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    def start_server(i):
        env = dict(os.environ)
        env.update({
            "BASE_DIR": base, "KMLS_PORT": "0",
            # fast poll so the mid-replay delta publication is applied
            # within ~0.3s on every replica
            "POLLING_WAIT_IN_MINUTES": "0.005",
            "KMLS_DELTA_ENABLED": "1",
            "KMLS_CACHE_MAX_ENTRIES": str(cache_entries),
            "KMLS_SHED_QUEUE_BUDGET_MS": "0",
            "KMLS_FLEET_SELF": peers[i],
            "KMLS_FLEET_PEERS": peers_csv,
        })
        proc = subprocess.Popen(
            [sys.executable, "-m", "kmlserver_tpu.serving.server"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        lines = []
        logs[i] = lines
        def drain():
            for line in proc.stdout:
                lines.append(line.rstrip())
                m = re.search(r"serving on \S+?:(\d+)", line)
                if m and i not in ports:
                    ports[i] = int(m.group(1))
        threading.Thread(target=drain, daemon=True).start()
        return proc

    try:
        for i in range(n_replicas):
            procs.append(start_server(i))
        t_wait = time.time()
        while len(ports) < n_replicas and time.time() - t_wait < 120:
            time.sleep(0.1)
        assert len(ports) == n_replicas, f"servers never reported ports: {ports}"
        urls = {peers[i]: f"http://127.0.0.1:{ports[i]}" for i in range(n_replicas)}
        def wait_ready(url, deadline_s=180):
            t0 = time.time()
            while time.time() - t0 < deadline_s:
                try:
                    with urllib.request.urlopen(url + "/readyz", timeout=5) as r:
                        if r.status == 200:
                            return True
                except Exception:
                    pass
                time.sleep(0.25)
            return False
        for p_name, url in urls.items():
            assert wait_ready(url), f"{p_name} never went ready"
        print(f"fleet up: {urls}", file=sys.stderr, flush=True)

        def scrape(url):
            with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
                text = r.read().decode()
            out = {}
            for line in text.splitlines():
                if line and not line.startswith("#"):
                    parts = line.split()
                    if len(parts) == 2:
                        try:
                            out[parts[0]] = float(parts[1])
                        except ValueError:
                            pass
            return out

        # the judged hit-ratio window ends BEFORE either mid-replay
        # event: the delta's selective invalidations + in-process mining
        # contention and the kill's cold remap all land on the routed
        # leg only, and simulate_fleet models neither — judging the
        # event-free prefix keeps the multiplier comparison apples-to-
        # apples (both legs AND the simulation see the same cold-start-
        # to-warm window); the delta and the kill stay genuinely
        # mid-replay for the zero-5xx claims
        window_end = int(n_req * 0.30)
        delta_at = window_end
        kill_at = int(n_req * 0.60)

        # ---- leg A: the same fleet under round-robin — what N independent
        # epoch-keyed LRUs do today (each replica re-warms the same head).
        # Distinct rng seed from leg B: neither leg may pre-warm the other's
        # keys, so both start cold for their own population, like the
        # simulation does.
        payloads_a = sample_seed_sets(
            vocab, n_req, rng_seed=31, zipf_s=0.9, zipf_pool=pool,
        )
        rep_a, fleet_a = replay_fleet_http(
            urls, payloads_a, qps=qps, policy="roundrobin",
            window_end=window_end,
        )
        print(
            f"independent: hit {fleet_a['window_hit_ratio']:.3f} (window), "
            f"{rep_a.achieved_qps:.0f} QPS, {fleet_a['http_5xx']} 5xx",
            file=sys.stderr, flush=True,
        )
        # misrouted baseline AFTER leg A: round-robin deliberately lands
        # ~ (N-1)/N of traffic off-owner, so the drift counter must be
        # read as a DELTA over the routed leg or the baseline's designed
        # misroutes would masquerade as routing drift
        misrouted_before = {
            i: scrape(urls[peers[i]]).get("kmls_cache_misrouted_total", 0)
            for i in range(n_replicas)
        }

        # ---- leg B: consistent-hash routed, with the kill + the delta
        # landing mid-replay
        payloads_b = sample_seed_sets(
            vocab, n_req, rng_seed=32, zipf_s=0.9, zipf_pool=pool,
        )
        victim = n_replicas - 1
        delta_state = {}
        def run_delta():
            rng = np.random.default_rng(7)
            lines = []
            for p in range(24):
                pid = 30_000_000 + p
                for t in 96 + rng.integers(0, 128, size=90):
                    t = int(t)
                    lines.append(
                        f"{pid},Track {t:07d},spotify:track:{t:07d},"
                        f"Artist {t % 997:04d},spotify:artist:{t % 997:04d},"
                        f"Album {t // 12:06d}"
                    )
            with open(csv_path, "a") as fh:
                fh.write("\n".join(lines) + "\n")
            summary = run_mining_job(mcfg)
            delta_state["seq"] = summary.delta_seq
        delta_thread = threading.Thread(target=run_delta, daemon=True)
        events = [
            (delta_at, delta_thread.start),
            (kill_at, procs[victim].kill),  # SIGKILL: a real crash, no drain
        ]
        rep_b, fleet_b = replay_fleet_http(
            urls, payloads_b, qps=qps, policy="ring",
            window_end=window_end, events=events,
        )
        delta_thread.join(timeout=120)
        assert delta_state.get("seq") == 1, (
            f"mid-replay delta never published: {delta_state}"
        )
        print(
            f"routed: hit {fleet_b['window_hit_ratio']:.3f} (window), "
            f"{rep_b.achieved_qps:.0f} QPS, {fleet_b['http_5xx']} 5xx, "
            f"rerouted {fleet_b['rerouted']}, ejections {fleet_b['ejections']}",
            file=sys.stderr, flush=True,
        )

        # ---- survivors: the delta applied in place on every one, with the
        # SELECTIVE per-seed invalidation (no epoch bump), and answers stay
        # identical across replicas (per-shard invalidation identity)
        survivors = [i for i in range(n_replicas) if i != victim]
        deadline = time.time() + 60
        metrics_by = {}
        for i in survivors:
            while time.time() < deadline:
                m = scrape(urls[peers[i]])
                if m.get("kmls_delta_seq", 0) >= 1:
                    break
                time.sleep(0.25)
            metrics_by[i] = scrape(urls[peers[i]])
        delta_applied_ok = all(
            metrics_by[i].get("kmls_delta_seq", 0) >= 1
            and metrics_by[i].get("kmls_delta_applied_total", 0) >= 1
            and metrics_by[i].get("kmls_delta_rejected_total", 0) == 0
            for i in survivors
        )
        selective = sum(
            metrics_by[i].get("kmls_cache_selective_invalidations_total", 0)
            for i in survivors
        )
        # routed-leg drift only: survivors' counter growth since the leg-A
        # snapshot (all of it comes from the post-kill spill — before the
        # kill, ring routing keeps every key on its owner)
        misrouted = sum(
            metrics_by[i].get("kmls_cache_misrouted_total", 0)
            - misrouted_before[i]
            for i in survivors
        )
        def probe(url, seeds):
            body = json.dumps({"songs": seeds}).encode()
            req = urllib.request.Request(
                url + "/api/recommend/", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.load(r)["songs"]
        probe_sets = payloads_b[:4] + [["Track 0000100"], vocab[:3]]
        # cross-replica identity needs >= 2 survivors to mean anything
        # (one answer compared with itself is vacuously identical):
        # None = not claimable at this replica count, never a pass
        identity_ok = (
            all(
                len({
                    tuple(probe(urls[peers[i]], seeds)) for i in survivors
                }) == 1
                for seeds in probe_sets
            )
            if len(survivors) >= 2
            else None
        )

    finally:
        _terminate_all()

    # ---- the simulated prediction (PR 10) this run falsifies or
    # confirms: SAME ring, SAME capacity, SAME key stream, same window
    keys_b = [seeds_key(p) for p in payloads_b[:window_end]]
    sim_aff = simulate_fleet(keys_b, n_replicas, cache_entries, "affinity")
    sim_rr = simulate_fleet(keys_b, n_replicas, cache_entries, "roundrobin")
    sim_mult = (sim_aff / sim_rr) if sim_rr > 0 else float("inf")
    ach_mult = (
        fleet_b["window_hit_ratio"] / fleet_a["window_hit_ratio"]
        if fleet_a["window_hit_ratio"]
        else float("inf")
    )

    print(json.dumps({
        "qps": qps,
        "requests": n_req,
        "replicas": n_replicas,
        "cache_entries": cache_entries,
        "zipf_pool": pool,
        "independent_hit_ratio": fleet_a["window_hit_ratio"],
        "routed_hit_ratio": fleet_b["window_hit_ratio"],
        "independent_hit_ratio_full": fleet_a["hit_ratio"],
        "routed_hit_ratio_full": fleet_b["hit_ratio"],
        "multiplier_achieved": ach_mult,
        "multiplier_simulated": sim_mult,
        "multiplier_vs_simulated": (
            ach_mult / sim_mult if sim_mult > 0 else float("inf")
        ),
        "sim_affinity_hit": sim_aff,
        "sim_roundrobin_hit": sim_rr,
        "offered_qps": rep_b.offered_qps,
        "achieved_qps": rep_b.achieved_qps,
        "p50_ms": rep_b.p50_ms,
        "p99_ms": rep_b.p99_ms,
        "errors": rep_a.n_errors + rep_b.n_errors,
        "http_5xx": fleet_a["http_5xx"] + fleet_b["http_5xx"],
        "kill_peer": peers[victim],
        "rerouted": fleet_b["rerouted"],
        "router_ejections": fleet_b["ejections"],
        "router_spills": fleet_b["spills"],
        "owner_stamped": fleet_b["owner_stamped"],
        "answered_by": fleet_b["answered_by"],
        "delta_applied_ok": delta_applied_ok,
        "selective_invalidations": selective,
        "misrouted_total": misrouted,
        "identity_ok": identity_ok,
        "platform": dev.platform,
    }))
"""

# the quality-loop phase (ISSUE 14): the first bracket that measures
# whether the ANSWERS are any good, next to all the latency evidence.
# One in-process run (CPU-platform by construction, self-labeled):
#   eval     — a full pipeline run with embed + eval on publishes
#              quality.report.json: held-out basket-completion recall@k
#              / MRR / coverage per serving mode through the production
#              kernels, plus the blend-weight sweep;
#   measured — the sweep's argmax round-trips into serving: an engine
#              under KMLS_HYBRID_BLEND_WEIGHT=measured reads the report
#              and serves that exact weight (weight_roundtrip);
#   compact  — two delta publications grow the chain, then the
#              snapshotting compactor folds base ∘ chain into a new
#              base MID-REPLAY: zero 5xx through the swap, and the
#              compacted npz is bit-identical to a pristine full
#              re-mine of the final CSV (compact_identical) at a
#              fraction of its wall clock (compact_speedup).
_QUALITY_BENCH = r"""
import dataclasses, json, os, shutil, sys, tempfile, threading, time
import numpy as np
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.io import artifacts
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.quality import lifecycle
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.replay import replay_pooled, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
rows = int(os.environ.get("KMLS_BENCH_QUALITY_ROWS", str(DS2_SHAPE["target_rows"])))
scale = rows / DS2_SHAPE["target_rows"]
shape = dict(
    n_playlists=max(int(DS2_SHAPE["n_playlists"] * scale), 200),
    n_tracks=max(int(DS2_SHAPE["n_tracks"] * scale), 150),
    target_rows=rows,
)
n_req = max(800, min(4000, rows // 50))
with tempfile.TemporaryDirectory(prefix="kmls_quality_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    csv_path = os.path.join(ds_dir, "2023_spotify_ds2.csv")
    write_tracks_csv(csv_path, synthetic_table(**shape, seed=123))
    mcfg = MiningConfig(
        base_dir=base, datasets_dir=ds_dir, min_support=0.05,
        delta_enabled=True, embed_enabled=True, als_rank=16, als_iters=5,
        eval_enabled=True, eval_max_playlists=1024,
    )
    t0 = time.perf_counter()
    run_mining_job(mcfg)
    full_job_s = time.perf_counter() - t0  # incl. the eval double-train
    report = artifacts.load_quality_report(mcfg.pickles_dir)
    assert report is not None, "eval phase must publish quality.report.json"
    modes = report["modes"]
    w = report["measured_blend_weight"]

    # measured blend optimum round-trips report -> bundle -> serve time
    cfg = dataclasses.replace(
        ServingConfig.from_env(), base_dir=base, delta_enabled=True,
        hybrid_blend_measured=True, shed_queue_budget_ms=0.0,
        batch_max_size=64,
    )
    app = RecommendApp(cfg)
    assert app.engine.load(), "mined artifacts must load"
    weight_roundtrip = bool(
        w is not None and app.engine.blend_weight == w
        and app.engine.measured_blend_weight == w
    )

    # grow a 2-bundle delta chain (the compaction trigger's shape)
    rng = np.random.default_rng(7)
    n_tracks = shape["n_tracks"]
    def append_rows(first_pid, lo):
        lines = []
        for p in range(16):
            pid = first_pid + p
            for t in lo + rng.integers(0, 96, size=40):
                t = int(t) % n_tracks
                lines.append(
                    f"{pid},Track {t:07d},spotify:track:{t:07d},"
                    f"Artist {t % 997:04d},spotify:artist:{t % 997:04d},"
                    f"Album {t // 12:06d}"
                )
        with open(csv_path, "a") as fh:
            fh.write("\n".join(lines) + "\n")
    for i in range(2):
        append_rows(10_000_000 + i * 1000, 40 + 100 * i)
        s = run_mining_job(mcfg)
        assert s.delta_seq == i + 1, f"delta never published: {s}"

    # control: pristine full re-mine of the final CSV — the identity
    # bar the compacted snapshot is judged against (and the wall clock
    # the compactor avoids paying)
    ctl = os.path.join(base, "ctl")
    ctl_ds = os.path.join(ctl, "datasets")
    os.makedirs(ctl_ds)
    shutil.copy(csv_path, os.path.join(ctl_ds, os.path.basename(csv_path)))
    ctl_cfg = dataclasses.replace(
        mcfg, base_dir=ctl, datasets_dir=ctl_ds,
        delta_enabled=False, eval_enabled=False, embed_enabled=False,
    )
    t1 = time.perf_counter()
    run_mining_job(ctl_cfg)
    remine_s = time.perf_counter() - t1

    # ---- mid-replay compaction through the production poll loop ----
    stop = [False]
    def poller():
        while not stop[0]:
            app.engine.reload_if_required()
            time.sleep(0.02)
    pt = threading.Thread(target=poller, daemon=True)
    pt.start()

    http_5xx = [0]
    lock = threading.Lock()
    def make_send():
        def send(seeds):
            status, headers, _ = app.handle(
                "POST", "/api/recommend/",
                json.dumps({"songs": seeds}).encode(),
            )
            if status >= 500:
                with lock:
                    http_5xx[0] += 1
                raise RuntimeError(f"HTTP {status}")
            if status != 200:
                raise RuntimeError(f"HTTP {status}")
            return ("degraded" if "X-KMLS-Degraded" in headers else "ok",
                    headers.get("X-KMLS-Cache") == "hit")
        return send

    vocab = app.engine.bundle.vocab
    payloads = sample_seed_sets(vocab, n_req, rng_seed=11, zipf_s=1.1)
    compact = {}
    def run_compact():
        t2 = time.perf_counter()
        res = lifecycle.compact_delta_chain(mcfg)
        compact["s"] = time.perf_counter() - t2
        compact["folded"] = res.n_folded
        compact["token"] = res.token
    ct = threading.Thread(target=run_compact, daemon=True)
    events = [(int(n_req * 0.3), ct.start)]
    replay = replay_pooled(
        make_send, payloads, qps=500.0, n_workers=12, max_queue=8192,
        events=events,
    )
    assert replay.n_requests > 0, "replay generated no completed requests"
    if ct.ident is not None:
        ct.join(timeout=120.0)
    # the poller must hot-swap onto the compacted token before teardown
    deadline = time.time() + 30.0
    while (
        app.engine.cache_value != compact.get("token")
        and time.time() < deadline
    ):
        time.sleep(0.01)
    stop[0] = True
    pt.join(timeout=5.0)
    assert compact.get("folded") == 2, f"compaction never ran: {compact}"
    assert app.engine.cache_value == compact["token"], (
        "compacted generation never hot-swapped into serving"
    )

    a = artifacts.load_rule_tensors(artifacts.tensor_artifact_path(
        os.path.join(mcfg.pickles_dir, mcfg.recommendations_file)))
    b = artifacts.load_rule_tensors(artifacts.tensor_artifact_path(
        os.path.join(ctl_cfg.pickles_dir, ctl_cfg.recommendations_file)))
    identical = bool(
        a["vocab"] == b["vocab"]
        and all(
            np.array_equal(a[k], b[k])
            for k in ("rule_ids", "rule_counts", "item_counts")
        )
        and a["n_playlists"] == b["n_playlists"]
    )

    sweep = report.get("sweep") or {}
    print(json.dumps({
        "recall_rules": modes["rules"]["recall_at_k"],
        "recall_embed": modes.get("embed", {}).get("recall_at_k"),
        "recall_blend": modes["blend"]["recall_at_k"],
        "recall_blend_best": sweep.get("best_recall_at_k"),
        "recall_popularity": modes["popularity"]["recall_at_k"],
        "mrr_blend": modes["blend"]["mrr"],
        "coverage_blend": modes["blend"]["coverage"],
        "measured_weight": w,
        "weight_roundtrip": weight_roundtrip,
        "eval_playlists": report["split"]["n_eval_playlists"],
        "full_job_s": full_job_s,
        "remine_s": remine_s,
        "compact_s": compact.get("s"),
        "compact_speedup": (
            remine_s / compact["s"] if compact.get("s") else None
        ),
        "compact_folded": compact.get("folded"),
        "compact_identical": identical,
        "http_5xx": http_5xx[0],
        "errors": replay.n_errors,
        "p99_ms": replay.p99_ms,
        "platform": dev.platform,
    }))
"""

# the traffic-shape phase (ISSUE 8): the PR 1-3 shed/degrade/eject
# machinery exercised under the load shapes production actually has,
# not constant-rate Poisson. Three brackets through the full in-process
# app path (cache → admission ladder → batcher → native kernel),
# statuses counted at the HTTP layer so a 5xx can never hide:
#   burst    — 10x burst trains at Zipf 1.1; the judged claims are
#              p99 < 10 ms, zero 5xx, zero errors straight through the
#              bursts (the cache absorbs the head, admission the tail);
#   flash    — flash crowd: a mid-run window collapses ALL traffic onto
#              a handful of cold seed sets (singleflight's worst case);
#              degradation (X-KMLS-Degraded / jittered 429) is allowed,
#              5xx never;
#   epochflip— hot-key flip pinned to a REAL epoch boundary: a second
#              mining generation is pre-published and the bundle
#              hot-swaps mid-burst, invalidating every hot cache key at
#              once; singleflight must collapse the miss wave (zero
#              5xx, zero errors).
# In-process for the same reason as replay10k: at QPS scale an HTTP
# loadgen on this sandbox measures the loadgen. CPU-platform by
# construction, self-labeled.
_LOADSHAPE_BENCH = r"""
import dataclasses, json, os, sys, tempfile, threading, time
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.replay import (
    flash_crowd_payloads,
    replay_pooled,
    sample_seed_sets,
    shaped_arrivals,
)

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
qps = float(os.environ.get("KMLS_BENCH_LOADSHAPE_QPS", "1000"))
n_req = int(os.environ.get("KMLS_BENCH_LOADSHAPE_REQUESTS", "8000"))
burst = float(os.environ.get("KMLS_BENCH_LOADSHAPE_BURST", "10"))
with tempfile.TemporaryDirectory(prefix="kmls_loadshape_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    mcfg = MiningConfig(base_dir=base, datasets_dir=ds_dir, min_support=0.05)
    run_mining_job(mcfg)
    # admission ladder ON at its production defaults (the whole point of
    # this bracket); generous deadline so only a genuine stall degrades
    cfg = dataclasses.replace(
        ServingConfig.from_env(), base_dir=base,
        batch_max_size=64, request_deadline_ms=2000.0,
    )
    app = RecommendApp(cfg)
    assert app.engine.load(), "mined artifacts must load"
    assert cfg.shed_queue_budget_ms > 0, "admission control must be on"
    http_5xx = [0]
    lock = threading.Lock()
    # pre-encoded request bodies, keyed by seed tuple: the loadgen's job
    # is pacing, not cooking (replay_async_http's rule) — at a 10x burst
    # peak the per-request json.dumps is half a core of GIL work on this
    # host, taxing the very tail being measured
    body_cache = {}

    def _body(seeds):
        key = tuple(seeds)
        body = body_cache.get(key)
        if body is None:
            body = json.dumps({"songs": seeds}).encode()
            body_cache[key] = body
        return body

    # make_send_http — full HTTP accounting (app.handle): statuses
    # counted, a 5xx can never hide. ~0.4 ms of GIL-held json per request
    # on this host, so this sender honestly paces ~1k QPS — the
    # flash/epochflip brackets (whose claims are about 5xx and
    # degradation) use it.
    def make_send_http():
        def send(seeds):
            status, headers, _ = app.handle(
                "POST", "/api/recommend/", _body(seeds),
            )
            if status >= 500:
                with lock:
                    http_5xx[0] += 1
                raise RuntimeError(f"HTTP {status}")
            if status == 429:
                # visible backpressure, tracked per-phase — never a 5xx,
                # and Retry-After carries the jitter
                return ("shed", None) if "Retry-After" in headers else (
                    "shed-nojitter", None)
            if status != 200:
                raise RuntimeError(f"HTTP {status}")
            return (
                "degraded" if "X-KMLS-Degraded" in headers else "ok"
            ), None
        return send

    # exception classes the HTTP layer maps AWAY from 5xx (app.py
    # _degrade_reason + the 429 path) — anything else would be a 500
    from kmlserver_tpu.serving.batcher import (
        DeadlineExceeded, NoHealthyReplicas, Overloaded, OverloadDegraded,
    )

    # make_send_direct — the replay10k sender (app.recommend_direct: the
    # same cache → admission → batcher → kernel path minus the json
    # encode/decode, which at a 10x burst peak measures the LOADGEN's
    # GIL, not the server). Exceptions are classified by the app layer's
    # own mapping: shed/degrade classes are non-5xx outcomes by
    # construction (unit-tested in test_batching/test_chaos); anything
    # else is counted as a would-be 5xx AND an error. The judged
    # p99-under-burst bracket uses this sender.
    def make_send_direct():
        def send(seeds):
            try:
                recs, source, cached = app.recommend_direct(seeds)
            except Overloaded:
                return "shed", None
            except (OverloadDegraded, DeadlineExceeded, NoHealthyReplicas):
                return "degraded", None
            except Exception:
                with lock:
                    http_5xx[0] += 1  # the handle() path would 500 this
                raise
            return "ok", cached
        return send

    vocab = app.engine.bundle.vocab
    payloads = sample_seed_sets(vocab, n_req, rng_seed=17, zipf_s=1.1)
    # warm to STEADY STATE before pacing (replay10k's posture: steady
    # state is what the rate sustains): every distinct payload in the
    # Zipf pool once — the measured bursts then run at the hit ratio a
    # long-lived pod actually has — plus a paced half-rate pass for the
    # jit/native and batcher paths
    warm_send = make_send_http()
    seen = set()
    for p in payloads:
        key = tuple(p)
        if key not in seen:
            seen.add(key)
            warm_send(p)
    replay_pooled(
        make_send_http, payloads[: min(3000, n_req)], qps=qps / 2,
        n_workers=16,
    )

    def phase(name, make_send, pl, arrivals, events=None):
        t5xx0 = http_5xx[0]
        shed0 = app.batcher.shed_total
        rep = replay_pooled(
            make_send, pl, qps=qps, n_workers=16, max_queue=16384,
            arrivals=arrivals, events=events,
        )
        out = {
            "offered_qps": round(rep.offered_qps, 1),
            "achieved_qps": round(rep.achieved_qps, 1),
            "p50_ms": round(rep.p50_ms, 3),
            "p99_ms": round(rep.p99_ms, 3),
            # arrival-windowed split (ISSUE 17): the first-40%-of-
            # schedule tail vs the last-40% tail — on shaped traffic the
            # onset window is where reactive adaptation is still
            # catching up, and a pooled p99 averages that away
            "onset_p99_ms": (
                round(rep.onset_p99_ms, 3)
                if rep.onset_p99_ms is not None else None
            ),
            "steady_p99_ms": (
                round(rep.steady_p99_ms, 3)
                if rep.steady_p99_ms is not None else None
            ),
            "errors": rep.n_errors,
            "http_5xx": http_5xx[0] - t5xx0,
            "shed": app.batcher.shed_total - shed0,
            "degraded": rep.by_source.get("degraded", 0),
            "ok": rep.by_source.get("ok", 0),
        }
        print(f"loadshape/{name}: {out}", file=sys.stderr, flush=True)
        return out

    # --- bracket 1: 10x burst trains (the judged p99-under-burst claim).
    # Median of 3 runs by p99, the same discipline as the 1k replay
    # bracket: this sandbox's CPU shares make any single run's tail
    # hostage to a neighbor, and the claim is about the SERVER, not one
    # lucky or unlucky scheduling window. Error/5xx counts are summed
    # across all runs — a failure in any run must not hide in the median.
    burst_arrivals = shaped_arrivals(n_req, qps, "burst", burst_factor=burst)
    runs = [
        phase(f"burst[{i}]", make_send_direct, payloads, burst_arrivals)
        for i in range(3)
    ]
    burst_res = sorted(runs, key=lambda r: r["p99_ms"])[len(runs) // 2]
    burst_res = dict(burst_res)
    burst_res["errors"] = sum(r["errors"] for r in runs)
    burst_res["http_5xx"] = sum(r["http_5xx"] for r in runs)
    burst_res["runs_p99_ms"] = [r["p99_ms"] for r in runs]

    # --- bracket 2: flash crowd (all traffic onto a cold hot-pool)
    n_flash = max(n_req // 2, 1000)
    flash_pl = flash_crowd_payloads(
        sample_seed_sets(vocab, n_flash, rng_seed=29, zipf_s=1.1),
        window=(0.4, 0.7), hot_pool=4,
    )
    flash_res = phase(
        "flash", make_send_http, flash_pl,
        shaped_arrivals(n_flash, qps, "constant"),
    )

    # --- bracket 3: hot-key flip at a REAL epoch boundary — publish a
    # second mining generation now, hot-swap the bundle mid-burst
    run_mining_job(mcfg)  # same data, new generation + invalidation token
    assert app.engine.is_data_stale()
    n_flip = max(n_req // 2, 1000)
    flip_pl = sample_seed_sets(vocab, n_flip, rng_seed=31, zipf_s=1.1)
    epoch_before = app.engine.bundle_epoch
    sf_before = app.cache.singleflight_joins if app.cache else 0

    flip_threads = []

    def flip():
        # the hot swap runs exactly like the production poller: on its
        # own thread, concurrent with serving — the epoch bump lands
        # mid-burst and every hot cache key invalidates at once
        t = threading.Thread(target=app.engine.load, daemon=True)
        t.start()
        flip_threads.append(t)

    flip_res = phase(
        "epochflip", make_send_http, flip_pl,
        shaped_arrivals(n_flip, qps, "constant"),
        events=[(n_flip // 2, flip)],
    )
    # the swap raced the burst (that's the scenario) but the epoch
    # assertion must not race a reload still pre-warming on a contended
    # host: bound the wait, don't leave it to replay-tail luck
    for t in flip_threads:
        t.join(timeout=120.0)
    flip_res["epoch_moved"] = int(app.engine.bundle_epoch > epoch_before)
    flip_res["singleflight_joins"] = (
        (app.cache.singleflight_joins - sf_before) if app.cache else None
    )

    print(json.dumps({
        "qps": qps,
        "burst_factor": burst,
        "zipf_s": 1.1,
        "requests": n_req,
        "burst": burst_res,
        "flash": flash_res,
        "epochflip": flip_res,
        "cache_hit_ratio": app.cache.hit_ratio() if app.cache else None,
        "utilization_after": round(app.batcher.utilization(), 4),
        "platform": dev.platform,
    }))
"""

# the predictive-serving phase (ISSUE 17): the same shaped-traffic rig as
# the loadshape bracket, run as paired A/B legs at EQUAL capacity — one
# server with the forecaster off (pure reactive, the PR 8 ladder), one
# with KMLS_FORECAST=1 — over the two shapes prediction exists for (ramp,
# sine) plus constant as the control where the forecaster must change
# nothing. Each leg reports pooled p99, the onset/steady arrival-window
# split (onset is where reactive adaptation lags and prediction can
# lead), and the shed/degrade counts; the predictive legs also report the
# forecaster's own counters so a "win" with zero observations reads as
# the measurement artifact it would be.
_LOADSHAPE_PRED_BENCH = r"""
import dataclasses, json, os, sys, tempfile, threading, time
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.batcher import (
    DeadlineExceeded, NoHealthyReplicas, Overloaded, OverloadDegraded,
)
from kmlserver_tpu.serving import forecast as forecast_mod
from kmlserver_tpu.serving.replay import (
    replay_pooled, sample_seed_sets, shaped_arrivals,
)

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
qps = float(os.environ.get("KMLS_BENCH_LOADSHAPE_QPS", "1000"))
n_req = int(os.environ.get("KMLS_BENCH_LOADSHAPE_REQUESTS", "8000"))
with tempfile.TemporaryDirectory(prefix="kmls_loadshape_pred_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    run_mining_job(MiningConfig(base_dir=base, datasets_dir=ds_dir,
                                min_support=0.05))
    # a tight shed budget puts the admission ladder IN PLAY at these
    # shapes: with the 250ms default neither leg ever sheds and the
    # judged shed/degrade comparison is a vacuous 0-0 tie. 30ms is
    # still ~10x the steady-state p99, so a leg sheds only when its
    # batch window lags the arrival rate — exactly the lag the
    # forecaster exists to remove. Applied to BOTH legs: equal capacity.
    base_cfg = dataclasses.replace(
        ServingConfig.from_env(), base_dir=base,
        batch_max_size=64, request_deadline_ms=2000.0,
        shed_queue_budget_ms=30.0,
    )
    assert base_cfg.shed_queue_budget_ms > 0, "admission control must be on"

    def run_leg(shape, predictive, payloads, arrivals):
        # equal capacity by construction: the ONLY config difference
        # between the paired legs is the forecaster knob
        cfg = dataclasses.replace(base_cfg, forecast_enabled=predictive)
        app = RecommendApp(cfg)
        assert app.engine.load(), "mined artifacts must load"
        would_5xx = [0]
        lock = threading.Lock()

        def make_send():
            def send(seeds):
                try:
                    recs, source, cached = app.recommend_direct(seeds)
                except Overloaded:
                    return "shed", None
                except (OverloadDegraded, DeadlineExceeded,
                        NoHealthyReplicas):
                    return "degraded", None
                except Exception:
                    with lock:
                        would_5xx[0] += 1  # handle() would 500 this
                    raise
                return "ok", cached
            return send

        # identical warm discipline both modes: every distinct payload
        # once, then a paced half-rate pass for the jit/batcher paths
        warm = make_send()
        seen = set()
        for p in payloads:
            key = tuple(p)
            if key not in seen:
                seen.add(key)
                warm(p)
        replay_pooled(
            make_send, payloads[: min(3000, n_req)], qps=qps / 2,
            n_workers=16,
        )
        shed0 = app.batcher.shed_total
        obs0 = forecast_mod.OBSERVATIONS_TOTAL
        rep = replay_pooled(
            make_send, payloads, qps=qps, n_workers=16, max_queue=16384,
            arrivals=arrivals,
        )
        out = {
            "p50_ms": round(rep.p50_ms, 3),
            "p99_ms": round(rep.p99_ms, 3),
            "onset_p99_ms": (
                round(rep.onset_p99_ms, 3)
                if rep.onset_p99_ms is not None else None
            ),
            "steady_p99_ms": (
                round(rep.steady_p99_ms, 3)
                if rep.steady_p99_ms is not None else None
            ),
            "errors": rep.n_errors,
            "http_5xx": would_5xx[0],
            "shed": app.batcher.shed_total - shed0,
            "degraded": rep.by_source.get("degraded", 0),
            "ok": rep.by_source.get("ok", 0),
            "achieved_qps": round(rep.achieved_qps, 1),
        }
        if predictive:
            f = app.forecaster
            assert f is not None, "KMLS_FORECAST leg must hold a forecaster"
            out["forecast_observations"] = f.observations
            out["prewarm_total"] = getattr(app.batcher, "prewarm_total", 0)
        else:
            # the zero-cost proof under REAL traffic: a disabled-mode
            # leg must never reach the forecaster (is-None gate)
            delta = forecast_mod.OBSERVATIONS_TOTAL - obs0
            assert delta == 0, f"disabled leg observed {delta} requests"
            out["forecast_disabled_obs_delta"] = delta
        mode = "pred" if predictive else "react"
        print(f"loadshape_pred/{shape}/{mode}: {out}", file=sys.stderr,
              flush=True)
        return out

    # one probe load for the catalog vocab; the measured legs each load
    # their own fresh app
    from kmlserver_tpu.serving.engine import RecommendEngine

    probe = RecommendEngine(base_cfg)
    assert probe.load(), "mined artifacts must load"
    vocab = list(probe.bundle.vocab)
    del probe

    shapes = {}
    rng_seeds = {"ramp": 41, "sine": 43, "constant": 47}
    for shape in ("ramp", "sine", "constant"):
        # fixed per-shape rng: the paired legs replay the SAME payloads
        # on the SAME arrival schedule — the knob is the only variable
        payloads = sample_seed_sets(
            vocab, n_req, rng_seed=rng_seeds[shape], zipf_s=1.1,
        )
        # the ramp climbs to 3x base — past the point where a
        # stale-wide batch window starts costing queue wait, so the
        # tightened shed budget has something to judge
        kw = {"ramp_stop_factor": 3.0} if shape == "ramp" else {}
        arrivals = shaped_arrivals(n_req, qps, shape, **kw)
        shapes[shape] = {
            "reactive": run_leg(shape, False, payloads, arrivals),
            "predictive": run_leg(shape, True, payloads, arrivals),
        }
    print(json.dumps({
        "qps": qps,
        "requests": n_req,
        "shapes": shapes,
        "platform": dev.platform,
    }))
"""

# the mining-interruption phase (ISSUE 4): kill the mining job right after
# a fixed phase's checkpoint lands (the deterministic preemption stand-in,
# KMLS_FAULT_MINE_CRASH_PHASE), restart it, and report resume-vs-full
# wall clock plus bit-identity of the resumed artifacts against an
# uninterrupted run. The full-run timing is taken on a SECOND, warm run so
# jit compilation (paid once per process, amortized to zero by the
# production job's PVC compilation cache) doesn't inflate the savings.
_TRACEOVERHEAD_BENCH = r"""
import dataclasses, json, os, sys, tempfile, time
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.replay import replay_pooled, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
qps = float(os.environ.get("KMLS_BENCH_TRACE_QPS", "1000"))
n_req = int(os.environ.get("KMLS_BENCH_TRACE_REQUESTS", "6000"))
with tempfile.TemporaryDirectory(prefix="kmls_traceov_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    mcfg = MiningConfig(base_dir=base, datasets_dir=ds_dir, min_support=0.05)
    run_mining_job(mcfg)

    # two identical apps, one knob apart: tracing sampled at 0.01 vs
    # disabled. Both are driven through app.handle (the full HTTP path
    # minus the socket) with pre-encoded bodies — the json cost is paid
    # identically on both sides, so the RATIO isolates the trace cost
    # (begin + queue/device/compose spans + tail retention). The cache
    # is OFF: a Zipf replay warmed through the cache would answer ~all
    # hits and never reach the batcher's per-pending span recording —
    # the dominant trace cost this bracket exists to bound.
    def build(sample):
        cfg = dataclasses.replace(
            ServingConfig.from_env(), base_dir=base,
            batch_max_size=64, trace_sample=sample, cache_enabled=False,
        )
        app = RecommendApp(cfg)
        assert app.engine.load(), "mined artifacts must load"
        return app

    apps = {"on": build(0.01), "off": build(0.0)}
    body_cache = {}

    def body_of(seeds):
        key = tuple(seeds)
        b = body_cache.get(key)
        if b is None:
            b = json.dumps({"songs": seeds}).encode()
            body_cache[key] = b
        return b

    def make_sender(app):
        def make_send():
            def send(seeds):
                status, headers, _ = app.handle(
                    "POST", "/api/recommend/", body_of(seeds),
                )
                if status >= 500:
                    raise RuntimeError(f"HTTP {status}")
                return ("ok" if status == 200 else "other"), None
            return send
        return make_send

    vocab = apps["on"].engine.bundle.vocab
    payloads = sample_seed_sets(vocab, n_req, rng_seed=47, zipf_s=1.1)
    # steady-state warm per app (replay10k posture), then ALTERNATE the
    # measured runs off/on/off/on so neighbor noise on this host drifts
    # across both modes instead of biasing one
    for app in apps.values():
        send = make_sender(app)()
        for p in {tuple(p): p for p in payloads}.values():
            send(list(p))
        replay_pooled(
            make_sender(app), payloads[: min(2000, n_req)], qps=qps / 2,
            n_workers=16,
        )
    p99s = {"on": [], "off": []}
    p50s = {"on": [], "off": []}
    for _ in range(2):
        for mode in ("off", "on"):
            rep = replay_pooled(
                make_sender(apps[mode]), payloads, qps=qps,
                n_workers=16, max_queue=16384,
            )
            assert rep.n_errors == 0, (mode, rep.n_errors)
            p99s[mode].append(rep.p99_ms)
            p50s[mode].append(rep.p50_ms)
            print(
                f"traceoverhead/{mode}: p50 {rep.p50_ms:.3f}ms "
                f"p99 {rep.p99_ms:.3f}ms ({rep.achieved_qps:.0f} qps)",
                file=sys.stderr, flush=True,
            )
    p99_on, p99_off = min(p99s["on"]), min(p99s["off"])
    rec_on, rec_off = apps["on"].recorder, apps["off"].recorder
    # the zero-cost contract: the disabled recorder never began a trace
    assert rec_off.began == 0, rec_off.began
    assert rec_on.began > 0 and rec_on.retained_total > 0
    print(json.dumps({
        "qps": qps,
        "requests": n_req,
        "p50_on_ms": round(min(p50s["on"]), 3),
        "p50_off_ms": round(min(p50s["off"]), 3),
        "p99_on_ms": round(p99_on, 3),
        "p99_off_ms": round(p99_off, 3),
        "p99_ratio": round(p99_on / max(p99_off, 1e-9), 4),
        "began_on": rec_on.began,
        "began_off": rec_off.began,
        "retained_on": rec_on.retained_total,
        "platform": dev.platform,
    }))
"""

# the cost-attribution bracket (ISSUE 12): replay a Zipf mix through the
# JITTED serve kernel (native kernel off — the XLA kernel is the one the
# TPU window re-runs on chip) with the cost model on, then report the
# device-truth numbers the costmodel layer derives: serve-kernel MFU
# against the backend peak table, the roofline classification, and the
# live compiles-post-publish counter (must be 0 — the invariant that was
# test-only before ISSUE 12). The disabled-mode proof rides along,
# began-counter style: a second app one knob apart (KMLS_COSTMODEL=0)
# sees the same traffic and the module observation counter must not move.
_COSTATTRIB_BENCH = r"""
import dataclasses, json, os, sys, tempfile
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.observability import costmodel
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.replay import replay_pooled, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
qps = float(os.environ.get("KMLS_BENCH_COSTATTRIB_QPS", "800"))
n_req = int(os.environ.get("KMLS_BENCH_COSTATTRIB_REQUESTS", "4000"))
with tempfile.TemporaryDirectory(prefix="kmls_costattrib_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    run_mining_job(
        MiningConfig(base_dir=base, datasets_dir=ds_dir, min_support=0.05)
    )

    def build(enabled):
        cfg = dataclasses.replace(
            ServingConfig.from_env(), base_dir=base,
            cache_enabled=False, native_serve=False,
            costmodel_enabled=enabled,
        )
        app = RecommendApp(cfg)
        assert app.engine.load(), "mined artifacts must load"
        return app

    app_on = build(True)
    body_cache = {}

    def body_of(seeds):
        key = tuple(seeds)
        b = body_cache.get(key)
        if b is None:
            b = json.dumps({"songs": seeds}).encode()
            body_cache[key] = b
        return b

    def make_sender(app):
        def make_send():
            def send(seeds):
                status, headers, _ = app.handle(
                    "POST", "/api/recommend/", body_of(seeds),
                )
                if status >= 500:
                    raise RuntimeError(f"HTTP {status}")
                return ("ok" if status == 200 else "other"), None
            return send
        return make_send

    vocab = app_on.engine.bundle.vocab
    payloads = sample_seed_sets(vocab, n_req, rng_seed=29, zipf_s=1.1)
    rep = replay_pooled(
        make_sender(app_on), payloads, qps=qps, n_workers=16,
        max_queue=16384,
    )
    assert rep.n_errors == 0, rep.n_errors
    cm = app_on.engine.cost_model
    summary = cm.summary()
    serve = summary["kernels"]["serve_rules"]
    compiles = sum(summary["compiles_post_publish"].values())
    # the invariant this bracket makes a live headline: zero compiles on
    # the serving path after publication, and MFU honestly in (0, 1]
    assert compiles == 0, summary["compiles_post_publish"]
    assert 0.0 < serve["mfu"] <= 1.0, serve
    assert summary["unspecced"] == {}, summary["unspecced"]

    # disabled-mode zero-cost proof: same traffic, one knob apart — the
    # module observation counter must not move (no CostModel exists)
    app_off = build(False)
    assert app_off.engine.cost_model is None
    obs_before = costmodel.OBSERVATIONS_TOTAL
    rep_off = replay_pooled(
        make_sender(app_off), payloads[: min(1000, n_req)], qps=qps,
        n_workers=16,
    )
    assert rep_off.n_errors == 0, rep_off.n_errors
    obs_off_delta = costmodel.OBSERVATIONS_TOTAL - obs_before
    assert obs_off_delta == 0, obs_off_delta

    print(json.dumps({
        "qps": qps,
        "requests": n_req,
        "p50_ms": round(rep.p50_ms, 3),
        "p99_ms": round(rep.p99_ms, 3),
        "mfu": serve["mfu"],
        "roofline": serve["roofline"],
        "flops_per_s": serve["flops_per_s"],
        "bytes_per_s": serve["bytes_per_s"],
        "device_s": round(serve["device_s"], 4),
        "dispatches": serve["dispatches"],
        "compiles": compiles,
        "obs_off_delta": obs_off_delta,
        "peak_flops": summary["peak_flops"],
        "peak_source": summary["peak_source"],
        "headroom_bytes": summary["headroom_bytes"],
        "platform": dev.platform,
    }))
"""

_MINE_RESUME_BENCH = r"""
import json, os, sys, tempfile, time
import jax
from kmlserver_tpu import faults
from kmlserver_tpu.config import MiningConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
crash_phase = os.environ.get("KMLS_BENCH_RESUME_PHASE", "mine")
with tempfile.TemporaryDirectory(prefix="kmls_resume_") as root:
    def make_base(name):
        base = os.path.join(root, name)
        ds = os.path.join(base, "datasets")
        os.makedirs(ds)
        write_tracks_csv(
            os.path.join(ds, "2023_spotify_ds2.csv"),
            synthetic_table(**DS2_SHAPE, seed=123),
        )
        return MiningConfig(base_dir=base, datasets_dir=ds, min_support=0.05)

    def artifact_bytes(cfg):
        out = {}
        for name in (cfg.recommendations_file, cfg.best_tracks_file):
            with open(os.path.join(cfg.pickles_dir, name), "rb") as fh:
                out[name] = fh.read()
        return out

    # run 1: warmup (pays every jit compile) + the reference bytes
    cfg_warm = make_base("warm")
    run_mining_job(cfg_warm)
    ref = artifact_bytes(cfg_warm)

    # run 2: the timed UNINTERRUPTED baseline, warm
    cfg_full = make_base("full")
    t0 = time.perf_counter()
    run_mining_job(cfg_full)
    full_s = time.perf_counter() - t0

    # run 3: killed right after crash_phase's checkpoint persists
    cfg_int = make_base("interrupted")
    faults.inject(f"mine.crash.{crash_phase}", times=1)
    t0 = time.perf_counter()
    try:
        run_mining_job(cfg_int)
        raise SystemExit(f"crash fault at {crash_phase} never fired")
    except faults.FaultInjected:
        pass
    interrupted_s = time.perf_counter() - t0
    faults.clear()

    # run 4: the restart — resumes from the checkpoint
    t0 = time.perf_counter()
    summary = run_mining_job(cfg_int)
    resume_s = time.perf_counter() - t0

    print(json.dumps({
        "crash_phase": crash_phase,
        "resumed_phases": list(summary.resumed_phases),
        "full_s": full_s,
        "interrupted_s": interrupted_s,
        "resume_s": resume_s,
        "saved_pct": 100.0 * (1.0 - resume_s / full_s) if full_s > 0 else 0.0,
        "identical": artifact_bytes(cfg_int) == ref,
        "platform": dev.platform,
    }))
"""

_REPLAY_CLIENT = r"""
import json, os, pickle, sys
from kmlserver_tpu.serving.replay import (
    ClientTraceLog, replay_async_http, sample_seed_sets,
)

url, qps, n, pickles = sys.argv[1], float(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
# optional 5th arg: JSONL path for echoed X-KMLS-Trace ids + client
# send/recv wall clocks — the client half of scripts/kmls_tracejoin.py
trace_path = sys.argv[5] if len(sys.argv) > 5 else None
# seed vocabulary straight from the artifact pickle — no jax in the client
# (the server owns the TPU; libtpu is one process per chip)
with open(pickles, "rb") as f:
    vocab = sorted(pickle.load(f).keys())
# the single-loop pipelined client (replay_async_http): thread-pool
# loadgens convoy on the GIL and pay ~2 syscall traps per request on this
# sandbox — they melt before the server does and mismeasure it. In-flight
# capacity = n_conns x pipeline; through the remote-TPU tunnel (~0.3-0.5 s
# per response) Little's law at 1k QPS needs ~500 in flight, so the conn
# count scales with the env override rather than a fixed 64.
trace_log = ClientTraceLog() if trace_path else None
report = replay_async_http(
    url, sample_seed_sets(vocab, n), qps=qps,
    n_conns=min(int(os.environ.get("KMLS_BENCH_REPLAY_WORKERS", "48")), 128),
    max_queue=int(os.environ.get("KMLS_BENCH_REPLAY_QUEUE", "4096")),
    trace_log=trace_log,
)
out = json.loads(report.to_json())
if trace_log is not None:
    out["trace_records"] = trace_log.write_jsonl(trace_path)
print(json.dumps(out))
"""


# the second-model-family phase (ISSUE 6): ALS embedding training time
# through the real pipeline (embed phase enabled), then hybrid
# rule∪embedding serving — 1k-QPS blend-mode replay p50/p99 through
# cache → batcher → both kernels, plus the cold-start bracket: every
# zero-rule track in the embedding vocabulary is asked as a single seed
# and the hit fraction counts answers served from the embedding space
# (source "embed") instead of the popularity fallback. In-process for the
# same reason as replay10k. CPU-platform by construction, self-labeled.
_ALS_HYBRID_BENCH = r"""
import dataclasses, json, os, sys, tempfile
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.replay import replay_pooled, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
with tempfile.TemporaryDirectory(prefix="kmls_als_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    mcfg = dataclasses.replace(
        MiningConfig.from_env(dotenv_path=None), base_dir=base,
        datasets_dir=ds_dir, min_support=0.05, embed_enabled=True,
    )
    summary = run_mining_job(mcfg)
    cfg = dataclasses.replace(
        ServingConfig.from_env(dotenv_path=None), base_dir=base,
        hybrid_mode="blend", batch_max_size=64, shed_queue_budget_ms=0.0,
    )
    app = RecommendApp(cfg)
    assert app.engine.load(), "mined artifacts must load"
    bundle = app.engine.bundle
    assert bundle.emb_factors is not None, "embedding artifact must attach"

    # cold-start bracket: every embedding-vocab track with ZERO rules
    known = {bundle.vocab[i] for i in range(len(bundle.vocab))
             if bundle.known_mask[i]}
    cold = [n for n in bundle.emb_vocab if n not in known][:512]
    embed_answered = 0
    for name in cold:
        _songs, source, _cached = app.recommend_direct([name])
        if source == "embed":
            embed_answered += 1

    def make_send():
        def send(seeds):
            recs, source, cached = app.recommend_direct(seeds)
            return source, cached
        return send

    payloads = sample_seed_sets(
        bundle.emb_vocab, 8000, rng_seed=11, zipf_s=1.1
    )
    replay_pooled(make_send, payloads[:1000], qps=250, n_workers=8)  # warm
    report = replay_pooled(
        make_send, payloads, qps=1000, n_workers=16, max_queue=4096
    )
    print(json.dumps({
        "als_train_s": round(summary.als_train_s, 3),
        "als_rank": mcfg.als_rank,
        "als_iters": mcfg.als_iters,
        "emb_vocab": len(bundle.emb_vocab),
        "qps": 1000.0,
        "achieved_qps": report.achieved_qps,
        "p50_ms": report.p50_ms,
        "p95_ms": report.p95_ms,
        "p99_ms": report.p99_ms,
        "errors": report.n_errors,
        "cold_start_seeds": len(cold),
        "cold_start_hit_frac": (
            embed_answered / len(cold) if cold else None
        ),
        "platform": dev.platform,
    }))
"""

# confidence-mode serving bracket (carried-over ROADMAP item): mine with
# the dormant slow path's true-confidence semantics + multi-antecedent
# rules (max_itemset_len 3), then replay-grade the SAME max-merge kernel
# those rules serve through (native kernel off so the jitted device
# kernel is the one measured). In-process; CPU-platform by construction.
_CONFSERVE_BENCH = r"""
import dataclasses, json, os, sys, tempfile
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.app import RecommendApp
from kmlserver_tpu.serving.replay import replay_pooled, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
with tempfile.TemporaryDirectory(prefix="kmls_confserve_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    mcfg = dataclasses.replace(
        MiningConfig.from_env(dotenv_path=None), base_dir=base,
        datasets_dir=ds_dir, min_support=0.05,
        confidence_mode="confidence", max_itemset_len=3,
    )
    run_mining_job(mcfg)
    cfg = dataclasses.replace(
        ServingConfig.from_env(dotenv_path=None), base_dir=base,
        native_serve=False, batch_max_size=64, shed_queue_budget_ms=0.0,
    )
    app = RecommendApp(cfg)
    assert app.engine.load(), "mined artifacts must load"
    bundle = app.engine.bundle

    def make_send():
        def send(seeds):
            recs, source, cached = app.recommend_direct(seeds)
            return source, cached
        return send

    payloads = sample_seed_sets(bundle.vocab, 8000, rng_seed=7, zipf_s=1.1)
    replay_pooled(make_send, payloads[:1000], qps=250, n_workers=8)  # warm
    report = replay_pooled(
        make_send, payloads, qps=1000, n_workers=16, max_queue=4096
    )
    print(json.dumps({
        "qps": 1000.0,
        "achieved_qps": report.achieved_qps,
        "p50_ms": report.p50_ms,
        "p95_ms": report.p95_ms,
        "p99_ms": report.p99_ms,
        "errors": report.n_errors,
        "rule_keys": int(bundle.known_mask.sum()),
        "max_itemset_len": mcfg.max_itemset_len,
        "confidence_mode": mcfg.confidence_mode,
        "platform": dev.platform,
    }))
"""


# model-parallel serving bracket (ISSUE 7): mine a real catalog, publish
# it under BOTH layouts, and prove the acceptance on the 8-virtual-device
# mesh — auto resolves to sharded because the rule tensors measure over
# the (deliberately tiny) per-device budget, answers are bit-identical to
# the replicated engine, zero compiles post-publish, and the p50/p99 of
# both layouts land in the artifact alongside the max servable catalog
# bytes the mesh buys (budget × shards vs one device's budget).
_SHARDSERVE_BENCH = r"""
import dataclasses, json, os, sys, tempfile, time
import numpy as np
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.engine import RecommendEngine

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
n_devices = len(jax.devices())
assert n_devices >= 4, f"mesh bracket needs >=4 virtual devices, have {n_devices}"
with tempfile.TemporaryDirectory(prefix="kmls_shardserve_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    mcfg = dataclasses.replace(
        MiningConfig.from_env(dotenv_path=None), base_dir=base,
        datasets_dir=ds_dir, min_support=0.05,
    )
    run_mining_job(mcfg)

    common = dict(
        base_dir=base, batch_max_size=32, max_seed_tracks=8,
        native_serve=False,
    )
    rep = RecommendEngine(dataclasses.replace(
        ServingConfig.from_env(dotenv_path=None), serve_devices=1, **common
    ))
    assert rep.load()
    catalog_bytes = int(
        np.asarray(rep.bundle.rule_ids).nbytes
        + np.asarray(rep.bundle.rule_confs).nbytes
    )
    # budget HALF the catalog: one (virtual) device cannot hold a replica,
    # so the auto layout MUST measure its way to sharded
    budget = max(catalog_bytes // 2, 1)
    shd = RecommendEngine(dataclasses.replace(
        ServingConfig.from_env(dotenv_path=None), serve_devices=n_devices,
        model_layout="auto", device_budget_bytes=budget, **common
    ))
    assert shd.load()
    assert shd.bundle.layout == "sharded", shd.bundle.layout
    shards = shd.bundle.n_shards

    bundle = shd.bundle
    rng = np.random.default_rng(0)
    known = [
        s for s in bundle.vocab if bundle.known_mask[bundle.index[s]]
    ]
    sets = [
        list(rng.choice(known, size=int(rng.integers(1, 5)), replace=False))
        for _ in range(32)
    ]
    identical = rep.recommend_many_async(sets)() == \
        shd.recommend_many_async(sets)()

    def bracket(engine, reps=40):
        engine.recommend_many_async(sets)()  # warm the bucket
        lat = []
        for _ in range(reps):
            t0 = time.perf_counter()
            engine.recommend_many_async(sets)()
            lat.append((time.perf_counter() - t0) * 1e3)
        lat.sort()
        return lat[len(lat) // 2], lat[min(int(len(lat) * 0.99), len(lat) - 1)]

    rep_p50, rep_p99 = bracket(rep)
    shd_p50, shd_p99 = bracket(shd)
    print(json.dumps({
        "shards": shards,
        "identical": bool(identical),
        "unwarmed_dispatches": shd.unwarmed_dispatches,
        "catalog_bytes": catalog_bytes,
        "device_budget_bytes": budget,
        "max_catalog_bytes": budget * shards,
        "replicated_p50_ms": round(rep_p50, 3),
        "replicated_p99_ms": round(rep_p99, 3),
        "sharded_p50_ms": round(shd_p50, 3),
        "sharded_p99_ms": round(shd_p99, 3),
        "shard_dispatch_counts": shd.shard_dispatch_counts,
        "platform": dev.platform,
    }))
"""

# the pod-spanning serve-mesh bracket (ISSUE 16): the same over-budget
# catalog served two ways — single-PROCESS sharded (the ISSUE 7 ceiling:
# whatever one host's devices hold) vs a 2-member serve GANG where each
# member holds only its vocab slab and the answer merges over the socket
# mesh transport. Identity leg pins gang answers bit-identical to the
# replicated reference AND the single-process sharded kernel on BOTH
# members with zero compiles post-publish; the chaos leg runs 2 REAL
# gang server processes + 1 solo replica behind the routed replay client
# and SIGKILLs a gang member mid-replay — the gang must degrade exactly
# like a dead replica (503 + X-KMLS-Mesh-Unavailable → whole-gang
# ejection → spill to the solo peer), never as a 5xx or a drop.
_MESHSERVE_BENCH = r"""
import dataclasses, json, os, re, signal, socket, subprocess, sys
import tempfile, threading, time, urllib.request
import numpy as np
import jax
from kmlserver_tpu.config import MiningConfig, ServingConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving.engine import RecommendEngine
from kmlserver_tpu.serving.replay import replay_fleet_http, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
qps = float(os.environ.get("KMLS_BENCH_MESHSERVE_QPS", "500"))
n_req = int(os.environ.get("KMLS_BENCH_MESHSERVE_REQUESTS", "4000"))
GANG = 2
n_devices = len(jax.devices())
assert n_devices >= GANG, f"mesh bracket needs >={GANG} virtual devices"

def gang_ports():
    # a base port where base..base+GANG-1 are all free: bare-host
    # coordinator addressing derives member ports by rank offset
    for base in range(29170, 29970, 10):
        socks = []
        try:
            for r in range(GANG):
                s = socket.socket()
                s.bind(("127.0.0.1", base + r))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free consecutive port pair")

with tempfile.TemporaryDirectory(prefix="kmls_meshserve_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    mcfg = dataclasses.replace(
        MiningConfig.from_env(dotenv_path=None), base_dir=base,
        datasets_dir=ds_dir, min_support=0.05,
    )
    run_mining_job(mcfg)

    common = dict(
        base_dir=base, batch_max_size=32, max_seed_tracks=8,
        native_serve=False,
    )
    rep = RecommendEngine(dataclasses.replace(
        ServingConfig.from_env(dotenv_path=None), serve_devices=1, **common
    ))
    assert rep.load()
    catalog_bytes = int(
        np.asarray(rep.bundle.rule_ids).nbytes
        + np.asarray(rep.bundle.rule_confs).nbytes
    )
    # budget HALF the catalog: neither one virtual device nor one gang
    # member can hold a replica — the single-process comparator must
    # measure its way to sharded, the gang spans the rest over sockets
    budget = max(catalog_bytes // 2, 1)
    shd = RecommendEngine(dataclasses.replace(
        ServingConfig.from_env(dotenv_path=None), serve_devices=n_devices,
        model_layout="auto", device_budget_bytes=budget, **common
    ))
    assert shd.load()
    assert shd.bundle.layout == "sharded", shd.bundle.layout

    mesh_base = gang_ports()
    members = []
    for rank in range(GANG):
        m = RecommendEngine(dataclasses.replace(
            ServingConfig.from_env(dotenv_path=None),
            device_budget_bytes=budget,
            serve_gang_coordinator=f"127.0.0.1:{mesh_base}",
            serve_gang_size=GANG, serve_gang_rank=rank,
            serve_gang_port=mesh_base + rank,
            **common,
        ))
        members.append(m)
    for rank, m in enumerate(members):
        assert m.load(), f"gang rank {rank} failed to load"
        assert m.bundle.layout == "mesh", m.bundle.layout

    bundle = shd.bundle
    rng = np.random.default_rng(0)
    known = [
        s for s in bundle.vocab if bundle.known_mask[bundle.index[s]]
    ]
    sets = [
        list(rng.choice(known, size=int(rng.integers(1, 5)), replace=False))
        for _ in range(32)
    ]
    ref_ans = rep.recommend_many_async(sets)()
    identical = (
        ref_ans == shd.recommend_many_async(sets)()
        and all(ref_ans == m.recommend_many_async(sets)() for m in members)
    )

    def bracket(engine, reps=40):
        engine.recommend_many_async(sets)()  # warm the bucket
        lat = []
        for _ in range(reps):
            t0 = time.perf_counter()
            engine.recommend_many_async(sets)()
            lat.append((time.perf_counter() - t0) * 1e3)
        lat.sort()
        return lat[len(lat) // 2], lat[min(int(len(lat) * 0.99), len(lat) - 1)]

    shd_p50, shd_p99 = bracket(shd)
    mesh_p50, mesh_p99 = bracket(members[0])
    unwarmed = sum(m.unwarmed_dispatches for m in members)
    missing = members[0].mesh_missing_shards()
    assert missing == [], f"gang dark mid-bracket: {missing}"
    for m in members:  # free the mesh ports before the HTTP leg
        if m.mesh_worker is not None:
            m.mesh_worker.stop()
        if m.mesh_coordinator is not None:
            m.mesh_coordinator.close()
    print(
        f"identity leg: identical={identical}, unwarmed={unwarmed}, "
        f"sharded p50 {shd_p50:.2f}ms vs mesh p50 {mesh_p50:.2f}ms",
        file=sys.stderr, flush=True,
    )

    # ---- chaos leg: 2 REAL gang server processes + 1 solo replica.
    # The ring lists the gang ONCE (rank 0's URL is the gang's front
    # door); mid-replay SIGKILL of rank 1 darkens a SHARD, and the
    # routed client must see only 503+X-KMLS-Mesh-Unavailable refusals
    # (ejection + spill to solo), zero 5xx, zero drops.
    http_base = gang_ports()  # fresh pair for the server gang
    procs, ports, logs = {}, {}, {}
    def _terminate_all():
        for proc in procs.values():
            try:
                proc.terminate()
            except Exception:
                pass
        for proc in procs.values():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    def start_server(name, gang_rank=None):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # servers don't need the virtual mesh
        env.update({
            "BASE_DIR": base, "KMLS_PORT": "0",
            "KMLS_SHED_QUEUE_BUDGET_MS": "0",
            "KMLS_FLEET_SELF": "gang" if gang_rank is not None else "solo",
            "KMLS_FLEET_PEERS": "gang,solo",
        })
        if gang_rank is not None:
            env.update({
                "KMLS_SERVE_GANG_COORDINATOR": f"127.0.0.1:{http_base}",
                "KMLS_SERVE_GANG_SIZE": str(GANG),
                "KMLS_SERVE_GANG_RANK": str(gang_rank),
                # bare-host addressing: member rank r binds base + r
                "KMLS_SERVE_GANG_PORT": str(http_base + gang_rank),
            })
        proc = subprocess.Popen(
            [sys.executable, "-m", "kmlserver_tpu.serving.server"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        lines = []
        logs[name] = lines
        def drain():
            for line in proc.stdout:
                lines.append(line.rstrip())
                m = re.search(r"serving on \S+?:(\d+)", line)
                if m and name not in ports:
                    ports[name] = int(m.group(1))
        threading.Thread(target=drain, daemon=True).start()
        procs[name] = proc
        return proc

    try:
        for rank in range(GANG):
            start_server(f"gang-{rank}", gang_rank=rank)
        start_server("solo")
        t_wait = time.time()
        while len(ports) < GANG + 1 and time.time() - t_wait < 120:
            time.sleep(0.1)
        assert len(ports) == GANG + 1, f"servers never reported ports: {ports}"
        def wait_ready(url, deadline_s=180):
            t0 = time.time()
            while time.time() - t0 < deadline_s:
                try:
                    with urllib.request.urlopen(url + "/readyz", timeout=5) as r:
                        if r.status == 200:
                            return True
                except Exception:
                    pass
                time.sleep(0.25)
            return False
        urls = {
            name: f"http://127.0.0.1:{port}" for name, port in ports.items()
        }
        for name, url in urls.items():
            assert wait_ready(url), f"{name} never went ready"
        print(f"mesh fleet up: {urls}", file=sys.stderr, flush=True)

        vocab = sorted(known)
        payloads = sample_seed_sets(
            vocab, n_req, rng_seed=61, zipf_s=1.1, zipf_pool=2048,
        )
        kill_at = int(n_req * 0.5)
        victim = procs[f"gang-{GANG - 1}"]
        events = [(kill_at, lambda: victim.send_signal(signal.SIGKILL))]
        # the gang is ONE ring peer, fronted by rank 0
        ring_urls = {"gang": urls["gang-0"], "solo": urls["solo"]}
        rep_http, fleet = replay_fleet_http(
            ring_urls, payloads, qps=qps, policy="ring", events=events,
        )
    finally:
        _terminate_all()

    assert fleet["http_5xx"] == 0, f"5xx through shard loss: {fleet}"
    assert rep_http.n_errors == 0, f"drops through shard loss: {rep_http}"
    assert fleet["mesh_unavailable"] >= 1, f"no mesh refusals seen: {fleet}"
    assert fleet["ejections"] >= 1, f"gang never ejected: {fleet}"
    print(json.dumps({
        "gang_size": GANG,
        "identical": bool(identical),
        "unwarmed_dispatches": unwarmed,
        "catalog_bytes": catalog_bytes,
        "host_budget_bytes": budget,
        "max_catalog_bytes": budget * GANG,
        "sharded_p50_ms": round(shd_p50, 3),
        "sharded_p99_ms": round(shd_p99, 3),
        "mesh_p50_ms": round(mesh_p50, 3),
        "mesh_p99_ms": round(mesh_p99, 3),
        "replay_qps": qps,
        "replay_requests": n_req,
        "achieved_qps": rep_http.achieved_qps,
        "replay_p99_ms": rep_http.p99_ms,
        "http_5xx": fleet["http_5xx"],
        "errors": rep_http.n_errors,
        "mesh_unavailable": fleet["mesh_unavailable"],
        "ejections": fleet["ejections"],
        "failed_shards": fleet["failed_shards"],
        "answered_by": fleet["answered_by"],
        "platform": dev.platform,
    }))
"""

# gray-failure chaos bracket (ISSUE 18): a 200 ms deterministic stall —
# injected via the KMLS_FAULT_*_PEER_DELAY_MS sites, never a kill — on
# one fleet peer and one gang member, with the hedged leg racing the
# no-hedge control at equal capacity. The stalled peer answers every
# request successfully (late), so nothing here ever trips the PR 15/16
# error breakers: only the slow-outlier ladder + hedged dispatch can
# route around it. Judged claims: hedged p99 ≥ 5x better than control,
# hedge overhead (extra dispatches / total) ≤ 5%, zero 5xx and zero
# drops on EVERY leg, bit-identical answers whichever copy wins
# (hedge_mismatch == 0 + post-replay cross-replica probe identity), and
# the in-bench zero-cost pin: the control leg leaves the module
# HEDGES_ISSUED counter at exactly 0 under real traffic.
_SLOWPEER_BENCH = r"""
import json, os, pickle, re, socket, subprocess, sys, tempfile
import threading, time, urllib.request
import jax
from kmlserver_tpu.config import MiningConfig
from kmlserver_tpu.data.csv import write_tracks_csv
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_table
from kmlserver_tpu.mining.pipeline import run_mining_job
from kmlserver_tpu.serving import replay as replay_mod
from kmlserver_tpu.serving.replay import replay_fleet_http, sample_seed_sets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
# qps sits deliberately UNDER the stalled peer's service capacity
# (n_conns / stall = 20 req/s at 200 ms): the control leg must measure
# the gray-failure tail itself, not an overload collapse on top of it —
# both legs then see the identical, stable fault
qps = float(os.environ.get("KMLS_BENCH_SLOWPEER_QPS", "32"))
n_req = int(os.environ.get("KMLS_BENCH_SLOWPEER_REQUESTS", "600"))
STALL_MS = 200
GANG = 2

with tempfile.TemporaryDirectory(prefix="kmls_slowpeer_") as base:
    ds_dir = os.path.join(base, "datasets")
    os.makedirs(ds_dir)
    write_tracks_csv(
        os.path.join(ds_dir, "2023_spotify_ds2.csv"),
        synthetic_table(**DS2_SHAPE, seed=123),
    )
    run_mining_job(MiningConfig(
        base_dir=base, datasets_dir=ds_dir, min_support=0.05,
    ))
    with open(
        os.path.join(base, "pickles", "recommendations.pickle"), "rb"
    ) as fh:
        vocab = sorted(pickle.load(fh).keys())

    procs, ports, logs = {}, {}, {}
    def _terminate_all():
        for proc in procs.values():
            try:
                proc.terminate()
            except Exception:
                pass
        for proc in procs.values():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        procs.clear()
        ports.clear()
    def start_server(name, extra_env):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # servers don't need the virtual mesh
        env.update({
            "BASE_DIR": base, "KMLS_PORT": "0",
            "KMLS_SHED_QUEUE_BUDGET_MS": "0",
        })
        env.update(extra_env)
        proc = subprocess.Popen(
            [sys.executable, "-m", "kmlserver_tpu.serving.server"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        lines = logs.setdefault(name, [])
        def drain():
            for line in proc.stdout:
                lines.append(line.rstrip())
                m = re.search(r"serving on \S+?:(\d+)", line)
                if m and name not in ports:
                    ports[name] = int(m.group(1))
        threading.Thread(target=drain, daemon=True).start()
        procs[name] = proc
    def await_up(n):
        t_wait = time.time()
        while len(ports) < n and time.time() - t_wait < 120:
            time.sleep(0.1)
        assert len(ports) == n, f"servers never reported ports: {ports}"
        urls = {name: f"http://127.0.0.1:{p}" for name, p in ports.items()}
        for name, url in urls.items():
            t0 = time.time()
            ready = False
            while time.time() - t0 < 180:
                try:
                    with urllib.request.urlopen(url + "/readyz", timeout=5) as r:
                        if r.status == 200:
                            ready = True
                            break
                except Exception:
                    pass
                time.sleep(0.25)
            assert ready, f"{name} never went ready"
        return urls
    def scrape(url):
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        out = {}
        for line in text.splitlines():
            if line and not line.startswith("#"):
                parts = line.split()
                if len(parts) == 2:
                    try:
                        out[parts[0]] = float(parts[1])
                    except ValueError:
                        pass
        return out
    def probe(url, seeds):
        body = json.dumps({"songs": seeds}).encode()
        req = urllib.request.Request(
            url + "/api/recommend/", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=15) as r:
            return json.load(r)["songs"]

    # ---- fleet pair: replica-1 (sorted fleet index 1) stalls EVERY
    # request STALL_MS via the armed fault site — a pure gray failure,
    # alive and answering for both legs at equal capacity
    fleet_env = {"KMLS_FLEET_PEERS": "replica-0,replica-1"}
    try:
        start_server("replica-0", {**fleet_env, "KMLS_FLEET_SELF": "replica-0"})
        start_server("replica-1", {
            **fleet_env, "KMLS_FLEET_SELF": "replica-1",
            "KMLS_FAULT_FLEET_PEER_DELAY_MS": f"1:{STALL_MS}:-1",
        })
        urls = await_up(2)
        print(f"fleet up: {urls}", file=sys.stderr, flush=True)

        # leg A — no-hedge control: PR 15 routing exactly. The stalled
        # peer owns ~half the keys and error-breaks NOTHING, so its
        # stall compounds down each pipelined connection — the gray-
        # failure tail the spine exists to cut.
        payloads_a = sample_seed_sets(
            vocab, n_req, rng_seed=41, zipf_s=1.1, zipf_pool=1024,
        )
        rep_ctl, fleet_ctl = replay_fleet_http(
            urls, payloads_a, qps=qps, policy="ring",
        )
        # the in-bench zero-cost pin: real traffic, hedging off, the
        # module counter must not have moved
        control_hedges = replay_mod.HEDGES_ISSUED
        print(
            f"control: p50 {rep_ctl.p50_ms:.1f}ms p99 {rep_ctl.p99_ms:.1f}ms, "
            f"{fleet_ctl['http_5xx']} 5xx, {rep_ctl.n_errors} errors, "
            f"hedges {control_hedges}",
            file=sys.stderr, flush=True,
        )

        # leg B — the gray-failure spine armed: slow ladder + hedged
        # dispatch + deadline budgets on every hop, same fleet, same
        # stall, same offered load
        payloads_b = sample_seed_sets(
            vocab, n_req, rng_seed=42, zipf_s=1.1, zipf_pool=1024,
        )
        # deadline 5 s: wide enough that nothing degrades (the digest
        # identity claim compares FULL answers — deadline-degraded
        # bodies are a different, correct answer), tight enough that the
        # budget header rides every hop; probes every 5 s so ejection-
        # probe hedges don't eat the ≤5% overhead budget
        rep_hdg, fleet_hdg = replay_fleet_http(
            urls, payloads_b, qps=qps, policy="ring",
            hedge=True, hedge_delay_ms=20.0, hedge_max_frac=0.5,
            slow_ratio=3.0, deadline_ms=5000.0, probe_interval_s=5.0,
        )
        print(
            f"hedged: p50 {rep_hdg.p50_ms:.1f}ms p99 {rep_hdg.p99_ms:.1f}ms, "
            f"{fleet_hdg['hedges_issued']} hedges "
            f"({fleet_hdg['hedge_wins']} won), "
            f"{fleet_hdg['slow_ejections']} slow ejections, "
            f"{fleet_hdg['http_5xx']} 5xx, {rep_hdg.n_errors} errors",
            file=sys.stderr, flush=True,
        )

        # bit-identity across the hedge winner: the digest check rode
        # every double-answered request (hedge_mismatch), and both
        # replicas must still answer probes identically — the stalled
        # peer is SLOW, never wrong
        probe_sets = payloads_b[:3] + [vocab[:3]]
        identity_ok = all(
            probe(urls["replica-0"], seeds) == probe(urls["replica-1"], seeds)
            for seeds in probe_sets
        )
        expired = scrape(urls["replica-0"]).get(
            "kmls_deadline_expired_total", 0
        ) + scrape(urls["replica-1"]).get("kmls_deadline_expired_total", 0)
    finally:
        _terminate_all()

    assert control_hedges == 0, (
        f"hedges issued with hedging off: {control_hedges}"
    )
    assert fleet_ctl["http_5xx"] == 0 and rep_ctl.n_errors == 0, (
        f"control leg not clean: {fleet_ctl} {rep_ctl}"
    )
    assert fleet_hdg["http_5xx"] == 0 and rep_hdg.n_errors == 0, (
        f"hedged leg not clean: {fleet_hdg} {rep_hdg}"
    )
    assert fleet_hdg["hedge_wins"] >= 1, f"no hedge ever won: {fleet_hdg}"
    assert fleet_hdg["hedge_mismatch"] == 0, (
        f"hedge answered differently from primary: {fleet_hdg}"
    )
    p99_ratio = (
        rep_ctl.p99_ms / rep_hdg.p99_ms if rep_hdg.p99_ms > 0 else float("inf")
    )
    overhead_pct = 100.0 * fleet_hdg["hedges_issued"] / max(1, n_req)

    # ---- gang pair: rank 1 stalls its first partials — the coordinator
    # must merge without the straggler (degraded answers, zero 5xx, the
    # rank never blamed missing), then recover when the stall drains
    def gang_ports():
        for gbase in range(29170, 29970, 10):
            socks = []
            try:
                for r in range(GANG):
                    s = socket.socket()
                    socks.append(s)
                    s.bind(("127.0.0.1", gbase + r))
                return gbase
            except OSError:
                continue
            finally:
                for s in socks:
                    s.close()
        raise RuntimeError("no free consecutive port pair")
    mesh_base = gang_ports()
    n_req_mesh = max(100, n_req // 2)
    logs.clear()
    try:
        for rank in range(GANG):
            env = {
                "KMLS_FLEET_SELF": "gang", "KMLS_FLEET_PEERS": "gang",
                "KMLS_SERVE_GANG_COORDINATOR": f"127.0.0.1:{mesh_base}",
                "KMLS_SERVE_GANG_SIZE": str(GANG),
                "KMLS_SERVE_GANG_RANK": str(rank),
                "KMLS_SERVE_GANG_PORT": str(mesh_base + rank),
                "KMLS_HEDGE": "1",
                "KMLS_HEDGE_DELAY_MS": "20",
                "KMLS_HEDGE_MAX_FRAC": "0.5",
                "KMLS_PEER_SLOW_RATIO": "3.0",
            }
            if rank == 1:
                # a finite stall: rank 1 recovers mid-replay, so the
                # bracket also covers the straggler rejoining the merge
                env["KMLS_FAULT_MESH_PEER_DELAY_MS"] = f"1:{STALL_MS}:12"
            start_server(f"gang-{rank}", env)
        urls = await_up(GANG)
        print(f"gang up: {urls}", file=sys.stderr, flush=True)
        ring_urls = {"gang": urls["gang-0"]}
        payloads_m = sample_seed_sets(
            vocab, n_req_mesh, rng_seed=43, zipf_s=1.1, zipf_pool=1024,
        )
        rep_m, fleet_m = replay_fleet_http(
            ring_urls, payloads_m, qps=qps, policy="ring",
            deadline_ms=1500.0,
        )
        front = scrape(urls["gang-0"])
        stalled = scrape(urls["gang-1"])
    finally:
        _terminate_all()

    assert fleet_m["http_5xx"] == 0 and rep_m.n_errors == 0, (
        f"mesh leg not clean: {fleet_m} {rep_m}"
    )
    mesh_hedge_wins = front.get("kmls_hedge_wins_total", 0)
    mesh_degraded = front.get("kmls_mesh_straggler_degraded_total", 0)
    assert mesh_hedge_wins >= 1, f"coordinator never hedged: {front}"
    assert mesh_degraded >= 1, f"no straggler-degraded answers: {front}"

    print(json.dumps({
        "qps": qps,
        "requests": n_req,
        "stall_ms": STALL_MS,
        "control_p50_ms": rep_ctl.p50_ms,
        "control_p99_ms": rep_ctl.p99_ms,
        "hedged_p50_ms": rep_hdg.p50_ms,
        "hedged_p99_ms": rep_hdg.p99_ms,
        "p99_ratio": p99_ratio,
        "hedge_overhead_pct": overhead_pct,
        "hedges_issued": fleet_hdg["hedges_issued"],
        "hedge_wins": fleet_hdg["hedge_wins"],
        "hedge_losses": fleet_hdg["hedge_losses"],
        "hedges_suppressed": fleet_hdg["hedges_suppressed"],
        "hedge_mismatch": fleet_hdg["hedge_mismatch"],
        "slow_ejections": fleet_hdg["slow_ejections"],
        "deadline_expired": fleet_hdg["deadline_expired"],
        "server_deadline_expired": expired,
        "control_hedges_issued": control_hedges,
        "control_http_5xx": fleet_ctl["http_5xx"],
        "control_errors": rep_ctl.n_errors,
        "http_5xx": fleet_hdg["http_5xx"] + fleet_ctl["http_5xx"]
        + fleet_m["http_5xx"],
        "errors": rep_hdg.n_errors + rep_ctl.n_errors + rep_m.n_errors,
        "identity_ok": bool(identity_ok),
        "mesh_requests": n_req_mesh,
        "mesh_hedge_wins": mesh_hedge_wins,
        "mesh_hedge_cancelled": front.get("kmls_hedge_cancelled_total", 0),
        "mesh_straggler_degraded": mesh_degraded,
        "mesh_expired_on_arrival": stalled.get(
            "kmls_mesh_expired_on_arrival_total", 0
        ),
        "mesh_p99_ms": rep_m.p99_ms,
        "mesh_http_5xx": fleet_m["http_5xx"],
        "mesh_errors": rep_m.n_errors,
        "platform": dev.platform,
    }))
"""

# vocab-sharded mining bracket (ISSUE 7): a basket matrix whose dense
# single-device formulation busts the (deliberately small) HBM budget is
# mined through the sharded count→emit pipeline on a 1x8 vocab mesh —
# counts stay column-sharded, each shard emits its own antecedent rows.
# Bitpack is pinned off so the bracket measures the MODEL-sharded dense
# path, not the bit-packed fallback the budget would otherwise trigger.
_SCALE_SHARD_BENCH = r"""
import dataclasses, json, sys, time
import jax
from kmlserver_tpu.config import MiningConfig
from kmlserver_tpu.data.synthetic import synthetic_table
from kmlserver_tpu.mining.miner import mine
from kmlserver_tpu.mining.vocab import build_baskets

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
n_devices = len(jax.devices())
assert n_devices >= 4, f"mesh bracket needs >=4 virtual devices, have {n_devices}"
P_N, V_N, ROWS = 20000, 2000, 400000
table = synthetic_table(
    n_playlists=P_N, n_tracks=V_N, target_rows=ROWS, seed=11
)
baskets = build_baskets(table)
# dense single-device plan: int8 one-hot + int32 counts + top-k scratch
dense_bytes = P_N * V_N + 8 * V_N * V_N
budget = dense_bytes // 2  # one device cannot hold the dense formulation
cfg = dataclasses.replace(
    MiningConfig.from_env(dotenv_path=None),
    min_support=0.005, k_max_consequents=64,
    model_layout="sharded", bitpack_threshold_elems=None,
    hbm_budget_bytes=budget, prune_vocab_threshold=1 << 30,
)
t0 = time.perf_counter()
result = mine(baskets, cfg)
mine_s = time.perf_counter() - t0
n_rules = int((result.tensors.rule_ids >= 0).sum())
print(json.dumps({
    "mine_s": round(mine_s, 3),
    "rows_per_s": round(ROWS / mine_s, 1),
    "shape": f"{P_N}x{V_N}",
    "count_path": result.count_path,
    "shards": n_devices,
    "dense_single_device_bytes": dense_bytes,
    "hbm_budget_bytes": budget,
    "per_shard_counts_bytes": 4 * V_N * V_N // n_devices,
    "rules_emitted": n_rules,
    "frequent_items": result.tensors.n_frequent_items,
    "platform": dev.platform,
}))
"""

# the sparsity-adaptive bracket (ISSUE 13): the sparse CSR×bitpacked
# hybrid vs the standing scale_cpu_native record-holder ON THE SAME
# ≥99%-sparse workload (same prune, same emission contract, tensors
# asserted bit-identical) — plus a dense/bitpack/sparse identity leg at
# a bounded sub-shape and the density sweep that re-measures and
# re-banks the dispatch lookup table the auto path consults.
_SCALE_SPARSE_BENCH = r"""
import dataclasses, json, os, socket, sys, time
import numpy as np
import jax
from kmlserver_tpu.config import MiningConfig
from kmlserver_tpu.data.synthetic import synthetic_baskets
from kmlserver_tpu.mining import dispatch as dispatch_mod
from kmlserver_tpu.mining.miner import mine
from kmlserver_tpu.mining.sweep import run_density_sweep

dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)
P_N = int(os.environ.get("KMLS_BENCH_SPARSE_PLAYLISTS", "1500000"))
V_N = int(os.environ.get("KMLS_BENCH_SPARSE_TRACKS", "40000"))
ROWS = int(os.environ.get("KMLS_BENCH_SPARSE_ROWS", "6000000"))
out = {}

def same_tensors(a, b):
    return bool(
        np.array_equal(a.rule_ids, b.rule_ids)
        and np.array_equal(a.rule_counts, b.rule_counts)
        and np.array_equal(a.item_counts, b.item_counts)
    )

# ---- identity leg: all four routes on a bounded sub-shape (small
# enough that the forced DENSE leg stays cheap on a 2-core CI runner) --
small = synthetic_baskets(
    n_playlists=8000, n_tracks=1200, target_rows=80000, seed=13
)
base_cfg = dataclasses.replace(
    MiningConfig.from_env(dotenv_path=None),
    min_support=0.001, k_max_consequents=64,
)
legs = {}
for name, kw in (
    ("sparse", dict(count_path="sparse")),
    ("dense", dict(count_path="dense", native_cpu_pair_counts=False)),
    ("bitpack", dict(count_path="bitpack")),
    ("native", dict(count_path="dense")),
):
    legs[name] = mine(small, dataclasses.replace(base_cfg, **kw)).tensors
out["identical"] = all(
    same_tensors(legs["sparse"], t) for t in legs.values()
)
print(json.dumps(out), flush=True)  # checkpoint

# ---- the headline: sparse vs the native record path, SAME workload ----
baskets = synthetic_baskets(
    n_playlists=P_N, n_tracks=V_N, target_rows=ROWS, seed=7
)
rows = len(baskets.playlist_rows)
cfg = dataclasses.replace(
    MiningConfig.from_env(dotenv_path=None),
    min_support=8.0 / P_N, k_max_consequents=64,
)
plan = dispatch_mod.plan_count_path(
    cfg, P_N, V_N, rows, backend=jax.default_backend(), baskets=baskets
)
# control probe: a dense-regime workload (5% density, toy size) must
# keep resolving to the dense family — the dispatch smoke pins both
# directions of the decision
plan_dense = dispatch_mod.plan_count_path(
    base_cfg, 4000, 1000, 200000, backend=jax.default_backend()
)
out.update({
    "shape": f"{P_N}x{V_N}",
    "rows": rows,
    "density": round(rows / (P_N * float(V_N)), 8),
    "auto_path": plan.path,
    "auto_source": plan.source,
    "auto_path_dense_regime": plan_dense.path,
    "table_cell": plan.cell,
})
r_sparse = mine(baskets, dataclasses.replace(cfg, count_path="sparse"))
out["sparse_mine_s"] = round(r_sparse.duration_s, 3)
out["sparse_rows_per_s"] = round(rows / r_sparse.duration_s, 1)
out["count_path"] = r_sparse.count_path
out["frequent_items"] = r_sparse.tensors.n_frequent_items
out["platform"] = dev.platform
print(json.dumps(out), flush=True)  # checkpoint before the slow leg
r_native = mine(baskets, dataclasses.replace(cfg, count_path="dense"))
out["native_mine_s"] = round(r_native.duration_s, 3)
out["native_rows_per_s"] = round(rows / r_native.duration_s, 1)
out["native_count_path"] = r_native.count_path
out["speedup_vs_native"] = round(
    r_native.duration_s / r_sparse.duration_s, 2
)
out["headline_identical"] = same_tensors(
    r_sparse.tensors, r_native.tensors
)
print(json.dumps(out), flush=True)  # checkpoint before the sweep

# ---- density axis: re-measure + re-bank the dispatch lookup table ----
records = run_density_sweep(
    max_rows=min(4_000_000, max(ROWS // 2, 20000))
)
table = dispatch_mod.table_from_records(
    records, jax.default_backend(),
    measured_on=f"{socket.gethostname()}/{dev.device_kind}",
    banked_at=time.time(),
    base=dispatch_mod.load_table(),
)
dispatch_mod.save_table(dispatch_mod.builtin_table_path(), table)
out["table_points"] = len(records)
out["table_cells"] = len(
    table["backends"][jax.default_backend()]["cells"]
)
out["sweep_identical"] = all(r["identical"] for r in records)
print(json.dumps(out))
"""


# every phase script prints "device: ..." to stderr right after backend
# init; on TPU, not seeing it within this grace period means the backend
# init hung (the flaky-pool failure mode) — kill early instead of burning
# the phase's full timeout on a process that will never start computing.
# Default matches the prober's timeout: a pool the prober certifies
# healthy must not have phases killed under a shorter fuse.
STARTUP_GRACE_S = 240.0


def _startup_grace_s() -> float:
    # env read at call time, not import time (envread checker)
    return float(
        os.environ.get("KMLS_BENCH_STARTUP_GRACE_S", str(STARTUP_GRACE_S))
    )


def _salvage_checkpoint(
    stdout_parts: list[str], name: str, reason: str
) -> dict | None:
    """Last parseable JSON DICT on a phase's stdout (phases checkpoint
    complete dicts; a bare scalar — e.g. a line truncated by a kill — must
    not be returned, callers assume dict). The ONE copy of this parse for
    the success, timeout, and crash paths."""
    stdout = "".join(stdout_parts)
    skipped = 0
    for line in reversed(stdout.strip().splitlines()):
        try:
            salvaged = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(salvaged, dict):
            if reason:
                log(f"{name} phase {reason} but a checkpoint was salvaged")
            elif skipped:
                # clean exit but the LAST line wasn't the result: say so —
                # an earlier checkpoint may be missing later keys
                log(
                    f"{name} phase: result taken {skipped} line(s) above "
                    "an unparseable stdout tail"
                )
            return salvaged
        skipped += 1
    return None


def _run_phase(
    name: str,
    code: str,
    argv: list[str],
    *,
    platform: str,
    timeout: float = 1800,
    attempts: int = 2,
    extra_env: dict | None = None,
) -> dict | None:
    """Run one bench phase in its own process with transient-failure
    retries and (on TPU) a backend-init watchdog; → parsed result JSON
    (last stdout line) or None (logged)."""
    env = _phase_env(platform)
    if extra_env:
        env.update(extra_env)
    for attempt in range(1, attempts + 1):
        proc = _tracked_popen(
            [sys.executable, "-c", code, *argv],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        stderr_lines: list[str] = []
        stdout_parts: list[str] = []
        started = threading.Event()

        def _drain_err() -> None:
            for line in proc.stderr:  # type: ignore[union-attr]
                stderr_lines.append(line.rstrip())
                log(f"[{name}] {line.rstrip()}")
                if "device:" in line:
                    started.set()

        def _drain_out() -> None:
            stdout_parts.append(proc.stdout.read())  # type: ignore[union-attr]

        t_err = threading.Thread(target=_drain_err, daemon=True)
        t_out = threading.Thread(target=_drain_out, daemon=True)
        t_err.start()
        t_out.start()

        timed_out = False
        t_phase = time.monotonic()
        if platform == "tpu":
            # never arm a grace longer than the phase's own budget, and
            # count grace time AGAINST that budget below — otherwise a
            # short-deadline phase could overrun the bench deadline by
            # grace+timeout and cost the whole JSON artifact
            grace = min(_startup_grace_s(), timeout)
            t_end = t_phase + grace
            # poll alongside the wait: a phase that crashes at import never
            # prints a device line and must not idle out the full grace
            while (
                not started.is_set()
                and proc.poll() is None
                and time.monotonic() < t_end
            ):
                started.wait(timeout=2.0)
            if not started.is_set() and proc.poll() is None:
                log(
                    f"{name} phase: no device line within "
                    f"{grace:.0f}s — backend init hang; killing "
                    "early instead of burning the phase timeout"
                )
                _kill_tree(proc)
                proc.wait()
                t_err.join(timeout=10)
                t_out.join(timeout=10)
                # unlike a full-timeout hang (which already burned the whole
                # phase budget), the early kill only cost the grace period —
                # the flaky pool often recovers, so this IS worth a retry
                # (when the deadline still has room for one)
                if attempt < attempts and _remaining() > grace + 60:
                    log(
                        f"{name} phase init hang (attempt {attempt}/"
                        f"{attempts}); retrying in 30s"
                    )
                    time.sleep(30)
                    continue
                return None
        if not timed_out:
            try:
                proc.wait(timeout=max(timeout - (time.monotonic() - t_phase), 5.0))
            except subprocess.TimeoutExpired:
                _kill_tree(proc)
                timed_out = True
                log(f"{name} phase timed out after {timeout:.0f}s (backend hang?)")
        proc.wait()
        t_err.join(timeout=10)
        t_out.join(timeout=10)
        stderr_text = "\n".join(stderr_lines)
        if timed_out:
            # no retry (a hang already burned budget once) — but salvage
            # the last checkpoint JSON the phase printed before the kill
            # (scale_demo checkpoints after every completed section)
            return _salvage_checkpoint(stdout_parts, name, "timed out")
        if proc.returncode == 0:
            result = _salvage_checkpoint(stdout_parts, name, "")
            if result is None:
                log(f"{name} phase produced no parseable result dict")
            return result
        kind = _classify(stderr_text, timed_out=False)
        if kind == "transient" and attempt < attempts:
            log(
                f"{name} phase hit a transient backend error "
                f"(attempt {attempt}/{attempts}); retrying in 30s"
            )
            time.sleep(30)
            continue
        log(
            f"{name} phase failed (exit {proc.returncode}): "
            + (
                "TPU unreachable (backend init error)"
                if kind == "transient"
                else f"compute failed on {platform}"
            )
        )
        # salvage like the timeout path: a phase that checkpointed partial
        # JSON before crashing (config4's cold line, scale_demo's section
        # lines) still contributes — the unloseable-artifact rule applies
        # to phase results too, not only the top-level line
        return _salvage_checkpoint(stdout_parts, name, "failed")
    return None


def _wait_ready(url: str, deadline_s: float) -> bool:
    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        try:
            with urllib.request.urlopen(url + "/readyz", timeout=5) as resp:
                if resp.status == 200:
                    return True
        except Exception:
            pass
        time.sleep(1.0)
    return False


def _parse_latency_percentiles(metrics_text: str) -> dict:
    """Prometheus text → {"p50_ms": ..., ...} (empty if absent)."""
    out = {}
    for q, key in (("0.5", "p50_ms"), ("0.95", "p95_ms"), ("0.99", "p99_ms")):
        m = re.search(
            r'kmls_request_latency_seconds\{quantile="%s"\} ([0-9.eE+-]+)' % q,
            metrics_text,
        )
        if m:
            out[key] = float(m.group(1)) * 1e3
    return out


def _parse_attribution(metrics_text: str) -> dict:
    """Queue-vs-device attribution summaries (serving/metrics.py renders
    them in milliseconds) → {"queue_wait_p99_ms": ..., ...} (empty if
    absent — an old server simply doesn't carry the split)."""
    out = {}
    for metric, label in (
        ("kmls_queue_wait_ms", "queue_wait"),
        ("kmls_device_ms", "device"),
        ("kmls_e2e_ms", "e2e"),
    ):
        for q, suffix in (
            ("0.5", "p50_ms"), ("0.99", "p99_ms"), ("0.999", "p999_ms")
        ):
            m = re.search(
                r'%s\{quantile="%s"\} ([0-9.eE+-]+)' % (metric, q),
                metrics_text,
            )
            if m:
                out[f"{label}_{suffix}"] = float(m.group(1))
    return out


def _scrape_server_percentiles(url: str) -> dict | None:
    """Read the server's own latency percentiles from /metrics
    (serving/metrics.py renders them) → {"p50_ms": ..., ...} or None,
    plus the queue-vs-device attribution under an "attribution" subkey.
    Recording these NEXT TO the client-observed replay numbers separates
    server time from harness queueing (VERDICT r2 next-round #7)."""
    try:
        with urllib.request.urlopen(url + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
    except Exception as exc:
        log(f"[replay] /metrics scrape failed: {type(exc).__name__}: {exc}")
        return None
    pcts = _parse_latency_percentiles(text)
    if not pcts:
        return None
    attribution = _parse_attribution(text)
    if attribution:
        pcts["attribution"] = attribution
    return pcts


def _reset_server_metrics(url: str) -> bool:
    """POST /metrics/reset (loopback-guarded, serving/app.py): start a
    fresh latency window so the next scrape covers exactly one replay run
    (VERDICT r4 #7)."""
    try:
        req = urllib.request.Request(
            url + "/metrics/reset", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status == 200
    except Exception as exc:
        log(f"[replay] /metrics/reset failed: {type(exc).__name__}: {exc}")
        return False


def replay_phase(platform: str) -> dict | None:
    """Full-stack serving measurement: mining job → PVC artifacts → real
    HTTP server (own process, owns the chip) → open-loop 1k-QPS replay."""
    qps = float(os.environ.get("KMLS_BENCH_REPLAY_QPS", "1000"))
    n_req = int(os.environ.get("KMLS_BENCH_REPLAY_REQUESTS", "8000"))
    with tempfile.TemporaryDirectory(prefix="kmls_bench_pvc_") as base:
        ds_dir = os.path.join(base, "datasets")
        os.makedirs(ds_dir)
        csv_path = os.path.join(ds_dir, "2023_spotify_ds2.csv")
        if _run_phase(
            "replay-setup", _CSV_SETUP, [csv_path], platform="cpu", timeout=300
        ) is None:
            return None
        job_env = {"BASE_DIR": base, "DATASETS_DIR": ds_dir,
                   "MIN_SUPPORT": str(MIN_SUPPORT)}
        env = _phase_env(platform)
        env.update(job_env)
        log(f"[replay] running the real mining job on {platform}...")
        job_timeout = min(900.0, max(_remaining(), 60.0))
        t_job = time.monotonic()
        try:
            job = subprocess.run(
                [sys.executable, "-m", "kmlserver_tpu.mining.job"],
                capture_output=True, text=True, timeout=job_timeout, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            log(f"replay skipped: mining job hung past {job_timeout:.0f}s")
            return None
        # the container-shaped end-to-end bracket (process start → pickles
        # on the PVC, interpreter + backend init included) — BASELINE.md's
        # "ML job end-to-end ≈ 1 min" row
        job_end_to_end_s = round(time.monotonic() - t_job, 2)
        log(f"[replay] mining job end-to-end: {job_end_to_end_s:.2f}s "
            "(reference: ~60s, relatorio.pdf p.3)")
        if job.returncode != 0:
            for line in job.stdout.splitlines()[-10:]:
                log(f"[replay-job] {line}")
            for line in job.stderr.splitlines()[-10:]:
                log(f"[replay-job] {line}")
            log(f"replay skipped: mining job failed (exit {job.returncode})")
            return None

        srv_env = _phase_env(platform)
        srv_env.update({"BASE_DIR": base, "KMLS_PORT": "0",
                        "POLLING_WAIT_IN_MINUTES": "1",
                        # arm span tracing at the overhead-bracket-proven
                        # sample so the final run's echoed ids can be
                        # JOINed against /debug/traces (ISSUE 9
                        # remainder); traceoverhead pins p99 ≤ 1.05x at
                        # this setting every round, and the per-run
                        # summaries keep the raw numbers honest
                        "KMLS_TRACE_SAMPLE": "0.01"})
        if platform == "tpu":
            # ride the tunnel: through this environment's remote-TPU link
            # every device call pays ~65 ms of round trip, so batch-32
            # dispatch caps throughput at ~150-480 QPS no matter how fast
            # the chip is (r03 first pass: 142 QPS, 6334 drops). Larger
            # batches amortize the RTT — the batcher's backpressure then
            # self-sizes batches to match the arrival rate (a blocked
            # dispatch grows the next batch). Latency stays RTT-floored
            # (physically unavoidable over this link — the on-device time
            # is the serving_batch32_p50_ms key); production pods have a
            # LOCAL chip and keep the default batch-32 low-latency config.
            srv_env.update({
                "KMLS_BATCH_MAX_SIZE": "256",
                "KMLS_BATCH_WINDOW_MS": "20",
                "KMLS_BATCH_MAX_INFLIGHT": "8",
            })
        server = _tracked_popen(
            [sys.executable, "-m", "kmlserver_tpu.serving.server"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=srv_env, cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        srv_lines: list[str] = []
        port_found = threading.Event()
        port_holder: list[int] = []

        def _drain() -> None:
            for line in server.stdout:  # type: ignore[union-attr]
                srv_lines.append(line.rstrip())
                m = re.search(r"serving on \S+?:(\d+)", line)
                if m and not port_found.is_set():
                    port_holder.append(int(m.group(1)))
                    port_found.set()

        t = threading.Thread(target=_drain, daemon=True)
        t.start()
        try:
            if not port_found.wait(timeout=120) or not port_holder:
                log("replay skipped: server never reported its port")
                for line in srv_lines[-10:]:
                    log(f"[replay-server] {line}")
                return None
            url = f"http://127.0.0.1:{port_holder[0]}"
            # jit warmup happens on first load; gate on readiness
            if not _wait_ready(url, deadline_s=min(300.0, max(_remaining(), 30.0))):
                log("replay skipped: server /readyz never went 200")
                for line in srv_lines[-10:]:
                    log(f"[replay-server] {line}")
                return None
            # median-of-N with an explicit warmup (VERDICT r3 weak #6: two
            # same-host runs spread 6.3 vs 10.5 ms p50 — one number is
            # luck; the mining phase already medians, now the replay does)
            load1 = os.getloadavg()[0] if hasattr(os, "getloadavg") else -1.0
            n_warm = int(os.environ.get("KMLS_BENCH_REPLAY_WARMUP", "1000"))
            n_runs = int(os.environ.get("KMLS_BENCH_REPLAY_RUNS", "3"))
            log(
                f"[replay] server ready at {url}; host load1 {load1:.2f}; "
                f"warmup {n_warm} requests, then {n_runs}x{n_req} at "
                f"{qps:.0f} QPS"
            )
            pickles = os.path.join(base, "pickles", "recommendations.pickle")
            client_env = None
            if platform == "tpu":
                # Little's law at ~0.3-0.5 s tunnel latency: 1k QPS needs
                # ~500 in flight; size the pool above that so the CLIENT
                # never caps what the batched server can absorb
                client_env = {"KMLS_BENCH_REPLAY_WORKERS": "768",
                              "KMLS_BENCH_REPLAY_QUEUE": "4096"}
            if n_warm > 0:
                _run_phase(
                    "replay-warmup", _REPLAY_CLIENT,
                    [url, str(qps), str(n_warm), pickles],
                    platform="cpu", timeout=300, extra_env=client_env,
                )
            runs: list[dict] = []
            # per-run server windows: reset the latency reservoir before
            # every run so the /metrics percentiles cover exactly the
            # requests that run's client percentiles cover (VERDICT r4 #7)
            window_clean = _reset_server_metrics(url)
            any_reset = window_clean
            for i in range(n_runs):
                if runs and _remaining() < 120:
                    log(
                        f"[replay] deadline headroom gone after run {i}; "
                        f"reporting the median of {len(runs)}"
                    )
                    break
                r = _run_phase(
                    "replay-client", _REPLAY_CLIENT,
                    [url, str(qps), str(n_req), pickles,
                     os.path.join(base, "trace_client.jsonl")],
                    platform="cpu", timeout=600, extra_env=client_env,
                )
                if r is not None:
                    log(
                        f"[replay] run {i}: p50 {r['p50_ms']:.2f}ms, "
                        f"{r['achieved_qps']:.0f} QPS, {r['n_errors']} errors"
                    )
                    if window_clean:
                        pcts = _scrape_server_percentiles(url)
                        if pcts:
                            r["server_percentiles"] = pcts
                    runs.append(r)
                window_clean = _reset_server_metrics(url)
                any_reset = any_reset or window_clean
            if not runs:
                return None
            run_summaries = []  # chronological, travels with the artifact
            for r in runs:
                s = {"p50_ms": round(r["p50_ms"], 3),
                     "achieved_qps": round(r["achieved_qps"], 1),
                     "n_errors": r["n_errors"]}
                if "server_percentiles" in r:
                    s["server_p50_ms"] = round(
                        r["server_percentiles"]["p50_ms"], 3
                    )
                run_summaries.append(s)
            report = sorted(runs, key=lambda r: r["p50_ms"])[len(runs) // 2]
            report["runs"] = run_summaries
            # trace JOIN (ISSUE 9 remainder): the last run's client
            # records vs the server's retained spans, merged by
            # scripts/kmls_tracejoin.py — proves the end-to-end id
            # propagation + join tooling against a REAL HTTP stack
            client_jsonl = os.path.join(base, "trace_client.jsonl")
            if os.path.exists(client_jsonl):
                try:
                    traces_path = os.path.join(base, "debug_traces.json")
                    with urllib.request.urlopen(
                        url + "/debug/traces", timeout=10
                    ) as resp:
                        with open(traces_path, "wb") as fh:
                            fh.write(resp.read())
                    join = subprocess.run(
                        [sys.executable,
                         os.path.join("scripts", "kmls_tracejoin.py"),
                         "--client", client_jsonl, "--traces", traces_path],
                        capture_output=True, text=True, timeout=60,
                        cwd=os.path.dirname(os.path.abspath(__file__)),
                    )
                    joined = len(
                        [ln for ln in join.stdout.splitlines() if ln.strip()]
                    )
                    report["trace_joined"] = joined
                    report["trace_sample"] = 0.01
                    log(
                        f"[replay] tracejoin: {joined} per-request "
                        "timelines merged (client send/recv x server "
                        "spans)"
                    )
                except Exception as exc:
                    log(f"[replay] tracejoin skipped: {exc!r}")
            report["host_load1"] = round(load1, 2)
            report["warmup_requests"] = n_warm
            report["job_end_to_end_s"] = job_end_to_end_s
            if "server_percentiles" in report:
                report["server_percentiles_basis"] = (
                    "per-run window: reservoir reset before each run; "
                    "covers the same requests as the reported client run"
                )
            elif not any_reset:
                # reset endpoint unavailable (old server) — fall back to
                # the cumulative scrape, honestly labeled. Guarded on NO
                # reset ever succeeding: after a successful reset the
                # reservoir no longer holds the cumulative window, and a
                # scrape would fabricate near-zero percentiles under a
                # false label; honest absence beats that.
                server_pcts = _scrape_server_percentiles(url)
                if server_pcts:
                    report["server_percentiles"] = server_pcts
                    report["server_percentiles_note"] = (
                        "cumulative over warmup + all replay runs"
                    )
            return report
        finally:
            server.terminate()
            try:
                server.wait(timeout=10)
            except subprocess.TimeoutExpired:
                _kill_tree(server)


def _mfu_keys(mining: dict, prefix: str = "mining") -> dict:
    """Utilization accounting from the isolated matmul timing (VERDICT r2
    next-round #2): closed-form op count vs measured time vs chip peak.
    MFU uses the amortized (pipelined) time when available — per-blocked-call
    time is floored by the host<->device round trip (~65ms over this
    environment's remote-TPU tunnel), which measures the tunnel, not the
    chip."""
    out: dict = {}
    if "matmul_s" not in mining:
        return out
    p, v = mining["n_playlists"], mining["n_tracks"]
    ops = 2.0 * p * v * v  # V² output cells × P MACs × 2 ops/MAC
    mfu_time = mining.get("matmul_amortized_s", mining["matmul_s"])
    achieved = ops / mfu_time
    out[f"{prefix}_matmul_ms"] = round(mining["matmul_s"] * 1e3, 4)
    if "matmul_amortized_s" in mining:
        out[f"{prefix}_matmul_amortized_ms"] = round(
            mining["matmul_amortized_s"] * 1e3, 4
        )
    out[f"{prefix}_matmul_gops"] = round(ops / 1e9, 2)
    out[f"{prefix}_matmul_gops_per_s"] = round(achieved / 1e9, 1)
    for key in ("chain_n1", "chain_n2", "chain_t_short_s", "chain_t_long_s"):
        if key in mining:
            out[f"{prefix}_{key}"] = (
                round(mining[key], 6) if isinstance(mining[key], float)
                else mining[key]
            )
    kind = mining.get("device_kind", "").lower().replace(" ", "")
    for marker, peak in _INT8_PEAK_OPS.items():
        if marker in kind and mining.get("platform") == "tpu":
            mfu = 100.0 * achieved / peak
            if mfu <= 100.0:
                out[f"{prefix}_mfu_pct"] = round(mfu, 2)
            else:
                # physically impossible — the timing understates device
                # time (r03 shipped 177% from overlapped dispatches through
                # the tunnel). Flag at emission, never as a headline MFU.
                out[f"{prefix}_mfu_pct_suspect"] = round(mfu, 2)
                out[f"{prefix}_mfu_suspect_reason"] = (
                    ">100% MFU is physically impossible: the matmul timing "
                    "understates device time (overlapped dispatch/ack "
                    "artifacts); see the *_chain_* keys for the raw "
                    "slope inputs"
                )
            out[f"{prefix}_mfu_peak_tops"] = round(peak / 1e12, 1)
            break
    return out


def _headline_keys(
    platform: str, mining: dict, cpu_mining: dict | None = None
) -> dict:
    """The artifact's headline block: metric/value/vs_baseline + MFU
    accounting + (when the TPU took the headline over a CPU run) the CPU
    comparison keys. Pure — the ONE assembly used by every checkpoint and
    the final line, so partial and final artifacts can never disagree."""
    median_s = mining["median_s"]
    line = {
        "metric": "fpgrowth_ds2_rule_generation_time",
        "value": round(median_s, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_RULE_GEN_S / median_s, 1),
        "platform": platform,
    }
    line.update(_mfu_keys(mining))
    if mining.get("count_path"):
        line["mining_count_path"] = mining["count_path"]
    if cpu_mining is not None and cpu_mining is not mining:
        # the TPU suite took the headline; keep the CPU evidence too,
        # under unambiguous keys. Through this environment's tunnel the
        # TPU bracket pays host<->device round trips, so the native CPU
        # path can be FASTER — surface the best measured number explicitly
        # rather than burying it.
        line["mining_cpu_s"] = round(cpu_mining["median_s"], 4)
        line.update(_mfu_keys(cpu_mining, prefix="mining_cpu"))
        best_s = min(median_s, cpu_mining["median_s"])
        line["best_mining_s"] = round(best_s, 4)
        line["best_mining_platform"] = "tpu" if best_s == median_s else "cpu"
        line["vs_baseline_best"] = round(BASELINE_RULE_GEN_S / best_s, 1)
    return line


def run_mining(
    platform: str,
    npz_path: str,
    attempts: int | None = None,
    timeout: float | None = None,
) -> dict | None:
    """The headline phase keeps a 300 s floor even near the deadline (a
    bench with no mining number is worthless); OPTIONAL callers must pass
    a deadline-respecting timeout instead."""
    mining = _run_phase(
        "mining", _MINING_BENCH, [npz_path, str(MIN_SUPPORT), str(REPEATS)],
        platform=platform,
        attempts=attempts if attempts is not None
        else (3 if platform == "tpu" else 2),
        timeout=timeout if timeout is not None
        else min(1800, max(_remaining(), 300)),
    )
    return mining


def run_tpu_suite(em: ArtifactEmitter, npz_path: str) -> dict | None:
    """The on-chip phases. → the TPU mining result (or None if mining
    failed); optional phases fill the emitter's extras as deadline headroom
    allows, checkpointing the artifact line after each.

    Serialized per bank: the watcher's capture and the driver's round-end
    bench share ONE chip — if another bench holds the lock past the wait
    budget, this one adopts the holder's banked measurements (replay-only)
    instead of contending through the tunnel and corrupting both."""
    if STATE.replay_only:
        return _run_tpu_suite_inner(em, npz_path)  # no live runs → no lock
    lock = _acquire_tpu_lock(min(max(_remaining() - 420, 0.0), 600.0))
    if lock is None:
        log(
            "another bench holds the TPU-suite lock — reloading the bank "
            "and replaying its measurements instead of contending"
        )
        fresh = BenchState(STATE.path)
        STATE.phases, STATE.banked_at = fresh.phases, fresh.banked_at
        STATE.replay_only = True
        try:
            mining = _run_tpu_suite_inner(em, npz_path)
        finally:
            # scoped to this suite: the caller may still run live
            # NON-chip work (e.g. the CPU comparison) afterwards
            STATE.replay_only = False
        if mining is not None:
            em.extras["tpu_suite_from_bank"] = True
            age = STATE.age_s("mining_tpu")
            if age is not None:
                em.extras["tpu_bank_age_s"] = round(age)
            em.checkpoint()
        return mining
    try:
        return _run_tpu_suite_inner(em, npz_path)
    finally:
        _release_tpu_lock(lock)


def _run_tpu_suite_inner(em: ArtifactEmitter, npz_path: str) -> dict | None:
    result = em.extras
    banked_mining = STATE.get("mining_tpu")
    mining = None
    if (
        banked_mining is not None
        and STATE.npz_path
        and os.path.exists(STATE.npz_path)
    ):
        # both the result AND the serving input survive across windows;
        # a bank without its npz sidecar re-mines (serving needs the npz)
        try:
            shutil.copyfile(STATE.npz_path, npz_path)
            log("mining_tpu: banked from a prior window — skipping live run")
            mining = dict(banked_mining)
            result["mining_tpu_from_bank"] = True
            age = STATE.age_s("mining_tpu")
            if age is not None:
                result["mining_tpu_bank_age_s"] = round(age)
        except OSError as exc:
            log(f"state bank npz restore failed ({exc}); re-mining live")
    if mining is None and banked_mining is not None and STATE.replay_only:
        # no sidecar, but no live serving run is coming either — the
        # banked headline alone is still real on-chip evidence
        log("mining_tpu: banked (npz sidecar missing; serving skipped)")
        mining = dict(banked_mining)
        result["mining_tpu_from_bank"] = True
        age = STATE.age_s("mining_tpu")
        if age is not None:
            result["mining_tpu_bank_age_s"] = round(age)
    if mining is None:
        if STATE.replay_only:
            return None  # no live runs in replay-only mode
        mining = run_mining("tpu", npz_path)
        if mining is not None:
            STATE.bank("mining_tpu", mining)
            if STATE.npz_path:
                try:
                    shutil.copyfile(npz_path, STATE.npz_path)
                except OSError as exc:
                    log(f"state bank npz copy failed ({exc})")
    if mining is None:
        return None
    em.set_headline("tpu", mining)

    # serving + replay directly after the headline: config 5 is a judged
    # BASELINE target and the pool window may be short — the supporting
    # phases (popcount/scale/config4/sweep) run after. A banked phase
    # replays even past the deadline gate (replaying is free; budgets gate
    # only live runs, inside _banked).
    _record_serving(result, npz_path, "tpu", bank="serving_tpu", budget_s=120)
    em.checkpoint()

    _record_replay(result, "tpu", bank="replay_tpu", budget_s=300)
    em.checkpoint()

    popcount = _banked("popcount_tpu", lambda: _run_phase(
        "popcount", _POPCOUNT_BENCH,
        ["compiled", "2246", "2171", "240249"],
        platform="tpu", timeout=min(900, _remaining()),
    ), budget_s=240, extras=result)
    if popcount is not None:
        log(
            f"popcount kernel [{popcount['kernel']}] (compiled TPU, "
            f"ds2 shape): {popcount['popcount_ms']:.2f}ms/call vs dense "
            f"MXU {popcount['dense_ms']:.2f}ms, exact match, "
            f"{popcount['words_per_s'] / 1e9:.2f} Gwords/s amortized"
        )
        result["popcount_ds2_ms"] = round(popcount["popcount_ms"], 3)
        result["dense_pair_ds2_ms"] = round(popcount["dense_ms"], 3)
        result["popcount_kernel"] = popcount["kernel"]
        result["popcount_words_per_s"] = round(popcount["words_per_s"])
        for key in ("popcount_amortized_ms", "dense_amortized_ms"):
            if key in popcount:
                result[key.replace("_ms", "_ds2_ms")] = round(
                    popcount[key], 3
                )
        # the MXU unpack-matmul impl (production default for the
        # bit-packed path), measured next to the VPU kernel
        for src, dst in (("mxu_ms", "bitpack_mxu_ds2_ms"),
                         ("mxu_amortized_ms", "bitpack_mxu_amortized_ds2_ms"),
                         ("mxu_words_per_s", "bitpack_mxu_words_per_s")):
            if src in popcount:
                result[dst] = round(popcount[src], 3)
    em.checkpoint()

    # TRUE config-4 shape (10M playlists × 1M tracks) on the single
    # chip, workload generated in HBM (Bernoulli-Zipf bitset — zero
    # host generation or transfer); compare CONFIG4_CPU_r03.json's
    # 77.8 s one-core bracket
    config4 = _banked("config4_tpu", lambda: _run_phase(
        "config4-devicegen", _CONFIG4_BENCH, ["--device-gen"],
        platform="tpu", timeout=min(900, _remaining()),
    ), budget_s=300, extras=result)
    if config4 is not None:
        for src, dst in (
            ("mine_s", "config4_mine_s"),
            ("mine_cold_s", "config4_mine_cold_s"),
            ("gen_device_s", "config4_gen_device_s"),
            ("rows", "config4_rows"),
            ("rows_basis", "config4_rows_basis"),
            ("rows_per_s", "config4_rows_per_s"),
            ("frequent_items", "config4_frequent_items"),
            ("n_rules", "config4_n_rules"),
            ("bitset_gib", "config4_bitset_gib"),
            ("workload_model", "config4_workload_model"),
            ("rows_measured", "config4_rows_measured"),
        ):
            if src in config4:
                result[dst] = config4[src]
    em.checkpoint()

    # config-4 scale mechanics on real HBM: 1M playlists x 100k vocab
    # through Apriori prune + the bit-packed popcount path (SCALE.md
    # documents the model; this captures the numbers)
    scale = _banked("scale_tpu", lambda: _run_phase(
        "scale", _SCALE_BENCH,
        ["--playlists", "1000000", "--tracks", "100000",
         "--rows", "50000000", "--min-support", "0.001"],
        platform="tpu", timeout=min(900, _remaining()),
    ), budget_s=300, extras=result)
    if scale is not None:
        result["scale_1m_x_100k_mine_s"] = scale["mine_s"]
        result["scale_rows_per_s"] = scale["rows_per_s"]
        result["scale_frequent_items"] = scale["frequent_items"]
        # auto dispatch (warm) + device-resident timings: the HBM-fit
        # dense path and the tunnel-free on-chip bracket, labeled
        for src, dst in (
            ("auto_mine_s", "scale_auto_mine_s"),
            ("auto_path", "scale_auto_path"),
            ("auto_rows_per_s", "scale_auto_rows_per_s"),
            ("device_resident_mine_s", "scale_device_resident_mine_s"),
            ("device_resident_path", "scale_device_resident_path"),
        ):
            if src in scale:
                result[dst] = scale[src]
    em.checkpoint()

    # the reference's full 68-point support sweep, count-once, on-chip
    sweep = _banked("sweep_tpu", lambda: _run_phase(
        "sweep", _SWEEP_BENCH, [], platform="tpu",
        timeout=min(600, _remaining()),
    ), budget_s=180, extras=result)
    if sweep is not None:
        result["sweep_points"] = sweep["points"]
        result["sweep_total_s"] = sweep["total_s"]
        result["sweep_emission_total_s"] = sweep["emission_total_s"]
        result["sweep_setup_plus_count_s"] = sweep["setup_plus_count_s"]
    em.checkpoint()

    # on-hardware Pallas tile tune (VERDICT r4 #4): pins the kernel's
    # tile defaults from measurement instead of guesswork, and settles
    # VPU-vs-MXU with same-bitset numbers (the popcount phase above
    # carries the MXU twin). Named "pallas-tune" — NOT "popcount-..." —
    # so result salvage/log greps can't confuse it with the kernel phase.
    def _tune_runner() -> dict | None:
        r = _run_phase(
            "pallas-tune", _TUNE_BENCH, [],
            platform="tpu", timeout=min(900, _remaining()),
        )
        # a no-config-succeeded error is a failure, not a result — banking
        # it would replay the failure into every later window
        return None if r is None or "error" in r else r

    tune = _banked(
        "popcount_tune_tpu", _tune_runner, budget_s=240, extras=result
    )
    if tune is not None:
        for src, dst in (
            ("best_config", "popcount_tune_best_config"),
            ("best_variant", "popcount_tune_best_variant"),
            ("best_ms", "popcount_tune_best_ms"),
            ("best_words_per_s", "popcount_tune_best_words_per_s"),
            ("results", "popcount_tune_results"),
            ("partial", "popcount_tune_partial"),
        ):
            if src in tune:
                result[dst] = tune[src]
    em.checkpoint()

    # supplementary CPU replay: through this environment's remote-TPU
    # tunnel every request pays ~65 ms of round trip, which measures
    # the tunnel, not the serving stack — a production pod has a LOCAL
    # chip. The CPU-stack replay (native mining fallback + host
    # kernels) is the closer proxy for framework overhead; record it
    # under cpu_-prefixed keys next to the tunnel numbers.
    cpu_replay: dict = {}
    _record_replay(cpu_replay, "cpu", bank="replay_cpu_supp", budget_s=300)
    for key, val in cpu_replay.items():
        # never clobber THIS run's freshly measured cpu_replay_* keys
        # (a takeover relabels the CPU suite's replay under these names;
        # those match the artifact's probe history and host-load context,
        # a banked prior-window supplement does not)
        result.setdefault(f"cpu_{key}", val)
    em.checkpoint()

    # the 10k-QPS Zipf throughput bracket is CPU-measured by construction
    # (self-labeled keys, no takeover relabeling) — skip only when a CPU
    # suite earlier in this run already recorded it
    if "replay10k_p50_ms" not in result:
        _record_replay10k(result, bank="replay10k_cpu", budget_s=240)
        em.checkpoint()

    # the kill-a-replica chaos bracket is CPU-measured by construction
    # too (self-labeled keys) — skip only when a CPU suite earlier in
    # this run already recorded it
    if "chaos_errors" not in result:
        _record_chaos(result, bank="chaos_cpu", budget_s=200)
        em.checkpoint()

    # the traffic-shape bracket (ISSUE 8): CPU-measured by construction
    if "loadshape_p99_ms" not in result:
        _record_loadshape(result, bank="loadshape_cpu", budget_s=200)
        em.checkpoint()

    # predictive-serving A/B bracket (ISSUE 17): CPU-measured by
    # construction — forecaster on vs off at equal capacity over
    # ramp/sine/constant
    if "loadshape_pred_ramp_pred_p99_ms" not in result:
        _record_loadshape_pred(
            result, bank="loadshape_pred_cpu", budget_s=240
        )
        em.checkpoint()

    # mining-interruption bracket: CPU-measured by construction as well
    if "mine_resume_s" not in result:
        _record_mine_resume(result, bank="mine_resume_cpu", budget_s=150)
        em.checkpoint()

    # second-model-family + confidence-mode brackets: CPU-measured by
    # construction (self-labeled keys) — skip only when a CPU suite
    # earlier in this run already recorded them
    if "hybrid_p99_ms" not in result:
        _record_als_hybrid(result, bank="als_hybrid_cpu", budget_s=240)
        em.checkpoint()
    if "confserve_p99_ms" not in result:
        _record_confserve(result, bank="confserve_cpu", budget_s=200)
        em.checkpoint()

    # tracing-overhead micro-bracket (ISSUE 9): CPU-measured by
    # construction (self-labeled keys) — the ≤1.05 p99 claim must ride
    # the TPU artifact too, same as every sibling bracket above
    if "traceoverhead_p99_ratio" not in result:
        _record_traceoverhead(result, bank="traceoverhead_cpu", budget_s=150)
        em.checkpoint()

    # continuous-freshness bracket (ISSUE 10): CPU-measured by
    # construction — the ≥5x delta speedup / zero-5xx / fleet-multiplier
    # acceptance evidence must ride the TPU artifact too
    if "freshness_speedup" not in result:
        _record_freshness(result, bank="freshness_cpu", budget_s=200)
        em.checkpoint()

    # fleet cache-routing bracket (ISSUE 15): CPU-measured by
    # construction (real local server processes) — the routed-vs-
    # independent multiplier + kill/delta zero-5xx evidence must ride
    # the TPU artifact too
    if "fleet_hit_ratio" not in result:
        _record_fleet(result, bank="fleet_cpu", budget_s=240)
        em.checkpoint()

    # pod-spanning serve-mesh bracket (ISSUE 16): CPU-measured by
    # construction (socket transport stands in for GSPMD-over-DCN) —
    # the gang-vs-sharded identity + shard-loss zero-5xx evidence must
    # ride the TPU artifact too
    if "meshserve_identical" not in result:
        _record_meshserve(result, bank="meshserve_cpu", budget_s=240)
        em.checkpoint()

    # gray-failure chaos bracket (ISSUE 18): CPU-measured by
    # construction (real local server processes under an injected
    # stall) — the hedged-vs-control tail + zero-5xx/zero-drop evidence
    # must ride the TPU artifact too
    if "slowpeer_p99_ratio" not in result:
        _record_slowpeer(result, bank="slowpeer_cpu", budget_s=240)
        em.checkpoint()

    # storage gray-failure bracket (ISSUE 19): CPU-measured by
    # construction (tmpfs artifact dir + injected IO faults) — the
    # zero-5xx / p99-unmoved / torn-free ENOSPC evidence must ride the
    # TPU artifact too
    if "graystore_http_5xx" not in result:
        _record_graystore(result, bank="graystore_cpu", budget_s=200)
        em.checkpoint()

    # quality-loop bracket (ISSUE 14): CPU-measured by construction —
    # the held-out recall / measured-weight / compaction-identity
    # evidence must ride the TPU artifact too
    if "quality_recall_blend" not in result:
        _record_quality(result, bank="quality_cpu", budget_s=240)
        em.checkpoint()

    # sparsity-adaptive bracket (ISSUE 13): CPU-measured by construction
    # (the native comparison IS a CPU kernel) — the ≥5x-at-≥99%-sparsity
    # and bit-identity evidence must ride the TPU artifact too
    if "sparse_speedup_vs_native" not in result:
        _record_scale_sparse(result, bank="scale_sparse_cpu", budget_s=240)
        em.checkpoint()

    # cost-attribution bracket (ISSUE 12): unlike the CPU-by-construction
    # siblings above, this phase runs ON the chip (platform="tpu" → the
    # phase subprocess sees the TPU), so a window measures serve-kernel
    # MFU against the real chip's peak — the MFU-anchored number
    # ROADMAP's TPU-window item names. Banked under its own TPU key; a
    # chipless round leaves it to the CPU suite's honestly-labeled run.
    if "costattrib_mfu" not in result:
        _record_costattrib(
            result, bank="costattrib_tpu", budget_s=150, platform="tpu"
        )
        em.checkpoint()
    return mining


def run_cpu_suite(em: ArtifactEmitter, npz_path: str) -> dict | None:
    """Everything that doesn't need the chip, including CPU-labeled
    stand-ins for the config-4 popcount/scale evidence (VERDICT r2 #4:
    never ship a round with zero config-4 evidence)."""
    result = em.extras
    mining = run_mining("cpu", npz_path)
    if mining is None:
        return None
    em.set_headline("cpu", mining)

    # serving + replay FIRST: config 5 is a judged BASELINE target; the
    # scale/popcount stand-ins are supporting evidence and run after
    if _remaining() > 120:
        _record_serving(result, npz_path, "cpu")
        em.checkpoint()

    if _remaining() > 240:
        _record_replay(result, "cpu")
        em.checkpoint()

    if _remaining() > 180:
        # the 10k-QPS Zipf throughput bracket: cache + batcher + native
        # kernel in-process (PR 2's tentpole acceptance)
        _record_replay10k(result)
        em.checkpoint()

    if _remaining() > 150:
        # kill-a-replica fault-tolerance bracket (PR 3's acceptance):
        # zero 5xx while a replica dies under 1k QPS
        _record_chaos(result)
        em.checkpoint()

    if _remaining() > 150:
        # traffic-shape bracket (ISSUE 8): 10x burst trains / flash
        # crowd / epoch-boundary hot-key flip through the admission
        # ladder — p99 < 10 ms and zero 5xx through the bursts
        _record_loadshape(result)
        em.checkpoint()

    if _remaining() > 240:
        # predictive-serving A/B bracket (ISSUE 17): forecaster on vs
        # off at equal capacity — predictive no worse on p99 AND
        # shed/degrade for ramp + sine, constant the unchanged control
        _record_loadshape_pred(result)
        em.checkpoint()

    if _remaining() > 120:
        # tracing-overhead micro-bracket (ISSUE 9): sampled tracing p99
        # within 5% of disabled; disabled recorder allocates nothing
        _record_traceoverhead(result)
        em.checkpoint()

    if _remaining() > 200:
        # continuous-freshness bracket (ISSUE 10): delta publish→applied
        # vs full re-mine + republish, zero 5xx through the in-place
        # apply, hot cache surviving selectively, fleet multiplier
        _record_freshness(result)
        em.checkpoint()

    if _remaining() > 240:
        # fleet cache-routing bracket (ISSUE 15): 3 real server
        # processes, routed vs independent hit ratio, zero 5xx through
        # a mid-replay replica kill + delta apply
        _record_fleet(result)
        em.checkpoint()

    if _remaining() > 120:
        # cost-attribution bracket (ISSUE 12): serve-kernel MFU +
        # roofline class + live compiles==0 + disabled-mode zero-cost
        _record_costattrib(result)
        em.checkpoint()

    if _remaining() > 240:
        # quality-loop bracket (ISSUE 14): held-out recall@k per mode,
        # measured blend optimum round-trip, compacted-snapshot
        # identity + zero 5xx through the mid-replay swap
        _record_quality(result)
        em.checkpoint()

    if _remaining() > 120:
        # mining-interruption bracket (ISSUE 4): kill-at-phase, resume,
        # bit-identical artifacts + wall-clock savings
        _record_mine_resume(result)
        em.checkpoint()

    if _remaining() > 200:
        # second model family (ISSUE 6): ALS train time, hybrid blend
        # replay p50/p99, cold-start hit fraction
        _record_als_hybrid(result)
        em.checkpoint()

    if _remaining() > 150:
        # confidence-mode serving bracket: multi-antecedent rules through
        # the jitted max-merge kernel (carried-over ROADMAP item)
        _record_confserve(result)
        em.checkpoint()

    if _remaining() > 200:
        # model-parallel serving (ISSUE 7): auto layout shards a catalog
        # that exceeds one (virtual) device's budget, answers stay
        # bit-identical to replicated, zero compiles post-publish
        _record_shardserve(result)
        em.checkpoint()

    if _remaining() > 240:
        # pod-spanning serve mesh (ISSUE 16): a 2-member gang over the
        # socket transport vs single-process sharded on the same
        # over-budget catalog, + the mid-replay gang-member SIGKILL
        _record_meshserve(result)
        em.checkpoint()

    if _remaining() > 240:
        # gray-failure spine (ISSUE 18): a 200 ms alive-but-late stall
        # on one fleet peer and one gang member, hedged leg vs no-hedge
        # control at equal capacity
        _record_slowpeer(result)
        em.checkpoint()

    if _remaining() > 200:
        # storage gray-failure spine (ISSUE 19): a 400 ms PVC read stall
        # under replay (degraded-not-unready, reload parked in backoff)
        # + ENOSPC landing exactly on the recommendations write
        _record_graystore(result)
        em.checkpoint()

    if _remaining() > 240:
        # vocab-sharded mining (ISSUE 7): the sharded count→emit path on
        # an input whose dense formulation busts the per-device budget
        _record_scale_shard(result)
        em.checkpoint()

    if _remaining() > 180:
        # interpret-mode Pallas popcount at a small shape: proves the
        # kernel path exists + counts match, labeled honestly as interpret
        popcount = _run_phase(
            "popcount-interpret", _POPCOUNT_BENCH,
            ["interpret", "2048", "512", "40000"],
            platform="cpu", timeout=min(600, _remaining()),
        )
        if popcount is not None:
            result["popcount_cpu_interpret_ms"] = round(popcount["popcount_ms"], 1)
            result["popcount_cpu_interpret_shape"] = popcount["shape"]
            result["popcount_cpu_interpret_exact"] = popcount["exact"]
            result["popcount_cpu_interpret_kernel"] = popcount["kernel"]
            if "mxu_ms" in popcount:
                # the MXU unpack-matmul impl is pure XLA: on CPU it runs
                # COMPILED (not interpreted) — real kernel evidence even
                # in a chipless round
                result["bitpack_mxu_cpu_compiled_ms"] = round(
                    popcount["mxu_ms"], 1
                )
        em.checkpoint()

    if _remaining() > 240:
        # config-4 mechanics on an 8-virtual-device dp mesh (sharded
        # bitpack path + psum), bounded shape — the SCALE.md row 1 run
        scale = _run_phase(
            "scale-cpu", _SCALE_BENCH,
            ["--playlists", "20000", "--tracks", "5000",
             "--rows", "400000", "--min-support", "0.01", "--mesh", "8x1"],
            platform="cpu", timeout=min(600, _remaining()),
            extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
        )
        if scale is not None:
            result["scale_cpu_mesh8_mine_s"] = scale["mine_s"]
            result["scale_cpu_mesh8_rows_per_s"] = scale["rows_per_s"]
            result["scale_cpu_mesh8_frequent_items"] = scale["frequent_items"]
            result["scale_cpu_mesh8_shape"] = "20000x5000"
            if "auto_mine_s" in scale:
                result["scale_cpu_mesh8_auto_mine_s"] = scale["auto_mine_s"]
                result["scale_cpu_mesh8_auto_path"] = scale["auto_path"]
        em.checkpoint()

    if _remaining() > 180:
        # half-million-playlist mine through the NATIVE fallback (Apriori
        # prune → C++ bitpack scatter → tiled POPCNT counts): real
        # large-scale evidence that doesn't need the chip at all
        # --require-native: without the native library this shape would
        # fall through to a ~25 GB dense one-hot on XLA:CPU — fail fast
        # and keep the budget for the serving/replay phases instead
        scale_n = _run_phase(
            "scale-cpu-native", _SCALE_BENCH,
            ["--playlists", "500000", "--tracks", "50000",
             "--rows", "25000000", "--min-support", "0.002",
             "--require-native"],
            platform="cpu", timeout=min(600, _remaining()),
        )
        if scale_n is not None:
            result["scale_cpu_native_mine_s"] = scale_n["mine_s"]
            result["scale_cpu_native_rows_per_s"] = scale_n["rows_per_s"]
            result["scale_cpu_native_frequent_items"] = scale_n["frequent_items"]
            result["scale_cpu_native_shape"] = "500000x50000"
            if "auto_mine_s" in scale_n:
                result["scale_cpu_native_auto_mine_s"] = scale_n["auto_mine_s"]
                result["scale_cpu_native_auto_path"] = scale_n["auto_path"]
        em.checkpoint()

    if _remaining() > 240:
        # sparsity-adaptive bracket (ISSUE 13): sparse-vs-native on one
        # ≥99%-sparse workload + identity leg + dispatch-table re-bank
        _record_scale_sparse(result)
        em.checkpoint()
    return mining


def _record_serving(
    result: dict, npz_path: str, platform: str,
    bank: str | None = None, budget_s: float | None = None,
) -> None:
    def _run() -> dict | None:
        return _run_phase(
            "serving", _SERVING_BENCH, [npz_path], platform=platform,
            timeout=min(900, _remaining()),
        )

    serving = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if serving is None:
        return
    p50 = serving["p50_ms"]
    log(
        f"serving ({platform}): batch-32 recommend p50 {p50:.3f}ms/call, "
        f"{serving['amortized_ms']:.3f}ms amortized"
    )
    result["serving_batch32_p50_ms"] = round(p50, 3)
    result["serving_batch32_amortized_ms"] = round(serving["amortized_ms"], 3)
    if "p50_256_ms" in serving:
        result["serving_batch256_p50_ms"] = round(serving["p50_256_ms"], 3)
        result["serving_batch256_amortized_ms"] = round(
            serving["amortized_256_ms"], 3
        )


def _record_replay(
    result: dict, platform: str,
    bank: str | None = None, budget_s: float | None = None,
) -> None:
    def _run() -> dict | None:
        try:
            return replay_phase(platform)
        except Exception as exc:
            # the replay stack is optional evidence; the headline mining
            # number in hand must reach stdout no matter what breaks here
            log(f"replay phase crashed ({type(exc).__name__}: {exc}); skipping")
            return None

    replay = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if replay is None:
        return
    log(
        f"replay @ {replay['target_qps']:.0f} QPS: "
        f"p50 {replay['p50_ms']:.2f}ms p95 {replay['p95_ms']:.2f}ms "
        f"p99 {replay['p99_ms']:.2f}ms, achieved "
        f"{replay['achieved_qps']:.0f} QPS "
        f"({replay['n_errors']} errors/drops)"
    )
    result.update(
        replay_target_qps=replay["target_qps"],
        replay_achieved_qps=round(replay["achieved_qps"], 1),
        replay_p50_ms=round(replay["p50_ms"], 3),
        replay_p95_ms=round(replay["p95_ms"], 3),
        replay_p99_ms=round(replay["p99_ms"], 3),
        replay_errors=replay["n_errors"],
    )
    # median-of-N provenance: every run's summary + host conditions, so a
    # single replay number is auditable instead of luck-dependent
    for src, dst in (("runs", "replay_runs"),
                     ("host_load1", "replay_host_load1"),
                     ("warmup_requests", "replay_warmup_requests"),
                     # replay_ prefix: rides the takeover relabeling, so a
                     # CPU-measured job bracket can never masquerade as TPU
                     ("job_end_to_end_s", "replay_job_end_to_end_s"),
                     ("server_percentiles_basis", "replay_server_basis"),
                     ("server_percentiles_note", "replay_server_note"),
                     # trace JOIN evidence (ISSUE 9 remainder): client
                     # records carrying echoed X-KMLS-Trace ids, and the
                     # per-request timelines kmls_tracejoin.py merged
                     ("trace_records", "replay_trace_records"),
                     ("trace_joined", "replay_trace_joined"),
                     ("trace_sample", "replay_trace_sample")):
        if src in replay:
            result[dst] = replay[src]
    server_pcts = replay.get("server_percentiles")
    if server_pcts:
        gap = replay["p50_ms"] - server_pcts.get("p50_ms", 0.0)
        log(
            f"replay server-side (from /metrics): "
            f"p50 {server_pcts.get('p50_ms', float('nan')):.2f}ms "
            f"(client-server p50 gap {gap:.2f}ms = harness queueing + HTTP)"
        )
        attribution = server_pcts.get("attribution") or {}
        for key, val in server_pcts.items():
            if key != "attribution":
                result[f"replay_server_{key}"] = round(val, 3)
        # the queue-vs-device split: WHERE the server-side tail lives
        # (replay_queue_wait_p99_ms vs replay_device_p99_ms), so the next
        # round optimizes the right stage instead of guessing
        for key, val in attribution.items():
            result[f"replay_{key}"] = round(val, 3)
        if "queue_wait_p99_ms" in attribution and "device_p99_ms" in attribution:
            log(
                f"replay attribution: queue-wait p99 "
                f"{attribution['queue_wait_p99_ms']:.2f}ms vs device p99 "
                f"{attribution['device_p99_ms']:.2f}ms"
            )


def _record_chaos(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The kill-a-replica chaos bracket: 1k-QPS in-process replay with
    one of two replicas killed mid-run. CPU-platform by construction
    (same rationale and self-labeling as replay10k); the judged claims
    are chaos_errors == 0 and chaos_http_5xx == 0 with a bounded
    chaos_eject_recovery_ms."""

    def _run() -> dict | None:
        return _run_phase(
            "chaos", _CHAOS_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
            # two virtual CPU devices: the kill-a-replica story needs a
            # second replica to survive on (a bare CPU host has 1 device)
            extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
        )

    chaos = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if chaos is None:
        return
    rec_ms = chaos.get("eject_recovery_ms")
    log(
        f"chaos @ {chaos['qps']:.0f} QPS, replica killed mid-run: "
        f"{chaos['errors']} errors, {chaos['http_5xx']} HTTP 5xx, "
        f"{chaos['degraded_answers']} degraded answers, "
        f"{chaos['redispatched']} re-dispatched, ejection in "
        f"{rec_ms:.0f}ms" if rec_ms is not None else
        f"chaos @ {chaos['qps']:.0f} QPS: replica never ejected (!)"
    )
    for src, dst in (
        ("qps", "chaos_qps"),
        ("achieved_qps", "chaos_achieved_qps"),
        ("p50_ms", "chaos_p50_ms"),
        ("p99_ms", "chaos_p99_ms"),
        ("errors", "chaos_errors"),
        ("http_5xx", "chaos_http_5xx"),
        ("degraded_answers", "chaos_degraded_answers"),
        ("ok_answers", "chaos_ok_answers"),
        ("redispatched", "chaos_redispatched"),
        ("ejections", "chaos_ejections"),
        ("eject_recovery_ms", "chaos_eject_recovery_ms"),
        ("zipf_s", "chaos_zipf_s"),
        ("cache_hit_ratio", "chaos_cache_hit_ratio"),
        ("platform", "chaos_platform"),
    ):
        if src in chaos and chaos[src] is not None:
            val = chaos[src]
            result[dst] = round(val, 3) if isinstance(val, float) else val


def _record_loadshape(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The traffic-shape bracket (ISSUE 8): burst trains, flash crowd,
    and a hot-key flip at a real epoch boundary through the full
    admission-ladder path. The judged claims are loadshape_p99_ms < 10
    with loadshape_errors == loadshape_http_5xx == 0 through the 10x
    bursts, and zero 5xx on the flash/epochflip brackets (degradation
    and jittered 429s allowed there — that IS the ladder working).
    CPU-platform by construction, self-labeled."""

    def _run() -> dict | None:
        return _run_phase(
            "loadshape", _LOADSHAPE_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    b, fl, fp = res["burst"], res["flash"], res["epochflip"]
    log(
        f"loadshape @ {res['qps']:.0f} QPS base, {res['burst_factor']:.0f}x "
        f"bursts: p99 {b['p99_ms']:.2f}ms, {b['errors']} errors, "
        f"{b['http_5xx']} 5xx, {b['shed']} shed, {b['degraded']} degraded; "
        f"flash p99 {fl['p99_ms']:.2f}ms ({fl['http_5xx']} 5xx); epoch-flip "
        f"{fp['http_5xx']} 5xx, epoch_moved={fp.get('epoch_moved')}"
    )
    flat = {
        "loadshape_qps": res["qps"],
        "loadshape_burst_factor": res["burst_factor"],
        "loadshape_offered_qps": b["offered_qps"],
        "loadshape_achieved_qps": b["achieved_qps"],
        "loadshape_p50_ms": b["p50_ms"],
        "loadshape_p99_ms": b["p99_ms"],
        "loadshape_onset_p99_ms": b.get("onset_p99_ms"),
        "loadshape_steady_p99_ms": b.get("steady_p99_ms"),
        "loadshape_errors": b["errors"],
        "loadshape_http_5xx": b["http_5xx"],
        "loadshape_shed": b["shed"],
        "loadshape_degraded": b["degraded"],
        "loadshape_flash_p99_ms": fl["p99_ms"],
        "loadshape_flash_http_5xx": fl["http_5xx"],
        "loadshape_flash_shed": fl["shed"],
        "loadshape_flash_degraded": fl["degraded"],
        "loadshape_flip_p99_ms": fp["p99_ms"],
        "loadshape_flip_errors": fp["errors"],
        "loadshape_flip_http_5xx": fp["http_5xx"],
        "loadshape_flip_epoch_moved": fp.get("epoch_moved"),
        "loadshape_flip_singleflight": fp.get("singleflight_joins"),
        "loadshape_cache_hit_ratio": res.get("cache_hit_ratio"),
        "loadshape_platform": res["platform"],
    }
    for key, val in flat.items():
        if val is not None:
            result[key] = round(val, 3) if isinstance(val, float) else val


def _record_loadshape_pred(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The predictive-serving bracket (ISSUE 17): paired A/B legs at
    equal capacity — forecaster off vs KMLS_FORECAST=1 — over ramp and
    sine (where prediction can lead the cliff) plus constant (the
    control, where it must change nothing). The judged claims: the
    predictive leg no worse than reactive on BOTH pooled p99 and
    shed+degrade count for ramp and sine, zero 5xx on every leg, and the
    predictive legs' forecaster observation counts > 0 (a win with no
    observations would be a measurement artifact). CPU-platform by
    construction, self-labeled."""

    def _run() -> dict | None:
        return _run_phase(
            "loadshape_pred", _LOADSHAPE_PRED_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    shapes = res.get("shapes")
    if not shapes:
        return
    total_5xx = sum(
        leg["http_5xx"] for pair in shapes.values() for leg in pair.values()
    )
    total_errors = sum(
        leg["errors"] for pair in shapes.values() for leg in pair.values()
    )
    for s in ("ramp", "sine"):
        if s not in shapes:
            continue
        react, pred = shapes[s]["reactive"], shapes[s]["predictive"]
        log(
            f"loadshape_pred/{s}: p99 react {react['p99_ms']:.2f}ms → pred "
            f"{pred['p99_ms']:.2f}ms (onset {react.get('onset_p99_ms')} → "
            f"{pred.get('onset_p99_ms')}); shed+degraded "
            f"{react['shed'] + react['degraded']} → "
            f"{pred['shed'] + pred['degraded']}; "
            f"{pred.get('forecast_observations', 0)} observations"
        )
    flat = {"loadshape_pred_http_5xx": total_5xx,
            "loadshape_pred_errors": total_errors,
            "loadshape_pred_qps": res["qps"],
            "loadshape_pred_platform": res["platform"]}
    for s, pair in shapes.items():
        for mode, tag in (("reactive", "react"), ("predictive", "pred")):
            leg = pair[mode]
            prefix = f"loadshape_pred_{s}_{tag}"
            flat[f"{prefix}_p99_ms"] = leg["p99_ms"]
            flat[f"{prefix}_onset_p99_ms"] = leg.get("onset_p99_ms")
            flat[f"{prefix}_steady_p99_ms"] = leg.get("steady_p99_ms")
            flat[f"{prefix}_shed"] = leg["shed"]
            flat[f"{prefix}_degraded"] = leg["degraded"]
            if tag == "react":
                # the zero-cost proof under real traffic: the disabled
                # leg's forecaster observation delta, asserted 0 in-phase
                flat[f"{prefix}_obs_delta"] = leg.get(
                    "forecast_disabled_obs_delta"
                )
        flat[f"loadshape_pred_{s}_obs"] = pair["predictive"].get(
            "forecast_observations"
        )
    for key, val in flat.items():
        if val is not None:
            result[key] = round(val, 3) if isinstance(val, float) else val


def _record_freshness(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The continuous-freshness bracket (ISSUE 10): full re-mine +
    republish vs incremental delta publish→applied-in-serving on the ds2
    shape, with a Zipf replay running through the in-place apply. Judged
    claims: freshness_speedup ≥ 5, freshness_http_5xx == 0 mid-apply,
    and the hot cache surviving the delta (selective invalidation —
    freshness_cache_invalidated_keys stays a sliver of the entry count).
    freshness_fleet_multiplier is the 3-replica affinity decision number.
    CPU-platform by construction, self-labeled."""

    def _run() -> dict | None:
        return _run_phase(
            "freshness", _FRESHNESS_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"freshness: full path {res['full_path_s']:.2f}s vs delta "
        f"{res['delta_path_s']:.2f}s ({res['speedup']:.1f}x), "
        f"publish→applied {res['publish_to_applied_ms']:.0f}ms, "
        f"{res['http_5xx']} 5xx mid-apply, "
        f"{res['cache_invalidated_keys']} keys selectively invalidated, "
        f"fleet multiplier {res['fleet_multiplier']:.2f}x"
    )
    for src, dst in (
        ("full_path_s", "freshness_full_path_s"),
        ("delta_path_s", "freshness_delta_path_s"),
        ("delta_publish_s", "freshness_delta_publish_s"),
        ("publish_to_applied_ms", "freshness_publish_to_applied_ms"),
        ("speedup", "freshness_speedup"),
        ("errors", "freshness_errors"),
        ("http_5xx", "freshness_http_5xx"),
        ("p99_ms", "freshness_p99_ms"),
        ("delta_applied_total", "freshness_delta_applied"),
        ("delta_rejected_total", "freshness_delta_rejected"),
        ("cache_hit_ratio", "freshness_cache_hit_ratio"),
        ("cache_invalidated_keys", "freshness_cache_invalidated_keys"),
        ("fleet_affinity_hit_ratio", "freshness_fleet_affinity_hit"),
        ("fleet_baseline_hit_ratio", "freshness_fleet_baseline_hit"),
        ("fleet_multiplier", "freshness_fleet_multiplier"),
        ("platform", "freshness_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 3) if isinstance(val, float) else val


def _record_fleet(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The fleet cache-routing bracket (ISSUE 15): 3 real server
    processes + routed replay vs the same fleet under round-robin, on a
    Zipf pool wider than one replica's LRU. Judged claims:
    fleet_hit_ratio ≥ fleet_independent_hit_ratio ×
    fleet_multiplier_simulated within 10% (the PR 10 simulation,
    falsified or confirmed with real sockets — one canonical ring on
    both sides), fleet_http_5xx == 0 through BOTH a mid-replay replica
    SIGKILL (router ejects + spills, survivors absorb) and a mid-replay
    delta apply (selective per-seed invalidation held per shard —
    fleet_identity_ok pins survivor answer identity). CPU-platform by
    construction, self-labeled."""

    def _run() -> dict | None:
        return _run_phase(
            "fleet", _FLEET_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"fleet @ {res['achieved_qps']:.0f}/{res['qps']:.0f} QPS x "
        f"{res['replicas']} replicas: routed hit "
        f"{res['routed_hit_ratio']:.3f} vs independent "
        f"{res['independent_hit_ratio']:.3f} = "
        f"{res['multiplier_achieved']:.2f}x (simulated "
        f"{res['multiplier_simulated']:.2f}x), p99 {res['p99_ms']:.2f}ms, "
        f"{res['http_5xx']} 5xx through kill+delta, "
        f"{res['rerouted']} rerouted, identity_ok={res['identity_ok']}"
    )
    for src, dst in (
        ("routed_hit_ratio", "fleet_hit_ratio"),
        ("independent_hit_ratio", "fleet_independent_hit_ratio"),
        ("multiplier_achieved", "fleet_multiplier_achieved"),
        ("multiplier_simulated", "fleet_multiplier_simulated"),
        ("multiplier_vs_simulated", "fleet_multiplier_vs_simulated"),
        ("achieved_qps", "fleet_achieved_qps"),
        ("offered_qps", "fleet_offered_qps"),
        ("p50_ms", "fleet_p50_ms"),
        ("p99_ms", "fleet_p99_ms"),
        ("errors", "fleet_errors"),
        ("http_5xx", "fleet_http_5xx"),
        ("replicas", "fleet_replicas"),
        ("cache_entries", "fleet_cache_entries"),
        ("zipf_pool", "fleet_zipf_pool"),
        ("rerouted", "fleet_rerouted"),
        ("router_ejections", "fleet_router_ejections"),
        ("owner_stamped", "fleet_owner_stamped"),
        ("misrouted_total", "fleet_misrouted_total"),
        ("delta_applied_ok", "fleet_delta_applied_ok"),
        ("selective_invalidations", "fleet_selective_invalidations"),
        ("identity_ok", "fleet_identity_ok"),
        ("platform", "fleet_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 4) if isinstance(val, float) else val


def _record_quality(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The quality-loop bracket (ISSUE 14): held-out ranking quality per
    serving mode next to the latency evidence for the first time. Judged
    claims: quality_recall_blend (the sweep's measured optimum) vs the
    pure-mode recalls, quality_weight_roundtrip (the published optimum
    IS what KMLS_HYBRID_BLEND_WEIGHT=measured serves),
    quality_compact_identical (compacted snapshot == pristine full
    re-mine of the final CSV, tensors exact) and quality_http_5xx == 0
    through the mid-replay compaction swap. CPU-platform by
    construction, self-labeled."""

    def _run() -> dict | None:
        return _run_phase(
            "quality", _QUALITY_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"quality: recall@k rules {res['recall_rules']:.3f} / embed "
        f"{res['recall_embed'] if res['recall_embed'] is not None else 'n/a'}"
        f" / blend@measured {res['recall_blend_best']}, measured w="
        f"{res['measured_weight']} (roundtrip {res['weight_roundtrip']}), "
        f"compaction {res['compact_s']:.2f}s vs re-mine "
        f"{res['remine_s']:.2f}s (identical={res['compact_identical']}), "
        f"{res['http_5xx']} 5xx mid-swap"
    )
    for src, dst in (
        ("recall_rules", "quality_recall_rules"),
        ("recall_embed", "quality_recall_embed"),
        ("recall_blend_best", "quality_recall_blend"),
        ("recall_popularity", "quality_recall_popularity"),
        ("mrr_blend", "quality_mrr_blend"),
        ("coverage_blend", "quality_coverage_blend"),
        ("measured_weight", "quality_blend_weight"),
        ("weight_roundtrip", "quality_weight_roundtrip"),
        ("eval_playlists", "quality_eval_playlists"),
        ("compact_s", "quality_compact_s"),
        ("compact_speedup", "quality_compact_speedup"),
        ("compact_identical", "quality_compact_identical"),
        ("remine_s", "quality_remine_s"),
        ("http_5xx", "quality_http_5xx"),
        ("errors", "quality_errors"),
        ("p99_ms", "quality_p99_ms"),
        ("platform", "quality_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 4) if isinstance(val, float) else val


def _record_traceoverhead(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The tracing-overhead micro-bracket (ISSUE 9): the same 1k-QPS
    Zipf constant replay through two apps one knob apart —
    KMLS_TRACE_SAMPLE=0.01 vs tracing disabled — alternated so host
    noise drifts across both modes. Judged claims: p99_ratio ≤ 1.05
    (sampled tracing inside 5% of disabled) and began_off == 0 (the
    disabled recorder allocated NOTHING — the zero-cost contract the
    compact line carries as traceoverhead_began_off)."""

    def _run() -> dict | None:
        return _run_phase(
            "traceoverhead", _TRACEOVERHEAD_BENCH, [], platform="cpu",
            timeout=min(480, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"traceoverhead @ {res['qps']:.0f} QPS: p99 on {res['p99_on_ms']:.2f}ms "
        f"vs off {res['p99_off_ms']:.2f}ms (ratio {res['p99_ratio']:.3f}); "
        f"began off={res['began_off']} on={res['began_on']}, "
        f"retained {res['retained_on']}"
    )
    for key in (
        "p99_on_ms", "p99_off_ms", "p99_ratio", "p50_on_ms", "p50_off_ms",
        "began_off", "retained_on",
    ):
        result[f"traceoverhead_{key}"] = res[key]


def _record_costattrib(
    result: dict, bank: str | None = None, budget_s: float | None = None,
    platform: str = "cpu",
) -> None:
    """The cost-attribution bracket (ISSUE 12): a Zipf replay through
    the JITTED serve kernel with the cost model on. Judged claims:
    costattrib_mfu ∈ (0, 1] (device-truth serve-kernel MFU against the
    backend peak table — the ROADMAP TPU-window headline runs this with
    platform="tpu" so the phase subprocess actually sees the chip),
    costattrib_roofline (compute vs bandwidth bound),
    costattrib_compiles == 0 (the zero-compiles-post-publish invariant
    measured LIVE), and costattrib_obs_off == 0 (the disabled cost
    model did literally nothing — began-counter style)."""

    def _run() -> dict | None:
        return _run_phase(
            "costattrib", _COSTATTRIB_BENCH, [], platform=platform,
            timeout=min(480, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"costattrib @ {res['qps']:.0f} QPS: serve-kernel MFU "
        f"{res['mfu']:.2e} ({res['roofline']}-bound, "
        f"{res['flops_per_s']:.3g} FLOP/s vs peak {res['peak_flops']:.3g} "
        f"[{res['peak_source']}]), {res['dispatches']} dispatches over "
        f"{res['device_s']:.2f}s device time, compiles={res['compiles']}, "
        f"disabled-mode observations={res['obs_off_delta']}"
    )
    for src, dst in (
        ("mfu", "costattrib_mfu"),
        ("roofline", "costattrib_roofline"),
        ("compiles", "costattrib_compiles"),
        ("obs_off_delta", "costattrib_obs_off"),
        ("flops_per_s", "costattrib_flops_per_s"),
        ("bytes_per_s", "costattrib_bytes_per_s"),
        ("device_s", "costattrib_device_s"),
        ("dispatches", "costattrib_dispatches"),
        ("p99_ms", "costattrib_p99_ms"),
        ("peak_source", "costattrib_peak_source"),
        ("platform", "costattrib_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = (
                float(f"{val:.4g}") if isinstance(val, float) else val
            )


def _record_mine_resume(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The mining-interruption bracket (ISSUE 4's satellite): kill the
    mining job right after a fixed phase's checkpoint, restart, and report
    resume-vs-full-recompute wall clock. The judged claims are
    mine_resume_identical == True (bit-identical artifacts after resume)
    and mine_resume_saved_pct > 0 (the checkpoint actually pays)."""
    def _run():
        return _run_phase(
            "mine-resume", _MINE_RESUME_BENCH, [], platform="cpu",
            timeout=min(600, max(_remaining(), 60)),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"mine-resume (killed after {res['crash_phase']!r}): full "
        f"{res['full_s']:.2f}s vs resume {res['resume_s']:.2f}s "
        f"({res['saved_pct']:.0f}% saved), bit-identical: {res['identical']}"
    )
    for src, dst in (
        ("crash_phase", "mine_resume_phase"),
        ("full_s", "mine_resume_full_s"),
        ("resume_s", "mine_resume_s"),
        ("saved_pct", "mine_resume_saved_pct"),
        ("identical", "mine_resume_identical"),
        ("platform", "mine_resume_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 3) if isinstance(val, float) else val


def _record_replay10k(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The 10k-QPS in-process throughput bracket (cache → batcher →
    engine, Zipf-skewed mix). Always CPU-platform — the native host
    kernel owns the CPU hot path and an HTTP loadgen can't honestly pace
    10k QPS on this sandbox — so the keys carry their own platform label
    and are never relabeled by a TPU takeover."""

    def _run() -> dict | None:
        return _run_phase(
            "replay10k", _REPLAY10K_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    r10k = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if r10k is None:
        return
    log(
        f"replay10k @ {r10k['qps']:.0f} QPS (zipf {r10k['zipf_s']}): "
        f"p50 {r10k['p50_ms']:.2f}ms p99 {r10k['p99_ms']:.2f}ms, achieved "
        f"{r10k['achieved_qps']:.0f} QPS, {r10k['errors']} errors, "
        f"cache hit ratio {r10k.get('cache_hit_ratio') or 0:.2f}"
    )
    for src, dst in (
        ("qps", "replay10k_qps"),
        ("offered_qps", "replay10k_offered_qps"),
        ("achieved_qps", "replay10k_achieved_qps"),
        ("p50_ms", "replay10k_p50_ms"),
        ("p95_ms", "replay10k_p95_ms"),
        ("p99_ms", "replay10k_p99_ms"),
        ("errors", "replay10k_errors"),
        ("cache_hit_ratio", "replay10k_cache_hit_ratio"),
        ("cached_p50_ms", "replay10k_cached_p50_ms"),
        ("uncached_p50_ms", "replay10k_uncached_p50_ms"),
        ("zipf_s", "replay10k_zipf_s"),
        ("per_device_dispatch", "replay10k_per_device_dispatch"),
        ("devices_active", "replay10k_devices_active"),
        ("n_replicas", "replay10k_n_replicas"),
        ("platform", "replay10k_platform"),
    ):
        if src in r10k and r10k[src] is not None:
            val = r10k[src]
            result[dst] = round(val, 3) if isinstance(val, float) else val


def _record_als_hybrid(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The second-model-family bracket (ISSUE 6): ALS training time
    through the real pipeline's embed phase, hybrid blend-mode replay
    p50/p99, and the cold-start hit fraction (zero-rule seeds answered
    from the embedding space, not the popularity fallback). CPU-platform
    by construction, self-labeled — never relabeled by a TPU takeover."""

    def _run() -> dict | None:
        return _run_phase(
            "als-hybrid", _ALS_HYBRID_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    frac = res.get("cold_start_hit_frac")
    log(
        f"als-hybrid: ALS train {res['als_train_s']:.2f}s (rank "
        f"{res['als_rank']}), blend replay p50 {res['p50_ms']:.2f}ms "
        f"p99 {res['p99_ms']:.2f}ms @ {res['achieved_qps']:.0f} QPS, "
        f"cold-start hit "
        f"{frac:.2%}" if frac is not None else
        "als-hybrid: no cold-start seeds in this workload (!)"
    )
    for src, dst in (
        ("als_train_s", "als_train_s"),
        ("als_rank", "als_rank"),
        ("als_iters", "als_iters"),
        ("emb_vocab", "als_emb_vocab"),
        ("achieved_qps", "hybrid_achieved_qps"),
        ("p50_ms", "hybrid_p50_ms"),
        ("p95_ms", "hybrid_p95_ms"),
        ("p99_ms", "hybrid_p99_ms"),
        ("errors", "hybrid_errors"),
        ("cold_start_seeds", "cold_start_seeds"),
        ("cold_start_hit_frac", "cold_start_hit_frac"),
        ("platform", "hybrid_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 4) if isinstance(val, float) else val


def _record_confserve(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """Confidence-mode serving bracket (carried-over ROADMAP item):
    multi-antecedent true-confidence rules replayed through the jitted
    max-merge kernel. CPU-platform by construction, self-labeled."""

    def _run() -> dict | None:
        return _run_phase(
            "confserve", _CONFSERVE_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"confserve (confidence mode, itemsets ≤{res['max_itemset_len']}): "
        f"p50 {res['p50_ms']:.2f}ms p99 {res['p99_ms']:.2f}ms @ "
        f"{res['achieved_qps']:.0f} QPS, {res['errors']} errors, "
        f"{res['rule_keys']} rule keys"
    )
    for src, dst in (
        ("achieved_qps", "confserve_qps"),
        ("p50_ms", "confserve_p50_ms"),
        ("p95_ms", "confserve_p95_ms"),
        ("p99_ms", "confserve_p99_ms"),
        ("errors", "confserve_errors"),
        ("rule_keys", "confserve_rule_keys"),
        ("max_itemset_len", "confserve_max_itemset_len"),
        ("platform", "confserve_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 3) if isinstance(val, float) else val


def _record_shardserve(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The model-parallel serving bracket (ISSUE 7): a catalog whose
    rule tensors exceed the per-device budget serves SHARDED (auto
    layout), bit-identical to replicated, zero compiles post-publish;
    replicated-vs-sharded p50/p99 and the max servable catalog bytes
    land in the artifact. CPU-platform by construction (virtual 8-device
    mesh), self-labeled."""

    def _run() -> dict | None:
        return _run_phase(
            "shardserve", _SHARDSERVE_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
            extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"shardserve: {res['shards']} shards, identical="
        f"{res['identical']}, unwarmed={res['unwarmed_dispatches']}, "
        f"replicated p50 {res['replicated_p50_ms']:.2f}ms vs sharded "
        f"p50 {res['sharded_p50_ms']:.2f}ms (batch bracket), max catalog "
        f"{res['max_catalog_bytes'] / 1e6:.1f} MB across the mesh"
    )
    for src, dst in (
        ("shards", "shardserve_shards"),
        ("identical", "shardserve_identical"),
        ("unwarmed_dispatches", "shardserve_unwarmed"),
        ("catalog_bytes", "shardserve_catalog_bytes"),
        ("device_budget_bytes", "shardserve_device_budget_bytes"),
        ("max_catalog_bytes", "shardserve_max_catalog_bytes"),
        ("replicated_p50_ms", "shardserve_replicated_p50_ms"),
        ("replicated_p99_ms", "shardserve_replicated_p99_ms"),
        ("sharded_p50_ms", "shardserve_sharded_p50_ms"),
        ("sharded_p99_ms", "shardserve_sharded_p99_ms"),
        ("platform", "shardserve_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 3) if isinstance(val, float) else val


def _record_meshserve(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The pod-spanning serve-mesh bracket (ISSUE 16): a 2-member gang
    (each holding only its vocab slab, merging over the socket mesh
    transport) serves the SAME over-budget catalog as the single-process
    sharded kernel — answers pinned bit-identical to replicated AND
    sharded on BOTH members, zero compiles post-publish, max servable
    catalog = per-host budget x gang size. The chaos leg SIGKILLs a
    gang member mid-replay behind the routed client: zero 5xx, zero
    drops, whole-gang ejection with the dark shard blamed. CPU-platform
    by construction (socket transport), self-labeled."""

    def _run() -> dict | None:
        return _run_phase(
            "meshserve", _MESHSERVE_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
            extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"},
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"meshserve: gang of {res['gang_size']}, identical="
        f"{res['identical']}, unwarmed={res['unwarmed_dispatches']}, "
        f"sharded p50 {res['sharded_p50_ms']:.2f}ms vs mesh p50 "
        f"{res['mesh_p50_ms']:.2f}ms, max catalog "
        f"{res['max_catalog_bytes'] / 1e6:.1f} MB across the gang; chaos "
        f"leg {res['http_5xx']} 5xx / {res['errors']} drops through a "
        f"gang-member SIGKILL ({res['mesh_unavailable']} mesh refusals, "
        f"{res['ejections']} ejections)"
    )
    for src, dst in (
        ("gang_size", "meshserve_gang"),
        ("identical", "meshserve_identical"),
        ("unwarmed_dispatches", "meshserve_unwarmed"),
        ("catalog_bytes", "meshserve_catalog_bytes"),
        ("host_budget_bytes", "meshserve_host_budget_bytes"),
        ("max_catalog_bytes", "meshserve_max_catalog_bytes"),
        ("sharded_p50_ms", "meshserve_sharded_p50_ms"),
        ("sharded_p99_ms", "meshserve_sharded_p99_ms"),
        ("mesh_p50_ms", "meshserve_p50_ms"),
        ("mesh_p99_ms", "meshserve_p99_ms"),
        ("achieved_qps", "meshserve_achieved_qps"),
        ("replay_p99_ms", "meshserve_replay_p99_ms"),
        ("http_5xx", "meshserve_http_5xx"),
        ("errors", "meshserve_errors"),
        ("mesh_unavailable", "meshserve_mesh_unavailable"),
        ("ejections", "meshserve_ejections"),
        ("platform", "meshserve_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 3) if isinstance(val, float) else val


def _record_slowpeer(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The gray-failure chaos bracket (ISSUE 18): a 200 ms deterministic
    stall on one fleet peer and one gang member — alive, answering,
    LATE, so no error breaker ever fires — with the hedged leg racing
    the no-hedge control at equal capacity. Judged claims: hedged p99
    ≥ 5x better than the control, hedge overhead (extra dispatches /
    total) ≤ 5%, zero 5xx and zero drops on every leg, answers
    bit-identical whichever copy wins (hedge_mismatch == 0 plus the
    post-replay cross-replica probe identity), and the in-bench
    zero-cost pin — the control leg leaves replay.HEDGES_ISSUED at
    exactly 0 under real traffic. CPU-platform by construction (real
    local server processes), self-labeled."""

    def _run() -> dict | None:
        return _run_phase(
            "slowpeer", _SLOWPEER_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"slowpeer: control p99 {res['control_p99_ms']:.0f}ms vs hedged "
        f"p99 {res['hedged_p99_ms']:.0f}ms ({res['p99_ratio']:.1f}x) "
        f"through a {res['stall_ms']}ms gray stall — "
        f"{res['hedges_issued']} hedges ({res['hedge_overhead_pct']:.1f}% "
        f"overhead, {res['hedge_wins']} won), {res['slow_ejections']} slow "
        f"ejections, {res['http_5xx']} 5xx / {res['errors']} drops across "
        f"all legs, identity_ok={res['identity_ok']}, control hedges "
        f"{res['control_hedges_issued']}; mesh leg {res['mesh_hedge_wins']} "
        f"coordinator hedge wins, {res['mesh_straggler_degraded']} "
        f"straggler-degraded merges"
    )
    for src, dst in (
        ("qps", "slowpeer_qps"),
        ("requests", "slowpeer_requests"),
        ("stall_ms", "slowpeer_stall_ms"),
        ("control_p50_ms", "slowpeer_control_p50_ms"),
        ("control_p99_ms", "slowpeer_control_p99_ms"),
        ("hedged_p50_ms", "slowpeer_hedged_p50_ms"),
        ("hedged_p99_ms", "slowpeer_hedged_p99_ms"),
        ("p99_ratio", "slowpeer_p99_ratio"),
        ("hedge_overhead_pct", "slowpeer_hedge_overhead_pct"),
        ("hedges_issued", "slowpeer_hedges_issued"),
        ("hedge_wins", "slowpeer_hedge_wins"),
        ("hedge_losses", "slowpeer_hedge_losses"),
        ("hedges_suppressed", "slowpeer_hedges_suppressed"),
        ("hedge_mismatch", "slowpeer_hedge_mismatch"),
        ("slow_ejections", "slowpeer_slow_ejections"),
        ("deadline_expired", "slowpeer_deadline_expired"),
        ("server_deadline_expired", "slowpeer_server_deadline_expired"),
        ("control_hedges_issued", "slowpeer_control_hedges_issued"),
        ("http_5xx", "slowpeer_http_5xx"),
        ("errors", "slowpeer_errors"),
        ("identity_ok", "slowpeer_identity_ok"),
        ("mesh_hedge_wins", "slowpeer_mesh_hedge_wins"),
        ("mesh_hedge_cancelled", "slowpeer_mesh_hedge_cancelled"),
        ("mesh_straggler_degraded", "slowpeer_mesh_straggler_degraded"),
        ("mesh_expired_on_arrival", "slowpeer_mesh_expired_on_arrival"),
        ("mesh_p99_ms", "slowpeer_mesh_p99_ms"),
        ("mesh_http_5xx", "slowpeer_mesh_http_5xx"),
        ("mesh_errors", "slowpeer_mesh_errors"),
        ("platform", "slowpeer_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 3) if isinstance(val, float) else val


def _record_graystore(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The storage gray-failure bracket (ISSUE 19): the shared artifact
    volume goes gray — every PVC read stalls 400 ms under a 1k-QPS
    replay, then ENOSPC lands exactly on the recommendations write of a
    full publication. Judged claims: zero 5xx on every leg, serving p99
    unmoved by the stall (the hot path never touches the volume), slow-IO
    conviction flips /readyz to ready-but-degraded reason storage-slow,
    the armed reload parks in bounded backoff holding last-good (and
    recovers once the stall clears), and the ENOSPC publication aborts
    resumable (exit 75) with the last-good bytes bit-identical, the
    token unmoved, and zero torn temp files on the volume. CPU-platform
    by construction (tmpfs-backed artifact dir + injected faults),
    self-labeled."""

    def _run() -> dict | None:
        return _run_phase(
            "graystore", _GRAYSTORE_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"graystore: serving p99 {res['control_p99_ms']:.1f}ms clean vs "
        f"{res['stalled_p99_ms']:.1f}ms under a {res['stall_ms']:.0f}ms "
        f"PVC read stall ({res['p99_ratio']:.2f}x), "
        f"storage_slow={res['storage_slow']}, "
        f"readyz_degraded={res['readyz_degraded']}, reload deferred="
        f"{res['reload_deferred']} (backoff bounded={res['backoff_bounded']}, "
        f"last-good held={res['last_good_held']}); ENOSPC mid-publish: "
        f"exit {res['enospc_exit']} (resumable={res['enospc_exit_resumable']}), "
        f"identical={res['enospc_identical']}, "
        f"token_moved={res['enospc_token_moved']}, "
        f"{res['torn_parts']} torn temps, recovered={res['recovered']}; "
        f"{res['http_5xx']} 5xx / {res['errors']} drops across all legs"
    )
    for src in (
        "qps", "requests", "stall_ms", "control_p50_ms", "control_p99_ms",
        "stalled_p50_ms", "stalled_p99_ms", "p99_ratio", "storage_slow",
        "readyz_degraded", "reload_deferred", "backoff_bounded",
        "last_good_held", "enospc_exit", "enospc_exit_resumable",
        "enospc_identical", "enospc_token_moved", "torn_parts",
        "probe_p99_ms", "recovered", "io_retries", "http_5xx", "errors",
        "platform",
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result["graystore_" + src] = (
                round(val, 3) if isinstance(val, float) else val
            )


def _record_scale_shard(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The vocab-sharded mining bracket (ISSUE 7): a basket matrix whose
    dense single-device formulation busts the HBM budget mines through
    the sharded count→emit pipeline on the 1x8 vocab mesh."""

    def _run() -> dict | None:
        return _run_phase(
            "scale-shard", _SCALE_SHARD_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
            extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    log(
        f"scale-shard: {res['shape']} mined in {res['mine_s']:.1f}s "
        f"({res['rows_per_s']:.0f} rows/s) via {res['count_path']} — "
        f"dense needs {res['dense_single_device_bytes'] / 1e6:.0f} MB on "
        f"one device (budget {res['hbm_budget_bytes'] / 1e6:.0f} MB); "
        f"per-shard counts {res['per_shard_counts_bytes'] / 1e6:.1f} MB"
    )
    for src, dst in (
        ("mine_s", "scale_shard_mine_s"),
        ("rows_per_s", "scale_shard_rows_per_s"),
        ("shape", "scale_shard_shape"),
        ("count_path", "scale_shard_count_path"),
        ("shards", "scale_shard_shards"),
        ("dense_single_device_bytes", "scale_shard_dense_bytes"),
        ("hbm_budget_bytes", "scale_shard_budget_bytes"),
        ("rules_emitted", "scale_shard_rules"),
        ("frequent_items", "scale_shard_frequent_items"),
        ("platform", "scale_shard_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 3) if isinstance(val, float) else val


def _record_scale_sparse(
    result: dict, bank: str | None = None, budget_s: float | None = None,
) -> None:
    """The sparsity-adaptive bracket (ISSUE 13): at ≥99% sparsity the
    sparse CSR×bitpacked hybrid must beat the standing
    ``scale_cpu_native`` record path ≥5x ON THE SAME workload with
    bit-identical tensors, the dense/bitpack/sparse identity leg must
    agree, and the density sweep re-banks the measured dispatch table
    the auto path consults."""

    def _run() -> dict | None:
        return _run_phase(
            "scale-sparse", _SCALE_SPARSE_BENCH, [], platform="cpu",
            timeout=min(600, _remaining()),
        )

    res = _banked(bank, _run, budget_s, extras=result) if bank else _run()
    if res is None:
        return
    if "speedup_vs_native" in res:
        log(
            f"scale-sparse: {res['shape']} at density {res['density']:.6f} "
            f"mined in {res['sparse_mine_s']:.2f}s "
            f"({res['sparse_rows_per_s']:.0f} rows/s, "
            f"{res['count_path']}) vs {res['native_mine_s']:.2f}s "
            f"{res['native_count_path']} — {res['speedup_vs_native']:.1f}x, "
            f"identical={res.get('headline_identical')}; auto dispatch "
            f"-> {res['auto_path']} ({res['auto_source']})"
        )
    for src, dst in (
        ("sparse_mine_s", "sparse_mine_s"),
        ("sparse_rows_per_s", "sparse_rows_per_s"),
        ("native_mine_s", "sparse_native_mine_s"),
        ("native_rows_per_s", "sparse_native_rows_per_s"),
        ("speedup_vs_native", "sparse_speedup_vs_native"),
        ("identical", "sparse_identical"),
        ("headline_identical", "sparse_headline_identical"),
        ("density", "sparse_density"),
        ("shape", "sparse_shape"),
        ("count_path", "sparse_count_path"),
        ("auto_path", "sparse_auto_path"),
        ("auto_source", "sparse_auto_source"),
        ("table_cells", "sparse_table_cells"),
        ("sweep_identical", "sparse_sweep_identical"),
        ("frequent_items", "sparse_frequent_items"),
        ("platform", "sparse_platform"),
    ):
        if src in res and res[src] is not None:
            val = res[src]
            result[dst] = round(val, 3) if isinstance(val, float) else val


def _tpu_takeover(
    em: ArtifactEmitter, result: dict, cpu_mining: dict | None,
    npz_path: str,
) -> dict | None:
    """Promote the artifact from a CPU headline to a TPU one (pool came
    up mid-run, or a banked prior window is being replayed): relabel the
    CPU suite's unprefixed serving/replay keys so every unprefixed key
    is TPU-measured, register the CPU comparison BEFORE the suite (a
    driver kill mid-suite must not lose the measured CPU evidence), run
    the TPU suite, and restore the CPU keys if it produced no headline.
    → the TPU mining result, or None (artifact stays platform=cpu)."""
    for key in list(result):
        if key.startswith(("serving_", "replay_")):
            result["cpu_" + key] = result.pop(key)
    # compose() keeps the comparison suppressed while the CPU result
    # still IS the headline (`is not mining` guard) and surfaces it the
    # instant the TPU headline takes over
    em.set_cpu_comparison(cpu_mining)
    tpu_mining = run_tpu_suite(em, npz_path)
    if tpu_mining is None:
        # run_tpu_suite wrote nothing — it bails before its optional
        # phases when mining fails
        for key in list(result):
            if key.startswith(("cpu_serving_", "cpu_replay_")):
                result[key[len("cpu_"):]] = result.pop(key)
        em.checkpoint()
    return tpu_mining


def main() -> int:
    prober = TpuProber()
    em = ArtifactEmitter(prober)
    _install_crash_handlers(em)
    result = em.extras
    if os.environ.get("KMLS_BENCH_CPU") == "1":  # debugging escape hatch
        log("KMLS_BENCH_CPU=1: skipping TPU, benching on CPU")
        prober.history.append({"t_s": 0.0, "outcome": "forced_cpu", "dur_s": 0.0})
        first = "forced_cpu"
    else:
        log("probing TPU backend (bounded)...")
        first = prober.probe_once()

    mining = None
    with tempfile.NamedTemporaryFile(suffix=".npz") as f:
        if first == "tpu":
            mining = run_tpu_suite(em, f.name)
            if mining is None:
                log(
                    "mining failed on TPU after retries — falling back to "
                    "CPU so the headline number is still captured"
                )
                mining = run_cpu_suite(em, f.name)
            else:
                # cheap CPU comparison point (native POPCNT path) so every
                # TPU artifact also carries the no-accelerator number —
                # optional, so its timeout respects the deadline (the
                # already-measured TPU headline must not be lost to a
                # harness kill past DEADLINE_S)
                cpu_cmp = _banked("mining_cpu_cmp", lambda: run_mining(
                    "cpu", f.name, attempts=1,
                    timeout=min(600, max(_remaining() - 30, 60)),
                ), budget_s=180, extras=result)
                if cpu_cmp is not None:
                    em.set_cpu_comparison(cpu_cmp)
        else:
            # CPU evidence first, re-probing the pool in the background the
            # whole time; if the pool comes back, the TPU suite runs too.
            # (A clean "cpu_only" first probe is terminal — the host simply
            # has no TPU platform — only hangs/errors are worth re-probing.)
            if first not in ("forced_cpu", "cpu_only"):
                prober.start_background()
            mining = run_cpu_suite(em, f.name)

            # keep waiting for the pool for as long as a minimal TPU mining
            # run still fits AND the prober is still probing (once it stops,
            # no new probe can flip the outcome)
            while (
                not prober.acquired.is_set()
                and prober.alive()
                and _remaining() > 330
            ):
                if prober.acquired.wait(timeout=15.0):
                    break
            prober.stop()
            if prober.acquired.is_set():
                log(
                    f"TPU pool came up at t={_elapsed():.0f}s — running the "
                    "TPU suite now"
                )
                mining = _tpu_takeover(em, result, mining, f.name) or mining
            elif first != "forced_cpu":
                if STATE.get("mining_tpu") is not None:
                    # the pool is down NOW, but an earlier reachability
                    # window this round banked real on-chip measurements
                    # (scripts/tpu_watch.sh shares the bank) — fold them
                    # into this artifact instead of shipping CPU-only,
                    # clearly labeled with their provenance and age
                    log(
                        "pool never came up, but a prior window banked "
                        "TPU phases — replaying the bank into this artifact"
                    )
                    STATE.replay_only = True
                    tpu_mining = _tpu_takeover(em, result, mining, f.name)
                    if tpu_mining is not None:
                        mining = tpu_mining
                        result["tpu_suite_from_bank"] = True
                        age = STATE.age_s("mining_tpu")
                        if age is not None:
                            result["tpu_bank_age_s"] = round(age)
                        em.checkpoint()
                log(
                    f"TPU never became reachable within the "
                    f"{_deadline_s():.0f}s window "
                    f"({len(prober.history_snapshot())} probes) — JSON "
                    "carries the full probe history"
                )

    if mining is None:
        log("FATAL: mining bench failed on every path; no number to report")
        return 1

    return 0 if em.finalize() else 1


if __name__ == "__main__":
    sys.exit(main())
