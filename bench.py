#!/usr/bin/env python
"""Headline benchmark: FP-Growth rule generation on a ds2-shaped workload.

The reference's published number (BASELINE.md): 20.31 s of rule generation —
mlxtend TransactionEncoder + FP-Growth + Python dict-expansion loops — on
ds2 (240,249 membership rows, 2,246 playlists, 2,171 tracks, min_support
0.05) on a CPU cluster node (relatorio.pdf p.6; timer bracket at
machine-learning/main.py:264,306-308).

This benchmark reproduces the same workload shape synthetically (the real
ds2 CSV is not distributed with the reference repo) and times the SAME
bracket for the TPU path: device one-hot encode + MXU pair-support matmul +
rule-tensor emission + host rule-dict expansion. Median of repeated runs,
compile excluded via warm-up (the reference's 20.31 s excludes Python/lib
import too).

Structure: this parent process never imports jax. The mining phase and the
serving phase each run in their OWN subprocess, sequentially — matching
deployment (batch job pod vs API server pod are separate processes) and
keeping the two phases from contending for the single TPU chip (libtpu is
one-process-per-chip on real hardware).

Prints ONE JSON line:
    {"metric": ..., "value": <median seconds>, "unit": "s",
     "vs_baseline": <baseline_s / value = speedup factor>}

Extra context (per-phase timings, serving p50) goes to stderr.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

BASELINE_RULE_GEN_S = 20.31  # relatorio.pdf p.6 (BASELINE.md row 1)
MIN_SUPPORT = 0.05
REPEATS = 5

if os.environ.get("KMLS_BENCH_CPU") == "1":  # debugging escape hatch
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


_MINING_BENCH = r"""
import json, statistics, sys, time
import numpy as np
from kmlserver_tpu.config import MiningConfig
from kmlserver_tpu.data.synthetic import DS2_SHAPE, synthetic_baskets
from kmlserver_tpu.mining.miner import mine

out_npz, min_support, repeats = sys.argv[1], float(sys.argv[2]), int(sys.argv[3])

import jax
dev = jax.devices()[0]
print(f"device: {dev.platform} ({dev.device_kind})", file=sys.stderr, flush=True)

baskets = synthetic_baskets(**DS2_SHAPE, seed=123)
print(
    f"workload: {len(baskets.playlist_rows)} memberships, "
    f"{baskets.n_playlists} playlists, {baskets.n_tracks} tracks, "
    f"min_support {min_support} (ds2 shape)", file=sys.stderr, flush=True,
)
cfg = MiningConfig(min_support=min_support, k_max_consequents=256)

# warm-up: compile every kernel in the bracket
result = mine(baskets, cfg)
result.tensors.to_rules_dict(result.vocab_names)
print(f"warm-up mine: {result.duration_s:.3f}s (includes compile)",
      file=sys.stderr, flush=True)

times = []
for i in range(repeats):
    t0 = time.perf_counter()
    result = mine(baskets, cfg)
    rules_dict = result.tensors.to_rules_dict(result.vocab_names)
    times.append(time.perf_counter() - t0)
    print(f"run {i}: {times[-1]:.3f}s ({len(rules_dict)} rule keys)",
          file=sys.stderr, flush=True)

np.savez(out_npz, rule_ids=result.tensors.rule_ids,
         rule_confs=result.tensors.rule_confs)
print(json.dumps({"median_s": statistics.median(times)}))
"""

_SERVING_BENCH = r"""
import json, sys, time
import numpy as np
import jax, jax.numpy as jnp
from kmlserver_tpu.ops.serve import recommend_batch

with np.load(sys.argv[1]) as z:
    rule_ids = jax.device_put(jnp.asarray(z["rule_ids"]))
    rule_confs = jax.device_put(jnp.asarray(z["rule_confs"]))
v = rule_ids.shape[0]
rng = np.random.default_rng(0)
seeds = jnp.asarray(rng.integers(0, v, size=(32, 8), dtype=np.int32))
recommend_batch(rule_ids, rule_confs, seeds, k_best=10)[0].block_until_ready()
lat = []
for _ in range(50):
    t0 = time.perf_counter()
    recommend_batch(rule_ids, rule_confs, seeds, k_best=10)[0].block_until_ready()
    lat.append(time.perf_counter() - t0)
lat.sort()
print(json.dumps({"p50_ms": lat[len(lat) // 2] * 1e3}))
"""


def _run_phase(name: str, code: str, argv: list[str]) -> dict | None:
    """Run one bench phase in its own process; → parsed result JSON
    (last stdout line) or None on any failure (logged, fail-soft)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code, *argv],
            capture_output=True, text=True, timeout=1800,
            env=os.environ.copy(), cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired as exc:
        for line in (exc.stderr or "").splitlines():
            log(line)
        log(f"{name} phase timed out after {exc.timeout}s")
        return None
    for line in proc.stderr.splitlines():
        log(line)
    if proc.returncode != 0:
        log(f"{name} phase failed (exit {proc.returncode})")
        return None
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (IndexError, ValueError) as exc:
        log(f"{name} phase produced unparseable output: {exc}")
        return None


def main() -> int:
    with tempfile.NamedTemporaryFile(suffix=".npz") as f:
        mining = _run_phase(
            "mining", _MINING_BENCH, [f.name, str(MIN_SUPPORT), str(REPEATS)]
        )
        if mining is None:
            return 1
        # serving context number (stderr only): batch-32 recommend p50 in a
        # fresh process, like the real API server
        serving = _run_phase("serving", _SERVING_BENCH, [f.name])
    if serving is not None:
        p50 = serving["p50_ms"]
        log(
            f"serving: batch-32 recommend p50 {p50:.3f}ms "
            f"({p50 / 32 * 1e3:.1f}us/request)"
        )
    median_s = mining["median_s"]
    print(
        json.dumps(
            {
                "metric": "fpgrowth_ds2_rule_generation_time",
                "value": round(median_s, 4),
                "unit": "s",
                "vs_baseline": round(BASELINE_RULE_GEN_S / median_s, 1),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
