#!/usr/bin/env bash
# Build + push both workload images (the reference keeps one-line
# buildAndPushToDockerhub.sh scripts per workload; same role here).
set -euo pipefail
REGISTRY="${REGISTRY:-ghcr.io/example}"
TAG="${TAG:-latest}"
cd "$(dirname "$0")/.."
docker build -f docker/Dockerfile.mining -t "$REGISTRY/kmlserver-tpu-mining:$TAG" .
docker build -f docker/Dockerfile.api -t "$REGISTRY/kmlserver-tpu-api:$TAG" .
docker push "$REGISTRY/kmlserver-tpu-mining:$TAG"
docker push "$REGISTRY/kmlserver-tpu-api:$TAG"
