"""kmlserver_tpu — a TPU-native rebuild of `diogoneiss/kubernetes-machine-learning-server`.

The reference system (see SURVEY.md at the repo root) is a Kubernetes-deployed
playlist-recommendation stack: a batch FP-Growth association-rule-mining job
(reference: machine-learning/main.py) and an online recommendation REST service
(reference: rest_api/app/main.py) that exchange pickled artifacts through a
shared ReadWriteMany PVC, with freshness signaled by a polled token file.

This package re-implements every component TPU-first:

- ``ops/``      — the compute kernels (JAX/XLA, Pallas): one-hot / bit-packed
                  basket encoding, MXU pair-support counting (``XᵀX``),
                  itemset extension, rule-tensor emission, and the serve-time
                  gather → scatter-max → top-k recommendation kernel.
- ``parallel/`` — device-mesh sharding of the mining compute: data-parallel
                  ``psum`` over the transaction axis, tensor-parallel sharding
                  of the item axis with all-gather and ring (``ppermute``)
                  pair-count variants riding ICI.
- ``mining/``   — the batch job (reference: machine-learning/main.py:421-484):
                  dataset rotation, vocab building, device mining, artifact
                  emission, run-history bookkeeping.
- ``serving/``  — the online API (reference: rest_api/app/main.py): identical
                  HTTP surface served from HBM-resident rule tensors with a
                  double-buffered hot swap driven by the same polling protocol.
- ``models/``   — the model abstraction: rule tensors + vocabulary + jitted
                  apply as one deployable object, in two families
                  (support-mode / confidence-mode semantics).
- ``io/``       — artifact + state files: the pickle wire format the reference
                  serves from, dataset registry, run history, invalidation
                  token (reference: machine-learning/main.py:315-411).
- ``data/``     — CSV ingestion and synthetic basket generation.
- ``utils/``    — env contract, dotenv, timestamps, logging.

Nothing here is a line translation of the reference: the FP-tree
(pointer-chasing, recursion — hostile to XLA) is replaced by an exact dense /
bit-packed formulation; see ``ops/support.py`` for the dominance argument that
makes pair counting sufficient for the reference's output semantics.
"""

__version__ = "0.1.0"
