"""kmls-verify — project-invariant static analysis.

PRs 1–4 made the serving+mining stack fast and fault-tolerant; this
package makes the invariants that correctness now rests on MACHINE-
CHECKED instead of reviewer-remembered. Eleven checkers, each a pure-AST
pass (stdlib only — the analyzer must run in a bare CI job without jax):

- ``hotpath``      — no host-sync constructs reachable from the serving
                     dispatch entry points (PR 1's zero-compile/zero-sync
                     contract);
- ``locks``        — no lock-acquisition-order cycles, no blocking calls
                     while a hot-path lock is held (PR 2/3's batcher/
                     cache/metrics locking discipline);
- ``atomic-write`` — every artifact write flows through io/artifacts.py's
                     tmp+``os.replace`` writer (PR 3's torn-read fix);
- ``knobs``        — every ``KMLS_*`` env knob referenced in code is
                     declared in config.KNOB_REGISTRY, documented in the
                     README, and (runtime scopes) bound or documented in
                     the k8s manifests — no orphans in either direction;
- ``fault-sites``  — every ``KMLS_FAULT_*`` knob maps to a registered
                     faults.py site that is wired into the code and
                     exercised by at least one chaos test;
- ``exit-codes``   — the 0/64/75/76 contract in mining/job.py exactly
                     matches the ``podFailurePolicy`` rules in both Job
                     manifests (PR 4's preemption contract);
- ``metrics``      — every exported Prometheus series (serving
                     ``/metrics`` AND the mining ``job_metrics.prom``
                     textfile) is declared in
                     ``serving.metrics.METRIC_REGISTRY`` with a valid
                     type+scope and a README row, orphans flagged both
                     directions (ISSUE 9);
- ``costspec``     — every dispatched jitted kernel named at an
                     ``observe_kernel``/``phase_cost`` call site has an
                     analytic cost spec in
                     ``observability.costmodel.KERNEL_COST_SPECS`` and
                     vice versa, the required kernel set stays
                     registered, and every cost-model series is in
                     ``METRIC_REGISTRY`` (ISSUE 12);
- ``loopblock``    — no blocking constructs (sleeps, file/socket I/O,
                     un-awaited ``.result()``/``.wait()``, ``faults.
                     fire``, durable writers) in EVENT-LOOP context, on
                     the async-aware call graph's execution-context
                     classification (the PR 18 ``_dispatch`` stall bug
                     class; ISSUE 20);
- ``lockown``      — for classes that own a lock, each mutable field's
                     owning lock is inferred by majority vote over
                     guarded accesses and unguarded WRITES are flagged
                     (conservative data-race inference; ISSUE 20);
- ``envread``      — no ``KMLS_*``/``os.environ`` reads at module
                     import time or inside jit-traced functions, cross-
                     checked against ``config.KNOB_REGISTRY`` scopes
                     (the PR 12 frozen-knob bug class; ISSUE 20).

Findings carry ``file:line``, a severity, an explanation, and a stable
fingerprint; pre-existing accepted findings live in
``analysis/baseline.json`` so the CI gate is zero-NEW-findings. One-off
intentional sites can instead carry an inline pragma on (or immediately
above) the flagged line::

    x = np.asarray(probe)  # kmls-verify: allow[hotpath] one-time probe

Run locally: ``python scripts/kmls_verify.py`` (see README "Static
invariants").
"""

from __future__ import annotations

from .core import (
    AnalysisConfig,
    Finding,
    ProjectIndex,
    load_baseline,
    run_analysis,
    write_baseline,
)

__all__ = [
    "AnalysisConfig",
    "Finding",
    "ProjectIndex",
    "load_baseline",
    "run_analysis",
    "write_baseline",
]
