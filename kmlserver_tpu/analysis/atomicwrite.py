"""Checker 3 — atomic-write enforcement.

PR 3's decision: every artifact that lands on the shared PVC goes
through ``io/artifacts.py``'s tmp+``os.replace`` writer, because the
READ protocol (pickle shapes, filenames, token polling) is the interop
contract and a torn read is not. The ONE sanctioned exception is the
``KMLS_REFERENCE_RACE_COMPAT`` site — which lives inside artifacts.py
itself, so the rule collapses to: nothing outside the approved writer
modules/functions opens a file for writing or serializes straight to a
path.

Flags, outside the allowlist:

- ``open(path, mode)`` with a write-capable mode (``w``/``a``/``x`` or
  ``+``), and ``os.fdopen`` likewise;
- ``pickle.dump``, ``json.dump``, ``np.save``/``np.savez*``,
  ``np.savetxt`` — direct serialization to a handle/path;
- ``os.replace``/``os.rename`` anywhere in the package outside
  ``cfg.durable_rename_function`` (``io/artifacts.py::durable_replace``)
  and ``cfg.rename_allowed_modules``. ISSUE 19 tightened this from "a
  rename belongs in the writer module" to "a rename belongs in THE
  durable rename": publication-critical renames must fsync the source
  file and the parent directory, or a power cut after the rename can
  silently vanish the publication — so even inside the approved writer
  module, a bare ``os.replace`` that is not ``durable_replace`` itself
  is flagged.

Scope is the package only (``kmlserver_tpu/``): bench/scripts write
their own local state files and are not part of the PVC contract.
"""

from __future__ import annotations

import ast

from .callgraph import resolve_call
from .core import (
    SEVERITY_ERROR,
    AnalysisConfig,
    Finding,
    FunctionInfo,
    ProjectIndex,
)

_SERIALIZERS = (
    "pickle.dump",
    "json.dump",
    "np.save",
    "np.savez",
    "np.savez_compressed",
    "np.savetxt",
    "numpy.save",
    "numpy.savez",
    "numpy.savez_compressed",
    "numpy.savetxt",
)
_RENAMES = ("os.replace", "os.rename")


def _write_mode(call: ast.Call) -> str | None:
    """The write-capable mode literal of an ``open``/``os.fdopen`` call,
    or None for reads / non-literal modes (non-literal = unknowable;
    stay quiet rather than guess)."""
    mode_node: ast.AST | None = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if mode_node is None:
        return None
    if isinstance(mode_node, ast.Constant) and isinstance(
        mode_node.value, str
    ):
        mode = mode_node.value
        if any(c in mode for c in "wax+"):
            return mode
    return None


def _module_allowed(relpath: str, allowed: set[str]) -> bool:
    if relpath in allowed:
        return True
    return any(
        m.endswith("/") and relpath.startswith(m) for m in allowed
    )


def run(index: ProjectIndex, cfg: AnalysisConfig) -> list[Finding]:
    allowed_modules = set(cfg.atomic_allowed_modules)
    allowed_functions = set(cfg.atomic_allowed_functions)
    rename_allowed = set(cfg.rename_allowed_modules)
    findings: list[Finding] = []
    for relpath in sorted(index.modules):
        if not relpath.startswith(cfg.package_dir):
            continue
        # renames are checked EVERYWHERE in the package, including the
        # atomic-allowed writer modules (the durable-rename rule is
        # stricter than the direct-write rule); plain writes keep the
        # module allowlist.
        writes_allowed = _module_allowed(relpath, allowed_modules)
        renames_allowed = _module_allowed(relpath, rename_allowed)
        if writes_allowed and renames_allowed:
            continue
        mod = index.modules[relpath]
        # top-level function spans, so a write can be attributed to (and
        # allowlisted by) its enclosing function — including writes in
        # NESTED closures, which unlike the hotpath checker's
        # completion-closure exemption have no business being exempt
        # here: a torn PVC write from a closure tears exactly the same
        spans: list[tuple[int, int, FunctionInfo]] = []
        for (rel, _qual), info in index.functions.items():
            if rel != relpath:
                continue
            end = getattr(info.node, "end_lineno", None)
            start = getattr(info.node, "lineno", None)
            if start is not None and end is not None:
                spans.append((start, end, info))
        module_caller = FunctionInfo(relpath, "<module>", mod.tree, None)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            info = module_caller
            best_span = None
            for start, end, fn_info in spans:
                if start <= node.lineno <= end and (
                    best_span is None or start > best_span[0]
                ):
                    best_span = (start, end)
                    info = fn_info
            site = resolve_call(index, info, node)
            if site.dotted in _RENAMES:
                if (
                    renames_allowed
                    or info.ref == cfg.durable_rename_function
                ):
                    continue
                findings.append(
                    Finding(
                        checker="atomic-write",
                        severity=SEVERITY_ERROR,
                        file=info.relpath,
                        line=node.lineno,
                        key=f"{site.dotted}@{info.qualname}",
                        message=(
                            f"publication-critical rename `{site.dotted}`"
                            f" in `{info.qualname}` bypasses "
                            "io/artifacts.py::durable_replace; without "
                            "the fsync-file + fsync-parent-dir "
                            "discipline a power cut after the rename "
                            "can silently vanish the publication"
                        ),
                    )
                )
                continue
            if writes_allowed or info.ref in allowed_functions:
                continue
            construct: str | None = None
            mode: str | None = None
            if site.dotted in ("open", "os.fdopen"):
                mode = _write_mode(node)
                if mode is not None:
                    construct = f"{site.dotted}(mode={mode!r})"
            elif site.dotted in _SERIALIZERS:
                construct = site.dotted
            if construct is None:
                continue
            findings.append(
                Finding(
                    checker="atomic-write",
                    severity=SEVERITY_ERROR,
                    file=info.relpath,
                    line=node.lineno,
                    key=f"{construct}@{info.qualname}",
                    message=(
                        f"direct file write `{construct}` in "
                        f"`{info.qualname}` bypasses the atomic artifact "
                        "writer; route it through io/artifacts.py "
                        "(save_pickle / atomic_write_text / "
                        "_atomic_write_bytes) so a crash mid-write can "
                        "never leave torn bytes on the PVC"
                    ),
                )
            )
    return findings
