"""Best-effort project call graph.

Resolution is deliberately CONSERVATIVE — an edge exists only when the
callee is identifiable without type inference:

- ``name(...)``            → function in the same module, or a
                             ``from X import name`` project import, or a
                             project class (edge to ``Class.__init__``);
- ``self.m(...)``          → method ``m`` of the enclosing class;
- ``self.attr.m(...)``     → method ``m`` of ``attr``'s class, when
                             ``__init__`` annotated/constructed it
                             (:attr:`ProjectIndex.attr_types`);
- ``mod.f(...)``           → function ``f`` in the project module bound
                             to local name ``mod``;
- ``Class.m(...)``         → that class's method.

Anything else (calls through locals, parameters, callbacks, returned
closures) produces NO edge: a missed edge can hide a violation, but a
fabricated edge would fabricate a violation, and a CI gate must not cry
wolf. The nested-closure rule in :func:`core.iter_nodes_shallow` is part
of the same stance — a closure's body joins the graph only where the
closure itself is visibly invoked.

For forbidden-construct matching, every call site also gets a DOTTED
NAME (``"time.sleep"``, ``"np.asarray"``, ``"open"``) resolved through
the module's import aliases, plus the bare method name for
receiver-independent rules (``.item()``, ``.result()``).

Execution-context classification (ISSUE 20) lives here too: every
function is classified as event-loop (reachable from asyncio Protocol
callbacks, ``async def``s, ``loop.call_soon/call_later/call_at``
targets, ``add_done_callback`` callbacks registered in loop context, or
configured entries), worker-thread (reachable from ``Thread(target=…)``
/ ``executor.submit(fn)`` / ``run_in_executor`` / ``to_thread``
targets), or neither. The same conservative stance applies: a function
REFERENCE handed to a scheduler resolves only when it is visibly a
project function — which also means executor hops naturally END the
loop walk, because the handed-off callable produces no call edge.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from .core import (
    AnalysisConfig,
    FunctionInfo,
    ProjectIndex,
    iter_nodes_shallow,
)


@dataclasses.dataclass(frozen=True)
class CallSite:
    """One call expression inside a function body (nested scopes
    excluded)."""

    line: int
    dotted: str | None  # "time.sleep", "open", … None when unresolvable
    method: str | None  # bare attr name for ".item()"-style rules
    target: str | None  # project function ref "relpath::qualname"
    awaited: bool = False  # directly under an ``await`` — yields, not blocks


def _dotted_name(node: ast.AST) -> str | None:
    """Flatten Name/Attribute chains → "a.b.c" (None on anything else)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_call(
    index: ProjectIndex,
    caller: FunctionInfo,
    call: ast.Call,
    awaited: bool = False,
) -> CallSite:
    func = call.func
    line = call.lineno
    mod = index.modules[caller.relpath]
    dotted = _dotted_name(func)
    method = func.attr if isinstance(func, ast.Attribute) else None
    target: FunctionInfo | None = None

    if isinstance(func, ast.Name):
        name = func.id
        target = index.functions.get((caller.relpath, name))
        if target is None and name in mod.name_imports:
            src_rel, src_name = mod.name_imports[name]
            target = index.functions.get((src_rel, src_name))
            if target is None:
                # imported project CLASS: constructor edge
                if index.classes.get(src_name) is not None:
                    target = index.class_method(src_name, "__init__")
        if target is None and index.classes.get(name) == caller.relpath:
            target = index.class_method(name, "__init__")

    elif isinstance(func, ast.Attribute):
        value = func.value
        # self.m(...)
        if isinstance(value, ast.Name) and value.id == "self":
            if caller.class_name:
                target = index.class_method(caller.class_name, func.attr)
        # self.attr.m(...)
        elif (
            isinstance(value, ast.Attribute)
            and isinstance(value.value, ast.Name)
            and value.value.id == "self"
            and caller.class_name
        ):
            attr_cls = index.attr_types.get(
                (caller.class_name, value.attr)
            )
            if attr_cls:
                target = index.class_method(attr_cls, func.attr)
        # mod.OBJ.m(...) — module singleton through a module alias
        elif (
            isinstance(value, ast.Attribute)
            and isinstance(value.value, ast.Name)
            and value.value.id in mod.module_imports
        ):
            obj_cls = index.module_attr_types.get(
                (mod.module_imports[value.value.id], value.attr)
            )
            if obj_cls:
                target = index.class_method(obj_cls, func.attr)
        # mod.f(...) / Class.m(...) / OBJ.m(...)
        elif isinstance(value, ast.Name):
            name = value.id
            if name in mod.module_imports:
                target = index.functions.get(
                    (mod.module_imports[name], func.attr)
                )
            elif index.classes.get(name) is not None:
                target = index.class_method(name, func.attr)
            elif name in mod.external_imports:
                # canonicalize through the alias so "import numpy as np"
                # and "import numpy" both match "numpy.*" rules; the
                # local alias spelling is kept too via `dotted`
                root = mod.external_imports[name].split(".")[0]
                dotted = f"{root}.{func.attr}"
            else:
                # module singleton: same-module NAME, or
                # "from X import NAME" where X assigned NAME = Class()
                obj_cls = index.module_attr_types.get(
                    (caller.relpath, name)
                )
                if obj_cls is None and name in mod.name_imports:
                    obj_cls = index.module_attr_types.get(
                        mod.name_imports[name]
                    )
                if obj_cls:
                    target = index.class_method(obj_cls, func.attr)

    return CallSite(
        line=line,
        dotted=dotted,
        method=method,
        target=target.ref if target is not None else None,
        awaited=awaited,
    )


def function_calls(
    index: ProjectIndex, info: FunctionInfo
) -> list[CallSite]:
    """Every call site in ``info``'s own scope (closures excluded)."""
    nodes = list(iter_nodes_shallow(info.node))
    # every call under an ``await`` expression counts as awaited — the
    # direct coroutine call, and coroutine factories handed to awaited
    # combinators (``await asyncio.wait_for(event.wait(), …)``: that
    # ``.wait()`` builds a coroutine, it does not block)
    awaited_ids: set[int] = set()
    for node in nodes:
        if isinstance(node, ast.Await):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    awaited_ids.add(id(sub))
    out: list[CallSite] = []
    for node in nodes:
        if isinstance(node, ast.Call):
            out.append(
                resolve_call(
                    index, info, node, awaited=id(node) in awaited_ids
                )
            )
    return out


class CallGraph:
    """Edges + memoized per-function call sites over a ProjectIndex."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self._sites: dict[str, list[CallSite]] = {}

    def sites(self, ref: str) -> list[CallSite]:
        if ref not in self._sites:
            info = self.index.function(ref)
            self._sites[ref] = (
                function_calls(self.index, info) if info else []
            )
        return self._sites[ref]

    def reachable(
        self,
        entries: Iterable[str],
        cuts: Iterable[str] = (),
    ) -> dict[str, list[str]]:
        """BFS from ``entries`` → ``{ref: call path from an entry}``.
        The path (entry → … → ref) makes findings explainable. ``cuts``
        are refs the walk never enters — statically reachable functions
        that a dispatch layer guarantees never RUN in this context."""
        cut_set = set(cuts)
        paths: dict[str, list[str]] = {}
        queue: list[str] = []
        for entry in entries:
            if (
                self.index.function(entry)
                and entry not in paths
                and entry not in cut_set
            ):
                paths[entry] = [entry]
                queue.append(entry)
        while queue:
            ref = queue.pop(0)
            for site in self.sites(ref):
                tgt = site.target
                if tgt is not None and tgt not in paths and tgt not in cut_set:
                    paths[tgt] = paths[ref] + [tgt]
                    queue.append(tgt)
        return paths


def match_forbidden(
    site: CallSite,
    forbidden_calls: Iterable[str],
    forbidden_methods: Iterable[str],
) -> str | None:
    """→ the matched construct name, or None."""
    if site.dotted is not None and site.dotted in forbidden_calls:
        return site.dotted
    if site.method is not None and site.method in forbidden_methods:
        return f".{site.method}()"
    return None


# ---------------------------------------------------------------------------
# execution-context classification (ISSUE 20)
# ---------------------------------------------------------------------------

# asyncio transport base classes whose callbacks the loop invokes
_PROTOCOL_BASES = frozenset(
    {
        "asyncio.Protocol",
        "asyncio.BufferedProtocol",
        "asyncio.DatagramProtocol",
        "asyncio.SubprocessProtocol",
    }
)

# loop scheduling methods -> positional index of the callback argument
_SCHEDULE_CALLBACK_ARG = {
    "call_soon": 0,
    "call_soon_threadsafe": 0,
    "call_later": 1,
    "call_at": 1,
}

# thread-launch calls -> positional index of the callable argument
# (`Thread(target=…)` passes it by keyword and is handled separately)
_THREAD_CALLABLE_ARG = {
    "submit": 0,  # Executor.submit — only counts when arg 0 RESOLVES
    "run_in_executor": 1,
    "to_thread": 0,
}


@dataclasses.dataclass
class ExecContext:
    """Which functions run where. ``loop``/``thread`` map each
    reachable ref to its call path from a context root; ``loop_roots``
    maps each loop root to WHY it is one (for findings)."""

    loop: dict[str, list[str]]
    thread: dict[str, list[str]]
    loop_roots: dict[str, str]

    def contexts(self, ref: str) -> set[str]:
        out: set[str] = set()
        if ref in self.loop:
            out.add("event-loop")
        if ref in self.thread:
            out.add("worker-thread")
        return out


def resolve_func_ref(
    index: ProjectIndex, caller: FunctionInfo, node: ast.AST
) -> str | None:
    """Resolve a bare function REFERENCE (a callback handed to a
    scheduler) to a project ref, under the same conservative rules as
    :func:`resolve_call`. Locals, parameters, and closures → None."""
    mod = index.modules[caller.relpath]
    if isinstance(node, ast.Name):
        info = index.functions.get((caller.relpath, node.id))
        if info is None and node.id in mod.name_imports:
            info = index.functions.get(mod.name_imports[node.id])
        return info.ref if info else None
    if isinstance(node, ast.Attribute):
        value = node.value
        if isinstance(value, ast.Name):
            if value.id == "self" and caller.class_name:
                info = index.class_method(caller.class_name, node.attr)
                return info.ref if info else None
            if value.id in mod.module_imports:
                info = index.functions.get(
                    (mod.module_imports[value.id], node.attr)
                )
                return info.ref if info else None
            if index.classes.get(value.id) is not None:
                info = index.class_method(value.id, node.attr)
                return info.ref if info else None
        elif (
            isinstance(value, ast.Attribute)
            and isinstance(value.value, ast.Name)
            and value.value.id == "self"
            and caller.class_name
        ):
            attr_cls = index.attr_types.get(
                (caller.class_name, value.attr)
            )
            if attr_cls:
                info = index.class_method(attr_cls, node.attr)
                return info.ref if info else None
    return None


def _callback_targets(
    index: ProjectIndex, caller: FunctionInfo, node: ast.AST
) -> list[str]:
    """Refs a callback argument may invoke: the ref itself, or — for a
    lambda — every resolvable call in its body (the lambda runs in the
    scheduler's context, so its calls do too)."""
    ref = resolve_func_ref(index, caller, node)
    if ref is not None:
        return [ref]
    if isinstance(node, ast.Lambda):
        out: list[str] = []
        for sub in ast.walk(node.body):
            if isinstance(sub, ast.Call):
                site = resolve_call(index, caller, sub)
                if site.target is not None:
                    out.append(site.target)
        return out
    return []


def _is_protocol_class(index: ProjectIndex, class_name: str) -> bool:
    relpath = index.classes.get(class_name)
    if relpath is None:
        return False
    mod = index.modules[relpath]
    for base in index.class_bases.get(class_name, ()):
        if base in _PROTOCOL_BASES:
            return True
        # "from asyncio import Protocol" / aliased imports
        if "." not in base and mod.external_imports.get(base) in _PROTOCOL_BASES:
            return True
    return False


def _scheduled_loop_roots(index: ProjectIndex) -> dict[str, str]:
    """Global pre-pass: targets of ``loop.call_soon``/``call_later``/
    ``call_at``/``call_soon_threadsafe`` anywhere in the tree (full
    walk, closures and lambdas included — ``call_soon_threadsafe``
    schedules ONTO the loop from any context, so the scheduling site's
    own context is irrelevant)."""
    roots: dict[str, str] = {}
    for info in index.functions.values():
        for node in ast.walk(info.node):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            argidx = _SCHEDULE_CALLBACK_ARG.get(node.func.attr)
            if argidx is None or len(node.args) <= argidx:
                continue
            for ref in _callback_targets(index, info, node.args[argidx]):
                roots.setdefault(
                    ref,
                    f"scheduled onto the loop by "
                    f"`{info.qualname}` via {node.func.attr}",
                )
    return roots


def _thread_roots(index: ProjectIndex) -> dict[str, str]:
    """Targets handed to threads/executors anywhere in the tree:
    ``Thread(target=f)``, ``pool.submit(f)``, ``loop.run_in_executor
    (None, f)``, ``asyncio.to_thread(f)``. Only resolvable project
    function refs count — `batcher.submit(request)` hands off DATA, not
    a callable, and produces no root."""
    roots: dict[str, str] = {}
    for info in index.functions.values():
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted_name(node.func)
            if dotted is not None and dotted.split(".")[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        ref = resolve_func_ref(index, info, kw.value)
                        if ref:
                            roots.setdefault(
                                ref,
                                f"thread target launched by "
                                f"`{info.qualname}`",
                            )
                continue
            if isinstance(node.func, ast.Attribute):
                argidx = _THREAD_CALLABLE_ARG.get(node.func.attr)
                if argidx is None or len(node.args) <= argidx:
                    continue
                ref = resolve_func_ref(index, info, node.args[argidx])
                if ref:
                    roots.setdefault(
                        ref,
                        f"handed to an executor by `{info.qualname}` "
                        f"via {node.func.attr}",
                    )
    return roots


def classify_contexts(
    index: ProjectIndex, cfg: AnalysisConfig, graph: CallGraph | None = None
) -> ExecContext:
    """Classify every function by execution context (see module
    docstring). Loop reachability honors ``cfg.loop_cut_functions`` and
    iterates to a fixpoint over ``add_done_callback`` registrations:
    a done-callback registered by loop-context code runs on the loop
    (asyncio futures) or is a ``call_soon_threadsafe`` trampoline whose
    real target the scheduling pre-pass already captured."""
    graph = graph or CallGraph(index)
    loop_roots: dict[str, str] = {}
    for info in index.functions.values():
        if isinstance(info.node, ast.AsyncFunctionDef):
            loop_roots.setdefault(info.ref, "async def")
        elif info.class_name and _is_protocol_class(index, info.class_name):
            loop_roots.setdefault(
                info.ref, f"asyncio protocol callback on {info.class_name}"
            )
    loop_roots.update(_scheduled_loop_roots(index))
    for entry in cfg.loop_entries:
        if index.function(entry) is not None:
            loop_roots.setdefault(entry, "configured loop entry")

    cuts = set(cfg.loop_cut_functions)
    loop_paths = graph.reachable(loop_roots, cuts=cuts)
    while True:
        added = False
        for ref in list(loop_paths):
            info = index.function(ref)
            if info is None:
                continue
            for node in ast.walk(info.node):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_done_callback"
                    and node.args
                ):
                    continue
                for tgt in _callback_targets(index, info, node.args[0]):
                    if tgt not in loop_paths and tgt not in cuts:
                        loop_roots.setdefault(
                            tgt,
                            f"done-callback registered in loop context "
                            f"by `{info.qualname}`",
                        )
                        added = True
        if not added:
            break
        loop_paths = graph.reachable(loop_roots, cuts=cuts)

    thread_paths = graph.reachable(_thread_roots(index))
    return ExecContext(
        loop=loop_paths, thread=thread_paths, loop_roots=loop_roots
    )
