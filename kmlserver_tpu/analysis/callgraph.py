"""Best-effort project call graph.

Resolution is deliberately CONSERVATIVE — an edge exists only when the
callee is identifiable without type inference:

- ``name(...)``            → function in the same module, or a
                             ``from X import name`` project import, or a
                             project class (edge to ``Class.__init__``);
- ``self.m(...)``          → method ``m`` of the enclosing class;
- ``self.attr.m(...)``     → method ``m`` of ``attr``'s class, when
                             ``__init__`` annotated/constructed it
                             (:attr:`ProjectIndex.attr_types`);
- ``mod.f(...)``           → function ``f`` in the project module bound
                             to local name ``mod``;
- ``Class.m(...)``         → that class's method.

Anything else (calls through locals, parameters, callbacks, returned
closures) produces NO edge: a missed edge can hide a violation, but a
fabricated edge would fabricate a violation, and a CI gate must not cry
wolf. The nested-closure rule in :func:`core.iter_nodes_shallow` is part
of the same stance — a closure's body joins the graph only where the
closure itself is visibly invoked.

For forbidden-construct matching, every call site also gets a DOTTED
NAME (``"time.sleep"``, ``"np.asarray"``, ``"open"``) resolved through
the module's import aliases, plus the bare method name for
receiver-independent rules (``.item()``, ``.result()``).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from .core import FunctionInfo, ProjectIndex, iter_nodes_shallow


@dataclasses.dataclass(frozen=True)
class CallSite:
    """One call expression inside a function body (nested scopes
    excluded)."""

    line: int
    dotted: str | None  # "time.sleep", "open", … None when unresolvable
    method: str | None  # bare attr name for ".item()"-style rules
    target: str | None  # project function ref "relpath::qualname"


def _dotted_name(node: ast.AST) -> str | None:
    """Flatten Name/Attribute chains → "a.b.c" (None on anything else)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_call(
    index: ProjectIndex, caller: FunctionInfo, call: ast.Call
) -> CallSite:
    func = call.func
    line = call.lineno
    mod = index.modules[caller.relpath]
    dotted = _dotted_name(func)
    method = func.attr if isinstance(func, ast.Attribute) else None
    target: FunctionInfo | None = None

    if isinstance(func, ast.Name):
        name = func.id
        target = index.functions.get((caller.relpath, name))
        if target is None and name in mod.name_imports:
            src_rel, src_name = mod.name_imports[name]
            target = index.functions.get((src_rel, src_name))
            if target is None:
                # imported project CLASS: constructor edge
                if index.classes.get(src_name) is not None:
                    target = index.class_method(src_name, "__init__")
        if target is None and index.classes.get(name) == caller.relpath:
            target = index.class_method(name, "__init__")

    elif isinstance(func, ast.Attribute):
        value = func.value
        # self.m(...)
        if isinstance(value, ast.Name) and value.id == "self":
            if caller.class_name:
                target = index.class_method(caller.class_name, func.attr)
        # self.attr.m(...)
        elif (
            isinstance(value, ast.Attribute)
            and isinstance(value.value, ast.Name)
            and value.value.id == "self"
            and caller.class_name
        ):
            attr_cls = index.attr_types.get(
                (caller.class_name, value.attr)
            )
            if attr_cls:
                target = index.class_method(attr_cls, func.attr)
        # mod.f(...) / Class.m(...)
        elif isinstance(value, ast.Name):
            name = value.id
            if name in mod.module_imports:
                target = index.functions.get(
                    (mod.module_imports[name], func.attr)
                )
            elif index.classes.get(name) is not None:
                target = index.class_method(name, func.attr)
            elif name in mod.external_imports:
                # canonicalize through the alias so "import numpy as np"
                # and "import numpy" both match "numpy.*" rules; the
                # local alias spelling is kept too via `dotted`
                root = mod.external_imports[name].split(".")[0]
                dotted = f"{root}.{func.attr}"

    return CallSite(
        line=line,
        dotted=dotted,
        method=method,
        target=target.ref if target is not None else None,
    )


def function_calls(
    index: ProjectIndex, info: FunctionInfo
) -> list[CallSite]:
    """Every call site in ``info``'s own scope (closures excluded)."""
    out: list[CallSite] = []
    for node in iter_nodes_shallow(info.node):
        if isinstance(node, ast.Call):
            out.append(resolve_call(index, info, node))
    return out


class CallGraph:
    """Edges + memoized per-function call sites over a ProjectIndex."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self._sites: dict[str, list[CallSite]] = {}

    def sites(self, ref: str) -> list[CallSite]:
        if ref not in self._sites:
            info = self.index.function(ref)
            self._sites[ref] = (
                function_calls(self.index, info) if info else []
            )
        return self._sites[ref]

    def reachable(self, entries: Iterable[str]) -> dict[str, list[str]]:
        """BFS from ``entries`` → ``{ref: call path from an entry}``.
        The path (entry → … → ref) makes findings explainable."""
        paths: dict[str, list[str]] = {}
        queue: list[str] = []
        for entry in entries:
            if self.index.function(entry) and entry not in paths:
                paths[entry] = [entry]
                queue.append(entry)
        while queue:
            ref = queue.pop(0)
            for site in self.sites(ref):
                tgt = site.target
                if tgt is not None and tgt not in paths:
                    paths[tgt] = paths[ref] + [tgt]
                    queue.append(tgt)
        return paths


def match_forbidden(
    site: CallSite,
    forbidden_calls: Iterable[str],
    forbidden_methods: Iterable[str],
) -> str | None:
    """→ the matched construct name, or None."""
    if site.dotted is not None and site.dotted in forbidden_calls:
        return site.dotted
    if site.method is not None and site.method in forbidden_methods:
        return f".{site.method}()"
    return None
