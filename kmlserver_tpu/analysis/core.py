"""Analyzer core: project index, findings, baseline, and the runner.

Everything here is stdlib-``ast`` based and import-free with respect to
the code under analysis — the analyzer PARSES the tree, it never imports
it, so it runs identically against the real package and against the tiny
fixture trees the test suite seeds with deliberate violations (and in a
CI job with no jax installed).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Any, Callable, Iterable

SEVERITY_ERROR = "error"
SEVERITY_WARN = "warn"

# inline suppression pragma, honored on the flagged line or the line
# directly above it: `# kmls-verify: allow[<checker>]`
PRAGMA_PREFIX = "kmls-verify: allow["


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation.

    ``key`` is the checker-specific STABLE identity (knob name, lock
    pair, construct@function, …) — deliberately line-free, so a baseline
    entry survives unrelated edits that shift line numbers."""

    checker: str
    severity: str
    file: str  # repo-relative path
    line: int
    key: str
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.checker}::{self.file}::{self.key}"

    def render(self) -> str:
        return (
            f"{self.severity}: {self.file}:{self.line} [{self.checker}] "
            f"{self.message}"
        )


@dataclasses.dataclass
class AnalysisConfig:
    """Project policy: what the checkers treat as entry points, hot
    locks, approved writers, registries. Defaults describe THIS repo;
    tests override them to point at fixture trees."""

    # --- file discovery (repo-relative) ---
    package_dir: str = "kmlserver_tpu"
    extra_code: tuple[str, ...] = ("bench.py", "scripts")
    tests_dir: str = "tests"
    readme: str = "README.md"
    manifest_files: tuple[str, ...] = (
        "kubernetes/deployment.yaml",
        "kubernetes/statefulset.yaml",
        "kubernetes/serve-gang.yaml",
        "kubernetes/job.yaml",
        "kubernetes/job-multihost.yaml",
    )

    # --- hotpath checker ---
    # serving dispatch entry points, as "<relpath>::<qualname>". The
    # completion side (the finish() closures, which BLOCK by design) is
    # excluded structurally: nested defs are never traversed unless
    # called directly.
    hotpath_entries: tuple[str, ...] = (
        "kmlserver_tpu/serving/batcher.py::MicroBatcher.submit",
        "kmlserver_tpu/serving/batcher.py::MicroBatcher._collect_loop",
        "kmlserver_tpu/serving/batcher.py::AsyncMicroBatcher.submit",
        "kmlserver_tpu/serving/batcher.py::AsyncMicroBatcher._flush",
        "kmlserver_tpu/serving/engine.py::RecommendEngine.recommend_many_async",
        # the sharded-layout dispatch rides recommend_many_async, but its
        # staging step (seed transfer + per-shard accounting) is anchored
        # EXPLICITLY so a refactor that stops routing through the parent
        # entry cannot silently take the sharded path out of the purity
        # check (ISSUE 7; the anchor-existence test fails on a rename)
        "kmlserver_tpu/serving/engine.py::RecommendEngine._stage_seeds",
        # the span recorder's request-path halves (ISSUE 9): begin() runs
        # at admission for every traced request, finish() on the
        # completion side holding the retention lock — neither may ever
        # grow file I/O, sleeps, or host syncs
        "kmlserver_tpu/observability/trace.py::SpanRecorder.begin",
        "kmlserver_tpu/observability/trace.py::SpanRecorder.finish",
        # the cost model's observation path (ISSUE 12): runs on the
        # batch completion side for every dispatched kernel — a few
        # float adds under its private lock, and it must stay that way
        "kmlserver_tpu/observability/costmodel.py::CostModel.observe_kernel",
    )
    # host-sync / blocking constructs forbidden on the dispatch path,
    # by resolved dotted name …
    hotpath_forbidden_calls: tuple[str, ...] = (
        "time.sleep",
        "open",
        "np.asarray",
        "np.array",
        "numpy.asarray",
        "numpy.array",
        "jax.jit",
        "jax.block_until_ready",
        "jax.device_get",
        "pickle.load",
        "pickle.dump",
        "json.load",
        "json.dump",
        "subprocess.run",
        "subprocess.Popen",
        "subprocess.check_output",
        "os.replace",
        "os.rename",
    )
    # … and by bare method name on ANY receiver (`x.item()` is a host
    # sync whatever x is; `fut.result()` is a block)
    hotpath_forbidden_methods: tuple[str, ...] = ("item", "result")

    # --- locks checker ---
    # hot-path locks as "<ClassName>.<attr>" or "<module relpath>::<name>"
    # for module-level locks. engine._reload_lock is deliberately ABSENT:
    # the reload path is cold by design and does file I/O under it.
    hot_locks: tuple[str, ...] = (
        "MicroBatcher._n_lock",
        "MicroBatcher._rate_lock",
        "RecommendEngine._dispatch_lock",
        "RecommendEngine._staging_lock",
        "RecommendCache._lock",
        "ServingMetrics._lock",
        "LatencyReservoir._lock",
        "LatencyHistogram._lock",
        "SpanRecorder._lock",
        "RankWatchdog._guard_lock",
        "_Server.active_lock",
        "kmlserver_tpu/faults.py::_lock",
    )
    locks_blocking_calls: tuple[str, ...] = (
        "time.sleep",
        "open",
        "os.replace",
        "os.rename",
        "os.fdopen",
        "pickle.load",
        "pickle.dump",
        "json.load",
        "json.dump",
        "subprocess.run",
        "subprocess.Popen",
        "subprocess.check_output",
        "socket.create_connection",
        "jax.device_put",
        "jax.block_until_ready",
    )
    locks_blocking_methods: tuple[str, ...] = ("result",)

    # --- atomic-write checker ---
    # modules allowed to write bytes directly: the atomic writer itself
    # (the KMLS_REFERENCE_RACE_COMPAT site lives inside it) and the
    # corruption harness, whose JOB is producing torn bytes.
    # (a trailing "/" makes an entry a directory prefix — the analysis
    # package is tooling writing its OWN state, not PVC artifacts)
    atomic_allowed_modules: tuple[str, ...] = (
        "kmlserver_tpu/io/artifacts.py",
        "kmlserver_tpu/faults.py",
        "kmlserver_tpu/analysis/",
    )
    # functions allowed to write directly, with the reason in the name of
    # review: the dataset-history append is the reference's append-only
    # log (readers skip torn tails line-wise; byte-compat contract).
    atomic_allowed_functions: tuple[str, ...] = (
        "kmlserver_tpu/io/registry.py::append_history_and_invalidate",
    )
    # the ONE function allowed to call os.replace/os.rename anywhere in
    # the package (ISSUE 19): publication-critical renames must carry
    # the fsync-file + fsync-parent-dir discipline, which only
    # durable_replace implements — a bare os.replace elsewhere is a
    # publication that a power cut can silently vanish.
    durable_rename_function: str = (
        "kmlserver_tpu/io/artifacts.py::durable_replace"
    )
    # modules whose renames are NOT publication-critical (tooling state,
    # not PVC artifacts); trailing "/" = directory prefix, like
    # atomic_allowed_modules.
    rename_allowed_modules: tuple[str, ...] = (
        "kmlserver_tpu/analysis/",
    )

    # --- knob registry checker ---
    config_file: str = "kmlserver_tpu/config.py"
    knob_registry_name: str = "KNOB_REGISTRY"
    knob_prefix: str = "KMLS_"
    # scope -> manifest files at least one of which must mention the knob
    knob_scope_manifests: dict[str, tuple[str, ...]] = dataclasses.field(
        default_factory=lambda: {
            # a serving knob may be bound in either serving manifest —
            # the stateless Deployment or the fleet-identity StatefulSet
            # (ISSUE 15); "both"-scope routing below keys on the
            # basename containing "deployment", so the StatefulSet joins
            # the serving group here without widening that rule
            "serving": (
                "kubernetes/deployment.yaml",
                "kubernetes/statefulset.yaml",
                # the pod-spanning serve-gang recipe (ISSUE 16) binds
                # the KMLS_SERVE_GANG_* knobs
                "kubernetes/serve-gang.yaml",
            ),
            "mining": (
                "kubernetes/job.yaml",
                "kubernetes/job-multihost.yaml",
            ),
            "both": (
                "kubernetes/deployment.yaml",
                "kubernetes/job.yaml",
                "kubernetes/job-multihost.yaml",
            ),
            # tool (bench/dev/test harness) and fault knobs never ship
            # in manifests
            "tool": (),
            "fault": (),
        }
    )

    # --- metric registry checker (ISSUE 9) ---
    metrics_file: str = "kmlserver_tpu/serving/metrics.py"
    metric_registry_name: str = "METRIC_REGISTRY"
    # exposition module -> the scope its series must be registered under
    metric_exposition_files: dict[str, str] = dataclasses.field(
        default_factory=lambda: {
            "kmlserver_tpu/serving/metrics.py": "serving",
            "kmlserver_tpu/observability/jobmetrics.py": "mining",
            # ISSUE 12: the cost-attribution block and the SLO burn-rate
            # gauges render their own lines into /metrics — their series
            # literals live in these modules, not metrics.py
            "kmlserver_tpu/observability/costmodel.py": "serving",
            "kmlserver_tpu/observability/slo.py": "serving",
        }
    )
    # (function ref, rendered prefix, scope): dict keys / subscript stores
    # in the function render as <prefix><key> series — the app's
    # robustness-state dict reaches /metrics through the kmls_ prefix
    metric_dynamic_sources: tuple[tuple[str, str, str], ...] = (
        (
            "kmlserver_tpu/serving/app.py::RecommendApp._robustness_state",
            "kmls_",
            "serving",
        ),
    )

    # --- cost-spec checker (ISSUE 12) ---
    costmodel_file: str = "kmlserver_tpu/observability/costmodel.py"
    costspec_registry_name: str = "KERNEL_COST_SPECS"
    # the dispatched jitted kernels that must stay registered — the
    # anchor that keeps a rename from silently hollowing the checker
    # (tests assert these names exist in the real tree)
    costspec_required: tuple[str, ...] = (
        "serve_rules",
        "serve_sharded",
        "serve_native",
        "embed_topk",
        "als_sweep",
        "support_count",
        "delta_recount",
    )

    # --- fault-site checker ---
    faults_file: str = "kmlserver_tpu/faults.py"

    # --- exit-code checker ---
    job_file: str = "kmlserver_tpu/mining/job.py"
    job_manifests: tuple[str, ...] = (
        "kubernetes/job.yaml",
        "kubernetes/job-multihost.yaml",
    )

    # --- loopblock checker (ISSUE 20) ---
    # Event-loop roots the classifier cannot auto-detect: the asyncio
    # transport calls these through locals/attrs the conservative graph
    # refuses to resolve (`app = state.app; app.handle(...)` inline in
    # `_Conn._dispatch` for non-recommend routes; the loop-native
    # batcher's admission/flush pair). Auto-detected roots — asyncio
    # Protocol callbacks, `async def`s, call_soon/call_later targets —
    # need no entry here.
    loop_entries: tuple[str, ...] = (
        "kmlserver_tpu/serving/app.py::RecommendApp.handle",
        "kmlserver_tpu/serving/app.py::RecommendApp.finish_recommend",
        "kmlserver_tpu/serving/batcher.py::AsyncMicroBatcher.submit",
        "kmlserver_tpu/serving/batcher.py::AsyncMicroBatcher._flush",
    )
    # Statically reachable from a loop entry but never RUN on the loop:
    # the asyncio transport intercepts recommend POSTs in `_dispatch`
    # (before the inline `app.handle` call) and routes them through the
    # engine pool / loop-native batcher, so `_post_recommend`'s and
    # `recommend_direct`'s blocking branches only execute on the
    # threaded front end. Cutting here keeps the loop map honest; the
    # anchor test pins both refs so a rename can't hollow the cut.
    loop_cut_functions: tuple[str, ...] = (
        "kmlserver_tpu/serving/app.py::RecommendApp._post_recommend",
        "kmlserver_tpu/serving/app.py::RecommendApp.recommend_direct",
    )
    # Blocking constructs forbidden in event-loop context, by resolved
    # dotted name. jax.device_put / np.asarray are deliberately ABSENT:
    # async-dispatch staging pays those on the loop by design (bounded
    # work), unlike the unbounded stalls below.
    loopblock_forbidden_calls: tuple[str, ...] = (
        "time.sleep",
        "open",
        "os.replace",
        "os.rename",
        "os.fsync",
        "os.fdopen",
        "os.statvfs",
        "subprocess.run",
        "subprocess.Popen",
        "subprocess.check_output",
        "socket.create_connection",
        "pickle.load",
        "pickle.dump",
        "json.load",
        "json.dump",
        "jax.jit",
        "jax.block_until_ready",
    )
    # … and by bare method name on any receiver. `wait`/`acquire`/
    # `result` only match UN-awaited call sites — `await x.wait()`
    # yields to the loop, `x.wait()` freezes it.
    loopblock_forbidden_methods: tuple[str, ...] = (
        "result",
        "wait",
        "acquire",
        "item",
        "block_until_ready",
    )

    # --- lockown checker (ISSUE 20) ---
    # minimum guarded accesses before a field's owning lock is inferred;
    # below this the evidence is too thin to call an unguarded write a
    # race (deliberately lock-free classes stay silent).
    lockown_min_guarded: int = 2
    # the repo's documented ownership-handoff convention: a method named
    # `*_locked` is only ever called with the owning lock already held
    # (forecast._roll_locked, mesh._close_locked). Such methods are
    # excluded from both the ownership vote and the unguarded-write
    # sweep — the suffix IS the documentation.
    lockown_held_suffix: str = "_locked"

    # --- envread checker (ISSUE 20) ---
    # project wrappers around os.getenv — a call to one of these at
    # module import time freezes the knob exactly like a bare getenv
    envread_helper_functions: tuple[str, ...] = (
        "kmlserver_tpu/config.py::_getenv_int",
        "kmlserver_tpu/config.py::_getenv_float",
        "kmlserver_tpu/config.py::_getenv_bool",
        "kmlserver_tpu/config.py::_getenv_hybrid_mode",
        "kmlserver_tpu/config.py::_getenv_blend_weight",
        "kmlserver_tpu/config.py::_getenv_model_layout",
        "kmlserver_tpu/config.py::_getenv_gang_rank",
        "kmlserver_tpu/config.py::_getenv_bitpack_threshold",
    )


# ---------------------------------------------------------------------------
# project index
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ModuleInfo:
    relpath: str
    tree: ast.Module
    source_lines: list[str]
    # local name -> project module relpath ("from . import native_serve",
    # "from ..io import artifacts", "import kmlserver_tpu.faults as faults")
    module_imports: dict[str, str] = dataclasses.field(default_factory=dict)
    # local name -> (relpath, original name) for "from X import name"
    name_imports: dict[str, tuple[str, str]] = dataclasses.field(
        default_factory=dict
    )
    # local name -> dotted external root ("np" -> "numpy" … kept verbatim)
    external_imports: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class FunctionInfo:
    relpath: str
    qualname: str  # "func" or "Class.method"
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    class_name: str | None

    @property
    def ref(self) -> str:
        return f"{self.relpath}::{self.qualname}"


def iter_nodes_shallow(root: ast.AST) -> Iterable[ast.AST]:
    """Walk ``root``'s body WITHOUT descending into nested function /
    lambda scopes — a closure that is merely defined (e.g. the batcher's
    ``finish()``) is not part of the enclosing function's behavior until
    something actually calls it."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class ProjectIndex:
    """Parsed view of a source tree: modules, top-level functions and
    methods, imports, and ``self.<attr>`` type hints scraped from
    ``__init__`` annotations/constructions."""

    def __init__(self, root: str, py_files: Iterable[str]):
        self.root = root
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[tuple[str, str], FunctionInfo] = {}
        # class name -> defining relpath (single definition expected)
        self.classes: dict[str, str] = {}
        # method name -> [FunctionInfo] (for diagnostics only)
        self.methods_by_name: dict[str, list[FunctionInfo]] = {}
        # (class, attr) -> class name of the attribute's value
        self.attr_types: dict[tuple[str, str], str] = {}
        # class name -> dotted base expressions ("asyncio.Protocol")
        self.class_bases: dict[str, list[str]] = {}
        # (relpath, NAME) -> class, for module-level singletons
        # ``MONITOR = IoHealthMonitor()`` — lets the call graph resolve
        # ``mod.MONITOR.m()`` the way attr_types resolves ``self.x.m()``
        self.module_attr_types: dict[tuple[str, str], str] = {}
        for relpath in sorted(py_files):
            self._index_file(relpath)
        self._scrape_module_singletons()

    # ---------- construction ----------

    @classmethod
    def from_config(cls, root: str, cfg: AnalysisConfig) -> "ProjectIndex":
        return cls(root, discover_py_files(root, cfg))

    def _index_file(self, relpath: str) -> None:
        path = os.path.join(self.root, relpath)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            return
        try:
            tree = ast.parse(source, filename=relpath)
        except SyntaxError:
            return
        mod = ModuleInfo(relpath, tree, source.splitlines())
        self.modules[relpath] = mod
        self._index_imports(mod)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(relpath, node.name, node, None)
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = relpath
                self.class_bases[node.name] = [
                    dotted
                    for base in node.bases
                    if (dotted := _dotted_expr(base)) is not None
                ]
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self._add_function(
                            relpath, f"{node.name}.{item.name}", item, node.name
                        )
                        if item.name == "__init__":
                            self._scrape_attr_types(node.name, item)

    def _add_function(
        self,
        relpath: str,
        qualname: str,
        node: ast.AST,
        class_name: str | None,
    ) -> None:
        info = FunctionInfo(relpath, qualname, node, class_name)
        self.functions[(relpath, qualname)] = info
        method = qualname.rsplit(".", 1)[-1]
        self.methods_by_name.setdefault(method, []).append(info)

    def _scrape_module_singletons(self) -> None:
        """Second pass (all classes known): module-level ``NAME =
        ClassName()`` assignments, recorded so calls through the
        singleton resolve to that class's methods."""
        for relpath, mod in self.modules.items():
            for node in mod.tree.body:
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Name)
                ):
                    continue
                cls = node.value.func.id
                if cls not in self.classes and cls in mod.name_imports:
                    _src, orig = mod.name_imports[cls]
                    cls = orig
                if cls in self.classes:
                    self.module_attr_types[
                        (relpath, node.targets[0].id)
                    ] = cls

    def _index_imports(self, mod: ModuleInfo) -> None:
        """Best-effort: map local names onto project module relpaths.
        Project modules are identified by resolving the import back to a
        file that this index was (or will be) given."""
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    rel = self._module_to_relpath(alias.name)
                    if rel:
                        mod.module_imports[local] = rel
                    else:
                        mod.external_imports[local] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(mod.relpath, node)
                for alias in node.names:
                    local = alias.asname or alias.name
                    if base is None:
                        mod.external_imports[local] = (
                            f"{node.module or ''}.{alias.name}"
                        )
                        continue
                    # "from <pkg> import <name>": <name> may itself be a
                    # module file, else a function/class in <pkg>'s file
                    sub = self._module_to_relpath(f"{base}/{alias.name}")
                    if sub:
                        mod.module_imports[local] = sub
                    else:
                        target = self._module_to_relpath(base)
                        if target:
                            mod.name_imports[local] = (target, alias.name)

    def _module_to_relpath(self, dotted_or_path: str) -> str | None:
        """Dotted module or pseudo-path -> repo-relative file, if it is
        part of the analyzed tree."""
        frag = dotted_or_path.replace(".", "/")
        for candidate in (f"{frag}.py", f"{frag}/__init__.py"):
            if candidate in self.modules or os.path.exists(
                os.path.join(self.root, candidate)
            ):
                return candidate
        return None

    def _resolve_from(
        self, relpath: str, node: ast.ImportFrom
    ) -> str | None:
        """Resolve a ``from X import …`` to a pseudo-path base (slashes),
        or None for external imports."""
        if node.level == 0:
            if node.module is None:
                return None
            frag = node.module.replace(".", "/")
            if self._module_to_relpath(frag):
                return frag
            return None
        # relative import: climb from the importing file's package
        base = os.path.dirname(relpath)
        for _ in range(node.level - 1):
            base = os.path.dirname(base)
        if node.module:
            base = os.path.join(base, node.module.replace(".", "/"))
        return base.replace(os.sep, "/")

    def _scrape_attr_types(self, class_name: str, init: ast.AST) -> None:
        """Infer ``self.<attr>``'s class from __init__: either assigned
        from a parameter with a class annotation, or constructed from a
        known class name directly."""
        ann: dict[str, str] = {}
        args = getattr(init, "args", None)
        if args is not None:
            for a in list(args.args) + list(args.kwonlyargs):
                if a.annotation is not None:
                    name = _annotation_class_name(a.annotation)
                    if name:
                        ann[a.arg] = name
        for node in iter_nodes_shallow(init):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                continue
            value = node.value
            if isinstance(value, ast.Name) and value.id in ann:
                self.attr_types[(class_name, target.attr)] = ann[value.id]
            elif isinstance(value, ast.Call) and isinstance(
                value.func, ast.Name
            ):
                self.attr_types[(class_name, target.attr)] = value.func.id

    # ---------- queries ----------

    def function(self, ref: str) -> FunctionInfo | None:
        relpath, _, qualname = ref.partition("::")
        return self.functions.get((relpath, qualname))

    def class_method(
        self, class_name: str, method: str
    ) -> FunctionInfo | None:
        relpath = self.classes.get(class_name)
        if relpath is None:
            return None
        return self.functions.get((relpath, f"{class_name}.{method}"))

    def source_line(self, relpath: str, lineno: int) -> str:
        lines = self.modules[relpath].source_lines
        if 1 <= lineno <= len(lines):
            return lines[lineno - 1]
        return ""


def _dotted_expr(node: ast.AST) -> str | None:
    """Flatten a Name/Attribute chain → "a.b.c" (None otherwise)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _annotation_class_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        # string annotation: take the last dotted segment, strip generics
        frag = node.value.split("[")[0].split(".")[-1].strip()
        return frag or None
    return None


def discover_py_files(root: str, cfg: AnalysisConfig) -> list[str]:
    """All .py files of the analyzed code: the package plus the extra
    top-level harness files (bench.py, scripts/)."""
    out: list[str] = []
    roots = [cfg.package_dir, *cfg.extra_code]
    for entry in roots:
        path = os.path.join(root, entry)
        if os.path.isfile(path) and entry.endswith(".py"):
            out.append(entry)
            continue
        for dirpath, _dirnames, filenames in os.walk(path):
            for name in sorted(filenames):
                if name.endswith(".py"):
                    full = os.path.join(dirpath, name)
                    out.append(os.path.relpath(full, root).replace(os.sep, "/"))
    return sorted(set(out))


# ---------------------------------------------------------------------------
# baseline + pragma suppression
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> set[str]:
    """The accepted-finding fingerprints, or empty when absent."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return set()
    entries = data.get("findings", []) if isinstance(data, dict) else []
    return {
        e["fingerprint"]
        for e in entries
        if isinstance(e, dict) and "fingerprint" in e
    }


def load_baseline_entries(path: str) -> list[dict[str, Any]]:
    """Raw baseline entries (fingerprint + message), empty when absent."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return []
    entries = data.get("findings", []) if isinstance(data, dict) else []
    return [
        e for e in entries if isinstance(e, dict) and "fingerprint" in e
    ]


def write_baseline(
    path: str,
    findings: list[Finding],
    keep_entries: list[dict[str, Any]] | None = None,
) -> None:
    """Pin ``findings`` (plus ``keep_entries`` — pre-existing raw entries
    to carry over verbatim, used when only a CHECKER SUBSET ran: the
    unselected checkers' pins must survive the rewrite, or a partial
    --write-baseline would silently un-pin them and redden CI)."""
    payload = {
        "version": 1,
        "comment": (
            "Accepted pre-existing findings, pinned so the CI gate is "
            "zero-NEW-findings. Shrink this file; never grow it casually "
            "(see README 'Static invariants')."
        ),
        "findings": sorted(
            {
                **{
                    e["fingerprint"]: {
                        "fingerprint": e["fingerprint"],
                        "message": e.get("message", ""),
                    }
                    for e in (keep_entries or [])
                },
                **{
                    f.fingerprint: {
                        "fingerprint": f.fingerprint,
                        "message": f.message,
                    }
                    for f in findings
                },
            }.values(),
            key=lambda e: e["fingerprint"],
        ),
    }
    # atomic, eating our own cooking (and the analysis package is
    # tooling, not runtime: stdlib-only, so io.artifacts — which imports
    # numpy — is off-limits here)
    data = json.dumps(payload, indent=1, sort_keys=True) + "\n"
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(data)
    os.replace(tmp, path)


def _pragma_suppressed(index: ProjectIndex, finding: Finding) -> bool:
    mod = index.modules.get(finding.file)
    if mod is None:
        return False
    needle = f"{PRAGMA_PREFIX}{finding.checker}]"
    lines = mod.source_lines
    if 1 <= finding.line <= len(lines) and needle in lines[finding.line - 1]:
        return True
    # walk the contiguous comment block directly above the flagged line
    lineno = finding.line - 1
    while 1 <= lineno <= len(lines):
        stripped = lines[lineno - 1].strip()
        if not stripped.startswith("#"):
            break
        if needle in stripped:
            return True
        lineno -= 1
    return False


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def all_checkers() -> dict[str, Callable[[ProjectIndex, AnalysisConfig], list[Finding]]]:
    from . import (
        atomicwrite,
        costspec,
        envread,
        exitcodes,
        hotpath,
        locking,
        lockown,
        loopblock,
        metricsreg,
        registries,
    )

    return {
        "hotpath": hotpath.run,
        "locks": locking.run,
        "atomic-write": atomicwrite.run,
        "knobs": registries.run_knobs,
        "fault-sites": registries.run_fault_sites,
        "exit-codes": exitcodes.run,
        "metrics": metricsreg.run,
        "costspec": costspec.run,
        "loopblock": loopblock.run,
        "lockown": lockown.run,
        "envread": envread.run,
    }


def run_analysis(
    root: str,
    cfg: AnalysisConfig | None = None,
    checkers: Iterable[str] | None = None,
    baseline: set[str] | None = None,
    index: ProjectIndex | None = None,
) -> dict[str, Any]:
    """Run the selected checkers → ``{"findings": new, "baselined": old,
    "suppressed": pragma'd}`` (each a list of :class:`Finding`). The CI
    gate fails iff ``findings`` is non-empty."""
    cfg = cfg or AnalysisConfig()
    index = index or ProjectIndex.from_config(root, cfg)
    registry = all_checkers()
    selected = list(checkers) if checkers else list(registry)
    unknown = [name for name in selected if name not in registry]
    if unknown:
        raise ValueError(f"unknown checker(s): {unknown}")
    raw: list[Finding] = []
    for name in selected:
        raw.extend(registry[name](index, cfg))
    raw.sort(key=lambda f: (f.file, f.line, f.checker, f.key))
    baseline = baseline or set()
    new: list[Finding] = []
    old: list[Finding] = []
    suppressed: list[Finding] = []
    for finding in raw:
        if _pragma_suppressed(index, finding):
            suppressed.append(finding)
        elif finding.fingerprint in baseline:
            old.append(finding)
        else:
            new.append(finding)
    return {"findings": new, "baselined": old, "suppressed": suppressed}
