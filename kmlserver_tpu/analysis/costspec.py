"""Checker 8 — kernel cost-spec registry (ISSUE 12).

The cost-attribution layer (observability/costmodel.py) derives MFU and
roofline classifications from analytic FLOPs/bytes specs, one per jitted
kernel. That only stays true if the spec registry and the dispatch sites
cannot drift: a kernel observed without a spec silently attributes zero
work (the runtime counts it as ``kmls_costmodel_unspecced_total``, but
nothing fails), and a spec nothing observes is a dead formula a reviewer
will trust anyway. This checker closes both directions statically:

- every ``observe_kernel("<name>", ...)`` call site anywhere in the
  analyzed tree must name a key of ``KERNEL_COST_SPECS``;
- every registry key must have at least one observe site (orphans are
  warnings — a mining-side spec consumed only via ``phase_cost`` keeps
  itself alive through the required-anchor list below);
- ``phase_cost("<name>", ...)`` call sites are held to the same
  membership rule (they KeyError at runtime — this catches it in CI);
- a non-literal kernel name is flagged: the registry contract is only
  checkable when the name is visible at the call site (forwarding
  helpers carry a pragma);
- the REQUIRED kernel names (the dispatched jitted kernels: replicated/
  sharded/native serve, embed top-k, ALS sweep, support count, delta
  recount) must all be registered — the anchor that keeps a rename from
  silently hollowing the checker;
- every ``kmls_*`` series the cost model renders must be declared in
  ``serving.metrics.METRIC_REGISTRY`` (the metrics checker covers the
  file too; this keeps the invariant named even if the exposition-file
  list drifts).
"""

from __future__ import annotations

import ast

from .core import (
    SEVERITY_ERROR,
    SEVERITY_WARN,
    AnalysisConfig,
    Finding,
    ProjectIndex,
)
from .metricsreg import (
    _CHILD_SUFFIXES,
    _iter_series_literals,
    parse_metric_registry,
)

# call names whose FIRST positional argument is a cost-spec kernel name
_SPEC_CALLS = ("observe_kernel", "phase_cost")


def parse_cost_specs(
    index: ProjectIndex, cfg: AnalysisConfig
) -> tuple[dict[str, int], int]:
    """``KERNEL_COST_SPECS = {...}`` parsed WITHOUT importing →
    (kernel name -> line, registry line; empty when absent)."""
    mod = index.modules.get(cfg.costmodel_file)
    if mod is None:
        return {}, 0
    for node in mod.tree.body:
        target = None
        value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        if (
            isinstance(target, ast.Name)
            and target.id == cfg.costspec_registry_name
            and isinstance(value, ast.Dict)
        ):
            out = {
                k.value: k.lineno
                for k in value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
            return out, node.lineno
    return {}, 0


def collect_observe_sites(
    index: ProjectIndex,
) -> tuple[dict[str, list[tuple[str, int]]], list[tuple[str, int, str]]]:
    """Scan every module for cost-spec call sites →
    (kernel name -> [(file, line)], unresolvable sites as
    (file, line, call name)). A site is any call to one of
    ``observe_kernel`` / ``phase_cost`` / ``timed_observation`` — as a
    method or a bare imported name — whose kernel argument is the first
    positional: a string literal resolves, anything else is
    unresolvable (pragma-suppressed where forwarding is the point)."""
    sites: dict[str, list[tuple[str, int]]] = {}
    unresolved: list[tuple[str, int, str]] = []
    for relpath, mod in index.modules.items():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Attribute):
                name = func.attr
            elif isinstance(func, ast.Name):
                name = func.id
            if name not in _SPEC_CALLS:
                continue
            if not node.args:
                # keyword-only spelling: treat as unresolvable — the
                # contract is a visible literal first argument
                unresolved.append((relpath, node.lineno, name))
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                sites.setdefault(first.value, []).append(
                    (relpath, node.lineno)
                )
            else:
                unresolved.append((relpath, node.lineno, name))
    return sites, unresolved


def run(index: ProjectIndex, cfg: AnalysisConfig) -> list[Finding]:
    specs, reg_line = parse_cost_specs(index, cfg)
    findings: list[Finding] = []
    if not specs:
        findings.append(
            Finding(
                checker="costspec",
                severity=SEVERITY_ERROR,
                file=cfg.costmodel_file,
                line=1,
                key="registry-missing",
                message=(
                    f"no `{cfg.costspec_registry_name}` dict found in "
                    f"{cfg.costmodel_file}; every dispatched jitted "
                    "kernel needs an analytic cost spec there"
                ),
            )
        )
        return findings

    sites, unresolved = collect_observe_sites(index)

    for name in sorted(sites):
        if name not in specs:
            relpath, line = sites[name][0]
            findings.append(
                Finding(
                    checker="costspec",
                    severity=SEVERITY_ERROR,
                    file=relpath,
                    line=line,
                    key=f"unregistered:{name}",
                    message=(
                        f"kernel `{name}` is observed/attributed here "
                        "but has no entry in "
                        f"costmodel.{cfg.costspec_registry_name} — its "
                        "dispatches would attribute ZERO flops/bytes "
                        "(kmls_costmodel_unspecced_total); register an "
                        "analytic spec"
                    ),
                )
            )
    for name in sorted(specs):
        if name not in sites:
            findings.append(
                Finding(
                    checker="costspec",
                    severity=SEVERITY_WARN,
                    file=cfg.costmodel_file,
                    line=specs[name],
                    key=f"orphan:{name}",
                    message=(
                        f"cost spec `{name}` has no observe_kernel/"
                        "phase_cost call site anywhere — remove the "
                        "spec or wire the dispatch up"
                    ),
                )
            )
    for relpath, line, call in unresolved:
        findings.append(
            Finding(
                checker="costspec",
                severity=SEVERITY_WARN,
                file=relpath,
                line=line,
                key=f"unresolvable:{relpath}:{call}",
                message=(
                    f"`{call}` called with a non-literal kernel name — "
                    "the spec-registry contract is only checkable when "
                    "the name is visible at the call site (forwarding "
                    "helpers carry a `# kmls-verify: allow[costspec]` "
                    "pragma)"
                ),
            )
        )
    for name in cfg.costspec_required:
        if name not in specs:
            findings.append(
                Finding(
                    checker="costspec",
                    severity=SEVERITY_ERROR,
                    file=cfg.costmodel_file,
                    line=reg_line,
                    key=f"required-missing:{name}",
                    message=(
                        f"required kernel `{name}` (a dispatched jitted "
                        "kernel) has no cost spec in "
                        f"{cfg.costspec_registry_name} — a rename must "
                        "update the checker config, not hollow the "
                        "registry"
                    ),
                )
            )

    # every series the cost model renders must be in METRIC_REGISTRY —
    # the metrics checker enforces this too (costmodel.py is one of its
    # exposition files); repeating it HERE keeps checker 8 sound even if
    # that file list drifts
    entries, _lines, _reg = parse_metric_registry(index, cfg)
    mod = index.modules.get(cfg.costmodel_file)
    if entries and mod is not None:
        seen: set[str] = set()
        for series, line in _iter_series_literals(mod.tree):
            if series in seen or any(
                series.endswith(sfx) for sfx in _CHILD_SUFFIXES
            ):
                continue
            seen.add(series)
            if series not in entries:
                findings.append(
                    Finding(
                        checker="costspec",
                        severity=SEVERITY_ERROR,
                        file=cfg.costmodel_file,
                        line=line,
                        key=f"series-unregistered:{series}",
                        message=(
                            f"cost-model series `{series}` is not "
                            "declared in metrics.METRIC_REGISTRY"
                        ),
                    )
                )
    return findings
