"""Checker 11 — env reads at import time or under a jit trace.

PR 12's bug class: a ``KMLS_*`` knob read at module import time (or,
worse, inside a ``jax.jit``-traced function) freezes its value — into
the process for import-time reads, into the compiled artifact for
traced reads — so flipping the env var later silently does nothing.
The project contract is that knobs are read LAZILY through the
``config._getenv_*`` helpers at call time, from untraced code.

Two sweeps, both pure-AST:

- **import time** — ``os.getenv`` / ``os.environ.get`` /
  ``os.environ[...]`` / any configured project helper called at module
  scope (class bodies and module-level ``if``/``try`` blocks included;
  function bodies excluded — they run later).
- **jit-traced** — the same reads inside any function reachable from a
  jit root. Roots are detected structurally: ``@jax.jit`` and
  ``@partial(jax.jit, …)`` decorators, module-level ``name =
  jax.jit(impl)`` / ``name = partial(jax.jit, …)(impl)`` wrappings, and
  in-function ``jax.jit(fn)`` calls with a resolvable target — the
  shapes the ``ops/`` and ``parallel/`` kernels actually use (the
  anchor test pins that these roots keep existing). Reachability rides
  the conservative project call graph.

Findings whose literal names a registered knob carry its
``KNOB_REGISTRY`` scope, cross-checked via the knobs checker's parser,
so the message says exactly which declared knob just got frozen.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .callgraph import CallGraph, _dotted_name, resolve_func_ref
from .core import (
    SEVERITY_ERROR,
    AnalysisConfig,
    Finding,
    ModuleInfo,
    ProjectIndex,
)
from .registries import parse_knob_registry


def _canon_dotted(mod: ModuleInfo, dotted: str) -> str:
    """Canonicalize the leading alias segment through the module's
    external imports ("getenv" -> "os.getenv", "environ.get" ->
    "os.environ.get")."""
    root, _, rest = dotted.partition(".")
    ext = mod.external_imports.get(root)
    if ext:
        return f"{ext}.{rest}" if rest else ext
    return dotted


def _literal_arg(node: ast.Call) -> str | None:
    if node.args:
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return str(first.value)
    return None


def _env_read(
    index: ProjectIndex,
    mod: ModuleInfo,
    node: ast.AST,
    helpers: frozenset[str],
) -> tuple[str, str | None] | None:
    """→ (construct, env-var literal or None) when ``node`` reads the
    environment; None otherwise."""
    if isinstance(node, ast.Subscript):
        dotted = _dotted_name(node.value)
        if dotted and _canon_dotted(mod, dotted) == "os.environ":
            name: str | None = None
            sub = node.slice
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                name = sub.value
            return "os.environ[...]", name
        return None
    if not isinstance(node, ast.Call):
        return None
    dotted = _dotted_name(node.func)
    if dotted is not None:
        canon = _canon_dotted(mod, dotted)
        if canon in ("os.getenv", "os.environ.get"):
            return canon, _literal_arg(node)
    # project helper call: same-module def or "from config import helper"
    if isinstance(node.func, ast.Name):
        name = node.func.id
        ref = None
        if (mod.relpath, name) in index.functions:
            ref = f"{mod.relpath}::{name}"
        elif name in mod.name_imports:
            src_rel, src_name = mod.name_imports[name]
            ref = f"{src_rel}::{src_name}"
        if ref is not None and ref in helpers:
            return f"{name}()", _literal_arg(node)
    return None


def _module_scope_nodes(mod: ModuleInfo) -> Iterator[ast.AST]:
    """Every node that executes at import time: the module body,
    descending through class bodies and control flow but NEVER into
    function/lambda bodies."""
    stack: list[ast.AST] = list(mod.tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_jit_expr(mod: ModuleInfo, node: ast.AST) -> bool:
    """True for ``jax.jit`` and ``partial(jax.jit, …)`` expressions."""
    dotted = _dotted_name(node)
    if dotted is not None and _canon_dotted(mod, dotted) == "jax.jit":
        return True
    if isinstance(node, ast.Call):
        func = _dotted_name(node.func)
        if func is not None and _canon_dotted(mod, func) in (
            "functools.partial",
            "partial",
        ):
            return bool(node.args) and _is_jit_expr(mod, node.args[0])
    return False


def jit_roots(index: ProjectIndex) -> dict[str, str]:
    """Function refs whose bodies are traced by jax.jit (see module
    docstring for the recognized shapes) → why."""
    roots: dict[str, str] = {}
    for info in index.functions.values():
        mod = index.modules[info.relpath]
        node = info.node
        decorators = getattr(node, "decorator_list", [])
        for dec in decorators:
            if _is_jit_expr(mod, dec):
                roots.setdefault(info.ref, "jit-decorated")
        # in-function jax.jit(fn) / partial(jax.jit, …)(fn) wrappings
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Call) and sub.args):
                continue
            if _is_jit_expr(mod, sub.func) and not isinstance(
                sub.func, ast.Call
            ):
                # direct jax.jit(fn)
                ref = resolve_func_ref(index, info, sub.args[0])
                if ref:
                    roots.setdefault(
                        ref, f"jit-wrapped in `{info.qualname}`"
                    )
            elif isinstance(sub.func, ast.Call) and _is_jit_expr(
                mod, sub.func
            ):
                # partial(jax.jit, …)(fn)
                ref = resolve_func_ref(index, info, sub.args[0])
                if ref:
                    roots.setdefault(
                        ref, f"jit-wrapped in `{info.qualname}`"
                    )
    # module-level wrappings: name = jax.jit(impl) & co
    for relpath, mod in index.modules.items():
        for node in _module_scope_nodes(mod):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            if not _is_jit_expr(mod, node.func):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                info2 = index.functions.get((relpath, arg.id))
                if info2 is None and arg.id in mod.name_imports:
                    info2 = index.functions.get(mod.name_imports[arg.id])
                if info2 is not None:
                    roots.setdefault(
                        info2.ref, "jit-wrapped at module level"
                    )
    return roots


def _knob_note(
    name: str | None, knob_scopes: dict[str, str], prefix: str
) -> str:
    if name is None:
        return ""
    if name in knob_scopes:
        return (
            f" `{name}` is a registered {knob_scopes[name]}-scope knob —"
            " flipping it after this read silently does nothing."
        )
    if name.startswith(prefix):
        return f" `{name}` is not in KNOB_REGISTRY."
    return ""


def run(index: ProjectIndex, cfg: AnalysisConfig) -> list[Finding]:
    helpers = frozenset(cfg.envread_helper_functions)
    knob_scopes, _lines, _reg_line = parse_knob_registry(index, cfg)
    findings: list[Finding] = []

    # sweep 1: import-time reads (the config module itself is exempt —
    # its helpers' bodies are functions anyway, and its registry is data)
    for relpath in sorted(index.modules):
        mod = index.modules[relpath]
        for node in _module_scope_nodes(mod):
            hit = _env_read(index, mod, node, helpers)
            if hit is None:
                continue
            construct, name = hit
            findings.append(
                Finding(
                    checker="envread",
                    severity=SEVERITY_ERROR,
                    file=relpath,
                    line=getattr(node, "lineno", 0),
                    key=f"import-time:{name or construct}",
                    message=(
                        f"environment read `{construct}` at module "
                        "import time: the value freezes when the module "
                        "first loads, defeating lazy knob reads (PR 12 "
                        "bug class) — move it into the function that "
                        f"needs it.{_knob_note(name, knob_scopes, cfg.knob_prefix)}"
                    ),
                )
            )

    # sweep 2: reads inside jit-traced functions
    graph = CallGraph(index)
    roots = jit_roots(index)
    paths = graph.reachable(roots)
    for ref in sorted(paths):
        info = index.function(ref)
        if info is None:
            continue
        mod = index.modules[info.relpath]
        for node in ast.walk(info.node):
            hit = _env_read(index, mod, node, helpers)
            if hit is None:
                continue
            construct, name = hit
            path = paths[ref]
            via = " -> ".join(p.split("::", 1)[1] for p in path)
            reason = roots.get(path[0], "jit root")
            findings.append(
                Finding(
                    checker="envread",
                    severity=SEVERITY_ERROR,
                    file=info.relpath,
                    line=getattr(node, "lineno", 0),
                    key=f"jit:{name or construct}@{info.qualname}",
                    message=(
                        f"environment read `{construct}` inside "
                        f"jit-traced `{info.qualname}` (traced via "
                        f"{via}; root is {reason}): the value bakes "
                        "into the compiled artifact at first trace — "
                        "read it at call time and pass it as an "
                        f"argument.{_knob_note(name, knob_scopes, cfg.knob_prefix)}"
                    ),
                )
            )
    return findings
