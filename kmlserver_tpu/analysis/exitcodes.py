"""Checker 6 — exit-code contract vs. podFailurePolicy.

PR 4's contract: ``mining/job.py`` exits 0 (success), 64 (fatal config —
retrying burns TPU quota for the same failure), 75 (resumable — a
checkpoint restart makes progress) or 76 (dead-rank watchdog abort, also
resumable). The Kubernetes Job manifests encode the SAME policy as
``podFailurePolicy`` rules: FailJob on 64, Ignore on 75/76. Nothing ties
the two files together — an edit to either silently rots the other (a
new resumable code the manifest doesn't Ignore burns ``backoffLimit`` on
preemptions; a manifest Ignoring a code the job treats as fatal retries
a job that can never succeed). This checker diffs them.

The manifest side is parsed with a deliberately small line-based reader
(no yaml dependency in the analyzer): it tracks ``action:`` context and
collects the ``values: [..]`` lists under ``onExitCodes``. It also
verifies ``restartPolicy: Never`` — podFailurePolicy requires it, and a
kubelet-local restart would bypass the policy entirely.
"""

from __future__ import annotations

import ast
import os
import re

from .core import SEVERITY_ERROR, AnalysisConfig, Finding, ProjectIndex

_VALUES_RE = re.compile(r"values:\s*\[([0-9,\s]+)\]")
_ACTION_RE = re.compile(r"action:\s*(\w+)")


def parse_job_contract(
    index: ProjectIndex, cfg: AnalysisConfig
) -> tuple[dict[str, int], set[int]] | None:
    """→ ({EXIT_* name: code}, retryable codes) from mining/job.py."""
    mod = index.modules.get(cfg.job_file)
    if mod is None:
        return None
    consts: dict[str, int] = {}
    retryable_names: list[str] = []
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            if target.id.startswith("EXIT_") and isinstance(
                node.value, ast.Constant
            ):
                if isinstance(node.value.value, int):
                    consts[target.id] = node.value.value
            elif target.id == "RETRYABLE_EXIT_CODES" and isinstance(
                node.value, (ast.Tuple, ast.List)
            ):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Name):
                        retryable_names.append(elt.id)
                    elif isinstance(elt, ast.Constant) and isinstance(
                        elt.value, int
                    ):
                        consts[f"_literal_{elt.value}"] = elt.value
                        retryable_names.append(f"_literal_{elt.value}")
    if not consts:
        return None
    retryable = {consts[n] for n in retryable_names if n in consts}
    return consts, retryable


def parse_pod_failure_policy(text: str) -> dict[str, set[int]]:
    """action name -> exit-code set, from the manifest's podFailurePolicy
    block(s)."""
    out: dict[str, set[int]] = {}
    action: str | None = None
    for line in text.splitlines():
        stripped = line.split("#", 1)[0]
        m = _ACTION_RE.search(stripped)
        if m:
            action = m.group(1)
        m = _VALUES_RE.search(stripped)
        if m and action:
            codes = {
                int(v) for v in m.group(1).replace(",", " ").split() if v
            }
            out.setdefault(action, set()).update(codes)
    return out


def run(index: ProjectIndex, cfg: AnalysisConfig) -> list[Finding]:
    findings: list[Finding] = []
    contract = parse_job_contract(index, cfg)
    if contract is None:
        findings.append(
            Finding(
                checker="exit-codes",
                severity=SEVERITY_ERROR,
                file=cfg.job_file,
                line=1,
                key="contract-missing",
                message=(
                    f"could not parse EXIT_* constants / "
                    f"RETRYABLE_EXIT_CODES from {cfg.job_file}"
                ),
            )
        )
        return findings
    consts, retryable = contract
    # fatal = every declared non-zero exit code that is NOT retryable —
    # derived, not name-matched, so (a) a new fatal code (EXIT_FATAL_DATA
    # = 65) correctly demands a FailJob rule, and (b) a new code that is
    # neither fatal-classified nor in RETRYABLE_EXIT_CODES still shows up
    # as a mismatch instead of silently burning backoffLimit
    fatal = {
        code
        for code in consts.values()
        if code != 0 and code not in retryable
    }
    for manifest in cfg.job_manifests:
        path = os.path.join(index.root, manifest)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            findings.append(
                Finding(
                    checker="exit-codes",
                    severity=SEVERITY_ERROR,
                    file=manifest,
                    line=1,
                    key="manifest-missing",
                    message=f"job manifest {manifest} not found",
                )
            )
            continue
        policy = parse_pod_failure_policy(text)
        if not policy:
            findings.append(
                Finding(
                    checker="exit-codes",
                    severity=SEVERITY_ERROR,
                    file=manifest,
                    line=1,
                    key="policy-missing",
                    message=(
                        f"{manifest} has no parseable podFailurePolicy; "
                        "the 0/64/75/76 exit contract must be bound here"
                    ),
                )
            )
            continue
        fail_job = policy.get("FailJob", set())
        ignore = policy.get("Ignore", set())
        if fail_job != fatal:
            findings.append(
                Finding(
                    checker="exit-codes",
                    severity=SEVERITY_ERROR,
                    file=manifest,
                    line=1,
                    key=f"failjob-mismatch:{sorted(fail_job)}!={sorted(fatal)}",
                    message=(
                        f"{manifest} FailJob codes {sorted(fail_job)} != "
                        f"job.py's non-retryable EXIT_* codes "
                        f"{sorted(fatal)}; a fatal exit the policy "
                        "doesn't FailJob on retries a job that can never "
                        "succeed (and vice versa)"
                    ),
                )
            )
        if ignore != retryable:
            findings.append(
                Finding(
                    checker="exit-codes",
                    severity=SEVERITY_ERROR,
                    file=manifest,
                    line=1,
                    key=(
                        f"ignore-mismatch:{sorted(ignore)}"
                        f"!={sorted(retryable)}"
                    ),
                    message=(
                        f"{manifest} Ignore codes {sorted(ignore)} != "
                        f"job.py RETRYABLE_EXIT_CODES "
                        f"{sorted(retryable)}; a resumable exit the "
                        "policy counts against backoffLimit turns "
                        "preemptions into Job failures"
                    ),
                )
            )
        if "restartPolicy: Never" not in text:
            findings.append(
                Finding(
                    checker="exit-codes",
                    severity=SEVERITY_ERROR,
                    file=manifest,
                    line=1,
                    key="restart-policy",
                    message=(
                        f"{manifest} must set `restartPolicy: Never` — "
                        "podFailurePolicy requires it, and kubelet-local "
                        "container restarts would bypass the exit-code "
                        "policy entirely"
                    ),
                )
            )
    return findings
