"""Checker 1 — hot-path purity.

PR 1's serving contract: the dispatch path (batcher admission/collection
→ engine replica dispatch) never compiles, never host-syncs, never does
file I/O, never sleeps. A compile or a blocking transfer on this path is
a multi-millisecond tail landing inside every request of a batch — the
exact regression class PR 1's bucketed pre-warming eliminated (p99 51.2
→ 7.2 ms). Runtime evidence exists (the compile-counter test, the
unwarmed-dispatch counter) but only fires AFTER a bad diff ships; this
checker rejects the diff.

Mechanics: BFS the call graph from the configured dispatch entry points
(``AnalysisConfig.hotpath_entries``) and flag every forbidden construct
(``time.sleep``, ``open``, ``np.asarray``, ``jax.jit``,
``block_until_ready``, ``.item()``, ``.result()``, pickle/json file I/O,
…) in any reachable function body. Completion-side closures — the
``finish()`` callables, which block on the device BY DESIGN — never join
the graph because nested defs are only traversed where they are visibly
called (see callgraph module docstring).
"""

from __future__ import annotations

from .callgraph import CallGraph, match_forbidden
from .core import SEVERITY_ERROR, AnalysisConfig, Finding, ProjectIndex


def run(index: ProjectIndex, cfg: AnalysisConfig) -> list[Finding]:
    graph = CallGraph(index)
    paths = graph.reachable(cfg.hotpath_entries)
    findings: list[Finding] = []
    for ref, path in paths.items():
        info = index.function(ref)
        if info is None:
            continue
        for site in graph.sites(ref):
            construct = match_forbidden(
                site,
                cfg.hotpath_forbidden_calls,
                cfg.hotpath_forbidden_methods,
            )
            if construct is None:
                continue
            via = " -> ".join(p.split("::", 1)[1] for p in path)
            findings.append(
                Finding(
                    checker="hotpath",
                    severity=SEVERITY_ERROR,
                    file=info.relpath,
                    line=site.line,
                    key=f"{construct}@{info.qualname}",
                    message=(
                        f"host-sync/blocking construct `{construct}` in "
                        f"`{info.qualname}`, reachable from the serving "
                        f"dispatch path ({via}); compiles, host syncs, "
                        "file I/O and sleeps are forbidden here — move it "
                        "off the dispatch path (publication/completion "
                        "side) or justify with a pragma/baseline entry"
                    ),
                )
            )
    return findings
