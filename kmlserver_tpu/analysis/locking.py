"""Checker 2 — lock-acquisition order and blocking-under-lock.

The serving tier holds 16+ ``threading.Lock``/``Condition`` instances
(batcher, engine, cache, metrics, faults, nativelib, watchdog, HTTP
server). Two invariants keep them deadlock- and tail-free, both
documented in code comments today and enforced only by load tests:

- **acyclic acquisition order** — e.g. the cache's singleflight calls
  the batcher's admission UNDER the cache lock (documented as safe
  because the batcher never calls back into the cache); the inverse
  edge appearing anywhere would be an AB/BA deadlock at QPS.
- **no blocking under a hot-path lock** — a ``time.sleep``, file open,
  ``Future.result`` or device sync while holding a lock on the request
  path serializes every concurrent request behind one slow operation
  (the GIL makes this WORSE than a plain stall: waiters burn sched
  wakeups). The reload lock is deliberately exempt — the reload path is
  cold and does file I/O under it by design.

Mechanics:

- **lock discovery**: ``self.<attr> = threading.Lock()/RLock()/
  Condition(...)`` in any method, and module-level ``<name> =
  threading.Lock()``. A ``Condition(self.<lock>)`` ALIASES the wrapped
  lock — acquiring the condition is acquiring that lock.
- **acquisition**: ``with <lock-expr>:`` over a discovered lock
  (``self.x``, module-global ``x``, or ``<anything>.x`` when the attr
  name is unique among discovered locks).
- **order edges**: lock A → lock B when B is acquired inside A's
  ``with`` body, directly or through resolved project calls (fixpoint
  over the call graph). Cycles are reported once per cycle set.
- **blocking**: a configured blocking construct inside a HOT lock's
  body, directly or through resolved calls (``Condition.wait`` is
  allowed: it releases the lock).
"""

from __future__ import annotations

import ast
import dataclasses

from .callgraph import CallGraph, match_forbidden, resolve_call
from .core import (
    SEVERITY_ERROR,
    AnalysisConfig,
    Finding,
    FunctionInfo,
    ProjectIndex,
)

_LOCK_CTORS = ("Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore")


@dataclasses.dataclass(frozen=True)
class LockId:
    owner: str  # class name, or "<relpath>" for module-level locks
    attr: str

    def render(self) -> str:
        return f"{self.owner}.{self.attr}"


@dataclasses.dataclass
class _FuncLockFacts:
    # locks this function acquires in its own body (outermost only —
    # nested ones are reported as order edges, not as direct acquires)
    acquires: set[LockId] = dataclasses.field(default_factory=set)
    # (held lock, acquired lock, line) order edges from this body
    edges: set[tuple[LockId, LockId, int]] = dataclasses.field(
        default_factory=set
    )
    # (held lock, construct, line) blocking sites from this body
    blocking: set[tuple[LockId, str, int]] = dataclasses.field(
        default_factory=set
    )
    # (held lock, callee ref, line): calls made while holding a lock
    held_calls: set[tuple[LockId, str, int]] = dataclasses.field(
        default_factory=set
    )


def _is_threading_lock_ctor(node: ast.AST) -> str | None:
    """→ ctor name when ``node`` is ``threading.X(...)``/bare ``X(...)``
    with X a lock constructor."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    name = None
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        if func.value.id == "threading":
            name = func.attr
    elif isinstance(func, ast.Name):
        name = func.id
    return name if name in _LOCK_CTORS else None


def discover_locks(
    index: ProjectIndex,
) -> tuple[set[LockId], dict[LockId, LockId]]:
    """→ (locks, aliases). ``aliases`` maps a Condition built over
    another discovered lock onto that lock."""
    locks: set[LockId] = set()
    pending_alias: dict[LockId, tuple[str, str]] = {}
    for (relpath, _qual), info in index.functions.items():
        if info.class_name is None:
            continue
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            ctor = _is_threading_lock_ctor(node.value)
            if ctor is None:
                continue
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                lock = LockId(info.class_name, target.attr)
                locks.add(lock)
                if ctor == "Condition" and node.value.args:
                    arg = node.value.args[0]
                    if (
                        isinstance(arg, ast.Attribute)
                        and isinstance(arg.value, ast.Name)
                        and arg.value.id == "self"
                    ):
                        pending_alias[lock] = (info.class_name, arg.attr)
    for relpath, mod in index.modules.items():
        for node in mod.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and _is_threading_lock_ctor(node.value)
            ):
                locks.add(LockId(relpath, node.targets[0].id))
    aliases = {
        cond: LockId(owner, attr)
        for cond, (owner, attr) in pending_alias.items()
        if LockId(owner, attr) in locks
    }
    return locks, aliases


class _LockWalker:
    """Per-function walk tracking the ``with``-lock stack."""

    def __init__(
        self,
        index: ProjectIndex,
        info: FunctionInfo,
        locks: set[LockId],
        aliases: dict[LockId, LockId],
        cfg: AnalysisConfig,
    ):
        self.index = index
        self.info = info
        self.locks = locks
        self.aliases = aliases
        self.cfg = cfg
        self.facts = _FuncLockFacts()
        # attr name -> lock, for unique-attr resolution on unknown
        # receivers (`self.server.active_lock`)
        by_attr: dict[str, list[LockId]] = {}
        for lock in locks:
            by_attr.setdefault(lock.attr, []).append(lock)
        self.unique_attr = {
            attr: ls[0] for attr, ls in by_attr.items() if len(ls) == 1
        }

    def _lock_of(self, node: ast.AST) -> LockId | None:
        lock: LockId | None = None
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and self.info.class_name
            ):
                cand = LockId(self.info.class_name, node.attr)
                if cand in self.locks:
                    lock = cand
            if lock is None:
                lock = self.unique_attr.get(node.attr)
        elif isinstance(node, ast.Name):
            cand = LockId(self.info.relpath, node.id)
            if cand in self.locks:
                lock = cand
        if lock is not None:
            lock = self.aliases.get(lock, lock)
        return lock

    def walk(self) -> _FuncLockFacts:
        self._visit_body(list(ast.iter_child_nodes(self.info.node)), [])
        return self.facts

    def _visit_body(self, nodes: list[ast.AST], held: list[LockId]) -> None:
        for node in nodes:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.With):
                acquired: list[LockId] = []
                for item in node.items:
                    lock = self._lock_of(item.context_expr)
                    if lock is not None and lock not in held:
                        acquired.append(lock)
                for lock in acquired:
                    if not held:
                        self.facts.acquires.add(lock)
                    for holder in held:
                        self.facts.edges.add((holder, lock, node.lineno))
                self._visit_body(list(node.body), held + acquired)
                # with-items' own expressions still need call scanning
                for item in node.items:
                    self._scan_expr(item.context_expr, held)
                continue
            if isinstance(node, ast.Call):
                self._scan_call(node, held)
            self._visit_body(list(ast.iter_child_nodes(node)), held)

    def _scan_expr(self, node: ast.AST, held: list[LockId]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._scan_call(sub, held)

    def _scan_call(self, node: ast.Call, held: list[LockId]) -> None:
        if not held:
            return
        site = resolve_call(self.index, self.info, node)
        # Condition.wait releases the lock while blocked — not a block
        if site.method == "wait":
            return
        construct = match_forbidden(
            site, self.cfg.locks_blocking_calls, self.cfg.locks_blocking_methods
        )
        for holder in held:
            if construct is not None:
                self.facts.blocking.add((holder, construct, node.lineno))
            if site.target is not None:
                self.facts.held_calls.add((holder, site.target, node.lineno))


def run(index: ProjectIndex, cfg: AnalysisConfig) -> list[Finding]:
    locks, aliases = discover_locks(index)
    graph = CallGraph(index)
    facts: dict[str, _FuncLockFacts] = {}
    for (relpath, qual), info in index.functions.items():
        facts[info.ref] = _LockWalker(index, info, locks, aliases, cfg).walk()

    # interprocedural fixpoint: what may each function acquire / block
    # on, transitively through resolved project calls?
    trans_acquires: dict[str, set[LockId]] = {
        ref: set(f.acquires) for ref, f in facts.items()
    }
    trans_blocking: dict[str, set[str]] = {
        ref: {c for _h, c, _l in f.blocking} for ref, f in facts.items()
    }
    # also: blocking constructs in a function body OUTSIDE any lock still
    # block a caller that holds one
    for ref in facts:
        info = index.function(ref)
        if info is None:
            continue
        for site in graph.sites(ref):
            if site.method == "wait":
                continue
            construct = match_forbidden(
                site, cfg.locks_blocking_calls, cfg.locks_blocking_methods
            )
            if construct is not None:
                trans_blocking[ref].add(construct)
    changed = True
    while changed:
        changed = False
        for ref in facts:
            for site in graph.sites(ref):
                tgt = site.target
                if tgt is None or tgt not in facts:
                    continue
                if not trans_acquires[tgt] <= trans_acquires[ref]:
                    trans_acquires[ref] |= trans_acquires[tgt]
                    changed = True
                if not trans_blocking[tgt] <= trans_blocking[ref]:
                    trans_blocking[ref] |= trans_blocking[tgt]
                    changed = True

    hot = _parse_hot_locks(cfg)
    findings: list[Finding] = []
    edges: set[tuple[LockId, LockId]] = set()
    edge_sites: dict[tuple[LockId, LockId], tuple[str, int]] = {}

    for ref, f in facts.items():
        info = index.function(ref)
        if info is None:
            continue
        # direct nested-with edges
        for holder, acquired, line in f.edges:
            edges.add((holder, acquired))
            edge_sites.setdefault((holder, acquired), (info.relpath, line))
        # interprocedural edges + blocking through calls
        for holder, callee, line in f.held_calls:
            for acquired in trans_acquires.get(callee, ()):
                if acquired != holder:
                    edges.add((holder, acquired))
                    edge_sites.setdefault(
                        (holder, acquired), (info.relpath, line)
                    )
            if holder in hot:
                callee_info = index.function(callee)
                for construct in sorted(trans_blocking.get(callee, ())):
                    findings.append(
                        Finding(
                            checker="locks",
                            severity=SEVERITY_ERROR,
                            file=info.relpath,
                            line=line,
                            key=(
                                f"block:{holder.render()}:{construct}"
                                f"@{info.qualname}"
                            ),
                            message=(
                                f"`{info.qualname}` calls "
                                f"`{callee_info.qualname if callee_info else callee}`"
                                f" while holding hot-path lock "
                                f"{holder.render()}, and that call may "
                                f"block on `{construct}`; blocking under "
                                "a hot lock serializes every concurrent "
                                "request behind one slow operation"
                            ),
                        )
                    )
        # direct blocking under a hot lock
        for holder, construct, line in f.blocking:
            if holder in hot:
                findings.append(
                    Finding(
                        checker="locks",
                        severity=SEVERITY_ERROR,
                        file=info.relpath,
                        line=line,
                        key=f"block:{holder.render()}:{construct}@{info.qualname}",
                        message=(
                            f"blocking construct `{construct}` while "
                            f"holding hot-path lock {holder.render()} in "
                            f"`{info.qualname}`; move the blocking work "
                            "outside the critical section"
                        ),
                    )
                )

    findings.extend(_cycle_findings(edges, edge_sites))
    # de-dup by fingerprint+line (fixpoint can re-derive the same fact)
    seen: set[tuple[str, int]] = set()
    unique: list[Finding] = []
    for f in findings:
        ident = (f.fingerprint, f.line)
        if ident not in seen:
            seen.add(ident)
            unique.append(f)
    return unique


def _parse_hot_locks(cfg: AnalysisConfig) -> set[LockId]:
    hot: set[LockId] = set()
    for spec in cfg.hot_locks:
        if "::" in spec:
            relpath, _, name = spec.partition("::")
            hot.add(LockId(relpath, name))
        else:
            owner, _, attr = spec.rpartition(".")
            hot.add(LockId(owner, attr))
    return hot


def _cycle_findings(
    edges: set[tuple[LockId, LockId]],
    edge_sites: dict[tuple[LockId, LockId], tuple[str, int]],
) -> list[Finding]:
    """DFS cycle detection over the acquisition-order graph; one finding
    per cycle, keyed by the sorted lock set so the fingerprint is stable
    whichever edge the walk enters through."""
    graph: dict[LockId, set[LockId]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    findings: list[Finding] = []
    reported: set[tuple[str, ...]] = set()
    for start in sorted(graph, key=lambda lock: lock.render()):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(
                graph.get(node, ()), key=lambda lock: lock.render()
            ):
                if nxt == start and len(path) > 1:
                    cycle = tuple(sorted(x.render() for x in path))
                    if cycle in reported:
                        continue
                    reported.add(cycle)
                    relpath, line = edge_sites.get(
                        (node, start), ("<unknown>", 0)
                    )
                    chain = " -> ".join(x.render() for x in path + [start])
                    findings.append(
                        Finding(
                            checker="locks",
                            severity=SEVERITY_ERROR,
                            file=relpath,
                            line=line,
                            key=f"cycle:{'|'.join(cycle)}",
                            message=(
                                f"lock-acquisition-order cycle: {chain} — "
                                "two threads taking these locks in "
                                "opposite orders deadlock; pick one "
                                "global order"
                            ),
                        )
                    )
                elif nxt not in path and len(path) < 8:
                    stack.append((nxt, path + [nxt]))
    return findings
