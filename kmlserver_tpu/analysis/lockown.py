"""Checker 10 — lock-ownership race inference.

The serving tier is full of classes whose methods run in DIFFERENT
execution contexts: the threaded batcher's collection loop vs the
asyncio loop, the iohealth monitor's PVC-thread writers vs the engine's
readers, the forecaster's per-request ``observe`` vs its actuator
reads. Each such class guards its mutable state with a lock — but
nothing today notices when one method quietly skips it. That is a data
race the GIL mostly hides until a torn read lands under load.

Mechanics — deliberately conservative, in the house style:

- Only classes that OWN at least one discovered lock
  (:func:`locking.discover_locks`) are examined: owning a lock is the
  author's own declaration that the class is shared across contexts.
  Deliberately lock-free classes (the loop-confined AsyncMicroBatcher,
  plain value objects) are structurally out of scope.
- A class's mutable fields are the ``self.<attr>`` names assigned in
  ``__init__`` (minus the locks themselves).
- Every ``self.<attr>`` read/write in every method is collected with
  the set of class-owned locks held at that point (``with``-stack walk,
  Condition aliases resolved, nested closures excluded — they run in
  whatever context invokes them).
- A field's OWNING lock is inferred by majority vote over its guarded
  accesses, but only when the evidence is convincing: at least
  ``cfg.lockown_min_guarded`` guarded accesses AND at least as many
  guarded as unguarded. Below that bar the field has no inferred owner
  and is never flagged — thin evidence must not manufacture races.
- Findings are UNGUARDED WRITES (outside ``__init__``) to a field with
  an inferred owner. Unguarded reads are not flagged: many are benign
  snapshot reads, and a write-path gate catches the mutations that
  actually tear.
- Methods named ``*_locked`` (``cfg.lockown_held_suffix``) are the
  repo's documented handoff convention — only ever called with the
  owning lock held — and are excluded from both the vote and the sweep.

Messages name the execution contexts the class's methods run in (from
:func:`callgraph.classify_contexts`) so the reviewer sees WHY the
unguarded write is cross-context reachable.
"""

from __future__ import annotations

import ast
import dataclasses

from .callgraph import classify_contexts
from .core import (
    SEVERITY_ERROR,
    AnalysisConfig,
    Finding,
    FunctionInfo,
    ProjectIndex,
)
from .locking import LockId, discover_locks


@dataclasses.dataclass(frozen=True)
class _Access:
    attr: str
    write: bool
    held: tuple[LockId, ...]  # class-owned locks held at the access
    method: str  # qualname of the accessing method
    line: int


class _FieldAccessWalker:
    """Per-method walk collecting ``self.<attr>`` accesses with the
    ``with``-lock stack, mirroring ``locking._LockWalker``'s
    resolution rules."""

    def __init__(
        self,
        index: ProjectIndex,
        info: FunctionInfo,
        locks: set[LockId],
        aliases: dict[LockId, LockId],
    ):
        self.index = index
        self.info = info
        self.locks = locks
        self.aliases = aliases
        self.accesses: list[_Access] = []
        by_attr: dict[str, list[LockId]] = {}
        for lock in locks:
            by_attr.setdefault(lock.attr, []).append(lock)
        self.unique_attr = {
            attr: ls[0] for attr, ls in by_attr.items() if len(ls) == 1
        }

    def _lock_of(self, node: ast.AST) -> LockId | None:
        lock: LockId | None = None
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and self.info.class_name
            ):
                cand = LockId(self.info.class_name, node.attr)
                if cand in self.locks:
                    lock = cand
            if lock is None:
                lock = self.unique_attr.get(node.attr)
        elif isinstance(node, ast.Name):
            cand = LockId(self.info.relpath, node.id)
            if cand in self.locks:
                lock = cand
        if lock is not None:
            lock = self.aliases.get(lock, lock)
        return lock

    def walk(self) -> list[_Access]:
        self._visit(list(ast.iter_child_nodes(self.info.node)), [])
        return self.accesses

    def _visit(self, nodes: list[ast.AST], held: list[LockId]) -> None:
        for node in nodes:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.With):
                acquired: list[LockId] = []
                for item in node.items:
                    lock = self._lock_of(item.context_expr)
                    if lock is not None and lock not in held:
                        acquired.append(lock)
                self._visit(list(node.body), held + acquired)
                continue
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                class_name = self.info.class_name or ""
                owned = tuple(
                    lock for lock in held if lock.owner == class_name
                )
                self.accesses.append(
                    _Access(
                        attr=node.attr,
                        write=isinstance(node.ctx, (ast.Store, ast.Del)),
                        held=owned,
                        method=self.info.qualname,
                        line=node.lineno,
                    )
                )
            self._visit(list(ast.iter_child_nodes(node)), held)


def _init_fields(index: ProjectIndex, class_name: str) -> set[str]:
    init = index.class_method(class_name, "__init__")
    if init is None:
        return set()
    fields: set[str] = set()
    for node in ast.walk(init.node):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for target in targets:
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                fields.add(target.attr)
    return fields


def run(index: ProjectIndex, cfg: AnalysisConfig) -> list[Finding]:
    locks, aliases = discover_locks(index)
    ctx = classify_contexts(index, cfg)
    lock_owners = {lock.owner for lock in locks} | {
        alias.owner for alias in aliases
    }
    findings: list[Finding] = []
    for class_name in sorted(lock_owners):
        relpath = index.classes.get(class_name)
        if relpath is None:
            continue  # module-level locks have a relpath "owner"
        lock_attrs = {
            lock.attr for lock in locks if lock.owner == class_name
        } | {
            cond.attr
            for cond, real in aliases.items()
            if cond.owner == class_name or real.owner == class_name
        }
        fields = _init_fields(index, class_name) - lock_attrs
        if not fields:
            continue
        methods = [
            info
            for (rel, _qual), info in sorted(index.functions.items())
            if rel == relpath
            and info.class_name == class_name
            and not info.qualname.endswith(".__init__")
            # `*_locked` methods run with the owning lock already held
            # (the repo's handoff convention) — out of scope both ways
            and not info.qualname.endswith(cfg.lockown_held_suffix)
        ]
        accesses: list[_Access] = []
        for info in methods:
            accesses.extend(
                _FieldAccessWalker(index, info, locks, aliases).walk()
            )
        class_contexts = sorted(
            {c for info in methods for c in ctx.contexts(info.ref)}
        ) or ["unclassified"]
        for field in sorted(fields):
            touches = [a for a in accesses if a.attr == field]
            guarded = [a for a in touches if a.held]
            unguarded = [a for a in touches if not a.held]
            if (
                len(guarded) < cfg.lockown_min_guarded
                or len(guarded) < len(unguarded)
            ):
                continue
            votes: dict[LockId, int] = {}
            for access in guarded:
                for lock in access.held:
                    votes[lock] = votes.get(lock, 0) + 1
            owner = max(
                sorted(votes, key=lambda lock: lock.render()),
                key=lambda lock: votes[lock],
            )
            for access in unguarded:
                if not access.write:
                    continue
                findings.append(
                    Finding(
                        checker="lockown",
                        severity=SEVERITY_ERROR,
                        file=relpath,
                        line=access.line,
                        key=f"unguarded:{field}@{access.method}",
                        message=(
                            f"unguarded write to `{class_name}.{field}` "
                            f"in `{access.method}`: {len(guarded)} other "
                            f"access(es) guard this field with "
                            f"{owner.render()}, and the class's methods "
                            f"run in {'/'.join(class_contexts)} "
                            "context(s) — take the owning lock, or "
                            "document the ownership handoff with a "
                            "pragma"
                        ),
                    )
                )
    return findings
