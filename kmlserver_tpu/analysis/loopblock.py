"""Checker 9 — no blocking constructs in event-loop context.

PR 18 shipped — then had to hot-fix — the exact defect class this
checker rejects: ``faults.fire()``'s blocking ``time.sleep`` running ON
the asyncio event loop inside ``aioserver._Conn._dispatch``, which
turned a per-request chaos stall into a whole-replica outage (effective
concurrency 1). The asyncio front end's contract is that the loop NEVER
blocks: stalls are scheduled via ``loop.call_later``, device waits live
on the engine pool, and file/socket I/O stays on worker threads.

Mechanics: :func:`callgraph.classify_contexts` builds the event-loop
context map — asyncio Protocol callbacks, ``async def``s,
``call_soon``/``call_later``/``call_at`` targets (a global pre-pass,
because ``call_soon_threadsafe`` schedules ONTO the loop from any
thread), done-callbacks registered in loop context, plus the configured
entries the conservative graph can't see through (the inline
``app.handle`` dispatch). Every function in the map is scanned for the
configured blocking constructs; ``await``-ed calls are exempt (they
yield, not block), and an executor hop naturally ends the walk because
a callable handed to ``submit``/``run_in_executor`` produces no call
edge. Findings carry the entry → call path and why the entry is
loop-context, so the fix target is obvious.
"""

from __future__ import annotations

from .callgraph import CallGraph, classify_contexts, match_forbidden
from .core import SEVERITY_ERROR, AnalysisConfig, Finding, ProjectIndex


def run(index: ProjectIndex, cfg: AnalysisConfig) -> list[Finding]:
    graph = CallGraph(index)
    ctx = classify_contexts(index, cfg, graph)
    findings: list[Finding] = []
    for ref in sorted(ctx.loop):
        path = ctx.loop[ref]
        info = index.function(ref)
        if info is None:
            continue
        for site in graph.sites(ref):
            if site.awaited:
                continue
            construct = match_forbidden(
                site,
                cfg.loopblock_forbidden_calls,
                cfg.loopblock_forbidden_methods,
            )
            if construct is None:
                continue
            entry = path[0]
            reason = ctx.loop_roots.get(entry, "loop entry")
            via = " -> ".join(p.split("::", 1)[1] for p in path)
            findings.append(
                Finding(
                    checker="loopblock",
                    severity=SEVERITY_ERROR,
                    file=info.relpath,
                    line=site.line,
                    key=f"{construct}@{info.qualname}",
                    message=(
                        f"blocking construct `{construct}` in "
                        f"`{info.qualname}` runs in event-loop context "
                        f"(entry path: {via}; entry is {reason}); a "
                        "block here freezes every connection on the "
                        "replica — schedule it with loop.call_later, "
                        "hop through the engine pool/run_in_executor, "
                        "or justify with a pragma/baseline entry"
                    ),
                )
            )
    return findings
