"""Checker 7 — metric-series registry (ISSUE 9).

Every Prometheus series the project exports — the serving ``/metrics``
exposition (serving/metrics.py, plus the app's dynamically rendered
robustness keys) and the mining ``job_metrics.prom`` textfile
(observability/jobmetrics.py) — must be declared in
``serving.metrics.METRIC_REGISTRY`` as ``"<type>:<scope>"`` with a valid
type (counter/gauge/summary/histogram) and scope (serving/mining), must
carry a README row, and must match the scope of the module that renders
it. And the inverse: a registry entry nothing renders is an orphan — a
dashboard keeps querying a series the fleet stopped exporting.

Collection mirrors the knob checker's discipline: series names are
AST string literals (tokens matching ``kmls_[a-z0-9_]+`` embedded in
exposition-module strings — f-string constant fragments included, so
``f'kmls_cache_hits_total {cache.hits}'`` counts), docstrings are
skipped outright (prose must neither keep a series alive nor demand an
entry for an example), comments never reach the AST, and the
``METRIC_REGISTRY`` dict's own span is excluded so a key cannot count
as the exposition reference that keeps itself alive. Dynamically
rendered series (the robustness dict: plain keys prefixed ``kmls_`` at
render time) are collected from the configured source function's dict
keys and subscript stores.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from .core import (
    SEVERITY_ERROR,
    SEVERITY_WARN,
    AnalysisConfig,
    Finding,
    ProjectIndex,
)

_SERIES_RE = re.compile(r"\bkmls_[a-z0-9][a-z0-9_]*[a-z0-9]\b")
# histogram children are rendered per-bucket from the base name; they are
# implementation suffixes of the declared series, never declared themselves
_CHILD_SUFFIXES = ("_bucket", "_sum", "_count")

VALID_TYPES = ("counter", "gauge", "summary", "histogram")
VALID_METRIC_SCOPES = ("serving", "mining")


def _docstring_node_ids(tree: ast.AST) -> set[int]:
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(id(body[0].value))
    return out


def _iter_series_literals(tree: ast.AST) -> Iterator[tuple[str, int]]:
    docstrings = _docstring_node_ids(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if id(node) in docstrings:
                continue
            for token in _SERIES_RE.findall(node.value):
                yield token, node.lineno


def _registry_span(
    index: ProjectIndex, cfg: AnalysisConfig
) -> tuple[int, int] | None:
    mod = index.modules.get(cfg.metrics_file)
    if mod is None:
        return None
    for node in mod.tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if (
            isinstance(target, ast.Name)
            and target.id == cfg.metric_registry_name
        ):
            return (node.lineno, node.end_lineno or node.lineno)
    return None


def parse_metric_registry(
    index: ProjectIndex, cfg: AnalysisConfig
) -> tuple[dict[str, str], dict[str, int], int]:
    """``METRIC_REGISTRY = {...}`` parsed WITHOUT importing →
    (name -> "type:scope", name -> line, registry line)."""
    mod = index.modules.get(cfg.metrics_file)
    if mod is None:
        return {}, {}, 0
    for node in mod.tree.body:
        target = None
        value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        if (
            isinstance(target, ast.Name)
            and target.id == cfg.metric_registry_name
            and isinstance(value, ast.Dict)
        ):
            entries: dict[str, str] = {}
            lines: dict[str, int] = {}
            for k, v in zip(value.keys, value.values):
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    entries[k.value] = v.value
                    lines[k.value] = k.lineno
            return entries, lines, node.lineno
    return {}, {}, 0


def collect_exposed_series(
    index: ProjectIndex, cfg: AnalysisConfig
) -> dict[str, list[tuple[str, int, str]]]:
    """series -> [(file, line, scope), ...], first ref per exposition
    scope — a series rendered by BOTH the serving and mining surfaces
    keeps one ref from each, so the scope check can flag the surface
    that should not be rendering it."""
    span = _registry_span(index, cfg)
    refs: dict[str, list[tuple[str, int, str]]] = {}

    def add(name: str, relpath: str, line: int, scope: str) -> None:
        surfaces = refs.setdefault(name, [])
        if all(seen_scope != scope for _, _, seen_scope in surfaces):
            surfaces.append((relpath, line, scope))

    for relpath, scope in cfg.metric_exposition_files.items():
        mod = index.modules.get(relpath)
        if mod is None:
            continue
        for name, line in _iter_series_literals(mod.tree):
            if (
                relpath == cfg.metrics_file
                and span is not None
                and span[0] <= line <= span[1]
            ):
                continue
            if any(name.endswith(sfx) for sfx in _CHILD_SUFFIXES):
                continue
            add(name, relpath, line, scope)
    for ref, prefix, scope in cfg.metric_dynamic_sources:
        info = index.function(ref)
        if info is None:
            continue
        for node in ast.walk(info.node):
            keys: list[tuple[str, int]] = []
            if isinstance(node, ast.Dict):
                keys = [
                    (k.value, k.lineno)
                    for k in node.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                ]
            elif isinstance(node, ast.Assign) and isinstance(
                node.targets[0], ast.Subscript
            ):
                sl = node.targets[0].slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    keys = [(sl.value, node.lineno)]
            for key, line in keys:
                add(f"{prefix}{key}", info.relpath, line, scope)
    return refs


def _read_text(root: str, relpath: str) -> str:
    try:
        with open(os.path.join(root, relpath), "r", encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return ""


def run(index: ProjectIndex, cfg: AnalysisConfig) -> list[Finding]:
    entries, reg_lines, reg_line = parse_metric_registry(index, cfg)
    findings: list[Finding] = []
    if not entries:
        findings.append(
            Finding(
                checker="metrics",
                severity=SEVERITY_ERROR,
                file=cfg.metrics_file,
                line=1,
                key="registry-missing",
                message=(
                    f"no `{cfg.metric_registry_name}` dict found in "
                    f"{cfg.metrics_file}; every exported Prometheus "
                    "series must be declared there as "
                    f"\"<type>:<scope>\" ({'/'.join(VALID_TYPES)} : "
                    f"{'/'.join(VALID_METRIC_SCOPES)})"
                ),
            )
        )
        return findings

    refs = collect_exposed_series(index, cfg)
    readme_text = _read_text(index.root, cfg.readme)

    for name in sorted(refs):
        relpath, line, _scope = refs[name][0]
        if name not in entries:
            findings.append(
                Finding(
                    checker="metrics",
                    severity=SEVERITY_ERROR,
                    file=relpath,
                    line=line,
                    key=f"unregistered:{name}",
                    message=(
                        f"series `{name}` is exported here but not "
                        f"declared in metrics.{cfg.metric_registry_name}; "
                        "add it with a type+scope and a README row"
                    ),
                )
            )
            continue
        declared_scope = entries[name].partition(":")[2]
        if declared_scope not in VALID_METRIC_SCOPES:
            continue  # bad-entry finding below covers it
        # check every surface: a series both modules render is a
        # mismatch on whichever side the registry did not declare
        for relpath, line, scope in refs[name]:
            if declared_scope != scope:
                findings.append(
                    Finding(
                        checker="metrics",
                        severity=SEVERITY_ERROR,
                        file=relpath,
                        line=line,
                        key=f"scope-mismatch:{name}",
                        message=(
                            f"series `{name}` is exported by a "
                            f"{scope!r}-side module but registered with "
                            f"scope {declared_scope!r} — the two "
                            "exposition surfaces must not swap series"
                        ),
                    )
                )
    for name in sorted(entries):
        value = entries[name]
        kline = reg_lines.get(name, reg_line)
        mtype, sep, scope = value.partition(":")
        if not sep or mtype not in VALID_TYPES or scope not in VALID_METRIC_SCOPES:
            findings.append(
                Finding(
                    checker="metrics",
                    severity=SEVERITY_ERROR,
                    file=cfg.metrics_file,
                    line=kline,
                    key=f"bad-entry:{name}",
                    message=(
                        f"`{name}` has malformed registry value "
                        f"{value!r}; expected \"<type>:<scope>\" with "
                        f"type in {', '.join(VALID_TYPES)} and scope in "
                        f"{', '.join(VALID_METRIC_SCOPES)}"
                    ),
                )
            )
            continue
        if name not in refs:
            findings.append(
                Finding(
                    checker="metrics",
                    severity=SEVERITY_WARN,
                    file=cfg.metrics_file,
                    line=kline,
                    key=f"orphan:{name}",
                    message=(
                        f"`{name}` is declared in the registry but no "
                        "exposition module renders it — remove the entry "
                        "(and its README row) or wire the series up"
                    ),
                )
            )
        if readme_text and name not in readme_text:
            findings.append(
                Finding(
                    checker="metrics",
                    severity=SEVERITY_WARN,
                    file=cfg.metrics_file,
                    line=kline,
                    key=f"undocumented:{name}",
                    message=(
                        f"`{name}` is not mentioned anywhere in "
                        f"{cfg.readme}; every exported series needs a "
                        "row in the metrics table (README "
                        "\"Observability\")"
                    ),
                )
            )
    return findings
