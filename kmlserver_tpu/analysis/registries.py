"""Checkers 4 & 5 — env-knob registry and fault-site registry.

**knobs**: every ``KMLS_*`` environment knob referenced anywhere in the
code (package + bench + scripts) must be declared in
``config.KNOB_REGISTRY`` with a scope, mentioned in the README, and —
for runtime scopes — bound or documented in the matching Kubernetes
manifest(s). And the inverse: a registry entry nothing references is an
orphan (a knob that was removed from code but not from docs keeps
operators setting a dead variable).

Knob references are EXACT string literals (``ast.Constant``) matching
``^KMLS_[A-Z0-9][A-Z0-9_]*$`` (no trailing underscore — prefix strings
like ``"KMLS_FAULT_"`` are not knobs). AST literals, so comments and
prose never count, and docstrings can't match (a knob name embedded in
a sentence is not an exact literal).

**fault-sites**: every ``KMLS_FAULT_*`` knob parsed by
``faults.load_env`` must arm a site that some production module actually
``fire()``s, and must be exercised by at least one test that names the
knob or its site (the chaos suites). F-string sites (``mine.crash.{p}``)
match by literal prefix.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from .core import (
    SEVERITY_ERROR,
    SEVERITY_WARN,
    AnalysisConfig,
    Finding,
    ProjectIndex,
)

_KNOB_RE = re.compile(r"^KMLS_[A-Z0-9][A-Z0-9_]*[A-Z0-9]$")
_KNOB_TOKEN_RE = re.compile(r"\bKMLS_[A-Z0-9_]+\b")

VALID_SCOPES = ("serving", "mining", "both", "tool", "fault")


def _docstring_node_ids(tree: ast.AST) -> set[int]:
    """ids of every Constant that is a module/class/function docstring."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(id(body[0].value))
    return out


def _iter_knob_literals(tree: ast.AST) -> Iterator[tuple[str, int]]:
    """Knob names in string constants: exact literals (the getenv reads)
    plus tokens EMBEDDED in longer strings — bench.py's phase brackets
    are whole scripts carried as string literals, and their knob reads
    are real reads. Comments never reach the AST, so a commented-out
    knob can't count — and DOCSTRINGS are skipped outright: prose that
    mentions a knob must neither count as the read that keeps it alive
    (it would neuter the orphan check) nor demand a registry entry for a
    knob-shaped example."""
    docstrings = _docstring_node_ids(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if id(node) in docstrings:
                continue
            if _KNOB_RE.match(node.value):
                yield node.value, node.lineno
            elif len(node.value) > len("KMLS_X"):
                for token in _KNOB_TOKEN_RE.findall(node.value):
                    if _KNOB_RE.match(token):
                        yield token, node.lineno


def collect_code_knobs(
    index: ProjectIndex, cfg: AnalysisConfig | None = None
) -> dict[str, tuple[str, int]]:
    """knob -> first (file, line) reference across the analyzed code.
    The analysis package itself is excluded (its checkers spell
    knob-shaped strings without reading any environment), and so is the
    KNOB_REGISTRY dict's own span — a registry key must not count as the
    code reference that keeps itself alive, or the orphan check could
    never fire."""
    registry_span: tuple[int, int] | None = None
    config_file = cfg.config_file if cfg else None
    if cfg is not None:
        mod = index.modules.get(cfg.config_file)
        if mod is not None:
            for node in mod.tree.body:
                target = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target = node.targets[0]
                elif isinstance(node, ast.AnnAssign):
                    target = node.target
                if (
                    isinstance(target, ast.Name)
                    and target.id == cfg.knob_registry_name
                ):
                    registry_span = (
                        node.lineno,
                        node.end_lineno or node.lineno,
                    )
    refs: dict[str, tuple[str, int]] = {}
    for relpath in sorted(index.modules):
        if "/analysis/" in relpath:
            continue
        for knob, line in _iter_knob_literals(index.modules[relpath].tree):
            if (
                relpath == config_file
                and registry_span is not None
                and registry_span[0] <= line <= registry_span[1]
            ):
                continue
            refs.setdefault(knob, (relpath, line))
    return refs


def parse_knob_registry(
    index: ProjectIndex, cfg: AnalysisConfig
) -> tuple[dict[str, str], dict[str, int], int]:
    """Parse ``KNOB_REGISTRY = {...}`` out of config.py WITHOUT importing
    it → (knob -> scope, knob -> line, registry line)."""
    mod = index.modules.get(cfg.config_file)
    if mod is None:
        return {}, {}, 0
    for node in mod.tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = node.target
            value = node.value
        else:
            continue
        if (
            isinstance(target, ast.Name)
            and target.id == cfg.knob_registry_name
            and isinstance(value, ast.Dict)
        ):
            scopes: dict[str, str] = {}
            lines: dict[str, int] = {}
            for k, v in zip(value.keys, value.values):
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)
                ):
                    scopes[k.value] = v.value
                    lines[k.value] = k.lineno
            return scopes, lines, node.lineno
    return {}, {}, 0


def _read_text(root: str, relpath: str) -> str:
    try:
        with open(os.path.join(root, relpath), "r", encoding="utf-8") as fh:
            return fh.read()
    except OSError:
        return ""


def run_knobs(index: ProjectIndex, cfg: AnalysisConfig) -> list[Finding]:
    refs = collect_code_knobs(index, cfg)
    scopes, reg_lines, reg_line = parse_knob_registry(index, cfg)
    findings: list[Finding] = []
    if not scopes:
        findings.append(
            Finding(
                checker="knobs",
                severity=SEVERITY_ERROR,
                file=cfg.config_file,
                line=1,
                key="registry-missing",
                message=(
                    f"no `{cfg.knob_registry_name}` dict found in "
                    f"{cfg.config_file}; every KMLS_* knob must be "
                    "declared there with a scope "
                    f"({'/'.join(VALID_SCOPES)})"
                ),
            )
        )
        return findings

    readme_text = _read_text(index.root, cfg.readme)
    manifest_text = {
        m: _read_text(index.root, m) for m in cfg.manifest_files
    }

    for knob in sorted(refs):
        relpath, line = refs[knob]
        if knob not in scopes:
            findings.append(
                Finding(
                    checker="knobs",
                    severity=SEVERITY_ERROR,
                    file=relpath,
                    line=line,
                    key=f"undeclared:{knob}",
                    message=(
                        f"env knob `{knob}` is read here but not "
                        f"declared in config.{cfg.knob_registry_name}; "
                        "add it with a scope and a README row"
                    ),
                )
            )
    for knob in sorted(scopes):
        scope = scopes[knob]
        kline = reg_lines.get(knob, reg_line)
        if scope not in VALID_SCOPES:
            findings.append(
                Finding(
                    checker="knobs",
                    severity=SEVERITY_ERROR,
                    file=cfg.config_file,
                    line=kline,
                    key=f"bad-scope:{knob}",
                    message=(
                        f"`{knob}` has unknown scope {scope!r}; expected "
                        f"one of {', '.join(VALID_SCOPES)}"
                    ),
                )
            )
            continue
        if knob not in refs:
            findings.append(
                Finding(
                    checker="knobs",
                    severity=SEVERITY_WARN,
                    file=cfg.config_file,
                    line=kline,
                    key=f"orphan:{knob}",
                    message=(
                        f"`{knob}` is declared in the registry but "
                        "nothing in the code reads it — remove the "
                        "entry (and its README row) or wire the knob up"
                    ),
                )
            )
        if readme_text and knob not in readme_text:
            findings.append(
                Finding(
                    checker="knobs",
                    severity=SEVERITY_WARN,
                    file=cfg.config_file,
                    line=kline,
                    key=f"undocumented:{knob}",
                    message=(
                        f"`{knob}` is not mentioned anywhere in "
                        f"{cfg.readme}; every knob needs a row in the "
                        "configuration tables"
                    ),
                )
            )
        required = cfg.knob_scope_manifests.get(scope, ())
        if scope == "both":
            # must appear in the serving manifest AND one job manifest
            groups = [
                tuple(
                    m for m in required if "deployment" in os.path.basename(m)
                ),
                tuple(
                    m
                    for m in required
                    if "deployment" not in os.path.basename(m)
                ),
            ]
        else:
            groups = [required] if required else []
        for group in groups:
            if not group:
                continue
            if not any(knob in manifest_text.get(m, "") for m in group):
                findings.append(
                    Finding(
                        checker="knobs",
                        severity=SEVERITY_WARN,
                        file=cfg.config_file,
                        line=kline,
                        key=f"unbound:{knob}:{group[0]}",
                        message=(
                            f"`{knob}` (scope {scope!r}) is neither "
                            "bound nor documented in "
                            f"{' / '.join(group)}; a runtime knob "
                            "operators can set must be visible in the "
                            "manifest that deploys it"
                        ),
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# fault sites
# ---------------------------------------------------------------------------


def _site_literal(node: ast.AST) -> str | None:
    """A fire()/inject() site argument → its literal value, or the
    literal PREFIX of an f-string (``f"mine.crash.{p}"`` → "mine.crash.")."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        prefix = ""
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                prefix += part.value
            else:
                break
        return prefix or None
    return None


def _sites_match(a: str, b: str) -> bool:
    return a.startswith(b) or b.startswith(a)


def collect_fault_env_map(
    index: ProjectIndex, cfg: AnalysisConfig
) -> dict[str, tuple[str, int]]:
    """``load_env``'s knob → (site, line) pairing: each ``os.getenv(
    "KMLS_FAULT_X")`` read is associated with the next ``inject(site)``
    call in statement order."""
    info = index.function(f"{cfg.faults_file}::load_env")
    if info is None:
        return {}

    def _call_name(node: ast.Call) -> str | None:
        if isinstance(node.func, ast.Attribute) and isinstance(
            node.func.value, ast.Name
        ):
            return f"{node.func.value.id}.{node.func.attr}"
        if isinstance(node.func, ast.Name):
            return node.func.id
        return None

    def _getenv_knob(node: ast.Call) -> str | None:
        if _call_name(node) in ("os.getenv", "getenv") and node.args:
            lit = _site_literal(node.args[0])
            if lit and lit.startswith("KMLS_FAULT"):
                return lit
        return None

    mapping: dict[str, tuple[str, int]] = {}
    paired_getenvs: set[int] = set()
    inject_calls: list[ast.Call] = []
    # pass 1: a getenv NESTED inside an inject call pairs directly —
    # `inject("site", times=int(os.getenv("KMLS_FAULT_X")))` must never
    # depend on event ordering
    for node in ast.walk(info.node):
        if isinstance(node, ast.Call) and _call_name(node) == "inject":
            inject_calls.append(node)
            site = _site_literal(node.args[0]) if node.args else None
            if site is None:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    knob = _getenv_knob(sub)
                    if knob is not None:
                        mapping[knob] = (site, sub.lineno)
                        paired_getenvs.add(id(sub))
                        break
    # pass 2: the remaining reads pair with the next inject in SOURCE
    # order — (lineno, col_offset), since ast.walk order is
    # breadth-first, not statement order
    events: list[tuple[int, int, str, str]] = []
    consumed_injects = {
        id(c) for c in inject_calls if any(
            isinstance(sub, ast.Call) and id(sub) in paired_getenvs
            for sub in ast.walk(c)
        )
    }
    for node in ast.walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        knob = _getenv_knob(node)
        if knob is not None and id(node) not in paired_getenvs:
            events.append((node.lineno, node.col_offset, "knob", knob))
        elif (
            _call_name(node) == "inject"
            and node.args
            and id(node) not in consumed_injects
        ):
            site = _site_literal(node.args[0])
            if site:
                events.append((node.lineno, node.col_offset, "inject", site))
    pending: str | None = None
    pending_line = 0
    for line, _col, kind, value in sorted(events):
        if kind == "knob":
            pending, pending_line = value, line
        elif pending is not None:
            mapping.setdefault(pending, (value, pending_line))
            pending = None
    return mapping


def collect_fire_sites(index: ProjectIndex, cfg: AnalysisConfig) -> set[str]:
    sites: set[str] = set()
    for relpath, mod in index.modules.items():
        if not relpath.startswith(cfg.package_dir):
            continue
        if relpath == cfg.faults_file:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                func = node.func
                name = None
                if isinstance(func, ast.Attribute):
                    name = func.attr
                elif isinstance(func, ast.Name):
                    name = func.id
                # fire() raises/kills at the site; take()/take_io() are
                # the consume-style variants (fleet peer delays, ISSUE 19
                # storage faults) — all three mean "this site is wired"
                if name in ("fire", "take", "take_io") and node.args:
                    site = _site_literal(node.args[0])
                    if site:
                        sites.add(site)
    return sites


def run_fault_sites(
    index: ProjectIndex, cfg: AnalysisConfig
) -> list[Finding]:
    env_map = collect_fault_env_map(index, cfg)
    fire_sites = collect_fire_sites(index, cfg)
    findings: list[Finding] = []
    if not env_map:
        findings.append(
            Finding(
                checker="fault-sites",
                severity=SEVERITY_ERROR,
                file=cfg.faults_file,
                line=1,
                key="no-env-map",
                message=(
                    f"could not extract any KMLS_FAULT_* -> site mapping "
                    f"from {cfg.faults_file}::load_env"
                ),
            )
        )
        return findings

    # tests: any string literal naming the knob or its site counts as
    # exercising it
    test_literals: set[str] = set()
    tests_root = os.path.join(index.root, cfg.tests_dir)
    if os.path.isdir(tests_root):
        for name in sorted(os.listdir(tests_root)):
            if not name.endswith(".py"):
                continue
            try:
                with open(
                    os.path.join(tests_root, name), "r", encoding="utf-8"
                ) as fh:
                    tree = ast.parse(fh.read())
            except (OSError, SyntaxError):
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    test_literals.add(node.value)

    # inverse direction: a production fire() site no env knob can arm is
    # dead chaos surface (programmatic inject still reaches it, so warn)
    armed_sites = {site for site, _line in env_map.values()}
    for site in sorted(fire_sites):
        if not any(_sites_match(site, armed) for armed in armed_sites):
            findings.append(
                Finding(
                    checker="fault-sites",
                    severity=SEVERITY_WARN,
                    file=cfg.faults_file,
                    line=1,
                    key=f"unarmed-site:{site}",
                    message=(
                        f"fire site `{site}` exists in code but no "
                        "KMLS_FAULT_* knob in load_env can arm it; add "
                        "an env knob so containers/CI chaos can reach it"
                    ),
                )
            )

    for knob in sorted(env_map):
        site, line = env_map[knob]
        if not any(_sites_match(site, fired) for fired in fire_sites):
            findings.append(
                Finding(
                    checker="fault-sites",
                    severity=SEVERITY_ERROR,
                    file=cfg.faults_file,
                    line=line,
                    key=f"dead-knob:{knob}",
                    message=(
                        f"`{knob}` arms site `{site}` but nothing in the "
                        "package ever fire()s that site — the knob is a "
                        "no-op; wire the site or delete the knob"
                    ),
                )
            )
            continue
        # strict matching: the knob name itself, the exact site, or — for
        # prefix sites like "mine.crash." — any literal under the prefix.
        # (Loose prefix matching here would let a stray short literal
        # mark a knob as exercised.)
        exercised = (
            knob in test_literals
            or site in test_literals
            or (
                site.endswith(".")
                and any(lit.startswith(site) for lit in test_literals)
            )
        )
        if not exercised:
            findings.append(
                Finding(
                    checker="fault-sites",
                    severity=SEVERITY_ERROR,
                    file=cfg.faults_file,
                    line=line,
                    key=f"untested:{knob}",
                    message=(
                        f"`{knob}` (site `{site}`) is not exercised by "
                        "any test — no chaos test names the knob or "
                        "injects its site; a recovery path nothing "
                        "drives is a recovery path that regresses "
                        "silently"
                    ),
                )
            )
    return findings
