"""Typed configuration over the reference's env-var contract.

The reference configures both workloads purely through environment variables
(reference: machine-learning/main.py:17-49, rest_api/app/main.py:31-50), bound
in-cluster by the manifests (reference: kubernetes/job.yaml:24-40,
kubernetes/deployment.yaml:33-53). The variable NAMES and defaults here are
that contract and must not drift — the Kubernetes layer depends on them.

On top, the TPU rebuild adds its own knobs under a ``KMLS_`` prefix (mesh
shape, rule-row capacity, confidence semantics, server port); these have safe
defaults and are absent from the reference.
"""

from __future__ import annotations

import dataclasses
import os

from .utils.envfile import load_dotenv


def _getenv_int(name: str, default: int) -> int:
    raw = os.getenv(name)
    return int(raw) if raw not in (None, "") else default


def _getenv_float(name: str, default: float) -> float:
    raw = os.getenv(name)
    return float(raw) if raw not in (None, "") else default


def _getenv_bool(name: str, default: bool) -> bool:
    raw = os.getenv(name)
    if raw in (None, ""):
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


def _getenv_hybrid_mode() -> str:
    """``KMLS_HYBRID_MODE``: one of ``rules``/``embed``/``blend``
    (case-insensitive). An unrecognized value falls back to ``rules`` —
    the FAIL-SAFE direction: a typo while trying to pin the legacy path
    must never silently enable the hybrid merge — with a loud warning."""
    raw = os.getenv("KMLS_HYBRID_MODE")
    if raw in (None, ""):
        return "blend"
    word = raw.strip().lower()
    if word in ("rules", "embed", "blend"):
        return word
    import logging

    logging.getLogger("kmlserver_tpu.serving").warning(
        "KMLS_HYBRID_MODE=%r is not one of rules/embed/blend; "
        "serving rules-only", raw,
    )
    return "rules"


def _getenv_blend_weight() -> tuple[float, bool]:
    """``KMLS_HYBRID_BLEND_WEIGHT``: a float, or ``measured`` — serve
    the blend optimum the quality loop published in
    ``quality.report.json`` (ISSUE 14). → ``(weight, measured)``; the
    explicit float always wins over a report, and anything unparseable
    fails SAFE to the default weight with a loud warning (a typo while
    opting into measurement must not silently pin a wrong float)."""
    raw = os.getenv("KMLS_HYBRID_BLEND_WEIGHT")
    if raw in (None, ""):
        return 0.5, False
    word = raw.strip().lower()
    if word == "measured":
        return 0.5, True
    try:
        return float(raw), False
    except ValueError:
        import logging

        logging.getLogger("kmlserver_tpu.serving").warning(
            "KMLS_HYBRID_BLEND_WEIGHT=%r is neither a float nor "
            "'measured'; using the default 0.5", raw,
        )
        return 0.5, False


def _getenv_model_layout() -> str:
    """``KMLS_MODEL_LAYOUT``: ``replicated`` (default), ``sharded``, or
    ``auto`` (shard when measured tensor bytes exceed
    ``KMLS_DEVICE_BUDGET_BYTES``). Validation — including the fail-safe
    fallback to ``replicated`` on a typo — lives in ONE place:
    ``parallel.layout.validate_layout`` (both workloads resolve through
    it, so the knob can never mean different things to the two sides)."""
    from .parallel.layout import validate_layout

    return validate_layout(os.getenv("KMLS_MODEL_LAYOUT", "replicated"))


def _getenv_gang_rank() -> int:
    """``KMLS_SERVE_GANG_RANK``: explicit rank, falling back to the same
    identity recipe as the mining bootstrap (``JOB_COMPLETION_INDEX``,
    then the hostname's trailing StatefulSet ordinal —
    ``parallel.distributed.gang_rank_fallback`` is the canonical copy;
    this mirror keeps config import-light)."""
    raw = os.getenv("KMLS_SERVE_GANG_RANK")
    if raw not in (None, ""):
        return int(raw)
    idx = os.getenv("JOB_COMPLETION_INDEX")
    if idx is not None and idx.isdigit():
        return int(idx)
    import socket

    _, _, ordinal = socket.gethostname().rpartition("-")
    return int(ordinal) if ordinal.isdigit() else 0


def _getenv_bitpack_threshold() -> int | str | None:
    """``KMLS_BITPACK_THRESHOLD_ELEMS``: "auto" (HBM-fit dispatch, the
    default), "none"/"never" (dense always), or an explicit element count."""
    raw = os.getenv("KMLS_BITPACK_THRESHOLD_ELEMS")
    if raw in (None, ""):
        return "auto"
    word = raw.strip().lower()
    if word == "auto":
        return "auto"
    if word in ("none", "never"):
        return None
    return int(raw)


# ---------------------------------------------------------------------------
# The env-knob registry — THE declaration point for every KMLS_* knob.
#
# kmls-verify's `knobs` checker (kmlserver_tpu/analysis/registries.py)
# enforces, in CI: every knob read anywhere in the code is declared here;
# every entry here is still read somewhere (no dead docs); every entry has a
# README row; and runtime scopes are bound or documented in the Kubernetes
# manifest that deploys them. Scopes:
#
#   "serving" — read by the API pod           (kubernetes/deployment.yaml)
#   "mining"  — read by the batch mining job  (kubernetes/job*.yaml)
#   "both"    — read by both workloads        (all three manifests)
#   "tool"    — bench/sweep/dev harness only  (never shipped in manifests)
#   "fault"   — fault injection (faults.py)   (chaos tests must exercise it)
#
# Adding a knob = add the os.getenv read, an entry here, and a README row
# (+ a manifest line for runtime scopes) — or CI's verify job rejects the
# diff, naming exactly what is missing.
# ---------------------------------------------------------------------------
KNOB_REGISTRY: dict[str, str] = {
    # --- serving: request path / transport ---
    "KMLS_PORT": "serving",
    "KMLS_HTTP_IMPL": "serving",
    "KMLS_MAX_SEED_TRACKS": "serving",
    "KMLS_BATCH_WINDOW_MS": "serving",
    "KMLS_BATCH_MAX_SIZE": "serving",
    "KMLS_BATCH_ADAPTIVE": "serving",
    "KMLS_BATCH_WINDOW_MIN_MS": "serving",
    "KMLS_BATCH_MAX_INFLIGHT": "serving",
    "KMLS_SHED_QUEUE_BUDGET_MS": "serving",
    "KMLS_SHED_RETRY_AFTER_S": "serving",
    # adaptive admission ladder (ISSUE 8): degrade band start, hard-shed
    # band end, and the bounded Retry-After jitter fraction
    "KMLS_SHED_SOFT_RATIO": "serving",
    "KMLS_SHED_HARD_RATIO": "serving",
    "KMLS_SHED_RETRY_JITTER": "serving",
    "KMLS_SERVE_DEVICES": "serving",
    "KMLS_CACHE_ENABLED": "serving",
    "KMLS_CACHE_MAX_ENTRIES": "serving",
    "KMLS_PREFER_TENSOR_ARTIFACT": "serving",
    "KMLS_NATIVE_SERVE": "serving",
    "KMLS_DRAIN_SETTLE_S": "serving",
    "KMLS_GIL_SWITCH_S": "serving",
    # --- serving: fault tolerance ---
    "KMLS_VERIFY_MANIFEST": "serving",
    "KMLS_QUARANTINE_AFTER_FAILURES": "serving",
    "KMLS_RELOAD_BACKOFF_BASE_S": "serving",
    "KMLS_RELOAD_BACKOFF_MAX_S": "serving",
    "KMLS_REPLICA_EJECT_THRESHOLD": "serving",
    "KMLS_REPLICA_PROBE_INTERVAL_S": "serving",
    "KMLS_REDISPATCH_MAX_RETRIES": "serving",
    "KMLS_REQUEST_DEADLINE_MS": "serving",
    "KMLS_FALLBACK_BUDGET_MS": "serving",
    # --- serving: hybrid rule∪embedding merge (second model family) ---
    "KMLS_HYBRID_MODE": "serving",
    "KMLS_HYBRID_BLEND_WEIGHT": "serving",
    # --- serving: quality loop (ISSUE 14) ---
    # per-artifact staleness bound: any served artifact older than this
    # flags /readyz ready-but-degraded and sets kmls_artifact_stale
    # (0 = disabled — age gauges stay observability-only)
    "KMLS_ARTIFACT_MAX_AGE_S": "serving",
    # --- serving: fleet cache affinity (ISSUE 10) ---
    # rendezvous-hash request affinity (freshness/ring.py): count how much
    # real traffic an affinity router would keep ring-local before
    # committing to one (or to a shared external cache tier)
    "KMLS_CACHE_AFFINITY": "serving",
    "KMLS_CACHE_AFFINITY_PEERS": "serving",
    "KMLS_CACHE_AFFINITY_SELF": "serving",
    # --- serving: fleet cache routing (ISSUE 15) ---
    # stable replica identity for the routing tier (kubernetes/
    # statefulset.yaml binds SELF from the pod name; PEERS lists the
    # StatefulSet ordinals). Setting PEERS arms owner-aware serving:
    # the ring (same rendezvous implementation the router and
    # simulate_fleet use), X-KMLS-Cache-Owner stamping on non-owned
    # answers, and the kmls_cache_misrouted_total drift counter.
    "KMLS_FLEET_SELF": "serving",
    "KMLS_FLEET_PEERS": "serving",
    # --- serving: pod-spanning serve mesh (ISSUE 16) ---
    # gang bootstrap mirroring the mining job's KMLS_PROCESS_ID recipe
    # (kubernetes/serve-gang.yaml binds RANK from the StatefulSet pod
    # index): COORDINATOR is rank 0's partial-fetch address, SIZE the
    # gang width (== spec.replicas), PORT the base partial-protocol
    # port. SIZE > 1 arms the "mesh" layout: each member holds only its
    # vocab slab yet the gang presents ONE logical replica (and one
    # ring peer) to the dispatcher.
    "KMLS_SERVE_GANG_COORDINATOR": "serving",
    "KMLS_SERVE_GANG_SIZE": "serving",
    "KMLS_SERVE_GANG_RANK": "serving",
    "KMLS_SERVE_GANG_PORT": "serving",
    # --- serving: gray-failure spine (ISSUE 18) ---
    # hedged dispatch master switch (0 = off, the proven-zero-cost
    # default: no hedge state allocated, module hedge counters pinned 0)
    "KMLS_HEDGE": "serving",
    # slow-outlier ladder: eject a peer whose EWMA latency exceeds
    # RATIO × the healthy-peer median (0 disables the ladder; slowness
    # then never ejects, only hedging absorbs it)
    "KMLS_PEER_SLOW_RATIO": "serving",
    # hedge trigger floor in ms — the adaptive per-peer delay (tracked
    # latency ~p95) never fires earlier than this
    "KMLS_HEDGE_DELAY_MS": "serving",
    # amplification bound: hedges may add at most this fraction of extra
    # dispatches (token bucket earning FRAC per primary dispatch);
    # exhausted budget falls back to plain waiting
    "KMLS_HEDGE_MAX_FRAC": "serving",
    # --- serving: storage gray-failure spine (ISSUE 19) ---
    # slow-IO conviction threshold: any artifact-plane op whose latency
    # EWMA crosses this flips /readyz ready-but-degraded with reason
    # storage-slow (kmls_storage_slow gauge); clears at half (hysteresis)
    "KMLS_IO_SLOW_MS": "serving",
    # deadline on reload-path artifact reads: a hung NFS read parks the
    # reload in the normal failure backoff with last-good still serving
    # instead of wedging the reload thread (0 = no deadline)
    "KMLS_IO_READ_DEADLINE_S": "serving",
    # --- serving: observability (ISSUE 9) ---
    # span tracing: baseline sample rate for OK traces (0 = tracing off —
    # the zero-hot-path-cost default; shed/degraded/slowest-N traces are
    # ALWAYS retained once tracing is on), ring capacity, slowest-N size
    "KMLS_TRACE_SAMPLE": "serving",
    "KMLS_TRACE_BUFFER": "serving",
    "KMLS_TRACE_SLOW_N": "serving",
    # event-loop-lag collector: peak-hold decay half-life (0 disables the
    # collector AND its admission-pressure fold)
    "KMLS_LOOP_LAG_HALF_LIFE_S": "serving",
    # --- serving: device-truth cost attribution + SLOs (ISSUE 12) ---
    # per-kernel MFU/roofline + memory/compile telemetry (0 disables the
    # cost model entirely — proven zero-cost, observation-counter style)
    "KMLS_COSTMODEL": "serving",
    # peak FLOP/s and HBM bytes/s the MFU/roofline math measures against
    # (default: auto from the device kind — observability/costmodel.py's
    # peak table; the TPU window pins the exact chip)
    "KMLS_PEAK_FLOPS": "serving",
    "KMLS_PEAK_BYTES_PER_S": "serving",
    # SLO layer (observability/slo.py): latency target, error/degrade
    # budgets, and the fast/slow burn-rate windows — observability only,
    # the PR 8 admission ladder stays the actuator
    "KMLS_SLO_P99_MS": "serving",
    "KMLS_SLO_ERROR_BUDGET": "serving",
    "KMLS_SLO_DEGRADE_BUDGET": "serving",
    "KMLS_SLO_FAST_WINDOW_S": "serving",
    "KMLS_SLO_SLOW_WINDOW_S": "serving",
    # --- serving: predictive serving (ISSUE 17) ---
    # online traffic forecaster (serving/forecast.py): arrival-rate +
    # request-mix EWMAs with trend, feeding three actuators — batch-
    # window pre-widening/shape pre-touch, a bounded HPA-lead term in
    # kmls_utilization, and owner-targeted post-delta cache pre-fetch.
    # 0 (default) leaves the hook None — proven zero-cost, observation-
    # counter style like KMLS_COSTMODEL.
    "KMLS_FORECAST": "serving",
    "KMLS_FORECAST_HORIZON_S": "serving",
    "KMLS_FORECAST_WINDOW_S": "serving",
    "KMLS_FORECAST_ALPHA": "serving",
    "KMLS_FORECAST_UTIL_CAP": "serving",
    "KMLS_FORECAST_RAMP_RATIO": "serving",
    "KMLS_FORECAST_PREFETCH_TOP_N": "serving",
    # --- mining: semantics / device dispatch ---
    "KMLS_MAX_ITEMSET_LEN": "mining",
    "KMLS_K_MAX_CONSEQUENTS": "mining",
    "KMLS_CONFIDENCE_MODE": "mining",
    "KMLS_MIN_CONFIDENCE": "mining",
    "KMLS_MESH_SHAPE": "mining",
    "KMLS_BITPACK_THRESHOLD_ELEMS": "mining",
    "KMLS_BITPACK_IMPL": "mining",
    # sparsity-adaptive dispatch (ISSUE 13): pin a count family
    # (dense/bitpack/sparse; anything else fails safe to the measured
    # auto), point at an alternative measured dispatch table, and set
    # the hybrid's long-basket split point
    "KMLS_COUNT_PATH": "mining",
    "KMLS_DISPATCH_TABLE": "mining",
    "KMLS_SPARSE_LONG_BASKET": "mining",
    "KMLS_HBM_BUDGET_BYTES": "mining",
    "KMLS_SHARDED_IMPL": "mining",
    "KMLS_PRUNE_VOCAB_THRESHOLD": "mining",
    "KMLS_WRITE_TENSOR_ARTIFACT": "mining",
    "KMLS_WRITE_MANIFEST": "mining",
    "KMLS_REFERENCE_RACE_COMPAT": "mining",
    "KMLS_NATIVE_PAIR_COUNTS": "mining",
    "KMLS_NATIVE_PAIR_METHOD": "mining",
    "KMLS_NATIVE_THREADS": "mining",
    "KMLS_POPCOUNT_VARIANT": "mining",
    "KMLS_POPCOUNT_SWAR": "mining",
    "KMLS_POPCOUNT_TILE_I": "mining",
    "KMLS_POPCOUNT_TILE_J": "mining",
    "KMLS_POPCOUNT_WORD_CHUNK": "mining",
    # jax.profiler trace dumps: the mining PhaseTimer sessions AND the
    # serving /debug/profile?seconds=N capture endpoint (ISSUE 12) —
    # unset (the default) disables both, so production pods can never
    # be profiled by accident
    "KMLS_PROFILE_DIR": "both",
    # --- mining: ALS embedding phase (second model family) ---
    "KMLS_EMBED_ENABLED": "mining",
    "KMLS_ALS_RANK": "mining",
    "KMLS_ALS_ITERS": "mining",
    "KMLS_ALS_REG": "mining",
    # sparse ALS storage (ISSUE 13): auto = compressed interaction matrix
    # exactly when the dense one busts the HBM guard; always/never pin it
    "KMLS_ALS_SPARSE": "mining",
    # --- mining: telemetry (ISSUE 9) ---
    # write pickles/job_metrics.prom (textfile-exporter format) as phases
    # complete, so a fleet's Prometheus sees mining progress
    "KMLS_JOB_METRICS": "mining",
    # --- mining: preemption-proofing / multi-host ---
    "KMLS_CKPT_ENABLED": "mining",
    "KMLS_CKPT_DIR": "mining",
    "KMLS_CKPT_QUARANTINE_AFTER": "mining",
    "KMLS_LEASE_ENABLED": "mining",
    "KMLS_LEASE_TTL_S": "mining",
    "KMLS_LEASE_HEARTBEAT_S": "mining",
    # --- mining: storage gray-failure spine (ISSUE 19) ---
    # ENOSPC ladder floor: publication preflight requires
    # max(last-manifest bytes, this) free on the artifact volume,
    # reclaims (quarantine + orphaned temp files) when short, then
    # exits resumable (75) rather than starting a write it can't finish
    "KMLS_DISK_MIN_FREE_BYTES": "mining",
    # transient-EIO retry ladder for artifact-plane writes: attempt
    # count and exponential-backoff base (ENOSPC and fsync failures
    # never retry — see io/artifacts.py)
    "KMLS_IO_RETRIES": "mining",
    "KMLS_IO_RETRY_BASE_MS": "mining",
    # lease heartbeat self-fence: a heartbeat write stalling past this
    # fraction of the TTL means the writer can't prove it still holds
    # the lease (hung mount) — it marks itself lost and aborts resumable
    "KMLS_LEASE_STALL_FRACTION": "mining",
    "KMLS_RANK_TIMEOUT_S": "mining",
    "KMLS_RANK_HEARTBEAT_S": "mining",
    "KMLS_COLLECTIVE_TIMEOUT_S": "mining",
    "KMLS_COORDINATOR_ADDRESS": "mining",
    "KMLS_NUM_PROCESSES": "mining",
    "KMLS_PROCESS_ID": "mining",
    # --- mining: continuous freshness (ISSUE 10) ---
    # cap on the delta chain length before the pipeline forces a full
    # re-mine (accumulated patch cost + chain-replay cost at cold start)
    "KMLS_DELTA_MAX_CHAIN": "mining",
    # --- mining: quality loop (ISSUE 14) ---
    # snapshotting compactor: fold a delta chain of this length into a
    # new base bundle WITHOUT a full re-mine (0 = disabled; keep below
    # KMLS_DELTA_MAX_CHAIN so compaction fires before the hard cap)
    "KMLS_DELTA_COMPACT_AFTER": "mining",
    # offline ranking evaluation (quality/eval.py): run the optional
    # checkpointed `eval` phase after `embed` — held-out basket
    # completion scored through the production kernels, published as
    # quality.report.json via the manifest + lease path
    "KMLS_EVAL_ENABLED": "mining",
    # leave-n-out per playlist, recall@k depth, and the deterministic
    # cap on evaluated playlists (bounds eval cost at scale; 0 = all)
    "KMLS_EVAL_HOLDOUT_N": "mining",
    "KMLS_EVAL_K": "mining",
    "KMLS_EVAL_MAX_PLAYLISTS": "mining",
    # --- both workloads ---
    "KMLS_NATIVE": "both",
    # continuous freshness (ISSUE 10): mining publishes incremental
    # delta-<seq>.bundle artifacts between full re-mines; serving applies
    # them in place (engine.apply_pending_deltas) with selective cache
    # invalidation instead of a full reload
    "KMLS_DELTA_ENABLED": "both",
    "KMLS_JAX_CACHE_DIR": "both",
    # model layout: replicated per-device tensors vs vocab-sharded across
    # the mesh — read by the serving engine (rule/embedding tensors) and
    # the mining dispatch (one-hot / support counting / ALS half-sweep)
    "KMLS_MODEL_LAYOUT": "both",
    "KMLS_DEVICE_BUDGET_BYTES": "both",
    # --- bench / sweep / dev harness ---
    "KMLS_BENCH_CPU": "tool",
    "KMLS_BENCH_DEADLINE_S": "tool",
    "KMLS_BENCH_SIDECAR": "tool",
    "KMLS_BENCH_STATE": "tool",
    "KMLS_BENCH_STATE_MAX_AGE_S": "tool",
    "KMLS_BENCH_STARTUP_GRACE_S": "tool",
    "KMLS_BENCH_PROBE_INTERVAL_S": "tool",
    "KMLS_BENCH_PROBE_TIMEOUT_S": "tool",
    "KMLS_BENCH_PROBE_TIMEOUT_DECAY_S": "tool",
    "KMLS_BENCH_REPLAY_QPS": "tool",
    "KMLS_BENCH_REPLAY_REQUESTS": "tool",
    "KMLS_BENCH_REPLAY_RUNS": "tool",
    "KMLS_BENCH_REPLAY_WARMUP": "tool",
    "KMLS_BENCH_REPLAY_WORKERS": "tool",
    "KMLS_BENCH_REPLAY_QUEUE": "tool",
    "KMLS_BENCH_REPLAY10K_QPS": "tool",
    "KMLS_BENCH_REPLAY10K_REQUESTS": "tool",
    "KMLS_BENCH_REPLAY10K_ZIPF_S": "tool",
    "KMLS_BENCH_CHAOS_QPS": "tool",
    "KMLS_BENCH_CHAOS_REQUESTS": "tool",
    "KMLS_BENCH_CHAOS_ZIPF_S": "tool",
    "KMLS_BENCH_RESUME_PHASE": "tool",
    # traffic-shape replay (ISSUE 8): shape selector for the replay CLI
    # and the loadshape bench bracket's base rate / volume / burst factor
    "KMLS_REPLAY_SHAPE": "tool",
    "KMLS_BENCH_LOADSHAPE_QPS": "tool",
    "KMLS_BENCH_LOADSHAPE_REQUESTS": "tool",
    "KMLS_BENCH_LOADSHAPE_BURST": "tool",
    # tracing-overhead micro-phase (ISSUE 9): base rate / volume for the
    # sampled-vs-disabled p99 comparison bracket
    "KMLS_BENCH_TRACE_QPS": "tool",
    "KMLS_BENCH_TRACE_REQUESTS": "tool",
    # cost-attribution phase (ISSUE 12): rate / volume for the
    # serve-kernel MFU + roofline + compiles==0 bracket
    "KMLS_BENCH_COSTATTRIB_QPS": "tool",
    "KMLS_BENCH_COSTATTRIB_REQUESTS": "tool",
    # continuous-freshness phase (ISSUE 10): request rate/volume for the
    # mid-delta zero-5xx replay bracket
    "KMLS_BENCH_FRESHNESS_QPS": "tool",
    "KMLS_BENCH_FRESHNESS_REQUESTS": "tool",
    # fleet cache-routing phase (ISSUE 15): aggregate rate / volume /
    # replica count / per-replica LRU entries for the multi-process
    # routed-vs-independent bracket (the CI smoke shrinks all four)
    "KMLS_BENCH_FLEET_QPS": "tool",
    "KMLS_BENCH_FLEET_REQUESTS": "tool",
    "KMLS_BENCH_FLEET_REPLICAS": "tool",
    "KMLS_BENCH_FLEET_CACHE": "tool",
    # serve-mesh phase (ISSUE 16): rate / volume for the 2-process-gang
    # vs single-process-sharded identity + chaos bracket (CI smoke
    # shrinks both)
    "KMLS_BENCH_MESHSERVE_QPS": "tool",
    "KMLS_BENCH_MESHSERVE_REQUESTS": "tool",
    # gray-failure phase (ISSUE 18): rate / volume for the slowpeer
    # bracket's hedged-vs-control legs (CI smoke shrinks both)
    "KMLS_BENCH_SLOWPEER_QPS": "tool",
    "KMLS_BENCH_SLOWPEER_REQUESTS": "tool",
    # storage gray-failure phase (ISSUE 19): rate / volume for the
    # graystore bracket's stall-injected artifact-plane replay legs
    # (CI smoke shrinks both)
    "KMLS_BENCH_GRAYSTORE_QPS": "tool",
    "KMLS_BENCH_GRAYSTORE_REQUESTS": "tool",
    # quality-loop phase (ISSUE 14): membership-row volume of the eval/
    # compaction bracket's synthetic workload (CI smoke shrinks it)
    "KMLS_BENCH_QUALITY_ROWS": "tool",
    # sparsity-adaptive phase (ISSUE 13): the ≥99%-sparse headline
    # workload's shape (CI smoke shrinks it)
    "KMLS_BENCH_SPARSE_PLAYLISTS": "tool",
    "KMLS_BENCH_SPARSE_TRACKS": "tool",
    "KMLS_BENCH_SPARSE_ROWS": "tool",
    "KMLS_SWEEP_START": "tool",
    "KMLS_SWEEP_STOP": "tool",
    "KMLS_SWEEP_STEP": "tool",
    # --- fault injection (faults.py switchboard) ---
    "KMLS_FAULT_RELOAD_FAIL": "fault",
    "KMLS_FAULT_REPLICA_FAIL": "fault",
    "KMLS_FAULT_REPLICA_DELAY_MS": "fault",
    "KMLS_FAULT_MINE_CRASH_PHASE": "fault",
    "KMLS_FAULT_CKPT_CORRUPT": "fault",
    "KMLS_FAULT_RANK_DEAD": "fault",
    "KMLS_FAULT_EMBED_CORRUPT": "fault",
    "KMLS_FAULT_DELTA_CORRUPT": "fault",
    "KMLS_FAULT_MESH_PEER_DELAY_MS": "fault",
    "KMLS_FAULT_FLEET_PEER_DELAY_MS": "fault",
    # storage plane (ISSUE 19): path-scoped faults consumed inside
    # io/artifacts.py's single writer/reader (faults.take_io)
    "KMLS_FAULT_IO_WRITE": "fault",
    "KMLS_FAULT_IO_WRITE_STALL_MS": "fault",
    "KMLS_FAULT_IO_READ": "fault",
    "KMLS_FAULT_IO_READ_STALL_MS": "fault",
    "KMLS_FAULT_IO_FSYNC": "fault",
}

# Columns dropped from the raw CSV before any processing
# (reference: machine-learning/main.py:42).
DROP_COLUMNS = ("duration_ms",)

# First dataset index in the rotation scheme (reference: machine-learning/main.py:46).
BASE_INDEX = 1


@dataclasses.dataclass(frozen=True)
class MiningConfig:
    """Batch mining job config (reference: machine-learning/main.py:17-49,
    kubernetes/job.yaml:24-40)."""

    base_dir: str = "./api-data"
    datasets_dir: str = ""
    regex_filename: str = "2023_spotify_ds*.csv"
    min_support: float = 0.05
    pickles_folder: str = "pickles"
    recommendations_file: str = "recommendations.pickle"
    best_tracks_file: str = "best_tracks.pickle"
    data_invalidation_file: str = "last_execution.txt"
    top_tracks_save_percentile: float = 0.03
    artists_mapping_file: str = "artistsMapping.pickle"
    repeated_tracks_file: str = "trackNameToRepeatedUris.pickle"
    track_info_file: str = "trackIdsToInfo.pickle"
    datasets_list_file: str = "datasets_list.txt"
    dataset_history_file: str = "dataset_history.csv"
    sample_ratio: float = 1.0

    # --- TPU-rebuild knobs (not in the reference) ---
    # Max itemset length the miner enumerates. 2 reproduces the reference
    # fast path's OUTPUT exactly (see ops/support.py dominance note); 3/4 add
    # the itemset census + true-confidence rules.
    max_itemset_len: int = 2
    # Padded per-antecedent rule-row capacity (consequents kept per song).
    k_max_consequents: int = 256
    # "support" = reference fast-path semantics (itemset support stored as the
    # confidence, symmetric rules — machine-learning/main.py:284-296);
    # "confidence" = the dormant slow path's true asymmetric confidence
    # (machine-learning/main.py:224-260).
    confidence_mode: str = "support"
    # Minimum confidence when confidence_mode == "confidence"
    # (reference slow path hardcodes 0.04 — machine-learning/main.py:226-227).
    min_confidence: float = 0.04
    # Device-mesh shape for sharded mining: "auto", "1x1", "dpxtp" e.g.
    # "4x1", or "hybrid"/"hybrid:tpN" (DCN×ICI layout for multi-host — tp
    # pinned to intra-host devices). "auto" picks hybrid automatically when
    # the multi-host runtime is active (KMLS_COORDINATOR_ADDRESS set).
    mesh_shape: str = "auto"
    # When to use the bit-packed popcount path instead of the dense int8
    # MXU matmul (single-device AND sharded: over a mesh this selects the
    # dp-sharded popcount slabs). "auto" (default) dispatches on estimated
    # HBM footprint: dense whenever the pruned one-hot + count matrix fit
    # ``hbm_budget_bytes`` — the MXU matmul beats the VPU popcount kernel
    # by an order of magnitude whenever it fits, so element count alone is
    # the wrong dispatch key (r03: 1M×100k pruned to 5k items is 5 GiB
    # dense — easily resident — yet an element threshold routed it to the
    # slow kernel). An int forces the old explicit element threshold;
    # None disables bitpack entirely.
    bitpack_threshold_elems: int | str | None = "auto"
    # HBM the mining job may plan against for the auto dispatch. Default
    # leaves ~4 GiB of a v5e's 16 GiB for XLA workspace/fusion copies.
    hbm_budget_bytes: int = 12 * (1 << 30)
    # Sparsity-adaptive dispatch (mining/dispatch.py): "auto" (default)
    # resolves dense/bitpack/sparse from the MEASURED per-backend lookup
    # table (bench-banked; legacy heuristic when no cell matches);
    # "dense"/"bitpack"/"sparse" pin a family; any other spelling fails
    # SAFE to auto with a loud warning.
    count_path: str = "auto"
    # Alternative measured dispatch table (JSON; see
    # mining/dispatch_table.json for the banked shape). Empty = the
    # packaged bench-banked table.
    dispatch_table: str = ""
    # Baskets longer than this leave the sparse path's CSR pair
    # expansion for the gathered bitpacked/dense sub-count (the
    # quadratic-per-basket guard). 0 = the ops/sparse.py default (256).
    sparse_long_basket: int = 0
    # Sharded dense pair-count implementation: "gspmd" (annotate + let XLA
    # partition), "allgather" (explicit shard_map), "ring" (ppermute
    # neighbor exchange; lowest peak memory).
    sharded_impl: str = "gspmd"
    # Model layout (parallel/layout.py — shared with the serving side):
    # "replicated" keeps the legacy single-device-shaped mining compute;
    # "sharded" lays the one-hot, the support counts, the rule emission,
    # and the ALS item half-sweep out along the vocab axis of the mesh
    # (a 1xN vocab-major mesh is built automatically when none is given),
    # so the encode/mine phases accept inputs whose dense replicated
    # formulation cannot fit one device; "auto" engages the sharded path
    # only when the configured mesh already spans the vocab axis.
    model_layout: str = "replicated"
    # Per-device byte budget the LAYOUT decision measures against (the
    # serving engine's auto trigger; distinct from hbm_budget_bytes,
    # which routes the bitpack-vs-dense COUNTING dispatch). 0 = fall
    # back to hbm_budget_bytes.
    device_budget_bytes: int = 0
    # Above this vocabulary size, prune infrequent items (exact, by the
    # Apriori property) before pair counting — the path that makes the
    # 1M-track configs feasible (a dense 1M x 1M count matrix is 4 TB).
    # Low by default: pruning is exact and pays at EVERY scale — it shrinks
    # the matmul, the emission, and (the TPU bracket's floor through a
    # tunneled link) the rule-tensor fetch, e.g. ds2's 2171 rows -> its 429
    # frequent items. The threshold only spares tiny vocabularies the
    # (trivial) host bincount.
    prune_vocab_threshold: int = 512
    # Write the tensor-native artifact (rules npz) alongside the pickles.
    write_tensor_artifact: bool = True
    # Write the integrity manifest (artifacts.manifest.json: size + sha256
    # per artifact) after each artifact set — the serving engine validates
    # against it before publishing a bundle, so a torn/corrupt artifact is
    # caught before it can poison a reload.
    write_manifest: bool = True
    # On a CPU backend (no TPU reachable), count pair supports with the
    # native bit-packed POPCNT kernel (native/kmls_popcount.cpp) instead of
    # XLA:CPU's int8 matmul — exact, ~40x faster on the dominant phase.
    # Ignored on TPU; falls back automatically when the .so can't build.
    native_cpu_pair_counts: bool = True

    # --- second model family: ALS embedding phase (mining/als.py) ---
    # Optional `embed` pipeline phase after `rules`: train ALS item
    # embeddings over the playlist×track matrix and publish embeddings.npz
    # through the same manifest + lease-fenced path as the rule tensors.
    # Off by default — the reference pipeline has no embedding model, and
    # the serving side degrades to rules-only when the artifact is absent.
    embed_enabled: bool = False
    # Factorization rank (embedding dimension).
    als_rank: int = 32
    # Alternating sweeps (users then items per sweep).
    als_iters: int = 8
    # L2 regularization λ on both factor matrices.
    als_reg: float = 0.1
    # Interaction-matrix storage for the ALS half-sweeps (mining/als.py):
    # "auto" = dense while the dense f32 matrix fits the HBM guard,
    # compressed (indices-only, nnz-proportional) exactly when it does
    # not — the case that previously SKIPPED the embed phase; "always" /
    # "never" pin it. Sparse factors are float-different from dense ones
    # (accumulation order), so this knob joins the checkpoint
    # fingerprint like model_layout did.
    als_sparse: str = "auto"

    # --- continuous freshness (ISSUE 10) ---
    # Incremental delta mining: after a full publication the pipeline
    # saves a freshness base state (encode membership + published rule
    # tensors + dataset byte-prefix fingerprint); a later run finds the
    # dataset grew append-only and publishes a delta-<seq>.bundle (changed
    # rule rows + tombstones, base-sha256-bound) through the lease path
    # instead of re-mining everything. Off by default — the reference has
    # no incremental posture, and serving ignores chains unless its own
    # KMLS_DELTA_ENABLED is set.
    delta_enabled: bool = False
    # Chain cap: at this many unapplied-on-top-of-base deltas the next
    # run full-re-mines instead (bounds cold-start chain replay and
    # accumulated patch drift surface). 0 = unlimited.
    delta_max_chain: int = 16

    # --- quality loop (ISSUE 14) ---
    # Snapshotting compactor (quality/lifecycle.py): once the delta
    # chain reaches this length, fold base ∘ chain into a new base
    # bundle WITHOUT a full re-mine — the canonical delta application
    # makes the fold bit-identical to the chain it replaces. 0 disables
    # (KMLS_DELTA_MAX_CHAIN stays the hard full-re-mine backstop; keep
    # this below it so the cheap snapshot fires first).
    delta_compact_after: int = 0
    # Offline ranking evaluation (quality/eval.py): run the optional
    # checkpointed `eval` phase after `embed` — deterministic held-out
    # basket-completion recall@k / MRR / coverage per serving mode
    # through the production kernels, plus the blend-weight sweep —
    # published as quality.report.json through the manifest+lease path.
    # Off by default: eval re-trains both model families on the train
    # split, roughly doubling job compute.
    eval_enabled: bool = False
    # Tracks held out per playlist (playlists shorter than holdout+2
    # are not evaluated — something must remain to seed with).
    eval_holdout_n: int = 1
    # recall@k depth — matches serving's K_BEST_TRACKS default.
    eval_k: int = 10
    # Deterministic cap on evaluated playlists (hash-selected, not a
    # prefix slice); bounds eval cost at scale. 0 = evaluate all.
    eval_max_playlists: int = 2048

    # --- mining telemetry (ISSUE 9) ---
    # Write per-phase progress/duration/bytes counters to
    # pickles/job_metrics.prom (node-exporter textfile-collector format)
    # through the atomic-write path, rewritten as each phase completes —
    # a preempted job leaves the telemetry of the phases it DID finish,
    # and a resumed job reports the compute it skipped.
    job_metrics: bool = True

    # --- preemption-proofing knobs (checkpoint / lease / watchdog) ---
    # Phase-level checkpointing: after each expensive phase (encode, mine,
    # rules) the writer rank persists an atomic, sha256-manifested
    # checkpoint keyed by a config+dataset fingerprint, so a preempted/
    # evicted job resumes from the last completed phase instead of
    # recomputing everything. Retired automatically after a successful
    # publication (the next rotation run starts fresh).
    checkpoint_enabled: bool = True
    # Checkpoint directory; empty = <base_dir>/mining_checkpoint (on the
    # PVC, so a replacement pod sees its predecessor's progress).
    checkpoint_dir: str = ""
    # A checkpoint whose bytes verify but fail to UNPICKLE this many
    # consecutive loads is quarantined (pickles-style quarantine dir) and
    # recomputed — one torn read must not cost a good checkpoint, but a
    # poison one must not wedge every restart. 0 disables quarantining.
    checkpoint_quarantine_after: int = 2
    # Lease-fenced publication: the rank-0 writer takes a heartbeat lease
    # (pickles/publish.lease.json) with a monotonically-increasing fencing
    # token before mining and re-validates it before every publication
    # step — a zombie job superseded by an ArgoCD Replace cannot tear
    # artifacts a newer run already published.
    lease_enabled: bool = True
    # A lease whose heartbeat is older than this is expired (its writer
    # died) and can be taken over by the next job.
    lease_ttl_s: float = 60.0
    # Heartbeat period; 0 = ttl/3.
    lease_heartbeat_interval_s: float = 0.0
    # Dead-rank watchdog (multi-host jobs only): every rank heartbeats a
    # shared file every rank_heartbeat_interval_s; a peer silent for
    # rank_timeout_s turns the would-be forever-hang into a bounded-time
    # abort with the resumable EXIT_RANK_DEAD code (mining/job.py).
    # 0 disables.
    rank_timeout_s: float = 300.0
    rank_heartbeat_interval_s: float = 5.0
    # Deadline for one guarded COLLECTIVE section (the mine). Separate
    # from — and much larger than — rank_timeout_s: the guard brackets
    # real compute, and a legitimately long mine must not read as a hang
    # (a shared timeout would livelock every restart into the same
    # too-long recompute). Keep below the Job's activeDeadlineSeconds;
    # 0 = 6 × rank_timeout_s.
    collective_timeout_s: float = 1800.0
    # Storage gray-failure spine (ISSUE 19): operator floor for the
    # publication free-space preflight — publication requires
    # max(estimated artifact bytes, this) free, reclaims, then exits
    # resumable. 0 disables the preflight.
    disk_min_free_bytes: int = 64 * (1 << 20)
    # Lease heartbeat self-fence threshold as a fraction of the TTL
    # (0 disables self-fencing).
    lease_stall_fraction: float = 0.5

    @property
    def pickles_dir(self) -> str:
        return os.path.join(self.base_dir, self.pickles_folder)

    @property
    def checkpoint_path(self) -> str:
        return self.checkpoint_dir or os.path.join(
            self.base_dir, "mining_checkpoint"
        )

    @staticmethod
    def from_env(dotenv_path: str | None = ".env") -> "MiningConfig":
        if dotenv_path:
            load_dotenv(dotenv_path)
        base_dir = os.getenv("BASE_DIR", "./api-data")
        return MiningConfig(
            base_dir=base_dir,
            datasets_dir=os.getenv("DATASETS_DIR", os.path.join(base_dir, "datasets")),
            regex_filename=os.getenv("REGEX_FILENAME", "2023_spotify_ds*.csv"),
            min_support=_getenv_float("MIN_SUPPORT", 0.05),
            pickles_folder=os.getenv("PICKLES_FOLDER", "pickles"),
            recommendations_file=os.getenv("RECOMMENDATIONS_FILE", "recommendations.pickle"),
            best_tracks_file=os.getenv("BEST_TRACKS_FILE", "best_tracks.pickle"),
            data_invalidation_file=os.getenv("DATA_INVALIDATION_FILE", "last_execution.txt"),
            top_tracks_save_percentile=_getenv_float("TOP_TRACKS_SAVE_PERCENTILE", 0.03),
            artists_mapping_file=os.getenv("ARTISTS_MAPPING_FILE", "artistsMapping.pickle"),
            repeated_tracks_file=os.getenv("REPEATED_TRACKS_FILE", "trackNameToRepeatedUris.pickle"),
            track_info_file=os.getenv("TRACK_INFO_FILE", "trackIdsToInfo.pickle"),
            datasets_list_file=os.getenv("DATASETS_LIST_FILE", "datasets_list.txt"),
            dataset_history_file=os.getenv("DATASET_HISTORY_FILE", "dataset_history.csv"),
            sample_ratio=_getenv_float("SAMPLE_RATIO", 1.0),
            max_itemset_len=_getenv_int("KMLS_MAX_ITEMSET_LEN", 2),
            k_max_consequents=_getenv_int("KMLS_K_MAX_CONSEQUENTS", 256),
            confidence_mode=os.getenv("KMLS_CONFIDENCE_MODE", "support"),
            min_confidence=_getenv_float("KMLS_MIN_CONFIDENCE", 0.04),
            mesh_shape=os.getenv("KMLS_MESH_SHAPE", "auto"),
            bitpack_threshold_elems=_getenv_bitpack_threshold(),
            count_path=os.getenv("KMLS_COUNT_PATH", "auto"),
            dispatch_table=os.getenv("KMLS_DISPATCH_TABLE", ""),
            sparse_long_basket=_getenv_int("KMLS_SPARSE_LONG_BASKET", 0),
            hbm_budget_bytes=_getenv_int("KMLS_HBM_BUDGET_BYTES", 12 * (1 << 30)),
            sharded_impl=os.getenv("KMLS_SHARDED_IMPL", "gspmd"),
            model_layout=_getenv_model_layout(),
            device_budget_bytes=_getenv_int("KMLS_DEVICE_BUDGET_BYTES", 0),
            prune_vocab_threshold=_getenv_int("KMLS_PRUNE_VOCAB_THRESHOLD", 512),
            write_tensor_artifact=_getenv_bool("KMLS_WRITE_TENSOR_ARTIFACT", True),
            write_manifest=_getenv_bool("KMLS_WRITE_MANIFEST", True),
            native_cpu_pair_counts=_getenv_bool("KMLS_NATIVE_PAIR_COUNTS", True),
            embed_enabled=_getenv_bool("KMLS_EMBED_ENABLED", False),
            als_rank=_getenv_int("KMLS_ALS_RANK", 32),
            als_iters=_getenv_int("KMLS_ALS_ITERS", 8),
            als_reg=_getenv_float("KMLS_ALS_REG", 0.1),
            als_sparse=os.getenv("KMLS_ALS_SPARSE", "auto"),
            delta_enabled=_getenv_bool("KMLS_DELTA_ENABLED", False),
            delta_max_chain=_getenv_int("KMLS_DELTA_MAX_CHAIN", 16),
            delta_compact_after=_getenv_int("KMLS_DELTA_COMPACT_AFTER", 0),
            eval_enabled=_getenv_bool("KMLS_EVAL_ENABLED", False),
            eval_holdout_n=_getenv_int("KMLS_EVAL_HOLDOUT_N", 1),
            eval_k=_getenv_int("KMLS_EVAL_K", 10),
            eval_max_playlists=_getenv_int("KMLS_EVAL_MAX_PLAYLISTS", 2048),
            job_metrics=_getenv_bool("KMLS_JOB_METRICS", True),
            checkpoint_enabled=_getenv_bool("KMLS_CKPT_ENABLED", True),
            checkpoint_dir=os.getenv("KMLS_CKPT_DIR", ""),
            checkpoint_quarantine_after=_getenv_int(
                "KMLS_CKPT_QUARANTINE_AFTER", 2
            ),
            lease_enabled=_getenv_bool("KMLS_LEASE_ENABLED", True),
            lease_ttl_s=_getenv_float("KMLS_LEASE_TTL_S", 60.0),
            lease_heartbeat_interval_s=_getenv_float(
                "KMLS_LEASE_HEARTBEAT_S", 0.0
            ),
            rank_timeout_s=_getenv_float("KMLS_RANK_TIMEOUT_S", 300.0),
            rank_heartbeat_interval_s=_getenv_float(
                "KMLS_RANK_HEARTBEAT_S", 5.0
            ),
            collective_timeout_s=_getenv_float(
                "KMLS_COLLECTIVE_TIMEOUT_S", 1800.0
            ),
            disk_min_free_bytes=_getenv_int(
                "KMLS_DISK_MIN_FREE_BYTES", 64 * (1 << 20)
            ),
            lease_stall_fraction=_getenv_float(
                "KMLS_LEASE_STALL_FRACTION", 0.5
            ),
        )


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Online API config (reference: rest_api/app/main.py:31-50,
    kubernetes/deployment.yaml:33-53)."""

    version: str = "V1.1"
    base_dir: str = "./api-data/"
    pickle_dir: str = "pickles/"
    app_path_from_root: str = "/app"
    recommendations_file: str = "recommendations.pickle"
    best_tracks_file: str = "best_tracks.pickle"
    data_invalidation_file: str = "last_execution.txt"
    k_best_tracks: int = 10
    polling_wait_in_minutes: float = 5.0

    # --- TPU-rebuild knobs ---
    port: int = 80
    # Max seed songs per request the jitted kernel is specialized for;
    # requests are bucketed to powers of two up to this bound.
    max_seed_tracks: int = 128
    # Micro-batching window for aggregating concurrent requests into one
    # device call (milliseconds); 0 disables batching. With the adaptive
    # controller on, this is the window CEILING — the controller sizes the
    # actual wait from the observed arrival rate and the shed budget.
    batch_window_ms: float = 2.0
    batch_max_size: int = 32
    # Adaptive deadline-aware window: size the collection wait from the
    # arrival-gap EWMA (time to fill the batch at the current rate) instead
    # of always burning the full fixed window. Off = fixed window.
    batch_adaptive_window: bool = True
    # Floor for the adaptive window (milliseconds). Not lower: closed-loop
    # clients arrive in bursts (a completed batch releases its waiters at
    # once), and a near-zero floor splits each wave into undersized
    # batches — measured 896 vs 1000+ QPS through the 65 ms-RTT tunnel
    # model at 0.2 ms.
    batch_window_min_ms: float = 1.0
    # Load shedding: when the EFFECTIVE queue wait for a new request
    # (max of the instantaneous projection and the measured queue-wait
    # EWMA) exceeds this budget (milliseconds), the request is shed with
    # HTTP 429 + Retry-After instead of rotting in the queue
    # (backpressure made visible, not a silent p99 cliff). 0 disables
    # admission control entirely.
    shed_queue_budget_ms: float = 250.0
    # Retry-After hint (seconds) returned with a 429 shed — the BASE
    # value; the controller jitters it (see shed_retry_jitter).
    shed_retry_after_s: float = 1.0
    # Adaptive admission ladder (ISSUE 8): pressure = effective queue
    # wait / budget. Below soft_ratio every request is admitted at full
    # quality; between soft_ratio and 1.0 a rising fraction of cache
    # MISSES degrades to the popularity fallback (200 + X-KMLS-Degraded:
    # overload — hits are untouched); between 1.0 and hard_ratio a
    # rising fraction sheds (429) and the rest degrades; past hard_ratio
    # everything sheds. soft_ratio=1 + hard_ratio=1 restores the legacy
    # cliff-at-the-budget behavior.
    shed_soft_ratio: float = 0.6
    shed_hard_ratio: float = 1.5
    # Bounded Retry-After jitter: the 429 header carries a value uniform
    # on base*(1 ± this fraction). A constant Retry-After re-synchronizes
    # every shed client into one retry wave exactly one hint later — the
    # storm the shed was supposed to absorb. 0 restores the constant.
    shed_retry_jitter: float = 0.5
    # Device-call pipeline depth PER REPLICA: batches dispatched but not yet
    # completed. >1 overlaps the next batch's dispatch with the previous
    # transfer — essential when the host<->device link is high-latency
    # (remote tunnel). The aggregate pipeline bound is this times the
    # number of serving replicas.
    batch_max_inflight: int = 4
    # Serving replicas, one per local device: 0 = auto (every local device
    # on accelerator backends; 1 on CPU, where the native host kernel owns
    # the hot path and extra virtual-device replicas only multiply warmup
    # compiles). N > 0 pins min(N, local device count) replicas — e.g.
    # KMLS_SERVE_DEVICES=8 on an 8-virtual-device CPU host exercises the
    # full data-parallel dispatch tier without hardware.
    serve_devices: int = 0
    # Model layout for the published serving tensors (parallel/layout.py,
    # shared with the mining side): "replicated" = one full rule-tensor
    # copy per serving device (PR 2's data-parallel replicas, the
    # default); "sharded" = ONE logical model vocab-sharded across every
    # serving device via NamedSharding — per-device HBM holds V/S rule
    # rows, so the servable catalog scales with the mesh; "auto" measures
    # the loaded tensor bytes against device_budget_bytes and shards only
    # when a replica would not fit. Sharded layout serves through the
    # jitted sharded kernel (the native host kernel has no per-device
    # state to partition, so it is bypassed) and presents as one replica
    # to the dispatcher.
    model_layout: str = "replicated"
    # Per-device byte budget the auto layout measures rule+confidence
    # tensor bytes against. 0 disables the auto trigger (auto then always
    # resolves to replicated).
    device_budget_bytes: int = 12 * (1 << 30)
    # Epoch-keyed recommendation cache in front of the batcher: answers are
    # keyed by (bundle epoch, canonicalized seed set), so a bundle hot-swap
    # invalidates the whole cache for free (the epoch moves, old keys can
    # never match again). 0 entries — or KMLS_CACHE_ENABLED=0 — disables.
    cache_enabled: bool = True
    cache_max_entries: int = 8192
    # Prefer the tensor-native npz artifact over the pickle when present.
    prefer_tensor_artifact: bool = True
    # On a CPU backend, serve lookups with the native C++ kernel
    # (native/kmls_serve.cpp) instead of the jitted XLA kernel — exact
    # (lax.top_k tie order reproduced), ~24x faster on the scatter-bound
    # XLA:CPU path (measured 12.6 -> 0.52 ms per 32-row ds2 batch).
    # Ignored on accelerators; falls back automatically when the .so
    # can't build. KMLS_NATIVE=0 also kills it.
    native_serve: bool = True

    # --- robustness knobs (fault-tolerance layer) ---
    # Validate artifacts against the mining job's integrity manifest
    # (artifacts.manifest.json) before publishing a bundle; a mismatched
    # best/recommendations pickle aborts the reload (last-good keeps
    # serving), a mismatched npz falls back to the pickle. No manifest on
    # the PVC (older miner, or the reference's) = no validation.
    verify_manifest: bool = True
    # Move an artifact that keeps failing to load/verify into
    # pickles/quarantine/ after this many CONSECUTIVE failed reloads (a
    # single mid-update mismatch resolves itself next poll and must not
    # cost a good file). 0 disables quarantining.
    quarantine_after_failures: int = 2
    # Exponential backoff between FAILED reload attempts (corrupt
    # artifacts, not merely-missing ones): base doubles per consecutive
    # failure up to max. Keeps a poison artifact from turning the poller
    # into a checksum-hashing busy loop; the invalidation token is never
    # consumed, so the retry ladder always ends in a reload of whatever
    # the miner writes next.
    reload_backoff_base_s: float = 0.5
    reload_backoff_max_s: float = 30.0
    # Storage gray-failure spine (ISSUE 19): deadline on reload-path
    # artifact reads — a hung NFS read fails the reload into the normal
    # backoff ladder above (last-good keeps serving) instead of wedging
    # the reload thread forever. 0 disables the deadline.
    io_read_deadline_s: float = 0.0
    # Per-replica consecutive-failure circuit breaker in the batchers:
    # after this many consecutive batch failures a replica is EJECTED from
    # the least-loaded dispatcher (its in-flight requests re-dispatch to
    # healthy replicas) and probed for re-admission every
    # replica_probe_interval_s. 0 disables ejection.
    replica_eject_threshold: int = 3
    replica_probe_interval_s: float = 5.0
    # Bounded re-dispatch: how many times one request may be re-queued
    # after a batch failure before the failure propagates (and the HTTP
    # layer degrades it). Keep >= replica_eject_threshold: a sick replica
    # fails at most eject_threshold batches before the breaker takes it
    # out, so a request that can retry that many times is GUARANTEED to
    # outlive any single-replica failure burst.
    redispatch_max_retries: int = 3
    # Per-request deadline budget (milliseconds), propagated cache →
    # batcher → device: on exhaustion the request degrades to the
    # popularity-fallback answer with an X-KMLS-Degraded header instead
    # of queueing forever or 500ing. 0 disables deadlines.
    request_deadline_ms: float = 0.0
    # Latency budget for the degraded popularity-fallback answer itself:
    # past the request deadline the sampler is skipped for a head slice
    # of the popularity ranking (cheapest possible answer).
    fallback_budget_ms: float = 50.0

    # --- continuous freshness (ISSUE 10) ---
    # Apply delta bundles published between full re-mines: the poll loop
    # checks the delta chain alongside the invalidation token and patches
    # the live per-device tensors in place (epoch advances to a
    # (base, delta_seq) pair; the answer cache invalidates selectively).
    # Off by default; a full token rewrite always behaves as before.
    delta_enabled: bool = False
    # Rendezvous-hash request affinity (freshness/ring.py): when on, the
    # app counts ring-local vs ring-remote requests over the peer set so
    # operators can measure the affinity win before routing on it.
    cache_affinity: bool = False
    # Comma-separated replica identities (headless-Service pod DNS names);
    # this replica's own identity (default: hostname) is added if absent.
    cache_affinity_peers: str = ""
    cache_affinity_self: str = ""

    # --- fleet cache routing (ISSUE 15) ---
    # Stable replica identity for the ROUTING tier (the acted-on twin of
    # the measurement knobs above): a non-empty fleet_peers arms
    # owner-aware serving — the app builds the canonical rendezvous ring
    # over these identities, answers every request locally (mis-routed
    # traffic degrades gracefully, never fails), stamps
    # X-KMLS-Cache-Owner on answers this replica does not own, and
    # counts non-owned misses as kmls_cache_misrouted_total so routing
    # drift at the ingress/client is observable. Under the StatefulSet
    # recipe (kubernetes/statefulset.yaml) fleet_self is the pod's own
    # stable ordinal name; empty falls back to the hostname, which IS
    # that name in-cluster.
    fleet_self: str = ""
    fleet_peers: str = ""

    # --- pod-spanning serve mesh (ISSUE 16) ---
    # Gang bootstrap mirroring the mining job's KMLS_PROCESS_ID recipe:
    # serve_gang_size > 1 arms the "mesh" layout — engine.load() on each
    # gang member holds only its own vocab slab (rows
    # [rank·slab, (rank+1)·slab)), serves per-slab top-k partials to its
    # peers over the partial-fetch protocol (serving/mesh.py), and
    # merges all slabs' partials exactly like the single-process sharded
    # kernel's all_gather + max-merge — the gang presents ONE logical
    # replica to the dispatcher and ONE ring member to the FleetRouter.
    # coordinator is rank 0's partial-fetch address ("host:port"; the
    # k8s recipe points it at the headless-Service ordinal-0 DNS name,
    # the CPU simulation at 127.0.0.1 with per-rank ports base+rank);
    # rank falls back to the hostname's trailing ordinal (the
    # StatefulSet pod identity), mirroring JOB_COMPLETION_INDEX.
    serve_gang_coordinator: str = ""
    serve_gang_size: int = 1
    serve_gang_rank: int = 0
    serve_gang_port: int = 8477

    # --- gray-failure spine (ISSUE 18) ---
    # Hedged dispatch master switch. False (default) is the proven-
    # zero-cost path: no hedge bookkeeping allocated, the module hedge
    # counters stay pinned at 0, and the PR 8 admission ladder has
    # structurally no hedge input (hedges are client/coordinator-side —
    # they never enter the admission queue as a new class of work).
    hedge_enabled: bool = False
    # Slow-outlier ladder: eject a peer whose EWMA latency exceeds
    # ratio × the healthy-peer median (FleetRouter.mark_latency /
    # MeshCoordinator rank tracking). 0 disables the ladder.
    peer_slow_ratio: float = 0.0
    # Hedge trigger floor (ms): the adaptive per-peer delay — tracked
    # latency ~p95 — never fires earlier than this, so a cold router
    # can't hedge on noise.
    hedge_delay_ms: float = 30.0
    # Amplification bound: a token bucket earns this fraction per
    # primary dispatch and each hedge spends one token — extra
    # dispatches are structurally ≤ this fraction of total. An empty
    # bucket means plain waiting, never an unbounded retry storm.
    hedge_max_frac: float = 0.05

    # --- observability (ISSUE 9): span tracing + runtime health ---
    # Baseline retention probability for OK traces once tracing is on.
    # 0 (default) disables tracing entirely: no trace context, no id
    # generation, no per-request allocation anywhere on the hot path
    # (the SpanRecorder's `began` counter proves it, compile-counter
    # style). With any sample > 0, retention is TAIL-BASED: every shed/
    # degraded/deadline-exceeded/error trace and the slowest-N OK traces
    # are always kept; this knob only rates the representative baseline.
    trace_sample: float = 0.0
    # Ring capacity of retained traces served at GET /debug/traces.
    trace_buffer: int = 512
    # How many slowest-OK traces the tail-based policy always retains.
    trace_slow_n: int = 32
    # Event-loop-lag collector (closes the PR 8 inline-path blind spot):
    # peak-hold decay half-life for the stall estimate exported as
    # kmls_loop_lag_ms and folded into AdmissionController pressure.
    # 0 disables the collector and the pressure fold.
    loop_lag_half_life_s: float = 1.0

    # --- device-truth cost attribution + SLOs (ISSUE 12) ---
    # Per-kernel cost attribution (observability/costmodel.py): fenced
    # device seconds × analytic FLOPs/bytes specs → achieved rates, MFU
    # vs the backend peak, roofline class, live compile counter, and
    # the publish-time memory accounting — all at /metrics. Off = the
    # engine holds no cost model at all (one is-None check per batch;
    # the module observation counter proves zero work, test-pinned).
    costmodel_enabled: bool = True
    # SLO burn rates (observability/slo.py, /debug/slo +
    # kmls_slo_burn_rate): the p99-latency target (snapped up to the
    # nearest histogram bucket boundary), the availability (errors +
    # sheds) and quality (degraded answers) budgets as bad-event
    # fractions, and the fast/slow alerting windows.
    slo_p99_ms: float = 25.0
    slo_error_budget: float = 0.001
    slo_degrade_budget: float = 0.01
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0

    # --- predictive serving (ISSUE 17, serving/forecast.py) ---
    # Online arrival-rate + request-mix forecaster feeding the three
    # predictive actuators (batch-window pre-widening + shape pre-touch,
    # the bounded HPA-lead term in kmls_utilization, owner-targeted
    # post-delta cache pre-fetch). Off (default) = the app holds no
    # forecaster at all: every call site is one is-None check, and the
    # module observation counter proves zero work (test-pinned, the
    # KMLS_COSTMODEL pattern). A wrong forecast can only over-provision
    # — the admission ladder never reads it, so shedding can never start
    # earlier than reactive.
    forecast_enabled: bool = False
    # How far ahead the rate prediction looks: predicted = level +
    # trend·horizon. Matches the scale-out lead the HPA can actually
    # use (its scaleUp stabilization window is 15 s; the batcher's
    # actuators work at sub-second scale from the same prediction).
    forecast_horizon_s: float = 2.0
    # Width of the arrival-count windows the level/trend EWMAs smooth
    # over; silent windows fold in as zeros so the forecast decays in
    # real time after a burst.
    forecast_window_s: float = 0.5
    # Smoothing factor for the rate level (the trend term uses 0.3,
    # fixed — one knob tunes responsiveness, the pair stays stable).
    forecast_alpha: float = 0.35
    # Ceiling on the forecast CONTRIBUTION to kmls_utilization: the
    # lead term is clamped to [reactive, this cap], so prediction alone
    # can drive the HPA to the cap but only measured overload reports
    # past it.
    forecast_util_cap: float = 1.0
    # Growth ratio (predicted/current rate) that arms the pre-widen/
    # pre-touch actuators; below it the batcher behaves exactly
    # reactively.
    forecast_ramp_ratio: float = 1.2
    # How many predicted-hot seed sets the post-delta pre-fetch
    # re-materializes (owner-owned, invalidation-cold sets only).
    forecast_prefetch_top_n: int = 8

    # --- second model family: hybrid rule∪embedding serving ---
    # How the two model families combine when an embedding artifact is
    # published: "rules" ignores embeddings entirely (the legacy path),
    # "embed" serves embedding top-k (rules only when the seeds are
    # unknown to the embedding vocab), "blend" unions both candidate
    # lists with blended scores. With no embedding artifact on the PVC —
    # or one that fails validation — every mode serves rules-only.
    hybrid_mode: str = "blend"
    # Weight of the EMBEDDING similarity in blend mode: blended score =
    # (1 - w)·rule_confidence + w·cosine_similarity. 0 ranks like
    # rules-only (embeddings still backfill rule-less candidates),
    # 1 like embed-only.
    hybrid_blend_weight: float = 0.5
    # KMLS_HYBRID_BLEND_WEIGHT=measured (ISSUE 14): serve the blend
    # optimum the quality loop's held-out sweep published in
    # quality.report.json. An explicit float wins (measured stays
    # False); an absent/unusable report fails safe to the default
    # weight above, with a warning at load.
    hybrid_blend_measured: bool = False
    # Per-artifact staleness bound (ISSUE 14): when any served artifact
    # (rules/delta-chain/embeddings/popularity) is older than this many
    # seconds, /readyz reports ready-but-degraded with the stale
    # artifact named and kmls_artifact_stale{artifact} flips to 1 — an
    # aging embeddings.npz becomes visible before it misleads.
    # 0 disables (the age gauges stay observability-only).
    artifact_max_age_s: float = 0.0

    @property
    def pickles_dir(self) -> str:
        return os.path.join(self.base_dir, self.pickle_dir)

    @staticmethod
    def from_env(dotenv_path: str | None = ".env") -> "ServingConfig":
        if dotenv_path:
            load_dotenv(dotenv_path)
        base_dir = os.getenv("BASE_DIR", "./api-data/")
        _blend_weight, _blend_measured = _getenv_blend_weight()
        return ServingConfig(
            version=os.getenv("VERSION", "V1.1"),
            base_dir=base_dir,
            pickle_dir=os.getenv("PICKLE_DIR", "pickles/"),
            app_path_from_root=os.getenv("APP_PATH_FROM_ROOT", "/app"),
            recommendations_file=os.getenv("RECOMMENDATIONS_FILE", "recommendations.pickle"),
            best_tracks_file=os.getenv("BEST_TRACKS_FILE", "best_tracks.pickle"),
            data_invalidation_file=os.getenv("DATA_INVALIDATION_FILE", "last_execution.txt"),
            k_best_tracks=_getenv_int("K_BEST_TRACKS", 10),
            polling_wait_in_minutes=_getenv_float("POLLING_WAIT_IN_MINUTES", 5.0),
            port=_getenv_int("KMLS_PORT", 80),
            max_seed_tracks=_getenv_int("KMLS_MAX_SEED_TRACKS", 128),
            batch_window_ms=_getenv_float("KMLS_BATCH_WINDOW_MS", 2.0),
            batch_max_size=_getenv_int("KMLS_BATCH_MAX_SIZE", 32),
            batch_adaptive_window=_getenv_bool("KMLS_BATCH_ADAPTIVE", True),
            batch_window_min_ms=_getenv_float("KMLS_BATCH_WINDOW_MIN_MS", 1.0),
            shed_queue_budget_ms=_getenv_float("KMLS_SHED_QUEUE_BUDGET_MS", 250.0),
            shed_retry_after_s=_getenv_float("KMLS_SHED_RETRY_AFTER_S", 1.0),
            shed_soft_ratio=_getenv_float("KMLS_SHED_SOFT_RATIO", 0.6),
            shed_hard_ratio=_getenv_float("KMLS_SHED_HARD_RATIO", 1.5),
            shed_retry_jitter=_getenv_float("KMLS_SHED_RETRY_JITTER", 0.5),
            batch_max_inflight=_getenv_int("KMLS_BATCH_MAX_INFLIGHT", 4),
            serve_devices=_getenv_int("KMLS_SERVE_DEVICES", 0),
            model_layout=_getenv_model_layout(),
            device_budget_bytes=_getenv_int(
                "KMLS_DEVICE_BUDGET_BYTES", 12 * (1 << 30)
            ),
            cache_enabled=_getenv_bool("KMLS_CACHE_ENABLED", True),
            cache_max_entries=_getenv_int("KMLS_CACHE_MAX_ENTRIES", 8192),
            prefer_tensor_artifact=_getenv_bool("KMLS_PREFER_TENSOR_ARTIFACT", True),
            native_serve=_getenv_bool("KMLS_NATIVE_SERVE", True),
            verify_manifest=_getenv_bool("KMLS_VERIFY_MANIFEST", True),
            quarantine_after_failures=_getenv_int(
                "KMLS_QUARANTINE_AFTER_FAILURES", 2
            ),
            reload_backoff_base_s=_getenv_float("KMLS_RELOAD_BACKOFF_BASE_S", 0.5),
            reload_backoff_max_s=_getenv_float("KMLS_RELOAD_BACKOFF_MAX_S", 30.0),
            io_read_deadline_s=_getenv_float("KMLS_IO_READ_DEADLINE_S", 0.0),
            replica_eject_threshold=_getenv_int("KMLS_REPLICA_EJECT_THRESHOLD", 3),
            replica_probe_interval_s=_getenv_float(
                "KMLS_REPLICA_PROBE_INTERVAL_S", 5.0
            ),
            redispatch_max_retries=_getenv_int("KMLS_REDISPATCH_MAX_RETRIES", 3),
            request_deadline_ms=_getenv_float("KMLS_REQUEST_DEADLINE_MS", 0.0),
            fallback_budget_ms=_getenv_float("KMLS_FALLBACK_BUDGET_MS", 50.0),
            hybrid_mode=_getenv_hybrid_mode(),
            hybrid_blend_weight=_blend_weight,
            hybrid_blend_measured=_blend_measured,
            artifact_max_age_s=_getenv_float("KMLS_ARTIFACT_MAX_AGE_S", 0.0),
            delta_enabled=_getenv_bool("KMLS_DELTA_ENABLED", False),
            cache_affinity=_getenv_bool("KMLS_CACHE_AFFINITY", False),
            cache_affinity_peers=os.getenv("KMLS_CACHE_AFFINITY_PEERS", ""),
            cache_affinity_self=os.getenv("KMLS_CACHE_AFFINITY_SELF", ""),
            fleet_self=os.getenv("KMLS_FLEET_SELF", ""),
            fleet_peers=os.getenv("KMLS_FLEET_PEERS", ""),
            serve_gang_coordinator=os.getenv(
                "KMLS_SERVE_GANG_COORDINATOR", ""
            ),
            serve_gang_size=_getenv_int("KMLS_SERVE_GANG_SIZE", 1),
            serve_gang_rank=_getenv_gang_rank(),
            serve_gang_port=_getenv_int("KMLS_SERVE_GANG_PORT", 8477),
            hedge_enabled=_getenv_bool("KMLS_HEDGE", False),
            peer_slow_ratio=_getenv_float("KMLS_PEER_SLOW_RATIO", 0.0),
            hedge_delay_ms=_getenv_float("KMLS_HEDGE_DELAY_MS", 30.0),
            hedge_max_frac=_getenv_float("KMLS_HEDGE_MAX_FRAC", 0.05),
            trace_sample=_getenv_float("KMLS_TRACE_SAMPLE", 0.0),
            trace_buffer=_getenv_int("KMLS_TRACE_BUFFER", 512),
            trace_slow_n=_getenv_int("KMLS_TRACE_SLOW_N", 32),
            loop_lag_half_life_s=_getenv_float(
                "KMLS_LOOP_LAG_HALF_LIFE_S", 1.0
            ),
            costmodel_enabled=_getenv_bool("KMLS_COSTMODEL", True),
            slo_p99_ms=_getenv_float("KMLS_SLO_P99_MS", 25.0),
            slo_error_budget=_getenv_float("KMLS_SLO_ERROR_BUDGET", 0.001),
            slo_degrade_budget=_getenv_float(
                "KMLS_SLO_DEGRADE_BUDGET", 0.01
            ),
            slo_fast_window_s=_getenv_float("KMLS_SLO_FAST_WINDOW_S", 300.0),
            slo_slow_window_s=_getenv_float(
                "KMLS_SLO_SLOW_WINDOW_S", 3600.0
            ),
            forecast_enabled=_getenv_bool("KMLS_FORECAST", False),
            forecast_horizon_s=_getenv_float("KMLS_FORECAST_HORIZON_S", 2.0),
            forecast_window_s=_getenv_float("KMLS_FORECAST_WINDOW_S", 0.5),
            forecast_alpha=_getenv_float("KMLS_FORECAST_ALPHA", 0.35),
            forecast_util_cap=_getenv_float("KMLS_FORECAST_UTIL_CAP", 1.0),
            forecast_ramp_ratio=_getenv_float(
                "KMLS_FORECAST_RAMP_RATIO", 1.2
            ),
            forecast_prefetch_top_n=_getenv_int(
                "KMLS_FORECAST_PREFETCH_TOP_N", 8
            ),
        )
