from . import csv  # noqa: F401
