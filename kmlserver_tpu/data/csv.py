"""CSV ingestion — the L0→L1 boundary.

The reference reads the playlist-membership CSVs with polars and drops
``duration_ms`` before processing (reference: machine-learning/main.py:148-166,
DROP_COLUMNS at :42). polars is not in this image; ingestion here goes through
pandas' C parser, behind a small facade so the native (C++ mmap) scanner can
slot in underneath later without touching callers.

Expected schema (reference: SURVEY.md §1 L0): ``pid, track_uri, track_name,
artist_name, artist_uri, album_name, duration_ms`` (extra columns tolerated).
Only ``pid`` and ``track_name`` are required; the artist/album columns power
the auxiliary vocab artifacts when present.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pandas as pd

from ..config import DROP_COLUMNS

REQUIRED_COLUMNS = ("pid", "track_name")
OPTIONAL_COLUMNS = ("track_uri", "artist_name", "artist_uri", "album_name")


@dataclasses.dataclass
class TrackTable:
    """Row-oriented membership table: one row per (playlist, track) pair."""

    pid: np.ndarray  # int64
    track_name: np.ndarray  # object (str)
    track_uri: np.ndarray | None = None
    artist_name: np.ndarray | None = None
    artist_uri: np.ndarray | None = None
    album_name: np.ndarray | None = None

    def __len__(self) -> int:
        return len(self.pid)

    @property
    def n_playlists(self) -> int:
        return len(np.unique(self.pid))

    @property
    def n_tracks(self) -> int:
        return len(np.unique(self.track_name))


def read_tracks(path: str, sample_ratio: float = 1.0) -> TrackTable:
    """Read a membership CSV, optionally head-sampling ``sample_ratio`` of the
    rows, and drop ``duration_ms`` (reference: read_tracks main.py:152-166 +
    clean_df main.py:148-150 — there sampling is also a head-slice, not random).

    Uses the native C++ dictionary-encoding loader (native/kmls_csv.cpp)
    when its .so is available, falling back to pandas' parser.
    """
    from . import native

    if native.available():
        try:
            return _table_from_native(
                native.read_csv_native(path, skip_columns=tuple(DROP_COLUMNS)),
                sample_ratio,
            )
        except ValueError:
            pass  # malformed for the strict native parser → pandas fallback
    # keep_default_na=False: empty cells stay "" exactly as the native path
    # produces them (pandas' default would turn them into NaN → "nan")
    df = pd.read_csv(path, keep_default_na=False)
    missing = [c for c in REQUIRED_COLUMNS if c not in df.columns]
    if missing:
        raise ValueError(f"{path}: missing required columns {missing}; has {list(df.columns)}")
    if 0 < sample_ratio < 1.0:
        df = df.head(max(1, int(len(df) * sample_ratio)))
    df = df.drop(columns=[c for c in DROP_COLUMNS if c in df.columns])
    # same contract as the native parser: non-numeric pids are a parse error,
    # never silently-wrong data (pandas leaves them as an object column)
    try:
        pid_num = pd.to_numeric(df["pid"], errors="raise")
        # reject float-formatted ("1.5", "1.0", "2e3") and out-of-int64-range
        # pids instead of truncating/wrapping them into the wrong playlist —
        # the same strictness the native parser enforces (strtoll + ERANGE
        # treats any non-[0-9] trailing byte as a parse error, so even
        # integral-VALUED float spellings must fail here, not round-trip)
        if pid_num.dtype == np.uint64:
            if (pid_num.to_numpy() > np.uint64(np.iinfo(np.int64).max)).any():
                raise ValueError("pid exceeds int64 range")
        elif not np.issubdtype(pid_num.dtype, np.integer):
            raise ValueError(
                "non-integer-formatted pid value (float spellings like "
                "'1.0' are rejected, matching the native parser)"
            )
        pid = pid_num.astype(np.int64).to_numpy()
    except (ValueError, TypeError) as exc:
        raise ValueError(f"{path}: invalid pid column: {exc}") from None

    def col(name: str) -> np.ndarray | None:
        return df[name].to_numpy() if name in df.columns else None

    return TrackTable(
        pid=pid,
        track_name=df["track_name"].astype(str).to_numpy(),
        track_uri=col("track_uri"),
        artist_name=col("artist_name"),
        artist_uri=col("artist_uri"),
        album_name=col("album_name"),
    )


def _table_from_native(nt, sample_ratio: float) -> TrackTable:
    n = len(nt)
    if "track_name" not in nt.columns:
        raise ValueError("missing required column track_name")
    stop = n
    if 0 < sample_ratio < 1.0:
        stop = max(1, int(n * sample_ratio))

    def col(name: str) -> np.ndarray | None:
        dc = nt.columns.get(name)
        if dc is None:
            return None
        return dc.materialize()[:stop]

    return TrackTable(
        pid=nt.pids[:stop],
        track_name=col("track_name"),
        track_uri=col("track_uri"),
        artist_name=col("artist_name"),
        artist_uri=col("artist_uri"),
        album_name=col("album_name"),
    )


def write_tracks_csv(path: str, table: TrackTable) -> None:
    """Emit a membership table back to CSV (used by tests and the synthetic
    generator; the reference has no writer — its datasets are inputs only)."""
    data = {"pid": table.pid, "track_name": table.track_name}
    for name in OPTIONAL_COLUMNS:
        arr = getattr(table, name)
        if arr is not None:
            data[name] = arr
    pd.DataFrame(data).to_csv(path, index=False)
