"""Device-resident synthetic workload generation — config-4 scale data
born in HBM, in the compressed operand format, with zero host involvement.

The host generator (``data/synthetic.py``) draws ~1.8× the target rows,
deduplicates (playlist, track) pairs with a 900M-element sort, and ships
the result through the host→device link — 645 s of host time plus ~4 GB
of transfer for BASELINE config 4 (10M playlists × 1M tracks, 500M rows).
Through a remote-TPU tunnel that transfer alone is minutes. This module
replaces all of it with the TPU-native formulation:

**Bernoulli-Zipf bipartite model.** Membership of playlist p in track t is
an independent Bernoulli(q_t) with ``q_t = min(1, target_rows · w_t / P)``
and ``w_t`` the same Zipf popularity law the host generator samples from
(``data/synthetic.py zipf_weights``). Expected per-track membership counts
match the host model's (``target_rows · w_t``, capped); set semantics hold
BY CONSTRUCTION — a (p, t) pair either exists or not, so the bit-packed
operand needs no dedup at all (the additive bitset scatter's documented
precondition, ops/popcount.py popcount_pair_counts). The generator emits
the ``(v_pad, w_pad)`` uint32 bitset DIRECTLY: each frequent track's row is
a stream of Bernoulli(q_t) bits packed 32/word, produced by a jitted scan
over row blocks. No membership array ever exists, on host or device.

**Exact Apriori pruning, analytically.** Only candidate-frequent rows are
generated: tracks whose EXPECTED count ``P·q_t`` is at least
``min_count − margin·sqrt(min_count)``. For an excluded track,
P(Binomial(P, q_t) ≥ min_count) ≤ exp(−margin²/2) (Chernoff) — at the
default margin of 8 standard deviations that is < 1e-14 per track, < 1e-8
after a union bound over 10⁶ tracks: no empirically-frequent item is ever
dropped, which is the exactness contract of the Apriori prune. Rows kept
by the margin but empirically below ``min_count`` are discarded by rule
emission on their TRUE (bitset-popcount) counts, exactly like any pruned
mining run. Padded rows get q = 0 and stay all-zero.

The counting and emission downstream are the production paths untouched:
``ops/popcount.mxu_pair_counts_padded`` on the generated bitset, then
``ops/rules.mine_rules_from_counts``.
"""

from __future__ import annotations

import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jaxcompat import shard_map
from .synthetic import zipf_weights

# margin (in standard deviations of Binomial at min_count) for the
# analytic candidate-frequent cut; 8σ ⇒ drop probability < 1e-8 after a
# union bound over a 10⁶-track vocabulary
CANDIDATE_MARGIN_SIGMAS = 8.0


def zipf_bit_probs(
    n_tracks: int,
    n_playlists: int,
    target_rows: int,
    zipf_exponent: float = 1.0,
) -> np.ndarray:
    """Per-track membership probability ``q_t`` (float64, descending)."""
    w = zipf_weights(n_tracks, zipf_exponent)
    return np.minimum(target_rows * w / n_playlists, 1.0)


def candidate_frequent_count(
    q: np.ndarray,
    n_playlists: int,
    min_count: int,
    margin_sigmas: float = CANDIDATE_MARGIN_SIGMAS,
) -> int:
    """How many (Zipf-descending) tracks clear the analytic candidate cut
    ``P·q_t ≥ min_count − margin·sqrt(min_count)``. Every track outside is
    empirically infrequent with probability ≥ 1 − exp(−margin²/2).

    The σ bound only separates when ``min_count > margin² (+1)``; below
    that the margin swallows the threshold and ANY track with q > 0 could
    be empirically frequent — then every such track is a candidate
    (smoke shapes only; production min_counts are in the thousands)."""
    cut = min_count - margin_sigmas * np.sqrt(max(min_count, 1))
    if cut <= 1.0:
        return int((q > 0).sum())
    return int(np.searchsorted(-(q * n_playlists), -cut, side="right"))


def _scan_bernoulli_words(
    keys: jax.Array,  # (n_blocks, key)
    q_blocks: jax.Array,  # (n_blocks, row_block)
    valid: jax.Array,  # (w_width, 32) uint32 — 1 where the bit position is real
    *,
    row_block: int,
    w_width: int,
) -> jax.Array:
    """The ONE generator core (single-device and per-shard): scan over row
    blocks, each drawing Bernoulli bits and packing 32/word. The scan
    bounds the transient uniform buffer to ``row_block × w_width × 32``
    floats while the packed output accumulates at 1/32 of that.
    → ``(n_blocks·row_block, w_width) uint32``."""
    shifts = jnp.arange(32, dtype=jnp.uint32)

    def step(carry, args):
        key, qb = args  # (row_block,)
        u = jax.random.uniform(key, (row_block, w_width, 32))
        bits = (u < qb[:, None, None]).astype(jnp.uint32) * valid[None]
        words = jnp.sum(  # distinct powers of two: the sum IS the OR
            bits << shifts, axis=-1, dtype=jnp.uint32
        )
        return carry, words

    _, blocks = jax.lax.scan(step, None, (keys, q_blocks))
    return blocks.reshape(-1, w_width)


def _position_mask(
    word_offset, w_width: int, n_playlists: int
) -> jax.Array:
    """(w_width, 32) uint32: 1 where global bit position
    ``(word_offset + w)·32 + b`` is a real playlist — word padding beyond
    ``n_playlists`` must stay zero or it counts as phantom playlists."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    positions = (
        (word_offset + jnp.arange(w_width, dtype=jnp.uint32))[:, None] * 32
        + shifts[None, :]
    )
    return (positions < n_playlists).astype(jnp.uint32)


@partial(jax.jit, static_argnames=("n_playlists", "v_pad", "w_pad", "row_block"))
def bitset_from_probs(
    q_padded: jax.Array,  # (v_pad,) float32; 0 for pad rows
    seed: int,
    *,
    n_playlists: int,
    v_pad: int,
    w_pad: int,
    row_block: int = 32,
) -> jax.Array:
    """Generate the ``(v_pad, w_pad)`` uint32 bitset: bit p of word
    ``[t, p // 32]`` ~ Bernoulli(q_padded[t]) for p < n_playlists, all
    independent; bit positions beyond ``n_playlists`` stay zero."""
    if v_pad % row_block:
        raise ValueError(f"v_pad {v_pad} must be a multiple of row_block {row_block}")
    n_blocks = v_pad // row_block
    keys = jax.random.split(jax.random.PRNGKey(seed), n_blocks)
    return _scan_bernoulli_words(
        keys,
        q_padded.reshape(n_blocks, row_block),
        _position_mask(jnp.uint32(0), w_pad, n_playlists),
        row_block=row_block,
        w_width=w_pad,
    )


def sharded_bitset_from_probs(
    q_padded: jax.Array,  # (v_pad,) float32; 0 for pad rows
    seed: int,
    mesh,
    *,
    n_playlists: int,
    v_pad: int,
    w_pad: int,
    row_block: int = 32,
) -> jax.Array:
    """Multi-chip twin of :func:`bitset_from_probs`: the bitset is born
    ALREADY word-axis-dp-sharded — each chip generates only its own
    ``w_pad/dp`` slab (PRNG keys folded by shard index, bit positions
    masked against the slab's global offset), so no chip ever holds or
    communicates another's slab. Feed the result to
    ``parallel.support.counts_from_sharded_bitset`` for psum'd counts —
    BASELINE config 4 on a v5e-4 with zero host involvement."""
    from ..parallel.mesh import AXIS_DP, AXIS_TP

    if mesh.shape.get(AXIS_TP, 1) > 1:
        raise ValueError(
            f"sharded_bitset_from_probs needs a dp-only (Nx1) mesh, got "
            f"{dict(mesh.shape)}"
        )
    dp = mesh.shape[AXIS_DP]
    if w_pad % dp:
        raise ValueError(f"w_pad {w_pad} must divide over dp={dp}")
    w_local = w_pad // dp
    if v_pad % row_block:
        raise ValueError(
            f"v_pad {v_pad} must be a multiple of row_block {row_block}"
        )
    n_blocks = v_pad // row_block
    # uint32 truncation keeps full-range Python seeds valid (PRNGKey
    # folds 32 bits of entropy either way)
    return _sharded_gen_fn(mesh, n_playlists, w_local, row_block, n_blocks)(
        q_padded, jnp.uint32(seed & 0xFFFFFFFF)
    )


@functools.lru_cache(maxsize=32)
def _sharded_gen_fn(mesh, n_playlists, w_local, row_block, n_blocks):
    """Cached jitted program per (mesh, shape): the seed rides as a traced
    argument so re-generation with a new seed hits the compile cache."""
    import jax.sharding as jsh

    from ..parallel.mesh import AXIS_DP

    def shard_gen(q_full: jax.Array, seed: jax.Array) -> jax.Array:
        shard = jax.lax.axis_index(AXIS_DP)
        base = jax.random.fold_in(jax.random.PRNGKey(seed), shard)
        return _scan_bernoulli_words(
            jax.random.split(base, n_blocks),
            q_full.reshape(n_blocks, row_block),
            # mask against THIS slab's global word offset
            _position_mask(
                (shard * w_local).astype(jnp.uint32), w_local, n_playlists
            ),
            row_block=row_block,
            w_width=w_local,
        )

    spec = jsh.PartitionSpec
    return jax.jit(
        shard_map(
            shard_gen, mesh=mesh, in_specs=(spec(), spec()),
            out_specs=spec(None, AXIS_DP),
        )
    )


def device_synthetic_bitset(
    n_playlists: int,
    n_tracks: int,
    target_rows: int,
    min_count: int,
    *,
    zipf_exponent: float = 1.0,
    seed: int = 0,
    row_block: int = 32,
    margin_sigmas: float = CANDIDATE_MARGIN_SIGMAS,
    mesh=None,
) -> tuple[jax.Array, int, dict]:
    """Full device-side workload: → ``(bitset (v_pad, w_pad) uint32,
    n_candidates, info)``. ``info`` carries the analytic accounting
    (expected total rows over the FULL vocabulary incl. never-generated
    infrequent tracks, the candidate cut, HBM bytes). With ``mesh`` (a
    dp-only Nx1 mesh) the bitset is born word-axis-sharded, each chip
    generating only its slab."""
    from ..ops import popcount as pc

    q = zipf_bit_probs(n_tracks, n_playlists, target_rows, zipf_exponent)
    f = candidate_frequent_count(q, n_playlists, min_count, margin_sigmas)
    if f == 0:
        raise ValueError(
            f"no candidate-frequent tracks at min_count {min_count}; "
            "lower min_support or raise target_rows"
        )
    v_pad, w_pad = pc.padded_shape(f, n_playlists)
    q_padded = np.zeros(v_pad, dtype=np.float32)
    q_padded[:f] = q[:f]
    if mesh is not None:
        from ..parallel.mesh import AXIS_DP, round_up

        w_pad = round_up(w_pad, mesh.shape[AXIS_DP] * pc.word_chunk())
        bitset = sharded_bitset_from_probs(
            jnp.asarray(q_padded), seed, mesh, n_playlists=n_playlists,
            v_pad=v_pad, w_pad=w_pad, row_block=row_block,
        )
    else:
        bitset = bitset_from_probs(
            jnp.asarray(q_padded), seed, n_playlists=n_playlists,
            v_pad=v_pad, w_pad=w_pad, row_block=row_block,
        )
    info = {
        "model": "bernoulli-zipf",
        "expected_rows_total": float(n_playlists * q.sum()),
        "expected_rows_candidates": float(n_playlists * q[:f].sum()),
        "candidate_cut_count": f,
        "margin_sigmas": margin_sigmas,
        "v_pad": v_pad,
        "w_pad": w_pad,
        "bitset_bytes": int(v_pad) * int(w_pad) * 4,
    }
    return bitset, f, info
