"""ctypes bindings for the native CSV loader (native/kmls_csv.cpp).

The native layer goes mmap → dictionary-encoded int columns in one C++
pass: int64 pids plus, per string column, int32 codes and a first-occurrence
vocabulary (blob + offsets). That is already the shape the device pipeline
wants; ``DictColumn.materialize`` produces numpy object arrays (vectorized
fancy-indexing) only where the host-side aux builders need strings —
``data/csv.py`` adapts a :class:`NativeTable` into the ``TrackTable`` facade.

Build: ``make -C native`` (or :func:`ensure_built`, which shells out to the
same Makefile). Loading falls back gracefully — callers check
:func:`available` and use the pandas path otherwise; set ``KMLS_NATIVE=0``
to force the fallback off explicitly.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os

import numpy as np

from ..utils import nativelib

# must match KMLS_ABI_VERSION in native/kmls_csv.cpp
_ABI_VERSION = 2


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    try:
        lib.kmls_abi_version.restype = ctypes.c_int32
        lib.kmls_abi_version.argtypes = []
        got = lib.kmls_abi_version()
    except AttributeError:  # pre-versioning build
        raise OSError("native CSV loader .so predates ABI versioning")
    if got != _ABI_VERSION:
        raise OSError(
            f"native CSV loader ABI {got} != expected {_ABI_VERSION} "
            f"(stale build: run make -C native)"
        )
    lib.kmls_read_csv.restype = ctypes.c_void_p
    lib.kmls_read_csv.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.kmls_table_error.restype = ctypes.c_char_p
    lib.kmls_table_error.argtypes = [ctypes.c_void_p]
    lib.kmls_table_nrows.restype = ctypes.c_int64
    lib.kmls_table_nrows.argtypes = [ctypes.c_void_p]
    lib.kmls_table_pids.restype = ctypes.POINTER(ctypes.c_int64)
    lib.kmls_table_pids.argtypes = [ctypes.c_void_p]
    lib.kmls_table_ncols.restype = ctypes.c_int32
    lib.kmls_table_ncols.argtypes = [ctypes.c_void_p]
    lib.kmls_table_col_name.restype = ctypes.c_char_p
    lib.kmls_table_col_name.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.kmls_table_col_codes.restype = ctypes.POINTER(ctypes.c_int32)
    lib.kmls_table_col_codes.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.kmls_table_col_vocab_size.restype = ctypes.c_int32
    lib.kmls_table_col_vocab_size.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.kmls_table_col_vocab_blob.restype = ctypes.POINTER(ctypes.c_char)
    lib.kmls_table_col_vocab_blob.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int64)
    ]
    lib.kmls_table_col_vocab_offsets.restype = ctypes.POINTER(ctypes.c_uint64)
    lib.kmls_table_col_vocab_offsets.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.kmls_table_free.restype = None
    lib.kmls_table_free.argtypes = [ctypes.c_void_p]
    return lib


_loader = nativelib.NativeLib("libkmls_csv.so", _bind)


def ensure_built(quiet: bool = True) -> bool:
    """Build (or incrementally rebuild) the .so; returns availability.

    Runs make once per process — its kmls_csv.cpp dependency makes it a
    no-op when current, and it replaces a STALE .so left by an older
    checkout, which would otherwise silently serve an outdated parser ABI."""
    nativelib.run_make_once(quiet)
    return os.path.exists(_loader.so_path)


def _load() -> ctypes.CDLL | None:
    return _loader.load()


def available() -> bool:
    return _loader.available()


@dataclasses.dataclass
class DictColumn:
    """Dictionary-encoded string column: ``values = vocab[codes]``."""

    codes: np.ndarray  # int32 (N,)
    vocab: list[str]

    def materialize(self) -> np.ndarray:
        return np.asarray(self.vocab, dtype=object)[self.codes]


@dataclasses.dataclass
class NativeTable:
    pids: np.ndarray  # int64 (N,)
    columns: dict[str, DictColumn]

    def __len__(self) -> int:
        return len(self.pids)


def read_csv_native(
    path: str, skip_columns: tuple[str, ...] = ()
) -> NativeTable:
    """Load `path`; `skip_columns` are scanned but never interned/returned
    (saves the dictionary-encoding work for columns the caller will drop)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native CSV loader unavailable (build native/ first)")
    handle = lib.kmls_read_csv(path.encode(), ",".join(skip_columns).encode())
    if not handle:
        raise MemoryError("kmls_read_csv allocation failed")
    try:
        err = lib.kmls_table_error(handle)
        if err:
            raise ValueError(f"{path}: {err.decode()}")
        n = lib.kmls_table_nrows(handle)
        # empty vectors hand back nullptr data(); as_array would balk at it
        pids = (
            np.ctypeslib.as_array(lib.kmls_table_pids(handle), shape=(n,)).copy()
            if n else np.empty(0, dtype=np.int64)
        )
        columns: dict[str, DictColumn] = {}
        for i in range(lib.kmls_table_ncols(handle)):
            name = lib.kmls_table_col_name(handle, i).decode()
            codes = (
                np.ctypeslib.as_array(
                    lib.kmls_table_col_codes(handle, i), shape=(n,)
                ).copy()
                if n else np.empty(0, dtype=np.int32)
            )
            vsize = lib.kmls_table_col_vocab_size(handle, i)
            nbytes = ctypes.c_int64()
            blob_ptr = lib.kmls_table_col_vocab_blob(handle, i, ctypes.byref(nbytes))
            blob = ctypes.string_at(blob_ptr, nbytes.value) if nbytes.value else b""
            offsets = np.ctypeslib.as_array(
                lib.kmls_table_col_vocab_offsets(handle, i), shape=(vsize + 1,)
            ).copy()
            vocab = [
                blob[offsets[j]: offsets[j + 1]].decode("utf-8", "replace")
                for j in range(vsize)
            ]
            columns[name] = DictColumn(codes=codes, vocab=vocab)
        return NativeTable(pids=pids, columns=columns)
    finally:
        lib.kmls_table_free(handle)
