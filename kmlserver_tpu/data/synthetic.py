"""Synthetic basket generation — the scale driver the reference lacks.

BASELINE.json's configs go up to 10M playlists × 1M tracks; the reference has
no generator (its datasets are course-provided CSVs, two of which are not in
the repo). This produces Zipf-popularity membership data shaped like the real
ds2 (240,249 rows over 2,246 playlists × 2,171 tracks — relatorio.pdf p.6)
at any scale, deterministically.

Generation is vectorized numpy: draw playlist sizes (Poisson around the
target mean), draw track ids from a Zipf(s) law, then deduplicate
(playlist, track) pairs — matching how real playlists can't contain a track
twice (the reference's encoder has the same set semantics,
machine-learning/main.py:267-269).
"""

from __future__ import annotations

import numpy as np

from ..mining.vocab import Baskets, Vocab
from .csv import TrackTable


def zipf_weights(n_tracks: int, exponent: float = 1.0) -> np.ndarray:
    w = 1.0 / np.arange(1, n_tracks + 1, dtype=np.float64) ** exponent
    return w / w.sum()


def synthetic_memberships(
    n_playlists: int,
    n_tracks: int,
    target_rows: int,
    *,
    zipf_exponent: float = 1.0,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """→ deduplicated ``(playlist_rows int32, track_ids int32)`` with roughly
    ``target_rows`` memberships."""
    rng = np.random.default_rng(seed)
    # oversample draws: Zipf popularity makes duplicate (playlist, track)
    # draws common, and dedup would otherwise undershoot the target density
    draw_rows = int(target_rows * 1.8)
    mean_len = max(draw_rows / n_playlists, 1.0)
    sizes = np.maximum(rng.poisson(mean_len, size=n_playlists), 1)
    playlist_rows = np.repeat(np.arange(n_playlists, dtype=np.int64), sizes)
    track_ids = rng.choice(
        n_tracks, size=playlist_rows.shape[0], p=zipf_weights(n_tracks, zipf_exponent)
    )
    key = playlist_rows * np.int64(n_tracks) + track_ids
    unique_key = np.unique(key)
    if len(unique_key) > target_rows:
        unique_key = np.sort(
            rng.choice(unique_key, size=target_rows, replace=False)
        )
    return (
        (unique_key // n_tracks).astype(np.int32),
        (unique_key % n_tracks).astype(np.int32),
    )


def synthetic_baskets(
    n_playlists: int,
    n_tracks: int,
    target_rows: int,
    *,
    zipf_exponent: float = 1.0,
    seed: int = 0,
) -> Baskets:
    """Basket tensor ready for the miner, with a generated name vocabulary."""
    rows, tids = synthetic_memberships(
        n_playlists, n_tracks, target_rows, zipf_exponent=zipf_exponent, seed=seed
    )
    names = [f"Track {i:07d}" for i in range(n_tracks)]
    vocab = Vocab(names=names, index={n: i for i, n in enumerate(names)})
    return Baskets(
        playlist_rows=rows, track_ids=tids, n_playlists=n_playlists, vocab=vocab
    )


def synthetic_table(
    n_playlists: int,
    n_tracks: int,
    target_rows: int,
    *,
    zipf_exponent: float = 1.0,
    seed: int = 0,
) -> TrackTable:
    """Full membership table (with uri/artist/album metadata) for exercising
    the complete pipeline incl. the aux-artifact builders."""
    rows, tids = synthetic_memberships(
        n_playlists, n_tracks, target_rows, zipf_exponent=zipf_exponent, seed=seed
    )
    names = np.asarray([f"Track {i:07d}" for i in range(n_tracks)], dtype=object)
    artists = np.asarray([f"Artist {i % 997:04d}" for i in range(n_tracks)], dtype=object)
    return TrackTable(
        pid=rows.astype(np.int64),
        track_name=names[tids],
        track_uri=np.asarray([f"spotify:track:{t:07d}" for t in tids], dtype=object),
        artist_name=artists[tids],
        artist_uri=np.asarray(
            [f"spotify:artist:{t % 997:04d}" for t in tids], dtype=object
        ),
        album_name=np.asarray([f"Album {t // 12:06d}" for t in tids], dtype=object),
    )


# the published shape of the reference's ds2 run (relatorio.pdf p.6)
DS2_SHAPE = dict(n_playlists=2246, n_tracks=2171, target_rows=240249)
