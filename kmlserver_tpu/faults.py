"""Deterministic fault injection — the chaos harness every recovery path
is tested through.

Production recommendation stacks treat fault tolerance as a first-class
serving feature, which means every recovery path needs a way to FIRE
deterministically in a test: a corrupt artifact at reload time, a sick
replica's kernel, a device running slow past the request deadline. This
module is that switchboard. Serving code calls :func:`fire` at named
sites; nothing happens unless a fault has been armed for that site —
the disarmed check is one module-global read, so the hooks cost nothing
on the hot path.

Sites currently wired:

- ``"engine.load"`` — fired inside :meth:`RecommendEngine.load`'s
  artifact-build block, BEFORE publication: a fail fault makes the whole
  reload fail exactly like a torn artifact would (the engine must keep
  the last-good bundle and must NOT consume the invalidation token).
- ``"replica.kernel"`` (keyed by replica index) — fired inside the
  ``finish()`` closure of :meth:`RecommendEngine.recommend_many_async`,
  i.e. on the completion path where a real device failure or stall
  surfaces: a fail fault raises (exercising the batcher's circuit
  breaker + re-dispatch), a delay fault sleeps (exercising the
  deadline-budgeted degradation path).
- ``"mine.crash.<phase>"`` — fired by the mining pipeline right AFTER the
  named phase's checkpoint is persisted (``encode``/``mine``/``rules``):
  a fail fault aborts the job exactly where a pod eviction or TPU
  preemption would, so the restarted job must resume from the checkpoint
  and reproduce bit-identical artifacts.
- ``"ckpt.corrupt"`` — fired inside :meth:`CheckpointStore.save`: instead
  of raising, the store corrupts the checkpoint bytes it just wrote
  (digest recorded over the corrupt bytes, so the next load passes the
  integrity check but fails to PARSE — the two-strike quarantine path).
- ``"rank.heartbeat"`` (keyed by rank) — fired in the dead-rank
  watchdog's heartbeat loop: a fail fault silences that rank's
  heartbeats from then on, simulating a dead process so peers' watchdogs
  must convert the would-be forever-hang into a bounded-time abort.
- ``"embed.artifact"`` — fired inside the engine's embedding-artifact
  load (the second model family's reader): a fail fault makes
  ``embeddings.npz`` unloadable exactly like a torn/corrupt file — the
  reload must still publish a rules-only bundle (graceful degradation,
  never a failed reload, never a 5xx).
- ``"delta.apply"`` — fired inside the engine's delta-bundle apply path
  (continuous freshness, freshness/delta.py): a fail fault rejects the
  bundle exactly like a torn/wrong-base delta — the base generation
  keeps serving (kmls_delta_rejected_total counts it), never a 5xx.
- ``"mesh.peer"`` (keyed by gang rank) — fired inside the mesh worker's
  partial-serve handler (:meth:`RecommendEngine._mesh_serve_partial`),
  i.e. on a REMOTE rank's answer path: a delay fault turns that gang
  member into a gray failure — alive, fenced, correct, just slow — so
  the coordinator's hedge/straggler-degrade machinery (ISSUE 18) is
  what keeps the merge's tail bounded.
- ``"fleet.peer"`` (keyed by the peer's sorted-fleet index) — fired at
  the top of the app's recommend path when this replica is a fleet
  member: a delay fault stalls every answer this peer serves, the
  fleet-side gray failure that the router's slow-outlier ladder and
  client hedging must absorb without a single 5xx.

Arming, two ways:

- programmatic (tests): ``faults.inject("replica.kernel", replica=1,
  times=3)`` / ``faults.inject("replica.kernel", replica=0,
  delay_s=0.2, times=-1)``; ``faults.clear()`` in teardown.
- env knobs (containers, bench, CI chaos job), parsed once at first
  fire (or explicitly via :func:`load_env`):

  - ``KMLS_FAULT_RELOAD_FAIL=N`` — fail the next N engine reloads;
  - ``KMLS_FAULT_REPLICA_FAIL=idx[:N]`` — replica ``idx``'s kernel
    raises on its next N completions (default 1; ``-1`` = forever);
  - ``KMLS_FAULT_REPLICA_DELAY_MS=idx:ms[:N]`` — replica ``idx``'s
    kernel sleeps ``ms`` per completion (default every completion);
  - ``KMLS_FAULT_MINE_CRASH_PHASE=phase[:N]`` — crash the mining job
    right after checkpointing ``phase`` (N jobs; default 1);
  - ``KMLS_FAULT_CKPT_CORRUPT=N`` — corrupt the next N checkpoint
    payloads at save time;
  - ``KMLS_FAULT_RANK_DEAD=rank`` — silence rank ``rank``'s watchdog
    heartbeats permanently (a dead multi-host process);
  - ``KMLS_FAULT_EMBED_CORRUPT=N`` — fail the next N embedding-artifact
    loads (rules-only degradation, not a failed reload);
  - ``KMLS_FAULT_DELTA_CORRUPT=N`` — reject the next N delta-bundle
    applies (base keeps serving, delta_rejected counted);
  - ``KMLS_FAULT_MESH_PEER_DELAY_MS=rank:ms[:N]`` — gang rank ``rank``
    stalls ``ms`` per partial it serves (default every partial);
  - ``KMLS_FAULT_FLEET_PEER_DELAY_MS=idx:ms[:N]`` — fleet peer ``idx``
    (sorted-peer position) stalls ``ms`` per request it answers
    (default every request).

File corruption is a separate concern (faults happen to BYTES, not call
sites): :func:`truncate_file` and :func:`flip_byte` are the helpers the
chaos suite and the bench use to produce torn/corrupt artifacts on a
real filesystem, so the integrity/quarantine machinery is tested against
what an interrupted writer actually leaves behind.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

# fast-path gate: fire() returns immediately while nothing is armed.
# Benign race: a stale False read can only skip a fault armed
# concurrently with the dispatch it would have hit — tests arm faults
# before driving traffic.
_armed = False
_env_loaded = False
_lock = threading.Lock()


class FaultInjected(RuntimeError):
    """Raised by :func:`fire` when a fail fault triggers."""


@dataclasses.dataclass
class _Fault:
    remaining: int  # -1 = unlimited
    delay_s: float = 0.0
    fired: int = 0


# (site, replica-or-None) -> _Fault; a replica-keyed lookup falls back to
# the site-wide (replica=None) entry
_faults: dict[tuple[str, int | None], _Fault] = {}


def inject(
    site: str,
    *,
    replica: int | None = None,
    times: int = 1,
    delay_s: float = 0.0,
) -> None:
    """Arm a fault at ``site``: ``delay_s > 0`` sleeps per fire (a slow
    kernel), otherwise the fire raises :class:`FaultInjected` (a failing
    kernel / reload). ``times=-1`` keeps firing until :func:`clear`."""
    global _armed
    with _lock:
        _faults[(site, replica)] = _Fault(remaining=times, delay_s=delay_s)
        _armed = True


def clear() -> None:
    """Disarm everything (test teardown). Also forgets the env parse so a
    later :func:`load_env` re-reads the knobs."""
    global _armed, _env_loaded
    with _lock:
        _faults.clear()
        _armed = False
        _env_loaded = False


def active() -> dict[tuple[str, int | None], int]:
    """Snapshot of armed faults → remaining counts (diagnostics)."""
    with _lock:
        return {k: f.remaining for k, f in _faults.items()}


def fired_counts() -> dict[tuple[str, int | None], int]:
    with _lock:
        return {k: f.fired for k, f in _faults.items()}


def take(site: str, replica: int | None = None) -> float:
    """Consume one armed fault for ``(site, replica)`` or ``(site,
    None)`` → its delay in seconds (0.0 when nothing is armed). Fail
    faults raise :class:`FaultInjected` exactly like :func:`fire`.
    Loop-native callers (serving/aioserver.py) use this to put the
    stall on a timer: a blocking sleep on the event loop would stall
    EVERY in-flight request, turning a per-request gray failure into a
    whole-replica outage."""
    if not _armed and _env_loaded:
        return 0.0
    _ensure_env()
    if not _armed:
        return 0.0
    with _lock:
        fault = _faults.get((site, replica)) or _faults.get((site, None))
        if fault is None or fault.remaining == 0:
            return 0.0
        if fault.remaining > 0:
            fault.remaining -= 1
        fault.fired += 1
        delay = fault.delay_s
    if delay > 0:
        return delay
    raise FaultInjected(f"injected fault at {site}"
                        + (f" (replica {replica})" if replica is not None else ""))


def fire(site: str, replica: int | None = None) -> None:
    """Trigger point, called from serving code. No-op unless a fault is
    armed for ``(site, replica)`` or ``(site, None)``. Delay faults
    sleep (on the calling thread — see :func:`take` for the loop-native
    form); fail faults raise :class:`FaultInjected`."""
    delay = take(site, replica)
    if delay > 0:
        time.sleep(delay)


def load_env(force: bool = False) -> None:
    """Parse the ``KMLS_FAULT_*`` env knobs into armed faults. Runs once
    per process (lazily, at the first :func:`fire`); ``force=True``
    re-reads after an env change."""
    global _env_loaded
    with _lock:
        if _env_loaded and not force:
            return
        _env_loaded = True
    raw = os.getenv("KMLS_FAULT_RELOAD_FAIL")
    if raw:
        inject("engine.load", times=int(raw))
    raw = os.getenv("KMLS_FAULT_REPLICA_FAIL")
    if raw:
        parts = raw.split(":")
        inject(
            "replica.kernel", replica=int(parts[0]),
            times=int(parts[1]) if len(parts) > 1 else 1,
        )
    raw = os.getenv("KMLS_FAULT_REPLICA_DELAY_MS")
    if raw:
        parts = raw.split(":")
        inject(
            "replica.kernel", replica=int(parts[0]),
            delay_s=float(parts[1]) / 1e3,
            times=int(parts[2]) if len(parts) > 2 else -1,
        )
    raw = os.getenv("KMLS_FAULT_MINE_CRASH_PHASE")
    if raw:
        parts = raw.split(":")
        inject(
            f"mine.crash.{parts[0]}",
            times=int(parts[1]) if len(parts) > 1 else 1,
        )
    raw = os.getenv("KMLS_FAULT_CKPT_CORRUPT")
    if raw:
        inject("ckpt.corrupt", times=int(raw))
    raw = os.getenv("KMLS_FAULT_RANK_DEAD")
    if raw:
        inject("rank.heartbeat", replica=int(raw), times=-1)
    raw = os.getenv("KMLS_FAULT_EMBED_CORRUPT")
    if raw:
        inject("embed.artifact", times=int(raw))
    raw = os.getenv("KMLS_FAULT_DELTA_CORRUPT")
    if raw:
        inject("delta.apply", times=int(raw))
    raw = os.getenv("KMLS_FAULT_MESH_PEER_DELAY_MS")
    if raw:
        parts = raw.split(":")
        inject(
            "mesh.peer", replica=int(parts[0]),
            delay_s=float(parts[1]) / 1e3,
            times=int(parts[2]) if len(parts) > 2 else -1,
        )
    raw = os.getenv("KMLS_FAULT_FLEET_PEER_DELAY_MS")
    if raw:
        parts = raw.split(":")
        inject(
            "fleet.peer", replica=int(parts[0]),
            delay_s=float(parts[1]) / 1e3,
            times=int(parts[2]) if len(parts) > 2 else -1,
        )


def _ensure_env() -> None:
    if not _env_loaded:
        load_env()


# ---------- artifact corruption helpers (bytes, not call sites) ----------


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Tear ``path`` the way an interrupted writer does: keep the leading
    ``keep_fraction`` of its bytes, drop the rest. → bytes kept."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "rb+") as fh:
        fh.truncate(keep)
    return keep


def flip_byte(path: str, offset: int | None = None) -> int:
    """Flip one byte in place (silent bit-rot / bad sector). ``offset``
    defaults to the middle of the file. → the offset flipped."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty; nothing to corrupt")
    if offset is None:
        offset = size // 2
    with open(path, "rb+") as fh:
        fh.seek(offset)
        byte = fh.read(1)
        fh.seek(offset)
        fh.write(bytes([byte[0] ^ 0xFF]))
    return offset
