"""Deterministic fault injection — the chaos harness every recovery path
is tested through.

Production recommendation stacks treat fault tolerance as a first-class
serving feature, which means every recovery path needs a way to FIRE
deterministically in a test: a corrupt artifact at reload time, a sick
replica's kernel, a device running slow past the request deadline. This
module is that switchboard. Serving code calls :func:`fire` at named
sites; nothing happens unless a fault has been armed for that site —
the disarmed check is one module-global read, so the hooks cost nothing
on the hot path.

Sites currently wired:

- ``"engine.load"`` — fired inside :meth:`RecommendEngine.load`'s
  artifact-build block, BEFORE publication: a fail fault makes the whole
  reload fail exactly like a torn artifact would (the engine must keep
  the last-good bundle and must NOT consume the invalidation token).
- ``"replica.kernel"`` (keyed by replica index) — fired inside the
  ``finish()`` closure of :meth:`RecommendEngine.recommend_many_async`,
  i.e. on the completion path where a real device failure or stall
  surfaces: a fail fault raises (exercising the batcher's circuit
  breaker + re-dispatch), a delay fault sleeps (exercising the
  deadline-budgeted degradation path).
- ``"mine.crash.<phase>"`` — fired by the mining pipeline right AFTER the
  named phase's checkpoint is persisted (``encode``/``mine``/``rules``):
  a fail fault aborts the job exactly where a pod eviction or TPU
  preemption would, so the restarted job must resume from the checkpoint
  and reproduce bit-identical artifacts.
- ``"ckpt.corrupt"`` — fired inside :meth:`CheckpointStore.save`: instead
  of raising, the store corrupts the checkpoint bytes it just wrote
  (digest recorded over the corrupt bytes, so the next load passes the
  integrity check but fails to PARSE — the two-strike quarantine path).
- ``"rank.heartbeat"`` (keyed by rank) — fired in the dead-rank
  watchdog's heartbeat loop: a fail fault silences that rank's
  heartbeats from then on, simulating a dead process so peers' watchdogs
  must convert the would-be forever-hang into a bounded-time abort.
- ``"embed.artifact"`` — fired inside the engine's embedding-artifact
  load (the second model family's reader): a fail fault makes
  ``embeddings.npz`` unloadable exactly like a torn/corrupt file — the
  reload must still publish a rules-only bundle (graceful degradation,
  never a failed reload, never a 5xx).
- ``"delta.apply"`` — fired inside the engine's delta-bundle apply path
  (continuous freshness, freshness/delta.py): a fail fault rejects the
  bundle exactly like a torn/wrong-base delta — the base generation
  keeps serving (kmls_delta_rejected_total counts it), never a 5xx.
- ``"mesh.peer"`` (keyed by gang rank) — fired inside the mesh worker's
  partial-serve handler (:meth:`RecommendEngine._mesh_serve_partial`),
  i.e. on a REMOTE rank's answer path: a delay fault turns that gang
  member into a gray failure — alive, fenced, correct, just slow — so
  the coordinator's hedge/straggler-degrade machinery (ISSUE 18) is
  what keeps the merge's tail bounded.
- ``"fleet.peer"`` (keyed by the peer's sorted-fleet index) — fired at
  the top of the app's recommend path when this replica is a fleet
  member: a delay fault stalls every answer this peer serves, the
  fleet-side gray failure that the router's slow-outlier ladder and
  client hedging must absorb without a single 5xx.
- ``"io.write"`` / ``"io.read"`` / ``"io.fsync"`` — the STORAGE fault
  plane, consumed via :func:`take_io` inside ``io/artifacts.py``'s
  single writer/reader so every artifact, manifest, token, lease and
  checkpoint byte is coverable. Unlike the call-site faults above these
  are **path-scoped**: each armed fault carries an optional path
  substring, so a test can tear exactly ``recommendations`` while the
  lease heartbeat keeps writing. Kinds: ``enospc`` (raise
  ``OSError(ENOSPC)``), ``eio`` (raise ``OSError(EIO)``), ``torn@N``
  (write only the first N bytes, then raise :class:`TornWrite` — what
  a crashed writer leaves behind), ``stall`` (return seconds for the
  caller to sleep — the slow-NFS gray failure), and plain ``fail`` for
  ``io.fsync`` (fsyncgate: an fsync failure must abort, never retry).

Arming, two ways:

- programmatic (tests): ``faults.inject("replica.kernel", replica=1,
  times=3)`` / ``faults.inject("replica.kernel", replica=0,
  delay_s=0.2, times=-1)``; ``faults.clear()`` in teardown.
- env knobs (containers, bench, CI chaos job), parsed once at first
  fire (or explicitly via :func:`load_env`):

  - ``KMLS_FAULT_RELOAD_FAIL=N`` — fail the next N engine reloads;
  - ``KMLS_FAULT_REPLICA_FAIL=idx[:N]`` — replica ``idx``'s kernel
    raises on its next N completions (default 1; ``-1`` = forever);
  - ``KMLS_FAULT_REPLICA_DELAY_MS=idx:ms[:N]`` — replica ``idx``'s
    kernel sleeps ``ms`` per completion (default every completion);
  - ``KMLS_FAULT_MINE_CRASH_PHASE=phase[:N]`` — crash the mining job
    right after checkpointing ``phase`` (N jobs; default 1);
  - ``KMLS_FAULT_CKPT_CORRUPT=N`` — corrupt the next N checkpoint
    payloads at save time;
  - ``KMLS_FAULT_RANK_DEAD=rank`` — silence rank ``rank``'s watchdog
    heartbeats permanently (a dead multi-host process);
  - ``KMLS_FAULT_EMBED_CORRUPT=N`` — fail the next N embedding-artifact
    loads (rules-only degradation, not a failed reload);
  - ``KMLS_FAULT_DELTA_CORRUPT=N`` — reject the next N delta-bundle
    applies (base keeps serving, delta_rejected counted);
  - ``KMLS_FAULT_MESH_PEER_DELAY_MS=rank:ms[:N]`` — gang rank ``rank``
    stalls ``ms`` per partial it serves (default every partial);
  - ``KMLS_FAULT_FLEET_PEER_DELAY_MS=idx:ms[:N]`` — fleet peer ``idx``
    (sorted-peer position) stalls ``ms`` per request it answers
    (default every request);
  - ``KMLS_FAULT_IO_WRITE=kind[:N][:substr]`` — next N artifact-plane
    writes whose destination path contains ``substr`` fail with
    ``kind`` ∈ ``enospc`` | ``eio`` | ``torn@BYTES`` (default N=1,
    any path);
  - ``KMLS_FAULT_IO_WRITE_STALL_MS=ms[:N][:substr]`` — stall matching
    writes ``ms`` each (default every write, any path);
  - ``KMLS_FAULT_IO_READ=N[:substr]`` — next N matching artifact reads
    raise ``OSError(EIO)``;
  - ``KMLS_FAULT_IO_READ_STALL_MS=ms[:N][:substr]`` — stall matching
    reads ``ms`` each (the hung-NFS-mount shape; default every read);
  - ``KMLS_FAULT_IO_FSYNC=N[:substr]`` — next N matching fsyncs fail
    (publication must abort cleanly — fsync errors are never retried).

File corruption is a separate concern (faults happen to BYTES, not call
sites): :func:`truncate_file` and :func:`flip_byte` are the helpers the
chaos suite and the bench use to produce torn/corrupt artifacts on a
real filesystem, so the integrity/quarantine machinery is tested against
what an interrupted writer actually leaves behind.
"""

from __future__ import annotations

import dataclasses
import errno
import os
import threading
import time

# fast-path gate: fire() returns immediately while nothing is armed.
# Benign race: a stale False read can only skip a fault armed
# concurrently with the dispatch it would have hit — tests arm faults
# before driving traffic.
_armed = False
_env_loaded = False
_lock = threading.Lock()


class FaultInjected(RuntimeError):
    """Raised by :func:`fire` when a fail fault triggers."""


class TornWrite(OSError):
    """Raised by :func:`take_io` for a ``torn@N`` write fault: the caller
    must write only the first ``keep_bytes`` bytes to the TEMP file and
    then re-raise — reproducing exactly what a writer killed mid-write
    leaves behind (a short temp file, never a torn destination)."""

    def __init__(self, site: str, keep_bytes: int):
        super().__init__(errno.EIO, f"injected torn write at {site}")
        self.keep_bytes = keep_bytes


@dataclasses.dataclass
class _Fault:
    remaining: int  # -1 = unlimited
    delay_s: float = 0.0
    fired: int = 0


@dataclasses.dataclass
class _IoFault:
    """A path-scoped storage fault (``io.*`` sites only)."""

    kind: str  # "enospc" | "eio" | "torn" | "stall" | "fail"
    remaining: int  # -1 = unlimited
    stall_s: float = 0.0
    torn_at: int = -1
    path_substr: str = ""
    fired: int = 0


# (site, replica-or-None) -> _Fault; a replica-keyed lookup falls back to
# the site-wide (replica=None) entry
_faults: dict[tuple[str, int | None], _Fault] = {}

# "io.write"/"io.read"/"io.fsync" -> armed storage faults, consumed in
# arming order by the first fault whose path_substr matches
_io_faults: dict[str, list[_IoFault]] = {}


def inject(
    site: str,
    *,
    replica: int | None = None,
    times: int = 1,
    delay_s: float = 0.0,
    kind: str = "",
    torn_at: int = -1,
    path: str = "",
) -> None:
    """Arm a fault at ``site``: ``delay_s > 0`` sleeps per fire (a slow
    kernel), otherwise the fire raises :class:`FaultInjected` (a failing
    kernel / reload). ``times=-1`` keeps firing until :func:`clear`.

    ``io.*`` sites route to the path-scoped storage plane instead:
    ``kind`` picks the failure (``enospc``/``eio``/``torn``/``stall``/
    ``fail``; defaults to ``stall`` when ``delay_s > 0``, else ``eio``
    for reads/writes and ``fail`` for fsync), ``torn_at`` is the byte
    count kept by a torn write, and ``path`` scopes the fault to
    destinations containing that substring (empty = every path)."""
    global _armed
    if site.startswith("io."):
        if not kind:
            if delay_s > 0:
                kind = "stall"
            elif torn_at >= 0:
                kind = "torn"
            else:
                kind = "fail" if site == "io.fsync" else "eio"
        with _lock:
            _io_faults.setdefault(site, []).append(
                _IoFault(
                    kind=kind,
                    remaining=times,
                    stall_s=delay_s,
                    torn_at=torn_at,
                    path_substr=path,
                )
            )
            _armed = True
        return
    with _lock:
        _faults[(site, replica)] = _Fault(remaining=times, delay_s=delay_s)
        _armed = True


def clear() -> None:
    """Disarm everything (test teardown). Also forgets the env parse so a
    later :func:`load_env` re-reads the knobs."""
    global _armed, _env_loaded
    with _lock:
        _faults.clear()
        _io_faults.clear()
        _armed = False
        _env_loaded = False


def active() -> dict[tuple[str, int | None], int]:
    """Snapshot of armed faults → remaining counts (diagnostics)."""
    with _lock:
        snap = {k: f.remaining for k, f in _faults.items()}
        for site, lst in _io_faults.items():
            for i, io_fault in enumerate(lst):
                snap[(f"{site}#{i}", None)] = io_fault.remaining
        return snap


def fired_counts() -> dict[tuple[str, int | None], int]:
    with _lock:
        return {k: f.fired for k, f in _faults.items()}


def take(site: str, replica: int | None = None) -> float:
    """Consume one armed fault for ``(site, replica)`` or ``(site,
    None)`` → its delay in seconds (0.0 when nothing is armed). Fail
    faults raise :class:`FaultInjected` exactly like :func:`fire`.
    Loop-native callers (serving/aioserver.py) use this to put the
    stall on a timer: a blocking sleep on the event loop would stall
    EVERY in-flight request, turning a per-request gray failure into a
    whole-replica outage."""
    if not _armed and _env_loaded:
        return 0.0
    _ensure_env()
    if not _armed:
        return 0.0
    with _lock:
        fault = _faults.get((site, replica)) or _faults.get((site, None))
        if fault is None or fault.remaining == 0:
            return 0.0
        if fault.remaining > 0:
            fault.remaining -= 1
        fault.fired += 1
        delay = fault.delay_s
    if delay > 0:
        return delay
    raise FaultInjected(f"injected fault at {site}"
                        + (f" (replica {replica})" if replica is not None else ""))


def fire(site: str, replica: int | None = None) -> None:
    """Trigger point, called from serving code. No-op unless a fault is
    armed for ``(site, replica)`` or ``(site, None)``. Delay faults
    sleep (on the calling thread — see :func:`take` for the loop-native
    form); fail faults raise :class:`FaultInjected`."""
    delay = take(site, replica)
    if delay > 0:
        time.sleep(delay)


def take_io(site: str, path: str) -> float:
    """Consume one armed storage fault at ``site`` whose path scope
    matches ``path`` → stall seconds (0.0 when nothing matches; the
    CALLER sleeps, so read stalls can run under a deadline thread).
    Error kinds raise the errno a real bad mount would: ``enospc`` →
    ``OSError(ENOSPC)``, ``eio`` → ``OSError(EIO)``, ``torn`` →
    :class:`TornWrite` (caller keeps ``keep_bytes`` then re-raises),
    ``fail`` (fsync) → ``OSError(EIO)``."""
    if not _armed and _env_loaded:
        return 0.0
    _ensure_env()
    if not _armed:
        return 0.0
    with _lock:
        fault = None
        for candidate in _io_faults.get(site, ()):
            if candidate.remaining != 0 and candidate.path_substr in path:
                fault = candidate
                break
        if fault is None:
            return 0.0
        if fault.remaining > 0:
            fault.remaining -= 1
        fault.fired += 1
        kind, stall_s, torn_at = fault.kind, fault.stall_s, fault.torn_at
    if kind == "stall":
        return stall_s
    if kind == "enospc":
        raise OSError(errno.ENOSPC, f"injected ENOSPC at {site}: {path}")
    if kind == "torn":
        raise TornWrite(site, max(torn_at, 0))
    # "eio" and fsync "fail" both surface as the mount's EIO
    raise OSError(errno.EIO, f"injected EIO at {site}: {path}")


def load_env(force: bool = False) -> None:
    """Parse the ``KMLS_FAULT_*`` env knobs into armed faults. Runs once
    per process (lazily, at the first :func:`fire`); ``force=True``
    re-reads after an env change."""
    global _env_loaded
    with _lock:
        if _env_loaded and not force:
            return
        _env_loaded = True
    raw = os.getenv("KMLS_FAULT_RELOAD_FAIL")
    if raw:
        inject("engine.load", times=int(raw))
    raw = os.getenv("KMLS_FAULT_REPLICA_FAIL")
    if raw:
        parts = raw.split(":")
        inject(
            "replica.kernel", replica=int(parts[0]),
            times=int(parts[1]) if len(parts) > 1 else 1,
        )
    raw = os.getenv("KMLS_FAULT_REPLICA_DELAY_MS")
    if raw:
        parts = raw.split(":")
        inject(
            "replica.kernel", replica=int(parts[0]),
            delay_s=float(parts[1]) / 1e3,
            times=int(parts[2]) if len(parts) > 2 else -1,
        )
    raw = os.getenv("KMLS_FAULT_MINE_CRASH_PHASE")
    if raw:
        parts = raw.split(":")
        inject(
            f"mine.crash.{parts[0]}",
            times=int(parts[1]) if len(parts) > 1 else 1,
        )
    raw = os.getenv("KMLS_FAULT_CKPT_CORRUPT")
    if raw:
        inject("ckpt.corrupt", times=int(raw))
    raw = os.getenv("KMLS_FAULT_RANK_DEAD")
    if raw:
        inject("rank.heartbeat", replica=int(raw), times=-1)
    raw = os.getenv("KMLS_FAULT_EMBED_CORRUPT")
    if raw:
        inject("embed.artifact", times=int(raw))
    raw = os.getenv("KMLS_FAULT_DELTA_CORRUPT")
    if raw:
        inject("delta.apply", times=int(raw))
    raw = os.getenv("KMLS_FAULT_MESH_PEER_DELAY_MS")
    if raw:
        parts = raw.split(":")
        inject(
            "mesh.peer", replica=int(parts[0]),
            delay_s=float(parts[1]) / 1e3,
            times=int(parts[2]) if len(parts) > 2 else -1,
        )
    raw = os.getenv("KMLS_FAULT_FLEET_PEER_DELAY_MS")
    if raw:
        parts = raw.split(":")
        inject(
            "fleet.peer", replica=int(parts[0]),
            delay_s=float(parts[1]) / 1e3,
            times=int(parts[2]) if len(parts) > 2 else -1,
        )
    raw = os.getenv("KMLS_FAULT_IO_WRITE")
    if raw:
        parts = raw.split(":")
        kind, _, torn = parts[0].partition("@")
        inject(
            "io.write",
            kind="torn" if kind == "torn" else kind,
            torn_at=int(torn) if torn else -1,
            times=int(parts[1]) if len(parts) > 1 else 1,
            path=parts[2] if len(parts) > 2 else "",
        )
    raw = os.getenv("KMLS_FAULT_IO_WRITE_STALL_MS")
    if raw:
        parts = raw.split(":")
        inject(
            "io.write",
            kind="stall",
            delay_s=float(parts[0]) / 1e3,
            times=int(parts[1]) if len(parts) > 1 else -1,
            path=parts[2] if len(parts) > 2 else "",
        )
    raw = os.getenv("KMLS_FAULT_IO_READ")
    if raw:
        parts = raw.split(":")
        inject(
            "io.read",
            kind="eio",
            times=int(parts[0]) if parts[0] else 1,
            path=parts[1] if len(parts) > 1 else "",
        )
    raw = os.getenv("KMLS_FAULT_IO_READ_STALL_MS")
    if raw:
        parts = raw.split(":")
        inject(
            "io.read",
            kind="stall",
            delay_s=float(parts[0]) / 1e3,
            times=int(parts[1]) if len(parts) > 1 else -1,
            path=parts[2] if len(parts) > 2 else "",
        )
    raw = os.getenv("KMLS_FAULT_IO_FSYNC")
    if raw:
        parts = raw.split(":")
        inject(
            "io.fsync",
            kind="fail",
            times=int(parts[0]) if parts[0] else 1,
            path=parts[1] if len(parts) > 1 else "",
        )


def _ensure_env() -> None:
    if not _env_loaded:
        load_env()


# ---------- artifact corruption helpers (bytes, not call sites) ----------


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Tear ``path`` the way an interrupted writer does: keep the leading
    ``keep_fraction`` of its bytes, drop the rest. → bytes kept."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "rb+") as fh:
        fh.truncate(keep)
    return keep


def flip_byte(path: str, offset: int | None = None) -> int:
    """Flip one byte in place (silent bit-rot / bad sector). ``offset``
    defaults to the middle of the file. → the offset flipped."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"{path} is empty; nothing to corrupt")
    if offset is None:
        offset = size // 2
    with open(path, "rb+") as fh:
        fh.seek(offset)
        byte = fh.read(1)
        fh.seek(offset)
        fh.write(bytes([byte[0] ^ 0xFF]))
    return offset
