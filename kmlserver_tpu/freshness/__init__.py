"""Continuous freshness — incremental delta mining, delta bundle
publication, and the fleet-aware cache tier (ISSUE 10).

The third writer/reader pair on the PR 2-4 artifact spine:

- :mod:`.delta` — the mining-side ``delta`` pipeline mode (fingerprint
  the previous run's encode state, re-encode only appended CSV rows,
  recount support restricted to affected baskets' vocab columns, publish
  a versioned delta bundle through the lease + fencing-token path) and
  the ONE canonical base∘delta application both sides share;
- :mod:`.ring` — rendezvous-hash request affinity over the replica
  fleet, plus the simulated-topology harness that measures the
  fleet-wide effective-hit-ratio multiplier before committing to a
  shared external cache tier.
"""

from .delta import DeltaIneligible, apply_delta_to_tensors  # noqa: F401
from .ring import RendezvousRing  # noqa: F401
