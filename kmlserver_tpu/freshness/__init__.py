"""Continuous freshness — incremental delta mining, delta bundle
publication, and the fleet-aware cache tier (ISSUE 10).

The third writer/reader pair on the PR 2-4 artifact spine:

- :mod:`.delta` — the mining-side ``delta`` pipeline mode (fingerprint
  the previous run's encode state, re-encode only appended CSV rows,
  recount support restricted to affected baskets' vocab columns, publish
  a versioned delta bundle through the lease + fencing-token path) and
  the ONE canonical base∘delta application both sides share;
- :mod:`.ring` — rendezvous-hash request affinity over the replica
  fleet, the simulated-topology harness that measures the fleet-wide
  effective-hit-ratio multiplier, and (ISSUE 15) the health-aware
  :class:`~.ring.FleetRouter` that ACTS on it — consistent-hash request
  routing with circuit-breaker peer ejection and bounded remap on
  membership change, making N replicas behave as one logical cache.
"""

from .delta import DeltaIneligible, apply_delta_to_tensors  # noqa: F401
from .ring import FleetRouter, RendezvousRing, seeds_key  # noqa: F401
