"""Incremental delta mining — freshness without the full-mine wall clock.

Every GitOps sync used to re-mine and re-publish the full bundle, so
freshness lag equaled full-mine time (the continuous-training posture the
Google Ads infra paper argues against — PAPERS.md, arXiv:2501.10546:
models are never retrained from scratch on a sync cadence; deltas flow).
This module is the mining half of the third writer/reader pair on the
artifact spine:

- after a FULL publication, :func:`save_base_state` persists the encode
  state (membership pairs, pid ranks, full vocabulary) plus the published
  rule tensors and the dataset's byte-prefix fingerprint;
- a later run with ``KMLS_DELTA_ENABLED=1`` calls :func:`run_delta_job`,
  which fingerprints the CSV against the base: an UNCHANGED prefix plus
  appended rows is the delta case — only the appended rows are re-encoded
  (``pandas`` over the suffix bytes, never the full file), and support is
  recounted restricted to the affected baskets' vocab columns
  (``parallel.support.restricted_pair_counts`` — rows R of C = XᵀX, the
  same int8 MXU contraction the full mine uses, mesh-sharded under the
  sharded layout);
- the changed rule rows + tombstones publish as a versioned
  ``delta-<seq>.bundle`` (io/artifacts.py) bound to the base generation
  by token AND the published npz's sha256, under the same
  :class:`~..io.artifacts.PublicationLease` fencing-token protocol as a
  full publication — a zombie writer cannot tear the chain. The
  invalidation token is deliberately NOT rewritten: serving applies the
  bundle in place (``engine.apply_pending_deltas``) instead of a full
  swap.

**Bit-identity** is the contract: base ∘ delta chain == full re-mine,
tensors and answers, at replicated AND vocab-sharded layouts (pinned by
tests/test_freshness.py). It holds because the recompute set is provably
sufficient under append-only input:

- a pair count C[i, j] changes only when some playlist whose basket
  contains i (or j) gained a membership → every changed row index is in
  the affected baskets' vocab (the **touched** set);
- appended rows can only GROW ``n_playlists``, so ``min_count`` is
  non-decreasing: rules can only drop OUT of untouched rows, and a
  dropped rule is visible in the base tensors — rows carrying any count
  in the ``[old_min_count, new_min_count)`` crossing band are added to
  the recompute set (no unstored rule can re-enter: emission kept the
  top-k by count, so everything it truncated sits below what it kept);
- vocabulary membership travels by NAME: the bundle carries the complete
  new (pruned) vocabulary, unchanged base rows re-map into it by name,
  and a consequent pointing at a name that left the vocabulary can only
  occur in a crossing-band row, which is recomputed.

Anything outside those guarantees — a rewritten/truncated prefix,
``sample_ratio`` head-slicing, the triple-antecedent confidence merge
(``max_itemset_len >= 3``), a multi-host gang, a chain at its cap —
raises :class:`DeltaIneligible` and the pipeline falls back to a full
re-mine: the delta path must never publish an approximation.

Deltas patch the RULE model only: the popularity ranking, the auxiliary
vocab artifacts, and the ALS embeddings refresh on the next full re-mine
(documented in README "Continuous freshness").
"""

from __future__ import annotations

import dataclasses
import hashlib
import io as io_mod
import json
import os
import time
from typing import Any

import numpy as np

from ..config import MiningConfig
from ..io import artifacts
from ..mining.vocab import Baskets, Vocab
from ..ops.rules import derive_confs
from ..ops.support import min_count_for

BASE_STATE_FILENAME = "freshness.base.pickle"
BASE_STATE_VERSION = 1

# MiningConfig fields that change delta-relevant output; a base state
# written under different values never seeds a delta (full re-mine).
_DELTA_CONFIG_FIELDS = (
    "min_support",
    "sample_ratio",
    "max_itemset_len",
    "k_max_consequents",
    "confidence_mode",
    "min_confidence",
    "prune_vocab_threshold",
    "model_layout",
)


class DeltaIneligible(RuntimeError):
    """This run cannot be served by a delta — full re-mine instead."""


def base_state_path(pickles_dir: str) -> str:
    return os.path.join(pickles_dir, BASE_STATE_FILENAME)


def delta_config_fingerprint(cfg: MiningConfig) -> str:
    ident = {f: getattr(cfg, f) for f in _DELTA_CONFIG_FIELDS}
    blob = json.dumps(ident, sort_keys=True, default=str).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


# ---------------------------------------------------------------------------
# base state
# ---------------------------------------------------------------------------


def save_base_state(
    cfg: MiningConfig,
    *,
    token: str,
    run_index: int,
    dataset_path: str,
    baskets: Baskets,
    pid_values: np.ndarray,
    published: dict[str, Any],
    npz_sha256: str | None,
    dataset_digest: tuple[int, str] | None = None,
) -> str:
    """Persist the delta seed after a publication (full or delta): the
    encode state the next incremental run extends, plus the CURRENT
    logical rule tensors (base ∘ applied chain) the next crossing-band
    scan reads. Atomic, writer rank only (callers gate).

    ``dataset_digest``: ``(bytes, sha256)`` when the caller already
    streamed the dataset (the delta route's append-only fingerprint
    covers the whole file) — re-reading a multi-GB CSV just to re-hash
    it would put a linear-in-dataset term back into the delta path. The
    pair is the fingerprint-time snapshot, so bytes and digest always
    describe the SAME prefix even if the feed appends mid-run."""
    if dataset_digest is not None:
        ds_bytes, ds_sha = dataset_digest
    else:
        digest = artifacts.file_digest(dataset_path)
        ds_bytes, ds_sha = digest["bytes"], digest["sha256"]
    state = {
        "version": BASE_STATE_VERSION,
        "token": token,
        "run_index": run_index,
        "dataset": os.path.basename(dataset_path),
        "dataset_bytes": ds_bytes,
        "dataset_sha256": ds_sha,
        "config_fingerprint": delta_config_fingerprint(cfg),
        "playlist_rows": np.asarray(baskets.playlist_rows, dtype=np.int32),
        "track_ids": np.asarray(baskets.track_ids, dtype=np.int32),
        "n_playlists": int(baskets.n_playlists),
        "vocab_names": list(baskets.vocab.names),
        "pid_values": np.asarray(pid_values, dtype=np.int64),
        "published": published,
        "npz_sha256": npz_sha256,
    }
    path = base_state_path(cfg.pickles_dir)
    artifacts.save_pickle(state, path)
    return path


def load_base_state(pickles_dir: str) -> dict[str, Any] | None:
    path = base_state_path(pickles_dir)
    try:
        state = artifacts.load_pickle(path)
    except Exception:
        return None
    if not isinstance(state, dict) or state.get("version") != BASE_STATE_VERSION:
        return None
    return state


def published_from_tensors(tensors, vocab_names: list[str]) -> dict[str, Any]:
    """The ``published`` base-state slice from a mined RuleTensors."""
    return {
        "vocab": list(vocab_names),
        "rule_ids": np.asarray(tensors.rule_ids, dtype=np.int32),
        "rule_counts": np.asarray(tensors.rule_counts, dtype=np.int32),
        "item_counts": np.asarray(tensors.item_counts, dtype=np.int32),
        "n_playlists": int(tensors.n_playlists),
        "min_support": float(tensors.min_support),
        "mode": str(tensors.mode),
        "min_confidence": float(tensors.min_confidence),
    }


# ---------------------------------------------------------------------------
# the ONE canonical base ∘ delta application (mining AND serving use it)
# ---------------------------------------------------------------------------


def apply_delta_to_tensors(
    prev: dict[str, Any], bundle: dict[str, Any]
) -> dict[str, Any]:
    """Apply one delta bundle to the previous logical tensors → the new
    logical tensors, in :func:`published_from_tensors` shape.

    Row identity travels by name: every new-vocab row is either overwritten
    from the bundle's changed set or copied from the base row of the SAME
    name with its consequent ids re-mapped old→new. A structural
    impossibility (a new name with no base row and no changed row, or an
    unchanged row whose consequent left the vocabulary) raises
    ``ValueError`` — the caller rejects the bundle and keeps serving."""
    prev_vocab: list[str] = prev["vocab"]
    new_vocab: list[str] = bundle["vocab"]
    prev_index = {n: i for i, n in enumerate(prev_vocab)}
    k_prev = prev["rule_ids"].shape[1]
    k_new = bundle["changed_rule_ids"].shape[1] if len(
        bundle["changed_rows"]
    ) else k_prev
    if len(bundle["changed_rows"]) and k_new != k_prev:
        raise ValueError(
            f"delta row capacity {k_new} != base row capacity {k_prev}"
        )
    v_new = len(new_vocab)
    # old-id → new-id map (−1 = name left the vocabulary)
    remap = np.full(len(prev_vocab) + 1, -1, dtype=np.int32)
    new_index = {n: i for i, n in enumerate(new_vocab)}
    for old_i, name in enumerate(prev_vocab):
        remap[old_i] = new_index.get(name, -1)
    changed = np.zeros(v_new, dtype=bool)
    changed[bundle["changed_rows"]] = True
    # gather source rows for unchanged entries
    src = np.full(v_new, -1, dtype=np.int64)
    for new_i, name in enumerate(new_vocab):
        if not changed[new_i]:
            j = prev_index.get(name)
            if j is None:
                raise ValueError(
                    f"new vocab row {name!r} has no base row and no "
                    "changed entry — corrupt delta"
                )
            src[new_i] = j
    rule_ids = np.full((v_new, k_prev), -1, dtype=np.int32)
    rule_counts = np.zeros((v_new, k_prev), dtype=np.int32)
    item_counts = np.zeros(v_new, dtype=np.int32)
    unchanged = ~changed
    if unchanged.any():
        rows = src[unchanged]
        old_ids = prev["rule_ids"][rows]
        mapped = np.where(old_ids >= 0, remap[old_ids], -1)
        if bool(((old_ids >= 0) & (mapped < 0)).any()):
            raise ValueError(
                "an unchanged row's consequent left the vocabulary — "
                "the crossing-band recompute should have covered it; "
                "corrupt delta"
            )
        rule_ids[unchanged] = mapped
        rule_counts[unchanged] = prev["rule_counts"][rows]
        item_counts[unchanged] = prev["item_counts"][rows]
    if len(bundle["changed_rows"]):
        rule_ids[bundle["changed_rows"]] = bundle["changed_rule_ids"]
        rule_counts[bundle["changed_rows"]] = bundle["changed_rule_counts"]
        item_counts[bundle["changed_rows"]] = bundle["changed_item_counts"]
    return {
        "vocab": list(new_vocab),
        "rule_ids": rule_ids,
        "rule_counts": rule_counts,
        "item_counts": item_counts,
        "n_playlists": int(bundle["n_playlists"]),
        "min_support": float(prev["min_support"]),
        "mode": str(prev["mode"]),
        "min_confidence": float(prev["min_confidence"]),
    }


def touched_names(bundle: dict[str, Any]) -> set[str]:
    """The seed names whose answers may have changed under this bundle —
    the selective cache-invalidation set: changed rows + tombstones.
    Rows that merely re-mapped ids kept their name-level answers."""
    vocab = bundle["vocab"]
    out = {vocab[int(i)] for i in bundle["changed_rows"]}
    out.update(bundle["tombstones"])
    return out


# ---------------------------------------------------------------------------
# restricted emission (numpy twin of the dense emission, per selected row)
# ---------------------------------------------------------------------------


def emit_rule_rows_np(
    counts_rows: np.ndarray,
    row_ids: np.ndarray,
    min_count: int,
    k_max: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Emission for SELECTED rows of the count matrix: identical per-row
    semantics (diagonal masking at the global row id, threshold, top-k
    with ``lax.top_k``'s ascending-index tie order via the same composite
    integer key as ``ops.rules.emit_rule_tensors_np``) → ``(rule_ids,
    rule_counts, item_counts)`` for the selected rows."""
    r, v = counts_rows.shape
    if r == 0:
        return (
            np.full((0, k_max), -1, np.int32),
            np.zeros((0, k_max), np.int32),
            np.zeros(0, np.int32),
        )
    counts = counts_rows.astype(np.int64, copy=False)
    rows = np.arange(r)
    item_counts = counts[rows, row_ids].astype(np.int32)
    valid = counts >= min_count
    valid[rows, row_ids] = False
    score = np.where(valid, counts, np.int64(-1))
    key = score * np.int64(v) + (v - 1 - np.arange(v, dtype=np.int64))[None, :]
    k = min(k_max, v)
    if k < v:
        part = np.argpartition(-key, k - 1, axis=1)[:, :k]
    else:
        part = np.broadcast_to(np.arange(v)[None, :], (r, v)).copy()
    part_key = np.take_along_axis(key, part, axis=1)
    order = np.argsort(-part_key, axis=1)
    top_ids = np.take_along_axis(part, order, axis=1)
    top_counts = np.take_along_axis(score, top_ids, axis=1)
    keep = top_counts > 0
    rule_ids = np.where(keep, top_ids, -1).astype(np.int32)
    rule_counts = np.where(keep, top_counts, 0).astype(np.int32)
    if k < k_max:
        pad = ((0, 0), (0, k_max - k))
        rule_ids = np.pad(rule_ids, pad, constant_values=-1)
        rule_counts = np.pad(rule_counts, pad)
    return rule_ids, rule_counts, item_counts


def _confidence_filter_rows(
    rule_ids: np.ndarray,
    rule_counts: np.ndarray,
    item_counts: np.ndarray,
    min_confidence: float,
) -> tuple[np.ndarray, np.ndarray]:
    """Mirror of ``assemble_rule_tensors``'s confidence-mode host filter
    (float64, so device float32 rounding can never flip a decision)."""
    conf64 = rule_counts / np.maximum(item_counts, 1)[:, None].astype(
        np.float64
    )
    keep = (rule_ids >= 0) & (conf64 >= min_confidence)
    return (
        np.where(keep, rule_ids, -1).astype(np.int32),
        np.where(keep, rule_counts, 0).astype(np.int32),
    )


# ---------------------------------------------------------------------------
# the delta mining job
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DeltaResult:
    """What one delta run produced (``bundle_path`` None = no new rows)."""

    seq: int
    bundle_path: str | None
    n_new_rows: int
    n_changed: int
    n_tombstones: int
    n_touched: int
    duration_s: float
    fencing_token: int | None
    base_token: str
    dataset: str = ""
    run_index: int = 0
    # the recounted shape (combined baskets after the prune decision) —
    # what the restricted recount's analytic cost attribution
    # (costmodel "delta_recount", jobmetrics phase cost) is computed
    # over; 0/0 when nothing was recounted
    n_playlists: int = 0
    n_tracks: int = 0


def _read_suffix_table(path: str, offset: int, limit: int | None = None):
    """Parse ONLY the appended CSV rows (header + suffix bytes through the
    same pandas parser the full path falls back to) → (pids, names).
    ``limit`` bounds the suffix to the bytes the caller fingerprinted, so
    a feed appending mid-run can never desynchronize the saved digest
    from the rows actually encoded (the extras land in the NEXT delta)."""
    import pandas as pd

    with open(path, "rb") as fh:
        header = fh.readline()
        if offset < len(header):
            raise DeltaIneligible("appended region overlaps the CSV header")
        fh.seek(offset - 1)
        if fh.read(1) != b"\n":
            raise DeltaIneligible(
                "base prefix does not end at a line boundary — the "
                "appender continued a partial row"
            )
        suffix = fh.read() if limit is None else fh.read(limit)
    df = pd.read_csv(
        io_mod.BytesIO(header + suffix), keep_default_na=False
    )
    if "pid" not in df.columns or "track_name" not in df.columns:
        raise DeltaIneligible("appended rows missing pid/track_name columns")
    try:
        pids = df["pid"].astype(np.int64).to_numpy()
    except (ValueError, TypeError) as exc:
        raise DeltaIneligible(f"appended rows have invalid pids: {exc}")
    return pids, df["track_name"].astype(str).to_numpy()


def _check_eligibility(cfg: MiningConfig, base: dict[str, Any] | None) -> None:
    import jax

    if jax.process_count() > 1:
        raise DeltaIneligible("multi-host gang (delta mining is single-host)")
    if base is None:
        raise DeltaIneligible("no freshness base state on the PVC")
    if base.get("config_fingerprint") != delta_config_fingerprint(cfg):
        raise DeltaIneligible("mining config changed since the base run")
    if cfg.sample_ratio != 1.0:
        raise DeltaIneligible("sample_ratio head-slicing breaks append semantics")
    if cfg.max_itemset_len >= 3:
        raise DeltaIneligible(
            "triple/quad extensions need the full one-hot matrix"
        )


def _combined_baskets(
    base: dict[str, Any], new_pids: np.ndarray, new_names: np.ndarray
) -> tuple[Baskets, np.ndarray, np.ndarray]:
    """Extend the base membership with the appended rows →
    ``(combined baskets over the merged sorted vocab, merged pid values,
    affected playlist-row mask)``. Exactly what a full re-mine's
    ``build_baskets`` over the whole file produces: sorted-unique vocab,
    pid-rank playlist rows, deduplicated membership pairs."""
    base_names = base["vocab_names"]
    merged_names = sorted(set(base_names) | set(new_names.tolist()))
    vocab = Vocab(
        names=merged_names, index={n: i for i, n in enumerate(merged_names)}
    )
    names_arr = np.asarray(merged_names, dtype=object)
    # base ids re-rank into the merged sorted vocabulary
    base_remap = np.searchsorted(
        names_arr, np.asarray(base_names, dtype=object)
    ).astype(np.int64)
    merged_pids = np.union1d(base["pid_values"], np.unique(new_pids))
    base_row_remap = np.searchsorted(merged_pids, base["pid_values"])
    # scalar-key merge instead of a 2-D unique: encode (row, track) as
    # row·V + track (monotone in lex order, V ≪ 2^31 so no overflow) —
    # union1d over int64 keys is an order of magnitude faster than the
    # structured lexsort np.unique(axis=0) runs on the full pair set,
    # and the delta path exists to NOT pay full-mine-shaped costs
    v_merged = np.int64(len(merged_names))
    old_keys = (
        base_row_remap[base["playlist_rows"].astype(np.int64)].astype(np.int64)
        * v_merged
        + base_remap[base["track_ids"].astype(np.int64)]
    )
    new_rows = np.searchsorted(merged_pids, new_pids)
    new_tids = vocab.encode(new_names).astype(np.int64)
    new_keys = new_rows.astype(np.int64) * v_merged + new_tids
    keys = np.union1d(old_keys, new_keys)
    combined = Baskets(
        playlist_rows=(keys // v_merged).astype(np.int32),
        track_ids=(keys % v_merged).astype(np.int32),
        n_playlists=len(merged_pids),
        vocab=vocab,
    )
    affected = np.zeros(len(merged_pids), dtype=bool)
    affected[np.unique(new_rows)] = True
    return combined, merged_pids, affected


def run_delta_job(cfg: MiningConfig, mesh=None) -> DeltaResult:
    """The ``delta`` pipeline mode. Raises :class:`DeltaIneligible`
    whenever a full re-mine is the only correct answer."""
    import jax  # noqa: F401  (process_count in _check_eligibility)

    from ..mining import miner
    from ..parallel import layout as layout_mod
    from ..parallel.support import restricted_pair_counts

    t0 = time.perf_counter()
    base = load_base_state(cfg.pickles_dir)
    _check_eligibility(cfg, base)
    assert base is not None

    # the base generation must still be the published one: another writer
    # rewriting the token (or the npz) retires this base state
    token_path = os.path.join(cfg.base_dir, cfg.data_invalidation_file)
    try:
        current_token = artifacts.read_text(token_path)
    except FileNotFoundError:
        raise DeltaIneligible("no invalidation token on the PVC")
    if current_token != base["token"]:
        raise DeltaIneligible("another generation published since the base run")
    npz_path = artifacts.tensor_artifact_path(
        os.path.join(cfg.pickles_dir, cfg.recommendations_file)
    )
    if base.get("npz_sha256") is None or not os.path.exists(npz_path):
        raise DeltaIneligible("base run published no tensor artifact")
    if artifacts.file_digest(npz_path)["sha256"] != base["npz_sha256"]:
        raise DeltaIneligible("published tensor artifact changed on disk")

    # chain cap: past it, accumulated patch cost exceeds a clean re-mine
    state = artifacts.read_delta_state(cfg.pickles_dir)
    entries: list[dict[str, Any]] = []
    if state is not None:
        if state.get("base_token") != base["token"]:
            raise DeltaIneligible("delta chain bound to another generation")
        entries = list(state["entries"])
    if cfg.delta_max_chain > 0 and len(entries) >= cfg.delta_max_chain:
        raise DeltaIneligible(
            f"delta chain at its cap ({len(entries)}) — full re-mine"
        )

    # dataset fingerprint: unchanged prefix + appended suffix is the delta
    # case; anything else is a rewrite and must fully re-mine
    dataset_path = os.path.join(cfg.datasets_dir, base["dataset"])
    if not os.path.exists(dataset_path):
        raise DeltaIneligible(f"base dataset {base['dataset']} is gone")
    size = os.path.getsize(dataset_path)
    if size < base["dataset_bytes"]:
        raise DeltaIneligible("dataset shrank — not append-only")
    prefix_sha = hashlib.sha256()
    with open(dataset_path, "rb") as fh:
        remaining = base["dataset_bytes"]
        while remaining > 0:
            chunk = fh.read(min(1 << 20, remaining))
            if not chunk:
                break
            prefix_sha.update(chunk)
            remaining -= len(chunk)
        prefix_hex = prefix_sha.hexdigest()
        # continue the SAME stream through the suffix: the full-file
        # digest the rolled-forward base state needs comes out of this
        # one pass instead of a second linear re-read at save time
        suffix_len = 0
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            prefix_sha.update(chunk)
            suffix_len += len(chunk)
    full_sha = prefix_sha.hexdigest()
    hashed_bytes = base["dataset_bytes"] + suffix_len
    if prefix_hex != base["dataset_sha256"]:
        raise DeltaIneligible("dataset prefix rewritten — not append-only")
    if size == base["dataset_bytes"]:
        print("Delta mining: no new rows — nothing to publish")
        return DeltaResult(
            seq=entries[-1]["seq"] if entries else 0,
            bundle_path=None, n_new_rows=0, n_changed=0, n_tombstones=0,
            n_touched=0, duration_s=time.perf_counter() - t0,
            fencing_token=None, base_token=base["token"],
            dataset=base["dataset"], run_index=int(base["run_index"]),
        )

    # ---------- lease BEFORE the compute (fence zombies early) ----------
    lease = None
    if cfg.lease_enabled:
        lease = artifacts.PublicationLease.acquire(
            cfg.pickles_dir,
            ttl_s=cfg.lease_ttl_s,
            heartbeat_interval_s=cfg.lease_heartbeat_interval_s or None,
        )
        lease.start_heartbeat()
        print(
            f"Delta publication lease acquired (fencing token "
            f"{lease.fencing_token})"
        )
    try:
        new_pids, new_names = _read_suffix_table(
            dataset_path, base["dataset_bytes"], limit=suffix_len
        )
        print(
            f"Delta mining: {len(new_pids)} appended rows over "
            f"{len(np.unique(new_pids))} playlists"
        )
        combined, merged_pids, affected = _combined_baskets(
            base, new_pids, new_names
        )

        # mirror the full path's Apriori pruning decision EXACTLY
        new_min = min_count_for(cfg.min_support, combined.n_playlists)
        mined = combined
        if combined.n_tracks > cfg.prune_vocab_threshold:
            mined, _ = miner.prune_infrequent(combined, new_min)
            if mined.n_tracks == 0:
                if combined.n_tracks <= 4096:
                    mined = combined
                else:
                    raise DeltaIneligible(
                        "pruned vocabulary emptied — full re-mine decides"
                    )

        prev = base["published"]
        old_min = min_count_for(cfg.min_support, prev["n_playlists"])
        pruned_index = mined.vocab.index
        # touched: every item of every affected basket (the columns whose
        # count-matrix rows can have changed)
        touched_mask = affected[combined.playlist_rows]
        touched_full = np.unique(combined.track_ids[touched_mask])
        recompute = {
            combined.vocab.names[int(i)] for i in touched_full
        }
        # crossing band: untouched rows whose emitted rules (or key-set
        # membership) can drop under the risen threshold
        if new_min > old_min:
            counts_band = (
                (prev["rule_counts"] >= old_min)
                & (prev["rule_counts"] < new_min)
            ).any(axis=1)
            items_band = (prev["item_counts"] >= old_min) & (
                prev["item_counts"] < new_min
            )
            for i in np.flatnonzero(counts_band | items_band):
                recompute.add(prev["vocab"][int(i)])
        # names entering the published row space are touched by
        # construction; keep the explicit union as a belt-and-braces
        prev_set = set(prev["vocab"])
        recompute.update(n for n in mined.vocab.names if n not in prev_set)
        r_ids = np.asarray(
            sorted(
                pruned_index[n] for n in recompute if n in pruned_index
            ),
            dtype=np.int32,
        )
        tombstones = [n for n in prev["vocab"] if n not in pruned_index]
        # sanity: every surviving unchanged row must exist in the base
        changed_mark = np.zeros(mined.n_tracks, dtype=bool)
        changed_mark[r_ids] = True
        for i, name in enumerate(mined.vocab.names):
            if not changed_mark[i] and name not in prev_set:
                raise DeltaIneligible(
                    f"row {name!r} is new but outside the recompute set"
                )

        # ---------- column-restricted recount (the device compute) ------
        mesh = layout_mod.mining_mesh(cfg, mesh)
        use_mesh = mesh is not None and layout_mod.wants_sharded_mining(
            cfg, mesh
        )
        # same measured dispatcher as the full mine (mining/dispatch.py):
        # a sparse-eligible delta must not silently pay the dense
        # recount. Sparse restricted counts are integer-exact, so the
        # base ∘ chain == full-re-mine bit-identity pin holds with the
        # sparse path on (tests/test_freshness.py).
        from ..mining import dispatch as dispatch_mod

        plan = dispatch_mod.plan_count_path(
            cfg, mined.n_playlists, mined.n_tracks,
            len(mined.playlist_rows),
            backend=jax.default_backend(),
            n_devices=mesh.devices.size if use_mesh else 1,
            baskets=mined,
        )
        counts_r = restricted_pair_counts(
            mined, r_ids, mesh=mesh if use_mesh else None,
            count_path="sparse" if plan.path == "sparse" else None,
        )
        rule_ids, rule_counts, item_counts = emit_rule_rows_np(
            counts_r, r_ids.astype(np.int64), new_min, cfg.k_max_consequents
        )
        if cfg.confidence_mode == "confidence":
            rule_ids, rule_counts = _confidence_filter_rows(
                rule_ids, rule_counts, item_counts, cfg.min_confidence
            )
        if rule_ids.shape[1] != prev["rule_ids"].shape[1]:
            raise DeltaIneligible(
                "row capacity changed vs the base artifact"
            )

        # shrink: drop recomputed rows that equal their (re-mapped) base
        # row — their answers did not change, so the bundle (and the
        # cache invalidation set) should not name them
        new_index = {n: i for i, n in enumerate(mined.vocab.names)}
        remap = np.full(len(prev["vocab"]) + 1, -1, dtype=np.int32)
        for old_i, name in enumerate(prev["vocab"]):
            remap[old_i] = new_index.get(name, -1)
        prev_index = {n: i for i, n in enumerate(prev["vocab"])}
        keep_rows = np.ones(len(r_ids), dtype=bool)
        for e, row in enumerate(r_ids):
            name = mined.vocab.names[int(row)]
            j = prev_index.get(name)
            if j is None:
                continue
            old_ids = prev["rule_ids"][j]
            mapped = np.where(old_ids >= 0, remap[old_ids], -1)
            if (
                bool((mapped == rule_ids[e]).all())
                and bool((prev["rule_counts"][j] == rule_counts[e]).all())
                and int(prev["item_counts"][j]) == int(item_counts[e])
            ):
                keep_rows[e] = False
        r_ids_k = r_ids[keep_rows]
        rule_ids_k = rule_ids[keep_rows]
        rule_counts_k = rule_counts[keep_rows]
        item_counts_k = item_counts[keep_rows]

        seq = (entries[-1]["seq"] + 1) if entries else 1
        bundle_name = artifacts.delta_bundle_filename(seq)
        bundle_path = os.path.join(cfg.pickles_dir, bundle_name)
        if lease is not None:
            lease.check()  # fence point: no zombie writes the chain
        artifacts.save_delta_bundle(
            bundle_path,
            seq=seq,
            base_token=base["token"],
            base_npz_sha256=base["npz_sha256"],
            n_playlists=combined.n_playlists,
            min_count=new_min,
            vocab=list(mined.vocab.names),
            changed_rows=r_ids_k,
            changed_rule_ids=rule_ids_k,
            changed_rule_counts=rule_counts_k,
            changed_item_counts=item_counts_k,
            tombstones=tombstones,
        )
        digest = artifacts.file_digest(bundle_path)
        entries.append(
            {
                "seq": seq,
                "file": bundle_name,
                "sha256": digest["sha256"],
                "bytes": digest["bytes"],
                "written_at": time.time(),
                "fencing_token": lease.fencing_token if lease else None,
                "n_changed": int(len(r_ids_k)),
                "n_tombstones": len(tombstones),
                "n_playlists": int(combined.n_playlists),
            }
        )
        if lease is not None:
            # last fence before the chain rewrite makes the bundle live
            lease.check()
        artifacts.write_delta_state(
            cfg.pickles_dir, base["token"], base["npz_sha256"], entries
        )

        # roll the base state forward so the NEXT delta extends THIS one:
        # membership/pids/dataset fingerprint advance, and `published`
        # becomes base ∘ chain (the crossing-band scan must read current
        # counts, not the original base's)
        bundle = artifacts.load_delta_bundle(
            bundle_path, expect_sha256=digest["sha256"]
        )
        applied = apply_delta_to_tensors(prev, bundle)
        save_base_state(
            cfg,
            token=base["token"],
            run_index=base["run_index"],
            dataset_path=dataset_path,
            baskets=combined,
            pid_values=merged_pids,
            published=applied,
            npz_sha256=base["npz_sha256"],
            dataset_digest=(hashed_bytes, full_sha),
        )
        if lease is not None:
            lease.release()
        duration = time.perf_counter() - t0
        print(
            f"Delta {seq} published: {len(r_ids_k)} changed rows, "
            f"{len(tombstones)} tombstones, {len(recompute)} recomputed, "
            f"{duration:.2f}s"
        )
        return DeltaResult(
            seq=seq,
            bundle_path=bundle_path,
            n_new_rows=len(new_pids),
            n_changed=int(len(r_ids_k)),
            n_tombstones=len(tombstones),
            n_touched=len(recompute),
            duration_s=duration,
            fencing_token=lease.fencing_token if lease else None,
            base_token=base["token"],
            dataset=base["dataset"], run_index=int(base["run_index"]),
            n_playlists=int(mined.n_playlists),
            n_tracks=int(mined.n_tracks),
        )
    except BaseException:
        if lease is not None:
            lease.stop_heartbeat()
            try:
                lease.release()
            except (artifacts.LeaseLostError, OSError):
                pass
        raise
    finally:
        if lease is not None:
            lease.stop_heartbeat()


def derive_serving_arrays(
    state: dict[str, Any]
) -> tuple[list[str], np.ndarray, np.ndarray, np.ndarray]:
    """Logical tensors → the serving-engine array quadruple
    ``(vocab, rule_ids, rule_confs float32, known_mask)`` using exactly
    the load-path derivations (shared so a patched generation can never
    derive differently from a freshly loaded one)."""
    confs = derive_confs(
        state["rule_counts"], state["item_counts"],
        state["n_playlists"], state["mode"],
    )
    known = state["item_counts"] >= min_count_for(
        state["min_support"], state["n_playlists"]
    )
    return state["vocab"], state["rule_ids"], confs, np.asarray(known)
