"""Fleet-aware cache affinity — rendezvous hashing over replica identities.

The answer cache (serving/cache.py) is per-process: the reference
topology's 3 replicas each re-compute the same hot heads, so the fleet
does ~N× the unique-query work one pod would. Two fixes exist — route
requests so one replica OWNS each key (consistent-hash affinity at the
ingress/client), or bolt on a shared external cache tier. The ROADMAP's
decision path says MEASURE the affinity win first: this module is that
measurement layer plus the production half of the affinity option.

**Rendezvous (highest-random-weight) hashing**: the owner of a key is
``argmax over peers of H(peer, key)``. Unlike a modulo ring, removing a
peer re-maps ONLY the keys it owned (each surviving peer keeps its
argmax), which is exactly the property a rolling k8s deployment needs —
a pod replacement must not stampede every replica's cache at once.

Wiring (all default-off): ``KMLS_CACHE_AFFINITY=1`` arms the layer,
``KMLS_CACHE_AFFINITY_PEERS`` lists the replica identities (the headless
Service's pod DNS names — e.g. ``fast-api-0.fast-api,...`` — or any
stable ids), ``KMLS_CACHE_AFFINITY_SELF`` names THIS replica (default:
hostname, which under a StatefulSet IS the pod DNS label). The app then
counts ring-local vs ring-remote requests (``kmls_cache_affinity_*`` in
/metrics) — the observable that says what fraction of real traffic an
affinity router would keep local, before anyone deploys one.

:func:`simulate_fleet` is the offline half: replay a key stream against
an N-replica topology of bounded caches under affinity vs round-robin
routing and report the effective-hit-ratio multiplier (the bench
``freshness`` phase runs it at the reference's 3-replica shape).

**The routing half** (ISSUE 15): :class:`FleetRouter` is the live
client/ingress router the measurement above was collecting decision
data for. It routes each key to its rendezvous owner over the SAME ring
the simulation uses — one canonical implementation, so the simulated
multiplier is a prediction the fleet bench can falsify — and treats a
failing peer exactly like the PR 3 replica circuit breaker treats a
sick device replica: ``eject_threshold`` consecutive failures eject it
from routing (traffic spills to the next-highest rendezvous weight for
each key, the same bounded remap a peer removal would cause), and a
half-open probe every ``probe_interval_s`` re-admits it on the first
success. The serving side stays symmetric: replicas identified by
``KMLS_FLEET_SELF`` / ``KMLS_FLEET_PEERS`` answer mis-routed traffic
locally (degrade, never fail) while stamping ``X-KMLS-Cache-Owner`` and
counting ``kmls_cache_misrouted_total`` so routing drift is observable.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict


def _weight(peer: str, key: str) -> int:
    digest = hashlib.blake2b(
        f"{peer}\x1f{key}".encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


class RendezvousRing:
    """Highest-random-weight owner selection over a stable peer set."""

    def __init__(self, peers: list[str]):
        cleaned = [p.strip() for p in peers if p and p.strip()]
        if not cleaned:
            raise ValueError("rendezvous ring needs at least one peer")
        # stable order for deterministic max-tie resolution (a tie on the
        # 64-bit weight is astronomically unlikely; order makes it defined)
        self.peers = sorted(set(cleaned))

    def owner(self, key: str) -> str:
        return max(self.peers, key=lambda p: (_weight(p, key), p))

    def owner_index(self, key: str) -> int:
        return self.peers.index(self.owner(key))

    def owns(self, key: str, peer: str) -> bool:
        """True when ``peer`` is the rendezvous owner of ``key`` — the
        gate the predictive cache pre-fetch (ISSUE 17) applies so a
        predicted-hot seed set re-materializes on its owner replica
        ONLY, never as a fleet-wide broadcast."""
        return self.owner(key) == peer

    def ranked(self, key: str) -> list[str]:
        """Every peer in descending rendezvous weight for ``key`` — THE
        spill order. ``ranked(key)[0]`` is :meth:`owner`; removing the
        owner promotes ``ranked(key)[1]``, exactly the peer a ring built
        without the owner would elect (each survivor keeps its weight),
        so a router that spills down this list on peer loss remaps ONLY
        the lost peer's keys — the bounded-remap property."""
        return sorted(
            self.peers, key=lambda p: (_weight(p, key), p), reverse=True
        )


def seeds_key(seeds: list[str]) -> str:
    """The ring key for a seed set — same canonicalization as the answer
    cache (sorted, duplicates kept), so the owner of a request is the
    owner of its cache entry."""
    return "\x1f".join(sorted(seeds))


class _PeerHealth:
    __slots__ = (
        "consecutive_failures", "ejected", "next_probe_at", "failed_shard"
    )

    def __init__(self) -> None:
        self.consecutive_failures = 0
        self.ejected = False
        self.next_probe_at = 0.0
        # when the peer is a pod-gang (ISSUE 16) and its failure named a
        # missing vocab shard (X-KMLS-Mesh-Unavailable), the blamed rank
        # — None for a plain transport/5xx failure. Observability only:
        # ejection/spill/probe mechanics are identical either way (a
        # gang missing one shard is as unservable as a dead replica).
        self.failed_shard = None


class FleetRouter:
    """Health-aware rendezvous routing over the live peer set — the
    client/ingress half of the fleet cache tier (ISSUE 15).

    :meth:`route` returns the highest-weight NON-ejected peer for a key
    (the rendezvous owner while everyone is healthy). Failure handling
    mirrors the PR 3 replica circuit breaker, peer-for-peer:

    - ``eject_threshold`` CONSECUTIVE failures (``mark_failure``) eject
      a peer from routing; its keys spill to each key's next-highest
      rendezvous weight — the same bounded remap an actual membership
      change would cause, so survivors' caches never stampede;
    - an ejected peer is half-open probed: once per ``probe_interval_s``
      :meth:`route` hands it ONE request; ``mark_success`` re-admits it
      (its keys return — again only its own keys remap), another
      failure re-arms the probe timer;
    - with EVERY peer ejected the router fails open to the rendezvous
      owner (routing somewhere beats routing nowhere — the serving side
      degrades, never fails).

    Thread-safe (a pacing thread routes while worker threads mark);
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        peers: list[str],
        *,
        eject_threshold: int = 3,
        probe_interval_s: float = 1.0,
        clock=time.monotonic,
    ):
        self.ring = RendezvousRing(peers)
        self.eject_threshold = max(1, eject_threshold)
        self.probe_interval_s = probe_interval_s
        self._clock = clock
        self._health = {p: _PeerHealth() for p in self.ring.peers}
        self._lock = threading.Lock()
        self.ejections = 0
        self.readmissions = 0
        self.probes = 0
        self.spills = 0

    @property
    def peers(self) -> list[str]:
        return self.ring.peers

    def route(self, key: str) -> str:
        now = self._clock()
        ranked = self.ring.ranked(key)
        with self._lock:
            for i, peer in enumerate(ranked):
                health = self._health[peer]
                if not health.ejected:
                    if i > 0:
                        self.spills += 1
                    return peer
                if now >= health.next_probe_at:
                    # half-open: ONE request per probe interval auditions
                    # the ejected peer; everything else keeps spilling
                    health.next_probe_at = now + self.probe_interval_s
                    self.probes += 1
                    return peer
            # every peer ejected: fail open to the rendezvous owner
            return ranked[0]

    def mark_failure(self, peer: str, shard: int | None = None) -> None:
        """Count one failure against ``peer``. ``shard`` carries the
        blamed gang rank when the peer is a pod-gang that answered
        gang-degraded (503 + ``X-KMLS-Mesh-Unavailable`` — a dead gang
        MEMBER); the breaker mechanics are shard-blind — shard loss
        degrades exactly like replica loss."""
        with self._lock:
            health = self._health.get(peer)
            if health is None:
                return
            health.consecutive_failures += 1
            if shard is not None:
                health.failed_shard = int(shard)
            if health.ejected:
                # failed probe: push the next audition out a full interval
                health.next_probe_at = self._clock() + self.probe_interval_s
            elif health.consecutive_failures >= self.eject_threshold:
                health.ejected = True
                health.next_probe_at = self._clock() + self.probe_interval_s
                self.ejections += 1

    def mark_success(self, peer: str) -> None:
        with self._lock:
            health = self._health.get(peer)
            if health is None:
                return
            health.consecutive_failures = 0
            health.failed_shard = None
            if health.ejected:
                health.ejected = False
                self.readmissions += 1

    def ejected_peers(self) -> list[str]:
        with self._lock:
            return [p for p, h in self._health.items() if h.ejected]

    def failed_shards(self) -> dict[str, int]:
        """peer → last blamed gang rank, for peers whose most recent
        failure named a missing shard (cleared on success) — how an
        operator reading the replay/router report tells 'the gang lost
        member 1' apart from 'the whole pod died'."""
        with self._lock:
            return {
                p: h.failed_shard
                for p, h in self._health.items()
                if h.failed_shard is not None
            }


class _BoundedSet:
    """Tiny LRU set standing in for one replica's answer cache."""

    def __init__(self, capacity: int):
        self.capacity = max(1, capacity)
        self._od: "OrderedDict[str, None]" = OrderedDict()

    def hit_or_insert(self, key: str) -> bool:
        if key in self._od:
            self._od.move_to_end(key)
            return True
        self._od[key] = None
        if len(self._od) > self.capacity:
            self._od.popitem(last=False)
        return False


def simulate_fleet(
    keys: list[str],
    n_replicas: int,
    capacity: int,
    policy: str = "affinity",
) -> float:
    """Effective FLEET hit ratio for a key stream under a routing policy:
    ``affinity`` (rendezvous owner), ``roundrobin``, or ``random``
    (hash-of-position — deterministic, so runs are reproducible). Each
    replica is a bounded LRU; the fleet hit ratio is hits/requests across
    all replicas — the "work done per unique query" number the ROADMAP's
    fleet item asks for."""
    if policy not in ("affinity", "roundrobin", "random"):
        raise ValueError(f"unknown routing policy {policy!r}")
    peers = [f"replica-{i}" for i in range(max(1, n_replicas))]
    # the ONE ring implementation: the same RendezvousRing the live
    # FleetRouter (and the app's owner stamping) routes on, so the
    # simulated multiplier is a prediction the fleet bench can falsify —
    # drift between simulation and routing is impossible by construction
    ring = RendezvousRing(peers) if policy == "affinity" else None
    caches = [_BoundedSet(capacity) for _ in peers]
    hits = 0
    for i, key in enumerate(keys):
        if ring is not None:
            idx = ring.owner_index(key)
        elif policy == "roundrobin":
            idx = i % len(peers)
        else:
            idx = _weight("route", f"{i}") % len(peers)
        if caches[idx].hit_or_insert(key):
            hits += 1
    return hits / len(keys) if keys else 0.0


def fleet_multiplier(
    keys: list[str], n_replicas: int = 3, capacity: int = 512
) -> dict[str, float]:
    """The decision number: affinity vs round-robin effective hit ratio
    over the same stream/topology, and their ratio (the fleet-wide
    effective-hit-ratio multiplier the bench compact line reports)."""
    affinity = simulate_fleet(keys, n_replicas, capacity, "affinity")
    baseline = simulate_fleet(keys, n_replicas, capacity, "roundrobin")
    return {
        "affinity_hit_ratio": affinity,
        "baseline_hit_ratio": baseline,
        "multiplier": (affinity / baseline) if baseline > 0 else float("inf"),
    }
