"""Fleet-aware cache affinity — rendezvous hashing over replica identities.

The answer cache (serving/cache.py) is per-process: the reference
topology's 3 replicas each re-compute the same hot heads, so the fleet
does ~N× the unique-query work one pod would. Two fixes exist — route
requests so one replica OWNS each key (consistent-hash affinity at the
ingress/client), or bolt on a shared external cache tier. The ROADMAP's
decision path says MEASURE the affinity win first: this module is that
measurement layer plus the production half of the affinity option.

**Rendezvous (highest-random-weight) hashing**: the owner of a key is
``argmax over peers of H(peer, key)``. Unlike a modulo ring, removing a
peer re-maps ONLY the keys it owned (each surviving peer keeps its
argmax), which is exactly the property a rolling k8s deployment needs —
a pod replacement must not stampede every replica's cache at once.

Wiring (all default-off): ``KMLS_CACHE_AFFINITY=1`` arms the layer,
``KMLS_CACHE_AFFINITY_PEERS`` lists the replica identities (the headless
Service's pod DNS names — e.g. ``fast-api-0.fast-api,...`` — or any
stable ids), ``KMLS_CACHE_AFFINITY_SELF`` names THIS replica (default:
hostname, which under a StatefulSet IS the pod DNS label). The app then
counts ring-local vs ring-remote requests (``kmls_cache_affinity_*`` in
/metrics) — the observable that says what fraction of real traffic an
affinity router would keep local, before anyone deploys one.

:func:`simulate_fleet` is the offline half: replay a key stream against
an N-replica topology of bounded caches under affinity vs round-robin
routing and report the effective-hit-ratio multiplier (the bench
``freshness`` phase runs it at the reference's 3-replica shape).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict


def _weight(peer: str, key: str) -> int:
    digest = hashlib.blake2b(
        f"{peer}\x1f{key}".encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


class RendezvousRing:
    """Highest-random-weight owner selection over a stable peer set."""

    def __init__(self, peers: list[str]):
        cleaned = [p.strip() for p in peers if p and p.strip()]
        if not cleaned:
            raise ValueError("rendezvous ring needs at least one peer")
        # stable order for deterministic max-tie resolution (a tie on the
        # 64-bit weight is astronomically unlikely; order makes it defined)
        self.peers = sorted(set(cleaned))

    def owner(self, key: str) -> str:
        return max(self.peers, key=lambda p: (_weight(p, key), p))

    def owner_index(self, key: str) -> int:
        return self.peers.index(self.owner(key))


def seeds_key(seeds: list[str]) -> str:
    """The ring key for a seed set — same canonicalization as the answer
    cache (sorted, duplicates kept), so the owner of a request is the
    owner of its cache entry."""
    return "\x1f".join(sorted(seeds))


class _BoundedSet:
    """Tiny LRU set standing in for one replica's answer cache."""

    def __init__(self, capacity: int):
        self.capacity = max(1, capacity)
        self._od: "OrderedDict[str, None]" = OrderedDict()

    def hit_or_insert(self, key: str) -> bool:
        if key in self._od:
            self._od.move_to_end(key)
            return True
        self._od[key] = None
        if len(self._od) > self.capacity:
            self._od.popitem(last=False)
        return False


def simulate_fleet(
    keys: list[str],
    n_replicas: int,
    capacity: int,
    policy: str = "affinity",
) -> float:
    """Effective FLEET hit ratio for a key stream under a routing policy:
    ``affinity`` (rendezvous owner), ``roundrobin``, or ``random``
    (hash-of-position — deterministic, so runs are reproducible). Each
    replica is a bounded LRU; the fleet hit ratio is hits/requests across
    all replicas — the "work done per unique query" number the ROADMAP's
    fleet item asks for."""
    if policy not in ("affinity", "roundrobin", "random"):
        raise ValueError(f"unknown routing policy {policy!r}")
    peers = [f"replica-{i}" for i in range(max(1, n_replicas))]
    ring = RendezvousRing(peers) if policy == "affinity" else None
    caches = [_BoundedSet(capacity) for _ in peers]
    hits = 0
    for i, key in enumerate(keys):
        if ring is not None:
            idx = ring.peers.index(ring.owner(key))
        elif policy == "roundrobin":
            idx = i % len(peers)
        else:
            idx = _weight("route", f"{i}") % len(peers)
        if caches[idx].hit_or_insert(key):
            hits += 1
    return hits / len(keys) if keys else 0.0


def fleet_multiplier(
    keys: list[str], n_replicas: int = 3, capacity: int = 512
) -> dict[str, float]:
    """The decision number: affinity vs round-robin effective hit ratio
    over the same stream/topology, and their ratio (the fleet-wide
    effective-hit-ratio multiplier the bench compact line reports)."""
    affinity = simulate_fleet(keys, n_replicas, capacity, "affinity")
    baseline = simulate_fleet(keys, n_replicas, capacity, "roundrobin")
    return {
        "affinity_hit_ratio": affinity,
        "baseline_hit_ratio": baseline,
        "multiplier": (affinity / baseline) if baseline > 0 else float("inf"),
    }
