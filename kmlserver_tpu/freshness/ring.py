"""Fleet-aware cache affinity — rendezvous hashing over replica identities.

The answer cache (serving/cache.py) is per-process: the reference
topology's 3 replicas each re-compute the same hot heads, so the fleet
does ~N× the unique-query work one pod would. Two fixes exist — route
requests so one replica OWNS each key (consistent-hash affinity at the
ingress/client), or bolt on a shared external cache tier. The ROADMAP's
decision path says MEASURE the affinity win first: this module is that
measurement layer plus the production half of the affinity option.

**Rendezvous (highest-random-weight) hashing**: the owner of a key is
``argmax over peers of H(peer, key)``. Unlike a modulo ring, removing a
peer re-maps ONLY the keys it owned (each surviving peer keeps its
argmax), which is exactly the property a rolling k8s deployment needs —
a pod replacement must not stampede every replica's cache at once.

Wiring (all default-off): ``KMLS_CACHE_AFFINITY=1`` arms the layer,
``KMLS_CACHE_AFFINITY_PEERS`` lists the replica identities (the headless
Service's pod DNS names — e.g. ``fast-api-0.fast-api,...`` — or any
stable ids), ``KMLS_CACHE_AFFINITY_SELF`` names THIS replica (default:
hostname, which under a StatefulSet IS the pod DNS label). The app then
counts ring-local vs ring-remote requests (``kmls_cache_affinity_*`` in
/metrics) — the observable that says what fraction of real traffic an
affinity router would keep local, before anyone deploys one.

:func:`simulate_fleet` is the offline half: replay a key stream against
an N-replica topology of bounded caches under affinity vs round-robin
routing and report the effective-hit-ratio multiplier (the bench
``freshness`` phase runs it at the reference's 3-replica shape).

**The routing half** (ISSUE 15): :class:`FleetRouter` is the live
client/ingress router the measurement above was collecting decision
data for. It routes each key to its rendezvous owner over the SAME ring
the simulation uses — one canonical implementation, so the simulated
multiplier is a prediction the fleet bench can falsify — and treats a
failing peer exactly like the PR 3 replica circuit breaker treats a
sick device replica: ``eject_threshold`` consecutive failures eject it
from routing (traffic spills to the next-highest rendezvous weight for
each key, the same bounded remap a peer removal would cause), and a
half-open probe every ``probe_interval_s`` re-admits it on the first
success. The serving side stays symmetric: replicas identified by
``KMLS_FLEET_SELF`` / ``KMLS_FLEET_PEERS`` answer mis-routed traffic
locally (degrade, never fail) while stamping ``X-KMLS-Cache-Owner`` and
counting ``kmls_cache_misrouted_total`` so routing drift is observable.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict, deque


def _weight(peer: str, key: str) -> int:
    digest = hashlib.blake2b(
        f"{peer}\x1f{key}".encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


class RendezvousRing:
    """Highest-random-weight owner selection over a stable peer set."""

    def __init__(self, peers: list[str]):
        cleaned = [p.strip() for p in peers if p and p.strip()]
        if not cleaned:
            raise ValueError("rendezvous ring needs at least one peer")
        # stable order for deterministic max-tie resolution (a tie on the
        # 64-bit weight is astronomically unlikely; order makes it defined)
        self.peers = sorted(set(cleaned))

    def owner(self, key: str) -> str:
        return max(self.peers, key=lambda p: (_weight(p, key), p))

    def owner_index(self, key: str) -> int:
        return self.peers.index(self.owner(key))

    def owns(self, key: str, peer: str) -> bool:
        """True when ``peer`` is the rendezvous owner of ``key`` — the
        gate the predictive cache pre-fetch (ISSUE 17) applies so a
        predicted-hot seed set re-materializes on its owner replica
        ONLY, never as a fleet-wide broadcast."""
        return self.owner(key) == peer

    def ranked(self, key: str) -> list[str]:
        """Every peer in descending rendezvous weight for ``key`` — THE
        spill order. ``ranked(key)[0]`` is :meth:`owner`; removing the
        owner promotes ``ranked(key)[1]``, exactly the peer a ring built
        without the owner would elect (each survivor keeps its weight),
        so a router that spills down this list on peer loss remaps ONLY
        the lost peer's keys — the bounded-remap property."""
        return sorted(
            self.peers, key=lambda p: (_weight(p, key), p), reverse=True
        )


def seeds_key(seeds: list[str]) -> str:
    """The ring key for a seed set — same canonicalization as the answer
    cache (sorted, duplicates kept), so the owner of a request is the
    owner of its cache entry."""
    return "\x1f".join(sorted(seeds))


# EWMA smoothing for per-peer latency; ~0.2 weights the last ~10 samples
_EWMA_ALPHA = 0.2
# samples a peer must contribute before its EWMA participates in the
# slow-outlier ladder (or in the healthy-median it is compared against)
_MIN_LATENCY_SAMPLES = 8
# bounded window backing the hedge-delay quantile
_LATENCY_WINDOW = 64


class _PeerHealth:
    __slots__ = (
        "consecutive_failures", "ejected", "next_probe_at", "failed_shard",
        "ewma_s", "samples", "recent", "slow",
    )

    def __init__(self) -> None:
        self.consecutive_failures = 0
        self.ejected = False
        self.next_probe_at = 0.0
        # when the peer is a pod-gang (ISSUE 16) and its failure named a
        # missing vocab shard (X-KMLS-Mesh-Unavailable), the blamed rank
        # — None for a plain transport/5xx failure. Observability only:
        # ejection/spill/probe mechanics are identical either way (a
        # gang missing one shard is as unservable as a dead replica).
        self.failed_shard = None
        # latency-aware health (ISSUE 18): EWMA of observed round-trip
        # seconds, sample count gating ladder participation, a bounded
        # recent window for the hedge-delay quantile, and whether the
        # current ejection was for SLOWNESS (re-admitted by a fast probe
        # sample, not by mark_success — a gray-failed peer still answers
        # successfully, just late).
        self.ewma_s = 0.0
        self.samples = 0
        self.recent: deque = deque(maxlen=_LATENCY_WINDOW)
        self.slow = False


class FleetRouter:
    """Health-aware rendezvous routing over the live peer set — the
    client/ingress half of the fleet cache tier (ISSUE 15).

    :meth:`route` returns the highest-weight NON-ejected peer for a key
    (the rendezvous owner while everyone is healthy). Failure handling
    mirrors the PR 3 replica circuit breaker, peer-for-peer:

    - ``eject_threshold`` CONSECUTIVE failures (``mark_failure``) eject
      a peer from routing; its keys spill to each key's next-highest
      rendezvous weight — the same bounded remap an actual membership
      change would cause, so survivors' caches never stampede;
    - an ejected peer is half-open probed: once per ``probe_interval_s``
      :meth:`route` hands it ONE request; ``mark_success`` re-admits it
      (its keys return — again only its own keys remap), another
      failure re-arms the probe timer;
    - with EVERY peer ejected the router fails open to the rendezvous
      owner (routing somewhere beats routing nowhere — the serving side
      degrades, never fails).

    **Gray failures** (ISSUE 18): a slow-but-alive peer never trips the
    error breaker — every answer is a 200, just late. ``mark_latency``
    feeds per-peer EWMA latency into a SLOW-outlier ladder that shares
    the ejection machinery above: when ``slow_ratio > 0`` and a peer's
    EWMA exceeds ``slow_ratio ×`` the healthy-peer median, it is ejected
    exactly like a failing peer (same spill, same half-open probe
    cadence) — slowness and sickness converge on one peer-state
    machine. Re-admission differs in ONE way: a slow-ejected peer is
    re-admitted by a probe whose own latency sample is back under the
    bar, not by ``mark_success`` (a gray-failed peer still succeeds,
    just late — success is no evidence of recovery).

    Thread-safe (a pacing thread routes while worker threads mark);
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        peers: list[str],
        *,
        eject_threshold: int = 3,
        probe_interval_s: float = 1.0,
        slow_ratio: float = 0.0,
        clock=time.monotonic,
    ):
        self.ring = RendezvousRing(peers)
        self.eject_threshold = max(1, eject_threshold)
        self.probe_interval_s = probe_interval_s
        # 0 disables the slow ladder: mark_latency still tracks (the
        # hedge delay quantile wants samples either way) but never ejects
        self.slow_ratio = max(0.0, slow_ratio)
        self._clock = clock
        self._health = {p: _PeerHealth() for p in self.ring.peers}
        self._lock = threading.Lock()
        self.ejections = 0
        self.readmissions = 0
        self.probes = 0
        self.spills = 0
        self.slow_ejections = 0

    @property
    def peers(self) -> list[str]:
        return self.ring.peers

    def route(self, key: str) -> str:
        now = self._clock()
        ranked = self.ring.ranked(key)
        with self._lock:
            for i, peer in enumerate(ranked):
                health = self._health[peer]
                if not health.ejected:
                    if i > 0:
                        self.spills += 1
                    return peer
                if now >= health.next_probe_at:
                    # half-open: ONE request per probe interval auditions
                    # the ejected peer; everything else keeps spilling
                    health.next_probe_at = now + self.probe_interval_s
                    self.probes += 1
                    return peer
            # every peer ejected: fail open to the rendezvous owner
            return ranked[0]

    def mark_failure(self, peer: str, shard: int | None = None) -> None:
        """Count one failure against ``peer``. ``shard`` carries the
        blamed gang rank when the peer is a pod-gang that answered
        gang-degraded (503 + ``X-KMLS-Mesh-Unavailable`` — a dead gang
        MEMBER); the breaker mechanics are shard-blind — shard loss
        degrades exactly like replica loss."""
        with self._lock:
            health = self._health.get(peer)
            if health is None:
                return
            health.consecutive_failures += 1
            if shard is not None:
                health.failed_shard = int(shard)
            if health.ejected:
                # failed probe: push the next audition out a full interval
                health.next_probe_at = self._clock() + self.probe_interval_s
            elif health.consecutive_failures >= self.eject_threshold:
                health.ejected = True
                health.next_probe_at = self._clock() + self.probe_interval_s
                self.ejections += 1

    def mark_success(self, peer: str) -> None:
        with self._lock:
            health = self._health.get(peer)
            if health is None:
                return
            health.consecutive_failures = 0
            health.failed_shard = None
            # a SLOW-ejected peer is not re-admitted by success — a gray
            # failure answers successfully, just late; only a fast probe
            # latency sample (mark_latency) clears it
            if health.ejected and not health.slow:
                health.ejected = False
                self.readmissions += 1

    def _healthy_median_locked(self, exclude: str) -> float | None:
        """Median EWMA over healthy peers with enough samples, excluding
        the peer under judgment (a slow outlier must not drag the bar it
        is measured against). Caller holds the lock."""
        ewmas = sorted(
            h.ewma_s
            for p, h in self._health.items()
            if p != exclude
            and not h.ejected
            and h.samples >= _MIN_LATENCY_SAMPLES
        )
        if not ewmas:
            return None
        mid = len(ewmas) // 2
        if len(ewmas) % 2:
            return ewmas[mid]
        return 0.5 * (ewmas[mid - 1] + ewmas[mid])

    def mark_latency(self, peer: str, seconds: float) -> None:
        """Feed one observed round-trip into ``peer``'s latency health.

        Always tracks (EWMA + bounded recent window — the hedge-delay
        quantile wants samples even with the ladder off). With
        ``slow_ratio > 0`` it also runs the slow-outlier ladder:

        - EWMA above ``slow_ratio × healthy-median`` (after at least
          ``_MIN_LATENCY_SAMPLES`` observations, with at least one other
          sampled healthy peer to define the median) ejects the peer —
          same machinery, counted in both ``ejections`` and
          ``slow_ejections``;
        - while slow-ejected, each half-open probe's OWN sample is the
          audition: back under the bar re-admits (EWMA reset to that
          sample so the stale slow history doesn't instantly re-eject),
          still slow re-arms the probe timer.
        """
        with self._lock:
            health = self._health.get(peer)
            if health is None:
                return
            s = max(0.0, float(seconds))
            health.recent.append(s)
            health.samples += 1
            if health.samples == 1:
                health.ewma_s = s
            else:
                health.ewma_s += _EWMA_ALPHA * (s - health.ewma_s)
            if self.slow_ratio <= 0.0:
                return
            if health.slow and health.ejected:
                median = self._healthy_median_locked(exclude=peer)
                if median is not None and s <= self.slow_ratio * median:
                    health.slow = False
                    health.ejected = False
                    health.ewma_s = s
                    self.readmissions += 1
                else:
                    health.next_probe_at = (
                        self._clock() + self.probe_interval_s
                    )
                return
            if health.ejected or health.samples < _MIN_LATENCY_SAMPLES:
                return
            median = self._healthy_median_locked(exclude=peer)
            if median is not None and health.ewma_s > self.slow_ratio * median:
                health.slow = True
                health.ejected = True
                health.next_probe_at = self._clock() + self.probe_interval_s
                self.ejections += 1
                self.slow_ejections += 1

    def hedge_delay_s(self, peer: str, floor_s: float = 0.0) -> float:
        """Adaptive hedge trigger for ``peer``: ~p95 of its recent
        latency window, floored at ``floor_s`` (KMLS_HEDGE_DELAY_MS).
        Until the window has enough samples the floor stands alone — a
        cold router must not hedge aggressively on noise."""
        with self._lock:
            health = self._health.get(peer)
            if health is None or len(health.recent) < _MIN_LATENCY_SAMPLES:
                return floor_s
            ordered = sorted(health.recent)
            q = ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]
            return max(floor_s, q)

    def peer_latency_s(self, peer: str) -> float:
        """Current EWMA latency estimate for ``peer`` (0.0 unsampled)."""
        with self._lock:
            health = self._health.get(peer)
            return health.ewma_s if health is not None else 0.0

    def ejected_peers(self) -> list[str]:
        with self._lock:
            return [p for p, h in self._health.items() if h.ejected]

    def slow_peers(self) -> list[str]:
        """Peers currently ejected for SLOWNESS (gray failure) — disjoint
        from error-ejected peers in ejected_peers() only by cause."""
        with self._lock:
            return [p for p, h in self._health.items() if h.slow]

    def failed_shards(self) -> dict[str, int]:
        """peer → last blamed gang rank, for peers whose most recent
        failure named a missing shard (cleared on success) — how an
        operator reading the replay/router report tells 'the gang lost
        member 1' apart from 'the whole pod died'."""
        with self._lock:
            return {
                p: h.failed_shard
                for p, h in self._health.items()
                if h.failed_shard is not None
            }


class _BoundedSet:
    """Tiny LRU set standing in for one replica's answer cache."""

    def __init__(self, capacity: int):
        self.capacity = max(1, capacity)
        self._od: "OrderedDict[str, None]" = OrderedDict()

    def hit_or_insert(self, key: str) -> bool:
        if key in self._od:
            self._od.move_to_end(key)
            return True
        self._od[key] = None
        if len(self._od) > self.capacity:
            self._od.popitem(last=False)
        return False


def simulate_fleet(
    keys: list[str],
    n_replicas: int,
    capacity: int,
    policy: str = "affinity",
) -> float:
    """Effective FLEET hit ratio for a key stream under a routing policy:
    ``affinity`` (rendezvous owner), ``roundrobin``, or ``random``
    (hash-of-position — deterministic, so runs are reproducible). Each
    replica is a bounded LRU; the fleet hit ratio is hits/requests across
    all replicas — the "work done per unique query" number the ROADMAP's
    fleet item asks for."""
    if policy not in ("affinity", "roundrobin", "random"):
        raise ValueError(f"unknown routing policy {policy!r}")
    peers = [f"replica-{i}" for i in range(max(1, n_replicas))]
    # the ONE ring implementation: the same RendezvousRing the live
    # FleetRouter (and the app's owner stamping) routes on, so the
    # simulated multiplier is a prediction the fleet bench can falsify —
    # drift between simulation and routing is impossible by construction
    ring = RendezvousRing(peers) if policy == "affinity" else None
    caches = [_BoundedSet(capacity) for _ in peers]
    hits = 0
    for i, key in enumerate(keys):
        if ring is not None:
            idx = ring.owner_index(key)
        elif policy == "roundrobin":
            idx = i % len(peers)
        else:
            idx = _weight("route", f"{i}") % len(peers)
        if caches[idx].hit_or_insert(key):
            hits += 1
    return hits / len(keys) if keys else 0.0


def fleet_multiplier(
    keys: list[str], n_replicas: int = 3, capacity: int = 512
) -> dict[str, float]:
    """The decision number: affinity vs round-robin effective hit ratio
    over the same stream/topology, and their ratio (the fleet-wide
    effective-hit-ratio multiplier the bench compact line reports)."""
    affinity = simulate_fleet(keys, n_replicas, capacity, "affinity")
    baseline = simulate_fleet(keys, n_replicas, capacity, "roundrobin")
    return {
        "affinity_hit_ratio": affinity,
        "baseline_hit_ratio": baseline,
        "multiplier": (affinity / baseline) if baseline > 0 else float("inf"),
    }
