from . import artifacts, registry  # noqa: F401
