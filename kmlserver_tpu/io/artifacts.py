"""Artifact I/O — the wire contract between the mining job and the API.

The reference hands everything between its two workloads as pickle files on a
shared RWX PVC (reference: machine-learning/main.py:136-145 writes;
rest_api/app/main.py:52-80 reads). This module keeps that pickle contract
byte-compatible (same object shapes, same filenames) so either side of the
reference could interoperate with this rebuild, and adds:

- **atomic writes** (tmp file + ``os.replace``) — the reference rewrites
  artifacts in place, racing readers (acknowledged in its report); atomic
  rename removes the torn-read window without changing the protocol;
- a **tensor-native artifact** (``.npz`` of the padded rule tensors) written
  alongside the pickle, so the serving engine can ``jax.device_put`` rule
  tensors straight into HBM without re-deriving them from the dict.
"""

from __future__ import annotations

import io
import os
import pickle
import tempfile
from typing import Any

import numpy as np

TENSOR_ARTIFACT_SUFFIX = ".tensors.npz"


def _atomic_write_bytes(path: str, data: bytes) -> None:
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp_path = tempfile.mkstemp(dir=directory, prefix=".tmp_", suffix=".part")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
        # mkstemp creates 0600; artifacts are read by the API replicas
        # (possibly a different uid on the shared volume)
        os.chmod(tmp_path, 0o644)
        os.replace(tmp_path, path)
    except BaseException:
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        raise


def save_pickle(obj: Any, path: str) -> None:
    """Pickle ``obj`` to ``path`` atomically.

    Same role as the reference's ``save_pickle`` (machine-learning/main.py:136-145),
    which mkdirs the folder and ``pickle.dump``s in place; here the folder is
    created and the write is atomic.
    """
    _atomic_write_bytes(path, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))


def load_pickle(path: str) -> Any:
    with open(path, "rb") as fh:
        return pickle.load(fh)


def atomic_write_text(path: str, text: str) -> None:
    _atomic_write_bytes(path, text.encode("utf-8"))


def read_text(path: str) -> str:
    with open(path, "r", encoding="utf-8") as fh:
        return fh.read()


def tensor_artifact_path(recommendations_pickle_path: str) -> str:
    """Path of the npz rule-tensor artifact shadowing a recommendations pickle."""
    return recommendations_pickle_path + TENSOR_ARTIFACT_SUFFIX


def save_rule_tensors(
    path: str,
    *,
    vocab: list[str],
    rule_ids: np.ndarray,
    rule_counts: np.ndarray,
    item_counts: np.ndarray,
    n_playlists: int,
    min_support: float,
    mode: str = "support",
    min_confidence: float = 0.0,
    rule_confs64: np.ndarray | None = None,
) -> None:
    """Write the padded rule tensors + vocabulary as one ``.npz``.

    ``rule_ids``    int32 (V, K_max) — consequent track ids, -1 padding.
    ``rule_counts`` int32 (V, K_max) — co-occurrence COUNTS (not floats:
                    consumers re-derive confidences with the same float64
                    arithmetic as the pickle path, so the two artifacts can
                    never drift).
    ``item_counts`` int32 (V,) — singleton supports; items with
                    count ≥ ceil(min_support·P) are the rule-dict key set
                    (including empty rows — see ops/rules.py).
    ``rule_confs64`` float64 (V, K_max), only when confidences carry
                    per-rule denominators (triple-antecedent merge) and so
                    cannot be re-derived from counts.
    """
    if rule_ids.shape != rule_counts.shape:
        raise ValueError(f"rule_ids {rule_ids.shape} != rule_counts {rule_counts.shape}")
    if rule_ids.shape[0] != len(vocab) or len(item_counts) != len(vocab):
        raise ValueError(
            f"rows {rule_ids.shape[0]}/{len(item_counts)} != vocab size {len(vocab)}"
        )
    arrays = dict(
        vocab=np.asarray(vocab, dtype=object),
        rule_ids=rule_ids.astype(np.int32),
        rule_counts=rule_counts.astype(np.int32),
        item_counts=item_counts.astype(np.int32),
        n_playlists=np.int64(n_playlists),
        min_support=np.float64(min_support),
        mode=np.asarray(mode),
        min_confidence=np.float64(min_confidence),
    )
    if rule_confs64 is not None:
        if rule_confs64.shape != rule_ids.shape:
            raise ValueError(
                f"rule_confs64 {rule_confs64.shape} != rule_ids {rule_ids.shape}"
            )
        arrays["rule_confs64"] = rule_confs64.astype(np.float64)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    _atomic_write_bytes(path, buf.getvalue())


def load_rule_tensors(path: str) -> dict[str, Any]:
    """Load the npz artifact, deriving serving-ready float32 confidences."""
    from ..ops.rules import derive_confs

    with np.load(path, allow_pickle=True) as npz:
        rule_counts = npz["rule_counts"]
        item_counts = npz["item_counts"]
        n_playlists = int(npz["n_playlists"])
        mode = str(npz["mode"])
        confs64 = npz["rule_confs64"] if "rule_confs64" in npz.files else None
        rule_ids = npz["rule_ids"]
        if confs64 is None and bool(((rule_ids >= 0) & (rule_counts <= 0)).any()):
            # valid rules with zero counts can only come from a
            # triple-merged artifact whose rule_confs64 was stripped —
            # re-deriving would silently turn every confidence into 0.0
            raise ValueError(
                f"{path}: rules present with zero counts and no rule_confs64 "
                f"— corrupt or stripped artifact"
            )
        confs = (
            confs64.astype(np.float32)
            if confs64 is not None
            else derive_confs(rule_counts, item_counts, n_playlists, mode)
        )
        return {
            "vocab": [str(s) for s in npz["vocab"]],
            "rule_ids": npz["rule_ids"],
            "rule_counts": rule_counts,
            "rule_confs": confs,
            "rule_confs64": confs64,
            "item_counts": item_counts,
            "n_playlists": n_playlists,
            "min_support": float(npz["min_support"]),
            "mode": mode,
            "min_confidence": float(npz["min_confidence"]),
        }


def rules_dict_from_tensors(loaded: dict[str, Any]) -> dict[str, dict[str, float]]:
    """Expand a :func:`load_rule_tensors` result into the reference's pickle
    object shape ``{song_name: {other_song_name: confidence}}`` (the object
    ``rest_api/app/main.py:68-76`` unpickles), via the one canonical
    expansion in ``ops/rules.py`` — guaranteeing npz→dict equals the dict
    the mining job pickled."""
    from ..ops.rules import expand_rules_dict

    return expand_rules_dict(
        loaded["vocab"],
        loaded["rule_ids"],
        loaded["rule_counts"],
        loaded["item_counts"],
        n_playlists=loaded["n_playlists"],
        min_support=loaded["min_support"],
        mode=loaded["mode"],
        rule_confs64=loaded.get("rule_confs64"),
    )


def tensors_from_rules_dict(
    rules: dict[str, dict[str, float]],
    vocab: list[str],
    k_max: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Inverse direction for loading legacy pickles (e.g. written by the
    reference job) into the device-resident layout. Returns
    ``(rule_ids, rule_confs, known_mask)`` — ``known_mask`` marks vocab
    entries that are dict KEYS (possibly with empty rows): the membership
    set the serving path must honor (rest_api/app/main.py:235)."""
    index = {name: i for i, name in enumerate(vocab)}
    v = len(vocab)
    rule_ids = np.full((v, k_max), -1, dtype=np.int32)
    rule_confs = np.zeros((v, k_max), dtype=np.float32)
    known_mask = np.zeros(v, dtype=bool)
    for name, row in rules.items():
        i = index.get(name)
        if i is None:
            continue
        known_mask[i] = True
        # resolve to known-vocab ids first, then truncate — so unknown
        # consequents neither punch -1 holes mid-row nor crowd out valid
        # lower-ranked ones
        resolved = [
            (index[other], conf) for other, conf in row.items() if other in index
        ]
        resolved.sort(key=lambda jc: -jc[1])
        for k, (j, conf) in enumerate(resolved[:k_max]):
            rule_ids[i, k] = j
            rule_confs[i, k] = conf
    return rule_ids, rule_confs, known_mask
