"""Artifact I/O — the wire contract between the mining job and the API.

The reference hands everything between its two workloads as pickle files on a
shared RWX PVC (reference: machine-learning/main.py:136-145 writes;
rest_api/app/main.py:52-80 reads). This module keeps that pickle contract
byte-compatible (same object shapes, same filenames) so either side of the
reference could interoperate with this rebuild, and adds:

- **atomic writes** (tmp file + ``os.replace``) — the reference rewrites
  artifacts in place, racing readers (acknowledged in its report); atomic
  rename removes the torn-read window without changing the protocol.
  ``KMLS_REFERENCE_RACE_COMPAT=1`` restores the reference's in-place
  ``pickle.dump`` for operators who need byte-compatible write behavior
  (the race included) — see ROADMAP's artifact-pipeline item;
- a **tensor-native artifact** (``.npz`` of the padded rule tensors) written
  alongside the pickle, so the serving engine can ``jax.device_put`` rule
  tensors straight into HBM without re-deriving them from the dict;
- an **integrity manifest** (``artifacts.manifest.json``, sizes + sha256
  per artifact) written after each artifact set, validated by the engine
  before a bundle publishes — a corrupt/torn artifact is detected BEFORE
  it can poison a reload, and the last-good bundle keeps serving;
- a **publication lease** (``publish.lease.json``: heartbeat + monotonic
  fencing token, :class:`PublicationLease`) so a zombie mining job left
  behind by the GitOps ``Replace`` resync cannot tear artifacts a newer
  run already published — the manifest records the fencing token of the
  generation that wrote it;
- a **durable-write discipline** (ISSUE 19): every publication-critical
  rename goes through :func:`durable_replace` — fsync the temp file,
  rename, fsync the parent directory — because ``os.replace`` alone
  orders nothing against the page cache: a node crash after the rename
  can reboot into a manifest whose bytes never hit the platter. Writes
  retry transient errnos (EIO/EAGAIN/ESTALE — the NFS gray-failure
  set) with bounded exponential backoff; ENOSPC never retries (the
  :func:`ensure_free_space` ladder + resumable exit own that), and an
  fsync failure never retries (after a failed fsync the kernel may have
  DROPPED the dirty pages — retrying reports durability that doesn't
  exist; see :class:`FsyncFailedError`). Every byte in or out feeds the
  IO-health monitor (``io/iohealth.py``) and every write/read/fsync
  passes a path-scoped fault gate (``faults.take_io``), so the whole
  artifact plane is chaos-coverable.
"""

from __future__ import annotations

import errno
import hashlib
import io
import json
import os
import pickle
import socket
import tempfile
import threading
import time
from typing import Any

import numpy as np

from .. import faults
from .iohealth import MONITOR

TENSOR_ARTIFACT_SUFFIX = ".tensors.npz"
MANIFEST_FILENAME = "artifacts.manifest.json"
QUARANTINE_DIRNAME = "quarantine"
# the second model family's artifact (mining/als.py writes it through the
# same manifest + lease-fenced publication path as the rule tensors; the
# engine loads it fail-soft — absent or corrupt means rules-only serving)
EMBEDDINGS_FILENAME = "embeddings.npz"
EMBEDDINGS_VERSION = 1
# continuous freshness (kmlserver_tpu/freshness/): incremental delta
# bundles published BETWEEN full re-mines. Each bundle carries the changed
# rule rows + tombstones of one incremental re-mine, bound to the base
# generation by token AND the published npz's sha256; the chain file lists
# the bundles in application order. Written through the same atomic +
# lease-fenced discipline as every other artifact; the invalidation token
# is deliberately NOT rewritten (a token rewrite means "full reload" —
# deltas are applied in place by engine.apply_pending_deltas()).
DELTA_STATE_FILENAME = "delta.state.json"
DELTA_BUNDLE_VERSION = 1
# quality loop (kmlserver_tpu/quality/): the offline ranking-evaluation
# report the optional `eval` pipeline phase publishes through the same
# manifest + lease-fenced path — held-out recall@k / MRR / coverage per
# serving mode plus the blend-weight sweep whose argmax the serving
# engine reads under KMLS_HYBRID_BLEND_WEIGHT=measured. Deterministic
# content (no timestamps), so a checkpoint-resumed publication writes
# byte-identical bytes.
QUALITY_REPORT_FILENAME = "quality.report.json"


def delta_bundle_filename(seq: int) -> str:
    return f"delta-{int(seq):06d}.bundle"


def delta_state_path(pickles_dir: str) -> str:
    return os.path.join(pickles_dir, DELTA_STATE_FILENAME)


class ArtifactIntegrityError(RuntimeError):
    """An artifact's bytes disagree with the manifest that shipped it.

    ``paths`` lists the offending files, so the engine can quarantine the
    right bytes instead of guessing."""

    def __init__(self, message: str, paths: list[str]):
        super().__init__(message)
        self.paths = paths


class StorageExhaustedError(RuntimeError):
    """The artifact volume is out of space even after reclamation.
    Resumable (exit 75): checkpoints are already on disk, so the retried
    job skips straight back to publication once an operator (or the
    cluster autoscaler) restores capacity."""


class FsyncFailedError(OSError):
    """``fsync`` reported failure on a publication-critical file.

    NEVER retried (the fsyncgate lesson): after a failed fsync, Linux
    marks the dirty pages clean — a second fsync returns success while
    the bytes were silently dropped. The only safe move is to abort the
    publication with the destination untouched and re-run from
    checkpoints, which rewrites the bytes from scratch."""


class IoStallError(OSError):
    """A deadline-bounded artifact read outlived its deadline — the
    hung-NFS-mount shape. The reader thread is parked (daemon) and the
    caller fails the operation instead of wedging; the engine turns this
    into a normal reload failure (backoff + last-good serving)."""


# the NFS/Filestore gray-failure errno set: worth one bounded retry
# ladder. ENOSPC is deliberately absent (the reclamation ladder owns
# it) and fsync failures bypass retries entirely (FsyncFailedError).
_TRANSIENT_ERRNOS = (errno.EIO, errno.EAGAIN, errno.ESTALE)


def _io_retries() -> int:
    from ..config import _getenv_int

    return max(_getenv_int("KMLS_IO_RETRIES", 2), 0)


def _io_retry_base_s() -> float:
    from ..config import _getenv_float

    return max(_getenv_float("KMLS_IO_RETRY_BASE_MS", 50.0), 0.0) / 1e3


def _fsync_file(path: str, dest_path: str) -> None:
    """fsync ``path`` (the temp file about to be renamed over
    ``dest_path``, which is the path fault scopes match against).
    Raises :class:`FsyncFailedError` — and only that — on failure."""
    try:
        stall = faults.take_io("io.fsync", dest_path)
        if stall > 0:
            time.sleep(stall)
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError as exc:
        raise FsyncFailedError(
            exc.errno or errno.EIO, f"fsync failed for {dest_path}: {exc}"
        ) from exc


def _fsync_dir(directory: str) -> None:
    """fsync the parent directory so the RENAME itself is durable. Best
    effort on refusal: some filesystems reject directory fsync (EINVAL)
    and the file fsync already carried the data — only the name's
    durability window remains, which a re-run closes."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def durable_replace(src: str, dst: str, *, durable: bool = True) -> None:
    """THE publication rename: fsync ``src``, ``os.replace`` it over
    ``dst``, fsync the parent directory. Every rename that publishes
    bytes readers trust (manifest, token, lease, delta bundles,
    checkpoints) must come through here — the atomic-write checker
    (``analysis/atomicwrite.py``) flags any rename that bypasses it.
    ``durable=False`` skips both fsyncs for best-effort writers
    (telemetry, quarantine moves) that still want the atomic rename."""
    if durable:
        _fsync_file(src, dst)
    os.replace(src, dst)
    if durable:
        _fsync_dir(os.path.dirname(os.path.abspath(dst)))


def _atomic_write_once(
    path: str, data: bytes, *, durable: bool, op: str
) -> None:
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp_path = tempfile.mkstemp(dir=directory, prefix=".tmp_", suffix=".part")
    torn = False
    start = time.monotonic()
    try:
        with os.fdopen(fd, "wb") as fh:
            try:
                stall = faults.take_io("io.write", path)
            except faults.TornWrite as exc:
                # a torn write IS the crash artifact: leave the short
                # temp file behind (reclaim_space collects orphans), the
                # destination is never touched
                torn = True
                fh.write(data[: exc.keep_bytes])
                raise
            if stall > 0:
                time.sleep(stall)
            fh.write(data)
        # mkstemp creates 0600; artifacts are read by the API replicas
        # (possibly a different uid on the shared volume)
        os.chmod(tmp_path, 0o644)
        durable_replace(tmp_path, path, durable=durable)
        MONITOR.note_latency(op, time.monotonic() - start)
    except BaseException:
        if not torn:
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
        raise


def _atomic_write_bytes(
    path: str, data: bytes, *, durable: bool = True, op: str = "write"
) -> None:
    """Atomic (and by default durable) write with the bounded transient-
    errno retry ladder. The retry set is deliberately narrow: EIO/
    EAGAIN/ESTALE (a flaky NFS mount) retry up to ``KMLS_IO_RETRIES``
    times with ``KMLS_IO_RETRY_BASE_MS`` exponential backoff; ENOSPC
    surfaces immediately (reclamation + resumable exit own it),
    :class:`FsyncFailedError` surfaces immediately (retrying a failed
    fsync masks dropped pages), torn writes surface immediately (they
    model a dead writer — nobody is left to retry)."""
    attempt = 0
    while True:
        try:
            _atomic_write_once(path, data, durable=durable, op=op)
            return
        except (FsyncFailedError, faults.TornWrite) as exc:
            MONITOR.note_error(op, exc.errno or 0)
            raise
        except OSError as exc:
            MONITOR.note_error(op, exc.errno or 0)
            if (
                exc.errno not in _TRANSIENT_ERRNOS
                or attempt >= _io_retries()
            ):
                raise
            MONITOR.note_retry()
            time.sleep(_io_retry_base_s() * (2**attempt))
            attempt += 1


def _read_bytes(
    path: str, *, op: str = "read", deadline_s: float | None = None
) -> bytes:
    """Read ``path`` through the fault gate + IO-health ledger.

    With ``deadline_s`` the read runs on a parked daemon thread and
    :class:`IoStallError` fires at the deadline — a hung NFS read must
    park the RELOAD in backoff (last-good keeps serving), not wedge the
    reload thread forever."""

    def _do_read() -> bytes:
        stall = faults.take_io("io.read", path)
        if stall > 0:
            time.sleep(stall)
        with open(path, "rb") as fh:
            return fh.read()

    start = time.monotonic()
    if deadline_s is None or deadline_s <= 0:
        try:
            data = _do_read()
        except OSError as exc:
            MONITOR.note_error(op, exc.errno or 0)
            raise
        MONITOR.note_latency(op, time.monotonic() - start)
        return data
    result: list[bytes] = []
    error: list[BaseException] = []

    def _worker() -> None:
        try:
            result.append(_do_read())
        except BaseException as exc:  # noqa: BLE001 — relayed below
            error.append(exc)

    thread = threading.Thread(
        target=_worker, name="kmls-io-read", daemon=True
    )
    thread.start()
    thread.join(deadline_s)
    if thread.is_alive():
        # the read's latency is AT LEAST the deadline — feed that floor
        # to the EWMA so a silently hung mount still convicts
        MONITOR.note_error(op, errno.ETIMEDOUT)
        MONITOR.note_latency(op, deadline_s)
        raise IoStallError(
            errno.ETIMEDOUT,
            f"read of {path} exceeded its {deadline_s:.3f}s deadline",
        )
    if error:
        exc = error[0]
        if isinstance(exc, OSError):
            MONITOR.note_error(op, exc.errno or 0)
        raise exc
    MONITOR.note_latency(op, time.monotonic() - start)
    return result[0]


def _reference_race_compat() -> bool:
    """``KMLS_REFERENCE_RACE_COMPAT=1`` restores the reference's in-place
    pickle writes — byte-compatible with machine-learning/main.py:136-145
    INCLUDING its acknowledged torn-read race. Read at call time (not
    import) so a test or an operator can flip it without re-importing."""
    from ..config import _getenv_bool

    return _getenv_bool("KMLS_REFERENCE_RACE_COMPAT", False)


def save_pickle(obj: Any, path: str) -> None:
    """Pickle ``obj`` to ``path`` atomically.

    Same role as the reference's ``save_pickle`` (machine-learning/main.py:136-145),
    which mkdirs the folder and ``pickle.dump``s in place; here the folder is
    created and the write is atomic — unless KMLS_REFERENCE_RACE_COMPAT
    opts back into the reference's in-place behavior.
    """
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if _reference_race_compat():
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "wb") as fh:
            fh.write(data)
        return
    _atomic_write_bytes(path, data)


def load_pickle(
    path: str, *, op: str = "read", deadline_s: float | None = None
) -> Any:
    return pickle.loads(_read_bytes(path, op=op, deadline_s=deadline_s))


def atomic_write_text(
    path: str, text: str, *, durable: bool = True, op: str = "write"
) -> None:
    _atomic_write_bytes(path, text.encode("utf-8"), durable=durable, op=op)


def read_text(
    path: str, *, op: str = "read", deadline_s: float | None = None
) -> str:
    return _read_bytes(path, op=op, deadline_s=deadline_s).decode("utf-8")


def tensor_artifact_path(recommendations_pickle_path: str) -> str:
    """Path of the npz rule-tensor artifact shadowing a recommendations pickle."""
    return recommendations_pickle_path + TENSOR_ARTIFACT_SUFFIX


# ---------- integrity manifest + quarantine ----------


def manifest_path(pickles_dir: str) -> str:
    return os.path.join(pickles_dir, MANIFEST_FILENAME)


def file_digest(path: str) -> dict[str, Any]:
    """→ ``{"bytes": n, "sha256": hex}`` (streamed; artifacts can be GBs)."""
    h = hashlib.sha256()
    n = 0
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
            n += len(chunk)
    return {"bytes": n, "sha256": h.hexdigest()}


def write_manifest(
    pickles_dir: str,
    filenames: list[str],
    token: str | None = None,
    fencing_token: int | None = None,
) -> str:
    """Write the integrity sidecar for an artifact set: size + sha256 per
    file, atomically, AFTER the artifacts themselves (the mining job calls
    this right before the invalidation-token rewrite, so any reader that
    sees the new token also sees a manifest matching the new bytes; a
    reader racing mid-update sees a mismatch, keeps its last-good bundle,
    and retries next poll — fail-soft, eventually consistent).

    ``token`` stamps the GENERATION this manifest describes (the
    invalidation-token value the miner is about to publish). Readers pass
    the current token to :func:`verify_files`, which validates only when
    the generations match — so a manifest left behind by this miner can
    never condemn fresh artifacts written by a manifest-less writer (the
    reference's job, or KMLS_WRITE_MANIFEST=0): that writer rewrites the
    token, the stale manifest stops matching, and validation steps aside
    instead of quarantining good bytes.

    ``fencing_token`` records the publication lease's monotonic fencing
    token (see :class:`PublicationLease`): which WRITER GENERATION
    produced this artifact set, so engine-side tooling and post-mortems
    can tell a zombie's manifest from the current writer's.

    Files that don't exist are skipped (e.g. the npz with
    KMLS_WRITE_TENSOR_ARTIFACT off). → the manifest path."""
    files: dict[str, Any] = {}
    for name in filenames:
        path = os.path.join(pickles_dir, name)
        if os.path.exists(path):
            files[name] = file_digest(path)
    out = manifest_path(pickles_dir)
    payload: dict[str, Any] = {
        "version": 1, "written_at": time.time(),
        "token": token, "files": files,
    }
    if fencing_token is not None:
        payload["fencing_token"] = fencing_token
    _atomic_write_bytes(
        out,
        json.dumps(payload, indent=1, sort_keys=True).encode("utf-8"),
    )
    return out


def load_manifest(
    pickles_dir: str, *, deadline_s: float | None = None
) -> dict[str, Any] | None:
    """The parsed manifest, or None when absent/unreadable — a PVC written
    by an older miner (or the reference) has no manifest, and integrity
    checking must degrade to the pre-manifest behavior there, not block."""
    path = manifest_path(pickles_dir)
    try:
        data = json.loads(
            _read_bytes(path, deadline_s=deadline_s).decode("utf-8")
        )
    except (OSError, ValueError):
        return None
    return data if isinstance(data.get("files"), dict) else None


def verify_files(
    pickles_dir: str, filenames: list[str], token: str | None = None
) -> list[str]:
    """Check ``filenames`` (relative to ``pickles_dir``) against the
    manifest → the list of paths whose on-disk bytes MISMATCH it (size or
    sha256). Files absent from the manifest, or missing on disk, are not
    mismatches (missing-on-disk surfaces as FileNotFoundError at load
    time, which the engine already treats as not-ready).

    ``token`` (the current invalidation-token value) gates validation to
    the manifest's own generation: a manifest stamped for a DIFFERENT
    token is stale — some other writer has published since — and
    validating fresh bytes against it would condemn good artifacts, so
    it is skipped entirely. ``token=None`` validates unconditionally
    (tests, offline checks)."""
    manifest = load_manifest(pickles_dir)
    if manifest is None:
        return []
    if token is not None and manifest.get("token") != token:
        return []
    bad: list[str] = []
    for name in filenames:
        entry = manifest["files"].get(name)
        path = os.path.join(pickles_dir, name)
        if entry is None or not os.path.exists(path):
            continue
        if os.path.getsize(path) != entry.get("bytes"):
            bad.append(path)
            continue
        if file_digest(path)["sha256"] != entry.get("sha256"):
            bad.append(path)
    return bad


def quarantine_file(path: str) -> str | None:
    """Move a corrupt artifact aside (``<pickles_dir>/quarantine/<name>.
    <epoch>``) so the next mining run writes fresh bytes and the bad ones
    stay inspectable. Never raises — a read-only volume must not turn a
    fail-soft reload into a crash. → the quarantine path, or None."""
    try:
        directory = os.path.dirname(os.path.abspath(path))
        qdir = os.path.join(directory, QUARANTINE_DIRNAME)
        os.makedirs(qdir, exist_ok=True)
        dest = os.path.join(
            qdir, f"{os.path.basename(path)}.{int(time.time())}"
        )
        # atomic but NOT durable: quarantine is forensics, not
        # publication — losing the move in a crash costs nothing
        durable_replace(path, dest, durable=False)
        return dest
    except OSError:
        return None


# ---------- the ENOSPC ladder (free space before publication) ----------


def disk_free_bytes(path: str) -> int:
    """Free bytes available to this process on ``path``'s filesystem."""
    stat = os.statvfs(path)
    return stat.f_bavail * stat.f_frsize


def estimate_publication_bytes(pickles_dir: str) -> int:
    """Expected size of the NEXT artifact set, estimated from the last
    manifest (generation-over-generation sizes move slowly — the vocab
    and rule caps are config-pinned). 0 with no manifest: the preflight
    then falls back to the operator floor alone."""
    manifest = load_manifest(pickles_dir)
    if manifest is None:
        return 0
    total = 0
    for entry in manifest.get("files", {}).values():
        try:
            total += int(entry.get("bytes", 0))
        except (TypeError, ValueError):
            continue
    return total


def reclaim_space(
    pickles_dir: str, extra_dirs: tuple[str, ...] | list[str] = ()
) -> int:
    """Delete every reclaimable byte the artifact plane owns → bytes
    freed (by file size, best effort, never raises).

    The ladder, cheapest-to-lose first: quarantined corpses (forensics
    only), orphaned ``.tmp_*.part`` files (dead writers' leftovers),
    then ``extra_dirs`` (retired checkpoint stores a caller explicitly
    hands over — NEVER the live store, which resume depends on).
    Delta bundles are deliberately NOT reclaimed here: pre-publication
    the serving fleet may still be applying them to last-good."""
    freed = 0

    def _unlink(path: str) -> None:
        nonlocal freed
        try:
            size = os.path.getsize(path)
            os.unlink(path)
            freed += size
        except OSError:
            pass

    qdir = os.path.join(pickles_dir, QUARANTINE_DIRNAME)
    try:
        for name in os.listdir(qdir):
            _unlink(os.path.join(qdir, name))
    except OSError:
        pass
    try:
        for name in os.listdir(pickles_dir):
            if name.startswith(".tmp_") and name.endswith(".part"):
                _unlink(os.path.join(pickles_dir, name))
    except OSError:
        pass
    for directory in extra_dirs:
        try:
            entries = os.listdir(directory)
        except OSError:
            continue
        for name in entries:
            path = os.path.join(directory, name)
            if os.path.isfile(path):
                _unlink(path)
    return freed


def ensure_free_space(
    pickles_dir: str,
    min_free_bytes: int,
    extra_dirs: tuple[str, ...] | list[str] = (),
) -> int:
    """The publication preflight: require ``min_free_bytes`` free on the
    artifact volume, reclaiming (:func:`reclaim_space`) if short, and
    raising :class:`StorageExhaustedError` (→ resumable exit 75) if
    still short — so publication NEVER starts a write it cannot finish:
    the failure mode is \"last-good keeps serving, job retries under
    k8s backoff\", never a torn artifact set. → free bytes after."""
    if min_free_bytes <= 0:
        return 0
    # first run: the artifact dir may not exist yet — the preflight runs
    # before any write, and the writer owns creating it anyway
    os.makedirs(pickles_dir, exist_ok=True)
    free = disk_free_bytes(pickles_dir)
    MONITOR.watch_disk(pickles_dir)
    if free >= min_free_bytes:
        return free
    freed = reclaim_space(pickles_dir, extra_dirs)
    free = disk_free_bytes(pickles_dir)
    if free >= min_free_bytes:
        print(
            f"Artifact volume short on space — reclaimed {freed} bytes "
            f"({free} now free, {min_free_bytes} required)"
        )
        return free
    raise StorageExhaustedError(
        f"artifact volume has {free} free bytes after reclaiming {freed}; "
        f"publication needs {min_free_bytes} — exiting resumable rather "
        "than risking a torn publication"
    )


# ---------- lease-fenced publication ----------


LEASE_FILENAME = "publish.lease.json"


class LeaseHeldError(RuntimeError):
    """Another writer holds a live publication lease. Resumable: the k8s
    Job retries after backoff, and wins once the holder finishes or its
    heartbeat expires."""


class LeaseLostError(RuntimeError):
    """This writer's lease was superseded (a newer fencing token is on
    disk) — it is a ZOMBIE and must not publish."""


def lease_path(pickles_dir: str) -> str:
    return os.path.join(pickles_dir, LEASE_FILENAME)


def _read_lease(pickles_dir: str) -> dict[str, Any] | None:
    try:
        data = json.loads(
            _read_bytes(lease_path(pickles_dir)).decode("utf-8")
        )
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


class PublicationLease:
    """Heartbeat lease + monotonic fencing token over the artifact set.

    The reference's GitOps loop recreates the mining Job with ArgoCD
    ``Force=true,Replace=true`` — which can leave a ZOMBIE of the previous
    run alive (slow termination, a hung TPU host) while its replacement is
    already mining. Without fencing, the zombie's late artifact writes
    would tear or roll back what the newer run published. The fix is the
    classic fencing-token protocol:

    - :meth:`acquire` reads the lease file; a live lease (not released,
      heartbeat younger than its TTL) → :class:`LeaseHeldError` (the
      caller exits resumable and retries under k8s backoff). A dead or
      released lease is taken over with ``fencing_token = previous + 1``
      — the token only ever increases, across arbitrarily many writer
      generations.
    - a background heartbeat (:meth:`start_heartbeat`) refreshes
      ``heartbeat_at`` every ``ttl/3`` so a LIVE writer is never
      expropriated mid-mine, no matter how long the mine takes.
    - :meth:`check` re-reads the file and raises :class:`LeaseLostError`
      the moment a newer (owner, token) is on disk. The pipeline calls it
      immediately before its first artifact write AND immediately before
      the invalidation-token rewrite, so a fenced zombie aborts without
      having torn anything.

    The lease file lives on the same PVC as the artifacts it guards
    (atomic tmp+rename writes). Acquisition is read-modify-write with a
    read-back confirmation — not a true CAS, which a shared POSIX FS
    cannot provide — so two same-instant acquirers may both think they
    won briefly; the loser's next :meth:`check`/heartbeat sees the other
    (owner, token) on disk and self-fences. That is exactly the fail-safe
    direction: over-fencing costs a retry, under-fencing would cost data.
    """

    def __init__(
        self,
        pickles_dir: str,
        owner: str,
        fencing_token: int,
        ttl_s: float,
        heartbeat_interval_s: float | None = None,
        stall_fraction: float | None = None,
    ):
        from ..config import _getenv_float

        self.pickles_dir = pickles_dir
        self.owner = owner
        self.fencing_token = fencing_token
        self.ttl_s = ttl_s
        self.heartbeat_interval_s = heartbeat_interval_s or max(ttl_s / 3, 0.05)
        # self-fencing threshold: a heartbeat WRITE that takes longer
        # than this fraction of the TTL means the mount is hung badly
        # enough that our on-disk heartbeat may already look dead to a
        # challenger — assume expropriated rather than risk two writers
        self.stall_fraction = (
            stall_fraction
            if stall_fraction is not None
            else _getenv_float("KMLS_LEASE_STALL_FRACTION", 0.5)
        )
        self.lost = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @classmethod
    def acquire(
        cls,
        pickles_dir: str,
        ttl_s: float = 60.0,
        owner: str | None = None,
        heartbeat_interval_s: float | None = None,
        stall_fraction: float | None = None,
    ) -> "PublicationLease":
        """Take the publication lease or raise :class:`LeaseHeldError`."""
        owner = owner or (
            f"{socket.gethostname()}:{os.getpid()}:{os.urandom(4).hex()}"
        )
        current = _read_lease(pickles_dir)
        prev_token = 0
        if current is not None:
            prev_token = int(current.get("fencing_token", 0))
            age = time.time() - float(current.get("heartbeat_at", 0.0))
            live = not current.get("released") and age < float(
                current.get("ttl_s", ttl_s)
            )
            if live and current.get("owner") != owner:
                raise LeaseHeldError(
                    f"publication lease held by {current.get('owner')!r} "
                    f"(token {prev_token}, heartbeat {age:.1f}s ago, ttl "
                    f"{current.get('ttl_s')}s)"
                )
        lease = cls(
            pickles_dir, owner, prev_token + 1, ttl_s, heartbeat_interval_s,
            stall_fraction=stall_fraction,
        )
        lease._write()
        # read-back: in a same-instant race the later rename wins; the
        # loser must find out NOW, not at publication time
        lease.check()
        return lease

    def _write(self, released: bool = False) -> None:
        _atomic_write_bytes(
            lease_path(self.pickles_dir),
            json.dumps(
                {
                    "version": 1,
                    "owner": self.owner,
                    "fencing_token": self.fencing_token,
                    "ttl_s": self.ttl_s,
                    "heartbeat_at": time.time(),
                    "released": released,
                },
                indent=1, sort_keys=True,
            ).encode("utf-8"),
        )

    def check(self) -> None:
        """Raise :class:`LeaseLostError` unless the on-disk lease is still
        (our owner, our token) and unreleased. Sticky: once lost, always
        lost — a released lease is lost too (this handle gave it up; any
        later write through it would race the next acquirer)."""
        if not self.lost:
            current = _read_lease(self.pickles_dir)
            if (
                current is not None
                and current.get("owner") == self.owner
                and int(current.get("fencing_token", -1)) == self.fencing_token
                and not current.get("released")
            ):
                return
            self.lost = True
        raise LeaseLostError(
            f"publication lease (token {self.fencing_token}) superseded — "
            "this writer is a zombie and must not publish"
        )

    def heartbeat(self) -> None:
        """One ownership-checked heartbeat (raises when fenced).

        SELF-FENCES on its own slowness: if the heartbeat write stalls
        past ``stall_fraction·ttl_s`` (a hung NFS mount), this writer
        cannot know whether its on-disk heartbeat is still younger than
        the TTL — a challenger may already hold a newer token. The only
        safe belief is "lost": mark sticky-lost and raise, so the
        pipeline's next :meth:`check` aborts resumable BEFORE any
        artifact write a real holder wouldn't have raced."""
        self.check()
        start = time.monotonic()
        self._write()
        elapsed = time.monotonic() - start
        if self.stall_fraction > 0 and elapsed > self.ttl_s * self.stall_fraction:
            self.lost = True
            raise LeaseLostError(
                f"lease heartbeat stalled {elapsed:.2f}s (> "
                f"{self.stall_fraction:.2f}·ttl {self.ttl_s:.2f}s) — this "
                "writer cannot prove it still holds the lease and "
                "self-fences"
            )

    def start_heartbeat(self) -> None:
        """Refresh the lease every ``heartbeat_interval_s`` until
        :meth:`stop_heartbeat` — or until fenced, which stops silently
        (the publication-path :meth:`check` raises the loud error)."""
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self.heartbeat_interval_s):
                try:
                    self.heartbeat()
                except (LeaseLostError, OSError):
                    return

        self._thread = threading.Thread(
            target=loop, name="kmls-lease-heartbeat", daemon=True
        )
        self._thread.start()

    def stop_heartbeat(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def release(self) -> None:
        """Mark the lease released (token RETAINED — the next acquirer
        still increments past it; monotonicity is the whole point).

        Called on BOTH the success path and a Python-level abort (the
        pipeline's except block): an exiting process provably writes
        nothing more, so handing the lease back immediately beats making
        its own k8s-restarted successor wait out the TTL. Only a hard
        kill (SIGKILL preemption) leaves the lease to expiry.

        Stops the heartbeat thread FIRST: a beat racing the release could
        land after ``released: true`` and resurrect the lease, making the
        next acquirer wait out the TTL against a dead owner."""
        self.stop_heartbeat()
        self.check()
        self._write(released=True)


def save_rule_tensors(
    path: str,
    *,
    vocab: list[str],
    rule_ids: np.ndarray,
    rule_counts: np.ndarray,
    item_counts: np.ndarray,
    n_playlists: int,
    min_support: float,
    mode: str = "support",
    min_confidence: float = 0.0,
    rule_confs64: np.ndarray | None = None,
) -> None:
    """Write the padded rule tensors + vocabulary as one ``.npz``.

    ``rule_ids``    int32 (V, K_max) — consequent track ids, -1 padding.
    ``rule_counts`` int32 (V, K_max) — co-occurrence COUNTS (not floats:
                    consumers re-derive confidences with the same float64
                    arithmetic as the pickle path, so the two artifacts can
                    never drift).
    ``item_counts`` int32 (V,) — singleton supports; items with
                    count ≥ ceil(min_support·P) are the rule-dict key set
                    (including empty rows — see ops/rules.py).
    ``rule_confs64`` float64 (V, K_max), only when confidences carry
                    per-rule denominators (triple-antecedent merge) and so
                    cannot be re-derived from counts.
    """
    if rule_ids.shape != rule_counts.shape:
        raise ValueError(f"rule_ids {rule_ids.shape} != rule_counts {rule_counts.shape}")
    if rule_ids.shape[0] != len(vocab) or len(item_counts) != len(vocab):
        raise ValueError(
            f"rows {rule_ids.shape[0]}/{len(item_counts)} != vocab size {len(vocab)}"
        )
    arrays = dict(
        vocab=np.asarray(vocab, dtype=object),
        rule_ids=rule_ids.astype(np.int32),
        rule_counts=rule_counts.astype(np.int32),
        item_counts=item_counts.astype(np.int32),
        n_playlists=np.int64(n_playlists),
        min_support=np.float64(min_support),
        mode=np.asarray(mode),
        min_confidence=np.float64(min_confidence),
    )
    if rule_confs64 is not None:
        if rule_confs64.shape != rule_ids.shape:
            raise ValueError(
                f"rule_confs64 {rule_confs64.shape} != rule_ids {rule_ids.shape}"
            )
        arrays["rule_confs64"] = rule_confs64.astype(np.float64)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    _atomic_write_bytes(path, buf.getvalue())


def load_rule_tensors(
    path: str, *, deadline_s: float | None = None
) -> dict[str, Any]:
    """Load the npz artifact, deriving serving-ready float32 confidences.
    The BYTES come through :func:`_read_bytes` (fault gate + IO health +
    optional deadline); parsing happens off-disk on a BytesIO."""
    from ..ops.rules import derive_confs

    raw = io.BytesIO(_read_bytes(path, deadline_s=deadline_s))
    with np.load(raw, allow_pickle=True) as npz:
        rule_counts = npz["rule_counts"]
        item_counts = npz["item_counts"]
        n_playlists = int(npz["n_playlists"])
        mode = str(npz["mode"])
        confs64 = npz["rule_confs64"] if "rule_confs64" in npz.files else None
        rule_ids = npz["rule_ids"]
        if confs64 is None and bool(((rule_ids >= 0) & (rule_counts <= 0)).any()):
            # valid rules with zero counts can only come from a
            # triple-merged artifact whose rule_confs64 was stripped —
            # re-deriving would silently turn every confidence into 0.0
            raise ValueError(
                f"{path}: rules present with zero counts and no rule_confs64 "
                f"— corrupt or stripped artifact"
            )
        confs = (
            confs64.astype(np.float32)
            if confs64 is not None
            else derive_confs(rule_counts, item_counts, n_playlists, mode)
        )
        return {
            "vocab": [str(s) for s in npz["vocab"]],
            "rule_ids": npz["rule_ids"],
            "rule_counts": rule_counts,
            "rule_confs": confs,
            "rule_confs64": confs64,
            "item_counts": item_counts,
            "n_playlists": n_playlists,
            "min_support": float(npz["min_support"]),
            "mode": mode,
            "min_confidence": float(npz["min_confidence"]),
        }


def embeddings_artifact_path(pickles_dir: str) -> str:
    return os.path.join(pickles_dir, EMBEDDINGS_FILENAME)


def save_embeddings(
    path: str,
    *,
    vocab: list[str],
    item_factors: np.ndarray,
    rank: int,
    iters: int,
    reg: float,
    final_loss: float | None = None,
) -> None:
    """Write the embedding artifact as one atomic ``.npz``.

    ``item_factors`` f32 (V, rank), rows L2-normalized — serving-ready:
    the engine ``device_put``s them straight into HBM and the lookup is
    cosine top-k (``ops/embed.py``). ``vocab`` is the EMBEDDING id space,
    which is the full encode-phase vocabulary — deliberately broader than
    the (possibly Apriori-pruned) rule vocabulary, because long-tail
    coverage is the whole point of the second model family. The hybrid
    merge happens at the name level, so the two id spaces never need to
    agree."""
    if item_factors.ndim != 2 or item_factors.shape[0] != len(vocab):
        raise ValueError(
            f"item_factors {item_factors.shape} does not match vocab size "
            f"{len(vocab)}"
        )
    arrays = dict(
        version=np.int64(EMBEDDINGS_VERSION),
        vocab=np.asarray(vocab, dtype=object),
        item_factors=item_factors.astype(np.float32),
        rank=np.int64(rank),
        iters=np.int64(iters),
        reg=np.float64(reg),
    )
    if final_loss is not None:
        arrays["final_loss"] = np.float64(final_loss)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    _atomic_write_bytes(path, buf.getvalue())


def remove_embeddings(pickles_dir: str) -> bool:
    """Retire the embedding artifact (an embed-DISABLED publication must
    not leave a previous generation's embeddings on disk, where the fresh
    manifest would re-bless them against new rules). → True if removed."""
    try:
        os.unlink(embeddings_artifact_path(pickles_dir))
        return True
    except FileNotFoundError:
        return False


def load_embeddings(
    path: str, *, deadline_s: float | None = None
) -> dict[str, Any]:
    """Load + validate the embedding artifact. Raises ``ValueError`` on
    any structural problem (shape mismatch, non-finite factors, unknown
    format version) — the engine treats every raise here as "corrupt"
    and serves rules-only, so validation must be strict enough that a
    torn file can never publish garbage similarities."""
    raw = io.BytesIO(_read_bytes(path, deadline_s=deadline_s))
    with np.load(raw, allow_pickle=True) as npz:
        if "item_factors" not in npz.files or "vocab" not in npz.files:
            raise ValueError(f"{path}: not an embedding artifact")
        version = int(npz["version"]) if "version" in npz.files else 0
        if version != EMBEDDINGS_VERSION:
            raise ValueError(
                f"{path}: embedding artifact version {version} != "
                f"{EMBEDDINGS_VERSION}"
            )
        vocab = [str(s) for s in npz["vocab"]]
        factors = np.asarray(npz["item_factors"], dtype=np.float32)
        if factors.ndim != 2 or factors.shape[0] != len(vocab):
            raise ValueError(
                f"{path}: item_factors {factors.shape} does not match "
                f"vocab size {len(vocab)}"
            )
        if factors.shape[1] < 1 or not np.isfinite(factors).all():
            raise ValueError(f"{path}: non-finite or rank-0 item factors")
        return {
            "vocab": vocab,
            "item_factors": factors,
            "rank": int(npz["rank"]) if "rank" in npz.files else factors.shape[1],
            "iters": int(npz["iters"]) if "iters" in npz.files else 0,
            "reg": float(npz["reg"]) if "reg" in npz.files else 0.0,
        }


def quality_report_path(pickles_dir: str) -> str:
    return os.path.join(pickles_dir, QUALITY_REPORT_FILENAME)


def save_quality_report(pickles_dir: str, report: dict[str, Any]) -> str:
    """Write the quality report atomically with SORTED keys and no
    whitespace jitter — byte-stable for identical content, which is what
    lets the mining chaos suite's bit-identity bar (manifest sha256)
    cover a checkpoint-resumed eval publication."""
    path = quality_report_path(pickles_dir)
    _atomic_write_bytes(
        path,
        json.dumps(report, indent=1, sort_keys=True).encode("utf-8"),
    )
    return path


def load_quality_report(pickles_dir: str) -> dict[str, Any] | None:
    """The parsed quality report, or None when absent/unreadable — the
    serving engine treats every None as 'no measurement published' and
    the measured blend mode fails safe to its default."""
    try:
        data = json.loads(
            _read_bytes(quality_report_path(pickles_dir)).decode("utf-8")
        )
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def remove_quality_report(pickles_dir: str) -> bool:
    """Retire the quality report (an eval-DISABLED publication must not
    leave a previous generation's measurements on disk, where the fresh
    manifest would re-bless a blend optimum measured against models that
    no longer serve). → True if removed."""
    try:
        os.unlink(quality_report_path(pickles_dir))
        return True
    except FileNotFoundError:
        return False


def rules_dict_from_tensors(loaded: dict[str, Any]) -> dict[str, dict[str, float]]:
    """Expand a :func:`load_rule_tensors` result into the reference's pickle
    object shape ``{song_name: {other_song_name: confidence}}`` (the object
    ``rest_api/app/main.py:68-76`` unpickles), via the one canonical
    expansion in ``ops/rules.py`` — guaranteeing npz→dict equals the dict
    the mining job pickled."""
    from ..ops.rules import expand_rules_dict

    return expand_rules_dict(
        loaded["vocab"],
        loaded["rule_ids"],
        loaded["rule_counts"],
        loaded["item_counts"],
        n_playlists=loaded["n_playlists"],
        min_support=loaded["min_support"],
        mode=loaded["mode"],
        rule_confs64=loaded.get("rule_confs64"),
    )


# ---------- continuous-freshness delta bundles ----------


def save_delta_bundle(
    path: str,
    *,
    seq: int,
    base_token: str,
    base_npz_sha256: str,
    n_playlists: int,
    min_count: int,
    vocab: list[str],
    changed_rows: np.ndarray,
    changed_rule_ids: np.ndarray,
    changed_rule_counts: np.ndarray,
    changed_item_counts: np.ndarray,
    tombstones: list[str],
) -> None:
    """Write one versioned delta bundle atomically.

    ``vocab`` is the COMPLETE new published row space (the possibly
    Apriori-pruned vocabulary after the incremental rows landed) — row
    identity travels by NAME, so applying a delta re-maps unchanged base
    rows into this ordering and overwrites ``changed_rows`` (indices into
    ``vocab``) with the re-mined tensors. ``tombstones`` are base-vocab
    names absent from the new vocabulary (their rows cease to exist).
    ``base_npz_sha256`` binds the bundle to the exact base artifact bytes
    it patches: a reader holding any other generation must reject it."""
    if changed_rule_ids.shape != changed_rule_counts.shape:
        raise ValueError(
            f"changed_rule_ids {changed_rule_ids.shape} != "
            f"changed_rule_counts {changed_rule_counts.shape}"
        )
    if len(changed_rows) != changed_rule_ids.shape[0] or len(
        changed_rows
    ) != len(changed_item_counts):
        raise ValueError(
            f"changed row count mismatch: {len(changed_rows)} rows vs "
            f"{changed_rule_ids.shape[0]} id rows / "
            f"{len(changed_item_counts)} item counts"
        )
    arrays = dict(
        version=np.int64(DELTA_BUNDLE_VERSION),
        seq=np.int64(seq),
        base_token=np.asarray(base_token),
        base_npz_sha256=np.asarray(base_npz_sha256),
        n_playlists=np.int64(n_playlists),
        min_count=np.int64(min_count),
        vocab=np.asarray(vocab, dtype=object),
        changed_rows=np.asarray(changed_rows, dtype=np.int32),
        changed_rule_ids=changed_rule_ids.astype(np.int32),
        changed_rule_counts=changed_rule_counts.astype(np.int32),
        changed_item_counts=np.asarray(
            changed_item_counts, dtype=np.int32
        ),
        tombstones=np.asarray(list(tombstones), dtype=object),
    )
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    _atomic_write_bytes(path, buf.getvalue())


def load_delta_bundle(path: str, expect_sha256: str | None = None) -> dict[str, Any]:
    """Load + strictly validate a delta bundle. Raises ``ValueError`` on
    ANY structural problem (torn bytes, wrong version, out-of-range row
    indices, chain-entry digest mismatch) — the engine treats every raise
    as "rejected": the base generation keeps serving, never a 5xx."""
    if expect_sha256 is not None:
        digest = file_digest(path)["sha256"]
        if digest != expect_sha256:
            raise ValueError(
                f"{path}: bundle sha256 {digest} != chain entry "
                f"{expect_sha256} (torn or tampered delta)"
            )
    raw = io.BytesIO(_read_bytes(path))
    with np.load(raw, allow_pickle=True) as npz:
        required = (
            "version", "seq", "base_token", "base_npz_sha256",
            "n_playlists", "min_count", "vocab", "changed_rows",
            "changed_rule_ids", "changed_rule_counts",
            "changed_item_counts", "tombstones",
        )
        missing = [k for k in required if k not in npz.files]
        if missing:
            raise ValueError(f"{path}: not a delta bundle (missing {missing})")
        version = int(npz["version"])
        if version != DELTA_BUNDLE_VERSION:
            raise ValueError(
                f"{path}: delta bundle version {version} != "
                f"{DELTA_BUNDLE_VERSION}"
            )
        vocab = [str(s) for s in npz["vocab"]]
        rows = np.asarray(npz["changed_rows"], dtype=np.int32)
        ids = np.asarray(npz["changed_rule_ids"], dtype=np.int32)
        counts = np.asarray(npz["changed_rule_counts"], dtype=np.int32)
        items = np.asarray(npz["changed_item_counts"], dtype=np.int32)
        if ids.shape != counts.shape or ids.ndim != 2:
            raise ValueError(f"{path}: malformed changed-row tensors")
        if len(rows) != ids.shape[0] or len(rows) != len(items):
            raise ValueError(f"{path}: changed-row count mismatch")
        if len(rows) and (rows.min() < 0 or rows.max() >= len(vocab)):
            raise ValueError(f"{path}: changed_rows outside the new vocab")
        if len(rows) != len(set(rows.tolist())):
            raise ValueError(f"{path}: duplicate changed_rows")
        if ids.size and ids.max() >= len(vocab):
            raise ValueError(f"{path}: rule ids outside the new vocab")
        return {
            "version": version,
            "seq": int(npz["seq"]),
            "base_token": str(npz["base_token"]),
            "base_npz_sha256": str(npz["base_npz_sha256"]),
            "n_playlists": int(npz["n_playlists"]),
            "min_count": int(npz["min_count"]),
            "vocab": vocab,
            "changed_rows": rows,
            "changed_rule_ids": ids,
            "changed_rule_counts": counts,
            "changed_item_counts": items,
            "tombstones": [str(s) for s in npz["tombstones"]],
        }


def read_delta_state(pickles_dir: str) -> dict[str, Any] | None:
    """The parsed delta chain file, or None when absent/unreadable (no
    chain is the normal state between full publications)."""
    try:
        data = json.loads(
            _read_bytes(delta_state_path(pickles_dir)).decode("utf-8")
        )
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or not isinstance(data.get("entries"), list):
        return None
    return data


def write_delta_state(
    pickles_dir: str,
    base_token: str,
    base_npz_sha256: str,
    entries: list[dict[str, Any]],
) -> str:
    """Atomically (re)write the delta chain file. Written AFTER the bundle
    bytes it references (same ordering discipline as manifest-then-token):
    a reader that sees a chain entry can always find verified bundle
    bytes, and a reader racing mid-publish simply retries next poll."""
    out = delta_state_path(pickles_dir)
    _atomic_write_bytes(
        out,
        json.dumps(
            {
                "version": 1,
                "base_token": base_token,
                "base_npz_sha256": base_npz_sha256,
                "entries": entries,
            },
            indent=1, sort_keys=True,
        ).encode("utf-8"),
    )
    return out


def retire_delta_chain(pickles_dir: str) -> int:
    """Remove the delta chain + its bundles (a FULL publication supersedes
    every delta of the previous generation — a stale chain would fail its
    base-token binding anyway, but dead bytes on the PVC invite operator
    confusion). Never raises. → files removed."""
    removed = 0
    try:
        names = os.listdir(pickles_dir)
    except OSError:
        return 0
    for name in names:
        if name == DELTA_STATE_FILENAME or (
            name.startswith("delta-") and name.endswith(".bundle")
        ):
            try:
                os.unlink(os.path.join(pickles_dir, name))
                removed += 1
            except OSError:
                pass
    return removed


def tensors_from_rules_dict(
    rules: dict[str, dict[str, float]],
    vocab: list[str],
    k_max: int,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Inverse direction for loading legacy pickles (e.g. written by the
    reference job) into the device-resident layout. Returns
    ``(rule_ids, rule_confs, known_mask)`` — ``known_mask`` marks vocab
    entries that are dict KEYS (possibly with empty rows): the membership
    set the serving path must honor (rest_api/app/main.py:235)."""
    index = {name: i for i, name in enumerate(vocab)}
    v = len(vocab)
    rule_ids = np.full((v, k_max), -1, dtype=np.int32)
    rule_confs = np.zeros((v, k_max), dtype=np.float32)
    known_mask = np.zeros(v, dtype=bool)
    for name, row in rules.items():
        i = index.get(name)
        if i is None:
            continue
        known_mask[i] = True
        # resolve to known-vocab ids first, then truncate — so unknown
        # consequents neither punch -1 holes mid-row nor crowd out valid
        # lower-ranked ones
        resolved = [
            (index[other], conf) for other, conf in row.items() if other in index
        ]
        resolved.sort(key=lambda jc: -jc[1])
        for k, (j, conf) in enumerate(resolved[:k_max]):
            rule_ids[i, k] = j
            rule_confs[i, k] = conf
    return rule_ids, rule_confs, known_mask
