"""IO-health monitor — the storage counterpart of the peer gray-failure
spine (ISSUE 18).

The artifact plane lives on a ReadWriteMany PVC which in practice means
NFS (Filestore, EFS, …), and the canonical NFS failure is not ENOENT —
it is *slow*: reads that take hundreds of milliseconds, writes that hang
for seconds, a mount that is alive enough to never error but sick enough
to wedge any thread that touches it. This module gives the artifact
plane the same observability the fleet router gives peers: a per-
operation latency EWMA (token poll, reload reads, publication writes,
fsync), an error/retry ledger, a free-space gauge, and a hysteresis
"storage slow" conviction that the app surfaces as a ready-but-degraded
``/readyz`` reason (``storage-slow``) — degraded, NOT unready, because
serving runs entirely from memory and a slow disk must never knock a
healthy replica out of the load balancer.

Conviction mirrors the peer-health constants: EWMA alpha 0.2, a minimum
sample count before any conviction (a single cold-cache read must not
flip the gauge), convict when any op's EWMA crosses
``KMLS_IO_SLOW_MS``, clear only when every op falls back under half the
threshold (hysteresis — a mount bouncing around the threshold reads as
one conviction, not a pulse train).
"""

from __future__ import annotations

import os
import threading
import time

from ..config import _getenv_float

# Same spine constants as serving/fleet.py's peer-health machine: a
# 0.2-alpha EWMA converges in a handful of observations while one
# outlier moves it only 20%, and 8 samples is enough history that a
# conviction means a *pattern*, not a cold cache.
EWMA_ALPHA = 0.2
MIN_SAMPLES = 8
DEFAULT_SLOW_MS = 250.0
# how stale the cached free-space reading may get before the next
# artifact operation re-runs statvfs
DISK_REFRESH_S = 5.0


class IoHealthMonitor:
    """Latency/error/space ledger for one process's artifact plane."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ewma_s: dict[str, float] = {}
        self._samples: dict[str, int] = {}
        self._errors: dict[tuple[str, int], int] = {}
        self._retries = 0
        self._slow = False
        self._disk_path: str | None = None
        self._disk_free: int | None = None
        self._disk_free_at: float | None = None

    # ---------- observations ----------

    def note_latency(self, op: str, seconds: float) -> None:
        """Record one operation's wall clock and re-evaluate the slow
        conviction. ``op`` ∈ token_poll / read / write / fsync."""
        seconds = max(seconds, 0.0)
        slow_s = _getenv_float("KMLS_IO_SLOW_MS", DEFAULT_SLOW_MS) / 1e3
        # every observation comes from a thread already touching the
        # PVC — the safe place to keep the free-space cache warm
        self.refresh_disk_free()
        with self._lock:
            prev = self._ewma_s.get(op)
            self._ewma_s[op] = (
                seconds
                if prev is None
                else prev + EWMA_ALPHA * (seconds - prev)
            )
            self._samples[op] = self._samples.get(op, 0) + 1
            convicted = any(
                ewma > slow_s and self._samples.get(name, 0) >= MIN_SAMPLES
                for name, ewma in self._ewma_s.items()
            )
            if convicted:
                self._slow = True
            elif self._slow and all(
                ewma < slow_s / 2 for ewma in self._ewma_s.values()
            ):
                self._slow = False

    def note_error(self, op: str, err_errno: int) -> None:
        with self._lock:
            key = (op, err_errno)
            self._errors[key] = self._errors.get(key, 0) + 1

    def note_retry(self) -> None:
        with self._lock:
            self._retries += 1

    # ---------- disk space ----------

    def watch_disk(self, path: str) -> None:
        """Point the free-space gauge at the artifact mount. Callers are
        PVC-touching threads (preflight, engine load), so the immediate
        first refresh is safe here."""
        with self._lock:
            self._disk_path = path
            self._disk_free_at = None  # force the refresh below
        self.refresh_disk_free()

    def refresh_disk_free(self) -> int | None:
        """Re-run ``statvfs`` on the watched mount and cache the result
        (rate-limited to one probe per :data:`DISK_REFRESH_S`). Only
        ever called from the worker threads that already touch the PVC —
        NEVER from the event loop: on a sick NFS mount ``statvfs`` can
        hang for seconds, the exact gray failure this monitor exists to
        convict (the loopblock checker pins the loop side to the cached
        :meth:`disk_free_bytes` read)."""
        with self._lock:
            path = self._disk_path
            stamp = self._disk_free_at
            cached = self._disk_free
        if not path:
            return None
        now = time.monotonic()
        if stamp is not None and now - stamp < DISK_REFRESH_S:
            return cached
        try:
            stat = os.statvfs(path)
            free: int | None = stat.f_bavail * stat.f_frsize
        except OSError:
            free = None
        with self._lock:
            self._disk_free = free
            self._disk_free_at = now
        return free

    def disk_free_bytes(self) -> int | None:
        """Last cached free-space reading — loop-safe: never touches the
        disk (see :meth:`refresh_disk_free`)."""
        with self._lock:
            return self._disk_free

    # ---------- state reads ----------

    def storage_slow(self) -> bool:
        with self._lock:
            return self._slow

    def snapshot(self) -> dict[str, object]:
        """One coherent view for the metrics renderer."""
        with self._lock:
            latency = dict(self._ewma_s)
            errors = dict(self._errors)
            retries = self._retries
            slow = self._slow
        return {
            "latency_s": latency,
            "errors": errors,
            "retries": retries,
            "storage_slow": slow,
            "disk_free_bytes": self.disk_free_bytes(),
        }

    def reset(self) -> None:
        """Forget everything (test teardown)."""
        with self._lock:
            self._ewma_s.clear()
            self._samples.clear()
            self._errors.clear()
            self._retries = 0
            self._slow = False
            self._disk_path = None
            self._disk_free = None
            self._disk_free_at = None


# One process-wide monitor: artifacts.py feeds it from whichever thread
# touches the PVC; the app renders it. Same singleton shape as the
# faults switchboard.
MONITOR = IoHealthMonitor()
