"""Dataset registry, run-history rotation, and the invalidation token.

This is the reference's inter-run scheduling state (reference:
machine-learning/main.py:315-411): a ``datasets_list.txt`` enumerating the
discovered CSVs, a ``dataset_history.csv`` append-only run log whose last line
drives a wraparound index rotation (so alternate runs mine alternate
datasets — the system's pseudo-cron state machine), and the
``last_execution.txt`` token whose rewrite is THE cross-workload cache
invalidation signal every API replica polls
(reference: machine-learning/main.py:406-408 → rest_api/app/main.py:82-97).

The file formats are byte-compatible with the reference so either side could
run against a PVC the other populated:
- ``dataset_history.csv`` has header ``time,dataset_index,dataset_file`` and
  rows ``{time},{index},{file}`` (reference: machine-learning/main.py:394-405);
- first run discovers datasets by glob and persists the sorted list;
- each run reads the history's last index, adds 1, wraps to ``BASE_INDEX``
  when past the end (reference: machine-learning/main.py:386-387);
- each run appends its row and rewrites the token.
"""

from __future__ import annotations

import glob as _glob
import os

from ..config import BASE_INDEX, MiningConfig
from ..utils.timeutil import get_current_time_str_precise
from .artifacts import atomic_write_text, read_text

HISTORY_HEADER = "time,dataset_index,dataset_file"


def discover_datasets(cfg: MiningConfig) -> list[str]:
    """Glob ``datasets_dir`` for dataset CSVs (reference: main.py:315-320, :38)."""
    pattern = os.path.join(cfg.datasets_dir, cfg.regex_filename)
    return sorted(_glob.glob(pattern))


def _datasets_list_path(cfg: MiningConfig) -> str:
    return os.path.join(cfg.base_dir, cfg.datasets_list_file)


def _history_path(cfg: MiningConfig) -> str:
    return os.path.join(cfg.base_dir, cfg.dataset_history_file)


def token_path_for(base_dir: str, data_invalidation_file: str) -> str:
    return os.path.join(base_dir, data_invalidation_file)


def write_dataset_list(cfg: MiningConfig, datasets: list[str]) -> None:
    """Persist the discovered dataset list (reference: main.py:329-346)."""
    atomic_write_text(_datasets_list_path(cfg), "\n".join(datasets) + "\n")


def read_dataset_list(cfg: MiningConfig) -> list[str]:
    """Read the persisted dataset list (reference: main.py:322-327)."""
    text = read_text(_datasets_list_path(cfg))
    return [
        line for line in (raw.strip() for raw in text.splitlines()) if line
    ]


def get_dataset_list(cfg: MiningConfig, persist: bool = True) -> list[str]:
    """First run: discover + persist; later runs: read the persisted list
    (reference: main.py:315-346 call pattern at :425).

    ``persist=False`` skips the first-run write — non-zero ranks of a
    multi-host job must not race rank 0 on the shared PVC (the sorted glob
    over the same volume is deterministic, so every rank sees one list)."""
    path = _datasets_list_path(cfg)
    if os.path.exists(path):
        existing = read_dataset_list(cfg)
        if existing:
            return existing
    datasets = discover_datasets(cfg)
    if not datasets:
        raise FileNotFoundError(
            f"no datasets matching {cfg.regex_filename!r} under {cfg.datasets_dir!r}"
        )
    if persist:
        write_dataset_list(cfg, datasets)
    return datasets


def read_history(cfg: MiningConfig) -> list[tuple[str, int, str]]:
    """Parse ``dataset_history.csv`` rows as ``(time, index, dataset_file)``
    (reference: main.py:349-362; row layout documented at main.py:377-378).

    Malformed lines are skipped (the reference instead falls back to
    ``BASE_INDEX`` when the *last* line is malformed, main.py:389-392 — here a
    corrupt tail degrades to the last parseable record instead of restarting
    the rotation).
    """
    path = _history_path(cfg)
    if not os.path.exists(path):
        return []
    rows: list[tuple[str, int, str]] = []
    for line in read_text(path).splitlines():
        line = line.strip()
        if not line or line == HISTORY_HEADER:
            continue
        parts = line.split(",", 2)
        if len(parts) != 3:
            continue
        try:
            rows.append((parts[0].strip(), int(parts[1].strip()), parts[2].strip()))
        except ValueError:
            continue
    return rows


def get_next_run_index(cfg: MiningConfig, datasets: list[str]) -> int:
    """Last history index + 1, wrapping to ``BASE_INDEX`` past the end of the
    dataset list (reference: main.py:364-392; wraparound :386-387).

    Indices are 1-based like the reference's ``BASE_INDEX = 1``
    (machine-learning/main.py:46).
    """
    history = read_history(cfg)
    if not history:
        return BASE_INDEX
    next_index = history[-1][1] + 1
    if next_index > len(datasets) + BASE_INDEX - 1:
        next_index = BASE_INDEX
    return next_index


def append_history_and_invalidate(
    cfg: MiningConfig, run_index: int, dataset: str, timestamp: str | None = None
) -> str:
    """Append the run record and rewrite the invalidation token — the only
    cross-workload signal in the system (reference: main.py:394-411; token
    write :406-408). Returns the token value written."""
    timestamp = timestamp or get_current_time_str_precise()
    path = _history_path(cfg)
    os.makedirs(cfg.base_dir, exist_ok=True)
    is_new = not os.path.exists(path)
    with open(path, "a", encoding="utf-8") as fh:
        if is_new:
            fh.write(HISTORY_HEADER + "\n")
        fh.write(f"{timestamp},{run_index},{dataset}\n")
    token = timestamp
    atomic_write_text(token_path_for(cfg.base_dir, cfg.data_invalidation_file), token)
    return token
