from . import vocab  # noqa: F401
