"""TPU-native ALS matrix factorization — the second model family.

FP-Growth rules (the paper's only model) structurally cannot answer
cold-start seeds or long-tail tracks that never co-occur above
``min_support``: a track with no frequent pair has an empty rule row, and
a track pruned before pair counting isn't even a rule-dict key. A learned
embedding space has no such floor — every track that appears in ANY
playlist gets a vector, and similarity generalizes across co-occurrence
gaps. ALX (PAPERS.md) is the recipe this follows: alternating least
squares over the playlist×track interaction matrix, where each half-sweep
is a batched normal-equation solve — matmul-shaped work that rides the
MXU, not a per-row Python loop.

Formulation: the binary membership matrix ``X ∈ {0,1}^{P×V}`` (the same
matrix the encode phase already produces as the mining one-hot) is
factorized as ``X ≈ U Fᵀ`` minimizing

    ‖X − U Fᵀ‖²_F + λ(‖U‖²_F + ‖F‖²_F)

with every cell observed (zeros included). Because the loss weights all
cells equally, both half-sweeps share ONE rank×rank Gramian, so the
per-row normal equations collapse into a single batched solve:

    U ← X F (FᵀF + λI)⁻¹        (all P users at once)
    F ← Xᵀ U (UᵀU + λI)⁻¹       (all V items at once)

Each iteration is two (big × skinny) matmuls plus two rank×rank solves —
exactly the shape ALX shards across TPU pods. Two layouts:

- **replicated** (default): the whole sweep on one device, as before.
- **mesh-sharded** (``KMLS_MODEL_LAYOUT=sharded``, or ``auto`` when the
  dense interaction matrix busts the per-device budget): the ALX recipe
  proper — the interaction matrix shards along the VOCAB axis of the
  same ``tp`` mesh the sharded miner uses (``P(None, 'tp')``), the item
  factors shard with it (``P('tp', None)``), and the user half-sweep's
  two reductions (``FᵀF`` Gramian and ``X F``) become ``psum``s over the
  vocab axis while the ITEM half-sweep stays fully shard-local
  (``X[:, lo:hi]ᵀ U`` touches only resident columns). Per-device memory
  drops to O(P·V/tp), so the auto layout can TRAIN an embedding the
  single-device HBM guard would previously have skipped. Collective
  reduction order makes the sharded factors float-equal-but-not-bit-
  equal to the replicated ones, which is exactly why ``model_layout``
  joined the checkpoint fingerprint (mining/checkpoint.py): resume
  within a layout is bit-identical, across layouts it re-trains.

Serving consumes only the ITEM factors: seed→candidate scores are
cosine similarities in item space (item-item collaborative filtering),
so the published artifact carries the L2-normalized item factors and the
user factors are discarded after training.

Determinism: factor init comes from a fixed-seed host RNG and every
device op is deterministic on a fixed backend, so two trainings of the
same baskets on the same host produce bit-identical factors — which is
what lets the ``embed`` phase checkpoint resume bit-identically and the
manifest sha256 prove it.
"""

from __future__ import annotations

import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MiningConfig
from ..ops import encode
from .vocab import Baskets


@jax.jit
def _als_sweep(
    x_mat: jax.Array,  # f32 (P, V) binary interactions
    user_f: jax.Array,  # f32 (P, R)
    item_f: jax.Array,  # f32 (V, R)
    reg: jax.Array,  # f32 scalar
) -> tuple[jax.Array, jax.Array]:
    """One alternating sweep: users then items, each a single batched
    normal-equation solve against the shared rank×rank Gramian."""
    rank = user_f.shape[1]
    eye = jnp.eye(rank, dtype=user_f.dtype)
    g_item = item_f.T @ item_f + reg * eye  # (R, R)
    # solve (R,R) @ Uᵀ = (X F)ᵀ for all P rows at once
    user_f = jnp.linalg.solve(g_item, (x_mat @ item_f).T).T
    g_user = user_f.T @ user_f + reg * eye
    item_f = jnp.linalg.solve(g_user, (x_mat.T @ user_f).T).T
    return user_f, item_f


@jax.jit
def _als_loss(
    x_mat: jax.Array, user_f: jax.Array, item_f: jax.Array, reg: jax.Array
) -> jax.Array:
    resid = x_mat - user_f @ item_f.T
    return (
        jnp.sum(resid * resid)
        + reg * (jnp.sum(user_f * user_f) + jnp.sum(item_f * item_f))
    )


@functools.lru_cache(maxsize=8)
def _sharded_sweep_fn(mesh):
    """One ALS sweep with the item axis sharded over the mesh's vocab
    (``tp``) axis — the ALX partitioning of these exact matmuls. The user
    half-sweep reduces over items (``psum`` of the Gramian and of
    ``X F``); the item half-sweep is embarrassingly shard-local. Cached
    per mesh so the iteration loop reuses one compiled program."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import AXIS_TP
    from ..utils.jaxcompat import shard_map

    def local(x_loc, user_f, item_f_loc, reg):
        # x_loc (P, V_loc) f32; user_f (P, R) replicated; item_f_loc
        # (V_loc, R) — this shard's rows of the item-factor matrix
        rank = user_f.shape[1]
        eye = jnp.eye(rank, dtype=user_f.dtype)
        g_item = (
            jax.lax.psum(item_f_loc.T @ item_f_loc, AXIS_TP) + reg * eye
        )
        xf = jax.lax.psum(x_loc @ item_f_loc, AXIS_TP)  # (P, R)
        user_f = jnp.linalg.solve(g_item, xf.T).T
        g_user = user_f.T @ user_f + reg * eye
        item_f_loc = jnp.linalg.solve(g_user, (x_loc.T @ user_f).T).T
        return user_f, item_f_loc

    return jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(
                P(None, AXIS_TP), P(None, None), P(AXIS_TP, None), P()
            ),
            out_specs=(P(None, None), P(AXIS_TP, None)),
            # the psums make user_f mesh-invariant; item_f varies by
            # design (it IS the sharded output)
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=8)
def _sharded_loss_fn(mesh):
    """Training loss over the column-sharded interaction matrix: local
    residual + local item-factor penalty, ``psum`` over the vocab axis;
    the (replicated) user-factor penalty is added once by the caller."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import AXIS_TP
    from ..utils.jaxcompat import shard_map

    def local(x_loc, user_f, item_f_loc, reg):
        resid = x_loc - user_f @ item_f_loc.T
        return jax.lax.psum(
            jnp.sum(resid * resid) + reg * jnp.sum(item_f_loc * item_f_loc),
            AXIS_TP,
        )

    return jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(
                P(None, AXIS_TP), P(None, None), P(AXIS_TP, None), P()
            ),
            out_specs=P(),
            check_vma=False,
        )
    )


def normalize_factors(item_factors: np.ndarray) -> np.ndarray:
    """Row-L2-normalize → unit vectors, so serving dot products are cosine
    similarities in [-1, 1] and blend cleanly with rule confidences. A
    zero row (can't arise from baskets — every vocab track appears at
    least once — but a loaded artifact must not NaN) keeps a zero vector."""
    norms = np.linalg.norm(item_factors, axis=1, keepdims=True)
    return (item_factors / np.maximum(norms, 1e-12)).astype(np.float32)


def _als_shards(cfg: MiningConfig, mesh, p: int, v: int, rank: int) -> int:
    """How many vocab shards the trainer lays the item axis over (1 =
    the legacy single-device sweep). Sharding engages only when the mesh
    spans the vocab (``tp``) axis AND the layout knob asks for it —
    explicitly (``sharded``), or via ``auto`` exactly when the
    single-device dense formulation would bust the HBM budget (the case
    that previously SKIPPED the embed phase: the mesh can hold what one
    device cannot). Deterministic in (config, dataset shape, mesh), so
    every rank of a multi-host job decides identically."""
    if mesh is None:
        return 1
    from ..parallel.mesh import AXIS_TP

    from ..parallel.layout import validate_layout

    tp = mesh.shape.get(AXIS_TP, 1)
    if tp <= 1:
        return 1
    layout = validate_layout(getattr(cfg, "model_layout", "replicated"))
    if layout == "sharded":
        return tp
    # auto: the LAYOUT decision measures against KMLS_DEVICE_BUDGET_BYTES
    # (0 = fall back to the HBM dispatch budget — the documented contract
    # in config.py); the fit GUARD below still budgets compute against
    # hbm_budget_bytes, which is a different question (can the planned
    # slab run) than this one (should the matrix shard at all)
    layout_budget = (
        getattr(cfg, "device_budget_bytes", 0) or cfg.hbm_budget_bytes
    )
    if (
        layout == "auto"
        and 5 * p * v + 8 * rank * (p + v) > layout_budget
    ):
        return tp
    return 1


def train_embeddings(
    baskets: Baskets, cfg: MiningConfig, seed: int = 0, mesh=None
) -> dict[str, Any]:
    """Train item embeddings over the transaction DB → the ``embed``
    phase's checkpoint payload:

    ``{"item_factors": f32 (V, rank) L2-normalized, "rank", "iters",
    "reg", "final_loss", "duration_s"}`` — or, when the dense
    formulation would not fit ``cfg.hbm_budget_bytes``, a payload with
    ``item_factors=None`` and a ``skipped`` reason (the pipeline then
    publishes a rules-only generation; the skip is a function of config
    + dataset shape, so every rank — and every resume — decides it
    identically).

    The interaction matrix is the SAME encode the mining path uses
    (``ops.encode.onehot_matrix`` over the deduplicated membership
    pairs), cast to f32 — two writers, one spine.
    """
    rank = max(1, cfg.als_rank)
    iters = max(1, cfg.als_iters)
    reg = jnp.float32(cfg.als_reg)
    p, v = baskets.n_playlists, baskets.n_tracks
    shards = _als_shards(cfg, mesh, p, v, rank)
    # HBM-fit guard: this formulation materializes the interaction matrix
    # DENSE float32 — 4x the int8 footprint the mining path's bitpack
    # dispatch exists to avoid. At scales where that dispatch fires, the
    # dense ALS would OOM the job AFTER the expensive mine; skip the
    # phase deterministically instead (rules-only generation, loud
    # message). Under the sharded layout the matrix-shaped terms divide
    # across the vocab shards (the ALX point), so the guard budgets the
    # PER-DEVICE slab. Budgeted terms: X (P·V f32) + its int8 encode
    # source + both factor matrices and their normal-equation right-hand
    # sides.
    dense_bytes = 5 * p * v // shards + 8 * rank * (p + v)
    if dense_bytes > cfg.hbm_budget_bytes:
        return {
            "item_factors": None,
            "rank": rank,
            "iters": iters,
            "reg": float(cfg.als_reg),
            "final_loss": None,
            "duration_s": 0.0,
            "skipped": (
                f"dense {p}x{v} interaction matrix (~{dense_bytes >> 20} MiB"
                f" per device across {shards} shard(s))"
                f" exceeds hbm_budget_bytes ({cfg.hbm_budget_bytes >> 20} "
                "MiB); embed phase skipped — serving stays rules-only"
            ),
        }
    t0 = time.perf_counter()
    # fixed-seed HOST init: device RNG streams differ across backends,
    # host bytes do not — resume/fingerprint identity depends on this.
    # The draw ORDER (users then items) is shared by both layouts.
    rng = np.random.default_rng(seed)
    user_init = rng.standard_normal((p, rank)).astype(np.float32) / np.sqrt(
        rank
    )
    item_init = rng.standard_normal((v, rank)).astype(np.float32) / np.sqrt(
        rank
    )
    if shards > 1:
        item_raw, final_loss = _train_sharded(
            baskets, mesh, user_init, item_init, reg, iters, p, v
        )
        item_host = normalize_factors(item_raw)
    else:
        x_mat = encode.onehot_matrix(
            jnp.asarray(baskets.playlist_rows),
            jnp.asarray(baskets.track_ids),
            n_playlists=p,
            n_tracks=v,
        ).astype(jnp.float32)
        user_f = jnp.asarray(user_init)
        item_f = jnp.asarray(item_init)
        for _ in range(iters):
            user_f, item_f = _als_sweep(x_mat, user_f, item_f, reg)
        final_loss = float(_als_loss(x_mat, user_f, item_f, reg))
        item_host = normalize_factors(np.array(jax.device_get(item_f)))
    duration_s = time.perf_counter() - t0
    return {
        "item_factors": item_host,
        "rank": rank,
        "iters": iters,
        "reg": float(cfg.als_reg),
        "final_loss": final_loss,
        "duration_s": duration_s,
        "shards": shards,
    }


def _train_sharded(
    baskets: Baskets, mesh, user_init: np.ndarray, item_init: np.ndarray,
    reg: jax.Array, iters: int, p: int, v: int,
) -> tuple[np.ndarray, float]:
    """The mesh-sharded sweep loop → ``(item factors (V, R) host, final
    loss)``. The interaction matrix is built DIRECTLY into its
    ``P(None, 'tp')`` layout (no single-device staging — the whole point
    is that no device ever holds all of X), the item factors ride
    ``P('tp', None)``, and the padded vocab rows are zero-initialized so
    they stay exactly zero through every sweep (zero interaction columns
    solve to zero rows) and slice off at the end."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import AXIS_TP, round_up

    tp = mesh.shape[AXIS_TP]
    v_pad = round_up(max(v, 1), tp)
    rank = user_init.shape[1]
    build = jax.jit(
        lambda pr, ti: encode.onehot_matrix(
            pr, ti, n_playlists=p, n_tracks=v_pad
        ).astype(jnp.float32),
        out_shardings=NamedSharding(mesh, P(None, AXIS_TP)),
    )
    x_mat = build(
        jnp.asarray(baskets.playlist_rows), jnp.asarray(baskets.track_ids)
    )
    user_f = jax.device_put(
        user_init, NamedSharding(mesh, P(None, None))
    )
    item_padded = np.zeros((v_pad, rank), dtype=np.float32)
    item_padded[:v] = item_init
    item_f = jax.device_put(
        item_padded, NamedSharding(mesh, P(AXIS_TP, None))
    )
    sweep = _sharded_sweep_fn(mesh)
    for _ in range(iters):
        user_f, item_f = sweep(x_mat, user_f, item_f, reg)
    user_host = np.array(jax.device_get(user_f))
    loss = float(_sharded_loss_fn(mesh)(x_mat, user_f, item_f, reg))
    loss += float(reg) * float(np.sum(user_host * user_host))
    item_host = np.array(jax.device_get(item_f))[:v]
    return item_host, loss
