"""TPU-native ALS matrix factorization — the second model family.

FP-Growth rules (the paper's only model) structurally cannot answer
cold-start seeds or long-tail tracks that never co-occur above
``min_support``: a track with no frequent pair has an empty rule row, and
a track pruned before pair counting isn't even a rule-dict key. A learned
embedding space has no such floor — every track that appears in ANY
playlist gets a vector, and similarity generalizes across co-occurrence
gaps. ALX (PAPERS.md) is the recipe this follows: alternating least
squares over the playlist×track interaction matrix, where each half-sweep
is a batched normal-equation solve — matmul-shaped work that rides the
MXU, not a per-row Python loop.

Formulation: the binary membership matrix ``X ∈ {0,1}^{P×V}`` (the same
matrix the encode phase already produces as the mining one-hot) is
factorized as ``X ≈ U Fᵀ`` minimizing

    ‖X − U Fᵀ‖²_F + λ(‖U‖²_F + ‖F‖²_F)

with every cell observed (zeros included). Because the loss weights all
cells equally, both half-sweeps share ONE rank×rank Gramian, so the
per-row normal equations collapse into a single batched solve:

    U ← X F (FᵀF + λI)⁻¹        (all P users at once)
    F ← Xᵀ U (UᵀU + λI)⁻¹       (all V items at once)

Each iteration is two (big × skinny) matmuls plus two rank×rank solves —
exactly the shape ALX shards across TPU pods; here it runs on the local
device (the mesh-sharded variant is the ROADMAP's model-parallel item).

Serving consumes only the ITEM factors: seed→candidate scores are
cosine similarities in item space (item-item collaborative filtering),
so the published artifact carries the L2-normalized item factors and the
user factors are discarded after training.

Determinism: factor init comes from a fixed-seed host RNG and every
device op is deterministic on a fixed backend, so two trainings of the
same baskets on the same host produce bit-identical factors — which is
what lets the ``embed`` phase checkpoint resume bit-identically and the
manifest sha256 prove it.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MiningConfig
from ..ops import encode
from .vocab import Baskets


@jax.jit
def _als_sweep(
    x_mat: jax.Array,  # f32 (P, V) binary interactions
    user_f: jax.Array,  # f32 (P, R)
    item_f: jax.Array,  # f32 (V, R)
    reg: jax.Array,  # f32 scalar
) -> tuple[jax.Array, jax.Array]:
    """One alternating sweep: users then items, each a single batched
    normal-equation solve against the shared rank×rank Gramian."""
    rank = user_f.shape[1]
    eye = jnp.eye(rank, dtype=user_f.dtype)
    g_item = item_f.T @ item_f + reg * eye  # (R, R)
    # solve (R,R) @ Uᵀ = (X F)ᵀ for all P rows at once
    user_f = jnp.linalg.solve(g_item, (x_mat @ item_f).T).T
    g_user = user_f.T @ user_f + reg * eye
    item_f = jnp.linalg.solve(g_user, (x_mat.T @ user_f).T).T
    return user_f, item_f


@jax.jit
def _als_loss(
    x_mat: jax.Array, user_f: jax.Array, item_f: jax.Array, reg: jax.Array
) -> jax.Array:
    resid = x_mat - user_f @ item_f.T
    return (
        jnp.sum(resid * resid)
        + reg * (jnp.sum(user_f * user_f) + jnp.sum(item_f * item_f))
    )


def normalize_factors(item_factors: np.ndarray) -> np.ndarray:
    """Row-L2-normalize → unit vectors, so serving dot products are cosine
    similarities in [-1, 1] and blend cleanly with rule confidences. A
    zero row (can't arise from baskets — every vocab track appears at
    least once — but a loaded artifact must not NaN) keeps a zero vector."""
    norms = np.linalg.norm(item_factors, axis=1, keepdims=True)
    return (item_factors / np.maximum(norms, 1e-12)).astype(np.float32)


def train_embeddings(
    baskets: Baskets, cfg: MiningConfig, seed: int = 0
) -> dict[str, Any]:
    """Train item embeddings over the transaction DB → the ``embed``
    phase's checkpoint payload:

    ``{"item_factors": f32 (V, rank) L2-normalized, "rank", "iters",
    "reg", "final_loss", "duration_s"}`` — or, when the dense
    formulation would not fit ``cfg.hbm_budget_bytes``, a payload with
    ``item_factors=None`` and a ``skipped`` reason (the pipeline then
    publishes a rules-only generation; the skip is a function of config
    + dataset shape, so every rank — and every resume — decides it
    identically).

    The interaction matrix is the SAME encode the mining path uses
    (``ops.encode.onehot_matrix`` over the deduplicated membership
    pairs), cast to f32 — two writers, one spine.
    """
    rank = max(1, cfg.als_rank)
    iters = max(1, cfg.als_iters)
    reg = jnp.float32(cfg.als_reg)
    p, v = baskets.n_playlists, baskets.n_tracks
    # HBM-fit guard: this formulation materializes the interaction matrix
    # DENSE float32 — 4x the int8 footprint the mining path's bitpack
    # dispatch exists to avoid. At scales where that dispatch fires, the
    # dense ALS would OOM the job AFTER the expensive mine; skip the
    # phase deterministically instead (rules-only generation, loud
    # message). The sparse/sharded ALS is the ROADMAP model-parallel
    # item. Budgeted terms: X (P·V f32) + its int8 encode source + both
    # factor matrices and their normal-equation right-hand sides.
    dense_bytes = 5 * p * v + 8 * rank * (p + v)
    if dense_bytes > cfg.hbm_budget_bytes:
        return {
            "item_factors": None,
            "rank": rank,
            "iters": iters,
            "reg": float(cfg.als_reg),
            "final_loss": None,
            "duration_s": 0.0,
            "skipped": (
                f"dense {p}x{v} interaction matrix (~{dense_bytes >> 20} MiB)"
                f" exceeds hbm_budget_bytes ({cfg.hbm_budget_bytes >> 20} "
                "MiB); embed phase skipped — serving stays rules-only"
            ),
        }
    t0 = time.perf_counter()
    x_mat = encode.onehot_matrix(
        jnp.asarray(baskets.playlist_rows),
        jnp.asarray(baskets.track_ids),
        n_playlists=p,
        n_tracks=v,
    ).astype(jnp.float32)
    # fixed-seed HOST init: device RNG streams differ across backends,
    # host bytes do not — resume/fingerprint identity depends on this
    rng = np.random.default_rng(seed)
    user_f = jnp.asarray(
        rng.standard_normal((p, rank)).astype(np.float32) / np.sqrt(rank)
    )
    item_f = jnp.asarray(
        rng.standard_normal((v, rank)).astype(np.float32) / np.sqrt(rank)
    )
    for _ in range(iters):
        user_f, item_f = _als_sweep(x_mat, user_f, item_f, reg)
    final_loss = float(_als_loss(x_mat, user_f, item_f, reg))
    item_host = normalize_factors(np.array(jax.device_get(item_f)))
    duration_s = time.perf_counter() - t0
    return {
        "item_factors": item_host,
        "rank": rank,
        "iters": iters,
        "reg": float(cfg.als_reg),
        "final_loss": final_loss,
        "duration_s": duration_s,
    }
