"""TPU-native ALS matrix factorization — the second model family.

FP-Growth rules (the paper's only model) structurally cannot answer
cold-start seeds or long-tail tracks that never co-occur above
``min_support``: a track with no frequent pair has an empty rule row, and
a track pruned before pair counting isn't even a rule-dict key. A learned
embedding space has no such floor — every track that appears in ANY
playlist gets a vector, and similarity generalizes across co-occurrence
gaps. ALX (PAPERS.md) is the recipe this follows: alternating least
squares over the playlist×track interaction matrix, where each half-sweep
is a batched normal-equation solve — matmul-shaped work that rides the
MXU, not a per-row Python loop.

Formulation: the binary membership matrix ``X ∈ {0,1}^{P×V}`` (the same
matrix the encode phase already produces as the mining one-hot) is
factorized as ``X ≈ U Fᵀ`` minimizing

    ‖X − U Fᵀ‖²_F + λ(‖U‖²_F + ‖F‖²_F)

with every cell observed (zeros included). Because the loss weights all
cells equally, both half-sweeps share ONE rank×rank Gramian, so the
per-row normal equations collapse into a single batched solve:

    U ← X F (FᵀF + λI)⁻¹        (all P users at once)
    F ← Xᵀ U (UᵀU + λI)⁻¹       (all V items at once)

Each iteration is two (big × skinny) matmuls plus two rank×rank solves —
exactly the shape ALX shards across TPU pods. Two layouts:

- **replicated** (default): the whole sweep on one device, as before.
- **mesh-sharded** (``KMLS_MODEL_LAYOUT=sharded``, or ``auto`` when the
  dense interaction matrix busts the per-device budget): the ALX recipe
  proper — the interaction matrix shards along the VOCAB axis of the
  same ``tp`` mesh the sharded miner uses (``P(None, 'tp')``), the item
  factors shard with it (``P('tp', None)``), and the user half-sweep's
  two reductions (``FᵀF`` Gramian and ``X F``) become ``psum``s over the
  vocab axis while the ITEM half-sweep stays fully shard-local
  (``X[:, lo:hi]ᵀ U`` touches only resident columns). Per-device memory
  drops to O(P·V/tp), so the auto layout can TRAIN an embedding the
  single-device HBM guard would previously have skipped. Collective
  reduction order makes the sharded factors float-equal-but-not-bit-
  equal to the replicated ones, which is exactly why ``model_layout``
  joined the checkpoint fingerprint (mining/checkpoint.py): resume
  within a layout is bit-identical, across layouts it re-trains.

- **sparse storage** (``KMLS_ALS_SPARSE``, ISSUE 13): the binary
  interaction matrix is kept COMPRESSED — the two int32 index vectors
  are the whole representation — and both big×skinny products become
  chunked gather+segment-adds over the nnz events (Tensor Casting's
  gather/scatter co-design). Memory drops from O(P·V) to O(nnz), so
  ``auto`` trains catalogs whose dense f32 matrix busts the HBM guard
  on a single device. Sparse factors are float-equal-but-not-bit-equal
  to dense ones (accumulation order), so the knob joins the checkpoint
  fingerprint exactly as ``model_layout`` did (v3 note there).

Serving consumes only the ITEM factors: seed→candidate scores are
cosine similarities in item space (item-item collaborative filtering),
so the published artifact carries the L2-normalized item factors and the
user factors are discarded after training.

Determinism: factor init comes from a fixed-seed host RNG and every
device op is deterministic on a fixed backend, so two trainings of the
same baskets on the same host produce bit-identical factors — which is
what lets the ``embed`` phase checkpoint resume bit-identically and the
manifest sha256 prove it.
"""

from __future__ import annotations

import functools
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MiningConfig
from ..ops import encode
from .vocab import Baskets


@jax.jit
def _als_sweep(
    x_mat: jax.Array,  # f32 (P, V) binary interactions
    user_f: jax.Array,  # f32 (P, R)
    item_f: jax.Array,  # f32 (V, R)
    reg: jax.Array,  # f32 scalar
) -> tuple[jax.Array, jax.Array]:
    """One alternating sweep: users then items, each a single batched
    normal-equation solve against the shared rank×rank Gramian."""
    rank = user_f.shape[1]
    eye = jnp.eye(rank, dtype=user_f.dtype)
    g_item = item_f.T @ item_f + reg * eye  # (R, R)
    # solve (R,R) @ Uᵀ = (X F)ᵀ for all P rows at once
    user_f = jnp.linalg.solve(g_item, (x_mat @ item_f).T).T
    g_user = user_f.T @ user_f + reg * eye
    item_f = jnp.linalg.solve(g_user, (x_mat.T @ user_f).T).T
    return user_f, item_f


@jax.jit
def _als_loss(
    x_mat: jax.Array, user_f: jax.Array, item_f: jax.Array, reg: jax.Array
) -> jax.Array:
    resid = x_mat - user_f @ item_f.T
    return (
        jnp.sum(resid * resid)
        + reg * (jnp.sum(user_f * user_f) + jnp.sum(item_f * item_f))
    )


ALS_SPARSE_MODES = ("auto", "always", "never")

# accumulation-chunk ceiling for the sparse half-sweeps: bounds the
# gathered (chunk, R) intermediate so peak memory is nnz-INDEPENDENT
# beyond the index arrays themselves
_SPARSE_CHUNK = 1 << 16


def _als_chunk(nnz: int) -> int:
    """Power-of-two accumulation chunk: capped by ``_SPARSE_CHUNK``, and
    scaled DOWN to the event count at small shapes so the fixed chunk
    buffer never dominates the sparse memory plan (the budget math and
    the sweep must agree — both call this)."""
    chunk = 256
    while chunk < min(max(nnz, 1), _SPARSE_CHUNK):
        chunk <<= 1
    return chunk


def resolve_als_sparse(value: str | None) -> str:
    """``KMLS_ALS_SPARSE`` validation. Fail-safe direction: sparse and
    dense factors are float-DIFFERENT (accumulation order), so a typo
    must resolve to ``auto`` — the default, whose dense-while-it-fits
    behavior is exactly what every existing deployment trains today."""
    word = (value or "auto").strip().lower()
    if word in ALS_SPARSE_MODES:
        return word
    import logging

    logging.getLogger("kmlserver_tpu.mining").warning(
        "KMLS_ALS_SPARSE=%r is not one of %s; using 'auto'",
        value, "/".join(ALS_SPARSE_MODES),
    )
    return "auto"


def sparse_als_bytes(nnz: int, p: int, v: int, rank: int) -> int:
    """Planned device bytes for the COMPRESSED formulation: the two
    int32 index vectors (the interaction matrix is binary — indices ARE
    the values), both factor matrices + their normal-equation right-hand
    sides, and one fixed-size gathered chunk. nnz-proportional — the
    dense ``P·V`` term is gone, which is the whole point."""
    return 8 * nnz + 8 * rank * (p + v) + 4 * _als_chunk(nnz) * rank


def _sparse_accumulate(seg, gidx, mat, n_out: int, chunk: int):
    """``out[s] += mat[g]`` over the padded event stream, in fixed-size
    chunks under ``lax.scan`` so the gathered intermediate never exceeds
    ``(chunk, R)``. Padding rides sentinel ids: ``seg == n_out`` lands in
    a scratch row sliced off at the end; the matching gather id is
    clipped (its value lands only in the dropped row). Traced inline by
    the jitted sweep/loss wrappers."""
    import jax

    rank = mat.shape[1]

    def step(acc, k):
        s = jax.lax.dynamic_slice_in_dim(seg, k * chunk, chunk)
        g = jax.lax.dynamic_slice_in_dim(gidx, k * chunk, chunk)
        vals = mat[jnp.minimum(g, mat.shape[0] - 1)]
        return acc.at[s].add(vals), None

    acc0 = jnp.zeros((n_out + 1, rank), mat.dtype)
    acc, _ = jax.lax.scan(step, acc0, jnp.arange(seg.shape[0] // chunk))
    return acc[:n_out]


@functools.partial(jax.jit, static_argnames=("p", "v", "chunk"))
def _sparse_als_sweep(rows, cols, user_f, item_f, reg, *, p, v, chunk):
    """One alternating sweep over the COMPRESSED interaction matrix:
    the two big×skinny products ``X F`` and ``Xᵀ U`` become chunked
    gather+segment-adds over the nnz events (Tensor Casting's
    gather/scatter co-design is the reference shape); the rank×rank
    Gramians and solves are unchanged — they never saw X at all."""
    rank = user_f.shape[1]
    eye = jnp.eye(rank, dtype=user_f.dtype)
    g_item = item_f.T @ item_f + reg * eye
    xf = _sparse_accumulate(rows, cols, item_f, p, chunk)  # X F, (P, R)
    user_f = jnp.linalg.solve(g_item, xf.T).T
    g_user = user_f.T @ user_f + reg * eye
    xtu = _sparse_accumulate(cols, rows, user_f, v, chunk)  # Xᵀ U, (V, R)
    item_f = jnp.linalg.solve(g_user, xtu.T).T
    return user_f, item_f


@functools.partial(jax.jit, static_argnames=("p", "chunk"))
def _sparse_als_loss(rows, cols, user_f, item_f, reg, nnz, *, p, chunk):
    """Exact training loss without densifying:
    ``‖X − U Fᵀ‖² = nnz − 2·Σ_nnz u_r·f_c + ‖U Fᵀ‖²`` where
    ``‖U Fᵀ‖² = Σ (UᵀU)∘(FᵀF)`` — every X-dependent term reduces over
    the nnz events only (X is binary: Σx² = nnz)."""
    import jax

    gram = jnp.sum((user_f.T @ user_f) * (item_f.T @ item_f))

    def step(acc, k):
        r = jax.lax.dynamic_slice_in_dim(rows, k * chunk, chunk)
        c = jax.lax.dynamic_slice_in_dim(cols, k * chunk, chunk)
        u = user_f[jnp.minimum(r, user_f.shape[0] - 1)]
        f = item_f[jnp.minimum(c, item_f.shape[0] - 1)]
        valid = (r < p).astype(user_f.dtype)
        return acc + jnp.sum(jnp.sum(u * f, axis=1) * valid), None

    cross, _ = jax.lax.scan(
        step, jnp.float32(0.0), jnp.arange(rows.shape[0] // chunk)
    )
    penalty = reg * (jnp.sum(user_f * user_f) + jnp.sum(item_f * item_f))
    return nnz - 2.0 * cross + gram + penalty


def _train_sparse(
    baskets: Baskets, user_init: np.ndarray, item_init: np.ndarray,
    reg: jax.Array, iters: int, p: int, v: int,
) -> tuple[np.ndarray, float]:
    """The compressed-storage sweep loop → ``(item factors, final
    loss)``. Deterministic: fixed host init, fixed chunking, XLA's
    deterministic scatter-add — two runs on the same backend produce
    bit-identical factors (test-pinned), which is what lets the embed
    checkpoint resume and the manifest sha256 keep their guarantees."""
    nnz = len(baskets.playlist_rows)
    chunk = _als_chunk(nnz)
    pad = (-nnz) % chunk if nnz else chunk
    rows = np.concatenate(
        [np.asarray(baskets.playlist_rows, np.int32), np.full(pad, p, np.int32)]
    )
    cols = np.concatenate(
        [np.asarray(baskets.track_ids, np.int32), np.full(pad, v, np.int32)]
    )
    rows_d, cols_d = jnp.asarray(rows), jnp.asarray(cols)
    user_f = jnp.asarray(user_init)
    item_f = jnp.asarray(item_init)
    for _ in range(iters):
        user_f, item_f = _sparse_als_sweep(
            rows_d, cols_d, user_f, item_f, reg, p=p, v=v, chunk=chunk
        )
    loss = float(
        _sparse_als_loss(
            rows_d, cols_d, user_f, item_f, reg, jnp.float32(nnz),
            p=p, chunk=chunk,
        )
    )
    return np.array(jax.device_get(item_f)), loss


@functools.lru_cache(maxsize=8)
def _sharded_sweep_fn(mesh):
    """One ALS sweep with the item axis sharded over the mesh's vocab
    (``tp``) axis — the ALX partitioning of these exact matmuls. The user
    half-sweep reduces over items (``psum`` of the Gramian and of
    ``X F``); the item half-sweep is embarrassingly shard-local. Cached
    per mesh so the iteration loop reuses one compiled program."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import AXIS_TP
    from ..utils.jaxcompat import shard_map

    def local(x_loc, user_f, item_f_loc, reg):
        # x_loc (P, V_loc) f32; user_f (P, R) replicated; item_f_loc
        # (V_loc, R) — this shard's rows of the item-factor matrix
        rank = user_f.shape[1]
        eye = jnp.eye(rank, dtype=user_f.dtype)
        g_item = (
            jax.lax.psum(item_f_loc.T @ item_f_loc, AXIS_TP) + reg * eye
        )
        xf = jax.lax.psum(x_loc @ item_f_loc, AXIS_TP)  # (P, R)
        user_f = jnp.linalg.solve(g_item, xf.T).T
        g_user = user_f.T @ user_f + reg * eye
        item_f_loc = jnp.linalg.solve(g_user, (x_loc.T @ user_f).T).T
        return user_f, item_f_loc

    return jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(
                P(None, AXIS_TP), P(None, None), P(AXIS_TP, None), P()
            ),
            out_specs=(P(None, None), P(AXIS_TP, None)),
            # the psums make user_f mesh-invariant; item_f varies by
            # design (it IS the sharded output)
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=8)
def _sharded_loss_fn(mesh):
    """Training loss over the column-sharded interaction matrix: local
    residual + local item-factor penalty, ``psum`` over the vocab axis;
    the (replicated) user-factor penalty is added once by the caller."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import AXIS_TP
    from ..utils.jaxcompat import shard_map

    def local(x_loc, user_f, item_f_loc, reg):
        resid = x_loc - user_f @ item_f_loc.T
        return jax.lax.psum(
            jnp.sum(resid * resid) + reg * jnp.sum(item_f_loc * item_f_loc),
            AXIS_TP,
        )

    return jax.jit(
        shard_map(
            local, mesh=mesh,
            in_specs=(
                P(None, AXIS_TP), P(None, None), P(AXIS_TP, None), P()
            ),
            out_specs=P(),
            check_vma=False,
        )
    )


def normalize_factors(item_factors: np.ndarray) -> np.ndarray:
    """Row-L2-normalize → unit vectors, so serving dot products are cosine
    similarities in [-1, 1] and blend cleanly with rule confidences. A
    zero row (can't arise from baskets — every vocab track appears at
    least once — but a loaded artifact must not NaN) keeps a zero vector."""
    norms = np.linalg.norm(item_factors, axis=1, keepdims=True)
    return (item_factors / np.maximum(norms, 1e-12)).astype(np.float32)


def _als_shards(cfg: MiningConfig, mesh, p: int, v: int, rank: int) -> int:
    """How many vocab shards the trainer lays the item axis over (1 =
    the legacy single-device sweep). Sharding engages only when the mesh
    spans the vocab (``tp``) axis AND the layout knob asks for it —
    explicitly (``sharded``), or via ``auto`` exactly when the
    single-device dense formulation would bust the HBM budget (the case
    that previously SKIPPED the embed phase: the mesh can hold what one
    device cannot). Deterministic in (config, dataset shape, mesh), so
    every rank of a multi-host job decides identically."""
    if mesh is None:
        return 1
    from ..parallel.mesh import AXIS_TP

    from ..parallel.layout import validate_layout

    tp = mesh.shape.get(AXIS_TP, 1)
    if tp <= 1:
        return 1
    layout = validate_layout(getattr(cfg, "model_layout", "replicated"))
    if layout == "sharded":
        return tp
    # auto: the LAYOUT decision measures against KMLS_DEVICE_BUDGET_BYTES
    # (0 = fall back to the HBM dispatch budget — the documented contract
    # in config.py); the fit GUARD below still budgets compute against
    # hbm_budget_bytes, which is a different question (can the planned
    # slab run) than this one (should the matrix shard at all)
    layout_budget = (
        getattr(cfg, "device_budget_bytes", 0) or cfg.hbm_budget_bytes
    )
    if (
        layout == "auto"
        and 5 * p * v + 8 * rank * (p + v) > layout_budget
    ):
        return tp
    return 1


def train_embeddings(
    baskets: Baskets, cfg: MiningConfig, seed: int = 0, mesh=None
) -> dict[str, Any]:
    """Train item embeddings over the transaction DB → the ``embed``
    phase's checkpoint payload:

    ``{"item_factors": f32 (V, rank) L2-normalized, "rank", "iters",
    "reg", "final_loss", "duration_s"}`` — or, when the dense
    formulation would not fit ``cfg.hbm_budget_bytes``, a payload with
    ``item_factors=None`` and a ``skipped`` reason (the pipeline then
    publishes a rules-only generation; the skip is a function of config
    + dataset shape, so every rank — and every resume — decides it
    identically).

    The interaction matrix is the SAME encode the mining path uses
    (``ops.encode.onehot_matrix`` over the deduplicated membership
    pairs), cast to f32 — two writers, one spine.
    """
    rank = max(1, cfg.als_rank)
    iters = max(1, cfg.als_iters)
    reg = jnp.float32(cfg.als_reg)
    p, v = baskets.n_playlists, baskets.n_tracks
    nnz = len(baskets.playlist_rows)
    shards = _als_shards(cfg, mesh, p, v, rank)
    # HBM-fit guard: the DENSE formulation materializes the interaction
    # matrix as f32 — 4x the int8 footprint the mining path's bitpack
    # dispatch exists to avoid — and under the sharded layout the
    # matrix-shaped terms divide across the vocab shards (the ALX
    # point), so the guard budgets the PER-DEVICE slab: X (P·V f32) +
    # its int8 encode source + both factor matrices and their
    # normal-equation right-hand sides. The SPARSE storage
    # (``KMLS_ALS_SPARSE``, ISSUE 13) replaces the P·V term with the
    # nnz-proportional compressed form, so `auto` now TRAINS the
    # catalogs the dense floor previously skipped; the deterministic
    # skip remains only when the knob pins dense-or-nothing ("never")
    # or even the compressed form busts the budget. Storage resolution
    # is a function of (config, dataset shape, budget), so every rank —
    # and every resume — decides identically.
    storage_mode = resolve_als_sparse(getattr(cfg, "als_sparse", "auto"))
    dense_bytes = 5 * p * v // shards + 8 * rank * (p + v)
    sparse_bytes = sparse_als_bytes(nnz, p, v, rank)
    use_sparse = False
    if storage_mode == "always":
        if shards > 1:
            print(
                "NOTE: KMLS_ALS_SPARSE=always under the mesh-sharded "
                "layout keeps the sharded dense half-sweeps (the mesh "
                "already divides the matrix); sparse storage applies to "
                "single-device training"
            )
        elif sparse_bytes > cfg.hbm_budget_bytes:
            # a pinned storage mode gets the SAME deterministic guard as
            # dense: training dense instead would silently change the
            # factors the pin exists to fix, and proceeding would OOM
            # after the expensive mine — skip loudly instead
            return {
                "item_factors": None,
                "rank": rank,
                "iters": iters,
                "reg": float(cfg.als_reg),
                "final_loss": None,
                "duration_s": 0.0,
                "storage": "none",
                "skipped": (
                    f"KMLS_ALS_SPARSE=always pins the compressed form "
                    f"but ~{sparse_bytes >> 20} MiB for {nnz} nnz "
                    f"exceeds hbm_budget_bytes "
                    f"({cfg.hbm_budget_bytes >> 20} MiB); embed phase "
                    "skipped — serving stays rules-only"
                ),
            }
        else:
            use_sparse = True
    elif (
        storage_mode == "auto"
        and shards == 1
        and dense_bytes > cfg.hbm_budget_bytes
        and sparse_bytes <= cfg.hbm_budget_bytes
    ):
        use_sparse = True
    if not use_sparse and dense_bytes > cfg.hbm_budget_bytes:
        return {
            "item_factors": None,
            "rank": rank,
            "iters": iters,
            "reg": float(cfg.als_reg),
            "final_loss": None,
            "duration_s": 0.0,
            "storage": "none",
            "skipped": (
                f"dense {p}x{v} interaction matrix (~{dense_bytes >> 20} MiB"
                f" per device across {shards} shard(s))"
                f" exceeds hbm_budget_bytes ({cfg.hbm_budget_bytes >> 20} "
                "MiB) and sparse storage is "
                + (
                    "disabled (KMLS_ALS_SPARSE=never)"
                    if storage_mode == "never"
                    else f"also over budget (~{sparse_bytes >> 20} MiB "
                    f"for {nnz} nnz)"
                    if shards == 1
                    else "single-device only (sharded layout active)"
                )
                + "; embed phase skipped — serving stays rules-only"
            ),
        }
    t0 = time.perf_counter()
    # fixed-seed HOST init: device RNG streams differ across backends,
    # host bytes do not — resume/fingerprint identity depends on this.
    # The draw ORDER (users then items) is shared by both layouts.
    rng = np.random.default_rng(seed)
    user_init = rng.standard_normal((p, rank)).astype(np.float32) / np.sqrt(
        rank
    )
    item_init = rng.standard_normal((v, rank)).astype(np.float32) / np.sqrt(
        rank
    )
    if use_sparse:
        item_raw, final_loss = _train_sparse(
            baskets, user_init, item_init, reg, iters, p, v
        )
        item_host = normalize_factors(item_raw)
    elif shards > 1:
        item_raw, final_loss = _train_sharded(
            baskets, mesh, user_init, item_init, reg, iters, p, v
        )
        item_host = normalize_factors(item_raw)
    else:
        x_mat = encode.onehot_matrix(
            jnp.asarray(baskets.playlist_rows),
            jnp.asarray(baskets.track_ids),
            n_playlists=p,
            n_tracks=v,
        ).astype(jnp.float32)
        user_f = jnp.asarray(user_init)
        item_f = jnp.asarray(item_init)
        for _ in range(iters):
            user_f, item_f = _als_sweep(x_mat, user_f, item_f, reg)
        final_loss = float(_als_loss(x_mat, user_f, item_f, reg))
        item_host = normalize_factors(np.array(jax.device_get(item_f)))
    duration_s = time.perf_counter() - t0
    return {
        "item_factors": item_host,
        "rank": rank,
        "iters": iters,
        "reg": float(cfg.als_reg),
        "final_loss": final_loss,
        "duration_s": duration_s,
        "shards": shards,
        "storage": "sparse" if use_sparse else "dense",
        "nnz": nnz,
    }


def _train_sharded(
    baskets: Baskets, mesh, user_init: np.ndarray, item_init: np.ndarray,
    reg: jax.Array, iters: int, p: int, v: int,
) -> tuple[np.ndarray, float]:
    """The mesh-sharded sweep loop → ``(item factors (V, R) host, final
    loss)``. The interaction matrix is built DIRECTLY into its
    ``P(None, 'tp')`` layout (no single-device staging — the whole point
    is that no device ever holds all of X), the item factors ride
    ``P('tp', None)``, and the padded vocab rows are zero-initialized so
    they stay exactly zero through every sweep (zero interaction columns
    solve to zero rows) and slice off at the end."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import AXIS_TP, round_up

    tp = mesh.shape[AXIS_TP]
    v_pad = round_up(max(v, 1), tp)
    rank = user_init.shape[1]
    build = jax.jit(
        lambda pr, ti: encode.onehot_matrix(
            pr, ti, n_playlists=p, n_tracks=v_pad
        ).astype(jnp.float32),
        out_shardings=NamedSharding(mesh, P(None, AXIS_TP)),
    )
    x_mat = build(
        jnp.asarray(baskets.playlist_rows), jnp.asarray(baskets.track_ids)
    )
    user_f = jax.device_put(
        user_init, NamedSharding(mesh, P(None, None))
    )
    item_padded = np.zeros((v_pad, rank), dtype=np.float32)
    item_padded[:v] = item_init
    item_f = jax.device_put(
        item_padded, NamedSharding(mesh, P(AXIS_TP, None))
    )
    sweep = _sharded_sweep_fn(mesh)
    for _ in range(iters):
        user_f, item_f = sweep(x_mat, user_f, item_f, reg)
    user_host = np.array(jax.device_get(user_f))
    loss = float(_sharded_loss_fn(mesh)(x_mat, user_f, item_f, reg))
    loss += float(reg) * float(np.sum(user_host * user_host))
    item_host = np.array(jax.device_get(item_f))[:v]
    return item_host, loss
