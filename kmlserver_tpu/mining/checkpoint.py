"""Phase-level mining checkpoints — preemption-proofing the batch job.

The reference's GitOps loop literally KILLS the mining Job on every resync
(``Force=true,Replace=true`` pseudo-CronJob), and on TPU node pools the
scheduler preempts batch pods at will — so before this module, any eviction
mid-mine lost all progress (config 4 mines for 78 s; a dead rank hung the
multi-host job forever). The fix is the standard training-stack recipe
(preemption-safe restart is table stakes in ALX / ads-training
infrastructure — PAPERS.md): after each expensive phase the writer rank
persists the phase's host-side payload to the PVC, and a restarted job
resumes from the last completed phase, producing bit-identical final
artifacts.

Correctness is guarded on three axes:

- **fingerprint**: the store is keyed by a sha256 over the mining-relevant
  config fields + the selected dataset's bytes + the rotation index. A
  checkpoint written for a different config or dataset NEVER resumes — the
  whole store self-retires to full recompute (it is stale state, not
  evidence of corruption, so it is deleted rather than quarantined).
- **integrity**: each payload is pickled, written through the shared
  durable writer (``io.artifacts._atomic_write_bytes``: tmp file,
  fsync, ``durable_replace``, transient-EIO retries — ISSUE 19 made
  that writer fsync-before-rename, closing the latent gap where a node
  crash after the rename rebooted into a state.json whose bytes never
  hit disk), and manifested with size + sha256 in the store's
  ``state.json``. Bytes that disagree with the manifest (a torn write,
  bit rot) retire that phase to recompute on the spot.
- **parse strikes**: bytes that VERIFY but fail to unpickle are a poison
  payload (e.g. written corrupt — ``KMLS_FAULT_CKPT_CORRUPT`` fires
  exactly this). One failure could be bad luck; after
  ``quarantine_after`` consecutive failures the file moves to the same
  quarantine dir the serving artifacts use (``io.artifacts
  .quarantine_file``) so restarts stop re-tripping on it and the bytes
  stay inspectable.

Multi-host discipline: every rank READS the store (the completed-phase set
is snapshotted once at job start, so all ranks make the same skip
decisions and the collectives stay aligned); only the writer rank SAVES.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import time
from typing import Any

from .. import faults
from ..config import MiningConfig
from ..io import artifacts
from ..io.artifacts import _atomic_write_bytes, file_digest, quarantine_file

# ordered checkpoint phases of the mining pipeline (mining/pipeline.py):
# encode  — CSV read + vocab/aux artifacts + basket encoding
# mine    — frequent-itemset mining + rule-tensor extraction (the device
#           compute; by far the dominant cost at scale)
# rules   — expansion of the rule tensors into the reference's pickle dict
# embed   — ALS item-embedding training (the second model family; runs —
#           and checkpoints — only with ``embed_enabled``, but keeps its
#           slot in the canonical order so resume bookkeeping and the
#           kill-at-phase chaos matrix cover it like any other phase)
# eval    — offline ranking evaluation (ISSUE 14; runs only with
#           ``eval_enabled``): held-out split + both model families
#           re-trained on the train half + per-mode basket-completion
#           metrics + the blend-weight sweep — the double-train makes it
#           the second-most-expensive phase, exactly what checkpointing
#           exists for; same conditional-slot discipline as `embed`
PHASES = ("encode", "mine", "rules", "embed", "eval")

STATE_FILENAME = "state.json"
# v2: the `embed` phase + ALS fields joined the fingerprint identity
# v3: the model layout joined it (ISSUE 7) — rule emission is layout-exact
#     either way, but the sharded ALS half-sweep's collective reduction
#     order makes the embedding FACTORS float-different across layouts,
#     so a checkpoint written under one layout must never publish under
#     the other; within a layout, resume stays bit-identical
# v4: continuous freshness (ISSUE 10) — the encode payload gained the
#     pid-rank values the delta base state extends, and `delta_enabled`
#     joined the fingerprint: a resume across a delta-enabled flip would
#     publish with (or without) the freshness base state its lineage
#     expects, desynchronizing base ∘ delta from the published artifacts
# v5: sparse ALS storage (ISSUE 13) — `als_sparse` joined the
#     fingerprint for the same reason model_layout did in v3: the
#     compressed half-sweeps' accumulation order makes the factors
#     float-different from the dense sweep's, so a checkpoint trained
#     under one storage mode must never publish under the other. The
#     auto mode's budget-driven resolution rides the checkpointed embed
#     payload itself (like the HBM skip decision always has), so a
#     mid-resume budget change cannot splice storages either.
# v6: quality loop (ISSUE 14) — the `eval` phase + its knobs joined the
#     fingerprint: the phase payload IS the published
#     quality.report.json, so a resume across an eval-config flip would
#     publish a report (or omit one) its lineage doesn't describe.
CKPT_VERSION = 6

# MiningConfig fields that can change the bytes of the final artifacts (or
# of any phase payload). Anything NOT listed — dispatch/backend knobs like
# bitpack_threshold_elems, sharded_impl, native_cpu_pair_counts — selects a
# different route to the SAME exact result (the miner's dominance/exactness
# guarantees), so a checkpoint survives e.g. a TPU-to-CPU restart.
# ``model_layout`` is the one deliberate exception (see v3 note above).
_FINGERPRINT_FIELDS = (
    "model_layout",
    "min_support",
    "sample_ratio",
    "top_tracks_save_percentile",
    "max_itemset_len",
    "k_max_consequents",
    "confidence_mode",
    "min_confidence",
    "prune_vocab_threshold",
    # second model family: toggling the embed phase or its ALS
    # hyperparameters changes the published artifact set, so a checkpoint
    # written under different values must never resume
    "embed_enabled",
    "als_rank",
    "als_iters",
    "als_reg",
    "als_sparse",
    # continuous freshness (ISSUE 10): a delta-enabled run's publication
    # step additionally writes the freshness base state derived from the
    # phase payloads — see the v4 note above
    "delta_enabled",
    # quality loop (ISSUE 14): the eval phase's payload is the published
    # quality report — any knob that changes the split or the metrics
    # changes the published bytes (see the v6 note above)
    "eval_enabled",
    "eval_holdout_n",
    "eval_k",
    "eval_max_playlists",
)


def compute_fingerprint(
    cfg: MiningConfig, dataset_path: str, run_index: int
) -> str:
    """The config+dataset identity a checkpoint is keyed by."""
    ident: dict[str, Any] = {
        "version": CKPT_VERSION,
        "run_index": run_index,
        "dataset": os.path.basename(dataset_path),
        "dataset_digest": file_digest(dataset_path),
    }
    for field in _FINGERPRINT_FIELDS:
        ident[field] = getattr(cfg, field)
    if getattr(cfg, "model_layout", "replicated") != "replicated":
        # the SHARD TOPOLOGY joins the identity for the same reason the
        # layout does: the sharded ALS half-sweep's psum order follows
        # the mesh, so a resume onto a rescaled gang (tp=4 → tp=8) must
        # re-mine rather than splice topology-mixed artifacts. Global
        # device count is identical on every rank of a gang, so all
        # ranks still fingerprint identically. The replicated default
        # deliberately omits it — its compute is device-count-invariant
        # and a TPU↔CPU restart must keep resuming.
        import jax

        ident["shard_topology"] = len(jax.devices())
    blob = json.dumps(ident, sort_keys=True).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


@dataclasses.dataclass
class ResumeInfo:
    """What :meth:`CheckpointStore.load` actually did, for the job log."""

    phase: str
    age_s: float


class CheckpointStore:
    """One mining run's phase checkpoints under ``directory``.

    ``writer=False`` (non-zero ranks of a multi-host job) reads but never
    mutates the shared store — no saves, no retires, no strike counting.
    """

    def __init__(
        self,
        directory: str,
        fingerprint: str,
        quarantine_after: int = 2,
        writer: bool = True,
    ):
        self.directory = directory
        self.fingerprint = fingerprint
        self.quarantine_after = quarantine_after
        self.writer = writer
        self._state = self._load_state()
        # snapshotted ONCE: phases completed by a PREVIOUS incarnation.
        # Mid-run saves are deliberately not re-read — on a multi-host job
        # every rank must make identical skip decisions from identical
        # state, or the collectives desynchronize.
        self.completed: frozenset[str] = frozenset(self._state["phases"])

    # ---------- state file ----------

    def _state_path(self) -> str:
        return os.path.join(self.directory, STATE_FILENAME)

    def _phase_path(self, phase: str) -> str:
        return os.path.join(self.directory, f"{phase}.ckpt")

    def _load_state(self) -> dict[str, Any]:
        empty: dict[str, Any] = {
            "version": CKPT_VERSION,
            "fingerprint": self.fingerprint,
            "phases": {},
        }
        try:
            with open(self._state_path(), "r", encoding="utf-8") as fh:
                state = json.load(fh)
            if not isinstance(state.get("phases"), dict):
                raise ValueError("malformed checkpoint state")
        except FileNotFoundError:
            return empty
        except (OSError, ValueError):
            # unreadable state: nothing in the store can be trusted
            print("Mining checkpoint state unreadable — retiring to full recompute")
            self._retire_all()
            return empty
        if state.get("fingerprint") != self.fingerprint or state.get(
            "version"
        ) != CKPT_VERSION:
            # a different config/dataset/format wrote this: STALE, not
            # corrupt — delete rather than quarantine, recompute fully
            print(
                "Mining checkpoint fingerprint mismatch (config or dataset "
                "changed) — ignoring and retiring the stale checkpoint"
            )
            self._retire_all()
            return empty
        return state

    def _write_state(self) -> None:
        _atomic_write_bytes(
            self._state_path(),
            json.dumps(self._state, indent=1, sort_keys=True).encode("utf-8"),
        )

    def _retire_all(self) -> None:
        if not self.writer:
            return
        try:
            for name in os.listdir(self.directory):
                if name == STATE_FILENAME or name.endswith(".ckpt"):
                    try:
                        os.unlink(os.path.join(self.directory, name))
                    except OSError:
                        pass
        except OSError:
            pass

    def _drop_phase(self, phase: str) -> None:
        """Retire one phase to recompute (torn/rotted bytes). Writer only —
        a reader rank must not mutate the shared store."""
        if not self.writer:
            return
        try:
            os.unlink(self._phase_path(phase))
        except OSError:
            pass
        if self._state["phases"].pop(phase, None) is not None:
            self._write_state()

    # ---------- the phase API ----------

    def load(self, phase: str) -> Any | None:
        """The phase's verified payload, or None → recompute.

        None paths: never completed; digest mismatch (torn/rotted bytes —
        phase retires immediately); unpickle failure (strike; quarantined
        after ``quarantine_after`` consecutive strikes)."""
        if phase not in self.completed:
            return None
        entry = self._state["phases"].get(phase)
        path = self._phase_path(phase)
        if entry is None or not os.path.exists(path):
            return None
        try:
            digest = file_digest(path)
        except OSError:
            return None
        if (
            digest["bytes"] != entry.get("bytes")
            or digest["sha256"] != entry.get("sha256")
        ):
            print(
                f"Checkpoint phase {phase!r} fails its sha256 manifest — "
                "retiring to recompute"
            )
            self._drop_phase(phase)
            return None
        try:
            with open(path, "rb") as fh:
                payload = pickle.load(fh)
        except Exception:
            strikes = int(entry.get("load_failures", 0)) + 1
            if self.writer:
                entry["load_failures"] = strikes
                if self.quarantine_after and strikes >= self.quarantine_after:
                    dest = quarantine_file(path)
                    print(
                        f"Checkpoint phase {phase!r} failed parsing "
                        f"{strikes}x — quarantined to {dest}"
                    )
                    self._state["phases"].pop(phase, None)
                else:
                    print(
                        f"Checkpoint phase {phase!r} failed parsing "
                        f"(strike {strikes}/{self.quarantine_after}) — "
                        "recomputing"
                    )
                self._write_state()
            return None
        return payload

    def age_s(self, phase: str) -> float:
        entry = self._state["phases"].get(phase) or {}
        saved = float(entry.get("saved_at", 0.0))
        return max(time.time() - saved, 0.0) if saved else 0.0

    def duration_s(self, phase: str) -> float:
        """The ORIGINAL compute duration annotated at save time (ISSUE 9:
        the span annotation that lets a resumed job report the compute it
        skipped in job_metrics.prom). 0.0 for checkpoints written before
        the annotation existed — the field is additive, so older stores
        keep resuming."""
        entry = self._state["phases"].get(phase) or {}
        return float(entry.get("duration_s", 0.0))

    def save(
        self, phase: str, payload: Any, duration_s: float | None = None
    ) -> str | None:
        """Persist the phase payload atomically + manifest it. Writer rank
        only (no-op otherwise). ``duration_s`` is the phase's measured
        compute wall clock, carried in the manifest entry as a span
        annotation. The ``ckpt.corrupt`` fault site corrupts
        the BYTES here (digest recorded over the corrupt bytes), modeling
        a writer that silently produced garbage — the next load then
        passes integrity but fails parsing, the two-strike path."""
        if not self.writer:
            return None
        data = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        try:
            faults.fire("ckpt.corrupt")
        except faults.FaultInjected:
            # truncation, not a bit flip: a flipped byte inside a pickled
            # string still parses (to wrong data); a truncated stream
            # deterministically fails to UNPICKLE while its digest —
            # recorded below over the corrupt bytes — still verifies.
            # That is the poison-payload shape the strike path exists for.
            data = data[: max(len(data) // 2, 1)]
        path = self._phase_path(phase)
        _atomic_write_bytes(path, data)
        self._state["phases"][phase] = {
            "bytes": len(data),
            "sha256": hashlib.sha256(data).hexdigest(),
            "saved_at": time.time(),
            "load_failures": 0,
            "duration_s": round(max(float(duration_s or 0.0), 0.0), 6),
        }
        self._write_state()
        return path

    def clear(self) -> None:
        """Retire the whole store after a successful publication — the next
        rotation run mines a different dataset and must start fresh (and a
        SAME-dataset re-run re-mining to a fresh token should re-pay its
        compute rather than silently replaying this run's)."""
        if not self.writer:
            return
        self._retire_all()
        self._state = {
            "version": CKPT_VERSION,
            "fingerprint": self.fingerprint,
            "phases": {},
        }
        self.completed = frozenset()


def open_store(
    cfg: MiningConfig, dataset_path: str, run_index: int, writer: bool
) -> CheckpointStore | None:
    """The pipeline's one constructor: None when checkpointing is off."""
    if not cfg.checkpoint_enabled:
        return None
    directory = cfg.checkpoint_path
    if writer:
        os.makedirs(directory, exist_ok=True)
    elif not os.path.isdir(directory):
        # non-writer before the writer ever created the dir: nothing to
        # resume, and creating it isn't this rank's job
        return None
    return CheckpointStore(
        directory,
        compute_fingerprint(cfg, dataset_path, run_index),
        quarantine_after=cfg.checkpoint_quarantine_after,
        writer=writer,
    )


def heartbeat_dir(cfg: MiningConfig) -> str:
    """Where the dead-rank watchdog's per-rank heartbeat files live —
    under the checkpoint dir so one PVC path owns all resume state."""
    return os.path.join(cfg.checkpoint_path, "heartbeats")


def retired_dirs(cfg: MiningConfig) -> tuple[str, ...]:
    """Checkpoint-side directories whose contents are safe to delete when
    the PVC runs short (``io.artifacts.reclaim_space`` extra_dirs): the
    store's quarantine of corrupt ``.ckpt`` corpses. The LIVE store is
    never offered — deleting it would cost this run its resume state."""
    return (os.path.join(cfg.checkpoint_path, artifacts.QUARANTINE_DIRNAME),)


__all__ = [
    "PHASES",
    "CheckpointStore",
    "compute_fingerprint",
    "open_store",
    "heartbeat_dir",
    "retired_dirs",
]
