"""Measured count-path dispatch: dense vs bitpack vs sparse, by evidence.

``bitpack_threshold_elems`` was ONE heuristic threshold deciding between
TWO families. With the sparse family (ops/sparse.py) there are three,
and the right choice genuinely depends on where the workload sits in
(density, size) space — the dense MXU matmul wins at toy sizes, the
bit-packed popcount wins when the dense operand can't fit, and the
sparse hybrid wins when the matrix is mostly air. COGNATE and Misam
(PAPERS.md) frame exactly this as a *measured or learned* decision
rather than a hand-set constant; this module is the lookup-table form:

- the decision key is the (density band, element-count band) cell of a
  small 2-D grid (:func:`table_cell`);
- the table's cells are POPULATED BY A BENCH SWEEP
  (``mining/sweep.py run_density_sweep`` times all three families per
  cell on the live backend and records the winner + the measured rates),
  banked per backend with provenance (host, device kind, timestamp) —
  the shipped ``dispatch_table.json`` was produced by that sweep and the
  ``scale_sparse`` bench phase re-measures and re-banks it;
- :func:`plan_count_path` consults the table AT PLAN TIME (one O(nnz)
  host bincount for the exact density/pair-event measurement — never a
  distributional guess), and the chosen path + its provenance ride
  ``MiningResult.count_path`` / ``count_path_source`` into job telemetry
  (``kmls_job_count_path`` in job_metrics.prom → the fleet's /metrics);
- the explicit override ``KMLS_COUNT_PATH=dense|bitpack|sparse`` pins a
  family; ANY unrecognized spelling fails SAFE to the measured/legacy
  auto behavior with a loud warning — a typo must never silently change
  which kernel mines production data. ``KMLS_COUNT_PATH=auto`` (the
  default) and a missing/unparseable table likewise degrade to the
  legacy ``bitpack_wanted`` heuristic, so the dispatcher can only ever
  ADD a measured improvement, never subtract the known-good behavior.

Explicit ``bitpack_threshold_elems`` values (an int, or "none"/"never")
keep their historical meaning and BYPASS the table: tests and demos pin
paths with tiny thresholds, and that contract must hold.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os

logger = logging.getLogger("kmlserver_tpu.dispatch")

PATHS = ("dense", "bitpack", "sparse")

# band upper edges; the last band is everything above the final edge.
# Chosen to straddle the regimes the density sweep actually measures:
# >5% (toy/dense), 1-5% (ds2-like), 0.1-1%, 0.01-0.1%, <0.01% (the
# production playlist regime — millions of users, tens-of-track baskets)
DENSITY_EDGES = (0.0001, 0.001, 0.01, 0.05)
ELEMS_EDGES = (1 << 22, 1 << 26, 1 << 30)

TABLE_FILENAME = "dispatch_table.json"
TABLE_VERSION = 1


def _band(value: float, edges: tuple) -> int:
    for i, edge in enumerate(edges):
        if value <= edge:
            return i
    return len(edges)


def table_cell(density: float, elems: float) -> str:
    """The lookup key for a workload: ``"d<i>:e<j>"`` band coordinates."""
    return f"d{_band(density, DENSITY_EDGES)}:e{_band(elems, ELEMS_EDGES)}"


@dataclasses.dataclass(frozen=True)
class CountPlan:
    """One resolved dispatch decision, with its provenance for telemetry."""

    path: str  # "dense" | "bitpack" | "sparse"
    source: str  # "override" | "threshold" | "table" | "heuristic"
    density: float
    elems: int
    cell: str
    # exact Σ k(k-1)/2 over short baskets (None: not measured — sparse
    # was never a candidate for this plan)
    pair_events: int | None = None


def resolve_override(value: str | None) -> str | None:
    """``KMLS_COUNT_PATH`` → a pinned path, or None for auto. The
    fail-safe direction: anything unrecognized behaves exactly like
    auto (the current behavior), loudly."""
    if value in (None, ""):
        return None
    word = str(value).strip().lower()
    if word == "auto":
        return None
    if word in PATHS:
        return word
    logger.warning(
        "KMLS_COUNT_PATH=%r is not one of %s/auto; keeping the measured "
        "auto dispatch (fail-safe)", value, "/".join(PATHS),
    )
    return None


_table_cache: dict[tuple[str, float], dict | None] = {}


def builtin_table_path() -> str:
    return os.path.join(os.path.dirname(__file__), TABLE_FILENAME)


def load_table(path: str | None = None) -> dict | None:
    """The measured dispatch table: ``path`` argument >
    ``KMLS_DISPATCH_TABLE`` env > the packaged bench-banked file. A
    missing or unparseable table is None (plan falls back to the
    heuristic — fail-safe, with a warning for an EXPLICITLY configured
    table only; the packaged file missing is a clean checkout state,
    not an operator error). Cached per (path, mtime)."""
    explicit = path or os.environ.get("KMLS_DISPATCH_TABLE") or None
    resolved = explicit or builtin_table_path()
    try:
        mtime = os.path.getmtime(resolved)
    except OSError:
        if explicit:
            logger.warning(
                "dispatch table %s unreadable; using the heuristic "
                "fallback", resolved,
            )
        return None
    key = (resolved, mtime)
    if key in _table_cache:
        return _table_cache[key]
    try:
        with open(resolved, "rb") as fh:
            table = json.load(fh)
        if table.get("version") != TABLE_VERSION or "backends" not in table:
            raise ValueError(f"unsupported table shape {sorted(table)}")
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        logger.warning(
            "dispatch table %s invalid (%s); using the heuristic fallback",
            resolved, exc,
        )
        table = None
    _table_cache.clear()  # one live entry; stale mtimes must not pile up
    _table_cache[key] = table
    return table


def table_lookup(
    table: dict | None, backend: str, cell: str
) -> dict | None:
    """→ the measured cell record ``{"path": ..., "rows_per_s": {...}}``
    or None when this (backend, cell) was never measured."""
    if not table:
        return None
    backend_entry = table.get("backends", {}).get(backend)
    if not backend_entry:
        return None
    rec = backend_entry.get("cells", {}).get(cell)
    if not isinstance(rec, dict) or rec.get("path") not in PATHS:
        return None
    return rec


def sparse_feasible(
    n_tracks: int,
    pair_events: int | None,
    hbm_budget_bytes: int,
    long_rows: int = 0,
    k_max: int = 256,
    backend: str = "cpu",
) -> bool:
    """Memory-feasibility gate for the sparse family, matching what the
    miner would ACTUALLY run: the fully-sparse count→emit (no ``(V, V)``
    matrix ever) exists only on the CPU route with no long baskets —
    there the plan charges the event stream (keys + sort scratch) plus
    the rule tensors. Every other route (long-basket fallback, and the
    device scatter-add twin any non-CPU backend dispatches) materializes
    the full count matrix, so the matrix plus accumulator transients
    (~16 bytes/cell worst-case) must fit. A plan-time event measurement
    must exist either way. Event COUNT is a speed question, not a
    feasibility one — the measured table owns speed."""
    if pair_events is None:
        return False
    if long_rows or backend != "cpu":
        return 16 * n_tracks * n_tracks <= hbm_budget_bytes
    return (
        32 * pair_events + 8 * k_max * n_tracks <= hbm_budget_bytes
    )


def plan_count_path(
    cfg,
    n_playlists: int,
    n_tracks: int,
    nnz: int,
    *,
    backend: str,
    n_devices: int = 1,
    baskets=None,
    table: dict | None = None,
) -> CountPlan:
    """THE three-family dispatch decision (the seam the miner, the
    support sweep, and the freshness delta recount all resolve through).

    Order: explicit ``KMLS_COUNT_PATH`` override → explicit legacy
    threshold semantics → measured table cell → legacy heuristic (with
    sparse as the new last-resort capability when NEITHER dense-shaped
    formulation fits the budget but the sparse one does).
    """
    from ..ops import sparse as sparse_mod
    from .miner import bitpack_wanted

    density = nnz / max(n_playlists * n_tracks, 1)
    elems = n_playlists * n_tracks
    cell = table_cell(density, elems)
    threshold = getattr(cfg, "bitpack_threshold_elems", "auto")
    budget = getattr(cfg, "hbm_budget_bytes", 12 << 30)
    pair_events: int | None = None
    long_rows = 0
    if baskets is not None:
        pair_events, long_rows = sparse_mod.pair_event_count(
            baskets.playlist_rows, n_playlists,
            getattr(cfg, "sparse_long_basket", 0) or None,
        )
    k_max = getattr(cfg, "k_max_consequents", 256)

    override = resolve_override(getattr(cfg, "count_path", None))
    if override is not None:
        return CountPlan(
            path=override, source="override", density=density,
            elems=elems, cell=cell, pair_events=pair_events,
        )

    if threshold != "auto":
        # the historical explicit contract: an int element count or
        # none/never pins the dense-vs-bitpack decision — tests, demos
        # and deployments that force a path this way keep working
        path = "bitpack" if bitpack_wanted(
            n_playlists, n_tracks, threshold,
            hbm_budget_bytes=budget, n_devices=n_devices,
            n_rows=nnz, backend=backend,
        ) else "dense"
        return CountPlan(
            path=path, source="threshold", density=density,
            elems=elems, cell=cell, pair_events=pair_events,
        )

    rec = table_lookup(
        table if table is not None
        else load_table(getattr(cfg, "dispatch_table", "") or None),
        backend, cell,
    )
    if rec is not None:
        path = rec["path"]
        feasible = True
        if path == "sparse":
            feasible = sparse_feasible(
                n_tracks, pair_events, budget, long_rows, k_max,
                backend=backend,
            )
        elif path == "dense":
            # the table measured small shapes; dense must still FIT here
            feasible = not bitpack_wanted(
                n_playlists, n_tracks, "auto",
                hbm_budget_bytes=budget, n_devices=n_devices, n_rows=nnz,
            )
        if feasible:
            return CountPlan(
                path=path, source="table", density=density,
                elems=elems, cell=cell, pair_events=pair_events,
            )

    # legacy heuristic, unchanged — plus the one new capability: when
    # neither dense-shaped formulation fits the budget but sparse does,
    # mine sparse instead of proceeding toward an allocator failure
    wants_bitpack = bitpack_wanted(
        n_playlists, n_tracks, "auto",
        hbm_budget_bytes=budget, n_devices=n_devices,
        n_rows=nnz, backend=backend,
    )
    path = "bitpack" if wants_bitpack else "dense"
    if wants_bitpack and sparse_feasible(
        n_tracks, pair_events, budget, long_rows, k_max, backend=backend
    ):
        from .miner import bitpack_plan_bytes

        if bitpack_plan_bytes(
            n_playlists, n_tracks, n_devices=n_devices, n_rows=nnz
        ) > budget:
            path = "sparse"
            print(
                "NOTE: neither dense-shaped formulation fits the HBM "
                "budget but the sparse event stream does "
                f"({pair_events} pair events) — mining SPARSE instead "
                "of risking the allocator failure warned above"
            )
    return CountPlan(
        path=path, source="heuristic", density=density,
        elems=elems, cell=cell, pair_events=pair_events,
    )


def table_from_records(
    records: list[dict],
    backend: str,
    *,
    measured_on: str,
    banked_at: float,
    base: dict | None = None,
) -> dict:
    """Fold density-sweep records (``mining/sweep.py run_density_sweep``:
    one record per measured (density, shape) point with per-path
    ``mine_s`` timings) into a dispatch table, merging over ``base`` so
    successive bench rounds accumulate cells per backend exactly like
    the bench bank merges brackets. The winner of a cell measured twice
    is the NEWER measurement (same newest-wins rule as the bank)."""
    table: dict = {
        "version": TABLE_VERSION,
        "banked_at": banked_at,
        "density_edges": list(DENSITY_EDGES),
        "elems_edges": list(ELEMS_EDGES),
        "backends": {},
    }
    if base and base.get("version") == TABLE_VERSION:
        for b, entry in base.get("backends", {}).items():
            table["backends"][b] = {
                "measured_on": entry.get("measured_on", ""),
                "banked_at": entry.get("banked_at", 0.0),
                "cells": dict(entry.get("cells", {})),
            }
    entry = table["backends"].setdefault(
        backend, {"measured_on": measured_on, "banked_at": banked_at, "cells": {}}
    )
    entry["measured_on"] = measured_on
    entry["banked_at"] = banked_at
    for rec in records:
        timings = {
            p: rec[f"{p}_s"]
            for p in PATHS
            if rec.get(f"{p}_s") is not None
        }
        if not timings:
            continue
        winner = min(timings, key=timings.get)
        entry["cells"][table_cell(rec["density"], rec["elems"])] = {
            "path": winner,
            "rows_per_s": {
                p: round(rec["rows"] / s, 1) for p, s in timings.items() if s > 0
            },
            "shape": rec.get("shape", ""),
            "identical": rec.get("identical"),
        }
    return table


def save_table(path: str, table: dict) -> None:
    """Persist a measured table (atomic, like every artifact write)."""
    from ..io.artifacts import atomic_write_text

    atomic_write_text(path, json.dumps(table, indent=1, sort_keys=True) + "\n")
