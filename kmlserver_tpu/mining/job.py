"""Container entrypoint for the batch mining job.

Run as ``python -m kmlserver_tpu.mining.job`` — the rebuild's equivalent of
the reference job image's ``CMD uv run main.py``
(reference: machine-learning/Dockerfile:10, machine-learning/main.py:421-484).
Configured entirely by environment variables (kubernetes/job.yaml contract).

Exit-code contract (kubernetes/job.yaml podFailurePolicy binds it):

- ``0``  — success (the reference's ``sys.exit(0)``, main.py:484).
- ``64`` (EXIT_FATAL_CONFIG) — the job can NEVER succeed as configured:
  bad env (rank >= world size, malformed mesh shape), no datasets on the
  PVC, invalid dataset content. Retrying burns TPU quota for the same
  failure, so the Job's podFailurePolicy fails the whole Job on it.
- ``75`` (EXIT_RESUMABLE, EX_TEMPFAIL) — transient abort: an injected
  preemption-style crash, the publication lease held/lost to another
  writer, or the PVC out of space even after reclamation
  (``StorageExhaustedError`` / ENOSPC — retention frees space, then a
  retry resumes). A retry resumes from the phase checkpoint; podFailurePolicy
  Ignores it (does not count against backoffLimit — a preempted pod is
  not a crashing pod).
- ``76`` (EXIT_RANK_DEAD) — the dead-rank watchdog bounded a multi-host
  hang (peer heartbeats stale, or a collective blocked past
  KMLS_RANK_TIMEOUT_S). Also resumable: the replacement gang restarts
  from the checkpoint.
- anything else (``1``) — an unclassified crash; counted against
  ``backoffLimit`` as usual.
"""

from __future__ import annotations

import sys
import traceback

from ..config import MiningConfig
from .pipeline import run_mining_job

EXIT_OK = 0
EXIT_FATAL_CONFIG = 64  # EX_USAGE: retrying cannot help
EXIT_RESUMABLE = 75  # EX_TEMPFAIL: retry resumes from the checkpoint
EXIT_RANK_DEAD = 76  # EX_PROTOCOL: watchdog-bounded multi-host hang

# the codes a k8s retry can make progress on (job.yaml podFailurePolicy)
RETRYABLE_EXIT_CODES = (EXIT_RESUMABLE, EXIT_RANK_DEAD)


def classify_exception(exc: BaseException) -> int:
    """Map an abort to the exit-code contract above. The ONE policy
    deciding what k8s should retry."""
    import errno

    from .. import faults
    from ..io.artifacts import (
        LeaseHeldError,
        LeaseLostError,
        StorageExhaustedError,
    )
    from .vocab import DuplicateArtistURIError

    if isinstance(exc, faults.FaultInjected):
        return EXIT_RESUMABLE  # the chaos stand-in for a preemption
    if isinstance(exc, (LeaseHeldError, LeaseLostError)):
        # another writer is live (or superseded us): back off and retry —
        # by then the holder has finished or its lease expired
        return EXIT_RESUMABLE
    if isinstance(exc, StorageExhaustedError) or (
        isinstance(exc, OSError) and exc.errno == errno.ENOSPC
    ):
        # disk full is an OPERATOR condition, not a config bug: reclaim/
        # retention frees space and a retry resumes from the checkpoint.
        # Must precede the FileNotFoundError branch — both are OSErrors.
        return EXIT_RESUMABLE
    if isinstance(exc, (DuplicateArtistURIError, ValueError, FileNotFoundError)):
        # bad config/env/data: the same inputs fail the same way forever
        return EXIT_FATAL_CONFIG
    return 1


def main() -> int:
    # join the multi-host runtime when configured (no-op single-process);
    # must precede the first device access
    from ..parallel.distributed import (
        RankWatchdog,
        distributed_env,
        maybe_initialize,
    )

    watchdog = None
    try:
        distributed = maybe_initialize()
        cfg = MiningConfig.from_env()
        # persistent XLA compilation cache (PVC-backed via KMLS_JAX_CACHE_DIR):
        # the pseudo-CronJob re-runs this container every ~20 min and would
        # otherwise re-pay every jit compile each run. AFTER from_env so the
        # knob honors .env like every other KMLS_ variable; before any jit.
        from ..utils.jaxcache import enable_compilation_cache

        enable_compilation_cache()
        from ..parallel.distributed import resolve_mesh

        if distributed and cfg.rank_timeout_s > 0:
            from .checkpoint import heartbeat_dir

            _, num_processes, process_id = distributed_env()
            watchdog = RankWatchdog(
                heartbeat_dir(cfg),
                rank=process_id,
                num_processes=num_processes,
                heartbeat_interval_s=cfg.rank_heartbeat_interval_s,
                timeout_s=cfg.rank_timeout_s,
                collective_timeout_s=cfg.collective_timeout_s or None,
                exit_code=EXIT_RANK_DEAD,
            )
            watchdog.start()

        run_mining_job(
            cfg,
            mesh=resolve_mesh(cfg.mesh_shape, distributed=distributed),
            watchdog=watchdog,
        )
        return EXIT_OK
    except Exception as exc:
        code = classify_exception(exc)
        traceback.print_exc()
        kind = "resumable" if code in RETRYABLE_EXIT_CODES else (
            "fatal-config" if code == EXIT_FATAL_CONFIG else "unclassified"
        )
        print(f"Job aborted ({kind}): exiting {code}", flush=True)
        return code
    finally:
        if watchdog is not None:
            watchdog.stop()


if __name__ == "__main__":
    sys.exit(main())
