"""Container entrypoint for the batch mining job.

Run as ``python -m kmlserver_tpu.mining.job`` — the rebuild's equivalent of
the reference job image's ``CMD uv run main.py``
(reference: machine-learning/Dockerfile:10, machine-learning/main.py:421-484).
Configured entirely by environment variables (kubernetes/job.yaml contract);
exits 0 on success like the reference's ``sys.exit(0)`` (main.py:484).
"""

from __future__ import annotations

import sys

from ..config import MiningConfig
from .pipeline import run_mining_job


def main() -> int:
    # join the multi-host runtime when configured (no-op single-process);
    # must precede the first device access
    from ..parallel.distributed import maybe_initialize

    distributed = maybe_initialize()
    cfg = MiningConfig.from_env()
    # persistent XLA compilation cache (PVC-backed via KMLS_JAX_CACHE_DIR):
    # the pseudo-CronJob re-runs this container every ~20 min and would
    # otherwise re-pay every jit compile each run. AFTER from_env so the
    # knob honors .env like every other KMLS_ variable; before any jit.
    from ..utils.jaxcache import enable_compilation_cache

    enable_compilation_cache()
    from ..parallel.distributed import resolve_mesh

    run_mining_job(
        cfg, mesh=resolve_mesh(cfg.mesh_shape, distributed=distributed)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
