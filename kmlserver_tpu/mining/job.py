"""Container entrypoint for the batch mining job.

Run as ``python -m kmlserver_tpu.mining.job`` — the rebuild's equivalent of
the reference job image's ``CMD uv run main.py``
(reference: machine-learning/Dockerfile:10, machine-learning/main.py:421-484).
Configured entirely by environment variables (kubernetes/job.yaml contract);
exits 0 on success like the reference's ``sys.exit(0)`` (main.py:484).
"""

from __future__ import annotations

import sys

from ..config import MiningConfig
from .pipeline import run_mining_job


def main() -> int:
    cfg = MiningConfig.from_env()
    mesh = None
    if cfg.mesh_shape in ("", "1x1"):
        pass  # explicit single-device
    elif cfg.mesh_shape == "auto":
        import jax

        if len(jax.devices()) > 1:  # default: shard over every chip present
            from ..parallel.mesh import make_mesh

            mesh = make_mesh("auto")
    else:
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(cfg.mesh_shape)
    run_mining_job(cfg, mesh=mesh)
    return 0


if __name__ == "__main__":
    sys.exit(main())
