"""Container entrypoint for the batch mining job.

Run as ``python -m kmlserver_tpu.mining.job`` — the rebuild's equivalent of
the reference job image's ``CMD uv run main.py``
(reference: machine-learning/Dockerfile:10, machine-learning/main.py:421-484).
Configured entirely by environment variables (kubernetes/job.yaml contract);
exits 0 on success like the reference's ``sys.exit(0)`` (main.py:484).
"""

from __future__ import annotations

import sys

from ..config import MiningConfig
from .pipeline import run_mining_job


def main() -> int:
    # join the multi-host runtime when configured (no-op single-process);
    # must precede the first device access
    from ..parallel.distributed import maybe_initialize

    distributed = maybe_initialize()
    cfg = MiningConfig.from_env()
    # persistent XLA compilation cache (PVC-backed via KMLS_JAX_CACHE_DIR):
    # the pseudo-CronJob re-runs this container every ~20 min and would
    # otherwise re-pay every jit compile each run. AFTER from_env so the
    # knob honors .env like every other KMLS_ variable; before any jit.
    from ..utils.jaxcache import enable_compilation_cache

    enable_compilation_cache()
    mesh = None
    if cfg.mesh_shape in ("", "1x1"):
        pass  # explicit single-device
    elif cfg.mesh_shape.startswith("hybrid"):
        # "hybrid" or "hybrid:tpN" — DCN×ICI layout (tp pinned intra-host);
        # anything else hybrid-shaped is a config error, fail fast
        from ..parallel.distributed import make_hybrid_mesh

        if cfg.mesh_shape == "hybrid":
            mesh = make_hybrid_mesh()
        elif cfg.mesh_shape.startswith("hybrid:tp") and cfg.mesh_shape[9:].isdigit():
            mesh = make_hybrid_mesh(tp=int(cfg.mesh_shape[9:]))
        else:
            raise ValueError(
                f"mesh shape must be 'hybrid' or 'hybrid:tpN', got {cfg.mesh_shape!r}"
            )
    elif cfg.mesh_shape == "auto":
        import jax

        if distributed:
            # multi-host: the hybrid layout is the only correct default —
            # the tp block-exchange axis must ride ICI, never DCN
            from ..parallel.distributed import make_hybrid_mesh

            mesh = make_hybrid_mesh()
        elif len(jax.devices()) > 1:  # default: shard over every chip present
            from ..parallel.mesh import make_mesh

            mesh = make_mesh("auto")
    else:
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(cfg.mesh_shape)
    run_mining_job(cfg, mesh=mesh)
    return 0


if __name__ == "__main__":
    sys.exit(main())
