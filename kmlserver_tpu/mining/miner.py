"""Device mining driver: baskets → rule tensors.

The TPU replacement for the reference's mlxtend call + expansion loops
(reference: machine-learning/main.py:262-313): encode memberships on device,
one MXU matmul for pair supports, threshold + top-k emission. Exact — not an
approximation — per the dominance argument in ``ops/support.py``.

Config wiring:
- ``cfg.confidence_mode`` selects the reference fast path's
  support-as-confidence semantics (``"support"``) or the dormant slow
  path's true asymmetric confidence (``"confidence"``,
  machine-learning/main.py:224-260).
- ``cfg.max_itemset_len`` ≥ 3 additionally computes a frequent-itemset
  census (per-length counts, exact via pair extension) — the reference's
  log surface reports itemset statistics; ≥ 4 is not yet enumerated and is
  reported as such rather than silently ignored.
- ``cfg.bitpack_threshold_elems``: above this one-hot size the bit-packed
  popcount path (Pallas) will take over; until that kernel lands the driver
  WARNS and uses the dense path rather than silently pretending.

Timing: the reference brackets rule generation with wall-clock timestamps and
prints the elapsed time (machine-learning/main.py:264,306-308); ``mine`` does
the same with ``block_until_ready`` so device work is actually inside the
bracket.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MiningConfig
from ..ops import encode, rules, support
from .vocab import Baskets


@dataclasses.dataclass
class MiningResult:
    tensors: rules.RuleTensors
    n_playlists: int
    n_tracks: int
    duration_s: float
    itemset_census: dict[int, int] | None = None  # length → frequent-itemset count


def pair_count_fn(
    baskets: Baskets, mesh: "jax.sharding.Mesh | None" = None
) -> tuple[jax.Array, jax.Array | None]:
    """One-hot encode + pair-support count, single device or sharded.

    Returns ``(counts, x_onehot_or_None)`` — the one-hot matrix is handed
    back on the single-device path so downstream steps (itemset census)
    reuse it instead of re-encoding; on the sharded path the full matrix
    deliberately never exists on one device (that's the point of sharding),
    so ``None`` is returned.
    """
    if mesh is not None:
        from ..parallel.support import sharded_pair_counts

        return sharded_pair_counts(baskets, mesh), None
    x = encode.onehot_matrix(
        jnp.asarray(baskets.playlist_rows),
        jnp.asarray(baskets.track_ids),
        n_playlists=baskets.n_playlists,
        n_tracks=baskets.n_tracks,
    )
    return support.pair_counts(x), x


def _itemset_census(
    x: jax.Array | None,
    counts: jax.Array,
    min_count: int,
    max_len: int,
    pair_capacity: int = 1 << 16,
) -> dict[int, int]:
    """Exact frequent-itemset counts per length (1, 2, and — via pair
    extension on the MXU over the already-built one-hot ``x`` — 3). Lengths
    beyond 3, and length 3 when ``x`` isn't materialized (sharded mining),
    are reported as -1 (not enumerated) rather than silently dropped."""
    item_counts = np.asarray(jnp.diagonal(counts))
    census = {1: int((item_counts >= min_count).sum())}
    if max_len < 2:
        return census
    pair_i, pair_j, _, n_pairs = support.frequent_pairs(
        counts, jnp.int32(min_count), capacity=pair_capacity
    )
    n_pairs = int(n_pairs)
    census[2] = n_pairs
    if max_len < 3:
        return census
    if n_pairs > pair_capacity or x is None:
        census[3] = -1  # capacity overflow / sharded x: report honestly
        return census
    t = support.triple_counts(x, jnp.where(pair_i >= 0, pair_i, 0), jnp.where(pair_j >= 0, pair_j, 0))
    t = np.asarray(t)
    pi, pj = np.asarray(pair_i), np.asarray(pair_j)
    valid_rows = pi >= 0
    v = t.shape[1]
    k_ids = np.arange(v)[None, :]
    # a triple {i,j,k} is counted once per frequent (i,j) with k > j > i:
    # restrict to k > j to avoid double counting across its three pairs
    mask = valid_rows[:, None] & (k_ids > pj[:, None]) & (t >= min_count)
    census[3] = int(mask.sum())
    if max_len > 3:
        census[max_len] = -1
    return census


def mine(
    baskets: Baskets,
    cfg: MiningConfig,
    mesh: "jax.sharding.Mesh | None" = None,
) -> MiningResult:
    """Run the full mining compute, timed like the reference's rule step."""
    onehot_elems = baskets.n_playlists * baskets.n_tracks
    if mesh is None and onehot_elems > cfg.bitpack_threshold_elems:
        print(
            f"WARNING: one-hot matrix has {onehot_elems:.2e} elements "
            f"(> KMLS_BITPACK_THRESHOLD_ELEMS={cfg.bitpack_threshold_elems:.2e}); "
            f"the bit-packed popcount path is not yet wired — using dense int8"
        )
    t0 = time.perf_counter()
    counts, x = pair_count_fn(baskets, mesh)
    jax.block_until_ready(counts)
    tensors = rules.mine_rules_from_counts(
        counts,
        n_playlists=baskets.n_playlists,
        min_support=cfg.min_support,
        k_max=cfg.k_max_consequents,
        mode=cfg.confidence_mode,
        min_confidence=cfg.min_confidence,
    )
    duration = time.perf_counter() - t0
    census = None
    if cfg.max_itemset_len >= 3:
        census = _itemset_census(x, counts, tensors.min_count, cfg.max_itemset_len)
    return MiningResult(
        tensors=tensors,
        n_playlists=baskets.n_playlists,
        n_tracks=baskets.n_tracks,
        duration_s=duration,
        itemset_census=census,
    )
