"""Device mining driver: baskets → rule tensors.

The TPU replacement for the reference's mlxtend call + expansion loops
(reference: machine-learning/main.py:262-313): encode memberships on device,
one MXU matmul for pair supports, threshold + top-k emission. Exact — not an
approximation — per the dominance argument in ``ops/support.py``.

Config wiring:
- ``cfg.confidence_mode`` selects the reference fast path's
  support-as-confidence semantics (``"support"``) or the dormant slow
  path's true asymmetric confidence (``"confidence"``,
  machine-learning/main.py:224-260).
- ``cfg.max_itemset_len`` ≥ 3 additionally computes a frequent-itemset
  census (per-length counts, exact via MXU pair→triple→quad extension up
  to length 4; ≥ 5 is reported as not enumerated rather than silently
  ignored), and in confidence mode merges the multi-antecedent rules those
  itemsets imply (see ops/rules.py merge_confidence_contributions).
- ``cfg.bitpack_threshold_elems``: selects when the bit-packed Pallas
  popcount path (ops/popcount.py) replaces the dense int8 matmul — 32×
  denser in HBM, exact. ``"auto"`` (default) dispatches on estimated HBM
  footprint via :func:`bitpack_wanted`: the MXU matmul wins by an order of
  magnitude whenever the dense operands fit, so bitpack is reserved for
  shapes that genuinely don't (true config-4 scale).
- ``cfg.prune_vocab_threshold``: above this vocabulary size, infrequent
  items are pruned before pair counting (exact by the Apriori property) —
  the step that makes 1M-track vocabularies feasible.

Timing: the reference brackets rule generation with wall-clock timestamps and
prints the elapsed time (machine-learning/main.py:264,306-308); ``mine`` does
the same with ``block_until_ready`` so device work is actually inside the
bracket.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MiningConfig
from ..ops import cpu_popcount, encode, rules, support
from ..parallel import layout as layout_mod
from ..utils.profiling import PhaseTimer, trace_session
from .vocab import Baskets, Vocab


@dataclasses.dataclass
class MiningResult:
    tensors: rules.RuleTensors
    # names for the tensor rows — the (possibly Apriori-pruned) vocabulary
    vocab_names: list[str]
    n_playlists: int
    n_tracks: int  # full dataset unique-track count (pre-pruning)
    duration_s: float
    pruned_vocab: int | None = None  # size after pruning, when it ran
    itemset_census: dict[int, int] | None = None  # length → frequent-itemset count
    phase_timings: dict[str, float] | None = None  # profiling detail (§5)
    # confidence mode with max_itemset_len >= 3: True when the triple-rule
    # merge ran, False when it had to be skipped (confidences pairwise-only),
    # None when not applicable
    triple_merge_applied: bool | None = None
    # which pair-count route ran: "native-cpu", "dense-fused",
    # "sparse-hybrid", "sparse-sharded", or (staged branch, straight from
    # pair_count_fn) "dense", "bitpack-mxu", "bitpack-vpu",
    # "sharded-bitpack", "sharded-dense-<impl>"
    count_path: str | None = None
    # how the dispatch decided (mining/dispatch.py CountPlan.source:
    # override/threshold/table/heuristic) — provenance for job telemetry
    count_path_source: str | None = None
    # exact pair-event count the sparse plan measured (None: not measured)
    sparse_events: int | None = None


def bitpack_plan_bytes(
    n_playlists: int,
    n_tracks: int,
    *,
    n_devices: int = 1,
    n_rows: int = 0,
) -> int:
    """Planned per-device bytes of the bit-packed formulation: bitset
    slab (word axis sharded over dp) + int32 counts with top-k scratch +
    one unpacked int8 slab (the mxu impl's per-scan-step intermediate) +
    membership operands. THE one copy of this footprint — the dispatch
    heuristic (mining/dispatch.py) and :func:`bitpack_wanted` must agree
    on what 'bitpack fits' means or the sparse rescue mis-fires."""
    from ..ops import popcount as pc

    v_pad, w_pad = pc.padded_shape(n_tracks, n_playlists)
    return (
        v_pad * w_pad * 4 // max(n_devices, 1)
        + 8 * v_pad * v_pad
        + v_pad * pc.word_chunk() * 32
        + 8 * n_rows // max(n_devices, 1)
    )


def bitpack_wanted(
    n_playlists: int,
    n_tracks: int,
    threshold: int | str | None,
    *,
    hbm_budget_bytes: int = 12 << 30,
    n_devices: int = 1,
    n_rows: int = 0,
    backend: str | None = None,
) -> bool:
    """The ONE bitpack-vs-dense dispatch decision (single-chip and sharded).

    - ``threshold == "auto"``: bitpack when the dense formulation's
      planned HBM — the int8 one-hot (sharded over ``n_devices``) plus the
      int32 count matrix and an equal-size top-k scratch (replicated) —
      exceeds ``hbm_budget_bytes`` per device. On the TPU backend that
      memory-fit rule is the whole decision (the MXU matmul beats the VPU
      popcount kernel by an order of magnitude whenever its operands fit);
      on non-TPU backends (``backend`` given and != "tpu") a SPEED rule
      also applies: above ~64M one-hot elements the 32×-compressed bitset
      operand streams through cache where the dense one thrashes it —
      measured 1.1 s vs 43 s on XLA:CPU at 100k×2k — so bitpack wins even
      though dense fits. Callers that only ask "does dense FIT?" (the
      census override in ``mine``) pass ``backend=None``.
    - ``threshold`` an int: the explicit element-count semantic (tests and
      demos use tiny values to force a path).
    - ``threshold is None`` (or ``"none"``/``"never"``, the env spellings):
      never bitpack.
    """
    if isinstance(threshold, str):
        if threshold == "auto":
            # one-hot (sharded) + count/top-k matrices (replicated) + the
            # int32 membership operands that coexist with the one-hot
            # during the encode scatter — data-proportional terms only;
            # the budget's headroom covers XLA workspace, not operands
            dense_bytes = (
                n_playlists * n_tracks // max(n_devices, 1)
                + 8 * n_tracks * n_tracks
                + 8 * n_rows // max(n_devices, 1)
            )
            if dense_bytes > hbm_budget_bytes:
                # the bitpack route is the fallback, not a guarantee:
                # check ITS footprint too (bitpack_plan_bytes — shared
                # with the dispatch heuristic) and warn loudly when
                # NEITHER formulation fits, so an impending allocator
                # failure is diagnosable before the opaque OOM (ADVICE r3)
                bitpack_bytes = bitpack_plan_bytes(
                    n_playlists, n_tracks,
                    n_devices=n_devices, n_rows=n_rows,
                )
                if bitpack_bytes > hbm_budget_bytes:
                    print(
                        "WARNING: neither the dense one-hot "
                        f"(~{dense_bytes / (1 << 30):.1f} GiB) nor the "
                        f"bit-packed path (~{bitpack_bytes / (1 << 30):.1f} "
                        "GiB: bitset + counts + unpack slab) fits the "
                        f"{hbm_budget_bytes / (1 << 30):.1f} GiB HBM budget "
                        f"per device (x{max(n_devices, 1)}); proceeding "
                        "bit-packed but expect an allocator failure — "
                        "shard over more devices or raise min_support to "
                        "shrink the frequent vocabulary"
                    )
                return True
            return (
                backend is not None
                and backend != "tpu"
                and n_playlists * n_tracks // max(n_devices, 1) > 1 << 26
            )
        if threshold in ("none", "never"):
            return False
        raise ValueError(
            f"bitpack threshold must be 'auto', 'none'/'never', None, or an "
            f"element count, got {threshold!r}"
        )
    if threshold is None:
        return False
    return n_playlists * n_tracks > threshold


def pair_count_fn(
    baskets: Baskets,
    mesh: "jax.sharding.Mesh | None" = None,
    bitpack_threshold_elems: int | str | None = None,
    sharded_impl: str = "gspmd",
    hbm_budget_bytes: int = 12 << 30,
) -> tuple[jax.Array, jax.Array | None, str]:
    """One-hot encode + pair-support count: sharded, bit-packed, or dense.

    Returns ``(counts, x_onehot_or_None, path)`` — the one-hot matrix is
    handed back on the dense single-device path so downstream steps
    (itemset census) reuse it instead of re-encoding; on the sharded and
    bit-packed paths the full int8 matrix deliberately never exists
    (that's their point), so ``None`` is returned. ``path`` names the
    route that actually ran (``"dense"``, ``"bitpack-mxu"``,
    ``"bitpack-vpu"``, ``"sharded-bitpack"``, ``"sharded-dense-<impl>"``)
    — the ONE source for ``MiningResult.count_path``, so artifacts can
    never desynchronize from the dispatch.
    """
    if mesh is not None:
        if bitpack_wanted(
            baskets.n_playlists, baskets.n_tracks, bitpack_threshold_elems,
            hbm_budget_bytes=hbm_budget_bytes, n_devices=mesh.devices.size,
            n_rows=len(baskets.playlist_rows),
            backend=jax.default_backend(),
        ):
            # config-4 scale: bit-packed slabs sharded over dp, per-chip
            # counts from the bitset slab, psum over ICI. The bitpack impl
            # shards the word axis over dp ONLY — on a dp×tp mesh the tp
            # chips would each redundantly hold the full per-host slab
            # (per-chip memory O(V·P/(32·dp)) instead of
            # O(V·P/(32·n_chips))), so flatten every device onto dp first.
            from ..ops.popcount import resolve_counts_impl
            from ..parallel.mesh import AXIS_TP, make_mesh
            from ..parallel.support import sharded_bitpack_pair_counts

            if mesh.shape.get(AXIS_TP, 1) > 1:
                mesh = make_mesh(
                    "auto", devices=list(mesh.devices.flatten())
                )
            # same backend gating as the single-device branch below: the
            # env-selected impl applies on TPU; off-TPU pin the pure-XLA
            # mxu impl so a TPU-targeted KMLS_BITPACK_IMPL=vpu can never
            # put a CPU mesh run into interpreted-Pallas territory
            impl = (
                resolve_counts_impl()
                if jax.default_backend() == "tpu"
                else "mxu"
            )
            return (
                sharded_bitpack_pair_counts(baskets, mesh, impl=impl), None,
                "sharded-bitpack",
            )
        from ..parallel.support import sharded_pair_counts

        return (
            sharded_pair_counts(baskets, mesh, impl=sharded_impl), None,
            f"sharded-dense-{sharded_impl}",
        )
    if bitpack_wanted(
        baskets.n_playlists, baskets.n_tracks, bitpack_threshold_elems,
        hbm_budget_bytes=hbm_budget_bytes, n_rows=len(baskets.playlist_rows),
        backend=jax.default_backend(),
    ):
        from ..ops.popcount import popcount_pair_counts, resolve_counts_impl

        # off-TPU the Pallas VPU kernel would run in Python-level
        # interpreter mode — a massive perf cliff on exactly the large
        # inputs this path targets — but the MXU unpack-matmul impl is
        # pure XLA and compiles on every backend, so the bitset path (and
        # its 32× memory saving) is available everywhere; only the kernel
        # choice is backend-gated
        impl = (
            resolve_counts_impl()
            if jax.default_backend() == "tpu"
            else "mxu"
        )
        counts = popcount_pair_counts(
            baskets.playlist_rows, baskets.track_ids,
            n_playlists=baskets.n_playlists, n_tracks=baskets.n_tracks,
            impl=impl,
        )
        return counts, None, f"bitpack-{impl}"
    x = encode.onehot_matrix(
        jnp.asarray(baskets.playlist_rows),
        jnp.asarray(baskets.track_ids),
        n_playlists=baskets.n_playlists,
        n_tracks=baskets.n_tracks,
    )
    return support.pair_counts(x), x, "dense"


def native_cpu_eligible(cfg: MiningConfig, mesh=None) -> bool:
    """True when the native POPCNT fallback carries pair counting: CPU
    backend, single device, and no downstream step (itemset census,
    triple/quad extensions) needing device intermediates. May trigger the
    one-time native build — call OUTSIDE any timed bracket. The ONE copy
    of this gate — the sweep harness must stay in lockstep with the miner."""
    return (
        mesh is None
        and cfg.max_itemset_len < 3
        and cfg.native_cpu_pair_counts
        and jax.default_backend() == "cpu"
        and cpu_popcount.available()
    )


def native_pair_counts(baskets: Baskets) -> np.ndarray:
    """The native counter invoked exactly as the miner invokes it."""
    return cpu_popcount.pair_counts(
        baskets.playlist_rows, baskets.track_ids,
        n_playlists=baskets.n_playlists, n_tracks=baskets.n_tracks,
    )


PAIR_CAPACITY = 1 << 16


def compute_triple_extension(
    x: jax.Array,
    counts: jax.Array,
    min_count: int,
    pair_capacity: int = PAIR_CAPACITY,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, int] | None:
    """Frequent pairs + their triple extensions, computed ONCE and shared by
    the itemset census and the confidence-mode triple-rule merge.

    → ``(pair_i, pair_j, pair_counts, triple_counts, n_pairs)`` as host
    arrays, or None when the frequent-pair count overflows ``pair_capacity``
    (reported honestly by the caller rather than silently truncated)."""
    pair_i, pair_j, pair_counts, n_pairs = support.frequent_pairs(
        counts, jnp.int32(min_count), capacity=pair_capacity
    )
    n_pairs = int(n_pairs)
    if n_pairs > pair_capacity:
        return None
    t = support.triple_counts(
        x, jnp.where(pair_i >= 0, pair_i, 0), jnp.where(pair_j >= 0, pair_j, 0)
    )
    return (
        np.asarray(pair_i),
        np.asarray(pair_j),
        np.asarray(pair_counts),
        np.asarray(t),
        n_pairs,
    )


TRIPLE_CAPACITY = 1 << 16


def frequent_triples_from_extension(
    triple_data: tuple, min_count: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Unique frequent triples (i < j < k) + their supports, extracted from
    the pair→triple extension. Each triple appears under exactly one pair
    row (its (i, j) with k > j), so restricting to k > j dedups across the
    three pair rows that could generate it."""
    pi, pj, _, t, _ = triple_data
    valid = pi >= 0
    v = t.shape[1]
    k_ids = np.arange(v)[None, :]
    mask = valid[:, None] & (k_ids > pj[:, None]) & (t >= min_count)
    e_idx, k_idx = np.nonzero(mask)
    return (
        pi[e_idx].astype(np.int32),
        pj[e_idx].astype(np.int32),
        k_idx.astype(np.int32),
        t[e_idx, k_idx].astype(np.int32),
    )


def compute_quad_extension(
    x: jax.Array,
    triple_data: tuple,
    min_count: int,
    capacity: int = TRIPLE_CAPACITY,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray] | None:
    """Frequent triples + their quad extensions:
    ``(ti, tj, tk, triple_supports, quad_counts (E3, V))`` as host arrays,
    or None when the frequent-triple count exceeds ``capacity``. Triple
    index arrays are padded to a multiple of 1024 (-1 sentinels) so the jit
    shape set stays bounded across runs."""
    ti, tj, tk, tc = frequent_triples_from_extension(triple_data, min_count)
    n = len(ti)
    if n > capacity:
        return None
    if n == 0:
        return ti, tj, tk, tc, np.zeros((0, x.shape[1]), np.int32)
    padded = ((n + 1023) // 1024) * 1024
    pad = padded - n
    ti_p = np.concatenate([ti, np.full(pad, -1, np.int32)])
    tj_p = np.concatenate([tj, np.zeros(pad, np.int32)])
    tk_p = np.concatenate([tk, np.zeros(pad, np.int32)])
    tc_p = np.concatenate([tc, np.zeros(pad, np.int32)])
    q = support.quad_counts(
        x,
        jnp.where(jnp.asarray(ti_p) >= 0, jnp.asarray(ti_p), 0),
        jnp.asarray(tj_p),
        jnp.asarray(tk_p),
    )
    return ti_p, tj_p, tk_p, tc_p, np.asarray(q)


def _itemset_census(
    counts: jax.Array,
    min_count: int,
    max_len: int,
    triple_data: tuple | None,
    n_pairs: int | None,
    quad_data: tuple | None = None,
) -> dict[int, int]:
    """Exact frequent-itemset counts per length (1, 2, and — via the shared
    triple extension — 3). Lengths beyond 3, and length 3 when the extension
    isn't available (sharded mining / capacity overflow), are reported as -1
    (not enumerated) rather than silently dropped."""
    item_counts = np.asarray(jnp.diagonal(counts))
    census = {1: int((item_counts >= min_count).sum())}

    def finish(first_unenumerated: int) -> dict[int, int]:
        # EVERY non-enumerated length gets an explicit -1, never a missing key
        for length in range(first_unenumerated, max_len + 1):
            census[length] = -1
        return census

    if max_len < 2:
        return census
    if n_pairs is None:
        n_pairs = int(
            support.frequent_pairs(
                counts, jnp.int32(min_count), capacity=1
            )[3]
        )
    census[2] = n_pairs
    if max_len < 3:
        return census
    if triple_data is None:
        return finish(3)  # capacity overflow / sharded x: report honestly
    if quad_data is not None:
        # quad extraction already enumerated the triples — reuse its count
        census[3] = int((quad_data[0] >= 0).sum())
    else:
        # one shared dedup rule with the rule merge: a triple {i,j,k} is
        # counted once, under its frequent (i,j) row with k > j > i
        census[3] = len(
            frequent_triples_from_extension(triple_data, min_count)[0]
        )
    if max_len < 4:
        return census
    if quad_data is None:
        return finish(4)  # triple-capacity overflow: report honestly
    ti, tj, tk, _, q = quad_data
    v = q.shape[1] if q.ndim == 2 else 0
    l_ids = np.arange(v)[None, :]
    # quad {i,j,k,l} counted once: under its (i,j,k) with l > k > j > i
    qmask = (ti >= 0)[:, None] & (l_ids > tk[:, None]) & (q >= min_count)
    census[4] = int(qmask.sum())
    return finish(5)


def prune_infrequent(baskets: Baskets, min_count: int) -> tuple[Baskets, np.ndarray]:
    """Apriori pre-filter: drop items whose SINGLETON support is below
    min_count before pair counting. Exact — an infrequent item cannot occur
    in any frequent itemset — and the step that collapses a 1M-track
    vocabulary (dense pair matrix: 4 TB) to the few thousand frequent items
    that can actually form rules. Host cost is one bincount + remap over the
    membership rows. Returns (reduced baskets, kept original ids)."""
    item_counts = np.bincount(baskets.track_ids, minlength=baskets.n_tracks)
    keep_ids = np.flatnonzero(item_counts >= min_count)
    remap = np.full(baskets.n_tracks, -1, dtype=np.int32)
    remap[keep_ids] = np.arange(len(keep_ids), dtype=np.int32)
    mapped = remap[baskets.track_ids]  # one gather over the rows, reused
    selected = mapped >= 0
    names = [baskets.vocab.names[i] for i in keep_ids]
    reduced = Baskets(
        playlist_rows=baskets.playlist_rows[selected],
        track_ids=mapped[selected],
        n_playlists=baskets.n_playlists,  # denominator stays ALL playlists
        vocab=Vocab(names=names, index={n: i for i, n in enumerate(names)}),
    )
    return reduced, keep_ids


def mine(
    baskets: Baskets,
    cfg: MiningConfig,
    mesh: "jax.sharding.Mesh | None" = None,
) -> MiningResult:
    """Run the full mining compute, timed like the reference's rule step."""
    timer = PhaseTimer()
    # model layout (KMLS_MODEL_LAYOUT): under the sharded layout a run
    # with no mesh — or the default dp-major auto mesh — gets a
    # vocab-major 1xN mesh over the local devices, so the one-hot, the
    # counts, and the emission all shard the vocab axis. Idempotent; a
    # replicated layout leaves the mesh untouched.
    mesh = layout_mod.mining_mesh(cfg, mesh)
    # native-library availability (and, on a fresh checkout, the one-time
    # g++ build it triggers) resolves BEFORE the reference-parity timer:
    # library setup is environment preparation, not rule generation — the
    # same reason the bench excludes jit compilation via warm-up
    native_cpu_ok = native_cpu_eligible(cfg, mesh)
    t0 = time.perf_counter()
    n_total = baskets.n_tracks
    pruned_vocab = None
    mined_baskets = baskets
    with trace_session("mine"):
        if baskets.n_tracks > cfg.prune_vocab_threshold:
            with timer.phase("apriori_prune"):
                min_count = support.min_count_for(
                    cfg.min_support, baskets.n_playlists
                )
                mined_baskets, _ = prune_infrequent(baskets, min_count)
                pruned_vocab = mined_baskets.n_tracks
            if mined_baskets.n_tracks == 0:
                if baskets.n_tracks <= 4096:
                    # nothing frequent, small vocab: fall back to the
                    # unpruned vocabulary (emission finds no rules either
                    # way) so no downstream shape is zero-sized
                    mined_baskets = baskets
                    pruned_vocab = None
                else:
                    # nothing frequent, LARGE vocab: restoring the full
                    # vocabulary would re-create the infeasible shapes
                    # pruning exists to avoid (a 1M-track dense count
                    # matrix is 4 TB) just to discover an empty result —
                    # emit it host-side for free instead
                    k = cfg.k_max_consequents
                    tensors = rules.RuleTensors(
                        rule_ids=np.full((0, k), -1, np.int32),
                        rule_counts=np.zeros((0, k), np.int32),
                        rule_confs=np.zeros((0, k), np.float32),
                        item_counts=np.zeros(0, np.int32),
                        n_playlists=baskets.n_playlists,
                        min_support=cfg.min_support,
                        min_count=min_count,
                        mode=cfg.confidence_mode,
                        min_confidence=cfg.min_confidence,
                        n_frequent_items=0,
                        n_songs_missing=n_total,
                        overflow_rows=0,
                        row_valid_counts=np.zeros(0, np.int32),
                    )
                    census = (
                        {length: 0 for length in
                         range(1, cfg.max_itemset_len + 1)}
                        if cfg.max_itemset_len >= 3 else None
                    )
                    return MiningResult(
                        tensors=tensors,
                        vocab_names=[],
                        n_playlists=baskets.n_playlists,
                        n_tracks=n_total,
                        duration_s=time.perf_counter() - t0,
                        pruned_vocab=0,
                        itemset_census=census,
                        phase_timings=dict(timer.phases),
                        count_path="pruned-empty",
                    )
        # the fused single-jit path (encode→matmul→emission, one compiled
        # program + one batched fetch) applies whenever no downstream step
        # needs the one-hot or count matrix on device: single-device dense
        # mining without an itemset census or triple/quad extensions. The
        # sharded, bit-packed, and census paths keep the staged pipeline.
        #
        # WHICH family counts is the measured three-way dispatch
        # (mining/dispatch.py): explicit KMLS_COUNT_PATH override →
        # explicit legacy threshold → measured (density, shape) table
        # cell → legacy bitpack_wanted heuristic. The plan measures the
        # exact density and pair-event volume with one O(nnz) host
        # bincount before any device work is committed.
        from . import dispatch as dispatch_mod

        plan = dispatch_mod.plan_count_path(
            cfg, mined_baskets.n_playlists, mined_baskets.n_tracks,
            len(mined_baskets.playlist_rows),
            backend=jax.default_backend(),
            n_devices=mesh.devices.size if mesh is not None else 1,
            baskets=mined_baskets,
        )
        wants_bitpack = plan.path == "bitpack"
        use_sparse = plan.path == "sparse"
        plan_source = plan.source
        if use_sparse and cfg.max_itemset_len >= 3:
            # the itemset census and the triple/quad extensions need
            # materialized device intermediates the sparse route never
            # builds — the same exactness-over-speed guard the bitpack
            # override below applies; fall back to what the legacy
            # dispatch would have chosen. LOUDLY — a pinned/table sparse
            # decision must never be dropped in silence — and the
            # telemetry source says what actually decided, not the plan
            # that was overridden.
            print(
                "NOTE: max_itemset_len >= 3 needs materialized device "
                "intermediates for the census/triple merge, which the "
                f"sparse path never builds — the {plan.source} sparse "
                "decision is overridden by the legacy dense/bitpack "
                "dispatch"
            )
            use_sparse = False
            plan_source = "census-override"
            wants_bitpack = bitpack_wanted(
                mined_baskets.n_playlists, mined_baskets.n_tracks, "auto",
                hbm_budget_bytes=cfg.hbm_budget_bytes,
                n_rows=len(mined_baskets.playlist_rows),
                backend=jax.default_backend(),
            )
        # exactness guard: the itemset census and the confidence-mode
        # triple/quad merge need the dense one-hot (x) — the bit-packed
        # route never materializes it and would silently downgrade those
        # to pairwise-only. When the dense formulation FITS the budget,
        # prefer it over a forced (explicit-threshold) bitpack; when it
        # doesn't fit, bitpack proceeds and the loud pairwise-only
        # warning below stands (dense was never an option).
        staged_threshold = cfg.bitpack_threshold_elems
        if plan.source == "override":
            # a pinned family must reach the staged pair_count_fn branch
            # too, which re-derives bitpack-vs-dense from the threshold
            if wants_bitpack:
                staged_threshold = 1
            elif plan.path == "dense":
                staged_threshold = None
        if (
            wants_bitpack
            and mesh is None
            and cfg.max_itemset_len >= 3
            and not bitpack_wanted(
                mined_baskets.n_playlists, mined_baskets.n_tracks, "auto",
                hbm_budget_bytes=cfg.hbm_budget_bytes,
                n_rows=len(mined_baskets.playlist_rows),
            )
        ):
            print(
                "NOTE: max_itemset_len >= 3 needs the dense one-hot for "
                "the census/triple merge and it fits the HBM budget — "
                "overriding the bitpack threshold with the dense path"
            )
            wants_bitpack = False
            plan_source = "census-override"
            # the override must reach pair_count_fn too, or the staged
            # branch would re-derive bitpack from the raw cfg threshold
            staged_threshold = None
        # CPU fallback with the native POPCNT kernel: when no TPU is
        # reachable, XLA:CPU's int8 matmul dominates the bracket (~75%);
        # the native bit-packed counter is the same exact XᵀX ~40x faster
        # (native/kmls_popcount.cpp). Same eligibility as the fused path
        # (no downstream step may need the one-hot or counts on device).
        # The native counter is the dense family's CPU implementation:
        # a measured/override SPARSE plan outranks it (that is the very
        # comparison the scale_sparse bench banks), and an explicit
        # bitpack override pins the bit-packed family as named.
        use_native_cpu = (
            native_cpu_ok
            and not use_sparse
            and not (plan.source == "override" and plan.path == "bitpack")
        )
        # vocab-sharded count+emit (the model-parallel layout's mining
        # half): counts stay column-sharded across the mesh and each
        # shard emits its own antecedent rows — the (V, V) matrix never
        # lands on one device. Exact (bit-identical emission); the
        # census/triple paths need materialized intermediates, so they
        # keep the staged pipeline and report honestly.
        use_shard_mine = (
            layout_mod.wants_sharded_mining(cfg, mesh)
            and not wants_bitpack
            and not use_sparse
            and cfg.max_itemset_len < 3
        )
        use_fused = (
            mesh is None
            and not wants_bitpack
            and not use_sparse
            and cfg.max_itemset_len < 3
            and not use_native_cpu
        )
        counts = x = None
        if use_sparse:
            count_path = None  # the sparse branch names hybrid vs sharded
        elif use_native_cpu:
            count_path = "native-cpu"
        elif use_shard_mine:
            count_path = f"sharded-vocab-{cfg.sharded_impl}"
        elif use_fused:
            count_path = "dense-fused"
        else:
            count_path = None  # the staged branch reports what actually ran
        if use_sparse:
            # the sparse family (ops/sparse.py): CSR-style pair-event
            # expansion + bitpacked long-basket sub-count — only the nnz
            # membership pairs are touched, no (P, V) operand exists in
            # any layout. Counts are bit-identical integers, so every
            # emission twin downstream yields identical rule tensors.
            with timer.phase("sparse_mine"):
                from ..ops import sparse as sparse_mod

                min_count = support.min_count_for(
                    cfg.min_support, mined_baskets.n_playlists
                )
                thr = cfg.sparse_long_basket or None
                if layout_mod.wants_sharded_mining(cfg, mesh):
                    from ..parallel.support import (
                        sparse_sharded_rule_tensors,
                    )

                    emitted = sparse_sharded_rule_tensors(
                        mined_baskets, mesh, min_count,
                        cfg.k_max_consequents, long_basket_threshold=thr,
                    )
                    tensors = rules.assemble_rule_tensors(
                        *emitted,
                        n_playlists=mined_baskets.n_playlists,
                        min_support=cfg.min_support,
                        k_max=cfg.k_max_consequents,
                        mode=cfg.confidence_mode,
                        min_confidence=cfg.min_confidence,
                        n_total_songs=n_total,
                        n_tracks=mined_baskets.n_tracks,
                    )
                    count_path = "sparse-sharded"
                else:
                    count_path = "sparse-hybrid"
                    if jax.default_backend() == "cpu":
                        # fully sparse count→emit when no long baskets:
                        # membership pairs straight to rule rows, the
                        # (V, V) matrix never exists. Long baskets fall
                        # back to the materialized-matrix route (sparse
                        # count + dense emission) — same tensors.
                        emitted = sparse_mod.sparse_rule_rows(
                            mined_baskets.playlist_rows,
                            mined_baskets.track_ids,
                            n_playlists=mined_baskets.n_playlists,
                            n_tracks=mined_baskets.n_tracks,
                            min_count=min_count,
                            k_max=cfg.k_max_consequents,
                            long_basket_threshold=thr,
                        )
                        if emitted is not None:
                            tensors = rules.assemble_rule_tensors(
                                *emitted,
                                n_playlists=mined_baskets.n_playlists,
                                min_support=cfg.min_support,
                                k_max=cfg.k_max_consequents,
                                mode=cfg.confidence_mode,
                                min_confidence=cfg.min_confidence,
                                n_total_songs=n_total,
                                n_tracks=mined_baskets.n_tracks,
                            )
                        else:
                            counts_host = sparse_mod.sparse_pair_counts_np(
                                mined_baskets.playlist_rows,
                                mined_baskets.track_ids,
                                n_playlists=mined_baskets.n_playlists,
                                n_tracks=mined_baskets.n_tracks,
                                long_basket_threshold=thr,
                            )
                            tensors = rules.mine_rules_from_counts_np(
                                counts_host,
                                n_playlists=mined_baskets.n_playlists,
                                min_support=cfg.min_support,
                                k_max=cfg.k_max_consequents,
                                mode=cfg.confidence_mode,
                                min_confidence=cfg.min_confidence,
                                n_total_songs=n_total,
                            )
                    else:
                        counts_dev = sparse_mod.sparse_pair_counts_device(
                            mined_baskets.playlist_rows,
                            mined_baskets.track_ids,
                            n_playlists=mined_baskets.n_playlists,
                            n_tracks=mined_baskets.n_tracks,
                            long_basket_threshold=thr,
                        )
                        tensors = rules.mine_rules_from_counts(
                            counts_dev,
                            n_playlists=mined_baskets.n_playlists,
                            min_support=cfg.min_support,
                            k_max=cfg.k_max_consequents,
                            mode=cfg.confidence_mode,
                            min_confidence=cfg.min_confidence,
                            n_total_songs=n_total,
                        )
        elif use_native_cpu:
            with timer.phase("native_pair_counts"):
                counts_np = native_pair_counts(mined_baskets)
            with timer.phase("rule_emission"):
                tensors = rules.mine_rules_from_counts_np(
                    counts_np,
                    n_playlists=mined_baskets.n_playlists,
                    min_support=cfg.min_support,
                    k_max=cfg.k_max_consequents,
                    mode=cfg.confidence_mode,
                    min_confidence=cfg.min_confidence,
                    n_total_songs=n_total,
                )
        elif use_shard_mine:
            with timer.phase("sharded_mine"):
                from ..parallel.support import sharded_rule_tensors

                min_count = support.min_count_for(
                    cfg.min_support, mined_baskets.n_playlists
                )
                emitted = sharded_rule_tensors(
                    mined_baskets, mesh, min_count,
                    cfg.k_max_consequents, impl=cfg.sharded_impl,
                )
                tensors = rules.assemble_rule_tensors(
                    *emitted,
                    n_playlists=mined_baskets.n_playlists,
                    min_support=cfg.min_support,
                    k_max=cfg.k_max_consequents,
                    mode=cfg.confidence_mode,
                    min_confidence=cfg.min_confidence,
                    n_total_songs=n_total,
                    n_tracks=mined_baskets.n_tracks,
                )
        elif use_fused:
            with timer.phase("fused_mine"):
                min_count = support.min_count_for(
                    cfg.min_support, mined_baskets.n_playlists
                )
                emitted = jax.device_get(
                    rules.fused_dense_rule_tensors(
                        jnp.asarray(mined_baskets.playlist_rows),
                        jnp.asarray(mined_baskets.track_ids),
                        jnp.int32(min_count),
                        n_playlists=mined_baskets.n_playlists,
                        n_tracks=mined_baskets.n_tracks,
                        k_max=cfg.k_max_consequents,
                    )
                )
                # the fused program compacts its outputs to int16 when the
                # static shapes allow (ops/rules.py); upcast back to the
                # int32 RuleTensors contract and log what actually crossed
                # the link — the fetch is the TPU bracket's floor through
                # a tunneled backend (VERDICT r3 next-round #4)
                fetch_bytes = sum(a.nbytes for a in emitted)
                print(
                    f"Fused fetch: {fetch_bytes / 1e6:.3f} MB device->host "
                    f"({mined_baskets.n_tracks}x{cfg.k_max_consequents} "
                    f"rule tensors, {emitted[0].dtype}/{emitted[1].dtype})"
                )
                emitted = tuple(
                    np.asarray(a, dtype=np.int32) for a in emitted
                )
                tensors = rules.assemble_rule_tensors(
                    *emitted,
                    n_playlists=mined_baskets.n_playlists,
                    min_support=cfg.min_support,
                    k_max=cfg.k_max_consequents,
                    mode=cfg.confidence_mode,
                    min_confidence=cfg.min_confidence,
                    n_total_songs=n_total,
                    n_tracks=mined_baskets.n_tracks,
                )
        else:
            with timer.phase("pair_counts"):
                counts, x, count_path = pair_count_fn(
                    mined_baskets, mesh,
                    bitpack_threshold_elems=staged_threshold,
                    sharded_impl=cfg.sharded_impl,
                    hbm_budget_bytes=cfg.hbm_budget_bytes,
                )
                jax.block_until_ready(counts)
            with timer.phase("rule_emission"):
                tensors = rules.mine_rules_from_counts(
                    counts,
                    n_playlists=mined_baskets.n_playlists,
                    min_support=cfg.min_support,
                    k_max=cfg.k_max_consequents,
                    mode=cfg.confidence_mode,
                    min_confidence=cfg.min_confidence,
                    n_total_songs=n_total,
                )
        triple_data = None
        quad_data = None
        triple_merge_applied = None
        needs_triples = (
            cfg.confidence_mode == "confidence" and cfg.max_itemset_len >= 3
        )
        if needs_triples:
            # multi-antecedent rules from frequent triples/quads: the
            # slow-path semantics pairwise mining cannot dominate
            # (ops/rules.py) — part of rule generation, inside the bracket
            if cfg.max_itemset_len >= 5:
                print(
                    "WARNING: confidence-mode antecedents are enumerated up "
                    f"to size 3 (itemsets of length 4); max_itemset_len="
                    f"{cfg.max_itemset_len} rules from longer itemsets are "
                    "not merged and confidences may understate them"
                )
            if x is not None:
                with timer.phase("triple_extension"):
                    triple_data = compute_triple_extension(
                        x, counts, tensors.min_count
                    )
            if triple_data is not None:
                if cfg.max_itemset_len >= 4:
                    with timer.phase("quad_extension"):
                        quad_data = compute_quad_extension(
                            x, triple_data, tensors.min_count
                        )
                    if quad_data is None:
                        print(
                            "WARNING: quad-rule merge skipped (frequent "
                            "triples exceed capacity); confidences include "
                            "antecedents up to size 2 only"
                        )
                # the O(E×V) contribution builds are the merge's dominant
                # host cost — keep them inside the timed merge phase
                with timer.phase("confidence_merge"):
                    contributions = [
                        rules.antecedent_contributions(
                            (triple_data[0], triple_data[1]),
                            triple_data[2], triple_data[3],
                            min_count=tensors.min_count,
                            min_confidence=cfg.min_confidence,
                        )
                    ]
                    if quad_data is not None:
                        contributions.append(
                            rules.antecedent_contributions(
                                (quad_data[0], quad_data[1], quad_data[2]),
                                quad_data[3], quad_data[4],
                                min_count=tensors.min_count,
                                min_confidence=cfg.min_confidence,
                            )
                        )
                    tensors = rules.merge_confidence_contributions(
                        tensors, contributions, k_max=cfg.k_max_consequents
                    )
                triple_merge_applied = True
            else:
                # sharded/bit-packed path (no one-hot matrix) or frequent
                # pairs over capacity: the merge CANNOT run — say so loudly,
                # confidences are pairwise-only (inexact for itemsets ≥ 3)
                triple_merge_applied = False
                print(
                    "WARNING: confidence-mode triple-rule merge skipped "
                    + (
                        "(frequent pairs exceed capacity)"
                        if x is not None
                        else "(one-hot matrix not materialized on the "
                        "sharded/bit-packed path)"
                    )
                    + "; confidences are pairwise-only"
                )
        duration = time.perf_counter() - t0
        census = None
        if cfg.max_itemset_len >= 3:
            # census-only extensions (support mode) run OUTSIDE the
            # rule-generation bracket: reporting, not rule work
            if triple_data is None and x is not None and not needs_triples:
                with timer.phase("triple_extension"):
                    triple_data = compute_triple_extension(
                        x, counts, tensors.min_count
                    )
            if (
                cfg.max_itemset_len >= 4
                and quad_data is None
                and triple_data is not None
                and x is not None
                and not needs_triples
            ):
                with timer.phase("quad_extension"):
                    quad_data = compute_quad_extension(
                        x, triple_data, tensors.min_count
                    )
            with timer.phase("itemset_census"):
                census = _itemset_census(
                    counts,
                    tensors.min_count,
                    cfg.max_itemset_len,
                    triple_data,
                    triple_data[4] if triple_data is not None else None,
                    quad_data,
                )
    return MiningResult(
        tensors=tensors,
        vocab_names=list(mined_baskets.vocab.names),
        n_playlists=mined_baskets.n_playlists,
        n_tracks=n_total,
        duration_s=duration,
        pruned_vocab=pruned_vocab,
        itemset_census=census,
        phase_timings=dict(timer.phases),
        triple_merge_applied=triple_merge_applied,
        count_path=count_path,
        count_path_source=plan_source,
        sparse_events=plan.pair_events,
    )
