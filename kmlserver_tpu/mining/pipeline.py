"""The batch mining job, end to end — parity with the reference's ``__main__``
orchestration (reference: machine-learning/main.py:421-484):

dataset list → rotation index → CSV read → vocab/aux artifacts →
baskets → device mining → recommendations artifact → history append +
invalidation-token rewrite — with the same printed progress/timing lines the
reference's report reads off the pod logs (Sao Paulo timestamps at :423,431;
"Time elapsed in rule generation" from :306-308; missing-songs counter
from :298-305).

Preemption-proofing (ISSUE 4) restructures the run into three checkpointed
phases (``mining/checkpoint.py``):

- **encode** — CSV read, vocab validation/aux maps, basket encoding;
- **mine**   — frequent-itemset mining + rule-tensor extraction (the
  device compute, the dominant cost at scale);
- **rules**  — expansion into the reference's pickle dict;
- **embed**  — (optional, ``embed_enabled``) ALS item-embedding training
  over the same baskets (``mining/als.py``) — the SECOND model family,
  published as ``embeddings.npz`` through the same manifest + lease path
  and checkpointed like any other phase, proving the artifact spine is
  model-agnostic plumbing rather than rule-specific.

After each phase the writer rank persists an atomic sha256-manifested
checkpoint keyed by a config+dataset fingerprint; a restarted job resumes
from the last completed phase and publishes bit-identical pickles, while a
stale or corrupt checkpoint self-retires to recompute. ALL artifact writes
now happen in one publication step AFTER the phases — a job that dies
mid-phase leaves the PVC's served artifact set untouched (the reference
wrote vocab artifacts early, so an eviction could strand a half-new set;
the READ contract — filenames, object shapes, token polling — is
unchanged). Publication itself is fenced by a heartbeat lease with a
monotonic fencing token (``io/artifacts.py PublicationLease``): a zombie
job superseded by the GitOps ``Replace`` resync aborts instead of tearing
what the newer run published; the manifest records the token. The
checkpoint store is retired after a successful publication.
"""

from __future__ import annotations

import dataclasses
import os
import time

import jax

from .. import faults
from ..config import BASE_INDEX, MiningConfig
from ..data.csv import read_tracks
from ..io import artifacts, registry
from ..observability import costmodel
from ..observability.jobmetrics import JobMetrics
from ..utils.timeutil import get_current_time_str, get_current_time_str_precise
from . import checkpoint as ckpt_mod
from . import vocab as vocab_mod
from .miner import MiningResult, mine


@dataclasses.dataclass
class JobSummary:
    dataset: str
    run_index: int
    n_rows: int
    n_playlists: int
    n_tracks: int
    n_songs_missing: int
    rule_generation_s: float
    token: str
    artifact_paths: dict[str, str]
    # phases skipped because a verified checkpoint covered them
    resumed_phases: tuple[str, ...] = ()
    # the publication lease's fencing token (None: lease disabled / reader)
    fencing_token: int | None = None
    # ALS embedding training wall clock (None: embed phase disabled)
    als_train_s: float | None = None
    # continuous freshness (ISSUE 10): set when this run published a delta
    # bundle instead of a full artifact set (the chain sequence number;
    # None = full publication)
    delta_seq: int | None = None


def _pickle_path(cfg: MiningConfig, filename: str) -> str:
    return os.path.join(cfg.pickles_dir, filename)


def _crash_site(phase: str) -> None:
    """Deterministic preemption stand-in: ``KMLS_FAULT_MINE_CRASH_PHASE``
    aborts the job right AFTER ``phase``'s checkpoint persisted — the
    restarted job must resume from it (chaos-tested at every phase)."""
    faults.fire(f"mine.crash.{phase}")


def _run_encode_phase(cfg: MiningConfig, selected: str) -> dict:
    """CSV read + vocab validation/aux maps + basket encoding."""
    import numpy as np

    table = read_tracks(selected, cfg.sample_ratio)
    print(
        f"Loaded {len(table)} rows, {table.n_playlists} playlists, "
        f"{table.n_tracks} unique tracks"
    )
    artists = vocab_mod.validate_and_map_artists(table)
    repeated = vocab_mod.extract_repeated_track_names(table)
    info = vocab_mod.map_track_ids_to_info(table)
    best = vocab_mod.most_frequent_tracks(table, cfg.top_tracks_save_percentile)
    baskets = vocab_mod.build_baskets(table)
    return {
        "n_rows": len(table),
        "artists": artists,
        "repeated": repeated,
        "info": info,
        "best": best,
        "baskets": baskets,
        # pid ranks backing playlist_rows (CKPT_VERSION 4): the delta
        # base state (freshness/delta.py) extends these with appended
        # rows' pids, so an incremental run re-ranks without re-reading
        # the full CSV
        "pid_values": np.unique(table.pid),
    }


def _report_mining(result: MiningResult, cfg: MiningConfig) -> None:
    tensors = result.tensors
    if result.pruned_vocab is not None:
        print(
            f"Apriori pruning: {result.n_tracks} -> {result.pruned_vocab} "
            f"candidate tracks before pair counting"
        )
    print(f"Songs without recommendations: {tensors.n_songs_missing}")
    print(f"Time elapsed in rule generation: {result.duration_s:.2f}s")
    if result.phase_timings:
        from ..utils.profiling import format_phases

        print(format_phases(result.phase_timings).capitalize())
    if result.count_path:
        print(f"Pair-count path: {result.count_path}")
    if result.itemset_census is not None:
        census = ", ".join(
            f"len {k}: {'not enumerated' if v < 0 else v}"
            for k, v in sorted(result.itemset_census.items())
        )
        print(f"Frequent itemsets — {census}")
    if tensors.overflow_rows:
        print(
            f"WARNING: {tensors.overflow_rows} songs exceeded the "
            f"K_max={cfg.k_max_consequents} consequent capacity (truncated "
            f"to the highest-support rules)"
        )


def run_mining_job(
    cfg: MiningConfig,
    mesh: "jax.sharding.Mesh | None" = None,
    watchdog=None,
) -> JobSummary:
    print(f"Job starting at {get_current_time_str()}")

    # continuous freshness (ISSUE 10): with KMLS_DELTA_ENABLED and a
    # matching base state on the PVC, this run publishes an incremental
    # delta bundle instead of re-mining everything — freshness lag drops
    # from full-mine wall clock to the restricted recount. ANY
    # ineligibility (no base, rewritten prefix, config drift, chain cap,
    # multi-host gang) falls through to the full pipeline below; the
    # delta path never publishes an approximation.
    if cfg.delta_enabled:
        from ..freshness import delta as delta_mod

        # delta-route telemetry (the Job manifests arm KMLS_JOB_METRICS
        # alongside KMLS_DELTA_ENABLED): a delta publication must refresh
        # job_metrics.prom — freshness-timestamp dashboards alert on its
        # age, and most syncs in steady state ARE deltas. Constructed
        # before the run so an abort still records success=0; the
        # ineligible fallthrough constructs nothing on disk (JobMetrics
        # only writes on phase_done/finish) and the full path below
        # writes its own. Writer-rank gate kept for symmetry even though
        # eligibility rejects multi-host gangs.
        jm_delta = (
            JobMetrics(cfg.pickles_dir)
            if cfg.job_metrics and jax.process_index() == 0
            else None
        )
        try:
            res = delta_mod.run_delta_job(cfg, mesh=mesh)
        except delta_mod.DeltaIneligible as exc:
            print(f"Delta mining ineligible ({exc}); running the full pipeline")
        except BaseException:
            if jm_delta is not None:
                try:
                    # same abort discipline as the full path: success=0
                    # telemetry, never masking the real cause
                    jm_delta.finish(False)
                except Exception:
                    pass
            raise
        else:
            if jm_delta is not None:
                try:
                    jm_delta.phase_done("delta", res.duration_s)
                    if res.bundle_path:
                        # analytic cost attribution (ISSUE 12): the
                        # delta's device compute is the column-
                        # restricted recount C[R, :] over the combined
                        # baskets — same formula the serving MFU uses
                        flops, moved = costmodel.phase_cost(
                            "delta_recount",
                            p=res.n_playlists, v=res.n_tracks,
                            rows=res.n_touched,
                        )
                        jm_delta.note_phase_cost("delta", flops, moved)
                        jm_delta.note_artifact("delta", res.bundle_path)
                    jm_delta.finish(
                        True,
                        rule_generation_s=res.duration_s,
                        fencing_token=res.fencing_token,
                    )
                except Exception as exc:
                    # publication already succeeded — telemetry is
                    # best-effort, exactly like the full path's guard
                    print(
                        f"WARNING: success telemetry skipped "
                        f"({jm_delta.path}): {exc!r}"
                    )
            # quality loop (ISSUE 14): once the chain reaches
            # KMLS_DELTA_COMPACT_AFTER bundles, fold base ∘ chain into a
            # new base bundle WITHOUT a full re-mine. Never fails the
            # job: a skipped compaction keeps the chain, the next delta
            # re-triggers, and KMLS_DELTA_MAX_CHAIN stays the backstop.
            if res.bundle_path:
                from ..quality import lifecycle as lifecycle_mod

                lifecycle_mod.maybe_compact(cfg)
            print(f"Job finished at {get_current_time_str()}")
            return JobSummary(
                dataset=res.dataset,
                run_index=res.run_index,
                n_rows=res.n_new_rows,
                n_playlists=0,
                n_tracks=0,
                n_songs_missing=0,
                rule_generation_s=res.duration_s,
                token=res.base_token,
                artifact_paths=(
                    {"delta": res.bundle_path} if res.bundle_path else {}
                ),
                fencing_token=res.fencing_token,
                delta_seq=res.seq if res.bundle_path else None,
            )

    # model layout (KMLS_MODEL_LAYOUT): resolved ONCE here so the mine
    # and embed phases ride the SAME vocab-sharded mesh — a sharded
    # layout with no mesh (or the dp-major auto mesh) gets a vocab-major
    # 1xN mesh over the local devices; replicated leaves it untouched
    from ..parallel import layout as layout_mod

    mesh = layout_mod.mining_mesh(cfg, mesh)

    # Multi-host: every rank participates in the sharded compute (the
    # collectives need all processes), but only rank 0 touches the shared
    # PVC — duplicate history appends would corrupt the dataset rotation,
    # and concurrent artifact writes could tear what the API replicas read.
    is_writer = jax.process_index() == 0

    datasets = registry.get_dataset_list(cfg, persist=is_writer)
    run_index = registry.get_next_run_index(cfg, datasets)
    selected = datasets[run_index - BASE_INDEX]
    print(f"Selected dataset {run_index}/{len(datasets)}: {selected}")

    # checkpoint store keyed by config+dataset fingerprint; every rank
    # reads (identical skip decisions keep the collectives aligned), the
    # writer saves. The completed-phase set is snapshotted at open time.
    store = ckpt_mod.open_store(cfg, selected, run_index, writer=is_writer)
    resumed: list[str] = []

    # mining-side telemetry (ISSUE 9): per-phase progress/duration/bytes
    # rewritten atomically to pickles/job_metrics.prom as phases complete
    # — a preempted job leaves the telemetry of what it DID finish.
    # Writer rank only, same discipline as every other PVC write.
    jm = (
        JobMetrics(cfg.pickles_dir)
        if is_writer and cfg.job_metrics
        else None
    )

    def phase(name: str, compute):
        """Resume ``name`` from its checkpoint or compute + persist it.
        The crash fault site fires AFTER the save — exactly where a
        preemption that already banked the phase would land. Either way
        the phase's compute duration reaches the telemetry file: a
        resumed phase reports the ORIGINAL duration from the
        checkpoint's span annotation, flagged resumed=1."""
        payload = store.load(name) if store is not None else None
        if payload is not None:
            resumed.append(name)
            print(
                f"Resumed phase {name!r} from checkpoint "
                f"({store.age_s(name):.0f}s old)"
            )
            if jm is not None:
                jm.phase_done(name, store.duration_s(name), resumed=True)
            return payload
        t_phase = time.perf_counter()
        payload = compute()
        duration_s = time.perf_counter() - t_phase
        if store is not None:
            store.save(name, payload, duration_s=duration_s)
        if jm is not None:
            jm.phase_done(name, duration_s)
        _crash_site(name)
        return payload

    # the writer takes the publication lease BEFORE the expensive phases:
    # its heartbeats prove liveness for the whole mine, and a superseding
    # run (GitOps Replace) fences this one out before it can publish.
    lease = None
    if is_writer:
        # ENOSPC preflight BEFORE the expensive phases: estimate the
        # publication from the last manifest (0 on first run), reclaim
        # quarantine + orphaned temp files if short, and exit resumable
        # (75) rather than tear a publication hours from now. Retired
        # phase checkpoints are fair game — a full mine re-derives them.
        free = artifacts.ensure_free_space(
            cfg.pickles_dir,
            max(
                artifacts.estimate_publication_bytes(cfg.pickles_dir),
                cfg.disk_min_free_bytes,
            ),
            extra_dirs=(ckpt_mod.retired_dirs(cfg)),
        )
        print(f"Disk preflight: {free / (1 << 20):.0f} MiB free on PVC")
    if is_writer and cfg.lease_enabled:
        lease = artifacts.PublicationLease.acquire(
            cfg.pickles_dir,
            ttl_s=cfg.lease_ttl_s,
            heartbeat_interval_s=cfg.lease_heartbeat_interval_s or None,
            stall_fraction=cfg.lease_stall_fraction,
        )
        lease.start_heartbeat()
        print(f"Publication lease acquired (fencing token {lease.fencing_token})")

    try:
        encoded = phase("encode", lambda: _run_encode_phase(cfg, selected))
        baskets = encoded["baskets"]

        def _mine() -> MiningResult:
            if watchdog is not None:
                # collective guard: a dead/hung peer rank turns the mine's
                # collectives into a forever-hang — bound it
                with watchdog.guard("mine"):
                    return mine(baskets, cfg, mesh=mesh)
            return mine(baskets, cfg, mesh=mesh)

        result: MiningResult = phase("mine", _mine)
        _report_mining(result, cfg)
        tensors = result.tensors
        if jm is not None:
            jm.set_dataset(
                rows=encoded["n_rows"],
                playlists=result.n_playlists,
                tracks=result.n_tracks,
            )
            # the measured dispatch decision (ISSUE 13), surfaced as a
            # labeled gauge: which family counted + what decided it
            if result.count_path:
                jm.note_count_path(
                    result.count_path, result.count_path_source or "",
                )
            # analytic cost attribution (ISSUE 12): the mine phase's
            # dominant kernel is the pair-support contraction C = XᵀX
            # over the (possibly pruned) mined shape — leading-order,
            # same costmodel.phase_cost formula serving MFU uses. A
            # sparse-family mine (ISSUE 13) did nnz-proportional work
            # instead, and the attribution must say so.
            if result.count_path and result.count_path.startswith("sparse"):
                pruned_v = result.pruned_vocab or result.n_tracks
                flops, moved = costmodel.phase_cost(
                    "sparse_count",
                    events=result.sparse_events or 0,
                    nnz=encoded["n_rows"], v=pruned_v,
                )
            else:
                flops, moved = costmodel.phase_cost(
                    "support_count",
                    p=result.n_playlists, v=result.n_tracks,
                )
            jm.note_phase_cost("mine", flops, moved)

        rules_dict = phase(
            "rules", lambda: tensors.to_rules_dict(result.vocab_names)
        )

        # the second model family: ALS item embeddings over the SAME
        # baskets the rule miner consumed (reused from the encode
        # checkpoint on resume), trained as its own checkpointed phase
        emb_payload = None
        if cfg.embed_enabled:

            def _embed():
                from . import als

                # the second model family rides the same mesh: under the
                # sharded layout the item half-sweep partitions along the
                # vocab axis (ALX recipe) instead of training one-device
                return als.train_embeddings(baskets, cfg, mesh=mesh)

            emb_payload = phase("embed", _embed)
            if emb_payload.get("item_factors") is None:
                # HBM-fit guard declined to train (als.py): this
                # generation publishes rules-only — loudly, not silently
                print(f"ALS embed phase skipped: {emb_payload.get('skipped')}")
                emb_payload = None
            else:
                print(
                    f"ALS embeddings trained: rank {emb_payload['rank']}, "
                    f"{emb_payload['iters']} iters, final loss "
                    f"{emb_payload['final_loss']:.3f} "
                    f"({emb_payload['duration_s']:.2f}s)"
                )
                if jm is not None:
                    # analytic cost attribution (ISSUE 12): the embed
                    # phase is the ALS half-sweep loop — over the full
                    # dense interaction matrix, or (ISSUE 13) over its
                    # compressed nnz-proportional form
                    if emb_payload.get("storage") == "sparse":
                        flops, moved = costmodel.phase_cost(
                            "als_sweep_sparse",
                            nnz=emb_payload.get(
                                "nnz", len(baskets.playlist_rows)
                            ),
                            p=baskets.n_playlists, v=baskets.n_tracks,
                            r=emb_payload["rank"],
                            iters=emb_payload["iters"],
                        )
                    else:
                        flops, moved = costmodel.phase_cost(
                            "als_sweep",
                            p=baskets.n_playlists, v=baskets.n_tracks,
                            r=emb_payload["rank"],
                            iters=emb_payload["iters"],
                        )
                    jm.note_phase_cost("embed", flops, moved)

        # quality loop (ISSUE 14): offline ranking evaluation over a
        # deterministic held-out split — trains BOTH model families on
        # the train half and scores every serving mode through the
        # production kernels. Its own checkpointed phase (a preempted
        # job resumes past the double-train), payload = the
        # deterministic report published below.
        qual_report = None
        if cfg.eval_enabled:

            def _eval():
                from ..quality import eval as qual_mod

                return qual_mod.run_eval_phase(cfg, baskets, mesh=mesh)

            qual_report = phase("eval", _eval)

        # ---------- publication (writer only, lease-fenced) ----------
        paths: dict[str, str] = {}
        token = ""
        if is_writer:
            if lease is not None:
                # fence point 1: a zombie aborts BEFORE its first write
                lease.check()
            paths["artists_mapping"] = _pickle_path(cfg, cfg.artists_mapping_file)
            artifacts.save_pickle(encoded["artists"], paths["artists_mapping"])
            if encoded["repeated"]:
                # the reference saves this one conditionally (main.py:86-109)
                paths["repeated_tracks"] = _pickle_path(
                    cfg, cfg.repeated_tracks_file
                )
                artifacts.save_pickle(
                    encoded["repeated"], paths["repeated_tracks"]
                )
            paths["track_info"] = _pickle_path(cfg, cfg.track_info_file)
            artifacts.save_pickle(encoded["info"], paths["track_info"])
            paths["best_tracks"] = _pickle_path(cfg, cfg.best_tracks_file)
            artifacts.save_pickle(encoded["best"], paths["best_tracks"])
            print(
                f"Saved {len(encoded['best'])} best tracks "
                f"(top {cfg.top_tracks_save_percentile:.0%})"
            )

            # the token value is generated BEFORE the manifest so the
            # manifest can be stamped with the generation it describes —
            # readers validate only when the published token matches
            token_value = get_current_time_str_precise()
            paths["recommendations"] = _pickle_path(cfg, cfg.recommendations_file)
            artifacts.save_pickle(rules_dict, paths["recommendations"])
            if cfg.write_tensor_artifact:
                paths["rule_tensors"] = artifacts.tensor_artifact_path(
                    paths["recommendations"]
                )
                artifacts.save_rule_tensors(
                    paths["rule_tensors"],
                    vocab=result.vocab_names,
                    rule_ids=tensors.rule_ids,
                    rule_counts=tensors.rule_counts,
                    item_counts=tensors.item_counts,
                    n_playlists=result.n_playlists,
                    min_support=cfg.min_support,
                    mode=tensors.mode,
                    min_confidence=tensors.min_confidence,
                    rule_confs64=tensors.rule_confs64,
                )
            if emb_payload is None:
                # embed phase off: a previous generation's embeddings must
                # not survive into this publication's manifest, where they
                # would be re-blessed against rules they weren't trained on
                artifacts.remove_embeddings(cfg.pickles_dir)
            else:
                # second writer on the same spine: the embedding artifact
                # rides the identical atomic-write + manifest + fence
                # discipline as the rule tensors — a reader that can
                # validate one can validate the other
                paths["embeddings"] = artifacts.embeddings_artifact_path(
                    cfg.pickles_dir
                )
                artifacts.save_embeddings(
                    paths["embeddings"],
                    vocab=baskets.vocab.names,
                    item_factors=emb_payload["item_factors"],
                    rank=emb_payload["rank"],
                    iters=emb_payload["iters"],
                    reg=emb_payload["reg"],
                    final_loss=emb_payload["final_loss"],
                )
            if qual_report is None:
                # eval off this generation: a previous report must not
                # survive into this publication's manifest, where a
                # blend optimum measured against retired models would be
                # re-blessed (the embeddings-retirement precedent)
                artifacts.remove_quality_report(cfg.pickles_dir)
            else:
                # fourth writer on the same spine: the quality report
                # rides the identical atomic-write + manifest + fence
                # discipline as every other artifact
                paths["quality_report"] = artifacts.save_quality_report(
                    cfg.pickles_dir, qual_report
                )
            if cfg.write_manifest:
                # integrity sidecar AFTER the artifact set, BEFORE the token:
                # any reader that sees the new token sees a manifest matching
                # the new bytes; a reader racing mid-update detects the
                # mismatch and keeps serving its last-good bundle (engine.load
                # validates before publishing). Stamped with the token value
                # about to publish, so a LATER manifest-less writer (the
                # reference job) retires this manifest just by rewriting the
                # token — its fresh artifacts are never judged by stale sums.
                # The file set is quality/lifecycle.py's ONE copy, shared
                # with the compactor.
                from ..quality.lifecycle import manifest_filenames

                paths["manifest"] = artifacts.write_manifest(
                    cfg.pickles_dir,
                    manifest_filenames(cfg),
                    token=token_value,
                    fencing_token=lease.fencing_token if lease else None,
                )
            if lease is not None:
                # fence point 2: the last instant a zombie can be stopped
                # before the token rewrite makes its stale set authoritative
                lease.check()
            token = registry.append_history_and_invalidate(
                cfg, run_index, selected, timestamp=token_value
            )
            # continuous freshness: a FULL publication supersedes any
            # delta chain of the previous generation and seeds the next
            # incremental run with this run's encode state + tensors.
            # Best-effort — the artifacts above already published, so a
            # freshness bookkeeping failure must not fail the job (the
            # next run simply full-mines).
            if cfg.delta_enabled:
                from ..freshness import delta as delta_mod

                try:
                    artifacts.retire_delta_chain(cfg.pickles_dir)
                    npz_sha = None
                    if "rule_tensors" in paths:
                        npz_sha = artifacts.file_digest(
                            paths["rule_tensors"]
                        )["sha256"]
                    delta_mod.save_base_state(
                        cfg,
                        token=token_value,
                        run_index=run_index,
                        dataset_path=selected,
                        baskets=encoded["baskets"],
                        pid_values=encoded.get("pid_values"),
                        published=delta_mod.published_from_tensors(
                            tensors, result.vocab_names
                        ),
                        npz_sha256=npz_sha,
                    )
                    print("Freshness base state saved (delta mining armed)")
                except Exception as exc:
                    print(
                        f"WARNING: freshness base state skipped: {exc!r}"
                    )
            else:
                # delta mining off: a chain left by a previous
                # configuration must not outlive the generation it patched
                artifacts.retire_delta_chain(cfg.pickles_dir)
            if store is not None:
                # published: the next rotation run must start fresh
                store.clear()
            if jm is not None:
                # success telemetry LAST: artifact sizes of the set just
                # published, the fencing token that fenced it, success=1
                # + the freshness timestamp dashboards alert on. Broad
                # guard like the abort path below: publication already
                # succeeded, so nothing from telemetry (write() is
                # best-effort on OSError; registry-drift KeyError is the
                # other escape) may fail the job or skip lease.release()
                # — the abort handler would overwrite this very telemetry
                # with success=0 for a run that actually published.
                try:
                    for artifact_name, artifact_path in paths.items():
                        jm.note_artifact(artifact_name, artifact_path)
                    jm.finish(
                        True,
                        rule_generation_s=result.duration_s,
                        fencing_token=lease.fencing_token if lease else None,
                    )
                except Exception as exc:
                    print(
                        f"WARNING: success telemetry skipped "
                        f"({jm.path}): {exc!r}"
                    )
            if lease is not None:
                lease.release()
    except BaseException:
        if jm is not None:
            try:
                # the abort itself is telemetry: success=0 with the
                # completed phases' durations still on the PVC. write()
                # is already best-effort on OSError; the broad guard is
                # for anything else (registry-drift KeyError) — nothing
                # from telemetry may mask the real abort cause or keep
                # the lease release below from running.
                jm.finish(False)
            except Exception:
                pass
        if lease is not None:
            # a Python-level abort releases: this process writes nothing
            # more, and the replacement pod must not wait out the TTL.
            # Hard kills (SIGKILL preemption) skip this and expire instead.
            lease.stop_heartbeat()
            try:
                lease.release()
            except (artifacts.LeaseLostError, OSError):
                pass  # already fenced/unwritable: nothing to hand back
        raise
    finally:
        if lease is not None:
            lease.stop_heartbeat()
    print(f"Job finished at {get_current_time_str()}")

    return JobSummary(
        dataset=selected,
        run_index=run_index,
        n_rows=encoded["n_rows"],
        n_playlists=result.n_playlists,
        n_tracks=result.n_tracks,
        n_songs_missing=tensors.n_songs_missing,
        rule_generation_s=result.duration_s,
        token=token,
        artifact_paths=paths,
        resumed_phases=tuple(resumed),
        fencing_token=lease.fencing_token if lease else None,
        als_train_s=(
            emb_payload["duration_s"] if emb_payload is not None else None
        ),
    )
