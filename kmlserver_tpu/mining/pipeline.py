"""The batch mining job, end to end — parity with the reference's ``__main__``
orchestration (reference: machine-learning/main.py:421-484):

dataset list → rotation index → CSV read → vocab/aux artifacts →
baskets → device mining → recommendations artifact → history append +
invalidation-token rewrite — with the same printed progress/timing lines the
reference's report reads off the pod logs (Sao Paulo timestamps at :423,431;
"Time elapsed in rule generation" from :306-308; missing-songs counter
from :298-305).
"""

from __future__ import annotations

import dataclasses
import os

import jax

from ..config import BASE_INDEX, MiningConfig
from ..data.csv import read_tracks
from ..io import artifacts, registry
from ..utils.timeutil import get_current_time_str, get_current_time_str_precise
from . import vocab as vocab_mod
from .miner import MiningResult, mine


@dataclasses.dataclass
class JobSummary:
    dataset: str
    run_index: int
    n_rows: int
    n_playlists: int
    n_tracks: int
    n_songs_missing: int
    rule_generation_s: float
    token: str
    artifact_paths: dict[str, str]


def _pickle_path(cfg: MiningConfig, filename: str) -> str:
    return os.path.join(cfg.pickles_dir, filename)


def run_mining_job(
    cfg: MiningConfig, mesh: "jax.sharding.Mesh | None" = None
) -> JobSummary:
    print(f"Job starting at {get_current_time_str()}")

    # Multi-host: every rank participates in the sharded compute (the
    # collectives need all processes), but only rank 0 touches the shared
    # PVC — duplicate history appends would corrupt the dataset rotation,
    # and concurrent artifact writes could tear what the API replicas read.
    is_writer = jax.process_index() == 0

    datasets = registry.get_dataset_list(cfg, persist=is_writer)
    run_index = registry.get_next_run_index(cfg, datasets)
    selected = datasets[run_index - BASE_INDEX]
    print(f"Selected dataset {run_index}/{len(datasets)}: {selected}")

    table = read_tracks(selected, cfg.sample_ratio)
    print(
        f"Loaded {len(table)} rows, {table.n_playlists} playlists, "
        f"{table.n_tracks} unique tracks"
    )

    paths: dict[str, str] = {}

    # auxiliary vocab artifacts (reference M5-M8: main.py:438-446)
    artists = vocab_mod.validate_and_map_artists(table)
    if is_writer:
        paths["artists_mapping"] = _pickle_path(cfg, cfg.artists_mapping_file)
        artifacts.save_pickle(artists, paths["artists_mapping"])

    repeated = vocab_mod.extract_repeated_track_names(table)
    if repeated and is_writer:
        # the reference saves this one conditionally (main.py:86-109)
        paths["repeated_tracks"] = _pickle_path(cfg, cfg.repeated_tracks_file)
        artifacts.save_pickle(repeated, paths["repeated_tracks"])

    info = vocab_mod.map_track_ids_to_info(table)
    best = vocab_mod.most_frequent_tracks(table, cfg.top_tracks_save_percentile)
    if is_writer:
        paths["track_info"] = _pickle_path(cfg, cfg.track_info_file)
        artifacts.save_pickle(info, paths["track_info"])
        paths["best_tracks"] = _pickle_path(cfg, cfg.best_tracks_file)
        artifacts.save_pickle(best, paths["best_tracks"])
        print(
            f"Saved {len(best)} best tracks "
            f"(top {cfg.top_tracks_save_percentile:.0%})"
        )

    # the compute core
    baskets = vocab_mod.build_baskets(table)
    result: MiningResult = mine(baskets, cfg, mesh=mesh)
    tensors = result.tensors
    if result.pruned_vocab is not None:
        print(
            f"Apriori pruning: {result.n_tracks} -> {result.pruned_vocab} "
            f"candidate tracks before pair counting"
        )
    print(f"Songs without recommendations: {tensors.n_songs_missing}")
    print(f"Time elapsed in rule generation: {result.duration_s:.2f}s")
    if result.phase_timings:
        from ..utils.profiling import format_phases

        print(format_phases(result.phase_timings).capitalize())
    if result.count_path:
        print(f"Pair-count path: {result.count_path}")
    if result.itemset_census is not None:
        census = ", ".join(
            f"len {k}: {'not enumerated' if v < 0 else v}"
            for k, v in sorted(result.itemset_census.items())
        )
        print(f"Frequent itemsets — {census}")
    if tensors.overflow_rows:
        print(
            f"WARNING: {tensors.overflow_rows} songs exceeded the "
            f"K_max={cfg.k_max_consequents} consequent capacity (truncated "
            f"to the highest-support rules)"
        )

    rules_dict = tensors.to_rules_dict(result.vocab_names)
    token = ""
    if is_writer:
        # the token value is generated BEFORE the manifest so the manifest
        # can be stamped with the generation it describes — readers
        # validate only when the published token matches the stamp
        token_value = get_current_time_str_precise()
        paths["recommendations"] = _pickle_path(cfg, cfg.recommendations_file)
        artifacts.save_pickle(rules_dict, paths["recommendations"])
        if cfg.write_tensor_artifact:
            paths["rule_tensors"] = artifacts.tensor_artifact_path(
                paths["recommendations"]
            )
            artifacts.save_rule_tensors(
                paths["rule_tensors"],
                vocab=result.vocab_names,
                rule_ids=tensors.rule_ids,
                rule_counts=tensors.rule_counts,
                item_counts=tensors.item_counts,
                n_playlists=result.n_playlists,
                min_support=cfg.min_support,
                mode=tensors.mode,
                min_confidence=tensors.min_confidence,
                rule_confs64=tensors.rule_confs64,
            )
        if cfg.write_manifest:
            # integrity sidecar AFTER the artifact set, BEFORE the token:
            # any reader that sees the new token sees a manifest matching
            # the new bytes; a reader racing mid-update detects the
            # mismatch and keeps serving its last-good bundle (engine.load
            # validates before publishing). Stamped with the token value
            # about to publish, so a LATER manifest-less writer (the
            # reference job) retires this manifest just by rewriting the
            # token — its fresh artifacts are never judged by stale sums.
            paths["manifest"] = artifacts.write_manifest(
                cfg.pickles_dir,
                [
                    cfg.best_tracks_file,
                    cfg.recommendations_file,
                    cfg.recommendations_file + artifacts.TENSOR_ARTIFACT_SUFFIX,
                    cfg.artists_mapping_file,
                    cfg.track_info_file,
                    cfg.repeated_tracks_file,
                ],
                token=token_value,
            )
        token = registry.append_history_and_invalidate(
            cfg, run_index, selected, timestamp=token_value
        )
    print(f"Job finished at {get_current_time_str()}")

    return JobSummary(
        dataset=selected,
        run_index=run_index,
        n_rows=len(table),
        n_playlists=result.n_playlists,
        n_tracks=result.n_tracks,
        n_songs_missing=tensors.n_songs_missing,
        rule_generation_s=result.duration_s,
        token=token,
        artifact_paths=paths,
    )
