"""Support-sweep experiment harness — the reference's disabled
``experiment_supports`` loop resurrected as a first-class benchmark driver
(reference: machine-learning/main.py:450-473; its output chart — coverage vs
min_support vs runtime — appears in the project report p.5).

Reference behavior: loop min_support over ``arange(0.03, 0.2, 0.0025)``,
re-run rule generation per support, record (missing songs, duration) to
``fp_growth_experiment_results.csv``.

TPU-first improvement: the pair-count matrix does not depend on min_support,
so it's computed ONCE and only the (cheap, device-side) threshold + top-k
emission re-runs per support point — turning the reference's
full-re-mine-per-point sweep into one matmul plus N emissions. Both phases
are timed separately and recorded honestly.

Run: ``python -m kmlserver_tpu.mining.sweep`` (env: BASE_DIR/DATASETS_DIR
as the job, plus KMLS_SWEEP_START/STOP/STEP).
"""

from __future__ import annotations

import os
import sys
import time

import jax
import numpy as np

from ..config import BASE_INDEX, MiningConfig
from ..data.csv import read_tracks
from ..io import registry
from ..io.artifacts import atomic_write_text
from ..ops import rules as rules_mod
from .miner import (
    native_cpu_eligible, native_pair_counts, pair_count_fn, prune_infrequent,
)
from .vocab import build_baskets

RESULTS_FILE = "fp_growth_experiment_results.csv"


def run_sweep(
    cfg: MiningConfig,
    supports: np.ndarray,
    dataset: str | None = None,
    mesh=None,
) -> list[dict]:
    """→ one record per support point:
    ``{min_support, missing_songs, frequent_items, duration_s}``.

    With ``mesh``, the count-once phase runs sharded (the same
    ``pair_count_fn`` dispatch the miner uses: dense dp×tp or dp-sharded
    bitset slabs); the per-point emissions reuse the replicated counts."""
    if dataset is None:
        datasets = registry.get_dataset_list(cfg)
        index = registry.get_next_run_index(cfg, datasets)
        dataset = datasets[index - BASE_INDEX]
    table = read_tracks(dataset, cfg.sample_ratio)
    baskets = build_baskets(table)
    n_total = baskets.n_tracks

    # resolved before the timer: may trigger the one-time native build
    use_native = native_cpu_eligible(cfg, mesh)

    t0 = time.perf_counter()
    # pruning must use the SMALLEST support in the sweep to stay exact for
    # every point
    mined_baskets = baskets
    if baskets.n_tracks > cfg.prune_vocab_threshold:
        from ..ops.support import min_count_for

        mined_baskets, _ = prune_infrequent(
            baskets, min_count_for(float(supports.min()), baskets.n_playlists)
        )
    if use_native:
        # the miner's native CPU fallback, via its own gate + call helpers
        counts = native_pair_counts(mined_baskets)
        emit = rules_mod.mine_rules_from_counts_np
    else:
        counts, _, _ = pair_count_fn(
            mined_baskets, mesh,
            bitpack_threshold_elems=cfg.bitpack_threshold_elems,
            sharded_impl=cfg.sharded_impl,
            hbm_budget_bytes=cfg.hbm_budget_bytes,
        )
        jax.block_until_ready(counts)
        emit = rules_mod.mine_rules_from_counts
    count_s = time.perf_counter() - t0
    print(f"pair counts once: {count_s:.3f}s (shared across the sweep)")

    records = []
    for s in supports:
        t0 = time.perf_counter()
        tensors = emit(
            counts,
            n_playlists=mined_baskets.n_playlists,
            min_support=float(s),
            k_max=cfg.k_max_consequents,
            mode=cfg.confidence_mode,
            min_confidence=cfg.min_confidence,
            n_total_songs=n_total,
        )
        duration = time.perf_counter() - t0
        records.append(
            {
                # full precision: rounding here would change min_count_for
                # at exact-threshold points (rounded only for CSV display)
                "min_support": float(s),
                "missing_songs": tensors.n_songs_missing,
                "frequent_items": tensors.n_frequent_items,
                "duration_s": round(duration, 6),
            }
        )
        print(
            f"min_support {s:.4f}: missing {tensors.n_songs_missing}, "
            f"emission {duration * 1e3:.1f}ms"
        )
    return records


def write_results_csv(cfg: MiningConfig, records: list[dict]) -> str:
    path = os.path.join(cfg.base_dir, RESULTS_FILE)
    header = "min_support,missing_songs,frequent_items,duration_s"
    lines = [header] + [
        f'{round(r["min_support"], 6)},{r["missing_songs"]},'
        f'{r["frequent_items"]},{r["duration_s"]}'
        for r in records
    ]
    atomic_write_text(path, "\n".join(lines) + "\n")
    return path


def main() -> int:
    cfg = MiningConfig.from_env()
    start = float(os.getenv("KMLS_SWEEP_START", "0.03"))
    stop = float(os.getenv("KMLS_SWEEP_STOP", "0.2"))
    step = float(os.getenv("KMLS_SWEEP_STEP", "0.0025"))
    supports = np.arange(start, stop, step)  # reference grid (main.py:452)
    # the sweep honors the same KMLS_MESH_SHAPE contract as the mining job,
    # including multi-host bootstrap: under a distributed runtime
    # KMLS_MESH_SHAPE=auto must build the hybrid DCN×ICI mesh, not a flat
    # local-device one (ADVICE r4 #2)
    from ..parallel.distributed import maybe_initialize, resolve_mesh

    distributed = maybe_initialize()
    records = run_sweep(
        cfg, supports,
        mesh=resolve_mesh(cfg.mesh_shape, distributed=distributed),
    )
    path = write_results_csv(cfg, records)
    print(f"wrote {len(records)} sweep points to {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
