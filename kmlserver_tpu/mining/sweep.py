"""Support-sweep experiment harness — the reference's disabled
``experiment_supports`` loop resurrected as a first-class benchmark driver
(reference: machine-learning/main.py:450-473; its output chart — coverage vs
min_support vs runtime — appears in the project report p.5).

Reference behavior: loop min_support over ``arange(0.03, 0.2, 0.0025)``,
re-run rule generation per support, record (missing songs, duration) to
``fp_growth_experiment_results.csv``.

TPU-first improvement: the pair-count matrix does not depend on min_support,
so it's computed ONCE and only the (cheap, device-side) threshold + top-k
emission re-runs per support point — turning the reference's
full-re-mine-per-point sweep into one matmul plus N emissions. Both phases
are timed separately and recorded honestly.

Run: ``python -m kmlserver_tpu.mining.sweep`` (env: BASE_DIR/DATASETS_DIR
as the job, plus KMLS_SWEEP_START/STOP/STEP).
"""

from __future__ import annotations

import os
import sys
import time

import jax
import numpy as np

from ..config import BASE_INDEX, MiningConfig
from ..data.csv import read_tracks
from ..io import registry
from ..io.artifacts import atomic_write_text
from ..ops import rules as rules_mod
from .miner import (
    native_cpu_eligible, native_pair_counts, pair_count_fn, prune_infrequent,
)
from .vocab import build_baskets

RESULTS_FILE = "fp_growth_experiment_results.csv"


def run_sweep(
    cfg: MiningConfig,
    supports: np.ndarray,
    dataset: str | None = None,
    mesh=None,
) -> list[dict]:
    """→ one record per support point:
    ``{min_support, missing_songs, frequent_items, duration_s}``.

    With ``mesh``, the count-once phase runs sharded (the same
    ``pair_count_fn`` dispatch the miner uses: dense dp×tp or dp-sharded
    bitset slabs); the per-point emissions reuse the replicated counts."""
    if dataset is None:
        datasets = registry.get_dataset_list(cfg)
        index = registry.get_next_run_index(cfg, datasets)
        dataset = datasets[index - BASE_INDEX]
    table = read_tracks(dataset, cfg.sample_ratio)
    baskets = build_baskets(table)
    n_total = baskets.n_tracks

    # resolved before the timer: may trigger the one-time native build
    use_native = native_cpu_eligible(cfg, mesh)

    t0 = time.perf_counter()
    # pruning must use the SMALLEST support in the sweep to stay exact for
    # every point
    mined_baskets = baskets
    if baskets.n_tracks > cfg.prune_vocab_threshold:
        from ..ops.support import min_count_for

        mined_baskets, _ = prune_infrequent(
            baskets, min_count_for(float(supports.min()), baskets.n_playlists)
        )
    if use_native:
        # the miner's native CPU fallback, via its own gate + call helpers
        counts = native_pair_counts(mined_baskets)
        emit = rules_mod.mine_rules_from_counts_np
    else:
        counts, _, _ = pair_count_fn(
            mined_baskets, mesh,
            bitpack_threshold_elems=cfg.bitpack_threshold_elems,
            sharded_impl=cfg.sharded_impl,
            hbm_budget_bytes=cfg.hbm_budget_bytes,
        )
        jax.block_until_ready(counts)
        emit = rules_mod.mine_rules_from_counts
    count_s = time.perf_counter() - t0
    print(f"pair counts once: {count_s:.3f}s (shared across the sweep)")

    records = []
    for s in supports:
        t0 = time.perf_counter()
        tensors = emit(
            counts,
            n_playlists=mined_baskets.n_playlists,
            min_support=float(s),
            k_max=cfg.k_max_consequents,
            mode=cfg.confidence_mode,
            min_confidence=cfg.min_confidence,
            n_total_songs=n_total,
        )
        duration = time.perf_counter() - t0
        records.append(
            {
                # full precision: rounding here would change min_count_for
                # at exact-threshold points (rounded only for CSV display)
                "min_support": float(s),
                "missing_songs": tensors.n_songs_missing,
                "frequent_items": tensors.n_frequent_items,
                "duration_s": round(duration, 6),
            }
        )
        print(
            f"min_support {s:.4f}: missing {tensors.n_songs_missing}, "
            f"emission {duration * 1e3:.1f}ms"
        )
    return records


DENSITY_GRID = (0.05, 0.01, 0.002, 0.0005, 0.00005)
DENSITY_SHAPES = ((4000, 1000), (20000, 2000), (80000, 2500))


def run_density_sweep(
    densities=DENSITY_GRID,
    shapes=DENSITY_SHAPES,
    *,
    seed: int = 123,
    max_elems: int | None = None,
    max_rows: int = 4_000_000,
    dense_max_elems: int = 1 << 25,
    sparse_max_events: int = 150_000_000,
    repeat: int = 1,
) -> list[dict]:
    """The DENSITY axis of the sweep (ISSUE 13): time all three count
    families — dense MXU contraction, bit-packed unpack-matmul, sparse
    CSR×bitpacked hybrid — on synthetic workloads across a
    (density, shape) grid, verify the counts bit-identical per point,
    and record per-path wall clock. One record per measured point:

    ``{density, elems, shape, rows, dense_s, bitpack_s, sparse_s,
    identical, winner}``

    This IS the measurement that populates the dispatch lookup table
    (``mining/dispatch.table_from_records``): the bench's
    ``scale_sparse`` phase runs it on the live backend and banks the
    result, and the packaged ``dispatch_table.json`` carries the last
    banked sweep. Timings exclude compile (one warm pass per jitted
    path); best-of-``repeat`` keeps a neighbor's noise out of a cell."""
    import jax.numpy as jnp

    from ..data.synthetic import synthetic_baskets
    from ..ops import encode as encode_mod
    from ..ops import popcount as pc
    from ..ops import sparse as sparse_mod
    from ..ops import support as support_mod

    records = []
    for n_playlists, n_tracks in shapes:
        elems = n_playlists * n_tracks
        if max_elems is not None and elems > max_elems:
            continue
        for density in densities:
            target = int(density * elems)
            if target < 16 or target > max_rows:
                continue
            baskets = synthetic_baskets(
                n_playlists=n_playlists, n_tracks=n_tracks,
                target_rows=target, seed=seed,
            )
            rows = len(baskets.playlist_rows)
            results: dict[str, np.ndarray] = {}
            timings: dict[str, float | None] = {
                "dense": None, "bitpack": None, "sparse": None,
            }

            def best_of(fn):
                best = None
                out = None
                for _ in range(max(repeat, 1)):
                    t0 = time.perf_counter()
                    out = fn()
                    dt = time.perf_counter() - t0
                    best = dt if best is None else min(best, dt)
                return out, best

            def run_dense():
                x = encode_mod.onehot_matrix(
                    jnp.asarray(baskets.playlist_rows),
                    jnp.asarray(baskets.track_ids),
                    n_playlists=n_playlists, n_tracks=n_tracks,
                )
                return np.asarray(
                    jax.block_until_ready(support_mod.pair_counts(x))
                )

            def run_bitpack():
                return np.asarray(
                    jax.block_until_ready(
                        pc.popcount_pair_counts(
                            baskets.playlist_rows, baskets.track_ids,
                            n_playlists=n_playlists, n_tracks=n_tracks,
                            impl="mxu",
                        )
                    )
                )

            def run_sparse():
                return sparse_mod.sparse_pair_counts_np(
                    baskets.playlist_rows, baskets.track_ids,
                    n_playlists=n_playlists, n_tracks=n_tracks,
                )

            # per-path guards keep the grid affordable — an unmeasured
            # path is an HONEST None (the table lookup then can't pick
            # it for the cell), never a silently extrapolated number
            if elems <= dense_max_elems:
                run_dense()  # warm: compile is env prep, not counting
                results["dense"], timings["dense"] = best_of(run_dense)
            run_bitpack()
            results["bitpack"], timings["bitpack"] = best_of(run_bitpack)
            events, _ = sparse_mod.pair_event_count(
                baskets.playlist_rows, n_playlists
            )
            if events <= sparse_max_events:
                results["sparse"], timings["sparse"] = best_of(run_sparse)

            ref_name = next(k for k in ("dense", "bitpack") if k in results)
            identical = all(
                np.array_equal(results[ref_name], other)
                for other in results.values()
            )
            timed = {k: v for k, v in timings.items() if v is not None}
            winner = min(timed, key=timed.get)
            records.append(
                {
                    "density": rows / max(elems, 1),
                    "elems": elems,
                    "shape": f"{n_playlists}x{n_tracks}",
                    "rows": rows,
                    **{
                        f"{k}_s": (None if v is None else round(v, 5))
                        for k, v in timings.items()
                    },
                    "identical": identical,
                    "winner": winner,
                }
            )
            print(
                f"density {rows / max(elems, 1):.5f} {n_playlists}x"
                f"{n_tracks}: "
                + " ".join(
                    f"{k} {v:.3f}s" for k, v in timed.items()
                )
                + f" -> {winner} (identical={identical})"
            )
    return records


def write_results_csv(cfg: MiningConfig, records: list[dict]) -> str:
    path = os.path.join(cfg.base_dir, RESULTS_FILE)
    header = "min_support,missing_songs,frequent_items,duration_s"
    lines = [header] + [
        f'{round(r["min_support"], 6)},{r["missing_songs"]},'
        f'{r["frequent_items"]},{r["duration_s"]}'
        for r in records
    ]
    atomic_write_text(path, "\n".join(lines) + "\n")
    return path


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "density":
        # the density axis (ISSUE 13): measure the three count families
        # across the (density, shape) grid and bank the winners into a
        # measured dispatch table — `python -m kmlserver_tpu.mining.sweep
        # density [table_out.json]` (default: the packaged table the
        # dispatcher consults).
        import socket

        from . import dispatch as dispatch_mod

        records = run_density_sweep()
        dev = jax.devices()[0]
        table = dispatch_mod.table_from_records(
            records, jax.default_backend(),
            measured_on=f"{socket.gethostname()}/{dev.device_kind}",
            banked_at=time.time(),
            base=dispatch_mod.load_table(),
        )
        out = (
            sys.argv[2] if len(sys.argv) > 2
            else dispatch_mod.builtin_table_path()
        )
        dispatch_mod.save_table(out, table)
        print(
            f"wrote measured dispatch table ({len(records)} points, "
            f"backend {jax.default_backend()}) to {out}"
        )
        return 0
    cfg = MiningConfig.from_env()
    start = float(os.getenv("KMLS_SWEEP_START", "0.03"))
    stop = float(os.getenv("KMLS_SWEEP_STOP", "0.2"))
    step = float(os.getenv("KMLS_SWEEP_STEP", "0.0025"))
    supports = np.arange(start, stop, step)  # reference grid (main.py:452)
    # the sweep honors the same KMLS_MESH_SHAPE contract as the mining job,
    # including multi-host bootstrap: under a distributed runtime
    # KMLS_MESH_SHAPE=auto must build the hybrid DCN×ICI mesh, not a flat
    # local-device one (ADVICE r4 #2)
    from ..parallel.distributed import maybe_initialize, resolve_mesh

    distributed = maybe_initialize()
    records = run_sweep(
        cfg, supports,
        mesh=resolve_mesh(cfg.mesh_shape, distributed=distributed),
    )
    path = write_results_csv(cfg, records)
    print(f"wrote {len(records)} sweep points to {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
