"""Vocabulary + auxiliary mapping artifacts (reference components M5-M8, M10).

Everything here is the host-side ID⇄name layer the device kernels depend on:
the mining compute works on dense int track-ids; these builders produce the
id↔name vocabulary plus the four auxiliary artifacts the reference pickles
(reference: machine-learning/main.py:51-133, 168-184, 195-207).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..data.csv import TrackTable


class DuplicateArtistURIError(ValueError):
    """Raised when one artist_name maps to more than one artist_uri —
    mirroring the reference's validation failure
    (reference: machine-learning/main.py:62-68)."""


@dataclasses.dataclass
class Vocab:
    """Track-name vocabulary: sorted unique names ↔ dense int ids."""

    names: list[str]
    index: dict[str, int]

    @staticmethod
    def build(track_names: np.ndarray) -> "Vocab":
        names = sorted(set(track_names.tolist()))
        return Vocab(names=names, index={n: i for i, n in enumerate(names)})

    def __len__(self) -> int:
        return len(self.names)

    def encode(self, track_names: np.ndarray) -> np.ndarray:
        """Vectorized name→id (int32) via binary search over the sorted name
        array (the per-row Python dict loop costs seconds at reference CSV
        scale). Unknown names map to -1."""
        names_arr = np.asarray(self.names, dtype=object)
        queries = np.asarray(track_names, dtype=object)
        pos = np.searchsorted(names_arr, queries)
        pos = np.clip(pos, 0, len(names_arr) - 1)
        ids = np.where(names_arr[pos] == queries, pos, -1)
        return ids.astype(np.int32)


def validate_and_map_artists(table: TrackTable) -> dict[str, str]:
    """artist_name → artist_uri, raising if any name maps to >1 distinct URI
    (reference: validate_and_map_artists_names_to_ids main.py:51-83)."""
    if table.artist_name is None or table.artist_uri is None:
        return {}
    mapping: dict[str, str] = {}
    duplicates: dict[str, set[str]] = {}
    for name, uri in zip(table.artist_name, table.artist_uri):
        name, uri = str(name), str(uri)
        prev = mapping.get(name)
        if prev is None:
            mapping[name] = uri
        elif prev != uri:
            duplicates.setdefault(name, {prev}).add(uri)
    if duplicates:
        raise DuplicateArtistURIError(
            f"{len(duplicates)} artist names map to multiple URIs, e.g. "
            f"{dict(list(duplicates.items())[:3])}"
        )
    return mapping


def extract_repeated_track_names(table: TrackTable) -> dict[str, list[str]]:
    """track_name → list of distinct track_uris, only for names with >1 URI
    (reference: extract_repeated_track_names main.py:86-109)."""
    if table.track_uri is None:
        return {}
    uris: dict[str, set[str]] = {}
    for name, uri in zip(table.track_name, table.track_uri):
        uris.setdefault(str(name), set()).add(str(uri))
    return {name: sorted(u) for name, u in uris.items() if len(u) > 1}


def map_track_ids_to_info(table: TrackTable) -> dict[str, dict[str, str]]:
    """track_uri → first-seen {track_name, artist_name, album_name}
    (reference: map_song_ids_to_song_info main.py:112-133)."""
    if table.track_uri is None:
        return {}
    info: dict[str, dict[str, str]] = {}
    artist = table.artist_name if table.artist_name is not None else np.repeat("", len(table))
    album = table.album_name if table.album_name is not None else np.repeat("", len(table))
    for uri, name, art, alb in zip(table.track_uri, table.track_name, artist, album):
        uri = str(uri)
        if uri not in info:
            info[uri] = {
                "track_name": str(name),
                "artist_name": str(art),
                "album_name": str(alb),
            }
    return info


def most_frequent_tracks(
    table: TrackTable, top_percentile: float
) -> list[dict[str, object]]:
    """Row-count popularity ranking, keeping the top ``top_percentile``
    fraction, as a list of ``{"track_name": ..., "count": ...}`` descending —
    the exact ``best_tracks.pickle`` object shape
    (reference: get_most_frequent_tracks + filter_best_tracks
    main.py:168-184, saved at :443-446).

    The keep count TRUNCATES (``int(N · pct)``, no minimum) to match the
    reference's slice — with a tiny vocabulary this can legitimately be
    empty, exactly as a reference-written PVC could be."""
    names, counts = np.unique(table.track_name, return_counts=True)
    order = np.lexsort((names, -counts))  # count desc, name asc for stable ties
    keep = int(len(names) * top_percentile)
    return [
        {"track_name": str(names[i]), "count": int(counts[i])}
        for i in order[:keep]
    ]


@dataclasses.dataclass
class Baskets:
    """The transaction DB in tensor form: deduplicated (playlist_row, track_id)
    membership pairs over dense ids — the device-side replacement for the
    reference's ``{pid: [track_name, ...]}`` dict
    (reference: group_tracks_by_playlist_and_generate_homogeneous_data
    main.py:195-207)."""

    playlist_rows: np.ndarray  # int32, dense 0..P-1
    track_ids: np.ndarray  # int32, dense 0..V-1
    n_playlists: int
    vocab: Vocab

    @property
    def n_tracks(self) -> int:
        return len(self.vocab)


def build_baskets(table: TrackTable, vocab: Vocab | None = None) -> Baskets:
    """Group memberships by pid into dense-id pairs, deduplicating repeated
    (pid, track) rows so each membership contributes one count — matching the
    reference, where baskets are dicts keyed by name and the one-hot encoder
    sets a boolean (machine-learning/main.py:195-207, 267-269)."""
    vocab = vocab or Vocab.build(table.track_name)
    pids, playlist_rows = np.unique(table.pid, return_inverse=True)
    track_ids = vocab.encode(table.track_name)
    valid = track_ids >= 0
    pairs = np.stack(
        [playlist_rows[valid].astype(np.int64), track_ids[valid].astype(np.int64)], axis=1
    )
    pairs = np.unique(pairs, axis=0)
    return Baskets(
        playlist_rows=pairs[:, 0].astype(np.int32),
        track_ids=pairs[:, 1].astype(np.int32),
        n_playlists=len(pids),
        vocab=vocab,
    )
