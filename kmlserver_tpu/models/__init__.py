"""Model layer — what "model" means in this framework.

The reference system has no neural network: its "model" is the association-
rule artifact the mining job produces and the API serves (reference:
machine-learning/main.py:262-313 produces it; rest_api/app/main.py:224-254
applies it). This package names that abstraction explicitly:

- :class:`RuleModel` — the deployable unit: HBM-resident rule tensors +
  vocabulary + the jitted apply (recommendation) function.
- two model *families*, selected by ``MiningConfig.confidence_mode``:
  ``"support"`` (the reference fast path's symmetric support-as-confidence
  rules) and ``"confidence"`` (true asymmetric confidence with
  multi-antecedent rules, the slow path's semantics).

Training = ``kmlserver_tpu.mining.miner.mine``; inference =
``kmlserver_tpu.ops.serve.recommend_batch``; serialization =
``kmlserver_tpu.io.artifacts``. This module composes them into the
model-object view without duplicating any of it.
"""

from .rule_model import RuleModel  # noqa: F401
