"""Model layer — what "model" means in this framework.

The reference system has no neural network: its "model" is the association-
rule artifact the mining job produces and the API serves (reference:
machine-learning/main.py:262-313 produces it; rest_api/app/main.py:224-254
applies it). This package names that abstraction explicitly:

- :class:`RuleModel` — the deployable rule unit: HBM-resident rule
  tensors + vocabulary + the jitted apply (recommendation) function.
  Two rule sub-families, selected by ``MiningConfig.confidence_mode``:
  ``"support"`` (the reference fast path's symmetric support-as-confidence
  rules) and ``"confidence"`` (true asymmetric confidence with
  multi-antecedent rules, the slow path's semantics).
- :class:`EmbeddingModel` — the SECOND model family (ISSUE 6): ALS item
  embeddings over the same playlist×track matrix, opening the cold-start
  and long-tail scenarios association rules structurally miss. Same
  artifact spine (manifest + lease-fenced publication), second writer.

Training = ``kmlserver_tpu.mining.miner.mine`` /
``kmlserver_tpu.mining.als.train_embeddings``; inference =
``kmlserver_tpu.ops.serve.recommend_batch`` /
``kmlserver_tpu.ops.embed.embed_topk``; serialization =
``kmlserver_tpu.io.artifacts``. This module composes them into the
model-object view without duplicating any of it.
"""

from .embedding_model import EmbeddingModel  # noqa: F401
from .rule_model import RuleModel  # noqa: F401
