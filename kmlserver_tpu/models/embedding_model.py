"""The deployable embedding model: item factors + vocab + jitted apply.

The second model family's twin of :class:`~.rule_model.RuleModel` — same
three primitives, different math: training is ALS matrix factorization
(``mining/als.py``), inference is the cosine top-k kernel
(``ops/embed.py``), serialization is the manifest-covered
``embeddings.npz`` (``io/artifacts.py``). The serving engine carries the
factors inside its :class:`~kmlserver_tpu.serving.engine.RuleBundle`
replicas for the hybrid merge; this object is the standalone view for
library users who want embedding recommendations without the job/API
stack.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MiningConfig
from ..io import artifacts
from ..mining.vocab import Baskets
from ..ops.embed import embed_topk


@dataclasses.dataclass
class EmbeddingModel:
    """ALS item-embedding model over a track vocabulary."""

    vocab: list[str]
    index: dict[str, int]
    item_factors: jax.Array  # float32 (V, rank), rows L2-normalized, device
    rank: int

    # ---------- construction ----------

    @classmethod
    def _from_factors(
        cls, vocab: list[str], item_factors: np.ndarray
    ) -> "EmbeddingModel":
        """The one place host factors become a device-resident model."""
        return cls(
            vocab=list(vocab),
            index={n: i for i, n in enumerate(vocab)},
            item_factors=jax.device_put(jnp.asarray(item_factors)),
            rank=int(item_factors.shape[1]),
        )

    @staticmethod
    def fit(
        baskets: Baskets, cfg: MiningConfig | None = None
    ) -> "EmbeddingModel":
        """Train from a transaction DB (the ALS "training" step)."""
        from ..mining.als import train_embeddings

        cfg = cfg or MiningConfig()
        result = train_embeddings(baskets, cfg)
        return EmbeddingModel._from_factors(
            baskets.vocab.names, result["item_factors"]
        )

    @staticmethod
    def load(npz_path: str) -> "EmbeddingModel":
        """Load from the embedding artifact the mining job publishes."""
        loaded = artifacts.load_embeddings(npz_path)
        return EmbeddingModel._from_factors(
            loaded["vocab"], loaded["item_factors"]
        )

    # ---------- inference ----------

    def encode_seeds(
        self, seed_sets: list[list[str]], pad_len: int | None = None
    ) -> np.ndarray:
        """Seed names → int32 (B, L) id batch, -1 padded; unknown names drop."""
        ids = [
            [self.index[s] for s in seeds if s in self.index]
            for seeds in seed_sets
        ]
        length = pad_len or max((len(r) for r in ids), default=1) or 1
        out = np.full((len(seed_sets), length), -1, dtype=np.int32)
        for r, row in enumerate(ids):
            out[r, : min(len(row), length)] = row[:length]
        return out

    def recommend(
        self, seed_sets: list[list[str]], k_best: int = 10
    ) -> list[list[str]]:
        """Batched apply: ONE device call for the whole batch, with the
        same power-of-two shape bucketing as :class:`RuleModel` so varying
        call shapes reuse a bounded compiled-kernel set."""
        longest = max((len(s) for s in seed_sets), default=1)
        pad_len = 1 << max(longest - 1, 0).bit_length()
        seed_arr = self.encode_seeds(seed_sets, pad_len=pad_len)
        n_rows = 1 << max(len(seed_sets) - 1, 0).bit_length()
        if n_rows > seed_arr.shape[0]:
            seed_arr = np.concatenate(
                [seed_arr, np.full((n_rows - seed_arr.shape[0], pad_len), -1,
                                   dtype=np.int32)]
            )
        top_ids, _ = self.apply_fn(k_best)(
            self.item_factors, jnp.asarray(seed_arr)
        )
        top_ids = np.asarray(top_ids)[: len(seed_sets)]
        return [
            [self.vocab[int(i)] for i in row if i >= 0] for row in top_ids
        ]

    @staticmethod
    def apply_fn(k_best: int = 10):
        """The raw jittable forward step (cosine top-k over item space)."""
        return partial(embed_topk, k_best=k_best)
