"""The deployable rule model: tensors + vocab + jitted apply.

Composes the existing pieces (miner → tensors, artifacts → persistence,
ops/serve → apply) into one object, for library users who want the model
without running the full job/API stack. The serving engine keeps its own
:class:`~kmlserver_tpu.serving.engine.RuleBundle` (adds hot-swap state);
both sit on the same three primitives.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MiningConfig
from ..io import artifacts
from ..mining.vocab import Baskets
from ..ops.serve import recommend_batch


@dataclasses.dataclass
class RuleModel:
    """Association-rule model over a track vocabulary."""

    vocab: list[str]
    index: dict[str, int]
    rule_ids: jax.Array  # int32 (V, K_max), device
    rule_confs: jax.Array  # float32 (V, K_max), device
    mode: str  # "support" | "confidence" (the model family)

    # ---------- construction ----------

    @classmethod
    def _from_tensors(
        cls, vocab: list[str], rule_ids, rule_confs, mode: str
    ) -> "RuleModel":
        """The one place host tensors become a device-resident model."""
        return cls(
            vocab=list(vocab),
            index={n: i for i, n in enumerate(vocab)},
            rule_ids=jax.device_put(jnp.asarray(rule_ids)),
            rule_confs=jax.device_put(jnp.asarray(rule_confs)),
            mode=mode,
        )

    @staticmethod
    def fit(
        baskets: Baskets,
        cfg: MiningConfig | None = None,
        mesh: "jax.sharding.Mesh | None" = None,
    ) -> "RuleModel":
        """Mine a model from a transaction DB (the "training" step)."""
        from ..mining.miner import mine

        cfg = cfg or MiningConfig()
        result = mine(baskets, cfg, mesh=mesh)
        t = result.tensors
        return RuleModel._from_tensors(
            result.vocab_names, t.rule_ids, t.rule_confs, t.mode
        )

    @staticmethod
    def load(npz_path: str) -> "RuleModel":
        """Load from the tensor-native artifact the mining job writes."""
        loaded = artifacts.load_rule_tensors(npz_path)
        return RuleModel._from_tensors(
            loaded["vocab"], loaded["rule_ids"], loaded["rule_confs"],
            loaded["mode"],
        )

    # ---------- inference ----------

    def encode_seeds(
        self, seed_sets: list[list[str]], pad_len: int | None = None
    ) -> np.ndarray:
        """Seed names → int32 (B, L) id batch, -1 padded; unknown names drop."""
        ids = [
            [self.index[s] for s in seeds if s in self.index]
            for seeds in seed_sets
        ]
        length = pad_len or max((len(r) for r in ids), default=1) or 1
        out = np.full((len(seed_sets), length), -1, dtype=np.int32)
        for r, row in enumerate(ids):
            out[r, : min(len(row), length)] = row[:length]
        return out

    def recommend(
        self, seed_sets: list[list[str]], k_best: int = 10
    ) -> list[list[str]]:
        """Batched apply: ONE device call for the whole batch. Batch and
        seed-length dims are bucketed to powers of two so naturally varying
        call shapes reuse a bounded set of compiled kernels (the same
        strategy as the serving engine's shape buckets) instead of paying a
        fresh jit compile per distinct (B, L)."""
        longest = max((len(s) for s in seed_sets), default=1)
        pad_len = 1 << max(longest - 1, 0).bit_length()
        seed_arr = self.encode_seeds(seed_sets, pad_len=pad_len)
        n_rows = 1 << max(len(seed_sets) - 1, 0).bit_length()
        if n_rows > seed_arr.shape[0]:
            seed_arr = np.concatenate(
                [seed_arr, np.full((n_rows - seed_arr.shape[0], pad_len), -1,
                                   dtype=np.int32)]
            )
        top_ids, _ = self.apply_fn(k_best)(
            self.rule_ids, self.rule_confs, jnp.asarray(seed_arr)
        )
        top_ids = np.asarray(top_ids)[: len(seed_sets)]
        return [
            [self.vocab[int(i)] for i in row if i >= 0] for row in top_ids
        ]

    @staticmethod
    def apply_fn(k_best: int = 10):
        """The raw jittable forward step (what ``__graft_entry__`` exposes)."""
        return partial(recommend_batch, k_best=k_best)
