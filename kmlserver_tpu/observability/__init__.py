"""Observability layer: per-request span tracing with tail-based
retention (``trace``, ISSUE 9), runtime-health collection — event-loop
lag + inline-kernel stalls — feeding the admission ladder (``runtime``),
mining-side textfile telemetry (``jobmetrics``), device-truth cost
attribution — per-kernel MFU/roofline, memory and compile telemetry
(``costmodel``, ISSUE 12) — and multi-window SLO burn rates (``slo``).
Serving metrics exposition itself stays in ``serving/metrics.py``;
everything here joins its ``METRIC_REGISTRY``."""

from __future__ import annotations

from .costmodel import KERNEL_COST_SPECS, CostModel
from .runtime import LoopLagMonitor
from .slo import SloTracker
from .trace import SpanRecorder, TraceContext

__all__ = [
    "CostModel",
    "KERNEL_COST_SPECS",
    "LoopLagMonitor",
    "SloTracker",
    "SpanRecorder",
    "TraceContext",
]
