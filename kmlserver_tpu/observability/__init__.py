"""Observability layer (ISSUE 9): per-request span tracing with
tail-based retention (``trace``), runtime-health collection — event-loop
lag + inline-kernel stalls — feeding the admission ladder (``runtime``),
and mining-side textfile telemetry (``jobmetrics``). Serving metrics
exposition itself stays in ``serving/metrics.py``; everything here joins
its ``METRIC_REGISTRY``."""

from __future__ import annotations

from .runtime import LoopLagMonitor
from .trace import SpanRecorder, TraceContext

__all__ = ["LoopLagMonitor", "SpanRecorder", "TraceContext"]
