"""Device-truth cost attribution (ISSUE 12): per-kernel MFU/roofline,
memory accounting, and compile telemetry.

PR 9 made every *request* visible; this module makes the *device* side
visible: where device time goes per kernel, how far each jitted kernel
sits from the backend's peak FLOP/s (MFU), whether it is compute- or
bandwidth-bound (roofline), how much HBM headroom the layout decision
actually has, and whether a compile ever sneaks onto the serving path in
production — the invariant that until now lived only in tests.

Three pieces:

- **Analytic cost specs** (:data:`KERNEL_COST_SPECS`): for every jitted
  kernel the project dispatches — the rule scatter-max serve kernel
  (``ops/serve.py recommend_batch``), its vocab-sharded twin
  (``sharded_recommend_fn``), the native host kernel (same algorithm,
  host peaks), the embedding cosine top-k (``ops/embed.py embed_topk``),
  the ALS half-sweeps (``mining/als.py``), the pair-support count
  (``parallel/support.py`` / ``ops/support.py``), and the delta
  restricted recount (``parallel/support.restricted_pair_counts``) — a
  FLOPs(shape) and bytes-moved(shape) formula. The formulas are
  leading-order analytic counts (matmul 2·m·n·k, scatter/compare work,
  top-k ~ n·log2(k)), not instrumented truth: combined with the fenced
  device timings the serving/mining paths already take, they yield
  achieved FLOP/s, achieved bytes/s, MFU against the backend peak, and
  a roofline classification (arithmetic intensity vs the ridge point).

- **Peak table**: per-device-kind dense peak FLOP/s and HBM bytes/s,
  overridable via ``KMLS_PEAK_FLOPS`` / ``KMLS_PEAK_BYTES_PER_S`` (the
  TPU window pins the exact chip; the CPU default is deliberately
  generous so MFU stays a LOWER bound and never exceeds 1).

- **:class:`CostModel`**: the serving-side accumulator. The engine calls
  :meth:`observe_kernel` on the completion path with the fenced device
  seconds and the dispatch shape; ``/metrics`` renders
  ``kmls_kernel_device_seconds{kernel}`` and friends from it. It also
  carries the compile watcher (``kmls_compiles_total{kernel}`` — jit
  cache growth after ``mark_published``, the live form of the
  zero-compiles-post-publish invariant) and the publish-time memory
  accounting (analytic tensor bytes vs ``KMLS_DEVICE_BUDGET_BYTES`` +
  live ``memory_stats()`` gauges where the backend provides them).

Zero-cost when disabled (``KMLS_COSTMODEL=0``): the engine holds no
CostModel at all and every call site is one ``is not None`` check. The
module-level :data:`OBSERVATIONS_TOTAL` counter proves it the same way
the compile counter proves zero-compile serving: a test drives traffic
with the knob off and asserts the counter never moved.

kmls-verify's ``costspec`` checker (analysis/costspec.py) keeps this
honest statically: every ``observe_kernel("<name>", ...)`` call site
must name a registered spec, every spec must have a call site, and every
series rendered here must be in ``METRIC_REGISTRY``.
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
from typing import Callable

# module-level observation counter — the zero-cost proof (began-counter
# discipline, ISSUE 9): must never move while KMLS_COSTMODEL=0, because
# a disabled engine holds no CostModel and nothing can reach
# observe_kernel. Benign GIL-coalesced increments, diagnostics only.
OBSERVATIONS_TOTAL = 0

PEAK_FLOPS_ENV = "KMLS_PEAK_FLOPS"
PEAK_BYTES_ENV = "KMLS_PEAK_BYTES_PER_S"

# per-chip dense peak (FLOP/s, HBM bytes/s) by device-kind substring,
# matched case-insensitively in order. Published bf16-dense MXU peaks —
# our kernels run f32/int32, so MFU reads conservative (a lower bound),
# which is the honest direction for a headline. The CPU entry is a
# deliberately GENEROUS envelope for the same reason: achieved/peak must
# never exceed 1 on any host this runs on.
PEAK_TABLE: tuple[tuple[str, float, float], ...] = (
    ("v6", 918e12, 1640e9),   # v6e (Trillium)
    ("v5p", 459e12, 2765e9),
    ("v5", 197e12, 819e9),    # v5e / "v5 lite" (matched after v5p)
    ("v4", 275e12, 1200e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
    ("cpu", 2e11, 1e11),
)


def resolve_peaks(device=None) -> tuple[float, float, str]:
    """→ ``(peak_flops, peak_bytes_per_s, source)``. Env knobs win
    (``KMLS_PEAK_FLOPS`` / ``KMLS_PEAK_BYTES_PER_S`` — the TPU window
    pins the exact chip); otherwise the table is keyed by the device
    kind of ``device`` (default: the first local device)."""
    env_flops = os.getenv(PEAK_FLOPS_ENV)
    env_bytes = os.getenv(PEAK_BYTES_ENV)
    kind = ""
    if device is None and (not env_flops or not env_bytes):
        import jax

        device = jax.local_devices()[0]
    if device is not None:
        kind = f"{getattr(device, 'platform', '')} {getattr(device, 'device_kind', '')}"
    flops = bw = 0.0
    auto_source = f"auto:{kind.strip()}"
    lowered = kind.lower()
    for needle, table_flops, table_bw in PEAK_TABLE:
        if needle in lowered:
            flops, bw = table_flops, table_bw
            break
    else:
        flops, bw = PEAK_TABLE[-1][1], PEAK_TABLE[-1][2]
        auto_source = f"auto-default:{kind.strip()}"
    if env_flops:
        flops = float(env_flops)
    if env_bytes:
        bw = float(env_bytes)
    # provenance must name BOTH values' origins: with only one knob set
    # the other side of the roofline ridge still comes from the table,
    # and labeling that "env" would claim a calibration nobody did
    if env_flops and env_bytes:
        source = "env"
    elif env_flops or env_bytes:
        source = f"env+{auto_source}"
    else:
        source = auto_source
    return flops, bw, source


def _log2k(k: float) -> float:
    """Comparison depth of a top-k pass, floored at 1."""
    return max(1.0, math.log2(max(float(k), 2.0)))


@dataclasses.dataclass(frozen=True)
class CostSpec:
    """Analytic leading-order cost of one jitted kernel, as functions of
    its dispatch shape (a plain dims dict — missing dims default sanely
    so a partial caller still gets an order-of-magnitude number)."""

    name: str
    flops: Callable[[dict], float]
    bytes_moved: Callable[[dict], float]
    doc: str


def _d(dims: dict, key: str, default: float = 1.0) -> float:
    return float(dims.get(key, default))


def _serve_flops(dims: dict) -> float:
    # gather + scatter-max over b·l·k_max candidate lanes (≈2 ops per
    # lane: compare + select), then top-k over the (b, v) score vector
    b, length, k_max = _d(dims, "b"), _d(dims, "l"), _d(dims, "k_max")
    v, k_best = _d(dims, "v"), _d(dims, "k_best", 10)
    return b * (2.0 * length * k_max + v * _log2k(k_best))


def _serve_bytes(dims: dict) -> float:
    # rule-row gather (ids+confs, 8 B/lane), the transient (b, v+1)
    # score vector written+read, seeds in, top-k out
    b, length, k_max = _d(dims, "b"), _d(dims, "l"), _d(dims, "k_max")
    v, k_best = _d(dims, "v"), _d(dims, "k_best", 10)
    return (
        b * length * (k_max * 8.0 + 4.0)
        + b * (v + 1.0) * 8.0
        + b * k_best * 8.0
    )


def _sharded_serve_flops(dims: dict) -> float:
    # per-shard work is the replicated kernel partitioned (same total),
    # plus the cross-shard merge: shards·k_best candidate lanes per row
    # rescattered + one more global top-k
    b, v = _d(dims, "b"), _d(dims, "v")
    shards, k_best = _d(dims, "shards"), _d(dims, "k_best", 10)
    return _serve_flops(dims) + b * (
        2.0 * shards * k_best + v * _log2k(k_best)
    )


def _sharded_serve_bytes(dims: dict) -> float:
    # adds the all_gather of (shards, b, k_best) partials (both tensors,
    # send+receive) and the merge pass's second (b, v+1) score vector
    b, v = _d(dims, "b"), _d(dims, "v")
    shards, k_best = _d(dims, "shards"), _d(dims, "k_best", 10)
    return _serve_bytes(dims) + 2.0 * shards * b * k_best * 8.0 + b * (
        v + 1.0
    ) * 8.0


def _mesh_serve_flops(dims: dict) -> float:
    # ONE gang member's share of the pod-spanning lookup: the sharded
    # kernel's per-shard half (1/shards of the candidate-lane gather,
    # one slab partial top-k at GLOBAL width) plus the coordinator-side
    # merge over the rank-stacked partials — peers' slab work runs on
    # peer processes and is attributed there
    b, length, k_max = _d(dims, "b"), _d(dims, "l"), _d(dims, "k_max")
    v, shards, k_best = _d(dims, "v"), _d(dims, "shards"), _d(dims, "k_best", 10)
    return b * (
        2.0 * length * k_max / max(shards, 1.0)
        + 2.0 * v * _log2k(k_best)
        + 2.0 * shards * k_best
    )


def _mesh_serve_bytes(dims: dict) -> float:
    # slab gather (1/shards of the rule lanes) + the partial and merge
    # passes' (b, v+1) score vectors + the gang exchange: the seed batch
    # sent to every peer and (shards-1) stacked (b, k_best) partials
    # received over DCN (or the simulation transport's sockets)
    b, length, k_max = _d(dims, "b"), _d(dims, "l"), _d(dims, "k_max")
    v, shards, k_best = _d(dims, "v"), _d(dims, "shards"), _d(dims, "k_best", 10)
    return (
        b * length * (k_max * 8.0 / max(shards, 1.0) + 4.0)
        + 2.0 * b * (v + 1.0) * 8.0
        + (shards - 1.0) * b * (k_best * 8.0 + length * 4.0)
        + b * k_best * 8.0
    )


def _embed_flops(dims: dict) -> float:
    # lax.scan over l seed slots: one (b, r) x (r, v) matmul each
    # (2·b·r·v), the running max-merge (b·v per step), final top-k
    b, length, v = _d(dims, "b"), _d(dims, "l"), _d(dims, "v")
    r, k_best = _d(dims, "r"), _d(dims, "k_best", 10)
    return b * length * v * (2.0 * r + 1.0) + b * v * _log2k(k_best)


def _embed_bytes(dims: dict) -> float:
    # the factor matrix re-read per scan step + the (b, v) running max
    # written+read per step + seeds/outputs
    b, length, v = _d(dims, "b"), _d(dims, "l"), _d(dims, "v")
    r, k_best = _d(dims, "r"), _d(dims, "k_best", 10)
    return length * (v * r * 4.0 + 2.0 * b * v * 4.0) + b * (
        length * 4.0 + k_best * 8.0
    )


def _als_flops(dims: dict) -> float:
    # per iteration: two big×skinny matmuls (X F and Xᵀ U, 2·p·v·r
    # each), two rank² Gramians, two batched normal-equation solves
    p, v, r = _d(dims, "p"), _d(dims, "v"), _d(dims, "r")
    iters = _d(dims, "iters")
    return iters * (
        4.0 * p * v * r + 2.0 * r * r * (p + v) + 2.0 * r * r * r
    )


def _als_bytes(dims: dict) -> float:
    # X (f32) streamed twice per iteration + both factor matrices
    # read/written per half-sweep
    p, v, r = _d(dims, "p"), _d(dims, "v"), _d(dims, "r")
    iters = _d(dims, "iters")
    return iters * (2.0 * p * v * 4.0 + 4.0 * r * (p + v) * 4.0)


def _support_flops(dims: dict) -> float:
    # C = XᵀX: one (v, p) x (p, v) contraction
    p, v = _d(dims, "p"), _d(dims, "v")
    return 2.0 * p * v * v


def _support_bytes(dims: dict) -> float:
    # int8 one-hot read (both operands of the symmetric contraction) +
    # the int32 count matrix out
    p, v = _d(dims, "p"), _d(dims, "v")
    return 2.0 * p * v + v * v * 4.0


def _recount_flops(dims: dict) -> float:
    # C[R, :] = X[:, R]ᵀ X — the row slice of the same contraction
    p, v, rows = _d(dims, "p"), _d(dims, "v"), _d(dims, "rows")
    return 2.0 * p * rows * v


def _recount_bytes(dims: dict) -> float:
    p, v, rows = _d(dims, "p"), _d(dims, "v"), _d(dims, "rows")
    return p * v + p * rows + rows * v * 4.0


def _sparse_count_flops(dims: dict) -> float:
    # one mirrored add per expanded pair event (2·E accumulates) plus
    # the O(nnz) expansion arithmetic itself — nnz-proportional, the
    # dense p·v² term is exactly what this kernel does NOT pay
    events, nnz = _d(dims, "events"), _d(dims, "nnz")
    return 2.0 * events + 4.0 * nnz


def _sparse_count_bytes(dims: dict) -> float:
    # expanded keys written+sorted+read (~12 B/event over the hybrid's
    # chunks), the membership indices in, the (v, v) int32 counts out
    events, nnz, v = _d(dims, "events"), _d(dims, "nnz"), _d(dims, "v")
    return 12.0 * events + 8.0 * nnz + v * v * 4.0


def _sparse_als_flops(dims: dict) -> float:
    # per iteration: two gather+segment-add products over the nnz
    # events (2·nnz·r each), two rank² Gramians, two batched solves —
    # the 4·p·v·r dense term collapses to 4·nnz·r
    nnz, p, v, r = _d(dims, "nnz"), _d(dims, "p"), _d(dims, "v"), _d(dims, "r")
    iters = _d(dims, "iters")
    return iters * (
        4.0 * nnz * r + 2.0 * r * r * (p + v) + 2.0 * r * r * r
    )


def _sparse_als_bytes(dims: dict) -> float:
    # index vectors streamed twice per iteration + the gathered factor
    # rows (r f32 per event per product) + both factor matrices
    # read/written per half-sweep
    nnz, p, v, r = _d(dims, "nnz"), _d(dims, "p"), _d(dims, "v"), _d(dims, "r")
    iters = _d(dims, "iters")
    return iters * (
        16.0 * nnz + 8.0 * nnz * r + 4.0 * r * (p + v) * 4.0
    )


# THE registry: every jitted kernel the project dispatches has an entry,
# and every entry is observed by some dispatch site — both directions
# machine-checked by kmls-verify's `costspec` checker (checker 8).
KERNEL_COST_SPECS: dict[str, CostSpec] = {
    "serve_rules": CostSpec(
        "serve_rules", _serve_flops, _serve_bytes,
        "replicated rule scatter-max + top-k (ops/serve.py "
        "recommend_batch; dims b, l, k_max, v, k_best)",
    ),
    "serve_sharded": CostSpec(
        "serve_sharded", _sharded_serve_flops, _sharded_serve_bytes,
        "vocab-sharded lookup + all_gather max-merge (ops/serve.py "
        "sharded_recommend_fn; dims + shards)",
    ),
    "serve_mesh": CostSpec(
        "serve_mesh", _mesh_serve_flops, _mesh_serve_bytes,
        "pod-spanning gang lookup: local slab partial + rank-stacked "
        "merge (ops/serve.py shard_partial_topk/merge_partial_topk via "
        "serving/mesh.py; dims + shards)",
    ),
    "serve_native": CostSpec(
        "serve_native", _serve_flops, _serve_bytes,
        "native host scatter-max kernel — identical algorithm to "
        "serve_rules, measured against host peaks",
    ),
    "embed_topk": CostSpec(
        "embed_topk", _embed_flops, _embed_bytes,
        "embedding cosine top-k (ops/embed.py embed_topk; dims b, l, "
        "v, r, k_best)",
    ),
    "als_sweep": CostSpec(
        "als_sweep", _als_flops, _als_bytes,
        "ALS half-sweeps, full training loop (mining/als.py; dims p, "
        "v, r, iters)",
    ),
    "support_count": CostSpec(
        "support_count", _support_flops, _support_bytes,
        "pair-support contraction C = XᵀX (ops/support.py, "
        "parallel/support.py; dims p, v)",
    ),
    "delta_recount": CostSpec(
        "delta_recount", _recount_flops, _recount_bytes,
        "delta restricted recount C[R, :] (parallel/support."
        "restricted_pair_counts; dims p, v, rows)",
    ),
    "sparse_count": CostSpec(
        "sparse_count", _sparse_count_flops, _sparse_count_bytes,
        "sparse CSR×bitpacked pair-support hybrid (ops/sparse.py "
        "sparse_pair_counts_np/_device; dims events, nnz, v)",
    ),
    "als_sweep_sparse": CostSpec(
        "als_sweep_sparse", _sparse_als_flops, _sparse_als_bytes,
        "ALS half-sweeps over the compressed interaction matrix "
        "(mining/als.py _train_sparse; dims nnz, p, v, r, iters)",
    ),
}


def phase_cost(kernel: str, **dims) -> tuple[float, float]:
    """Analytic ``(flops, bytes_moved)`` for one kernel invocation — the
    mining side's per-phase attribution (jobmetrics) and the bench's
    expected-work numerator both read this, so the serving and batch
    attributions can never use different formulas."""
    spec = KERNEL_COST_SPECS[kernel]
    return spec.flops(dims), spec.bytes_moved(dims)


def classify_roofline(
    flops: float, bytes_moved: float, peak_flops: float, peak_bytes_s: float
) -> str:
    """→ ``"compute"`` | ``"bandwidth"``: arithmetic intensity
    (flops/byte) vs the ridge point (peak_flops / peak_bytes_per_s).
    At or above the ridge the kernel can saturate the MXU; below it the
    memory system is the ceiling and MFU is bounded by
    intensity · peak_bw / peak_flops."""
    intensity = flops / max(bytes_moved, 1.0)
    ridge = peak_flops / max(peak_bytes_s, 1.0)
    return "compute" if intensity >= ridge else "bandwidth"


class CompileWatcher:
    """Live form of the zero-compiles-post-publish invariant: per-kernel
    jit-cache sizes snapshotted at publication; growth afterwards IS a
    compile on the serving path, exported as
    ``kmls_compiles_total{kernel}``. A re-publication legitimately warms
    new shapes — :meth:`mark_published` banks the running count and
    re-snapshots, so the counter stays monotonic and only ever counts
    compiles that landed OUTSIDE a publication."""

    def __init__(self):
        self._fns: dict[str, object] = {}
        self._base: dict[str, int] = {}
        self._accum: dict[str, int] = {}

    @staticmethod
    def _size(fn) -> int:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return 0
        try:
            return int(probe())
        except Exception:
            return 0

    def watch(self, kernel: str, fn) -> None:
        """Track ``fn``'s jit cache under ``kernel``. First sight
        snapshots the current size, so compiles that predate watching
        (another engine in the same process — the jitted functions are
        module-level) are never billed here."""
        if fn is None:
            return
        if self._fns.get(kernel) is not fn:
            self._fns[kernel] = fn
            self._base[kernel] = self._size(fn)
            self._accum.setdefault(kernel, 0)

    def note_prepublish(self) -> None:
        """Call BEFORE a (re)publication's warmup begins: growth since
        the last snapshot is genuine serving-path compiles — bank it so
        the counter stays monotonic — and re-baseline, so the warmup
        compiles about to happen land between this and
        :meth:`mark_published`, where they are absorbed."""
        for kernel, fn in self._fns.items():
            cur = self._size(fn)
            self._accum[kernel] = self._accum.get(kernel, 0) + max(
                0, cur - self._base.get(kernel, cur)
            )
            self._base[kernel] = cur

    def mark_published(self) -> None:
        """Call AFTER warmup: re-snapshot WITHOUT banking — everything
        since :meth:`note_prepublish` was the publication legitimately
        warming its shapes, not a compile on the serving path."""
        for kernel, fn in self._fns.items():
            self._base[kernel] = self._size(fn)

    def compiles(self) -> dict[str, int]:
        """kernel → compiles since its last publication snapshot (plus
        everything banked across earlier publications)."""
        out: dict[str, int] = {}
        for kernel, fn in self._fns.items():
            cur = self._size(fn)
            out[kernel] = self._accum.get(kernel, 0) + max(
                0, cur - self._base.get(kernel, cur)
            )
        return out


class CostModel:
    """Per-kernel device-time/FLOPs/bytes accumulator + compile watcher
    + publish-time memory accounting. One per engine; the app renders it
    into ``/metrics``. The observe path is completion-side only (never
    under a dispatch lock): one dict update under a private lock, no
    allocation beyond the first sight of a kernel name."""

    def __init__(self, peak_flops: float = 0.0, peak_bytes_s: float = 0.0):
        if peak_flops > 0 and peak_bytes_s > 0:
            # both pinned: never touch jax (unit tests construct here)
            self.peak_flops, self.peak_bytes_s = peak_flops, peak_bytes_s
            self.peak_source = "explicit"
        else:
            resolved_flops, resolved_bw, resolved_src = resolve_peaks()
            self.peak_flops = peak_flops if peak_flops > 0 else resolved_flops
            self.peak_bytes_s = (
                peak_bytes_s if peak_bytes_s > 0 else resolved_bw
            )
            # partial override: name both origins (see resolve_peaks)
            self.peak_source = (
                f"explicit+{resolved_src}"
                if (peak_flops > 0 or peak_bytes_s > 0)
                else resolved_src
            )
        self._lock = threading.Lock()
        # kernel -> [device_s, flops, bytes, dispatches]
        self._kernels: dict[str, list[float]] = {}
        # dispatches naming a kernel with no registered spec: kept
        # serving (zero-flop observation) but counted loudly — the
        # runtime shadow of the costspec checker's static guarantee
        self.unspecced: dict[str, int] = {}
        self.observations = 0
        self.compile_watcher = CompileWatcher()
        # ---- publish-time memory accounting (engine-fed) ----
        self.tensor_bytes: dict[str, int] = {}  # artifact -> bytes (total)
        self.budget_bytes = 0
        self.n_shards = 1
        self.publish_watermark_bytes = 0

    # ---------- observation (hot completion path) ----------

    def observe_kernel(self, kernel: str, device_s: float, **dims) -> None:
        """Fold one fenced kernel timing into the per-kernel totals.
        ``device_s`` is dispatch→result-on-host (the same semantics as
        the batcher's device attribution: an upper bound on device time,
        so the derived MFU is a lower bound)."""
        global OBSERVATIONS_TOTAL
        OBSERVATIONS_TOTAL += 1  # benign race: zero-cost proof counter
        spec = KERNEL_COST_SPECS.get(kernel)
        if spec is None:
            with self._lock:
                self.unspecced[kernel] = self.unspecced.get(kernel, 0) + 1
                entry = self._kernels.setdefault(kernel, [0.0, 0.0, 0.0, 0])
                entry[0] += max(device_s, 0.0)
                entry[3] += 1
                self.observations += 1
            return
        flops = spec.flops(dims)
        moved = spec.bytes_moved(dims)
        with self._lock:
            entry = self._kernels.setdefault(kernel, [0.0, 0.0, 0.0, 0])
            entry[0] += max(device_s, 0.0)
            entry[1] += flops
            entry[2] += moved
            entry[3] += 1
            self.observations += 1

    # ---------- compile telemetry ----------

    def watch_compiles(self, kernel: str, fn) -> None:
        self.compile_watcher.watch(kernel, fn)

    def note_prepublish(self) -> None:
        self.compile_watcher.note_prepublish()

    def mark_published(self) -> None:
        self.compile_watcher.mark_published()

    def compiles_post_publish(self) -> dict[str, int]:
        return self.compile_watcher.compiles()

    # ---------- memory accounting ----------

    def note_publish(
        self,
        tensor_bytes: dict[str, int],
        budget_bytes: int,
        n_shards: int = 1,
        watermark_bytes: int = 0,
    ) -> None:
        """Publish-time snapshot from the engine: analytic per-artifact
        tensor bytes (the same numbers layout.py's auto decision
        measured), the per-device budget they were judged against, and
        the live bytes-in-use watermark where the backend reports one."""
        with self._lock:
            self.tensor_bytes = dict(tensor_bytes)
            self.budget_bytes = int(budget_bytes)
            self.n_shards = max(1, int(n_shards))
            self.publish_watermark_bytes = int(watermark_bytes)

    def per_device_tensor_bytes(self) -> int:
        with self._lock:
            total = sum(self.tensor_bytes.values())
            return total // self.n_shards

    def headroom_bytes(self) -> int:
        """Budget minus the analytic per-device tensor residency — how
        observable the auto-layout decision's margin is."""
        with self._lock:
            total = sum(self.tensor_bytes.values())
            return self.budget_bytes - total // self.n_shards

    # ---------- derived stats ----------

    def kernel_stats(self) -> dict[str, dict]:
        """kernel → {device_s, dispatches, flops, bytes, flops_per_s,
        bytes_per_s, mfu, roofline} (rates 0 while no time observed)."""
        with self._lock:
            snap = {k: list(v) for k, v in self._kernels.items()}
        out: dict[str, dict] = {}
        for kernel, (device_s, flops, moved, n) in snap.items():
            flops_s = flops / device_s if device_s > 0 else 0.0
            bytes_s = moved / device_s if device_s > 0 else 0.0
            out[kernel] = {
                "device_s": device_s,
                "dispatches": n,
                "flops": flops,
                "bytes": moved,
                "flops_per_s": flops_s,
                "bytes_per_s": bytes_s,
                "mfu": min(flops_s / self.peak_flops, 1.0)
                if self.peak_flops > 0
                else 0.0,
                "roofline": classify_roofline(
                    flops, moved, self.peak_flops, self.peak_bytes_s
                ),
            }
        return out

    def summary(self) -> dict:
        """The /debug + bench view: peaks, per-kernel stats, compile
        counts, memory accounting."""
        return {
            "peak_flops": self.peak_flops,
            "peak_bytes_per_s": self.peak_bytes_s,
            "peak_source": self.peak_source,
            "observations": self.observations,
            "kernels": self.kernel_stats(),
            "compiles_post_publish": self.compiles_post_publish(),
            "unspecced": dict(self.unspecced),
            "tensor_bytes": dict(self.tensor_bytes),
            "budget_bytes": self.budget_bytes,
            "headroom_bytes": self.headroom_bytes(),
            "publish_watermark_bytes": self.publish_watermark_bytes,
        }

    # ---------- exposition ----------

    @staticmethod
    def device_memory_lines() -> list[str]:
        """Live ``memory_stats()`` gauges where the backend provides
        them (TPU does; CPU returns None → no lines, series absent —
        the analytic accounting below covers every backend)."""
        import jax

        in_use: list[str] = []
        limit: list[str] = []
        for i, dev in enumerate(jax.local_devices()):
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            used = stats.get("bytes_in_use")
            cap = stats.get("bytes_limit")
            if used is not None:
                in_use.append(f'kmls_device_bytes_in_use{{device="{i}"}} {int(used)}')
            if cap is not None:
                limit.append(f'kmls_device_bytes_limit{{device="{i}"}} {int(cap)}')
        lines: list[str] = []
        if in_use:
            lines.append("# TYPE kmls_device_bytes_in_use gauge")
            lines += in_use
        if limit:
            lines.append("# TYPE kmls_device_bytes_limit gauge")
            lines += limit
        return lines

    def render_lines(self) -> list[str]:
        """The cost-attribution block of ``/metrics``. Every series here
        is declared in ``serving.metrics.METRIC_REGISTRY`` (the metrics
        checker covers this file as a serving exposition surface)."""
        stats = self.kernel_stats()
        compiles = self.compiles_post_publish()
        lines = [
            "# TYPE kmls_costmodel_observations_total counter",
            f"kmls_costmodel_observations_total {self.observations}",
        ]
        if stats:
            blocks: list[tuple[str, str, Callable[[dict], str]]] = [
                ("kmls_kernel_device_seconds", "counter",
                 lambda s: f"{s['device_s']:.6f}"),
                ("kmls_kernel_dispatches_total", "counter",
                 lambda s: str(s["dispatches"])),
                ("kmls_kernel_flops_per_second", "gauge",
                 lambda s: f"{s['flops_per_s']:.6g}"),
                ("kmls_kernel_bytes_per_second", "gauge",
                 lambda s: f"{s['bytes_per_s']:.6g}"),
                ("kmls_mfu", "gauge", lambda s: f"{s['mfu']:.6g}"),
                ("kmls_kernel_compute_bound", "gauge",
                 lambda s: str(int(s["roofline"] == "compute"))),
            ]
            for name, mtype, value_of in blocks:
                lines.append(f"# TYPE {name} {mtype}")
                for kernel in sorted(stats):
                    lines.append(
                        f'{name}{{kernel="{kernel}"}} {value_of(stats[kernel])}'
                    )
        if compiles:
            lines.append("# TYPE kmls_compiles_total counter")
            for kernel in sorted(compiles):
                lines.append(
                    f'kmls_compiles_total{{kernel="{kernel}"}} {compiles[kernel]}'
                )
        with self._lock:
            unspecced_total = sum(self.unspecced.values())
            tensor_bytes = dict(self.tensor_bytes)
            budget = self.budget_bytes
            watermark = self.publish_watermark_bytes
        lines += [
            "# TYPE kmls_costmodel_unspecced_total counter",
            f"kmls_costmodel_unspecced_total {unspecced_total}",
        ]
        if tensor_bytes:
            lines.append("# TYPE kmls_model_tensor_bytes gauge")
            for artifact in sorted(tensor_bytes):
                lines.append(
                    f'kmls_model_tensor_bytes{{artifact="{artifact}"}} '
                    f"{tensor_bytes[artifact]}"
                )
            lines += [
                "# TYPE kmls_device_budget_bytes gauge",
                f"kmls_device_budget_bytes {budget}",
                "# TYPE kmls_device_headroom_bytes gauge",
                f"kmls_device_headroom_bytes {self.headroom_bytes()}",
                "# TYPE kmls_publish_watermark_bytes gauge",
                f"kmls_publish_watermark_bytes {watermark}",
            ]
        lines += self.device_memory_lines()
        return lines


def device_watermark_bytes(device=None) -> int:
    """Current ``bytes_in_use`` of ``device`` (default: first local), or
    0 where the backend has no ``memory_stats`` (CPU) — the publish-time
    watermark the engine records next to the analytic accounting."""
    import jax

    if device is None:
        devs = jax.local_devices()
        if not devs:
            return 0
        device = devs[0]
    try:
        stats = device.memory_stats()
    except Exception:
        return 0
    if not stats:
        return 0
    return int(stats.get("bytes_in_use", 0) or 0)
